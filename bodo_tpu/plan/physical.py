"""Plan executor: logical plan → relational-layer calls → Table.

Analogue of the reference's physical conversion + pipeline executor
(bodo/pandas/_physical_conv.h:29 PhysicalPlanBuilder,
bodo/pandas/_executor.h:76 Executor). The streaming C++ pipelines become
a post-order walk issuing cached jitted stages; results memoize on the
node (plan collapse) and in a session-level cache keyed by plan identity.
"""

from __future__ import annotations

import threading
import time as _time

import numpy as np

from bodo_tpu import relational as R
from bodo_tpu.config import config
from bodo_tpu.parallel import mesh as mesh_mod
from bodo_tpu.plan import logical as L
from bodo_tpu.plan.optimizer import optimize
from bodo_tpu.runtime import resilience, result_cache as _rcache
from bodo_tpu.table.table import ONED, REP, Table
from bodo_tpu.utils.logging import log

# session-level semantic result cache (runtime/result_cache.py): entries
# key on (plan fingerprint, environment, dataset signatures) so a
# changed source file never serves a stale result. The old name stays
# bound for its dict-shaped call sites (.clear() in tests/benches,
# .pop(raw_key) after fusion's buffer donation).
_result_cache = _rcache.cache()

# graceful-degradation state for the executing thread: while a stage is
# being re-run replicated, _maybe_shard must not re-shard its sources
_degrade_tls = threading.local()


def execute(node: L.Node, optimize_first: bool = True) -> Table:
    if optimize_first:
        node = optimize(node)
        if config.dump_plans:
            _dump(node)
    if config.plan_validate:
        # shardcheck layer 1: reject ill-typed plans (distribution /
        # schema invariant violations) before any kernel traces or
        # collectives dispatch — PlanInvariantError in milliseconds
        # instead of wrong answers or a wedged gang
        from bodo_tpu.analysis.plan_validator import validate_plan
        validate_plan(node)
    # whole-stage fusion planning: annotate maximal pipeline-compatible
    # regions (filter/project chains + dense-agg roots) so _exec_inner
    # dispatches each as ONE compiled program. Planning is best-effort —
    # a failure here must cost per-node execution, never the query.
    try:
        from bodo_tpu.plan.fusion import plan_fusion_groups
        plan_fusion_groups(node)
    except Exception as e:  # noqa: BLE001 - fusion is an optimization
        log(1, f"fusion planning failed, executing unfused: {e}")
    from bodo_tpu.utils import tracing
    if not tracing.is_tracing():
        return _rcache.cached_execute(node, _exec)
    # every traced execution belongs to a query: adopt the caller's
    # span if one is active, otherwise open one for this plan so all
    # events/records below carry a query id. The serving layer's
    # session (if any) tags the query — EXPLAIN/slow-query records then
    # say WHICH tenant ran the plan (multi-tenant attribution)
    from bodo_tpu.plan import explain
    session = _current_session()
    qid = tracing.current_query_id()
    if qid is not None:
        explain.begin_query(node, qid, session=session)
        return _rcache.cached_execute(node, _exec)
    with tracing.query_span() as qid:
        explain.begin_query(node, qid, session=session)
        return _rcache.cached_execute(node, _exec)


def _current_session():
    """Serving-session id of the executing query, or None outside the
    serving layer (lazy: never imports the scheduler)."""
    import sys
    sch = sys.modules.get("bodo_tpu.runtime.scheduler")
    if sch is None:
        return None
    try:
        return sch.current_session()
    except Exception:  # noqa: BLE001 - attribution is best-effort
        return None


def _maybe_shard(t: Table) -> Table:
    """Scan distribution policy: shard large sources over the mesh; keep
    small ones replicated so joins against them broadcast instead of
    shuffling (the reference's broadcast-join size heuristic)."""
    if t.distribution == ONED:
        return t
    if getattr(_degrade_tls, "force_rep", False):
        return t
    if t.nrows >= config.shard_min_rows and mesh_mod.num_shards() > 1:
        return t.shard()
    return t


def _exec(node: L.Node) -> Table:
    from bodo_tpu.utils import tracing
    traced = tracing.is_tracing()
    if node._cached is not None:
        if traced:
            _record_node(node, node._cached, 0.0, cached=True)
        return node._cached
    key = _rcache.node_key(node)
    hit = _rcache.lookup(key)
    if hit is not None:
        node._cached = hit
        if traced:
            _record_node(node, hit, 0.0, cached=True)
        return hit
    est_rows = aqe_before = comm_before = xla_before = None
    if traced:
        # pre-execution estimate + AQE decision snapshot, so the record
        # can show est-vs-actual and which adaptive decisions this node
        # triggered (EXPLAIN ANALYZE annotations)
        try:
            from bodo_tpu.plan import adaptive, stats
            est_rows = stats.estimate(node)[0]
            aqe_before = dict(adaptive.stats().get("decisions", {}))
        except Exception:  # noqa: BLE001 - annotation is best-effort
            pass
        try:
            # comm-observatory snapshot: the delta across the node's
            # span is its inclusive comm-wait vs compute split
            from bodo_tpu.parallel import comm
            comm_before = comm.stats()
        except Exception:  # noqa: BLE001
            pass
        try:
            # observatory snapshot: compiles/retraces/device bytes that
            # land during this node's span are attributed to it
            from bodo_tpu.runtime import xla_observatory
            xla_before = xla_observatory.head()
        except Exception:  # noqa: BLE001
            pass
    span_args = {}
    path = getattr(node, "_explain_path", None)
    if path is not None:
        span_args["path"] = path
    t0 = _time.perf_counter()
    with tracing.event(type(node).__name__, **span_args) as ev:
        t = _exec_with_oom_retry(node)
        if ev is not None:
            ev["rows"] = t.nrows
    wall_s = _time.perf_counter() - t0
    if traced:
        _record_node(node, t, wall_s,
                     est_rows=est_rows, aqe_before=aqe_before,
                     comm_before=comm_before, xla_before=xla_before)
    node._cached = t
    # stage-boundary statistics feedback; a stage that came back from a
    # degraded replicated re-run is tainted (execution artifact, not a
    # data property) and must not feed the stats store
    if getattr(_degrade_tls, "tainted", False):
        _degrade_tls.tainted = False
    else:
        from bodo_tpu.plan import adaptive
        adaptive.observe_stage(node, t)
    _rcache.record(key, node.key(), t, wall_s)
    try:
        # elastic checkpoint anchor: the AQE observation point doubles
        # as the resumable-suffix boundary (the result cache owns the
        # bytes; elastic tracks registration + accounting for /healthz)
        from bodo_tpu.runtime import elastic
        elastic.observe_stage(node, wall_s)
    except Exception:  # noqa: BLE001 - accounting never fails a query
        pass
    return t


def _record_node(node: L.Node, t: Table, wall_s: float,
                 cached: bool = False, est_rows=None,
                 aqe_before=None, comm_before=None,
                 xla_before=None) -> None:
    """EXPLAIN ANALYZE observation for one executed (or cache-hit) node:
    rows, result device bytes, inclusive wall, the delta of AQE
    decision counters and of the comm-observatory totals across the
    node's execution. Best-effort — an annotation failure never fails
    the query."""
    try:
        from bodo_tpu.plan import explain
        aqe_delta = None
        if aqe_before is not None:
            from bodo_tpu.plan import adaptive
            after = adaptive.stats().get("decisions", {})
            aqe_delta = {k: v - aqe_before.get(k, 0)
                         for k, v in after.items()
                         if v - aqe_before.get(k, 0)}
        comm_delta = None
        if comm_before is not None:
            try:
                from bodo_tpu.parallel import comm
                after_c = comm.stats()
                d = {
                    "wall_s": after_c["wall_s"] - comm_before["wall_s"],
                    "wait_s": after_c["wait_s"] - comm_before["wait_s"],
                    "bytes": (after_c["bytes_out"] + after_c["bytes_in"]
                              - comm_before["bytes_out"]
                              - comm_before["bytes_in"]),
                }
                if d["bytes"] or d["wall_s"] > 1e-9 \
                        or d["wait_s"] > 1e-9:
                    comm_delta = d
            except Exception:  # noqa: BLE001
                pass
        xla_delta = None
        if xla_before is not None:
            try:
                from bodo_tpu.runtime import xla_observatory
                after_x = xla_observatory.head()
                compiles = after_x["compiles"] - xla_before["compiles"]
                retraces = after_x["retraces"] - xla_before["retraces"]
                disp = after_x["dispatches"] - xla_before["dispatches"]
                dev = after_x["live_bytes"] - xla_before["live_bytes"]
                if compiles or retraces or disp or dev:
                    xla_delta = {"compiles": compiles,
                                 "retraces": retraces,
                                 "dispatches": disp,
                                 "dev_bytes": dev}
                    if retraces:
                        xla_delta["cause"] = after_x["last_cause"]
            except Exception:  # noqa: BLE001
                pass
        nbytes = None
        try:
            from bodo_tpu.runtime.memory_governor import \
                table_device_bytes
            nbytes = int(table_device_bytes(t))
        except Exception:  # noqa: BLE001
            pass
        explain.record(node, rows=t.nrows, wall_s=wall_s,
                       est_rows=est_rows, bytes=nbytes, cached=cached,
                       aqe=aqe_delta, comm=comm_delta,
                       fusion=getattr(node, "_fusion_info", None),
                       xla=xla_delta)
    except Exception:  # noqa: BLE001 - observability must not break exec
        pass


_MAX_OOM_RETRIES = 3


def _exec_with_oom_retry(node: L.Node) -> Table:
    """Stage-boundary recovery envelope, two legs:

    OOM retry — XLA RESOURCE_EXHAUSTED from a stage turns into (halve
    the fattest operator grant, spill parked state via the comptroller,
    re-run the stage) instead of a hard crash. Safe to re-run: child
    results are memoized on their nodes, so only the failed stage
    recomputes — under the shrunken grant it takes its partitioned/
    spill path.

    Graceful degradation — a sharded collective failing with a non-OOM
    internal error (or an armed `collective` fault) re-executes the
    stage replicated: materialized 1D inputs are gathered, sources stay
    REP for the re-run, and the REP kernel paths need no collectives."""
    from bodo_tpu.runtime.memory_governor import governor
    last = None
    for attempt in range(_MAX_OOM_RETRIES + 1):
        try:
            return _exec_inner(node)
        except Exception as e:  # noqa: BLE001 - classified below
            gov = governor()
            if (not config.mem_governor or not gov.is_oom(e)
                    or attempt == _MAX_OOM_RETRIES):
                out = _try_degrade(node, e)
                if out is not None:
                    return out
                raise
            last = e
            from bodo_tpu.utils import tracing
            with tracing.event("oom_retry", stage=type(node).__name__,
                               attempt=attempt + 1):
                if not gov.handle_oom(e):
                    raise
            log(1, f"OOM at {type(node).__name__} (attempt "
                   f"{attempt + 1}): grant halved, parked state "
                   f"spilled, re-running stage")
    raise last  # pragma: no cover - loop always returns or raises


def _try_degrade(node: L.Node, err: Exception):
    """Re-execute a stage replicated after a sharded-collective failure.

    Returns the replicated result, or None when degradation does not
    apply (disabled, error not collective-shaped, already inside a
    degraded re-run) or when the replicated re-run itself fails — the
    caller then raises the ORIGINAL error. The innermost failing stage
    degrades first; its replicated result feeds parent stages normally."""
    if not config.degrade_replicated or \
            getattr(_degrade_tls, "force_rep", False):
        return None
    if not resilience.is_degradable(err):
        return None
    stage = type(node).__name__
    # pull this stage's materialized 1D inputs back to one replicated
    # copy; un-materialized children re-execute under force_rep below.
    # Snapshot the originals so a failed re-run leaves the plan's cached
    # distributions untouched for any later re-execution.
    snapshot = [(c, c._cached) for c in node.children]
    for c in node.children:
        if c._cached is not None and c._cached.distribution == ONED:
            c._cached = c._cached.gather()
    from bodo_tpu.utils import tracing
    _degrade_tls.force_rep = True
    try:
        with tracing.event("degrade_replicated", stage=stage):
            out = _exec_inner(node)
    except Exception:  # noqa: BLE001 - degraded re-run failed too
        for c, cached in snapshot:
            c._cached = cached
        return None
    finally:
        _degrade_tls.force_rep = False
    resilience.count_degradation(stage)
    _degrade_tls.tainted = True
    log(1, f"collective failure at {stage}: re-executed replicated "
           f"({type(err).__name__})")
    return out


def apply_projection(t: Table, exprs) -> Table:
    """Evaluate a Projection node's exprs on a table (shared with the
    streaming executor's per-batch project stage)."""
    from bodo_tpu.plan.expr import ColRef
    new = {}
    names = []
    for n, e in exprs:
        names.append(n)
        if not (isinstance(e, ColRef) and e.name == n):
            new[n] = e
    t = R.assign_columns(t, new) if new else t
    return t.select(names)


def _exec_inner(node: L.Node) -> Table:
    resilience.maybe_inject("stage.boundary")
    if config.stream_exec and isinstance(node, (L.Aggregate, L.Reduce,
                                                L.Sort)):
        from bodo_tpu.plan import streaming
        out = streaming.try_stream_execute(node)
        if out is not None:
            return out
    # whole-stage fusion: a group root dispatches its whole region as
    # one compiled program. Streaming wins for memory-bounded aggregates
    # (above) — its per-batch chains fuse internally via stream_chain.
    # A None return (unfusable at runtime) falls through to per-node.
    group = getattr(node, "_fusion_group", None)
    if group is not None:
        from bodo_tpu.plan import fusion
        if isinstance(group, fusion.FusionGroup):
            out = fusion.execute_group(group, _exec)
        else:
            from bodo_tpu.plan import fusion_join
            out = fusion_join.execute_join_group(group, _exec)
        if out is not None:
            return out
    if isinstance(node, L.ReadParquet):
        from bodo_tpu.io import read_parquet
        from bodo_tpu.io.parquet import dataset_nbytes
        from bodo_tpu.runtime.memory_governor import reserve
        log(1, f"read_parquet({node.path}) columns={node.columns}")
        # admission-control the materializing scan against the derived
        # budget (on-disk bytes as the want estimate; 0 = unknown, skip)
        nbytes = dataset_nbytes(node.path)
        if nbytes > 0:
            with reserve("read_parquet", nbytes):
                return _maybe_shard(
                    read_parquet(node.path, columns=node.columns))
        return _maybe_shard(read_parquet(node.path, columns=node.columns))
    if isinstance(node, L.ReadCsv):
        from bodo_tpu.io import read_csv
        return _maybe_shard(read_csv(
            node.path, columns=node.columns,
            parse_dates=list(node.parse_dates) or None))
    if isinstance(node, L.FromPandas):
        return _maybe_shard(node.table)
    if isinstance(node, L.ViewScan):
        from bodo_tpu.runtime import views as _views
        return _maybe_shard(_views.materialized_table(node.name))
    if isinstance(node, L.Projection):
        return apply_projection(_exec(node.child), node.exprs)
    if isinstance(node, L.Filter):
        return R.filter_table(_exec(node.child), node.predicate)
    if isinstance(node, L.Aggregate):
        return R.groupby_agg(_exec(node.child), node.keys, node.aggs)
    if isinstance(node, L.Reduce):
        scalars = R.reduce_table(_exec(node.child), node.aggs)
        import pandas as pd
        df = pd.DataFrame({k: [v] for k, v in scalars.items()})
        return Table.from_pandas(df)
    if isinstance(node, L.Join):
        from bodo_tpu.plan import adaptive
        repl = adaptive.maybe_reoptimize_join(node, _exec)
        if repl is not None:
            # observed leaf cardinalities changed the join order:
            # execute the re-planned subtree (leaf results are memoized,
            # so only the joins themselves run). The rewrite must
            # preserve the original subtree's schema and abstract
            # distribution — validated before anything executes.
            if config.plan_validate:
                from bodo_tpu.analysis.plan_validator import \
                    validate_rewrite
                validate_rewrite(node, repl)
            from bodo_tpu.utils import tracing
            if tracing.is_tracing():
                # re-anchor the substituted subtree's EXPLAIN paths
                # under the join it replaced, flagged as replanned
                from bodo_tpu.plan import explain
                explain.assign_paths(
                    repl, getattr(node, "_explain_path", None) or "0",
                    force=True, replanned=True)
            return _exec(repl)
        left = _exec(node.left)
        right = _exec(node.right)
        return R.join_tables(left, right, node.left_on, node.right_on,
                             node.how, node.suffixes,
                             null_equal=node.null_equal)
    if isinstance(node, L.NonEquiJoin):
        from bodo_tpu.ops import nonequi
        left = _exec(node.left).gather()
        right = _exec(node.right).gather()
        iv = nonequi.match_interval_pattern(
            node.pred, set(node.left.schema), set(node.right.schema))
        if iv is not None:
            out = nonequi.nl_join_interval(left, right, node.pred,
                                           iv[0], iv[1], node.how)
        else:
            out = nonequi.nl_join_rep(left, right, node.pred, node.how)
        return _maybe_shard(out)
    if isinstance(node, L.Explode):
        from bodo_tpu.table import nested as _nested
        out = _nested.flatten_table(_exec(node.child), node.column,
                                    node.value_name, node.index_name,
                                    node.outer)
        return _maybe_shard(out)
    if isinstance(node, L.Union):
        return _maybe_shard(R.concat_tables(
            [_exec(c) for c in node.children]))
    if isinstance(node, L.Window):
        return R.window_table(_exec(node.child), node.specs)
    if isinstance(node, L.RankWindow):
        return R.rank_window(_exec(node.child), node.partition_by,
                             node.order_by, node.specs, node.ascending)
    if isinstance(node, L.AggWindow):
        return R.agg_window(_exec(node.child), node.partition_by,
                            node.order_by, node.specs, node.ascending)
    if isinstance(node, L.Sort):
        return R.sort_table(_exec(node.child), node.by, node.ascending,
                            node.na_last)
    if isinstance(node, L.Limit):
        return R.head_table(_exec(node.child), node.n)
    if isinstance(node, L.Distinct):
        child = _exec(node.child)
        others = [n for n in child.names if n not in node.subset]
        aggs = [(n, "first", n) for n in others]
        out = R.groupby_agg(child, node.subset, aggs)
        return out.select(child.names)
    raise TypeError(f"cannot execute {node!r}")


def _dump(node: L.Node, indent: int = 0) -> None:  # pragma: no cover
    import sys
    print("  " * indent + repr(node), file=sys.stderr)
    for c in node.children:
        _dump(c, indent + 1)
