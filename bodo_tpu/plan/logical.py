"""Logical plan nodes (lazy query DAG).

Analogue of the reference's LazyPlan node set (bodo/pandas/plan.py:44 —
LogicalProjection/Filter/Aggregate/Distinct/ComparisonJoin/Limit/Order and
the scan/write nodes at :480-556). Each node carries its output schema
(host-side dtype dict), computed at construction so the frontend can
type-check without executing. Nodes memoize their executed Table
(`_cached`) — re-using a materialized prefix is the reference's
plan-collapse behavior.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from bodo_tpu.ops.groupby import agg_dtype
from bodo_tpu.plan.expr import Expr, expr_columns, infer_dtype
from bodo_tpu.table import dtypes as dt

Schema = Dict[str, dt.DType]


class Node:
    schema: Schema
    children: List["Node"]
    _cached = None  # executed Table

    def key(self) -> Tuple:
        raise NotImplementedError

    def __repr__(self):  # pragma: no cover
        name = type(self).__name__
        return f"{name}({', '.join(self.schema)})[{len(self.children)} ch]"


class ReadParquet(Node):
    """Parquet scan. `path` may be a directory/glob/file or a
    pre-resolved TUPLE of data files (the Iceberg snapshot path — keeps
    the scan lazy so pruning/pushdown reach it)."""

    def __init__(self, path, columns: Optional[Sequence[str]] = None):
        import pyarrow.parquet as pq

        from bodo_tpu.io.parquet import (_dataset_files, _opened,
                                         split_rg_fragment)
        self.path = tuple(path) if isinstance(path, (list, tuple)) \
            else path
        self.children = []
        f = split_rg_fragment(_dataset_files(self.path)[0])[0]
        with _opened(f) as src:
            arrow_schema = pq.read_schema(src)
        names = list(columns) if columns else arrow_schema.names
        self.columns = names
        self.schema = {}
        for n in names:
            self.schema[n] = _arrow_field_dtype(arrow_schema.field(n).type)

    def key(self):
        return ("read_parquet", self.path, tuple(self.columns))


class ViewScan(Node):
    """Scan of a named materialized view (runtime/views.py). A leaf: the
    view's current materialization is served from the result cache at
    execution time, so downstream plans compose over views exactly like
    over base tables. key() carries only the NAME: a consumer plan keeps
    a stable fingerprint across view refreshes, and the result cache
    signs it with the view's BASE source signatures — so a refresh
    supersedes (and drops) the consumer's old entry instead of orphaning
    it. `version` is the view's maintenance generation at construction
    (introspection only)."""

    def __init__(self, name: str, schema: Schema, version: int = 0):
        self.name = name
        self.children = []
        self.schema = dict(schema)
        self.version = int(version)

    def key(self):
        return ("view_scan", self.name)


class ReadCsv(Node):
    def __init__(self, path: str, columns=None, parse_dates=None,
                 schema: Optional[Schema] = None):
        self.path = path
        self.columns = columns
        self.parse_dates = tuple(parse_dates) if parse_dates else ()
        self.children = []
        if schema is None:
            import pyarrow.csv as pacsv
            # infer from the first block only — never parse the whole file
            # at plan-construction time
            with pacsv.open_csv(path, read_options=pacsv.ReadOptions(
                    block_size=1 << 20)) as reader:
                head = reader.read_next_batch()
            schema = {}
            for f_ in head.schema:
                if f_.name in self.parse_dates:
                    schema[f_.name] = dt.DATETIME
                else:
                    schema[f_.name] = _arrow_field_dtype(f_.type)
            if columns:
                schema = {n: schema[n] for n in columns}
        self.schema = schema

    def key(self):
        return ("read_csv", self.path, tuple(self.columns or ()),
                self.parse_dates)


class FromPandas(Node):
    """In-memory source (bd.from_pandas analogue, reference base.py:74)."""
    _counter = [0]

    def __init__(self, df):
        from bodo_tpu.table.table import Table
        self.children = []
        if isinstance(df, Table):
            self.table = df
        else:
            self.table = Table.from_pandas(df)
        self.schema = {n: c.dtype for n, c in self.table.columns.items()}
        FromPandas._counter[0] += 1
        self._id = FromPandas._counter[0]

    def key(self):
        return ("from_pandas", self._id)


class Explode(Node):
    """LATERAL FLATTEN over a list column: one output row per element,
    adding `value_name` (element) + `index_name` (0-based position)
    while keeping every child column; empty/null arrays drop unless
    `outer` (reference: BodoSQL lateral FLATTEN,
    BodoSQL/bodosql/kernels/lateral.py, bodo/libs/_lateral.cpp)."""

    def __init__(self, child: Node, column: str, value_name: str,
                 index_name: str, outer: bool = False):
        self.children = [child]
        self.column = column
        self.value_name = value_name
        self.index_name = index_name
        self.outer = outer
        cdt = child.schema[column]
        if cdt.kind != "list":
            raise TypeError(f"FLATTEN input {column!r} is not an array "
                            f"column ({cdt.name})")
        sch = dict(child.schema)
        sch[value_name] = cdt.elem
        sch[index_name] = dt.INT64
        self.schema = sch

    @property
    def child(self):
        return self.children[0]

    def key(self):
        return ("explode", self.child.key(), self.column,
                self.value_name, self.index_name, self.outer)


class Projection(Node):
    def __init__(self, child: Node, exprs: Sequence[Tuple[str, Expr]]):
        self.children = [child]
        self.exprs = list(exprs)
        self.schema = {n: infer_dtype(e, child.schema) for n, e in self.exprs}

    @property
    def child(self):
        return self.children[0]

    def key(self):
        return ("project", self.child.key(),
                tuple((n, e.key()) for n, e in self.exprs))


class Filter(Node):
    def __init__(self, child: Node, predicate: Expr):
        self.children = [child]
        self.predicate = predicate
        self.schema = dict(child.schema)

    @property
    def child(self):
        return self.children[0]

    def key(self):
        return ("filter", self.child.key(), self.predicate.key())


class Aggregate(Node):
    def __init__(self, child: Node, keys: Sequence[str],
                 aggs: Sequence[Tuple[str, str, str]]):
        self.children = [child]
        self.keys = list(keys)
        self.aggs = list(aggs)
        sch: Schema = {k: child.schema[k] for k in self.keys}
        for col, op, out in self.aggs:
            sch[out] = agg_dtype(op, child.schema[col])
        self.schema = sch

    @property
    def child(self):
        return self.children[0]

    def key(self):
        return ("agg", self.child.key(), tuple(self.keys),
                tuple(self.aggs))


class Reduce(Node):
    """Whole-column reductions (Series.sum() etc.) — 1-row output."""

    def __init__(self, child: Node, aggs: Sequence[Tuple[str, str, str]]):
        self.children = [child]
        self.aggs = list(aggs)
        sch: Schema = {}
        for col, op, out in self.aggs:
            sch[out] = agg_dtype(op, child.schema[col])
        self.schema = sch

    @property
    def child(self):
        return self.children[0]

    def key(self):
        return ("reduce", self.child.key(), tuple(self.aggs))


class Union(Node):
    """UNION ALL / concat of schema-compatible inputs (reference:
    LogicalSetOperation plan.py, streaming union op)."""

    def __init__(self, children):
        assert len(children) >= 2
        self.children = list(children)
        first = children[0].schema
        for c in children[1:]:
            if list(c.schema) != list(first):
                raise ValueError(
                    f"union schema mismatch: {list(first)} vs "
                    f"{list(c.schema)}")
            for name in first:
                a, b = first[name], c.schema[name]
                if a is b:
                    continue
                if dt.is_numeric(a) and dt.is_numeric(b):
                    continue  # concat_tables promotes
                if dt.is_decimal(a) or dt.is_decimal(b):
                    # concat_tables handles every decimal mix: same-scale
                    # decimals keep the type, otherwise float64 descale
                    if (dt.is_decimal(a) or dt.is_numeric(a)) and \
                            (dt.is_decimal(b) or dt.is_numeric(b)):
                        continue
                raise ValueError(
                    f"union dtype mismatch on {name}: {a.name} vs {b.name}")
        self.schema = dict(first)
        # decimal result type mirrors concat_tables' promotion: all same
        # scale → widest precision; mixed scale / decimal+float → float64
        for name, a in first.items():
            kinds = [c.schema[name] for c in self.children]
            if any(dt.is_decimal(k) for k in kinds):
                scales = {k.scale for k in kinds if dt.is_decimal(k)}
                if len(scales) == 1 and all(dt.is_decimal(k)
                                            for k in kinds):
                    self.schema[name] = dt.decimal(
                        scales.pop(),
                        precision=max(k.precision for k in kinds))
                else:
                    self.schema[name] = dt.FLOAT64

    def key(self):
        return ("union", tuple(c.key() for c in self.children))


class Window(Node):
    """Row-aligned window transforms (cumsum/rolling/shift/diff) —
    specs = [(col, op, param, outname)]."""

    def __init__(self, child: Node, specs):
        self.children = [child]
        self.specs = [tuple(s) for s in specs]
        sch = dict(child.schema)
        for col, op, param, out in self.specs:
            sch[out] = dt.INT64 if op == "rowid" else dt.FLOAT64
        self.schema = sch

    @property
    def child(self):
        return self.children[0]

    def key(self):
        return ("window", self.child.key(), tuple(self.specs))


class RankWindow(Node):
    """Partitioned ranking windows: specs = [(op, param, out)] with op in
    row_number/rank/dense_rank/ntile/cumcount (SQL OVER(PARTITION BY ...
    ORDER BY ...); pandas groupby.rank/cumcount)."""

    def __init__(self, child: Node, partition_by, order_by, ascending,
                 specs):
        self.children = [child]
        self.partition_by = list(partition_by)
        self.order_by = list(order_by)
        self.ascending = list(ascending)
        self.specs = [tuple(s) for s in specs]
        sch = dict(child.schema)
        for op, param, out in self.specs:
            sch[out] = dt.INT64
        self.schema = sch

    @property
    def child(self):
        return self.children[0]

    def key(self):
        return ("rankwin", self.child.key(), tuple(self.partition_by),
                tuple(self.order_by), tuple(self.ascending),
                tuple(self.specs))


class AggWindow(Node):
    """Aggregate/navigation windows: specs = [(op, col, frame, param,
    out)] with op in sum/mean/count/min/max/lead/lag/first_value/
    last_value; frame = ("all",) | ("cumrange",) | ("rows", lo, hi)
    (SQL OVER(... ROWS BETWEEN ...); pandas groupby.transform /
    groupby.shift)."""

    def __init__(self, child: Node, partition_by, order_by, ascending,
                 specs):
        from bodo_tpu.ops.groupby import agg_dtype
        self.children = [child]
        self.partition_by = list(partition_by)
        self.order_by = list(order_by)
        self.ascending = list(ascending)
        self.specs = [(op, col, tuple(frame), param, out)
                      for op, col, frame, param, out in specs]
        sch = dict(child.schema)
        for op, col, frame, param, out in self.specs:
            src = sch[col]
            if op in ("lead", "lag", "first_value", "last_value"):
                sch[out] = src
            elif op == "count":
                sch[out] = dt.INT64
            else:
                sch[out] = agg_dtype("sum" if op == "sum0" else op, src)
        self.schema = sch

    @property
    def child(self):
        return self.children[0]

    def key(self):
        return ("aggwin", self.child.key(), tuple(self.partition_by),
                tuple(self.order_by), tuple(self.ascending),
                tuple(self.specs))


class Join(Node):
    def __init__(self, left: Node, right: Node, left_on, right_on,
                 how: str = "inner", suffixes=("_x", "_y"),
                 null_equal: bool = True):
        self.children = [left, right]
        self.left_on = list(left_on)
        self.right_on = list(right_on)
        self.how = how
        self.suffixes = tuple(suffixes)
        # pandas merge matches NaN keys to each other; SQL joins don't
        self.null_equal = null_equal
        overlap = (set(left.schema) & set(right.schema)) - \
            (set(self.left_on) & set(self.right_on))
        sch: Schema = {}
        for n, t in left.schema.items():
            sch[n + suffixes[0] if n in overlap else n] = t
        for i, (n, t) in enumerate(right.schema.items()):
            if n in self.right_on and \
                    self.left_on[self.right_on.index(n)] == n:
                continue
            sch[n + suffixes[1] if n in overlap else n] = t
        self.schema = sch

    @property
    def left(self):
        return self.children[0]

    @property
    def right(self):
        return self.children[1]

    def key(self):
        return ("join", self.left.key(), self.right.key(),
                tuple(self.left_on), tuple(self.right_on), self.how,
                self.suffixes, self.null_equal)


class NonEquiJoin(Node):
    """Join under an arbitrary predicate with no equality conjunct
    (tiled nested-loop / interval join; reference:
    bodo/libs/_nested_loop_join_impl.cpp, _interval_join.cpp). Column
    names must already be disjoint (the SQL planner's qualified names
    are); the predicate references the combined schema."""

    def __init__(self, left: Node, right: Node, pred, how: str = "inner"):
        assert how in ("inner", "left"), how
        self.children = [left, right]
        self.pred = pred
        self.how = how
        overlap = set(left.schema) & set(right.schema)
        assert not overlap, f"NonEquiJoin needs disjoint names: {overlap}"
        sch: Schema = dict(left.schema)
        sch.update(right.schema)
        self.schema = sch

    @property
    def left(self):
        return self.children[0]

    @property
    def right(self):
        return self.children[1]

    def key(self):
        return ("nejoin", self.left.key(), self.right.key(),
                self.pred.key(), self.how)


class Sort(Node):
    def __init__(self, child: Node, by, ascending, na_last: bool = True):
        self.children = [child]
        self.by = list(by)
        self.ascending = list(ascending)
        self.na_last = na_last
        self.schema = dict(child.schema)

    @property
    def child(self):
        return self.children[0]

    def key(self):
        return ("sort", self.child.key(), tuple(self.by),
                tuple(self.ascending), self.na_last)


class Limit(Node):
    def __init__(self, child: Node, n: int):
        self.children = [child]
        self.n = n
        self.schema = dict(child.schema)

    @property
    def child(self):
        return self.children[0]

    def key(self):
        return ("limit", self.child.key(), self.n)


class Distinct(Node):
    def __init__(self, child: Node, subset: Optional[Sequence[str]] = None):
        self.children = [child]
        self.subset = list(subset) if subset else list(child.schema)
        self.schema = dict(child.schema)

    @property
    def child(self):
        return self.children[0]

    def key(self):
        return ("distinct", self.child.key(), tuple(self.subset))


def _arrow_field_dtype(typ) -> dt.DType:
    import pyarrow as pa
    if pa.types.is_dictionary(typ) or pa.types.is_string(typ) or \
            pa.types.is_large_string(typ):
        return dt.STRING
    if pa.types.is_timestamp(typ):
        return dt.DATETIME
    if pa.types.is_date(typ):
        return dt.DATE
    if pa.types.is_duration(typ):
        return dt.TIMEDELTA
    if pa.types.is_struct(typ):
        from bodo_tpu.io.arrow_bridge import _arrow_scalar_dtype
        return dt.struct_of([(f.name, _arrow_scalar_dtype(f.type))
                             for f in typ])
    if pa.types.is_map(typ):
        from bodo_tpu.io.arrow_bridge import _arrow_scalar_dtype
        return dt.map_of(_arrow_scalar_dtype(typ.key_type),
                         _arrow_scalar_dtype(typ.item_type))
    if pa.types.is_list(typ) or pa.types.is_large_list(typ):
        from bodo_tpu.io.arrow_bridge import _arrow_scalar_dtype
        return dt.list_of(_arrow_scalar_dtype(typ.value_type))
    return dt.from_numpy(np.dtype(typ.to_pandas_dtype()))
