"""Distributed (1D) streaming execution: sharded batches + overlapped
all_to_all shuffle.

TPU-native redesign of the reference's distributed streaming operators
(reference: bodo/libs/streaming/_shuffle.h:777 `IncrementalShuffleState`
async sends overlapping compute, streaming/_groupby.cpp
`GroupbyState::UpdateGroupsAndCombine`, streaming/_join.h:892). Where the
reference posts MPI_Ialltoallv per batch and polls completion, here every
batch runs ONE fused jitted shard_map step —

    local partial aggregation
      → hash-bucket fixed-capacity `lax.all_to_all` to the owner shard
      → merge into the per-shard packed state

— and the host never syncs inside the loop: group counts stay on device,
state capacities are sized from host-known row-count BOUNDS, and the
shuffle-overflow flag is checked one batch LATE (deferred sync). By the
time batch k+1 is decoded on host, batch k's device work has already been
dispatched — XLA's async dispatch gives the same compute/communication
overlap the reference gets from MPI_Ialltoallv. On overflow the step is
re-run from a kept pre-state at a larger bucket capacity (the analogue of
the reference's partition re-splitting, streaming/_join.h:267); the
always-safe bound is the per-shard batch capacity, so the retry loop
terminates.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from bodo_tpu import relational as R
from bodo_tpu.config import config
from bodo_tpu.ops.groupby import (DECOMPOSE, groupby_local, groupby_merge,
                                  result_dtype)
from bodo_tpu.parallel import collectives as C
from bodo_tpu.parallel import mesh as mesh_mod
from bodo_tpu.parallel.shuffle import (_MESHES, _mesh_key,
                                       _plan_decomposition, _finalize,
                                       shuffle_partials)
from bodo_tpu.plan.streaming import _bucket_cap as _pow2_cap
from bodo_tpu.table import dtypes as dt
from bodo_tpu.table.table import Column, ONED, REP, Table
from bodo_tpu.utils.kernel_cache import cached_builder
from bodo_tpu.utils.logging import log


# ---------------------------------------------------------------------------
# sharded re-capacity / slicing (shard_map helpers)
# ---------------------------------------------------------------------------

@cached_builder("streaming")
def _build_recap(mesh_key, old_per: int, new_per: int):
    mesh = _MESHES[mesh_key]
    axis = config.data_axis

    def body(tree):
        def one(a):
            if a is None:
                return None
            if new_per <= old_per:
                return a[:new_per]
            pad = jnp.zeros((new_per - old_per,) + a.shape[1:], a.dtype)
            return jnp.concatenate([a, pad])
        return {n: (one(d), one(v)) for n, (d, v) in tree.items()}

    return jax.jit(C.smap(body, in_specs=(P(axis),), out_specs=P(axis),
                          mesh=mesh))


def shard_recapacity(t: Table, new_per: int, mesh=None) -> Table:
    """Change a 1D table's PER-SHARD capacity (device-side pad/slice, no
    host transit). Rows stay packed at the front of each shard."""
    assert t.distribution == ONED
    m = mesh or mesh_mod.get_mesh()
    per = t.shard_capacity
    if per == new_per:
        return t
    assert new_per >= int(t.counts.max(initial=0)), (new_per, t.counts)
    fn = _build_recap(_mesh_key(m), per, new_per)
    tree = fn(t.device_data())
    return t.with_device_data(tree, nrows=t.nrows, counts=t.counts)


@cached_builder("streaming")
def _build_slicer(mesh_key, per: int, bcap: int):
    mesh = _MESHES[mesh_key]
    axis = config.data_axis

    def body(tree, off):
        o = off[0]

        def one(a):
            if a is None:
                return None
            return lax.dynamic_slice_in_dim(a, o, bcap)
        return {n: (one(d), one(v)) for n, (d, v) in tree.items()}

    return jax.jit(C.smap(body, in_specs=(P(axis), P(axis)),
                          out_specs=P(axis), mesh=mesh))


def table_batches_sharded(t: Table, batch_rows: int,
                          mesh=None) -> Iterator[Table]:
    """Slice a 1D table into fixed-capacity 1D batches. All shards step in
    lockstep (a shard that ran out of rows contributes count-0 batches) so
    every per-batch collective sees the full mesh."""
    assert t.distribution == ONED
    m = mesh or mesh_mod.get_mesh()
    S = mesh_mod.num_shards(m)
    bcap = _pow2_cap(batch_rows)
    per = t.shard_capacity
    if per % bcap != 0:
        per = ((per + bcap - 1) // bcap) * bcap
        t = shard_recapacity(t, per, m)
    fn = _build_slicer(_mesh_key(m), per, bcap)
    max_count = int(t.counts.max(initial=0))
    n_batches = max(1, -(-max_count // bcap))
    off_shard = mesh_mod.row_sharding(m)
    for b in range(n_batches):
        off = b * bcap
        counts_b = np.clip(t.counts - off, 0, bcap).astype(np.int64)
        off_dev = jax.device_put(
            np.full((S,), off, dtype=np.int32), off_shard)
        tree = fn(t.device_data(), off_dev)
        yield t.with_device_data(tree, nrows=int(counts_b.sum()),
                                 counts=counts_b)


def parquet_batches_sharded(path: str, columns: Optional[Sequence[str]],
                            batch_rows: int, mesh=None) -> Iterator[Table]:
    """Stream a parquet dataset as 1D batches: fixed row windows scatter
    over the mesh at a FIXED per-shard capacity so every downstream
    kernel compiles once. With device decode on, the inner source ships
    raw page bytes and decodes on-chip (io/device_decode.py), so the
    host never materializes decoded windows at all."""
    from bodo_tpu.plan.streaming import parquet_batches
    from bodo_tpu.runtime.io_pool import prefetched
    # prefetch below the scatter: Arrow decode of window k+1 overlaps
    # the device-side shard/recapacity of window k
    return _shard_batches(
        prefetched(parquet_batches(path, columns, batch_rows),
                   label="parquet_sharded"),
        batch_rows, mesh)


def csv_batches_sharded(path: str, columns: Optional[Sequence[str]],
                        parse_dates, batch_rows: int,
                        mesh=None) -> Iterator[Table]:
    """Stream a CSV file as 1D batches (byte-range chunked host parse →
    fixed-capacity scatter; reference: the parallel chunked CSV scan,
    bodo/io/_csv_json_reader.cpp)."""
    from bodo_tpu.plan.streaming import csv_batches
    from bodo_tpu.runtime.io_pool import prefetched
    return _shard_batches(
        prefetched(csv_batches(path, columns, parse_dates, batch_rows),
                   label="csv_sharded"),
        batch_rows, mesh)


def _shard_batches(src: Iterator[Table], batch_rows: int,
                   mesh=None) -> Iterator[Table]:
    m = mesh or mesh_mod.get_mesh()
    S = mesh_mod.num_shards(m)
    bcap_s = _pow2_cap(-(-batch_rows // S))
    with mesh_mod.use_mesh(m):
        for rep_batch in src:
            sh = rep_batch.shard()
            out = shard_recapacity(sh, bcap_s, m)
            # scan provenance survives the scatter: fusion's
            # device_scan_batches counter and the bench scan suite read
            # this flag off sharded batches too
            if getattr(rep_batch, "_device_decoded", False):
                out._device_decoded = True
            yield out


# ---------------------------------------------------------------------------
# sharded streaming groupby
# ---------------------------------------------------------------------------

@cached_builder("streaming")
def _build_sharded_step(mesh_key, num_keys: int, specs: Tuple[str, ...],
                        bucket_cap: int, state_cap: int):
    """One streamed-groupby step: partial-agg the batch, shuffle partial
    rows to their hash-owner shard, merge into the per-shard state. The
    whole step is one jitted shard_map program — XLA overlaps the
    all_to_all with the surrounding compute, and nothing in it forces a
    host sync."""
    mesh = _MESHES[mesh_key]
    axis = config.data_axis
    S = mesh.shape[axis]
    partial_specs, combine_specs, _ = _plan_decomposition(specs)

    def body(batch_arrays, batch_counts, state_arrays, state_counts):
        count = batch_counts[0]
        n_state = state_counts[0]
        cap = batch_arrays[0][0].shape[0]
        keys = batch_arrays[:num_keys]
        values = batch_arrays[num_keys:]
        p_inputs = tuple(keys) + tuple(
            values[i] for i, op in enumerate(specs)
            for _ in DECOMPOSE[op])
        pk, pv, ng = groupby_local(p_inputs, count, partial_specs, cap,
                                   num_keys)
        rk, rv, cnt, ovf = shuffle_partials(pk, pv, num_keys, S,
                                            bucket_cap, ng, axis)
        state_flat = tuple(state_arrays[0]) + tuple(state_arrays[1])
        mk, mv, ng2 = groupby_merge(state_flat, rk + rv,
                                    n_state, cnt, combine_specs,
                                    state_cap, num_keys)
        return (mk, mv), ng2[None], ovf[None]

    shd = C.smap(body, in_specs=(P(axis), P(axis), P(axis), P(axis)),
                 out_specs=(P(axis), P(axis), P(axis)), mesh=mesh)
    return jax.jit(shd)


class ShardedGroupbyAccumulator:
    """Distributed streaming groupby over 1D batches.

    Per-shard packed state holds the groups HASH-OWNED by that shard
    (keys + partial-agg columns); finish() finalizes in place, so the
    result is already a valid 1D table — no gather anywhere.

    Pipelining: push(k) dispatches step k FIRST; overflow flags and
    output group counts resolve in WINDOWS of RESOLVE_WINDOW dispatches
    — all of a window's flags plus the newest resolved count travel in
    ONE batched `jax.device_get`, so host syncs per stage are
    O(batches / W), not O(batches), and by the time a window retires its
    flags are long computed (no read ever stalls the pipe). The host
    therefore knows the exact per-shard group count with an at-most-W-
    batch lag and sizes the state capacity as known_count + one recv
    window per in-flight dispatch — flat in the number of batches. The
    rare overflow rewinds to the kept pre-state of the FIRST overflowed
    dispatch and replays from there at a larger bucket capacity
    (O(W batches + 1 state) extra memory, the price of never blocking
    on a flag read).
    """

    RESOLVE_WINDOW = 8

    def __init__(self, keys: Sequence[str], aggs: Sequence[Tuple],
                 mesh=None):
        self.keys = list(keys)
        self.aggs = list(aggs)
        self.specs = tuple(op for _, op, _ in aggs)
        self.partial_specs, self.combine_specs, self.layout = \
            _plan_decomposition(self.specs)
        self.mesh = mesh or mesh_mod.get_mesh()
        self.S = mesh_mod.num_shards(self.mesh)
        self._mk = _mesh_key(self.mesh)
        self._state: Optional[Tuple] = None   # ((mk, mv), counts_dev)
        self._state_meta: Optional[List[Tuple]] = None
        self._known = 0          # exact max per-shard groups, 1 batch stale
        self._bucket_cap: Optional[int] = None
        self._state_cap = 0
        # unresolved dispatches: (pre_state, inputs, ovf, out, bcap)
        self._queue: List[Tuple] = []
        self._template: Optional[Table] = None
        self.peak_state_cap = 0  # observability: max per-shard state rows
        self.n_retries = 0       # observability: overflow replays
        from bodo_tpu.runtime.memory_governor import governor
        self._grant = governor().admit("stream_groupby")

    # -- schema plumbing ----------------------------------------------------

    def _plan_meta(self, batch: Table) -> None:
        """(name, DType, dictionary, src_column) for state columns: keys
        then one column per partial spec. src_column tracks which batch
        column's dictionary a dict-coded state column follows."""
        meta = []
        for k in self.keys:
            c = batch.column(k)
            meta.append((k, c.dtype, c.dictionary,
                         k if c.dictionary is not None else None))
        pi = 0
        for (cname, op, _), parts in zip(self.aggs,
                                         (DECOMPOSE[s] for s in self.specs)):
            src = batch.column(cname)
            for pop in parts:
                if pop in ("min", "max", "first", "last"):
                    meta.append((f"__p{pi}", src.dtype, src.dictionary,
                                 cname if src.dictionary is not None
                                 else None))
                else:
                    meta.append((f"__p{pi}",
                                 dt.from_numpy(result_dtype(pop,
                                                            src.dtype.numpy)),
                                 None, None))
                pi += 1
        self._state_meta = meta

    def _zero_state(self, state_cap: int) -> Tuple:
        nk = len(self.keys)
        sh = mesh_mod.row_sharding(self.mesh)
        cols = []
        for name, dtype, _, _ in self._state_meta:
            d = jax.device_put(
                np.zeros((self.S * state_cap,), dtype=dtype.numpy), sh)
            v = jax.device_put(np.zeros((self.S * state_cap,), bool), sh)
            cols.append((d, v))
        counts = jax.device_put(np.zeros((self.S,), np.int64), sh)
        return ((tuple(cols[:nk]), tuple(cols[nk:])), counts)

    def _batch_inputs(self, batch: Table):
        arrays = tuple((batch.column(k).data, batch.column(k).valid)
                       for k in self.keys)
        arrays += tuple((batch.column(c).data, batch.column(c).valid)
                        for c, _, _ in self.aggs)
        return arrays, batch.counts_device()

    # -- streaming protocol -------------------------------------------------

    def push(self, batch: Table) -> None:
        assert batch.distribution == ONED
        if self._template is None:
            self._template = batch
            self._plan_meta(batch)
        if batch.nrows == 0 and self._state is not None:
            return
        bcap = batch.shard_capacity
        if self._bucket_cap is None:
            tight = int(config.shuffle_skew_factor * bcap / self.S) + 64
            self._bucket_cap = min(_pow2_cap(tight), _pow2_cap(bcap))

        # re-code sharded state onto any grown dictionaries
        bdicts = self._batch_dicts(batch)
        self._absorb_dicts(bdicts)

        # state sizing gates on the last EXACT count plus this batch's
        # worst case only — NOT a worst-case sum over the in-flight
        # queue, which would force a drain (host sync) every few batches
        # whenever the state is small relative to the batch size. Queued
        # dispatches may have grown the true count past _known; that is
        # caught at window resolution (the step's ng2 is the TRUE group
        # count even when the state scatter dropped rows past capacity)
        # and repaired by the same rewind-replay that handles bucket
        # overflow, so the steady state keeps its O(B/W) sync cadence
        # and its flat capacity.
        recv = min(self.S * self._bucket_cap, self.S * bcap)
        need = self._known + recv
        if self._state is None:
            # first push: _known is definitionally stale (no resolve has
            # run yet) — budget one extra recv window of headroom so the
            # steady-state capacity is reached immediately rather than
            # via a growth step after the first exact count lands
            self._state_cap = _pow2_cap(max(2 * recv, 1))
            self._state = self._zero_state(self._state_cap)
        elif need > self._state_cap:
            self._state_cap = _pow2_cap(need)
            self._state = self._recap_state(self._state, self._state_cap)
        self._dispatch(self._batch_inputs(batch), bcap, bdicts)
        # resolve in windows, always after launching the newest dispatch:
        # a full window's flags retire with one batched host read, and
        # the newest dispatch stays in flight to keep decode(n+1)
        # overlapping compute(n)
        if len(self._queue) >= self.RESOLVE_WINDOW:
            self._resolve_window(len(self._queue) - 1)

    def _dispatch(self, inputs, bcap: int, bdicts) -> None:
        from bodo_tpu.parallel import comm
        from bodo_tpu.utils import tracing
        arrays, counts = inputs
        pre_state = self._state
        step = _build_sharded_step(self._mk, len(self.keys), self.specs,
                                   self._bucket_cap, self._state_cap)
        (st, cnts) = pre_state
        # per-batch lockstep sequence number (ROADMAP item 6: streaming
        # collectives carry seq numbers like the host-level dispatchers).
        # Overflow replays re-enter here too, but the ovf flags are SPMD-
        # deterministic so every rank replays the same batches — the seq
        # streams stay aligned.
        wait = 0.0
        if self.S > 1:
            from bodo_tpu.analysis import lockstep
            wait = lockstep.pre_collective("stream1d_step")
        in_bytes = sum(int(getattr(leaf, "nbytes", 0))
                       for leaf in jax.tree_util.tree_leaves(arrays))
        with tracing.event("stream1d_step"), \
                comm.collective_span("stream1d_step", bytes_in=in_bytes,
                                     wait_s=wait) as sp:
            mkv, ng2, ovf = step(arrays, counts, st, cnts)
            sp["bytes_out"] = sum(
                int(getattr(leaf, "nbytes", 0))
                for leaf in jax.tree_util.tree_leaves(mkv))
        self._state = (mkv, ng2)
        self._queue.append({
            "pre_state": pre_state,
            "pre_meta": list(self._state_meta),
            "inputs": inputs, "bdicts": bdicts,
            "ovf": ovf, "out_counts": ng2, "bcap": bcap,
            "scap": self._state_cap})
        self.peak_state_cap = max(self.peak_state_cap, self._state_cap)
        row_bytes = sum(m[1].numpy.itemsize + 1 for m in self._state_meta)
        self._grant.update(self.S * self._state_cap * row_bytes)

    def _resolve_oldest(self) -> None:
        self._resolve_window(1)

    def _resolve_window(self, k: int) -> None:
        """Retire the oldest k dispatches with ONE batched host read:
        every flag in the window plus the newest retired dispatch's
        group counts ride a single `jax.device_get`."""
        from bodo_tpu.plan.streaming import _note_sync
        if not self._queue or k <= 0:
            return
        k = min(k, len(self._queue))
        entries = self._queue[:k]
        _note_sync()
        got = jax.device_get(  # dispatch-boundary
            [e["ovf"] for e in entries]
            + [e["out_counts"] for e in entries])
        flags = [np.asarray(f).reshape(-1) for f in got[:k]]
        counts = [int(np.asarray(c).reshape(-1).max(initial=0))
                  for c in got[k:]]
        # two overflow modes per entry: the shuffle bucket dropped rows
        # (ovf flag), or the state scatter dropped groups — visible as
        # the TRUE group count ng2 exceeding the capacity the step was
        # built with (push sizes state from a one-window-stale count)
        first_bad = next(
            (i for i, (f, e2, c) in enumerate(zip(flags, entries, counts))
             if f.any() or c > e2["scap"]), None)
        if first_bad is None:
            self._queue = self._queue[k:]
            self._known = counts[-1]
            return
        bucket_bad = bool(flags[first_bad].any())
        # dispatches before the first overflow resolved clean — adopt
        # the last clean count
        self._queue = self._queue[first_bad:]
        if first_bad > 0:
            self._known = counts[first_bad - 1]
        e = self._queue.pop(0)
        # overflow: every dispatch from this one on was built on a state
        # missing the dropped rows — rewind state AND dictionary metadata
        # to just before it, then replay them all at a larger bucket
        # capacity (terminates: per-shard batch capacity is always safe).
        # Each replayed batch re-applies its own dictionary growth so the
        # rewound (older-dict) state is re-coded exactly as it was the
        # first time through.
        self.n_retries += 1
        replay = [e] + self._queue
        self._queue = []
        self._state = e["pre_state"]
        self._state_meta = list(e["pre_meta"])
        self._state_cap = e["scap"]  # capacity the rewound state has
        safe = max(_pow2_cap(x["bcap"]) for x in replay)
        if bucket_bad:
            self._bucket_cap = min(self._bucket_cap * 4, safe)
        # state overflow needs no explicit growth here: _known is exact
        # after the rewind, so the replay loop's known+recv sizing grows
        # the state just enough before re-dispatching
        log(1, f"stream1d overflow ({'bucket' if bucket_bad else 'state'})"
               f": replaying {len(replay)} batches at "
               f"bucket_cap={self._bucket_cap}")
        for x in replay:
            self._absorb_dicts(x["bdicts"])
            while True:
                recv = min(self.S * self._bucket_cap, self.S * x["bcap"])
                need = self._known + recv
                if need > self._state_cap:
                    self._state_cap = _pow2_cap(need)
                    self._state = self._recap_state(self._state,
                                                    self._state_cap)
                self._dispatch(x["inputs"], x["bcap"], x["bdicts"])
                e2 = self._queue.pop()
                _note_sync()
                f2, c2 = (np.asarray(a).reshape(-1) for a in
                          jax.device_get(  # dispatch-boundary
                              [e2["ovf"], e2["out_counts"]]))
                if not f2.any():
                    self._known = int(c2.max(initial=0))
                    break
                self._state = e2["pre_state"]
                assert self._bucket_cap < safe, \
                    "shuffle overflow at safe capacity"
                self._bucket_cap = min(self._bucket_cap * 4, safe)

    def _recap_state(self, state, new_cap: int):
        (mk, mv), cnts = state
        nk = len(self.keys)
        tree = {}
        for i, (d, v) in enumerate(tuple(mk) + tuple(mv)):
            tree[f"c{i:03d}"] = (d, v)
        fn = _build_recap(self._mk, next(iter(tree.values()))[0].shape[0]
                          // self.S, new_cap)
        out = fn(tree)
        cols = [out[f"c{i:03d}"] for i in range(nk + len(mv))]
        return ((tuple(cols[:nk]), tuple(cols[nk:])), cnts)

    def _batch_dicts(self, batch: Table) -> List[Optional[np.ndarray]]:
        """The batch's dictionary per state column (None for non-dict)."""
        return [batch.column(src).dictionary if src is not None else None
                for (_, _, _, src) in self._state_meta]

    def _absorb_dicts(self, bdicts: List[Optional[np.ndarray]]) -> None:
        """Re-code dict-coded state columns when the source dictionary has
        grown (elementwise LUT gather — sharding-preserving, no
        collective). Invariant (held by the sources' DictTracker and by
        batches sliced from one table): a batch's dictionary is always a
        superset of every earlier batch's, so the state dict is a subset
        of the incoming one and batch codes never need re-coding here."""
        if self._state is None:
            return
        from bodo_tpu.plan.streaming import remap_codes
        nk = len(self.keys)
        (mk, mv), cnts = self._state
        cols = list(mk) + list(mv)
        changed = False
        for i, (name, dtype, sdict, src) in enumerate(self._state_meta):
            if src is None:
                continue
            bdict = bdicts[i]
            if sdict is None or bdict is None or sdict is bdict or \
                    len(bdict) == len(sdict):
                continue
            d, v = cols[i]
            col = remap_codes(Column(d, v, dtype, sdict), bdict)
            cols[i] = (col.data, col.valid)
            self._state_meta[i] = (name, dtype, bdict, src)
            changed = True
        if changed:
            self._state = ((tuple(cols[:nk]), tuple(cols[nk:])), cnts)

    def finish(self) -> Table:
        from bodo_tpu.plan.streaming import _note_sync
        assert self._template is not None, "empty stream"
        while self._queue:
            self._resolve_window(len(self._queue))
        nk = len(self.keys)
        (mk, mv), cnts_dev = self._state
        _note_sync()
        counts = np.asarray(
            jax.device_get(cnts_dev)).reshape(-1) \
            .astype(np.int64)  # dispatch-boundary
        cols: Dict[str, Column] = {}
        for (name, dtype, dic, _), (d, v) in zip(self._state_meta[:nk],
                                                 mk):
            cols[name] = Column(d, v, dtype, dic)
        # finalize partials → final agg columns (elementwise on the
        # sharded arrays; sharding-preserving)
        pcols = list(mv)
        for i, (cname, op, oname) in enumerate(self.aggs):
            off, n = self.layout[i]
            src_dt = self._template.column(cname).dtype
            d, v = _finalize(op, tuple(pcols[off + j] for j in range(n)),
                             jnp.dtype(src_dt.numpy))
            if op in ("min", "max", "first", "last"):
                rdt, dic = src_dt, self._state_meta[nk + off][2]
            else:
                rdt = dt.from_numpy(result_dtype(op, src_dt.numpy))
                dic = None
            cols[oname] = Column(d, v, rdt, dic)
        self._grant.release()
        return Table(cols, int(counts.sum()), ONED, counts)


# ---------------------------------------------------------------------------
# sharded stream compilation (mirrors streaming._build_stream)
# ---------------------------------------------------------------------------

class ShardedStreamJoin:
    """Per-batch 1D probe against a replicated build side (the runtime
    broadcast join over a stream; reference: streaming hash join with a
    broadcast build, bodo/libs/streaming/_join.h:892)."""

    def __init__(self, build: Table, left_on, right_on, how, suffixes,
                 null_equal: bool = True):
        self.left_on, self.right_on = left_on, right_on
        self.how, self.suffixes = how, suffixes
        self.null_equal = null_equal
        self.build = build.gather() if build.distribution != REP else build
        # warm the device-resident build table at construction: every
        # probe batch then hits the LRU entry (plan/fusion_join) instead
        # of rebuilding the claim table per batch
        from bodo_tpu.plan import fusion_join
        fusion_join.prime_build(self.build, self.right_on, self.null_equal)

    def __call__(self, batch: Table) -> Table:
        out = R.join_tables(batch, self.build, self.left_on, self.right_on,
                            self.how, self.suffixes,
                            null_equal=self.null_equal)
        if out.distribution != ONED:
            out = out.shard()
        cap = _pow2_cap(max(int(out.counts.max(initial=0)), 1))
        return shard_recapacity(out, cap)


def build_stream_sharded(node, mesh=None) -> Optional[Iterator[Table]]:
    """Compile a plan subtree into a 1D batch iterator, or None when a
    node has no sharded streaming form."""
    from bodo_tpu.plan import logical as L
    m = mesh or mesh_mod.get_mesh()
    batch_rows = config.streaming_batch_size

    if isinstance(node, L.ReadParquet):
        return parquet_batches_sharded(node.path, node.columns, batch_rows,
                                       m)
    if isinstance(node, L.ReadCsv):
        return csv_batches_sharded(node.path, node.columns,
                                   node.parse_dates, batch_rows, m)
    if isinstance(node, L.FromPandas):
        t = node.table
        if t.distribution != ONED:
            if t.nrows < mesh_mod.num_shards(m):
                return None
            t = t.shard()
        return table_batches_sharded(t, max(batch_rows //
                                            mesh_mod.num_shards(m), 128), m)
    if isinstance(node, (L.Filter, L.Projection)):
        # whole-stage fusion over 1D batches: one shard_map program per
        # chain with a single count sync, instead of per-stage dispatch
        from bodo_tpu.plan import fusion
        chain = fusion.stream_chain(node)
        if chain is not None:
            steps, src = chain
            inner = build_stream_sharded(src, m)
            if inner is None:
                return None
            out = fusion.fused_batches(steps, inner, sharded=True)
            if any(isinstance(s, L.Filter) for s in steps):
                from bodo_tpu.plan import adaptive
                out = adaptive.coalesce_batches(out, sharded=True)
            return out
    if isinstance(node, L.Filter):
        inner = build_stream_sharded(node.child, m)
        if inner is None:
            return None
        pred = node.predicate

        def gen_filter(src):
            for b in src:
                yield R.filter_table(b, pred)
        # coalesce undersized post-filter 1D batches (device-side
        # append_sharded) before the next per-batch collective
        from bodo_tpu.plan import adaptive
        return adaptive.coalesce_batches(gen_filter(inner), sharded=True)
    if isinstance(node, L.Projection):
        inner = build_stream_sharded(node.child, m)
        if inner is None:
            return None
        from bodo_tpu.plan.physical import apply_projection
        exprs = node.exprs

        def gen_project(src):
            for b in src:
                yield apply_projection(b, exprs)
        return gen_project(inner)
    if isinstance(node, L.Join):
        if node.how not in ("inner", "left"):
            return None
        inner = build_stream_sharded(node.left, m)
        if inner is None:
            return None

        def _keys_streamable() -> bool:
            # Key dtypes must agree exactly (the stream skips
            # join_tables' promotion step) and string keys need a shared
            # dictionary — whole-table path otherwise.
            return not any(
                node.left.schema[lk] is not node.right.schema[rk]
                or node.left.schema[lk] is dt.STRING
                for lk, rk in zip(node.left_on, node.right_on))

        def _pjoin_gen(pj, src):
            # close() in finally: an abandoned consumer (generator GC'd
            # before exhaustion) must not leak parked host-pool chunks
            try:
                for b in src:
                    out = pj.probe(b)
                    if out is not None:
                        yield out
                yield from pj.drain()
            finally:
                pj.close()

        # stream the build side too when its subtree has a streaming
        # form: batches buffer (device) only up to the broadcast
        # threshold, then switch to the partitioned join — the build is
        # never fully materialized (reference: build streamed from the
        # scan into partitions, bodo/libs/streaming/_join.h:267).
        build_src = build_stream_sharded(node.right, m)
        if build_src is not None and _keys_streamable():
            try:
                pj = ShardedPartitionedJoin(
                    node.left_on, node.right_on, node.how, node.suffixes,
                    node.null_equal, m)
            except NotImplementedError:
                return None
            buffered: Optional[Table] = None
            nbb = 0
            for bb in build_src:
                nbb += 1
                if pj.state is not None or pj.spilling:
                    if not pj.push_build(bb):
                        return None
                    continue
                if not _dicts_compatible(buffered, bb):
                    return None
                buffered = append_sharded(buffered, bb, m)
                if buffered.nrows > config.bcast_join_threshold:
                    if not pj.push_build(buffered):
                        return None
                    buffered = None
            if pj.state is not None or pj.spilling:
                log(1, f"streaming partitioned join: build streamed over "
                       f"{nbb} batches"
                       + (f", {len(pj.build_chunks)} spilled chunks"
                          if pj.spilling else ""))
                return _pjoin_gen(pj, inner)
            if buffered is None:
                pj.close()
                return None  # empty build stream
            pj.close()  # build fit under the broadcast threshold
            log(1, f"streaming join: build streamed over {nbb} batches "
                   f"({buffered.nrows} rows, broadcast)")
            join = ShardedStreamJoin(buffered, node.left_on,
                                     node.right_on, node.how,
                                     node.suffixes, node.null_equal)

            def gen_join_s(src):
                for b in src:
                    yield join(b)
            return gen_join_s(inner)

        from bodo_tpu.plan import physical
        build = physical._exec(node.right)
        if build.nrows > config.bcast_join_threshold:
            if not _keys_streamable():
                return None
            try:
                pj = ShardedPartitionedJoin(
                    node.left_on, node.right_on, node.how, node.suffixes,
                    node.null_equal, m)
            except NotImplementedError:
                return None
            bt = build if build.distribution == ONED else build.shard()
            nbb = 0
            for bb in table_batches_sharded(
                    bt, max(batch_rows // mesh_mod.num_shards(m), 128),
                    m):
                if not pj.push_build(bb):
                    return None
                nbb += 1
            if pj.state is None and not pj.spilling:
                pj.close()
                return None
            log(1, f"streaming partitioned join: build state over "
                   f"{nbb} batches")
            return _pjoin_gen(pj, inner)
        join = ShardedStreamJoin(build, node.left_on, node.right_on,
                                 node.how, node.suffixes, node.null_equal)

        def gen_join(src):
            for b in src:
                yield join(b)
        return gen_join(inner)
    return None


def try_stream_execute_sharded(node) -> Optional[Table]:
    """Streaming executor over the full mesh: groupby plans stream 1D
    batches through the overlapped-shuffle accumulator. None → caller
    falls back to whole-table execution."""
    from bodo_tpu.plan import adaptive
    from bodo_tpu.plan import logical as L
    if not config.stream_exec:
        return None
    from bodo_tpu.runtime.resilience import maybe_inject
    maybe_inject("stage.boundary")
    m = mesh_mod.get_mesh()
    if mesh_mod.num_shards(m) <= 1:
        return None

    if isinstance(node, L.Aggregate):
        if any(dt.is_decimal(node.child.schema[c])
               for c, _, _ in node.aggs):
            return None
        if any(op not in DECOMPOSE for _, op, _ in node.aggs):
            return None
        if not node.keys:
            return None
        src = build_stream_sharded(node.child, m)
        if src is None:
            return None
        try:
            acc = ShardedGroupbyAccumulator(node.keys, node.aggs, m)
        except NotImplementedError:
            return None
        from bodo_tpu.plan.streaming import _note_batch
        nb = 0
        for b in src:
            adaptive.observe_batch(b)
            acc.push(b)
            nb += 1
            _note_batch()
        if acc._template is None:
            acc._grant.release()
            return None
        out = acc.finish()
        log(1, f"sharded streaming groupby: {nb} batches, "
               f"{out.nrows} groups over {acc.S} shards")
        return out

    if isinstance(node, L.Sort):
        # stream batches into 1D state (one pass over the child), then
        # one range exchange + local sort over the accumulated state
        src1 = build_stream_sharded(node.child, m)
        if src1 is None:
            return None
        from bodo_tpu.plan.streaming import _note_batch
        ss = ShardedStreamSort(node.by, node.ascending, node.na_last, m)
        nb = 0
        for b in src1:
            adaptive.observe_batch(b)
            if not ss.push(b):
                return None  # dict drift across batches: whole-table
            nb += 1
            _note_batch()
        if ss.state is None and not ss.runs:
            ss.close()
            return None
        out = ss.finish()
        log(1, f"sharded streaming sort: {nb} batches, {out.nrows} rows "
               f"over {ss.S} shards")
        return out

    return None


# ---------------------------------------------------------------------------
# per-shard append (shared by streaming join build state and sort state)
# ---------------------------------------------------------------------------

@cached_builder("streaming")
def _build_append(mesh_key, state_cap: int, batch_cap: int, new_cap: int):
    """shard_map kernel: place a packed batch block after the packed
    state block inside a [new_cap] buffer (per shard, no host transit)."""
    mesh = _MESHES[mesh_key]
    ax = config.data_axis

    def body(sflat, bflat, scnt, bcnt):
        s0, b0 = scnt[0], bcnt[0]
        out = []
        for sa, ba in zip(sflat, bflat):
            z = sa
            if new_cap > state_cap:
                pad = jnp.zeros((new_cap - state_cap,) + sa.shape[1:],
                                sa.dtype)
                z = jnp.concatenate([z, pad])
            idx = jnp.arange(batch_cap) + s0
            idx = jnp.where(jnp.arange(batch_cap) < b0, idx, new_cap)
            out.append(z.at[idx].set(ba, mode="drop"))
        return tuple(out), (s0 + b0)[None]

    return jax.jit(C.smap(body, in_specs=(P(ax), P(ax), P(ax), P(ax)),
                          out_specs=(P(ax), P(ax)), mesh=mesh))


def append_sharded(state: Optional[Table], batch: Table,
                   mesh=None) -> Table:
    """Append a 1D batch to a 1D state table per shard (device-side).

    Capacity grows in power-of-two steps so the jitted append kernel is
    reused across pushes. Column schemas must match; string columns must
    share the state's dictionary (the streaming gate checks this)."""
    m = mesh or mesh_mod.get_mesh()
    if state is None:
        cap = _pow2_cap(max(int(batch.counts.max(initial=0)), 1))
        return shard_recapacity(batch, cap, m)
    assert state.names == batch.names, (state.names, batch.names)
    need = int((state.counts + batch.counts).max(initial=0))
    new_cap = state.shard_capacity
    if need > new_cap:
        new_cap = _pow2_cap(need)
    names = state.names
    sflat, slots = [], []
    bflat = []
    for n in names:
        sc, bc = state.column(n), batch.column(n)
        # schema drift guard: a batch dtype WIDER than the state's would
        # wrap silently under astype (int64→int32), contradicting the
        # "column schemas must match" contract — fail loudly instead
        if bc.data.dtype != sc.data.dtype and not np.can_cast(
                bc.data.dtype, sc.data.dtype, casting="safe"):
            raise ValueError(
                f"append_sharded: batch column {n!r} dtype "
                f"{bc.data.dtype} does not safely cast to state dtype "
                f"{sc.data.dtype}")
        sflat.append(sc.data)
        bflat.append(bc.data.astype(sc.data.dtype))
        has_v = sc.valid is not None or bc.valid is not None
        slots.append(has_v)
        if has_v:
            per_s, per_b = state.shard_capacity, batch.shard_capacity
            sflat.append(sc.valid if sc.valid is not None
                         else jnp.ones(per_s * state.num_shards, bool))
            bflat.append(bc.valid if bc.valid is not None
                         else jnp.ones(per_b * batch.num_shards, bool))
    fn = _build_append(_mesh_key(m), state.shard_capacity,
                       batch.shard_capacity, new_cap)
    out, cnts = fn(tuple(sflat), tuple(bflat), state.counts_device(),
                   batch.counts_device())
    from bodo_tpu.plan.streaming import _note_sync
    _note_sync()
    counts = np.asarray(
        jax.device_get(cnts)).reshape(-1).astype(np.int64)  # dispatch-boundary
    cols: Dict[str, Column] = {}
    j = 0
    for n, has_v in zip(names, slots):
        sc = state.column(n)
        d = out[j]
        j += 1
        v = None
        if has_v:
            v = out[j].astype(bool)
            j += 1
        cols[n] = Column(d, v, sc.dtype, sc.dictionary, None)
    return Table(cols, int(counts.sum()), ONED, counts)


def _dict_template(t: Table) -> Dict:
    """Per-column dictionary snapshot; survives state parks so drift
    detection stays live across spilled chunks."""
    return {n: t.column(n).dictionary for n in t.names}


def _dicts_match_template(tmpl: Optional[Dict], batch: Table) -> bool:
    if tmpl is None:
        return True
    for n, sd in tmpl.items():
        bd = batch.column(n).dictionary
        if sd is None and bd is None:
            continue
        if sd is None or bd is None:
            return False
        if sd is not bd and not (len(sd) == len(bd)
                                 and bool(np.all(sd == bd))):
            return False
    return True


def _dicts_compatible(state: Optional[Table], batch: Table) -> bool:
    if state is None:
        return True
    return _dicts_match_template(_dict_template(state), batch)


# ---------------------------------------------------------------------------
# host-roundtrip helpers for spilled streaming state
# ---------------------------------------------------------------------------

def _table_device_bytes(t: Table) -> int:
    n = 0
    for c in t.columns.values():
        n += c.data.size * c.data.dtype.itemsize
        if c.valid is not None:
            n += c.valid.size
    return n


def _host_cols(t: Table):
    """(data, valid) numpy copies of the live rows of a REP table."""
    out = {}
    for n in t.names:
        c = t.column(n)
        d = np.asarray(jax.device_get(c.data))[:t.nrows]  # dispatch-boundary
        v = (np.asarray(jax.device_get(c.valid))[:t.nrows]  # dispatch-boundary
             if c.valid is not None else None)
        out[n] = (d, v)
    return out


def _table_from_host(host_cols, template: Table, nrows: int) -> Table:
    """REP device table from numpy columns, schema from `template`."""
    from bodo_tpu.table.table import round_capacity
    cap = round_capacity(max(nrows, 1))
    cols: Dict[str, Column] = {}
    for n, (d, v) in host_cols.items():
        src = template.column(n)
        pd_ = np.zeros((cap,), dtype=d.dtype)
        pd_[:nrows] = d
        pv = None
        if v is not None:
            pv = np.zeros((cap,), dtype=bool)
            pv[:nrows] = v
            pv = jnp.asarray(pv)
        cols[n] = Column(jnp.asarray(pd_), pv, src.dtype, src.dictionary)
    return Table(cols, nrows, REP, None)


def _concat_host_frames(frames: Sequence[Dict], template: Table,
                        nrows: int) -> Table:
    """Concatenate host-col dicts (np) into one REP device table."""
    cat = {}
    for n in template.names:
        has_v = any(f[n][1] is not None for f in frames)
        d = np.concatenate([f[n][0] for f in frames])
        v = (np.concatenate([f[n][1] if f[n][1] is not None
                             else np.ones(len(f[n][0]), bool)
                             for f in frames]) if has_v else None)
        cat[n] = (d, v)
    return _table_from_host(cat, template, nrows)


def _concat_tables_host(tables: Sequence[Table]) -> Table:
    """Concatenate REP tables host-side (np), preserving schema."""
    if len(tables) == 1:
        return tables[0]
    return _concat_host_frames([_host_cols(t) for t in tables],
                               tables[0], sum(t.nrows for t in tables))


def _host_filter(t: Table, mask: np.ndarray) -> Table:
    """Select rows of a REP table by a host bool mask (np gather)."""
    hc = _host_cols(t)
    out = {n: (d[mask], None if v is None else v[mask])
           for n, (d, v) in hc.items()}
    return _table_from_host(out, t, int(mask.sum()))


def _key_membership(p: Table, b: Table, left_on, right_on,
                    null_equal: bool) -> np.ndarray:
    """Host bool[p.nrows]: does each probe row's key appear in b?

    Scatter-claim membership probe (ops/hashtable.py); pathological
    probe-round exhaustion falls back to a pandas merge indicator."""
    from bodo_tpu.ops import hashtable as HT
    from bodo_tpu.ops import kernels as K

    pk = [(p.column(lk).data, p.column(lk).valid) for lk in left_on]
    bk = [(b.column(rk).data, b.column(rk).valid) for rk in right_on]
    pcodes, bcodes, p_ok0, b_ok0 = HT.aligned_codes(pk, bk, null_equal)
    b_pad = K.row_mask(jnp.asarray(b.nrows), b.capacity)
    p_pad = K.row_mask(jnp.asarray(p.nrows), p.capacity)
    b_ok = b_pad if b_ok0 is None else (b_pad & b_ok0)
    p_ok = p_pad if p_ok0 is None else (p_pad & p_ok0)
    T = HT.table_size(b.capacity)
    slot, owner, _r, un1 = HT.claim_slots(bcodes, b_ok, T)
    idx, un2 = HT.probe_slots(bcodes, owner, pcodes, p_ok, T)
    if bool(jax.device_get(un1 | un2)):  # dispatch-boundary
        from bodo_tpu.utils import tracing
        log(1, "stream join drain: membership probe-round exhaustion — "
               f"falling back to host pandas merge ({p.nrows} probe x "
               f"{b.nrows} build rows leave the device)")
        with tracing.event("host_membership_fallback") as ev:
            pl = p.select(list(left_on)).to_pandas()
            bl = b.select(list(right_on)).to_pandas().drop_duplicates()
            m = pl.merge(bl, left_on=list(left_on),
                         right_on=list(right_on),
                         how="left", indicator=True)
            matched = (m["_merge"] == "both").to_numpy()
            if not null_equal:
                matched &= ~pl.isna().any(axis=1).to_numpy()
            if ev is not None:
                ev["rows"] = p.nrows
        return matched
    return np.asarray(jax.device_get(idx))[:p.nrows] >= 0  # dispatch-boundary


# ---------------------------------------------------------------------------
# streaming partitioned hash join (build side too big to broadcast)
# ---------------------------------------------------------------------------

class ShardedPartitionedJoin:
    """Streaming partitioned hash join over the mesh: build batches are
    hash-shuffled to owner shards and appended into per-shard build
    state; probe batches shuffle by the same key hash and join locally
    against the accumulated state (co-partitioned by construction).

    TPU redesign of the reference's partitioned streaming hash join
    (bodo/libs/streaming/_join.h:892 HashJoinState: partitioned build
    table + per-batch probe): partitions are mesh shards, the MPI
    alltoallv is a fixed-capacity lax.all_to_all, and the per-shard
    probe is the static-shape join_local kernel under shard_map."""

    def __init__(self, left_on, right_on, how, suffixes,
                 null_equal: bool = True, mesh=None):
        if how not in ("inner", "left"):
            raise NotImplementedError(how)
        self.left_on, self.right_on = list(left_on), list(right_on)
        self.how, self.suffixes = how, suffixes
        self.null_equal = null_equal
        self.mesh = mesh or mesh_mod.get_mesh()
        self.state: Optional[Table] = None
        # larger-than-device build: when the accumulated build state
        # exceeds the governed device budget, whole state chunks park
        # into the spillable host pool; probe batches are then deferred
        # (parked too) and drained chunk-against-chunk at the end —
        # device memory stays bounded by ~2 chunks + one join output
        # (reference analogue: JoinPartition build spill + probe-side
        # chunk replay, bodo/libs/streaming/_join.h:267). The budget is
        # an admission-control grant from the memory governor (the
        # legacy stream_device_budget_mb override wins when set).
        from bodo_tpu.runtime.memory_governor import governor
        self._grant = governor().admit("stream_join")
        self.budget = self._grant.budget
        self.build_chunks: List = []    # OffloadedTable (REP row order)
        self.probe_chunks: List = []
        self._pending_probe: Optional[Table] = None
        self._key_template: Optional[Dict] = None
        self._build_dicts: Optional[Dict] = None   # survives state parks
        self._probe_dicts: Optional[Dict] = None
        self._comp = None
        self._op = None

    # -- spill plumbing -----------------------------------------------------

    def _park(self, t: Table):
        from bodo_tpu.runtime.comptroller import default_comptroller
        if self._comp is None:
            self._comp = default_comptroller()
            self._op = self._comp.register("stream_join")
        return self._comp.park(self._op, t.gather()
                               if t.distribution == ONED else t)

    @property
    def spilling(self) -> bool:
        return bool(self.build_chunks)

    def push_build(self, b: Table) -> bool:
        """Accumulate one 1D build batch. False → caller must abandon
        streaming (incompatible batch dictionaries)."""
        if b.distribution != ONED:
            b = b.shard()
        sb = R.shuffle_by_key(b, self.right_on)
        if not _dicts_match_template(self._build_dicts, sb):
            self.close()  # free any parked chunks before the fallback
            return False
        if self._build_dicts is None:
            self._build_dicts = _dict_template(sb)
            self._key_template = {
                rk: (sb.column(rk).dtype, sb.column(rk).dictionary)
                for rk in self.right_on}
        self.state = append_sharded(self.state, sb, self.mesh)
        nbytes = _table_device_bytes(self.state)
        if self._grant.over_budget(nbytes):
            self.build_chunks.append(self._park(self.state))
            self._grant.record_spill(nbytes)
            self.state = None
        return True

    def close(self) -> None:
        """Free parked host-pool state (idempotent). Called when
        streaming is abandoned or after drain() — parked chunks must not
        outlive the operator."""
        for ot in self.build_chunks + self.probe_chunks:
            try:
                ot.free()
            except Exception:
                pass
        self.build_chunks, self.probe_chunks = [], []
        self._pending_probe = None
        self.state = None
        if self._comp is not None:
            self._comp.unregister(self._op)
            self._comp = None
        self._grant.release()

    def _probe_keys_compatible(self, pb: Table) -> None:
        """Fail loudly when probe key columns cannot be compared against
        the build state raw (shuffle + local join compare dict CODES):
        drifting string dictionaries or dtype mismatch would otherwise
        return silently wrong matches for a direct user of this class
        (build_stream_sharded gates this, __graft_entry__-style callers
        don't)."""
        if self._key_template is None:
            return
        for lk, rk in zip(self.left_on, self.right_on):
            pc = pb.column(lk)
            bdt, bd = self._key_template[rk]
            if pc.dtype is not bdt:
                raise ValueError(
                    f"probe key {lk!r} dtype {pc.dtype} != build key "
                    f"{rk!r} dtype {bdt}")
            pd_ = pc.dictionary
            if pd_ is None and bd is None:
                continue
            if pd_ is None or bd is None or (
                    pd_ is not bd and not (len(pd_) == len(bd)
                                           and bool(np.all(pd_ == bd)))):
                raise ValueError(
                    f"probe key {lk!r} string dictionary differs from "
                    "build state's — codes are not comparable (re-encode "
                    "or use the whole-table join)")

    def probe(self, b: Table) -> Optional[Table]:
        """Join one probe batch. Returns the joined batch — or None when
        the build side spilled past the device budget: the batch is
        parked and its results come from drain() instead."""
        if b.distribution != ONED:
            b = b.shard()
        self._probe_keys_compatible(b)
        if self.spilling:
            # defer RAW batches (no shuffle: drain()'s join_tables
            # re-partitions restored chunks from scratch anyway)
            if not _dicts_match_template(self._probe_dicts, b):
                raise ValueError("probe batch dictionaries drifted "
                                 "across spilled streaming state")
            if self._probe_dicts is None:
                self._probe_dicts = _dict_template(b)
            self._pending_probe = append_sharded(self._pending_probe, b,
                                                 self.mesh)
            nbytes = _table_device_bytes(self._pending_probe)
            if self._grant.over_budget(nbytes):
                self.probe_chunks.append(self._park(self._pending_probe))
                self._grant.record_spill(nbytes)
                self._pending_probe = None
            return None
        pb = R.shuffle_by_key(b, self.left_on)
        out = R._join_sharded(pb, self.state, self.left_on, self.right_on,
                              self.how, self.suffixes,
                              null_equal=self.null_equal,
                              pre_shuffled=True)
        cap = _pow2_cap(max(int(out.counts.max(initial=0)), 1))
        return shard_recapacity(out, cap, self.mesh)

    def drain(self) -> Iterator[Table]:
        """Emit results for probe batches deferred while the build side
        was spilled: every (probe chunk × build chunk) pair joins inner
        at bounded device residency; for a left join, probe rows matched
        by NO chunk emit once against an empty build table (preserving
        output schema/suffix naming). Frees all parked state."""
        if not self.spilling:
            return
        if self.state is not None:
            self.build_chunks.append(self._park(self.state))
            self.state = None
        if self._pending_probe is not None:
            self.probe_chunks.append(self._park(self._pending_probe))
            self._pending_probe = None
        log(1, f"streaming join drain: {len(self.build_chunks)} build x "
               f"{len(self.probe_chunks)} probe spilled chunks")
        try:
            for pot in self.probe_chunks:
                p = pot.restore_slice(0, pot.nrows)
                matched = np.zeros(p.nrows, dtype=bool)
                empty_b = None
                for bot in self.build_chunks:
                    c = bot.restore_slice(0, bot.nrows)
                    out = R.join_tables(
                        p.shard(), c.shard(), self.left_on, self.right_on,
                        how="inner", suffixes=self.suffixes,
                        null_equal=self.null_equal)
                    if out.distribution != ONED:
                        out = out.shard()
                    yield out
                    if self.how == "left":
                        matched |= _key_membership(
                            p, c, self.left_on, self.right_on,
                            self.null_equal)
                    if empty_b is None:
                        zc = np.zeros(mesh_mod.num_shards(self.mesh),
                                      dtype=np.int64)
                        cb = c.shard()
                        empty_b = Table(dict(cb.columns), 0, ONED, zc)
                if self.how == "left" and not matched.all():
                    unm = _host_filter(p, ~matched)
                    out = R.join_tables(
                        unm.shard(), empty_b, self.left_on, self.right_on,
                        how="left", suffixes=self.suffixes,
                        null_equal=self.null_equal)
                    if out.distribution != ONED:
                        out = out.shard()
                    yield out
        finally:
            self.close()


# ---------------------------------------------------------------------------
# streaming sample sort (two passes over a re-buildable stream)
# ---------------------------------------------------------------------------

class ShardedStreamSort:
    """Distributed streaming sort with run-generation external sort.

    Batches append into per-shard 1D state as they flow (one pass over
    the child). Under a device budget (config.stream_device_budget_mb),
    each time the state exceeds the budget it is SORTED into a run and
    parked in the spillable host pool (the comptroller spills runs to
    disk under pressure); finish() then range-merges the sorted runs:
    global range splitters come from the runs' partition keys (host),
    each range restores only its row slices from every run (binary
    search on the runs' sorted keys — no full-run restore), concatenates
    and locally sorts them, so device residency during the merge is one
    range at a time.

    The reference streams sort chunks with spill + final k-way merge
    (bodo/libs/streaming/_sort.cpp external sort); the k-way comparator
    merge becomes a range-partitioned re-sort, the same trade the mesh
    sample sort makes (ops/sort.py). With no budget (0), finish() is the
    one-shot mesh sample sort over the accumulated state."""

    def __init__(self, by, ascending, na_last: bool, mesh=None):
        self.by = list(by)
        self.ascending = list(ascending)
        self.na_last = na_last
        self.mesh = mesh or mesh_mod.get_mesh()
        self.S = mesh_mod.num_shards(self.mesh)
        self.state: Optional[Table] = None
        from bodo_tpu.runtime.memory_governor import governor
        self._grant = governor().admit("stream_sort")
        self.budget = self._grant.budget
        self.runs: List[Tuple] = []  # (OffloadedTable, pk np, nbytes)
        self._dicts: Optional[Dict] = None  # survives run parks
        self._comp = None
        self._op = None

    def push(self, b: Table) -> bool:
        if b.distribution != ONED:
            b = b.shard()
        if not _dicts_match_template(self._dicts, b):
            self.close()
            return False
        if self._dicts is None:
            self._dicts = _dict_template(b)
        self.state = append_sharded(self.state, b, self.mesh)
        if self._grant.over_budget(_table_device_bytes(self.state)):
            self._park_run()
        return True

    def close(self) -> None:
        """Free parked runs (idempotent) — abandonment must not leak."""
        for ot, _pk, _b in self.runs:
            try:
                ot.free()
            except Exception:
                pass
        self.runs = []
        self.state = None
        if self._comp is not None:
            self._comp.unregister(self._op)
            self._comp = None
        self._grant.release()

    def _park_run(self) -> None:
        from bodo_tpu.ops.sort import _partition_key
        from bodo_tpu.runtime.comptroller import default_comptroller
        if self._comp is None:
            self._comp = default_comptroller()
            self._op = self._comp.register("stream_sort")
        run = R.sort_table(self.state, self.by, self.ascending,
                           self.na_last)
        g = run.gather() if run.distribution == ONED else run
        c0 = g.column(self.by[0])
        padmask = jnp.arange(g.capacity) < g.nrows
        pk = _partition_key([(c0.data, c0.valid)], [self.ascending[0]],
                            self.na_last, padmask)
        pk = np.asarray(jax.device_get(pk))[:g.nrows]  # dispatch-boundary
        nbytes = _table_device_bytes(g)
        ot = self._comp.park(self._op, g)
        self.runs.append((ot, pk, nbytes))
        self._grant.record_spill(nbytes)
        self.state = None
        log(1, f"streaming sort: parked run {len(self.runs)} "
               f"({g.nrows} rows, {nbytes >> 20} MiB)")

    def finish(self) -> Table:
        if not self.runs:
            out = R.sort_table(self.state, self.by, self.ascending,
                               self.na_last)
            self.close()
            return out
        if self.state is not None and self.state.nrows > 0:
            self._park_run()
        try:
            return self._merge_runs()
        finally:
            self.close()

    def _merge_runs(self) -> Table:
        total_rows = sum(pk.size for _ot, pk, _b in self.runs)
        total_bytes = sum(b for *_x, b in self.runs)
        nranges = max(2, -(-total_bytes // max(self.budget, 1)))
        allpk = np.sort(np.concatenate([pk for _ot, pk, _b in self.runs]))
        spl = [allpk[min(i * total_rows // nranges, total_rows - 1)]
               for i in range(1, nranges)]
        log(1, f"streaming sort merge: {len(self.runs)} runs, "
               f"{total_rows} rows, {nranges} ranges")
        frames = []
        template = None
        out_rows = 0
        for r in range(nranges):
            parts = []
            for ot, pk, _b in self.runs:
                lo = 0 if r == 0 else int(np.searchsorted(
                    pk, spl[r - 1], side="left"))
                hi = pk.size if r == nranges - 1 else int(np.searchsorted(
                    pk, spl[r], side="left"))
                if hi > lo:
                    parts.append(ot.restore_slice(lo, hi))
            if not parts:
                continue
            chunk = _concat_tables_host(parts)
            schunk = R.sort_table(chunk, self.by, self.ascending,
                                  self.na_last)
            if schunk.distribution == ONED:
                schunk = schunk.gather()
            frames.append(_host_cols(schunk))
            template = schunk
            out_rows += schunk.nrows
        out = _concat_host_frames(frames, template, out_rows)
        return out.shard()


