"""Streaming batch executor: batch-at-a-time pipelines with bounded
device memory.

TPU-native redesign of the reference's streaming execution model
(reference: bodo/pandas/_pipeline.h:106 Pipeline, _executor.h:76 Executor,
physical/operator.h:46 the ConsumeBatch/ProduceBatch operator protocol,
bodo/libs/streaming/_groupby.cpp GroupbyState). The C++ pull-pipeline with
NEED_MORE_INPUT/HAVE_MORE_OUTPUT states becomes a host-driven Python loop
over fixed-capacity device batches:

  - sources yield REP Tables padded to ONE static capacity, so every
    per-batch kernel (filter/project/join-probe/partial-agg) compiles
    once and is reused for the whole stream;
  - blocking operators accumulate packed *partial* state on device
    (groupby) or park batches in the native host buffer pool
    (runtime/offload.py) where they are spillable to disk (sort, join
    build sides) — device memory stays O(batch + state), not O(rows);
  - string columns ride a *running* unified dictionary so codes stay
    comparable across batches (the reference's dict-builder unification,
    bodo/libs/_dict_builder.cpp); accumulated state is re-coded on the
    rare batch that introduces new strings.

Capacities that vary at runtime (filter survivors, join fan-out) are
re-bucketed to powers of two so the compile count stays logarithmic.

v1 scope: single-shard (REP) streams — the multi-device path continues to
use the whole-table shard_map operators; streaming+shuffle overlap is the
async-shuffle milestone.
"""

from __future__ import annotations

import os
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from bodo_tpu import relational as R
from bodo_tpu.config import config
from bodo_tpu.ops.groupby import groupby_local, groupby_merge, result_dtype
from bodo_tpu.parallel import mesh as mesh_mod
from bodo_tpu.parallel.shuffle import _finalize, _plan_decomposition
from bodo_tpu.plan import logical as L
from bodo_tpu.table import dtypes as dt
from bodo_tpu.table.table import (Column, REP, Table, round_capacity)
from bodo_tpu.utils.kernel_cache import cached_builder
from bodo_tpu.utils.logging import log


def _bucket_cap(n: int) -> int:
    """Round capacity to a power of two (min 128) so streaming stages see
    a logarithmic number of distinct shapes."""
    c = 128
    while c < n:
        c <<= 1
    return c


# ---------------------------------------------------------------------------
# host-sync accounting
# ---------------------------------------------------------------------------
# Every `jax.device_get`/`block_until_ready` inside a streaming step body
# stalls the pipeline: the host waits for the device instead of decoding
# the next batch. The accumulators below are written so syncs per stage
# are O(1)–O(log batches), not O(batches); each legitimate sync site is
# annotated `# dispatch-boundary` (shardcheck lints unannotated ones) and
# counted here so the bench can regress on syncs-per-batch.

stream_stats: Dict[str, int] = {"host_syncs": 0, "batches": 0}


def _note_sync(n: int = 1) -> None:
    stream_stats["host_syncs"] += n


def _note_batch(n: int = 1) -> None:
    stream_stats["batches"] += n


def reset_stream_stats() -> None:
    for k in stream_stats:
        stream_stats[k] = 0


def _with_capacity(t: Table, cap: int) -> Table:
    """Re-capacity a packed REP table (slice down / zero-pad up)."""
    if cap == t.capacity:
        return t
    assert cap >= t.nrows, (cap, t.nrows)
    cols: Dict[str, Column] = {}
    for n, c in t.columns.items():
        if cap <= c.capacity:
            d = c.data[:cap]
            v = c.valid[:cap] if c.valid is not None else None
        else:
            pad = cap - c.capacity
            d = jnp.concatenate(
                [c.data, jnp.zeros((pad,), dtype=c.data.dtype)])
            v = None if c.valid is None else jnp.concatenate(
                [c.valid, jnp.zeros((pad,), dtype=bool)])
        cols[n] = Column(d, v, c.dtype, c.dictionary)
    return Table(cols, t.nrows, REP, None)


# ---------------------------------------------------------------------------
# running-dictionary tracker
# ---------------------------------------------------------------------------

class DictTracker:
    """Per-column running dictionaries for a stream.

    Re-encodes each batch's string columns onto the running (sorted,
    unioned) dictionary; the dictionary OBJECT stays stable while no new
    strings appear, which keeps downstream kernel caches warm."""

    def __init__(self):
        self._dicts: Dict[str, np.ndarray] = {}

    def current(self, name: str) -> Optional[np.ndarray]:
        return self._dicts.get(name)

    def absorb(self, t: Table) -> Table:
        cols = dict(t.columns)
        for name, c in t.columns.items():
            if c.dictionary is None:
                continue
            run = self._dicts.get(name)
            if run is None:
                self._dicts[name] = c.dictionary
                continue
            if c.dictionary is run:
                continue
            union = np.union1d(run, c.dictionary)
            if len(union) == len(run):
                union = run  # no new strings: keep the stable object
            else:
                self._dicts[name] = union
            cols[name] = remap_codes(c, union)
        return Table(cols, t.nrows, REP, None)


def remap_codes(c: Column, new_dict: np.ndarray) -> Column:
    """Re-encode a string column's codes onto a superset dictionary."""
    old = c.dictionary if c.dictionary is not None else np.array([], str)
    if new_dict is old:
        return c
    lut = np.searchsorted(new_dict, old).astype(np.int32)
    mp = jnp.asarray(lut if len(lut) else np.zeros(1, np.int32))
    data = mp[jnp.clip(c.data, 0, max(len(old) - 1, 0))]
    return Column(data, c.valid, c.dtype, new_dict)


# ---------------------------------------------------------------------------
# batch sources
# ---------------------------------------------------------------------------

def parquet_batches(path: str, columns: Optional[Sequence[str]],
                    batch_rows: int) -> Iterator[Table]:
    """Stream a parquet dataset as fixed-capacity REP Tables (the
    reference's ArrowReader streaming read, bodo/io/arrow_reader.h:170).

    Each raw iter_batches pull runs under the retry envelope (the
    `io.read` fault point fires per pull, so armed faults surface on
    whatever thread consumes this generator — including a Prefetcher
    worker — and transient flakes retry in place). Re-slicing to the
    fixed batch size goes through slice_arrow_batches, which is linear:
    the pending tail concatenates once per input chunk instead of
    rebuilding pa.Table.from_batches per carried-over row group."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    from bodo_tpu.io.arrow_bridge import arrow_to_table
    from bodo_tpu.io.csv import slice_arrow_batches
    from bodo_tpu.io.parquet import _dataset_files, _opened, footer_metadata
    from bodo_tpu.runtime import resilience

    cap = round_capacity(batch_rows)
    tracker = DictTracker()
    cols = list(columns) if columns else None
    _END = object()

    from bodo_tpu.config import config
    _units = [(f, rg) for f in _dataset_files(path)
              for rg in range(footer_metadata(f).num_row_groups)]
    if getattr(config, "device_decode", False):
        from bodo_tpu.io import device_decode as _dd
    else:
        _dd = None
    if _dd is not None and _dd.worth_device_decode(_units):
        # device route: pool-side raw-page bundles (prefetched BYTES,
        # admission charged at compressed+decoded size via
        # RawRowGroup.nbytes) decode on-chip at the consumer, then
        # re-slice to the fixed batch capacity. Per-pull retry lives
        # inside raw_bundles; unsupported columns fall back per column
        # inside decode, so this route never rejects a dataset.
        from bodo_tpu.runtime.io_pool import prefetched

        bundles = prefetched(_dd.raw_bundles(path, cols, units=_units),
                             label="parquet_raw")
        for b in _dd.decoded_batches(bundles, batch_rows):
            dd_flag = getattr(b, "_device_decoded", False)
            b = tracker.absorb(b)
            b._device_decoded = dd_flag
            yield b
        return

    def raw() -> Iterator[pa.Table]:
        for f in _dataset_files(path):
            with _opened(f) as src:
                pf = pq.ParquetFile(src, metadata=footer_metadata(f))
                it = pf.iter_batches(batch_size=batch_rows, columns=cols)
                while True:
                    rb = resilience.retry_call(
                        lambda: next(it, _END),
                        label="parquet_batch", point="io.read")
                    if rb is _END:
                        break
                    yield pa.Table.from_batches([rb])

    for at in slice_arrow_batches(raw(), batch_rows):
        yield tracker.absorb(arrow_to_table(at, capacity=cap))


def csv_batches(path: str, columns: Optional[Sequence[str]],
                parse_dates, batch_rows: int) -> Iterator[Table]:
    """Stream a CSV file as fixed-capacity REP Tables: newline-aligned
    byte-range chunks parsed one at a time (bounded host memory), then
    re-sliced to a fixed row count so every downstream kernel compiles
    once (reference: chunked parallel CSV read,
    bodo/io/_csv_json_reader.cpp + csv_iterator_ext.py)."""
    from bodo_tpu.io.arrow_bridge import arrow_to_table
    from bodo_tpu.io.csv import iter_csv_arrow, slice_arrow_batches

    cap = round_capacity(batch_rows)
    tracker = DictTracker()
    for at in slice_arrow_batches(
            iter_csv_arrow(path, columns, parse_dates), batch_rows):
        yield tracker.absorb(arrow_to_table(at, capacity=cap))


def table_batches(t: Table, batch_rows: int) -> Iterator[Table]:
    """Slice an in-memory REP table into fixed-capacity batches (static
    Python slice bounds, so every batch shares one compiled shape)."""
    assert t.distribution == REP
    cap = round_capacity(batch_rows)
    n = t.nrows

    def slice_pad(a, off):
        piece = a[off:min(off + cap, a.shape[0])]
        if piece.shape[0] < cap:
            piece = jnp.concatenate(
                [piece, jnp.zeros((cap - piece.shape[0],), piece.dtype)])
        return piece

    for off in range(0, max(n, 1), batch_rows):
        take = max(0, min(batch_rows, n - off))
        cols: Dict[str, Column] = {}
        for name, c in t.columns.items():
            cols[name] = Column(
                slice_pad(c.data, off),
                slice_pad(c.valid, off) if c.valid is not None else None,
                c.dtype, c.dictionary)
        yield Table(cols, take, REP, None)
        if n == 0:
            break


# ---------------------------------------------------------------------------
# blocking operators
# ---------------------------------------------------------------------------

class GroupbyAccumulator:
    """Streaming groupby: per-batch local partial aggregation merged into
    a packed device state (reference: GroupbyState::UpdateGroupsAndCombine,
    bodo/libs/streaming/_groupby.cpp). State is O(distinct groups).

    Pipelined (the async-overlap milestone): push() only DISPATCHES the
    partial aggregation — group counts stay on device as traced scalars,
    and merges size their static capacities from host-known row-count
    BOUNDS, so no host sync sits between batches. The device works on
    batch k's merge while the host decodes batch k+1 (the reference gets
    the same overlap from IncrementalShuffleState's async sends,
    bodo/libs/streaming/_shuffle.h:777).

    Sync schedule is GEOMETRIC: the k-th capacity-tightening sync lands
    after SYNC_EVERY·2^k merges, so a B-batch stream costs O(log B) host
    round-trips total (a fixed interval would cost O(B)). Between syncs
    the host bound creeps by at most the interval's batch rows, and each
    sync snaps both the bound and the state capacity back to the actual
    group count — capacity stays within one doubling of what a per-batch
    sync would keep."""

    SYNC_EVERY = 4  # first sync interval; doubles after every sync

    def __init__(self, keys: Sequence[str], aggs: Sequence[Tuple]):
        self.keys = list(keys)
        self.aggs = list(aggs)
        specs = tuple(op for _, op, _ in aggs)
        self.partial_specs, self.combine_specs, self.layout = \
            _plan_decomposition(specs)
        # parts per agg (layout is contiguous per spec)
        self._nparts = [len(_plan_decomposition((op,))[0])
                        for _, op, _ in self.aggs]
        self.state: Optional[Table] = None  # keys + __p{i} partial cols
        self._n_state_dev = None            # device scalar (deferred sync)
        self._bound = 0                     # host upper bound on n_state
        self._since_sync = 0
        self._sync_interval = self.SYNC_EVERY
        self._queue: List = []              # dispatched, unmerged partials
        self._template: Optional[Table] = None  # schema source
        self._grant = None                  # governor admission (lazy)

    @property
    def n_state(self) -> int:
        self._drain_all()
        if self._n_state_dev is None:
            return 0
        _note_sync()
        return int(jax.device_get(self._n_state_dev))  # dispatch-boundary

    def _partial_names(self) -> List[str]:
        return [f"__p{i}" for i in range(len(self.partial_specs))]

    def push(self, batch: Table) -> None:
        from bodo_tpu.utils import tracing
        nk = len(self.keys)
        if self._template is None:
            self._template = batch
        if self._grant is None:
            from bodo_tpu.runtime.memory_governor import governor
            self._grant = governor().admit("stream_groupby")
        if batch.nrows == 0 and (self.state is not None or self._queue):
            return  # empty batch (selective filter): nothing to merge
        arrays = tuple((batch.column(k).data, batch.column(k).valid)
                       for k in self.keys)
        arrays += tuple(
            (batch.column(c).data, batch.column(c).valid)
            for (c, _, _), np_ in zip(self.aggs, self._nparts)
            for _ in range(np_))
        with tracing.event("stream_partial"):
            pk, pv, ng = groupby_local(arrays, jnp.asarray(batch.nrows),
                                       self.partial_specs, batch.capacity,
                                       nk)
        ng_bound = max(min(batch.nrows, batch.capacity), 1)
        partial = self._as_state_table(batch, pk, pv, 0)
        partial = _with_capacity(partial, _bucket_cap(ng_bound))
        self._queue.append((partial, ng, ng_bound))
        # depth-1 lookahead: merge batch k while the caller decodes k+1
        while len(self._queue) > 1:
            self._drain_one()

    def _drain_all(self) -> None:
        while self._queue:
            self._drain_one()

    def _drain_one(self) -> None:
        from bodo_tpu.utils import tracing
        nk = len(self.keys)
        partial, ng_dev, ng_bound = self._queue.pop(0)

        if self.state is None:
            self.state = partial
            self._n_state_dev = ng_dev
            self._bound = ng_bound
            return

        # re-code state onto any grown dictionaries before merging
        state = self.state
        cols = dict(state.columns)
        changed = False
        for name, c in state.columns.items():
            bdict = partial.columns[name].dictionary
            if c.dictionary is not None and bdict is not None and \
                    c.dictionary is not bdict:
                cols[name] = remap_codes(c, bdict)
                changed = True
        if changed:
            state = Table(cols, state.nrows, REP, None)

        out_cap = _bucket_cap(max(self._bound + ng_bound, state.capacity))
        s_arrays = tuple((state.column(n).data, state.column(n).valid)
                         for n in state.names)
        b_arrays = tuple((partial.column(n).data, partial.column(n).valid)
                         for n in state.names)
        with tracing.event("stream_merge"):
            mk, mv, ng2 = groupby_merge(s_arrays, b_arrays,
                                        self._n_state_dev, ng_dev,
                                        self.combine_specs, out_cap, nk)
        self._n_state_dev = ng2
        self._bound += ng_bound
        self._since_sync += 1
        names = state.names
        cols = {}
        for name, (d, v) in zip(names[:nk], mk):
            src = state.columns[name]
            cols[name] = Column(d, v, src.dtype, src.dictionary)
        for name, (d, v) in zip(names[nk:], mv):
            src = state.columns[name]
            cols[name] = Column(d, v, src.dtype, src.dictionary)
        # mid-stream state.nrows is the host BOUND, not the true group
        # count — the true count lives on device until the next sync
        st = Table(cols, self._bound, REP, None)

        if self._since_sync >= self._sync_interval:
            # geometric sync: tighten the bound (and the state capacity)
            # to the actual group count, then double the interval so a
            # B-batch stream pays O(log B) of these round-trips total
            _note_sync()
            n = int(jax.device_get(ng2))  # dispatch-boundary
            self._bound = n
            self._since_sync = 0
            self._sync_interval *= 2
            st = Table(cols, n, REP, None)
            tight = _bucket_cap(max(n, 1))
            if tight * 2 <= st.capacity:
                st = _with_capacity(st, tight)
        self.state = st
        if self._grant is not None:
            from bodo_tpu.runtime.memory_governor import \
                table_device_bytes
            self._grant.update(table_device_bytes(st))

    def _as_state_table(self, batch: Table, pk, pv, ng: int) -> Table:
        cols: Dict[str, Column] = {}
        for name, (d, v) in zip(self.keys, pk):
            src = batch.column(name)
            cols[name] = Column(d, v, src.dtype, src.dictionary)
        pi = 0
        for (cname, op, _), nparts in zip(self.aggs, self._nparts):
            src = batch.column(cname)
            for j in range(nparts):
                pop = self.partial_specs[pi]
                d, v = pv[pi]
                if pop in ("min", "max", "first", "last"):
                    pdt, pdic = src.dtype, src.dictionary
                else:
                    pdt = dt.from_numpy(result_dtype(pop, src.dtype.numpy))
                    pdic = None
                cols[self._partial_names()[pi]] = Column(d, v, pdt, pdic)
                pi += 1
        return Table(cols, ng, REP, None)

    def finish(self) -> Table:
        nk = len(self.keys)
        n_final = self.n_state  # drains the pipeline + syncs the count
        # push() sets state on the first batch (even an all-padding one);
        # a truly batch-less stream is filtered by try_stream_execute
        assert self.state is not None
        state = self.state
        names = state.names
        pcols = [state.columns[n] for n in names[nk:]]
        finals = []
        for i, (cname, op, oname) in enumerate(self.aggs):
            off, n = self.layout[i]
            cols_in = tuple((pcols[off + j].data, pcols[off + j].valid)
                            for j in range(n))
            src_dt = self._template.column(cname).dtype
            d, v = _finalize(op, cols_in, jnp.dtype(src_dt.numpy))
            rdt = src_dt if op in ("min", "max", "first", "last") \
                else dt.from_numpy(result_dtype(op, src_dt.numpy))
            dic = pcols[off].dictionary if rdt is dt.STRING else None
            finals.append((oname, Column(d, v, rdt, dic)))
        out: Dict[str, Column] = {n: state.columns[n] for n in names[:nk]}
        for oname, col in finals:
            out[oname] = col
        if self._grant is not None:
            self._grant.release()
        return Table(out, n_final, REP, None)


class MixedGroupbyStream:
    """Streaming groupby covering non-decomposable aggregations
    (VERDICT r2 weak #5). Three strategies, mirroring the reference's
    streaming groupby modes (bodo/libs/streaming/_groupby.cpp):

    - decomposable ops: the partial/combine `GroupbyAccumulator`
      (AGG mode). A hidden `size` agg always rides along so the final
      key set covers every group.
    - nunique: a second-level decomposition — the streaming state is
      the DISTINCT (keys, value) pairs (an inner GroupbyAccumulator
      keyed on keys+value), finalized by a count per key. State stays
      O(distinct pairs), never O(rows).
    - order statistics / value-list ops (median, quantile, mode,
      listagg): no bounded exact state exists, so rows accumulate in
      the spillable host pool (the reference's ACC mode materializes
      input the same way) and the batch groupby runs at finish.

    Results of the three strategies join back on the group keys.
    """

    _ROWSTORE_OPS = ("median", "mode")

    def __init__(self, keys: Sequence[str], aggs: Sequence[Tuple]):
        self.keys = list(keys)
        self.aggs = list(aggs)
        dec, self.nun, self.acc = [], [], []
        for col, op, out in aggs:
            if op == "nunique":
                self.nun.append((col, op, out))
            elif op in self._ROWSTORE_OPS or op.startswith("quantile_") \
                    or op.startswith("listagg"):
                self.acc.append((col, op, out))
            else:
                dec.append((col, op, out))   # may still raise below
        self._hidden_size = "__msize"
        self.dec = GroupbyAccumulator(
            self.keys, dec + [(self.keys[0], "size", self._hidden_size)])
        self.nun_accs = {}
        for col, _, _ in self.nun:
            if col not in self.nun_accs:
                self.nun_accs[col] = GroupbyAccumulator(
                    self.keys + [col],
                    [(self.keys[0], "size", "__paircnt")])
        self.rows = None
        if self.acc:
            from bodo_tpu.runtime.comptroller import default_comptroller
            from bodo_tpu.runtime.memory_governor import governor
            self._comp = default_comptroller()
            self._op = self._comp.register("stream_groupby_acc")
            self._grant = governor().admit("stream_groupby_acc")
            self.rows = []
            self._acc_cols = list(dict.fromkeys(
                self.keys + [c for c, _, _ in self.acc]))

    def push(self, batch: Table) -> None:
        self.dec.push(batch)
        for acc in self.nun_accs.values():
            acc.push(batch)
        if self.rows is not None and batch.nrows:
            from bodo_tpu.runtime.memory_governor import \
                table_device_bytes
            part = _with_capacity(batch.select(self._acc_cols),
                                  _bucket_cap(max(batch.nrows, 1)))
            self.rows.append(self._comp.park(self._op, part))
            self._grant.record_spill(table_device_bytes(part))

    def finish(self) -> Table:
        base = self.dec.finish()
        for col, _, out in self.nun:
            pairs = self.nun_accs[col].finish()
            cnt = R.groupby_agg(pairs.select(self.keys + [col]),
                                self.keys, [(col, "count", out)])
            base = self._join(base, cnt, fill_zero=[out])
        if self.rows is not None:
            tables = [p.restore() for p in self.rows]
            self.rows = []
            self._comp.unregister(self._op)
            self._grant.release()
            if tables:
                full = R.concat_tables(tables) if len(tables) > 1 \
                    else tables[0]
                accres = R.groupby_agg(full, self.keys, self.acc)
                base = self._join(base, accres, fill_zero=[])
            else:
                # all batches were empty: no rows were parked, but the
                # output schema must still carry the agg columns (typed
                # all-null, matching the whole-table path)
                import jax.numpy as jnp
                for col, op, out in self.acc:
                    src = self.dec._template.column(col)
                    if op == "mode":
                        rdt, dic = src.dtype, src.dictionary
                    elif op.startswith("listagg"):
                        rdt, dic = dt.STRING, np.array([], dtype=str)
                    else:  # median / quantile_*
                        rdt, dic = dt.FLOAT64, None
                    cap = base.capacity
                    base.columns[out] = Column(
                        jnp.zeros(cap, rdt.numpy),
                        jnp.zeros(cap, bool), rdt, dic)
        order = self.keys + [out for _, _, out in self.aggs]
        return base.select([n for n in order if n in base.columns])

    def close(self) -> None:
        """Abandon (empty-stream fallback): free parked row parts."""
        if self.rows is not None:
            for p in self.rows:
                p.free()
            self.rows = []
            self._comp.unregister(self._op)
            self._grant.release()

    def _join(self, base: Table, other: Table, fill_zero) -> Table:
        from bodo_tpu.plan.expr import ColRef, Lit, UnOp, Where
        out = R.join_tables(base, other, self.keys, self.keys, "left")
        fills = {}
        for name in fill_zero:
            if name in out.columns and out.columns[name].valid is not None:
                fills[name] = Where(UnOp("isna", ColRef(name)), Lit(0),
                                    ColRef(name))
        if fills:
            out = R.assign_columns(out, fills)
        return out


_MOMENT_OPS = ("mean", "var", "std", "var0", "std0")


def _sum_acc_dtype(d):
    """Widened accumulation dtype for a sum over `d` (exact in the
    widened source family — matches relational.reduce_table)."""
    if jnp.issubdtype(d, jnp.floating):
        return jnp.float64
    if jnp.issubdtype(d, jnp.unsignedinteger):
        return jnp.uint64
    return jnp.int64


def _minmax_identity(dtype, op: str):
    if np.issubdtype(dtype, np.floating):
        return np.array(np.inf if op == "min" else -np.inf, dtype)
    if dtype == np.bool_:
        return np.array(op == "min", np.bool_)
    info = np.iinfo(dtype)
    return np.array(info.max if op == "min" else info.min, dtype)


@cached_builder("streaming")
def _build_reduce_step(sig: Tuple, cap: int, donate: bool):
    """One streamed-reduce step: per-batch masked partials folded into
    the running device carry (sums/counts add, min/max fold through
    their identities, moments combine with the exact delta-form Chan
    update). `sig` is one (op, dtype_str, has_valid) per agg; the carry
    is a flat tuple of 0-d device scalars, DONATED back to the step on
    accelerator backends so the state never holds two buffers."""
    from bodo_tpu.ops import kernels as K

    def step(carry, arrays, count):
        padmask = K.row_mask(count, cap)
        out: List = []
        ci = 0
        for (op, dstr, _hv), (d, v) in zip(sig, arrays):
            ok = K.value_ok(d, v, padmask)
            if op in _MOMENT_OPS:
                x = d.astype(jnp.float64)
                n_b = jnp.sum(ok).astype(jnp.int64)
                s_b = jnp.sum(jnp.where(ok, x, 0.0))
                nbf = jnp.maximum(n_b, 1).astype(jnp.float64)
                dd = jnp.where(ok, x - s_b / nbf, 0.0)
                m2_b = jnp.sum(dd * dd)
                n_a, s_a, m2_a = carry[ci], carry[ci + 1], carry[ci + 2]
                naf = jnp.maximum(n_a, 1).astype(jnp.float64)
                both = (n_a > 0) & (n_b > 0)
                delta = s_b / nbf - s_a / naf
                nf = n_a.astype(jnp.float64) + n_b.astype(jnp.float64)
                term = jnp.where(
                    both,
                    delta * delta * n_a.astype(jnp.float64)
                    * n_b.astype(jnp.float64) / jnp.maximum(nf, 1.0),
                    0.0)
                out += [n_a + n_b, s_a + s_b, m2_a + m2_b + term]
                ci += 3
            elif op in ("sum", "sumnull"):
                acc = carry[ci]
                x = d.astype(acc.dtype)
                s_b = jnp.sum(jnp.where(ok, x, jnp.zeros((), x.dtype)))
                out.append(acc + s_b)
                ci += 1
                if op == "sumnull":
                    out.append(carry[ci] + jnp.sum(ok).astype(jnp.int64))
                    ci += 1
            elif op in ("count", "size"):
                src = ok if op == "count" else padmask
                out.append(carry[ci] + jnp.sum(src).astype(jnp.int64))
                ci += 1
            elif op in ("min", "max"):
                ident = jnp.asarray(_minmax_identity(np.dtype(dstr), op))
                f = jnp.minimum if op == "min" else jnp.maximum
                red = jnp.min if op == "min" else jnp.max
                out.append(f(carry[ci], red(jnp.where(ok, d, ident))))
                out.append(carry[ci + 1] + jnp.sum(ok).astype(jnp.int64))
                ci += 2
            elif op == "prod":
                p_b = jnp.prod(jnp.where(ok, d.astype(jnp.float64), 1.0))
                out.append(carry[ci] * p_b)
                ci += 1
        return tuple(out)

    return jax.jit(step, donate_argnums=(0,) if donate else ())


class ReduceAccumulator:
    """Streaming whole-column reductions with a DEVICE-RESIDENT carry.

    The old shape — per-batch `reduce_table` → host scalars → Python
    combine — forced one device round-trip per batch, serializing decode
    and compute. Now each push dispatches ONE jitted step that folds the
    batch's masked partials into the running carry on device (Chan
    delta-form combine for the moments; reference:
    bodo/libs/groupby/_groupby_update.cpp var_combine), and the carry is
    DONATED back to the step on accelerator backends
    (`donate_argnums=(0,)`) so the state never occupies two buffers.
    The host reads nothing until finish(): host syncs per stage are
    O(1), was O(batches), and decode(n+1) overlaps compute(n)."""

    _SUPPORTED = {"sum", "sumnull", "count", "size", "min", "max", "mean",
                  "var", "std", "var0", "std0", "prod"}

    def __init__(self, aggs: Sequence[Tuple[str, str, str]]):
        for _, op, _ in aggs:
            if op not in self._SUPPORTED:
                raise NotImplementedError(op)
        self.aggs = list(aggs)
        self._template: Optional[Table] = None
        self._carry: Optional[Tuple] = None  # flat 0-d device scalars
        self._sig: Optional[Tuple] = None
        self._nbatches = 0
        self._donate = jax.default_backend() in ("tpu", "gpu")
        # verify_donation verdict after the first donated step (None
        # until one runs; False on backends that silently copy)
        self.donation_verified: Optional[bool] = None

    def _init_carry(self) -> Tuple:
        slots: List = []
        for op, dstr, _hv in self._sig:
            if op in _MOMENT_OPS:
                slots += [np.int64(0), np.float64(0.0), np.float64(0.0)]
            elif op == "sum":
                slots.append(np.zeros(
                    (), _sum_acc_dtype(np.dtype(dstr)))[()])
            elif op == "sumnull":
                slots += [np.zeros((), _sum_acc_dtype(np.dtype(dstr)))[()],
                          np.int64(0)]
            elif op in ("count", "size"):
                slots.append(np.int64(0))
            elif op in ("min", "max"):
                slots += [_minmax_identity(np.dtype(dstr), op),
                          np.int64(0)]
            elif op == "prod":
                slots.append(np.float64(1.0))
        return tuple(jnp.asarray(s) for s in slots)

    def push(self, batch: Table) -> None:
        if self._template is None:
            self._template = batch
            self._sig = tuple(
                (op, str(batch.column(col).data.dtype),
                 batch.column(col).valid is not None)
                for col, op, _ in self.aggs)
        if self._carry is None:
            self._carry = self._init_carry()
        arrays = tuple((batch.column(col).data, batch.column(col).valid)
                       for col, _, _ in self.aggs)
        step = _build_reduce_step(self._sig, batch.capacity, self._donate)
        from bodo_tpu.utils import tracing
        old = self._carry
        with tracing.event("stream_reduce"):
            self._carry = step(old, arrays, jnp.asarray(batch.nrows))
        self._nbatches += 1
        if self._donate and self.donation_verified is None:
            self.donation_verified = verify_carry_donation(old)

    def finish(self) -> Dict:
        from bodo_tpu.relational import _reduce_scalar
        if self._carry is None:
            host: List = []
        else:
            _note_sync()
            host = [np.asarray(x)
                    for x in jax.device_get(self._carry)]  # dispatch-boundary
        res = {}
        ci = 0
        for i, (col, op, oname) in enumerate(self.aggs):
            src_dt = (self._template.column(col).dtype
                      if self._template is not None else None)
            if op in _MOMENT_OPS:
                if not host:
                    res[oname] = np.nan
                    continue
                n = int(host[ci])
                s, m2 = float(host[ci + 1]), float(host[ci + 2])
                ci += 3
                if n == 0:
                    res[oname] = np.nan
                elif op == "mean":
                    res[oname] = _reduce_scalar(s / n, op, src_dt, n)
                else:
                    ddof = 0 if op.endswith("0") else 1
                    if n > ddof:
                        v = max(m2 / (n - ddof), 0.0)
                        v = float(np.sqrt(v)) if op.startswith("std") else v
                        res[oname] = _reduce_scalar(v, op, src_dt, n)
                    else:
                        res[oname] = np.nan
            elif op == "sum":
                res[oname] = (_reduce_scalar(host[ci], op, src_dt, None)
                              if host else np.nan)
                ci += 1
            elif op == "sumnull":
                if host and int(host[ci + 1]):
                    res[oname] = _reduce_scalar(host[ci], op, src_dt,
                                                int(host[ci + 1]))
                else:
                    res[oname] = np.nan
                ci += 2
            elif op in ("count", "size"):
                res[oname] = int(host[ci]) if host else 0
                ci += 1
            elif op in ("min", "max"):
                if host and int(host[ci + 1]):
                    res[oname] = _reduce_scalar(host[ci], op, src_dt,
                                                int(host[ci + 1]))
                else:
                    res[oname] = np.nan
                ci += 2
            elif op == "prod":
                res[oname] = (_reduce_scalar(host[ci], op, src_dt, None)
                              if host else 1.0)
                ci += 1
        return res


class _CarryView:
    """Duck-typed Table over a flat carry tuple, so the observatory's
    `verify_donation` (which walks `.columns[*].data/.valid`) can check
    a streamed carry's buffers were consumed by a donated dispatch."""

    class _Col:
        __slots__ = ("data", "valid")

        def __init__(self, data):
            self.data, self.valid = data, None

    def __init__(self, carry: Sequence):
        self.columns = {f"__c{i}": self._Col(a)
                        for i, a in enumerate(carry)}


def verify_carry_donation(carry: Sequence) -> bool:
    """After a donated streaming step, prove the previous carry's device
    buffers were actually consumed (not silently copied) via the
    observatory ledger. Returns the verdict; also feeds the
    donated-dispatch verification counters."""
    from bodo_tpu.runtime import xla_observatory as xobs
    return xobs.verify_donation(_CarryView(carry))


class SortAccumulator:
    """Streaming sort input: batches park in the native host pool
    (spillable, arbitrated by the operator comptroller) during
    accumulation; the sort itself runs on the restored whole table
    (device peak during accumulate is O(batch))."""

    def __init__(self, by, ascending, na_last: bool):
        from bodo_tpu.runtime.comptroller import default_comptroller
        from bodo_tpu.runtime.memory_governor import governor
        self._comp = default_comptroller()
        self._op = self._comp.register("stream_sort")
        self._grant = governor().admit("stream_sort")
        self.by, self.ascending, self.na_last = by, ascending, na_last
        self.parts: List = []

    def push(self, batch: Table) -> None:
        if batch.nrows:
            from bodo_tpu.runtime.memory_governor import \
                table_device_bytes
            part = _with_capacity(batch, _bucket_cap(max(batch.nrows, 1)))
            self.parts.append(self._comp.park(self._op, part))
            self._grant.record_spill(table_device_bytes(part))

    def finish(self) -> Table:
        assert self.parts, "empty stream — caller must fall back"
        tables = [p.restore() for p in self.parts]
        self.parts = []
        self._comp.unregister(self._op)
        self._grant.release()
        t = R.concat_tables(tables) if len(tables) > 1 else tables[0]
        return R.sort_table(t, self.by, self.ascending, self.na_last)

    def close(self) -> None:
        """Abandon without sorting (empty-stream fallback): free parked
        buffers and drop the comptroller registration."""
        for p in self.parts:
            p.free()
        self.parts = []
        self._comp.unregister(self._op)
        self._grant.release()


class StreamJoin:
    """Per-batch probe against a fully-built (offloaded) build side —
    the reference's streaming hash join with the build table parked in
    the buffer pool (bodo/libs/streaming/_join.cpp HashJoinState),
    accounted to this operator by the comptroller."""

    def __init__(self, build: Table, left_on, right_on, how, suffixes,
                 null_equal: bool = True):
        from bodo_tpu.runtime.comptroller import default_comptroller
        from bodo_tpu.runtime.memory_governor import (governor,
                                                      table_device_bytes)
        self.left_on, self.right_on = left_on, right_on
        self.how, self.suffixes = how, suffixes
        self.null_equal = null_equal
        self._comp = default_comptroller()
        self._op = self._comp.register("stream_join_build")
        if build.distribution != REP:
            # count-only comm row naming the streaming stage boundary;
            # the transfer wall/bytes land on the nested Table.gather
            # span, so no wall here (it would double-count in totals)
            from bodo_tpu.parallel import comm
            comm.record("stream_build_gather",
                        bytes_in=comm.table_bytes(build))
        b = build.gather() if build.distribution != REP else build
        self._grant = governor().admit("stream_join_build",
                                       want=table_device_bytes(b))
        self._off = self._comp.park(self._op, b)
        self._grant.record_spill(table_device_bytes(b))
        self._build: Optional[Table] = None

    def __call__(self, batch: Table) -> Table:
        if self._build is None:
            self._build = self._off.restore()
            self._comp.unregister(self._op)
            self._grant.release()
            # warm the device-resident build table once on restore so
            # every probe batch (including the first) skips the build
            # and its host dup-check sync (plan/fusion_join LRU)
            from bodo_tpu.plan import fusion_join
            fusion_join.prime_build(self._build, self.right_on,
                                    self.null_equal)
        out = R.join_tables(batch, self._build, self.left_on, self.right_on,
                            self.how, self.suffixes,
                            null_equal=self.null_equal)
        return _with_capacity(out, _bucket_cap(max(out.nrows, 1)))

    def close(self) -> None:
        """Release the parked build side if it was never probed (empty
        probe stream) — otherwise the comptroller would account a dead
        build table forever."""
        if self._build is None and not self._off._closed:
            self._off.free()
            self._comp.unregister(self._op)
            self._grant.release()


# ---------------------------------------------------------------------------
# plan → stream compilation
# ---------------------------------------------------------------------------

def _build_stream(node: L.Node) -> Optional[Iterator[Table]]:
    """Compile a plan subtree into a batch iterator, or None if any node
    is not streamable."""
    batch_rows = config.streaming_batch_size

    # scan sources run behind a Prefetcher: batch k+1 decodes on a host
    # thread while batch k runs on the device (runtime/io_pool.py). The
    # wrapper is lazy + self-closing, so a stream that try_stream_execute
    # builds and then abandons costs no thread.
    from bodo_tpu.runtime.io_pool import prefetched
    if isinstance(node, L.ReadParquet):
        return prefetched(
            parquet_batches(node.path, node.columns, batch_rows),
            label="parquet")
    if isinstance(node, L.ReadCsv):
        return prefetched(
            csv_batches(node.path, node.columns, node.parse_dates,
                        batch_rows),
            label="csv")
    if isinstance(node, L.FromPandas):
        if node.table.distribution != REP:
            return None
        return table_batches(node.table, batch_rows)
    if isinstance(node, (L.Filter, L.Projection)):
        # whole-stage fusion: compile a maximal filter/project chain
        # into ONE jitted per-batch program (single compaction at chain
        # exit) instead of one dispatch per stage per batch
        from bodo_tpu.plan import fusion
        chain = fusion.stream_chain(node)
        if chain is not None:
            steps, src = chain
            inner = _build_stream(src)
            if inner is None:
                return None
            out = fusion.fused_batches(steps, inner)
            if any(isinstance(s, L.Filter) for s in steps):
                from bodo_tpu.plan import adaptive
                out = adaptive.coalesce_batches(out, sharded=False)
            return out
    if isinstance(node, L.Filter):
        inner = _build_stream(node.child)
        if inner is None:
            return None
        pred = node.predicate

        def gen_filter(src):
            for b in src:
                yield R.filter_table(b, pred)
        # a selective filter leaves a tail of near-empty batches; merge
        # them back up to a useful fill before the next per-batch kernel
        from bodo_tpu.plan import adaptive
        return adaptive.coalesce_batches(gen_filter(inner), sharded=False)
    if isinstance(node, L.Projection):
        inner = _build_stream(node.child)
        if inner is None:
            return None
        from bodo_tpu.plan.physical import apply_projection
        exprs = node.exprs

        def gen_project(src):
            for b in src:
                yield apply_projection(b, exprs)
        return gen_project(inner)
    if isinstance(node, L.Join):
        if node.how not in ("inner", "left"):
            # right/outer emit unmatched BUILD rows: probing per batch
            # would duplicate them once per batch; cross would need the
            # probe-major order across batches — whole-table path instead
            return None
        inner = _build_stream(node.left)
        if inner is None:
            return None
        from bodo_tpu.runtime.pool import has_native_pool
        if not has_native_pool():
            # no C++ toolchain: whole-table fallback is correct, just
            # not memory-bounded
            log(1, "stream join disabled: native host pool unavailable")
            return None
        from bodo_tpu.plan import physical
        build = physical._exec(node.right)
        lo, ro = node.left_on, node.right_on
        how, suf, ne = node.how, node.suffixes, node.null_equal

        def gen_join(src):
            # the build side parks in the pool only once the generator
            # actually RUNS: a caller that abandons a never-started
            # generator skips `finally` blocks entirely (PEP 342), so an
            # eager park here would leak in the comptroller
            join = None
            try:
                for b in src:
                    if join is None:
                        join = StreamJoin(build, lo, ro, how, suf, ne)
                    yield join(b)
            finally:
                if join is not None:
                    join.close()  # releases the build if never probed
        return gen_join(inner)
    return None


def stream_to_parquet(node: L.Node, path: str) -> bool:
    """Stream an (already optimized) plan straight into a parquet file,
    one row group per batch — end-to-end bounded device memory for
    scan→filter→project→write shapes (reference:
    bodo/io/stream_parquet_write.py). Returns False when the plan isn't a
    streamable chain (caller materializes). Caller gates on
    config.stream_exec."""
    if mesh_mod.num_shards() > 1:
        return False
    # writing over one of the plan's own sources would truncate it while
    # the lazy reader is mid-file — materialize instead
    target = os.path.abspath(path)

    def reads_target(n: L.Node) -> bool:
        if isinstance(n, (L.ReadParquet, L.ReadCsv)):
            src_p = os.path.abspath(n.path)
            if src_p == target or src_p.startswith(target + os.sep) or \
                    target.startswith(src_p + os.sep):
                return True
        return any(reads_target(c) for c in n.children)

    if reads_target(node):
        return False
    src = _build_stream(node)
    if src is None:
        return False
    from bodo_tpu.io.parquet import StreamingParquetWriter
    n = 0
    with StreamingParquetWriter(path) as w:
        for b in src:
            w.push(b)
            n += 1
    if n == 0:
        return False  # empty stream: no schema to write — materialize
    log(1, f"streaming parquet write: {n} batches -> {path}")
    return True


def try_stream_execute(node: L.Node) -> Optional[Table]:
    """Execute a plan with the streaming batch executor when its shape
    supports it; None → caller falls back to whole-table execution."""
    if not config.stream_exec:
        return None
    from bodo_tpu.plan import adaptive
    from bodo_tpu.runtime.resilience import maybe_inject
    maybe_inject("stage.boundary")
    if mesh_mod.num_shards() > 1:
        from bodo_tpu.plan.streaming_sharded import \
            try_stream_execute_sharded
        return try_stream_execute_sharded(node)

    if isinstance(node, L.Aggregate):
        from bodo_tpu.table import dtypes as dt_
        if any(dt_.is_decimal(node.child.schema[c])
               for c, _, _ in node.aggs):
            return None  # streaming agg state isn't decimal-aware yet
        src = _build_stream(node.child)
        if src is None:
            return None
        try:
            acc = GroupbyAccumulator(node.keys, node.aggs)
        except NotImplementedError:
            try:
                # non-decomposable aggs: mixed streaming strategies
                # (distinct-pairs nunique, spillable ACC-mode rowstore)
                acc = MixedGroupbyStream(node.keys, node.aggs)
            except NotImplementedError:
                return None
        nb = 0
        for b in src:
            adaptive.observe_batch(b)
            acc.push(b)
            nb += 1
            _note_batch()
        if isinstance(acc, GroupbyAccumulator):
            if acc._template is None:
                return None  # empty stream: no schema — fall back
            log(1, f"streaming groupby: {nb} batches, "
                   f"{acc.n_state} groups")
            return acc.finish()
        if acc.dec._template is None:
            acc.close()
            return None
        log(1, f"streaming mixed groupby: {nb} batches")
        return acc.finish()

    if isinstance(node, L.Reduce):
        src = _build_stream(node.child)
        if src is None:
            return None
        try:
            acc = ReduceAccumulator(node.aggs)
        except NotImplementedError:
            return None
        for b in src:
            adaptive.observe_batch(b)
            acc.push(b)
            _note_batch()
        scalars = acc.finish()
        import pandas as pd
        return Table.from_pandas(
            pd.DataFrame({k: [v] for k, v in scalars.items()}))

    if isinstance(node, L.Sort):
        src = _build_stream(node.child)
        if src is None:
            return None
        try:
            acc = SortAccumulator(node.by, node.ascending, node.na_last)
        except RuntimeError as e:
            # native host pool unavailable: whole-table fallback
            log(1, f"stream sort disabled, falling back: {e}")
            return None
        for b in src:
            adaptive.observe_batch(b)
            acc.push(b)
            _note_batch()
        if not acc.parts:
            acc.close()
            return None  # empty stream: fall back (handles the 0-row case)
        return acc.finish()

    return None
