"""Hashable expression IR + JAX evaluator.

Analogue of the reference's expression nodes (bodo/pandas/plan.py:560-760
ColRefExpression/ArithOpExpression/ComparisonOpExpression/...). Being
frozen dataclasses, expressions are hashable and serve directly as jit
cache keys, so each distinct expression tree compiles exactly once.

String predicates evaluate against the host-side dictionary (tiny) and
become a boolean lookup-table gather on device — the dict-encoding trick
the reference uses for string-heavy workloads (bodo/libs/dict_arr_ext.py).
Null semantics follow SQL/pandas-float behavior: arithmetic propagates
nulls; comparisons with null produce null, and filters treat null as
False.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from bodo_tpu.ops import datetime as dtops
from bodo_tpu.table import dtypes as dt


class Expr:
    """Base class; all subclasses are frozen/hashable."""

    # -- operator sugar used by the frontend --------------------------------
    def _bin(self, op, other, reverse=False):
        o = other if isinstance(other, Expr) else Lit(other)
        return BinOp(op, o, self) if reverse else BinOp(op, self, o)

    def __add__(self, o): return self._bin("+", o)
    def __radd__(self, o): return self._bin("+", o, True)
    def __sub__(self, o): return self._bin("-", o)
    def __rsub__(self, o): return self._bin("-", o, True)
    def __mul__(self, o): return self._bin("*", o)
    def __rmul__(self, o): return self._bin("*", o, True)
    def __truediv__(self, o): return self._bin("/", o)
    def __rtruediv__(self, o): return self._bin("/", o, True)
    def __floordiv__(self, o): return self._bin("//", o)
    def __mod__(self, o): return self._bin("%", o)
    def __pow__(self, o): return self._bin("**", o)
    def __eq__(self, o): return self._bin("==", o)  # type: ignore[override]
    def __ne__(self, o): return self._bin("!=", o)  # type: ignore[override]
    def __lt__(self, o): return self._bin("<", o)
    def __le__(self, o): return self._bin("<=", o)
    def __gt__(self, o): return self._bin(">", o)
    def __ge__(self, o): return self._bin(">=", o)
    def __and__(self, o): return self._bin("&", o)
    def __rand__(self, o): return self._bin("&", o, True)
    def __or__(self, o): return self._bin("|", o)
    def __ror__(self, o): return self._bin("|", o, True)
    def __invert__(self): return UnOp("~", self)
    def __neg__(self): return UnOp("neg", self)
    def __abs__(self): return UnOp("abs", self)
    def key(self):
        """Structural cache key (expressions can't be dict keys directly:
        __eq__ is overloaded as the comparison *builder*)."""
        raise NotImplementedError

    def isin(self, values): return IsIn(self, tuple(values))
    def isna(self): return UnOp("isna", self)
    def notna(self): return UnOp("notna", self)
    def fillna(self, v): return Where(UnOp("isna", self), Lit(v), self)
    def astype(self, dtype): return Cast(self, dt.from_numpy(np.dtype(dtype)))


def _frozen(cls):
    return dataclass(frozen=True, eq=False, repr=True)(cls)


@_frozen
class ColRef(Expr):
    name: str
    def key(self): return ("col", self.name)


@_frozen
class Lit(Expr):
    value: Any
    def key(self): return ("lit", str(type(self.value).__name__), self.value)


@_frozen
class BinOp(Expr):
    op: str
    left: Expr
    right: Expr
    def key(self): return ("bin", self.op, self.left.key(), self.right.key())


@_frozen
class UnOp(Expr):
    op: str
    operand: Expr
    def key(self): return ("un", self.op, self.operand.key())


@_frozen
class Cast(Expr):
    operand: Expr
    to: dt.DType
    def key(self): return ("cast", self.operand.key(), self.to.name)


@_frozen
class DtField(Expr):
    field: str
    operand: Expr
    def key(self): return ("dtf", self.field, self.operand.key())


@_frozen
class IsIn(Expr):
    operand: Expr
    values: Tuple
    def key(self): return ("isin", self.operand.key(), self.values)


@_frozen
class Where(Expr):
    cond: Expr
    iftrue: Expr
    iffalse: Expr
    def key(self):
        return ("where", self.cond.key(), self.iftrue.key(), self.iffalse.key())


@_frozen
class RowUDF(Expr):
    """Compiled element-wise Python UDF (df.apply(axis=1) / Series.map).

    The callable is traced with jax.vmap over per-row scalars — the
    trace-to-XLA analogue of the reference compiling UDFs with a nested
    Numba pipeline (BodoCompilerUDF, bodo/compiler.py:705). String columns
    are withheld from the row namespace (dict codes would silently change
    semantics); a UDF touching one raises KeyError at trace time and the
    frontend falls back to pandas.
    """
    func: Any          # callable(Row) -> scalar, or callable(x) in scalar mode
    out_dtype: Any     # DType or None (trace default float64)
    operand: Any = None  # Expr → scalar mode (Series.map); None → row mode
    def key(self):
        return ("rowudf", _udf_serial(self.func),
                self.out_dtype.name if self.out_dtype else None,
                self.operand.key() if self.operand is not None else None)


_UDF_COUNTER = [0]
_UDF_SERIALS: Dict[int, Tuple] = {}  # id -> (weakref, serial)


def _udf_serial(func) -> int:
    """Stable serial per live callable — id() alone is unsafe as cache key
    (CPython reuses ids after GC; same guard as relational._dict_fp)."""
    s = getattr(func, "__bodo_tpu_udf_serial__", None)
    if s is not None:
        return s
    _UDF_COUNTER[0] += 1
    serial = _UDF_COUNTER[0]
    try:
        func.__bodo_tpu_udf_serial__ = serial
    except (AttributeError, TypeError):
        import weakref
        ent = _UDF_SERIALS.get(id(func))
        if ent is not None and ent[0]() is func:
            return ent[1]
        key = id(func)
        try:
            wr = weakref.ref(func, lambda _: _UDF_SERIALS.pop(key, None))
        except TypeError:
            wr = lambda: func  # not weakref-able: pin via closure
        _UDF_SERIALS[key] = (wr, serial)
    return serial


class _RowNS:
    """Attribute/item access over a dict of per-row scalar tracers;
    records which columns the UDF actually reads (for null propagation)."""
    __slots__ = ("_d", "_touched")

    def __init__(self, d, touched=None):
        object.__setattr__(self, "_d", d)
        object.__setattr__(self, "_touched", touched)

    def __getattr__(self, n):
        try:
            v = self._d[n]
        except KeyError:
            raise AttributeError(n)
        if self._touched is not None:
            self._touched.add(n)
        return v

    def __getitem__(self, n):
        if self._touched is not None and n in self._d:
            self._touched.add(n)
        return self._d[n]


@_frozen
class DictMap(Expr):
    """String→string transform applied to the host dictionary (substring,
    upper, lower): the device only remaps int32 codes through a host-built
    translation table — strings never reach the device (same trick as the
    reference's dict-encoded string kernels, bodo/libs/dict_arr_ext.py).
    Must sit at the top level of a projection (relational.assign_columns
    attaches the new dictionary host-side)."""
    kind: str          # substring | upper | lower | strip | replace | ...
    params: Tuple
    operand: Expr      # must reference a string column
    def key(self):
        return ("dictmap", self.kind, self.params, self.operand.key())

    def apply_host(self, s: str) -> str:
        if self.kind == "substring":
            start, length = self.params
            i = start - 1  # SQL is 1-based
            return s[i:i + length] if length is not None else s[i:]
        if self.kind == "slice":  # pandas .str.slice — 0-based, stop excl
            start, stop = self.params
            return s[start:stop]
        if self.kind == "upper":
            return s.upper()
        if self.kind == "lower":
            return s.lower()
        if self.kind == "strip":
            return s.strip(*self.params)
        if self.kind == "lstrip":
            return s.lstrip(*self.params)
        if self.kind == "rstrip":
            return s.rstrip(*self.params)
        if self.kind == "replace":
            old, new = self.params
            return s.replace(old, new)
        if self.kind == "title":
            return s.title()
        if self.kind == "capitalize":
            return s.capitalize()
        if self.kind == "zfill":
            return s.zfill(self.params[0])
        if self.kind == "lpad":
            n, fill = self.params
            if len(s) >= n:
                return s[:n]
            pad = (fill * n)[: n - len(s)] if fill else ""
            return pad + s
        if self.kind == "rpad":
            n, fill = self.params
            if len(s) >= n:
                return s[:n]
            return s + (fill * n)[: n - len(s)] if fill else s
        if self.kind == "left":
            n = self.params[0]
            return s[:n] if n > 0 else ""
        if self.kind == "right":
            n = self.params[0]
            return s[-n:] if n > 0 else ""
        if self.kind == "reverse":
            return s[::-1]
        if self.kind == "repeat":
            return s * self.params[0]
        if self.kind == "split_part":
            delim, n = self.params
            parts = s.split(delim) if delim else [s]
            return parts[n - 1] if 1 <= n <= len(parts) else ""
        if self.kind == "initcap":
            return re.sub(r"[A-Za-z0-9]+",
                          lambda m: m.group(0).capitalize(), s)
        if self.kind == "translate":
            src, dst = self.params
            return s.translate(str.maketrans(src, dst))
        if self.kind == "prepend":
            return self.params[0] + s
        if self.kind == "append":
            return s + self.params[0]
        if self.kind == "regexp_replace":
            # (pat, repl[, position, occurrence]) — occurrence 0 = all
            # (Snowflake REGEXP_REPLACE semantics,
            # bodosql/kernels/regexp_array_kernels.py)
            pat, repl = self.params[:2]
            pos = self.params[2] if len(self.params) > 2 else 1
            occ = self.params[3] if len(self.params) > 3 else 0
            head, tail = s[:pos - 1], s[pos - 1:]
            if occ == 0:
                return head + re.sub(pat, repl, tail)
            n = 0
            for m in re.finditer(pat, tail):
                n += 1
                if n == occ:
                    return (head + tail[:m.start()] + m.expand(repl)
                            + tail[m.end():])
            return s  # fewer than `occ` matches: unchanged
        if self.kind == "regexp_substr":
            # (pat[, position, occurrence, group]) — no-match rows become
            # NULL (validity handled by the assign_columns host pass,
            # relational._str_part)
            m = self._re_match(s)
            if m is None:
                return ""
            grp = self.params[3] if len(self.params) > 3 else 0
            return m.group(grp) or ""
        if self.kind == "json_extract":
            # JSON_EXTRACT_PATH_TEXT: dotted/indexed path into a JSON
            # string; missing path / bad JSON -> NULL via host_null
            # (bodosql/kernels/json_array_kernels.py)
            v = _json_path_get(s, self.params[0])
            if v is None:
                return ""
            if isinstance(v, (dict, list)):
                import json as _json
                return _json.dumps(v, separators=(",", ":"))
            if isinstance(v, bool):
                return "true" if v else "false"
            return str(v)
        if self.kind == "json_canon":
            # PARSE_JSON/TO_JSON canonical form; invalid JSON -> NULL
            import json as _json
            try:
                return _json.dumps(_json.loads(s),
                                   separators=(",", ":"))
            except Exception:
                return ""
        if self.kind == "strtok":
            # STRTOK(s[, delim, part]): split on ANY delimiter char,
            # empty tokens dropped (Snowflake)
            part = self.params[1] if len(self.params) > 1 else 1
            toks = self._strtok_tokens(s)
            return toks[part - 1] if 1 <= part <= len(toks) else ""
        if self.kind == "check_json":
            # Snowflake CHECK_JSON: NULL for valid JSON (host_null),
            # the parse-error description for invalid
            import json as _json
            try:
                _json.loads(s)
                return ""
            except Exception as exc:
                return str(exc)
        if self.kind == "insert":
            # INSERT(s, pos, len, repl) (Snowflake)
            pos, n, repl = self.params
            i = pos - 1
            return s[:i] + repl + s[i + n:]
        if self.kind == "ljust":
            n, fill = self.params
            return s.ljust(n, fill)
        if self.kind == "rjust":
            n, fill = self.params
            return s.rjust(n, fill)
        if self.kind == "center":
            n, fill = self.params
            return s.center(n, fill)
        if self.kind == "get":
            i = self.params[0]
            return s[i] if -len(s) <= i < len(s) else ""
        if self.kind == "md5":
            import hashlib
            return hashlib.md5(s.encode()).hexdigest()
        if self.kind == "sha1":
            import hashlib
            return hashlib.sha1(s.encode()).hexdigest()
        if self.kind == "sha2":
            import hashlib
            bits = self.params[0] if self.params else 256
            h = {224: hashlib.sha224, 256: hashlib.sha256,
                 384: hashlib.sha384, 512: hashlib.sha512}[bits]
            return h(s.encode()).hexdigest()
        raise ValueError(self.kind)

    def _strtok_tokens(self, s: str):
        """STRTOK tokens: split on ANY delimiter char, drop empties; an
        empty delimiter set means the whole string is one token."""
        delim = self.params[0] if self.params else " "
        if not delim:
            return [s] if s else []
        return [t_ for t_ in re.split(
            "|".join(re.escape(c) for c in delim), s) if t_]

    def _re_match(self, s: str):
        """regexp_substr match honoring (pat, position, occurrence)."""
        pat = self.params[0]
        pos = self.params[1] if len(self.params) > 1 else 1
        occ = self.params[2] if len(self.params) > 2 else 1
        n = 0
        for m in re.finditer(pat, s[pos - 1:]):
            n += 1
            if n == occ:
                return m
        return None

    def host_null(self, s: str) -> bool:
        """Whether this transform yields NULL for input `s` (applied by
        the assign_columns host pass; eval-side predicates ignore it)."""
        if self.kind == "regexp_substr":
            m = self._re_match(s)
            if m is None:
                return True
            grp = self.params[3] if len(self.params) > 3 else 0
            return m.group(grp) is None
        if self.kind == "json_extract":
            return _json_path_get(s, self.params[0]) is None
        if self.kind == "json_canon":
            import json as _json
            try:
                _json.loads(s)
                return False
            except Exception:
                return True
        if self.kind == "strtok":
            part = self.params[1] if len(self.params) > 1 else 1
            return not (1 <= part <= len(self._strtok_tokens(s)))
        if self.kind == "check_json":
            import json as _json
            try:
                _json.loads(s)
                return True   # valid JSON -> NULL (Snowflake CHECK_JSON)
            except Exception:
                return False
        if self.kind == "get":
            i = self.params[0]
            return not (-len(s) <= i < len(s))
        return False


def _json_path_get(s: str, path: str):
    """Walk a dotted/bracketed path into a JSON string; None on invalid
    JSON or a missing step (JSON_EXTRACT_PATH_TEXT / GET_PATH host
    evaluator; reference: bodosql/kernels/json_array_kernels.py)."""
    import json as _json
    try:
        v = _json.loads(s)
        parts = _split_json_path(path)
    except Exception:
        return None
    for part in parts:
        if isinstance(part, int):
            if not isinstance(v, list) or not (-len(v) <= part < len(v)):
                return None
            v = v[part]
        else:
            if not isinstance(v, dict) or part not in v:
                return None
            v = v[part]
    return v


def _split_json_path(path: str):
    """'a.b[2].c' / "a['b']" -> ['a', 'b', 2, 'c']. Quote-aware: a
    QUOTED segment is always a string key (even '\"2\"', and even when
    it contains '.' or '['); only bare bracketed integers become list
    indices. Raises ValueError on malformed paths (unclosed quote or
    bracket) — callers treat that as no-match."""
    parts: list = []
    i, n = 0, len(path)
    while i < n:
        c = path[i]
        if c == ".":
            i += 1
        elif c in "'\"":
            j = path.find(c, i + 1)
            if j < 0:
                raise ValueError(f"unclosed quote in path {path!r}")
            parts.append(path[i + 1:j])
            i = j + 1
        elif c == "[":
            j = path.find("]", i)
            if j < 0:
                raise ValueError(f"unclosed bracket in path {path!r}")
            seg = path[i + 1:j].strip()
            if seg[:1] in "'\"":
                if len(seg) < 2 or seg[-1] != seg[0]:
                    raise ValueError(f"bad quoted key in path {path!r}")
                parts.append(seg[1:-1])
            elif seg.lstrip("-").isdigit():
                parts.append(int(seg))
            else:
                parts.append(seg)
            i = j + 1
        else:
            j = i
            while j < n and path[j] not in ".[":
                j += 1
            seg = path[i:j].strip()
            if seg:
                parts.append(int(seg) if seg.lstrip("-").isdigit()
                             else seg)
            i = j
    return parts


@_frozen
class MathFn(Expr):
    """Element-wise math function on the VPU (SQL kernel library analogue
    of the reference's numeric kernels, BodoSQL/bodosql/kernels/
    numeric_array_kernels.py). kind: ceil|floor|sqrt|exp|ln|log10|log2|
    sign|sin|cos|tan|asin|acos|atan|degrees|radians|round|round_even|
    trunc. `round`/`trunc` take (digits,) in params; SQL `round` is
    half-away-from-zero, `round_even` is banker's (pandas)."""
    kind: str
    params: Tuple
    operand: Expr
    def key(self): return ("math", self.kind, self.params, self.operand.key())


@_frozen
class ToChar(Expr):
    """TO_CHAR/TO_VARCHAR of a non-string operand: the operand evaluates
    on device, values round-trip to host once, and the formatted strings
    dict-encode like any ingest (reference:
    bodosql/kernels/casting_array_kernels.py to_char). `fmt` is a
    Snowflake-style date format ('YYYY-MM-DD' etc., translated to
    strftime) or None for the canonical numeric/date rendering. Must sit
    at the top level of a projection (relational.assign_columns builds
    the new dictionary host-side, same contract as DictMap)."""
    fmt: Optional[str]
    operand: Expr

    def key(self):
        return ("tochar", self.fmt, self.operand.key())

    _FMT = (("YYYY", "%Y"), ("YY", "%y"), ("MMMM", "%B"),
            ("MON", "%b"), ("MM", "%m"), ("DD", "%d"), ("DY", "%a"),
            ("HH24", "%H"), ("HH12", "%I"), ("HH", "%H"),
            ("MI", "%M"), ("SS", "%S"), ("AM", "%p"), ("PM", "%p"))

    def strftime_fmt(self) -> Optional[str]:
        if self.fmt is None:
            return None
        out = self.fmt
        for sf, py in self._FMT:
            out = out.replace(sf, py).replace(sf.lower(), py)
        return out


@_frozen
class MaskNull(Expr):
    """Null out rows where `cond` holds (NULLIF building block): data
    passes through, validity becomes valid & ~cond."""
    cond: Expr
    operand: Expr
    def key(self): return ("masknull", self.cond.key(), self.operand.key())


def contains_expr(e, cls, stop=()) -> bool:
    """True when `e` or any sub-expression is an instance of `cls`
    (generic dataclass-field walk; tuples of Exprs are descended).
    Subtrees rooted at a `stop` node are not entered — callers use this
    to exempt nodes that consume the target legally (e.g. StrPredicate
    evaluates a CodeLUT operand at the dictionary level itself)."""
    if isinstance(e, cls):
        return True
    if stop and isinstance(e, stop):
        return False
    import dataclasses
    if not dataclasses.is_dataclass(e):
        return False
    for f in dataclasses.fields(e):
        v = getattr(e, f.name)
        for x in (v if isinstance(v, tuple) else (v,)):
            if isinstance(x, Expr) and contains_expr(x, cls, stop):
                return True
    return False


def codelut_misplaced(e, consumer_ok: bool = True) -> bool:
    """True when a CodeLUT sits in a position where evaluation would
    yield raw LUT codes with no dictionary attached.

    Legal positions: the top level of a projection, or the operand
    spine (DictMap* → CodeLUT) of a string-CONSUMING node
    (StrPredicate/StrLen/StrHostFn/StrCodes evaluate the LUT at
    dictionary level). Unlike a `stop`-pruned contains_expr walk, the
    scan continues INSIDE consumer operands, so e.g.
    StrPredicate(Where(c, CodeLUT, x)) is still reported."""
    import dataclasses
    if isinstance(e, CodeLUT):
        # a legally-consumed CodeLUT's integer operand must itself be
        # CodeLUT-free
        return (not consumer_ok) or codelut_misplaced(e.operand, False)
    if isinstance(e, (StrPredicate, StrLen, StrHostFn, StrCodes)):
        op = e.operand
        while isinstance(op, DictMap):
            op = op.operand
        return codelut_misplaced(op, True)
    if not dataclasses.is_dataclass(e):
        return False
    for f in dataclasses.fields(e):
        v = getattr(e, f.name)
        for x in (v if isinstance(v, tuple) else (v,)):
            if isinstance(x, Expr) and codelut_misplaced(x, False):
                return True
    return False


@_frozen
class CodeLUT(Expr):
    """String column from a small static vocabulary indexed by an integer
    expression (MONTHNAME/DAYNAME analogue of the reference's
    bodosql/kernels/datetime_array_kernels.py monthname). `operand` must
    produce codes in [0, len(strings)); the device only sees the
    remapping into the sorted dictionary."""
    strings: Tuple
    operand: Expr
    def key(self): return ("codelut", self.strings, self.operand.key())

    def sorted_dict(self) -> np.ndarray:
        return np.sort(np.asarray(self.strings, dtype=str))

    def rank_lut(self) -> np.ndarray:
        """rank_lut[i] = position of strings[i] in the sorted dictionary."""
        return np.argsort(np.argsort(np.asarray(self.strings, dtype=str))
                          ).astype(np.int32)


@_frozen
class StrHostFn(Expr):
    """Numeric function of a string column, evaluated per dictionary
    entry on host → device gather through the LUT (same trick as StrLen).
    kind: position(sub) 1-based 0-if-absent | ascii | to_number |
    to_date | regexp_count(pat). to_number/to_date entries that fail to
    parse become null."""
    kind: str
    params: Tuple
    operand: Expr
    def key(self): return ("strhost", self.kind, self.params,
                           self.operand.key())

    def apply_host(self, s: str):
        """Returns (value, ok)."""
        if self.kind == "position":
            return s.find(self.params[0]) + 1, True
        if self.kind == "ascii":
            return (ord(s[0]) if s else 0), True
        if self.kind == "to_number":
            try:
                return float(s), True
            except ValueError:
                return 0.0, False
        if self.kind == "to_date":
            try:
                d = np.datetime64(s.strip()[:10], "D")
            except ValueError:
                return 0, False
            if np.isnat(d):  # np.datetime64('') parses to NaT, no raise
                return 0, False
            return int(d.astype(np.int64)), True
        if self.kind == "regexp_count":
            pos = self.params[1] if len(self.params) > 1 else 1
            return len(re.findall(self.params[0], s[pos - 1:])), True
        if self.kind == "regexp_instr":
            # (pat[, position, occurrence, option]) -> 1-based match
            # start (option=0) or one past the end (option=1); 0 = no
            # match (Snowflake REGEXP_INSTR)
            pat = self.params[0]
            pos = self.params[1] if len(self.params) > 1 else 1
            occ = self.params[2] if len(self.params) > 2 else 1
            opt = self.params[3] if len(self.params) > 3 else 0
            n = 0
            for m in re.finditer(pat, s[pos - 1:]):
                n += 1
                if n == occ:
                    return (m.end() if opt else m.start()) + pos, True
            return 0, True
        if self.kind == "editdistance":
            t_ = self.params[0]
            cap = self.params[1] if len(self.params) > 1 else None
            prev = list(range(len(t_) + 1))
            for i, cs in enumerate(s, 1):
                cur = [i]
                for j, ct in enumerate(t_, 1):
                    cur.append(min(prev[j] + 1, cur[j - 1] + 1,
                                   prev[j - 1] + (cs != ct)))
                prev = cur
            d = prev[-1]
            return (min(d, cap) if cap is not None else d), True
        raise ValueError(self.kind)


@_frozen
class StrConcat(Expr):
    """Concatenation of string columns and literal fragments into one
    dict-encoded column. parts: str literals and string-producing Exprs.
    With k column parts the combined dictionary is the cross product of
    the part dictionaries (mixed-radix codes on device), gated by
    MAX_CONCAT_DICT — the dict-encoded analogue of the reference's
    concat kernel (BodoSQL/bodosql/kernels/string_array_kernels.py)."""
    parts: Tuple
    def key(self):
        return ("strcat", tuple(p if isinstance(p, str) else p.key()
                                for p in self.parts))


MAX_CONCAT_DICT = 1 << 20


@_frozen
class DateTrunc(Expr):
    """DATE_TRUNC(unit, x): start of the containing unit."""
    unit: str
    operand: Expr
    def key(self): return ("dtrunc", self.unit, self.operand.key())


@_frozen
class DateAdd(Expr):
    """DATEADD(unit, n, x) — calendar-correct for month/quarter/year
    (day-of-month clamped), tick arithmetic for fixed-width units."""
    unit: str
    amount: Expr
    operand: Expr
    def key(self): return ("dadd", self.unit, self.amount.key(),
                           self.operand.key())


@_frozen
class DateDiff(Expr):
    """DATEDIFF(unit, a, b) = boundary count from a to b (Snowflake
    semantics: year diff is year(b)-year(a), etc.)."""
    unit: str
    left: Expr
    right: Expr
    def key(self): return ("ddiff", self.unit, self.left.key(),
                           self.right.key())


@_frozen
class StrLen(Expr):
    """Per-row string length via a host dictionary LUT → device int32
    gather (same dict-encoded trick as StrPredicate; reference:
    bodo/libs/dict_arr_ext.py str_len kernel)."""
    operand: Expr

    def key(self):
        return ("strlen", self.operand.key())


@_frozen
class NestedFn(Expr):
    """Semi-structured access over nested (list/struct/map) columns
    (reference: BodoSQL/bodosql/kernels/semistructured_array_kernels.py
    GET/GET_PATH/ARRAY_SIZE). kind: list_len | list_get(i) |
    field(name). Kernels are host-dictionary LUTs gathered on device
    (table/nested.py); string-valued results attach their dictionary in
    the assign_columns host pass, so NestedFn must sit at the top level
    of a projection like DictMap."""
    kind: str
    params: Tuple
    operand: Expr
    def key(self): return ("nested", self.kind, self.params,
                           self.operand.key())


@_frozen
class StrToList(Expr):
    """str.split(expand=False) → list<string> column; the split runs
    once per distinct dictionary entry on host (table/nested.py design;
    reference: bodo/libs/dict_arr_ext.py str_split + array_item repr).
    Must sit at the top level of a projection like DictMap."""
    params: Tuple      # (pat, maxsplit)
    operand: Expr
    def key(self): return ("strtolist", self.params, self.operand.key())

    def split_host(self, s: str):
        pat, n = self.params
        return tuple(s.split(pat) if n <= 0 else s.split(pat, n))


@_frozen
class StrCodes(Expr):
    """Dictionary codes of a string column as int32 (pandas .cat.codes
    analogue; nulls become -1). The dictionary is sorted, so on a
    freshly-scanned column codes equal `astype('category')` codes; after
    a filter the full dictionary persists, so codes may be sparser than
    pandas' renumbering (see _CatAccessor docstring). Reference:
    bodo/hiframes/pd_categorical_ext.py get_categorical_arr_codes."""
    operand: Expr
    def key(self): return ("strcodes", self.operand.key())


@_frozen
class StrPredicate(Expr):
    """String predicate evaluated on the host dictionary → device LUT.
    kind: contains | startswith | endswith | match | eq_any | lower_eq"""
    kind: str
    pattern: Tuple
    operand: Expr
    def key(self):
        return ("strp", self.kind, self.pattern, self.operand.key())


# ---------------------------------------------------------------------------
# schema-level type inference (host side)
# ---------------------------------------------------------------------------

def infer_dtype(e: Expr, schema: Dict[str, dt.DType]) -> dt.DType:
    if isinstance(e, ColRef):
        return schema[e.name]
    if isinstance(e, Lit):
        v = e.value
        if isinstance(v, bool):
            return dt.BOOL
        if isinstance(v, (int, np.integer)):
            return dt.INT64
        if isinstance(v, (float, np.floating)):
            return dt.FLOAT64
        if isinstance(v, str):
            return dt.STRING
        if isinstance(v, (np.datetime64,)):
            return dt.DATETIME
        import datetime as _dtmod
        if isinstance(v, _dtmod.date) and not isinstance(v, _dtmod.datetime):
            return dt.DATE
        raise TypeError(f"unsupported literal: {v!r}")
    if isinstance(e, Cast):
        return e.to
    if isinstance(e, DtField):
        return dt.DATE if e.field == "date" else dt.INT64
    if isinstance(e, (IsIn, StrPredicate)):
        return dt.BOOL
    if isinstance(e, (DictMap, CodeLUT, StrConcat, ToChar)):
        return dt.STRING
    if isinstance(e, StrToList):
        return dt.list_of(dt.STRING)
    if isinstance(e, NestedFn):
        src = infer_dtype(e.operand, schema)
        if e.kind == "list_len":
            return dt.INT64
        if e.kind == "list_get":
            return src.elem if src.kind == "list" else dt.FLOAT64
        if e.kind == "field":
            if src.kind == "map":
                return src.value
            if src.kind == "struct":
                m = dict(src.fields)
                if e.params[0] in m:
                    return m[e.params[0]]
            return dt.FLOAT64
        raise ValueError(e.kind)
    if isinstance(e, StrLen):
        return dt.INT64
    if isinstance(e, StrCodes):
        return dt.INT32
    if isinstance(e, StrHostFn):
        if e.kind == "to_number":
            return dt.FLOAT64
        if e.kind == "to_date":
            return dt.DATE
        return dt.INT64
    if isinstance(e, MathFn):
        if e.kind == "sign":
            return dt.INT64
        if e.kind in ("ceil", "floor", "round", "round_even", "trunc"):
            src = infer_dtype(e.operand, schema)
            if dt.is_decimal(src):
                return dt.FLOAT64
            return src if src.kind in ("i", "u") else dt.FLOAT64
        return dt.FLOAT64
    if isinstance(e, MaskNull):
        return infer_dtype(e.operand, schema)
    if isinstance(e, DateTrunc):
        return infer_dtype(e.operand, schema)
    if isinstance(e, DateAdd):
        src = infer_dtype(e.operand, schema)
        if src is dt.DATE and e.unit in ("hour", "minute", "second"):
            return dt.DATETIME
        return src
    if isinstance(e, DateDiff):
        return dt.INT64
    if isinstance(e, RowUDF):
        if e.out_dtype is not None:
            return e.out_dtype
        return dt.FLOAT64
    if isinstance(e, UnOp):
        if e.op in ("isna", "notna", "~"):
            return dt.BOOL
        return infer_dtype(e.operand, schema)
    if isinstance(e, Where):
        t = infer_dtype(e.iftrue, schema)
        f = infer_dtype(e.iffalse, schema)
        if t is f:
            return t
        if dt.is_numeric(t) and dt.is_numeric(f):
            return dt.common_numeric(t, f)
        return t
    if isinstance(e, BinOp):
        if e.op in ("==", "!=", "<", "<=", ">", ">=", "&", "|"):
            return dt.BOOL
        if e.op in ("max2", "min2"):
            lt = infer_dtype(e.left, schema)
            rt = infer_dtype(e.right, schema)
            if dt.is_decimal(lt) or dt.is_decimal(rt):
                ls = lt.scale if dt.is_decimal(lt) else 0
                rs = rt.scale if dt.is_decimal(rt) else 0
                return dt.decimal(max(ls, rs))
            if dt.is_numeric(lt) and dt.is_numeric(rt):
                return dt.common_numeric(lt, rt)
            return lt
        lt = infer_dtype(e.left, schema)
        rt = infer_dtype(e.right, schema)
        if dt.is_decimal(lt) or dt.is_decimal(rt):
            ls = lt.scale if dt.is_decimal(lt) else None
            rs = rt.scale if dt.is_decimal(rt) else None
            float_side = (ls is None and lt.kind == "f") or \
                (rs is None and rt.kind == "f")
            if float_side or e.op == "/":
                return dt.FLOAT64
            if e.op == "*":
                return dt.decimal((ls or 0) + (rs or 0))
            return dt.decimal(max(ls or 0, rs or 0))
        if e.op == "/":
            return dt.FLOAT64 if lt.numpy.itemsize == 8 or rt.numpy.itemsize == 8 \
                else dt.FLOAT32
        if dt.is_numeric(lt) and dt.is_numeric(rt):
            return dt.common_numeric(lt, rt)
        return lt
    raise TypeError(f"cannot infer dtype of {e}")


def expr_columns(e: Expr) -> set:
    """Free column references (for projection pushdown)."""
    if isinstance(e, ColRef):
        return {e.name}
    if isinstance(e, Lit):
        return set()
    if isinstance(e, BinOp):
        return expr_columns(e.left) | expr_columns(e.right)
    if isinstance(e, RowUDF):
        if e.operand is not None:
            return expr_columns(e.operand)
        return {"*"}  # may touch any column — disables pruning above it
    if isinstance(e, (UnOp, Cast, DtField, IsIn, StrPredicate, DictMap,
                      StrLen, MathFn, StrHostFn, CodeLUT, DateTrunc,
                      StrCodes, StrToList, NestedFn, ToChar)):
        return expr_columns(e.operand)
    if isinstance(e, Where):
        return (expr_columns(e.cond) | expr_columns(e.iftrue)
                | expr_columns(e.iffalse))
    if isinstance(e, MaskNull):
        return expr_columns(e.cond) | expr_columns(e.operand)
    if isinstance(e, DateAdd):
        return expr_columns(e.amount) | expr_columns(e.operand)
    if isinstance(e, DateDiff):
        return expr_columns(e.left) | expr_columns(e.right)
    if isinstance(e, StrConcat):
        out = set()
        for p in e.parts:
            if isinstance(p, Expr):
                out |= expr_columns(p)
        return out
    return set()


# ---------------------------------------------------------------------------
# static value-range inference (host side; feeds Column.vrange)
# ---------------------------------------------------------------------------

# fields with fixed output ranges regardless of input
_FIELD_RANGES = {"month": (1, 12), "hour": (0, 23), "day": (1, 31),
                 "dayofweek": (0, 6), "weekday": (0, 6),
                 "quarter": (1, 4), "minute": (0, 59), "second": (0, 59),
                 "week": (1, 53), "weekofyear": (1, 53),
                 "dayofyear": (1, 366)}


def expr_range(e: Expr, columns) -> Optional[tuple]:
    """Host-known (lo, hi, tight) bound on the physical values of `e`,
    or None. `columns` maps name -> Column (for source vranges).
    `tight` means refinement (an exact device min/max) would not shrink
    the bound enough to matter — parquet scan stats and literals are
    tight, fixed field ranges (month in 1..12) are loose. Conservative:
    returns None unless the bound is certain."""
    if isinstance(e, ColRef):
        c = columns.get(e.name)
        return c.vrange if c is not None else None
    if isinstance(e, Lit):
        v = e.value
        if isinstance(v, (bool, np.bool_)):
            return (int(v), int(v), True)
        if isinstance(v, (int, np.integer)):
            return (int(v), int(v), True)
        return None
    if isinstance(e, DtField):
        if e.field in _FIELD_RANGES:
            lo, hi = _FIELD_RANGES[e.field]
            return (lo, hi, False)
        src = expr_range(e.operand, columns)
        if src is None:
            return None
        lo, hi = src[0], src[1]
        tight = len(src) > 2 and bool(src[2])
        if e.field == "date":       # monotone in ticks
            day = 86_400_000_000_000
            return (int(lo) // day, int(hi) // day, tight)
        if e.field == "year":       # monotone in ticks
            return (int(np.datetime64(int(lo), "ns").astype(
                        "datetime64[Y]").astype(int)) + 1970,
                    int(np.datetime64(int(hi), "ns").astype(
                        "datetime64[Y]").astype(int)) + 1970, tight)
        return None
    if isinstance(e, Where):
        a = expr_range(e.iftrue, columns)
        b = expr_range(e.iffalse, columns)
        if a is None or b is None:
            return None
        return (min(a[0], b[0]), max(a[1], b[1]),
                (len(a) > 2 and bool(a[2])) and
                (len(b) > 2 and bool(b[2])))
    if isinstance(e, Cast):
        if e.to.kind in ("i", "u"):
            r = expr_range(e.operand, columns)
            if r is None:
                return None
            # a narrowing cast (int64 → int32/int8) wraps values that
            # exceed the target type, so the operand's bound is only
            # sound when it fits entirely within the target's range —
            # otherwise dense-groupby planners would trust a violated
            # bound and silently mis-slot rows
            info = np.iinfo(e.to.numpy)
            if info.min <= r[0] and r[1] <= info.max:
                return r
            return None
        return None
    if isinstance(e, MaskNull):
        return expr_range(e.operand, columns)
    return None


# ---------------------------------------------------------------------------
# evaluation (device side, traced)
# ---------------------------------------------------------------------------

_CMP = {"==": jnp.equal, "!=": jnp.not_equal, "<": jnp.less,
        "<=": jnp.less_equal, ">": jnp.greater, ">=": jnp.greater_equal}


def eval_expr(e: Expr, tree: Dict[str, Tuple], dicts: Dict[str, np.ndarray],
              schema: Dict[str, dt.DType]):
    """Evaluate to (data, valid_or_None). `tree` maps column name to
    (data, valid); `dicts` holds host dictionaries for string columns."""
    if isinstance(e, ColRef):
        return tree[e.name]
    if isinstance(e, Lit):
        v = e.value
        if isinstance(v, str):
            raise TypeError(
                "string literal outside a string predicate — wrap string "
                "comparisons in StrPredicate (frontend does this)")
        if isinstance(v, np.datetime64):
            # match the DATETIME physical repr (int64 ns ticks)
            return jnp.asarray(np.int64(v.astype("datetime64[ns]")
                                        .astype(np.int64))), None
        import datetime as _dtmod
        if isinstance(v, _dtmod.date) and not isinstance(v, _dtmod.datetime):
            # match the DATE physical repr (int32 days since epoch)
            return jnp.asarray(np.int32(
                (np.datetime64(v, "D") - np.datetime64(0, "D"))
                .astype(np.int32))), None
        return jnp.asarray(v), None
    if isinstance(e, Cast):
        d, v = eval_expr(e.operand, tree, dicts, schema)
        src = infer_dtype(e.operand, schema)
        if e.to is dt.STRING:
            raise TypeError("cast to string not supported on device")
        if src.kind == "f" and e.to.kind in ("i", "u"):
            nan = jnp.isnan(d)
            v = (~nan) if v is None else (v & ~nan)
            d = jnp.where(nan, 0, d)
        return d.astype(e.to.numpy), v
    if isinstance(e, DtField):
        d, v = eval_expr(e.operand, tree, dicts, schema)
        if infer_dtype(e.operand, schema) is dt.DATE:
            # DATE stores days; field kernels expect ns ticks
            d = d.astype(jnp.int64) * dtops.NS_PER_DAY
        return dtops.FIELDS[e.field](d), v
    if isinstance(e, UnOp):
        if e.op in ("isna", "notna"):
            d, v = eval_expr(e.operand, tree, dicts, schema)
            isna = jnp.zeros(d.shape, dtype=bool)
            if v is not None:
                isna = ~v
            if jnp.issubdtype(d.dtype, jnp.floating):
                isna = isna | jnp.isnan(d)
            return (isna if e.op == "isna" else ~isna), None
        d, v = eval_expr(e.operand, tree, dicts, schema)
        if e.op == "~":
            return jnp.logical_not(d), v
        if e.op == "neg":
            return jnp.negative(d), v
        if e.op == "abs":
            return jnp.abs(d), v
        raise ValueError(f"unknown unop {e.op}")
    if isinstance(e, IsIn):
        d, v = eval_expr(e.operand, tree, dicts, schema)
        src = infer_dtype(e.operand, schema)
        if src is dt.STRING:
            return eval_expr(StrPredicate("eq_any", tuple(e.values),
                                          e.operand), tree, dicts, schema)
        acc = jnp.zeros(d.shape, dtype=bool)
        for val in e.values:
            acc = acc | (d == val)
        return acc, v
    if isinstance(e, RowUDF):
        import jax
        if e.operand is not None:  # scalar mode (Series.map)
            d, v = eval_expr(e.operand, tree, dicts, schema)
            out = jax.vmap(e.func)(d)
            if e.out_dtype is not None:
                out = out.astype(e.out_dtype.numpy)
            return out, v
        # row mode: withhold string/temporal columns — their physical repr
        # (dict codes, int ticks) would silently change meaning; a UDF
        # touching one fails the trace → frontend falls back to pandas
        numeric = {n: d for n, (d, v) in tree.items()
                   if schema.get(n) is not None
                   and schema[n].kind in ("i", "u", "f", "b")}
        # discover which columns the UDF reads (abstract pre-trace), so
        # null masks propagate only from consumed columns
        touched: set = set()
        jax.eval_shape(
            lambda row: e.func(_RowNS(row, touched)),
            {n: jax.ShapeDtypeStruct((), d.dtype) for n, d in numeric.items()})

        def one_row(row_vals):
            return e.func(_RowNS(row_vals))
        out = jax.vmap(one_row)(numeric)
        if e.out_dtype is not None:
            out = out.astype(e.out_dtype.numpy)
        valid = None
        for n in sorted(touched):
            v = tree[n][1]
            if v is not None:
                valid = v if valid is None else (valid & v)
        return out, valid
    if isinstance(e, MathFn):
        d, v = eval_expr(e.operand, tree, dicts, schema)
        src = infer_dtype(e.operand, schema)
        if dt.is_decimal(src):
            d = d.astype(jnp.float64) / (10.0 ** src.scale)
            src = dt.FLOAT64
        k = e.kind
        if k == "sign":
            return jnp.sign(d).astype(jnp.int64), v
        if k in ("ceil", "floor", "round", "round_even", "trunc"):
            if src.kind in ("i", "u") and k in ("ceil", "floor"):
                return d, v
            digits = int(e.params[0]) if e.params else 0
            mul = np.float64(10.0 ** digits)
            x = d.astype(jnp.float64) * mul
            if k == "ceil":
                r = jnp.ceil(d.astype(jnp.float64))
            elif k == "floor":
                r = jnp.floor(d.astype(jnp.float64))
            elif k == "round":     # SQL: half away from zero
                r = jnp.sign(x) * jnp.floor(jnp.abs(x) + 0.5) / mul
            elif k == "round_even":  # pandas/IEEE: half to even
                r = jnp.round(x) / mul
            else:                   # trunc: toward zero
                r = jnp.trunc(x) / mul
            if src.kind in ("i", "u"):
                return r.astype(src.numpy), v
            return r, v
        x = d.astype(jnp.float64)
        fns = {"sqrt": jnp.sqrt, "exp": jnp.exp, "ln": jnp.log,
               "log10": jnp.log10, "log2": jnp.log2, "sin": jnp.sin,
               "cos": jnp.cos, "tan": jnp.tan, "asin": jnp.arcsin,
               "acos": jnp.arccos, "atan": jnp.arctan,
               "degrees": jnp.degrees, "radians": jnp.radians}
        if k not in fns:
            raise ValueError(f"unknown math fn {k}")
        return fns[k](x), v
    if isinstance(e, MaskNull):
        c, cv = eval_expr(e.cond, tree, dicts, schema)
        d, v = eval_expr(e.operand, tree, dicts, schema)
        hit = c if cv is None else (c & cv)  # null cond does not mask
        valid = (~hit) if v is None else (v & ~hit)
        return d, valid
    if isinstance(e, CodeLUT):
        d, v = eval_expr(e.operand, tree, dicts, schema)
        lut = jnp.asarray(e.rank_lut())
        codes = lut[jnp.clip(d.astype(jnp.int32), 0, len(e.strings) - 1)]
        return codes, v
    if isinstance(e, DateTrunc):
        d, v = eval_expr(e.operand, tree, dicts, schema)
        src = infer_dtype(e.operand, schema)
        if src is dt.DATE:
            ns = d.astype(jnp.int64) * dtops.NS_PER_DAY
            out = dtops.trunc(e.unit, ns)
            return jnp.floor_divide(out, dtops.NS_PER_DAY
                                    ).astype(jnp.int32), v
        return dtops.trunc(e.unit, d), v
    if isinstance(e, DateAdd):
        d, v = eval_expr(e.operand, tree, dicts, schema)
        n, nv = eval_expr(e.amount, tree, dicts, schema)
        src = infer_dtype(e.operand, schema)
        out_dt = infer_dtype(e, schema)
        ns = d.astype(jnp.int64) * dtops.NS_PER_DAY if src is dt.DATE \
            else d.astype(jnp.int64)
        n = n.astype(jnp.int64)
        if e.unit in ("month", "quarter", "year"):
            mult = {"month": 1, "quarter": 3, "year": 12}[e.unit]
            out = dtops.add_months(ns, n * mult)
        else:
            step = {"week": dtops.NS_PER_DAY * 7, "day": dtops.NS_PER_DAY,
                    "hour": dtops.NS_PER_HOUR, "minute": dtops.NS_PER_MIN,
                    "second": dtops.NS_PER_SEC}[e.unit]
            out = ns + n * step
        if out_dt is dt.DATE:
            out = jnp.floor_divide(out, dtops.NS_PER_DAY).astype(jnp.int32)
        valid = None
        if v is not None or nv is not None:
            valid = (v if v is not None else jnp.ones(out.shape, bool)) & \
                    (nv if nv is not None else jnp.ones(out.shape, bool))
        return out, valid
    if isinstance(e, DateDiff):
        la, lv = eval_expr(e.left, tree, dicts, schema)
        ra, rv = eval_expr(e.right, tree, dicts, schema)
        lt = infer_dtype(e.left, schema)
        rt = infer_dtype(e.right, schema)
        lns = la.astype(jnp.int64) * dtops.NS_PER_DAY if lt is dt.DATE \
            else la.astype(jnp.int64)
        rns = ra.astype(jnp.int64) * dtops.NS_PER_DAY if rt is dt.DATE \
            else ra.astype(jnp.int64)
        u = e.unit
        if u == "year":
            out = dtops.year(rns) - dtops.year(lns)
        elif u == "quarter":
            out = (dtops.year(rns) * 4 + (dtops.quarter(rns) - 1)) - \
                  (dtops.year(lns) * 4 + (dtops.quarter(lns) - 1))
        elif u == "month":
            out = dtops.month_index(rns) - dtops.month_index(lns)
        elif u == "week":
            out = jnp.floor_divide(dtops.days_from_ns(rns) -
                                   dtops.dayofweek(rns), 7) - \
                jnp.floor_divide(dtops.days_from_ns(lns) -
                                 dtops.dayofweek(lns), 7)
        else:
            step = {"day": dtops.NS_PER_DAY, "hour": dtops.NS_PER_HOUR,
                    "minute": dtops.NS_PER_MIN, "second": dtops.NS_PER_SEC}[u]
            out = jnp.floor_divide(rns, step) - jnp.floor_divide(lns, step)
        valid = None
        if lv is not None or rv is not None:
            valid = (lv if lv is not None else jnp.ones(out.shape, bool)) & \
                    (rv if rv is not None else jnp.ones(out.shape, bool))
        return out.astype(jnp.int64), valid
    if isinstance(e, StrCodes):
        d, v = eval_expr(e.operand, tree, dicts, schema)
        codes = d.astype(jnp.int32)
        if v is not None:
            codes = jnp.where(v, codes, np.int32(-1))
        return codes, None
    if isinstance(e, (StrLen, StrHostFn)):
        col = e.operand
        transforms = []
        while isinstance(col, DictMap):
            transforms.append(col)
            col = col.operand
        base_codes = None
        if isinstance(col, CodeLUT):
            vals = list(col.sorted_dict())
            base_codes = eval_expr(col, tree, dicts, schema)
        elif isinstance(col, ColRef):
            dic = dicts.get(col.name)
            if dic is None:
                raise TypeError(f"column {col.name} has no dictionary")
            vals = list(dic)
            base_codes = tree[col.name]
        else:
            raise TypeError("string functions must apply to a string column")
        for tr in reversed(transforms):
            vals = [tr.apply_host(s) for s in vals]
        d, v = base_codes
        if isinstance(e, StrLen):
            lut = jnp.asarray(np.array([len(s) for s in vals] or [0],
                                       dtype=np.int64))
            return lut[jnp.clip(d, 0, len(vals) - 1 if vals else 0)], v
        pairs = [e.apply_host(s) for s in vals] or [(0, True)]
        out_np = np.asarray([p[0] for p in pairs])
        if e.kind == "to_number":
            out_np = out_np.astype(np.float64)
        elif e.kind == "to_date":
            out_np = out_np.astype(np.int32)
        else:
            out_np = out_np.astype(np.int64)
        lut = jnp.asarray(out_np)
        codes = jnp.clip(d, 0, len(vals) - 1 if vals else 0)
        out = lut[codes]
        ok = np.asarray([p[1] for p in pairs], dtype=bool)
        if not ok.all():
            okv = jnp.asarray(ok)[codes]
            v = okv if v is None else (v & okv)
        return out, v
    if isinstance(e, StrPredicate):
        col = e.operand
        transforms = []
        while isinstance(col, DictMap):  # compose host transforms
            transforms.append(col)
            col = col.operand
        if isinstance(col, CodeLUT):
            dic = list(col.sorted_dict())
            d, v = eval_expr(col, tree, dicts, schema)
        elif isinstance(col, ColRef):
            dic0 = dicts.get(col.name)
            if dic0 is None:
                raise TypeError(f"column {col.name} has no dictionary")
            dic = list(dic0)
            d, v = tree[col.name]
        else:
            raise TypeError("string predicates must apply to a column")
        if transforms:
            for tr in reversed(transforms):
                dic = [tr.apply_host(s) for s in dic]
        lut = np.zeros(max(len(dic), 1), dtype=bool)
        pats = [p for p in e.pattern]
        for i, s in enumerate(dic):
            if e.kind == "contains":
                lut[i] = pats[0] in s
            elif e.kind == "startswith":
                lut[i] = s.startswith(tuple(pats))
            elif e.kind == "endswith":
                lut[i] = s.endswith(tuple(pats))
            elif e.kind == "match":
                lut[i] = re.match(pats[0], s) is not None
            elif e.kind == "fullmatch":
                lut[i] = re.fullmatch(pats[0], s) is not None
            elif e.kind == "eq_any":
                lut[i] = s in pats
            elif e.kind == "lower_eq":
                lut[i] = s.lower() == pats[0]
            else:
                raise ValueError(f"unknown str predicate {e.kind}")
        res = jnp.asarray(lut)[jnp.clip(d, 0, len(dic) - 1)]
        return res, v
    if isinstance(e, Where):
        c, cv = eval_expr(e.cond, tree, dicts, schema)
        t, tv = eval_expr(e.iftrue, tree, dicts, schema)
        f, fv = eval_expr(e.iffalse, tree, dicts, schema)
        rdt = infer_dtype(e, schema)
        if rdt is dt.STRING:
            raise TypeError("string Where requires frontend dict rewrite")
        t = jnp.asarray(t).astype(rdt.numpy)
        f = jnp.asarray(f).astype(rdt.numpy)
        cond = c if cv is None else (c & cv)
        out = jnp.where(cond, t, f)
        valid = None
        if tv is not None or fv is not None:
            tvv = tv if tv is not None else jnp.ones(out.shape, bool)
            fvv = fv if fv is not None else jnp.ones(out.shape, bool)
            valid = jnp.where(cond, tvv, fvv)
        return out, valid
    if isinstance(e, BinOp):
        if e.op in ("&", "|"):
            ld, lv = eval_expr(e.left, tree, dicts, schema)
            rd, rv = eval_expr(e.right, tree, dicts, schema)
            # null-as-False three-valued logic collapse (filter semantics)
            if lv is not None:
                ld = ld & lv
            if rv is not None:
                rd = rd & rv
            return (ld & rd if e.op == "&" else ld | rd), None
        ld, lv = eval_expr(e.left, tree, dicts, schema)
        rd, rv = eval_expr(e.right, tree, dicts, schema)
        lt = infer_dtype(e.left, schema)
        rt = infer_dtype(e.right, schema)
        # DATE (days) vs DATETIME (ns) physical coercion
        if lt is dt.DATE and rt is dt.DATETIME:
            ld = ld.astype(jnp.int64) * dtops.NS_PER_DAY
        elif lt is dt.DATETIME and rt is dt.DATE:
            rd = rd.astype(jnp.int64) * dtops.NS_PER_DAY
        if lt is dt.STRING or rt is dt.STRING:
            raise TypeError(
                "string comparison must be rewritten to dict codes by the "
                "frontend (StrPredicate / code-space compare)")
        # decimal fixed-point coercion (scaled int64, exact where possible)
        if dt.is_decimal(lt) or dt.is_decimal(rt):
            ls = lt.scale if dt.is_decimal(lt) else None
            rs = rt.scale if dt.is_decimal(rt) else None
            float_side = (ls is None and lt.kind == "f") or \
                (rs is None and rt.kind == "f")
            if float_side or e.op == "/":
                # mixed float / division: leave fixed point
                ld = ld.astype(jnp.float64) / (10.0 ** ls) \
                    if ls is not None else ld.astype(jnp.float64)
                rd = rd.astype(jnp.float64) / (10.0 ** rs) \
                    if rs is not None else rd.astype(jnp.float64)
            elif e.op == "*":
                # dec(sa)·dec(sb) → dec(sa+sb): plain int64 product;
                # int sides carry scale 0
                ld = ld.astype(jnp.int64)
                rd = rd.astype(jnp.int64)
            else:
                # +,-,cmp: align both sides to the larger scale exactly
                s = max(ls or 0, rs or 0)
                ld = ld.astype(jnp.int64) * np.int64(10 ** (s - (ls or 0)))
                rd = rd.astype(jnp.int64) * np.int64(10 ** (s - (rs or 0)))
        valid = None
        if lv is not None or rv is not None:
            valid = (lv if lv is not None else jnp.ones(ld.shape, bool)) & \
                    (rv if rv is not None else jnp.ones(rd.shape, bool))
        if e.op in _CMP:
            return _CMP[e.op](ld, rd), valid
        if e.op == "max2":   # GREATEST/LEAST (null if either side null)
            return jnp.maximum(ld, rd), valid
        if e.op == "min2":
            return jnp.minimum(ld, rd), valid
        if e.op == "+":
            return ld + rd, valid
        if e.op == "-":
            return ld - rd, valid
        if e.op == "*":
            return ld * rd, valid
        if e.op == "/":
            rdt = infer_dtype(e, schema)
            return ld.astype(rdt.numpy) / rd.astype(rdt.numpy), valid
        if e.op == "//":
            return jnp.floor_divide(ld, jnp.where(rd == 0, 1, rd)), valid
        if e.op == "%":
            return jnp.mod(ld, jnp.where(rd == 0, 1, rd)), valid
        if e.op == "**":
            return jnp.power(ld, rd), valid
        raise ValueError(f"unknown binop {e.op}")
    raise TypeError(f"cannot evaluate {e}")
