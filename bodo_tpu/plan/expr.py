"""Hashable expression IR + JAX evaluator.

Analogue of the reference's expression nodes (bodo/pandas/plan.py:560-760
ColRefExpression/ArithOpExpression/ComparisonOpExpression/...). Being
frozen dataclasses, expressions are hashable and serve directly as jit
cache keys, so each distinct expression tree compiles exactly once.

String predicates evaluate against the host-side dictionary (tiny) and
become a boolean lookup-table gather on device — the dict-encoding trick
the reference uses for string-heavy workloads (bodo/libs/dict_arr_ext.py).
Null semantics follow SQL/pandas-float behavior: arithmetic propagates
nulls; comparisons with null produce null, and filters treat null as
False.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from bodo_tpu.ops import datetime as dtops
from bodo_tpu.table import dtypes as dt


class Expr:
    """Base class; all subclasses are frozen/hashable."""

    # -- operator sugar used by the frontend --------------------------------
    def _bin(self, op, other, reverse=False):
        o = other if isinstance(other, Expr) else Lit(other)
        return BinOp(op, o, self) if reverse else BinOp(op, self, o)

    def __add__(self, o): return self._bin("+", o)
    def __radd__(self, o): return self._bin("+", o, True)
    def __sub__(self, o): return self._bin("-", o)
    def __rsub__(self, o): return self._bin("-", o, True)
    def __mul__(self, o): return self._bin("*", o)
    def __rmul__(self, o): return self._bin("*", o, True)
    def __truediv__(self, o): return self._bin("/", o)
    def __rtruediv__(self, o): return self._bin("/", o, True)
    def __floordiv__(self, o): return self._bin("//", o)
    def __mod__(self, o): return self._bin("%", o)
    def __pow__(self, o): return self._bin("**", o)
    def __eq__(self, o): return self._bin("==", o)  # type: ignore[override]
    def __ne__(self, o): return self._bin("!=", o)  # type: ignore[override]
    def __lt__(self, o): return self._bin("<", o)
    def __le__(self, o): return self._bin("<=", o)
    def __gt__(self, o): return self._bin(">", o)
    def __ge__(self, o): return self._bin(">=", o)
    def __and__(self, o): return self._bin("&", o)
    def __rand__(self, o): return self._bin("&", o, True)
    def __or__(self, o): return self._bin("|", o)
    def __ror__(self, o): return self._bin("|", o, True)
    def __invert__(self): return UnOp("~", self)
    def __neg__(self): return UnOp("neg", self)
    def __abs__(self): return UnOp("abs", self)
    def key(self):
        """Structural cache key (expressions can't be dict keys directly:
        __eq__ is overloaded as the comparison *builder*)."""
        raise NotImplementedError

    def isin(self, values): return IsIn(self, tuple(values))
    def isna(self): return UnOp("isna", self)
    def notna(self): return UnOp("notna", self)
    def fillna(self, v): return Where(UnOp("isna", self), Lit(v), self)
    def astype(self, dtype): return Cast(self, dt.from_numpy(np.dtype(dtype)))


def _frozen(cls):
    return dataclass(frozen=True, eq=False, repr=True)(cls)


@_frozen
class ColRef(Expr):
    name: str
    def key(self): return ("col", self.name)


@_frozen
class Lit(Expr):
    value: Any
    def key(self): return ("lit", str(type(self.value).__name__), self.value)


@_frozen
class BinOp(Expr):
    op: str
    left: Expr
    right: Expr
    def key(self): return ("bin", self.op, self.left.key(), self.right.key())


@_frozen
class UnOp(Expr):
    op: str
    operand: Expr
    def key(self): return ("un", self.op, self.operand.key())


@_frozen
class Cast(Expr):
    operand: Expr
    to: dt.DType
    def key(self): return ("cast", self.operand.key(), self.to.name)


@_frozen
class DtField(Expr):
    field: str
    operand: Expr
    def key(self): return ("dtf", self.field, self.operand.key())


@_frozen
class IsIn(Expr):
    operand: Expr
    values: Tuple
    def key(self): return ("isin", self.operand.key(), self.values)


@_frozen
class Where(Expr):
    cond: Expr
    iftrue: Expr
    iffalse: Expr
    def key(self):
        return ("where", self.cond.key(), self.iftrue.key(), self.iffalse.key())


@_frozen
class RowUDF(Expr):
    """Compiled element-wise Python UDF (df.apply(axis=1) / Series.map).

    The callable is traced with jax.vmap over per-row scalars — the
    trace-to-XLA analogue of the reference compiling UDFs with a nested
    Numba pipeline (BodoCompilerUDF, bodo/compiler.py:705). String columns
    are withheld from the row namespace (dict codes would silently change
    semantics); a UDF touching one raises KeyError at trace time and the
    frontend falls back to pandas.
    """
    func: Any          # callable(Row) -> scalar, or callable(x) in scalar mode
    out_dtype: Any     # DType or None (trace default float64)
    operand: Any = None  # Expr → scalar mode (Series.map); None → row mode
    def key(self):
        return ("rowudf", _udf_serial(self.func),
                self.out_dtype.name if self.out_dtype else None,
                self.operand.key() if self.operand is not None else None)


_UDF_COUNTER = [0]
_UDF_SERIALS: Dict[int, Tuple] = {}  # id -> (weakref, serial)


def _udf_serial(func) -> int:
    """Stable serial per live callable — id() alone is unsafe as cache key
    (CPython reuses ids after GC; same guard as relational._dict_fp)."""
    s = getattr(func, "__bodo_tpu_udf_serial__", None)
    if s is not None:
        return s
    _UDF_COUNTER[0] += 1
    serial = _UDF_COUNTER[0]
    try:
        func.__bodo_tpu_udf_serial__ = serial
    except (AttributeError, TypeError):
        import weakref
        ent = _UDF_SERIALS.get(id(func))
        if ent is not None and ent[0]() is func:
            return ent[1]
        key = id(func)
        try:
            wr = weakref.ref(func, lambda _: _UDF_SERIALS.pop(key, None))
        except TypeError:
            wr = lambda: func  # not weakref-able: pin via closure
        _UDF_SERIALS[key] = (wr, serial)
    return serial


class _RowNS:
    """Attribute/item access over a dict of per-row scalar tracers;
    records which columns the UDF actually reads (for null propagation)."""
    __slots__ = ("_d", "_touched")

    def __init__(self, d, touched=None):
        object.__setattr__(self, "_d", d)
        object.__setattr__(self, "_touched", touched)

    def __getattr__(self, n):
        try:
            v = self._d[n]
        except KeyError:
            raise AttributeError(n)
        if self._touched is not None:
            self._touched.add(n)
        return v

    def __getitem__(self, n):
        if self._touched is not None and n in self._d:
            self._touched.add(n)
        return self._d[n]


@_frozen
class DictMap(Expr):
    """String→string transform applied to the host dictionary (substring,
    upper, lower): the device only remaps int32 codes through a host-built
    translation table — strings never reach the device (same trick as the
    reference's dict-encoded string kernels, bodo/libs/dict_arr_ext.py).
    Must sit at the top level of a projection (relational.assign_columns
    attaches the new dictionary host-side)."""
    kind: str          # substring | upper | lower | strip | replace | ...
    params: Tuple
    operand: Expr      # must reference a string column
    def key(self):
        return ("dictmap", self.kind, self.params, self.operand.key())

    def apply_host(self, s: str) -> str:
        if self.kind == "substring":
            start, length = self.params
            i = start - 1  # SQL is 1-based
            return s[i:i + length] if length is not None else s[i:]
        if self.kind == "slice":  # pandas .str.slice — 0-based, stop excl
            start, stop = self.params
            return s[start:stop]
        if self.kind == "upper":
            return s.upper()
        if self.kind == "lower":
            return s.lower()
        if self.kind == "strip":
            return s.strip(*self.params)
        if self.kind == "lstrip":
            return s.lstrip(*self.params)
        if self.kind == "rstrip":
            return s.rstrip(*self.params)
        if self.kind == "replace":
            old, new = self.params
            return s.replace(old, new)
        if self.kind == "title":
            return s.title()
        if self.kind == "capitalize":
            return s.capitalize()
        if self.kind == "zfill":
            return s.zfill(self.params[0])
        raise ValueError(self.kind)


@_frozen
class StrLen(Expr):
    """Per-row string length via a host dictionary LUT → device int32
    gather (same dict-encoded trick as StrPredicate; reference:
    bodo/libs/dict_arr_ext.py str_len kernel)."""
    operand: Expr

    def key(self):
        return ("strlen", self.operand.key())


@_frozen
class StrPredicate(Expr):
    """String predicate evaluated on the host dictionary → device LUT.
    kind: contains | startswith | endswith | match | eq_any | lower_eq"""
    kind: str
    pattern: Tuple
    operand: Expr
    def key(self):
        return ("strp", self.kind, self.pattern, self.operand.key())


# ---------------------------------------------------------------------------
# schema-level type inference (host side)
# ---------------------------------------------------------------------------

def infer_dtype(e: Expr, schema: Dict[str, dt.DType]) -> dt.DType:
    if isinstance(e, ColRef):
        return schema[e.name]
    if isinstance(e, Lit):
        v = e.value
        if isinstance(v, bool):
            return dt.BOOL
        if isinstance(v, (int, np.integer)):
            return dt.INT64
        if isinstance(v, (float, np.floating)):
            return dt.FLOAT64
        if isinstance(v, str):
            return dt.STRING
        if isinstance(v, (np.datetime64,)):
            return dt.DATETIME
        import datetime as _dtmod
        if isinstance(v, _dtmod.date) and not isinstance(v, _dtmod.datetime):
            return dt.DATE
        raise TypeError(f"unsupported literal: {v!r}")
    if isinstance(e, Cast):
        return e.to
    if isinstance(e, DtField):
        return dt.DATE if e.field == "date" else dt.INT64
    if isinstance(e, (IsIn, StrPredicate)):
        return dt.BOOL
    if isinstance(e, DictMap):
        return dt.STRING
    if isinstance(e, StrLen):
        return dt.INT64
    if isinstance(e, RowUDF):
        if e.out_dtype is not None:
            return e.out_dtype
        return dt.FLOAT64
    if isinstance(e, UnOp):
        if e.op in ("isna", "notna", "~"):
            return dt.BOOL
        return infer_dtype(e.operand, schema)
    if isinstance(e, Where):
        t = infer_dtype(e.iftrue, schema)
        f = infer_dtype(e.iffalse, schema)
        if t is f:
            return t
        if dt.is_numeric(t) and dt.is_numeric(f):
            return dt.common_numeric(t, f)
        return t
    if isinstance(e, BinOp):
        if e.op in ("==", "!=", "<", "<=", ">", ">=", "&", "|"):
            return dt.BOOL
        lt = infer_dtype(e.left, schema)
        rt = infer_dtype(e.right, schema)
        if dt.is_decimal(lt) or dt.is_decimal(rt):
            ls = lt.scale if dt.is_decimal(lt) else None
            rs = rt.scale if dt.is_decimal(rt) else None
            float_side = (ls is None and lt.kind == "f") or \
                (rs is None and rt.kind == "f")
            if float_side or e.op == "/":
                return dt.FLOAT64
            if e.op == "*":
                return dt.decimal((ls or 0) + (rs or 0))
            return dt.decimal(max(ls or 0, rs or 0))
        if e.op == "/":
            return dt.FLOAT64 if lt.numpy.itemsize == 8 or rt.numpy.itemsize == 8 \
                else dt.FLOAT32
        if dt.is_numeric(lt) and dt.is_numeric(rt):
            return dt.common_numeric(lt, rt)
        return lt
    raise TypeError(f"cannot infer dtype of {e}")


def expr_columns(e: Expr) -> set:
    """Free column references (for projection pushdown)."""
    if isinstance(e, ColRef):
        return {e.name}
    if isinstance(e, Lit):
        return set()
    if isinstance(e, BinOp):
        return expr_columns(e.left) | expr_columns(e.right)
    if isinstance(e, RowUDF):
        if e.operand is not None:
            return expr_columns(e.operand)
        return {"*"}  # may touch any column — disables pruning above it
    if isinstance(e, (UnOp, Cast, DtField, IsIn, StrPredicate, DictMap,
                      StrLen)):
        return expr_columns(e.operand)
    if isinstance(e, Where):
        return (expr_columns(e.cond) | expr_columns(e.iftrue)
                | expr_columns(e.iffalse))
    return set()


# ---------------------------------------------------------------------------
# evaluation (device side, traced)
# ---------------------------------------------------------------------------

_CMP = {"==": jnp.equal, "!=": jnp.not_equal, "<": jnp.less,
        "<=": jnp.less_equal, ">": jnp.greater, ">=": jnp.greater_equal}


def eval_expr(e: Expr, tree: Dict[str, Tuple], dicts: Dict[str, np.ndarray],
              schema: Dict[str, dt.DType]):
    """Evaluate to (data, valid_or_None). `tree` maps column name to
    (data, valid); `dicts` holds host dictionaries for string columns."""
    if isinstance(e, ColRef):
        return tree[e.name]
    if isinstance(e, Lit):
        v = e.value
        if isinstance(v, str):
            raise TypeError(
                "string literal outside a string predicate — wrap string "
                "comparisons in StrPredicate (frontend does this)")
        if isinstance(v, np.datetime64):
            # match the DATETIME physical repr (int64 ns ticks)
            return jnp.asarray(np.int64(v.astype("datetime64[ns]")
                                        .astype(np.int64))), None
        import datetime as _dtmod
        if isinstance(v, _dtmod.date) and not isinstance(v, _dtmod.datetime):
            # match the DATE physical repr (int32 days since epoch)
            return jnp.asarray(np.int32(
                (np.datetime64(v, "D") - np.datetime64(0, "D"))
                .astype(np.int32))), None
        return jnp.asarray(v), None
    if isinstance(e, Cast):
        d, v = eval_expr(e.operand, tree, dicts, schema)
        src = infer_dtype(e.operand, schema)
        if e.to is dt.STRING:
            raise TypeError("cast to string not supported on device")
        if src.kind == "f" and e.to.kind in ("i", "u"):
            nan = jnp.isnan(d)
            v = (~nan) if v is None else (v & ~nan)
            d = jnp.where(nan, 0, d)
        return d.astype(e.to.numpy), v
    if isinstance(e, DtField):
        d, v = eval_expr(e.operand, tree, dicts, schema)
        if infer_dtype(e.operand, schema) is dt.DATE:
            # DATE stores days; field kernels expect ns ticks
            d = d.astype(jnp.int64) * dtops.NS_PER_DAY
        return dtops.FIELDS[e.field](d), v
    if isinstance(e, UnOp):
        if e.op in ("isna", "notna"):
            d, v = eval_expr(e.operand, tree, dicts, schema)
            isna = jnp.zeros(d.shape, dtype=bool)
            if v is not None:
                isna = ~v
            if jnp.issubdtype(d.dtype, jnp.floating):
                isna = isna | jnp.isnan(d)
            return (isna if e.op == "isna" else ~isna), None
        d, v = eval_expr(e.operand, tree, dicts, schema)
        if e.op == "~":
            return jnp.logical_not(d), v
        if e.op == "neg":
            return jnp.negative(d), v
        if e.op == "abs":
            return jnp.abs(d), v
        raise ValueError(f"unknown unop {e.op}")
    if isinstance(e, IsIn):
        d, v = eval_expr(e.operand, tree, dicts, schema)
        src = infer_dtype(e.operand, schema)
        if src is dt.STRING:
            return eval_expr(StrPredicate("eq_any", tuple(e.values),
                                          e.operand), tree, dicts, schema)
        acc = jnp.zeros(d.shape, dtype=bool)
        for val in e.values:
            acc = acc | (d == val)
        return acc, v
    if isinstance(e, RowUDF):
        import jax
        if e.operand is not None:  # scalar mode (Series.map)
            d, v = eval_expr(e.operand, tree, dicts, schema)
            out = jax.vmap(e.func)(d)
            if e.out_dtype is not None:
                out = out.astype(e.out_dtype.numpy)
            return out, v
        # row mode: withhold string/temporal columns — their physical repr
        # (dict codes, int ticks) would silently change meaning; a UDF
        # touching one fails the trace → frontend falls back to pandas
        numeric = {n: d for n, (d, v) in tree.items()
                   if schema.get(n) is not None
                   and schema[n].kind in ("i", "u", "f", "b")}
        # discover which columns the UDF reads (abstract pre-trace), so
        # null masks propagate only from consumed columns
        touched: set = set()
        jax.eval_shape(
            lambda row: e.func(_RowNS(row, touched)),
            {n: jax.ShapeDtypeStruct((), d.dtype) for n, d in numeric.items()})

        def one_row(row_vals):
            return e.func(_RowNS(row_vals))
        out = jax.vmap(one_row)(numeric)
        if e.out_dtype is not None:
            out = out.astype(e.out_dtype.numpy)
        valid = None
        for n in sorted(touched):
            v = tree[n][1]
            if v is not None:
                valid = v if valid is None else (valid & v)
        return out, valid
    if isinstance(e, StrLen):
        col = e.operand
        transforms = []
        while isinstance(col, DictMap):
            transforms.append(col)
            col = col.operand
        if not isinstance(col, ColRef):
            raise TypeError("str.len must apply to a string column")
        dic = dicts.get(col.name)
        if dic is None:
            raise TypeError(f"column {col.name} has no dictionary")
        vals = list(dic)
        for tr in reversed(transforms):
            vals = [tr.apply_host(s) for s in vals]
        lut = jnp.asarray(np.array([len(s) for s in vals] or [0],
                                   dtype=np.int64))
        d, v = eval_expr(col, tree, dicts, schema)
        return lut[jnp.clip(d, 0, len(vals) - 1 if vals else 0)], v
    if isinstance(e, StrPredicate):
        col = e.operand
        transforms = []
        while isinstance(col, DictMap):  # compose host transforms
            transforms.append(col)
            col = col.operand
        if not isinstance(col, ColRef):
            raise TypeError("string predicates must apply to a column")
        dic = dicts.get(col.name)
        if dic is None:
            raise TypeError(f"column {col.name} has no dictionary")
        if transforms:
            for tr in reversed(transforms):
                dic = [tr.apply_host(s) for s in dic]
        lut = np.zeros(max(len(dic), 1), dtype=bool)
        pats = [p for p in e.pattern]
        for i, s in enumerate(dic):
            if e.kind == "contains":
                lut[i] = pats[0] in s
            elif e.kind == "startswith":
                lut[i] = s.startswith(tuple(pats))
            elif e.kind == "endswith":
                lut[i] = s.endswith(tuple(pats))
            elif e.kind == "match":
                lut[i] = re.match(pats[0], s) is not None
            elif e.kind == "eq_any":
                lut[i] = s in pats
            elif e.kind == "lower_eq":
                lut[i] = s.lower() == pats[0]
            else:
                raise ValueError(f"unknown str predicate {e.kind}")
        d, v = tree[col.name]
        res = jnp.asarray(lut)[jnp.clip(d, 0, len(dic) - 1)]
        return res, v
    if isinstance(e, Where):
        c, cv = eval_expr(e.cond, tree, dicts, schema)
        t, tv = eval_expr(e.iftrue, tree, dicts, schema)
        f, fv = eval_expr(e.iffalse, tree, dicts, schema)
        rdt = infer_dtype(e, schema)
        if rdt is dt.STRING:
            raise TypeError("string Where requires frontend dict rewrite")
        t = jnp.asarray(t).astype(rdt.numpy)
        f = jnp.asarray(f).astype(rdt.numpy)
        cond = c if cv is None else (c & cv)
        out = jnp.where(cond, t, f)
        valid = None
        if tv is not None or fv is not None:
            tvv = tv if tv is not None else jnp.ones(out.shape, bool)
            fvv = fv if fv is not None else jnp.ones(out.shape, bool)
            valid = jnp.where(cond, tvv, fvv)
        return out, valid
    if isinstance(e, BinOp):
        if e.op in ("&", "|"):
            ld, lv = eval_expr(e.left, tree, dicts, schema)
            rd, rv = eval_expr(e.right, tree, dicts, schema)
            # null-as-False three-valued logic collapse (filter semantics)
            if lv is not None:
                ld = ld & lv
            if rv is not None:
                rd = rd & rv
            return (ld & rd if e.op == "&" else ld | rd), None
        ld, lv = eval_expr(e.left, tree, dicts, schema)
        rd, rv = eval_expr(e.right, tree, dicts, schema)
        lt = infer_dtype(e.left, schema)
        rt = infer_dtype(e.right, schema)
        # DATE (days) vs DATETIME (ns) physical coercion
        if lt is dt.DATE and rt is dt.DATETIME:
            ld = ld.astype(jnp.int64) * dtops.NS_PER_DAY
        elif lt is dt.DATETIME and rt is dt.DATE:
            rd = rd.astype(jnp.int64) * dtops.NS_PER_DAY
        if lt is dt.STRING or rt is dt.STRING:
            raise TypeError(
                "string comparison must be rewritten to dict codes by the "
                "frontend (StrPredicate / code-space compare)")
        # decimal fixed-point coercion (scaled int64, exact where possible)
        if dt.is_decimal(lt) or dt.is_decimal(rt):
            ls = lt.scale if dt.is_decimal(lt) else None
            rs = rt.scale if dt.is_decimal(rt) else None
            float_side = (ls is None and lt.kind == "f") or \
                (rs is None and rt.kind == "f")
            if float_side or e.op == "/":
                # mixed float / division: leave fixed point
                ld = ld.astype(jnp.float64) / (10.0 ** ls) \
                    if ls is not None else ld.astype(jnp.float64)
                rd = rd.astype(jnp.float64) / (10.0 ** rs) \
                    if rs is not None else rd.astype(jnp.float64)
            elif e.op == "*":
                # dec(sa)·dec(sb) → dec(sa+sb): plain int64 product;
                # int sides carry scale 0
                ld = ld.astype(jnp.int64)
                rd = rd.astype(jnp.int64)
            else:
                # +,-,cmp: align both sides to the larger scale exactly
                s = max(ls or 0, rs or 0)
                ld = ld.astype(jnp.int64) * np.int64(10 ** (s - (ls or 0)))
                rd = rd.astype(jnp.int64) * np.int64(10 ** (s - (rs or 0)))
        valid = None
        if lv is not None or rv is not None:
            valid = (lv if lv is not None else jnp.ones(ld.shape, bool)) & \
                    (rv if rv is not None else jnp.ones(rd.shape, bool))
        if e.op in _CMP:
            return _CMP[e.op](ld, rd), valid
        if e.op == "+":
            return ld + rd, valid
        if e.op == "-":
            return ld - rd, valid
        if e.op == "*":
            return ld * rd, valid
        if e.op == "/":
            rdt = infer_dtype(e, schema)
            return ld.astype(rdt.numpy) / rd.astype(rdt.numpy), valid
        if e.op == "//":
            return jnp.floor_divide(ld, jnp.where(rd == 0, 1, rd)), valid
        if e.op == "%":
            return jnp.mod(ld, jnp.where(rd == 0, 1, rd)), valid
        if e.op == "**":
            return jnp.power(ld, rd), valid
        raise ValueError(f"unknown binop {e.op}")
    raise TypeError(f"cannot evaluate {e}")
