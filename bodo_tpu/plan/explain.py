"""EXPLAIN ANALYZE: the executed plan tree annotated with observations.

The plan validator knows what a plan SHOULD do and the optimizer what it
WILL do; this module records what it DID: per plan node, the observed
row count vs the estimator's pre-execution guess, result device bytes,
inclusive wall seconds, cache hits, and any adaptive-execution decisions
that fired while the node ran (Postgres' EXPLAIN ANALYZE crossed with
Spark AQE's final-plan annotations).

`physical.execute` assigns every node a stable dotted path ("0", "0.1",
"0.1.0" …) at the start of each query and `record()`s an observation per
node as it completes; AQE-replanned join subtrees get paths re-anchored
under the node they replaced, flagged `replanned`. Observations are
keyed by query id (tracing.query_span) and kept for the last
`_MAX_QUERIES` queries, so `explain_analyze()` after a run renders the
tree of any recent query — `bench.py --explain` and
`BodoDataFrame.explain_analyze()` are thin wrappers over it.

Recording is active only while tracing is on (BODO_TPU_TRACING_LEVEL
>= 1); with tracing off the executor's hot path skips this module
entirely.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, List, Optional

from bodo_tpu.plan import logical as L

_lock = threading.Lock()
_MAX_QUERIES = 64
# qid -> {"root": Node, "records": {path: record}}
_queries: "OrderedDict[str, dict]" = OrderedDict()
_last_qid: Optional[str] = None


def _qid() -> str:
    from bodo_tpu.utils import tracing
    return tracing.current_query_id() or "-"


def begin_query(root: L.Node, query_id: Optional[str] = None,
                session: Optional[str] = None) -> None:
    """Anchor a query: assign dotted paths over the (optimized) tree and
    open its record store. Called by physical.execute when tracing is
    on. Shared subplans (the optimizer memoizes by key) keep the first
    path they get — later parents see them as cache hits anyway.
    ``session`` tags the query with the serving session that issued it
    (rendered in the EXPLAIN ANALYZE header, carried by slow_queries)."""
    global _last_qid
    qid = query_id or _qid()
    assign_paths(root, "0", force=True)
    with _lock:
        q = _queries.get(qid)
        if q is None:
            q = _queries[qid] = {"root": root, "records": {}}
            while len(_queries) > _MAX_QUERIES:
                _queries.popitem(last=False)
        else:
            q["root"] = root
        if session:
            q["session"] = session
        _last_qid = qid


def query_session(query_id: Optional[str] = None) -> Optional[str]:
    """Serving session a recorded query was tagged with, if any."""
    with _lock:
        qid = query_id or _last_qid
        q = _queries.get(qid) if qid else None
        return q.get("session") if q else None


def assign_paths(node: L.Node, base: str, force: bool = False,
                 replanned: bool = False) -> None:
    """Depth-first dotted-path assignment. `force` overwrites paths
    left over from a previous query's tree walk (plan nodes are reused
    across executions via the session result cache); `replanned` marks
    an AQE-substituted subtree."""
    seen = set()

    def walk(n: L.Node, path: str) -> None:
        if id(n) in seen:
            return
        seen.add(id(n))
        if force or getattr(n, "_explain_path", None) is None:
            n._explain_path = path
            n._explain_replanned = replanned
        for i, c in enumerate(n.children):
            walk(c, f"{path}.{i}")

    walk(node, base)


def record(node: L.Node, *, rows: int, wall_s: float,
           est_rows: Optional[float] = None,
           bytes: Optional[int] = None, cached: bool = False,
           aqe: Optional[Dict[str, int]] = None,
           mem_peak: Optional[int] = None,
           fusion: Optional[dict] = None,
           comm: Optional[dict] = None,
           xla: Optional[dict] = None,
           rcache: Optional[dict] = None) -> None:
    """One node observation for the current query. Wall seconds are
    INCLUSIVE of the node's children (the executor recurses inside the
    node's span), matching Postgres' actual-time convention. A repeat
    record for the same path keeps the first full execution and only
    bumps its hit count (memoized subplan re-reached). `fusion` carries
    the whole-stage-fusion boundary annotation: for a group root, the
    member ops / compile seconds / cache hit / rows in+out; for an
    interior member, the root path it fused into. `comm` carries the
    comm-observatory delta across the node's execution
    ({wall_s, wait_s, bytes} — inclusive, like wall_s), rendering the
    per-node comm-wait vs compute split. `xla` carries the compile &
    device-memory observatory's delta across the node ({compiles,
    retraces, cause, dev_bytes}) rendered as
    compiled|cached|retraced[cause] plus the node's net device bytes."""
    path = getattr(node, "_explain_path", None)
    if path is None:
        return
    qid = _qid()
    rec = {"path": path, "op": type(node).__name__, "rows": int(rows),
           "wall_s": float(wall_s), "cached": bool(cached), "hits": 1}
    if est_rows is not None:
        rec["est_rows"] = int(est_rows)
    if bytes is not None:
        rec["bytes"] = int(bytes)
    if mem_peak is not None:
        rec["mem_peak"] = int(mem_peak)
    if aqe:
        rec["aqe"] = dict(aqe)
    if fusion:
        rec["fusion"] = dict(fusion)
    if comm:
        rec["comm"] = {k: (round(float(v), 6)
                           if k.endswith("_s") else int(v))
                       for k, v in comm.items()}
    if xla:
        rec["xla"] = dict(xla)
    if rcache:
        rec["rcache"] = dict(rcache)
    if getattr(node, "_explain_replanned", False):
        rec["replanned"] = True
    with _lock:
        q = _queries.get(qid)
        if q is None:
            q = _queries[qid] = {"root": None, "records": {}}
            while len(_queries) > _MAX_QUERIES:
                _queries.popitem(last=False)
        prev = q["records"].get(path)
        if prev is not None and not prev["cached"]:
            prev["hits"] += 1
            # a later record may carry boundary info the first lacked
            # (physical._record_node re-records a fused root with the
            # group annotation attached to the node)
            if fusion and "fusion" not in prev:
                prev["fusion"] = dict(fusion)
            if xla and "xla" not in prev:
                prev["xla"] = dict(xla)
            if rcache and "rcache" not in prev:
                prev["rcache"] = dict(rcache)
            return
        if prev is not None:
            rec["hits"] = prev["hits"] + 1
        q["records"][path] = rec


def _critical_paths(records: Dict[str, dict]) -> set:
    """The dotted paths on the wall-dominant root-to-leaf chain: start
    at the root and descend into the recorded child with the largest
    inclusive wall at every level. With inclusive walls this IS the
    chain that bounds query wall — shaving time anywhere off-chain
    cannot shorten the query. Ties break toward the lowest path index
    (deterministic goldens)."""
    if not records:
        return set()
    root = min(records, key=_pathkey)
    marked = set()
    cur = root
    while True:
        marked.add(cur)
        depth = cur.count(".") + 1
        kids = [p for p in records
                if p.startswith(cur + ".") and p.count(".") == depth]
        if not kids:
            return marked
        cur = max(kids, key=lambda p: (
            records[p]["wall_s"],
            tuple(-x for x in _pathkey(p))))


def critical_path(query_id: Optional[str] = None) -> List[str]:
    """Dotted paths of the query's critical chain, root first."""
    with _lock:
        qid = query_id or _last_qid
        q = _queries.get(qid) if qid else None
        records = dict(q["records"]) if q else {}
    return sorted(_critical_paths(records), key=_pathkey)


def node_profiles(query_id: Optional[str] = None) -> List[dict]:
    """The recorded observations for one query (default: last), in
    dotted-path order — the JSON-able form bench artifacts embed. Nodes
    on the wall-dominant chain carry ``critical: True``."""
    with _lock:
        qid = query_id or _last_qid
        q = _queries.get(qid) if qid else None
        if q is None:
            return []
        recs = [dict(r) for r in q["records"].values()]
    crit = _critical_paths({r["path"]: r for r in recs})
    for r in recs:
        if r["path"] in crit:
            r["critical"] = True
    recs.sort(key=lambda r: _pathkey(r["path"]))
    return recs


def last_query_id() -> Optional[str]:
    with _lock:
        return _last_qid


def slow_queries(n: int = 5) -> List[dict]:
    """The slowest-N recorded queries, each with its wall seconds and
    rendered EXPLAIN ANALYZE tree — the flight recorder embeds these so
    a post-mortem shows what the engine was busy with before it died.
    Wall time prefers the query span; a query recorded without a span
    falls back to its slowest (inclusive) node observation."""
    from bodo_tpu.utils import tracing
    with _lock:
        qids = list(_queries.keys())
    scored = []
    for qid in qids:
        wall = tracing.query_wall_s(qid)
        if wall is None:
            with _lock:
                q = _queries.get(qid)
                recs = list(q["records"].values()) if q else []
            wall = max((r["wall_s"] for r in recs), default=0.0)
        scored.append((float(wall), qid))
    scored.sort(key=lambda t: -t[0])
    out = []
    for wall, qid in scored[:max(0, int(n))]:
        row = {"query_id": qid, "wall_s": round(wall, 6),
               "explain": explain_analyze(qid)}
        sid = query_session(qid)
        if sid:
            row["session"] = sid
        out.append(row)
    return out


def reset() -> None:
    global _last_qid
    with _lock:
        _queries.clear()
        _last_qid = None


def _pathkey(path: str):
    return tuple(int(p) for p in path.split("."))


def _fmt_bytes(n: int) -> str:
    v = float(n)
    for unit in ("B", "KB", "MB", "GB"):
        if v < 1024 or unit == "GB":
            return f"{v:.1f}{unit}" if unit != "B" else f"{int(v)}B"
        v /= 1024
    return f"{v:.1f}GB"  # pragma: no cover


def _node_label(node: L.Node) -> str:
    if isinstance(node, L.ReadParquet):
        return f"ReadParquet({node.path})"
    if isinstance(node, L.ReadCsv):
        return f"ReadCsv({node.path})"
    if isinstance(node, L.Join):
        return f"Join({node.how}, on={list(node.left_on)})"
    if isinstance(node, L.Aggregate):
        return f"Aggregate(keys={list(node.keys)})"
    if isinstance(node, L.Filter):
        return "Filter"
    if isinstance(node, L.Sort):
        return f"Sort(by={list(node.by)})"
    if isinstance(node, L.Limit):
        return f"Limit({node.n})"
    return type(node).__name__


def _annotate(rec: Optional[dict]) -> str:
    if rec is None:
        return "(not executed)"
    parts = [f"rows={rec['rows']}"]
    if "est_rows" in rec:
        parts.append(f"est={rec['est_rows']}")
    if "bytes" in rec:
        parts.append(f"bytes={_fmt_bytes(rec['bytes'])}")
    if "mem_peak" in rec:
        parts.append(f"mem_peak={_fmt_bytes(rec['mem_peak'])}")
    parts.append(f"wall={rec['wall_s']:.3f}s")
    c = rec.get("comm")
    if c:
        # comm-wait vs compute split: comm wall (transfer+wait) out of
        # the node's inclusive wall, with the peer-wait share inside it
        compute = max(rec["wall_s"] - c.get("wall_s", 0.0), 0.0)
        bit = (f"comm={c.get('wall_s', 0.0):.3f}s"
               f"/compute={compute:.3f}s")
        if c.get("wait_s"):
            bit += f" (wait={c['wait_s']:.3f}s)"
        parts.append(bit)
    if rec.get("aqe"):
        decs = ",".join(f"{k}x{v}" if v > 1 else k
                        for k, v in sorted(rec["aqe"].items()))
        parts.append(f"aqe=[{decs}]")
    f = rec.get("fusion")
    if f:
        if "fused_into" in f:
            parts.append(f"fused->{f['fused_into']}")
        else:
            bits = [f"{len(f.get('members', ()))} ops",
                    "cache_hit" if f.get("cache_hit") else "compiled"]
            if f.get("compile_s"):
                bits.append(f"compile={f['compile_s']:.3f}s")
            if "rows_in" in f:
                bits.append(f"rows_in={f['rows_in']}")
            parts.append(f"fused[{', '.join(bits)}]")
    x = rec.get("xla")
    if x:
        if x.get("retraces"):
            cause = x.get("cause") or "unknown"
            parts.append(f"xla=retraced[{cause}]")
        elif x.get("compiles"):
            parts.append("xla=compiled")
        elif x.get("dispatches"):
            parts.append("xla=cached")
        db = x.get("dev_bytes")
        if db:
            sign = "+" if db > 0 else "-"
            parts.append(f"dev={sign}{_fmt_bytes(abs(int(db)))}")
    rc = rec.get("rcache")
    if rc:
        bits = [rc.get("event", "hit")]
        if rc.get("delta_files"):
            bits.append(f"delta_files={rc['delta_files']}")
        if rc.get("saved_s"):
            bits.append(f"saved={rc['saved_s']:.3f}s")
        parts.append(f"result_cache[{', '.join(bits)}]")
    if rec.get("replanned"):
        parts.append("replanned")
    if rec.get("cached"):
        parts.append("cached")
    if rec.get("hits", 1) > 1:
        parts.append(f"hits={rec['hits']}")
    if rec.get("critical"):
        parts.append("on critical path")
    return "  ".join(parts)


def explain_analyze(query_id: Optional[str] = None) -> str:
    """Render the executed plan tree of a query (default: the last one
    executed) with per-node observations. Returns a diagnostic string
    when the query is unknown or was run without tracing."""
    from bodo_tpu.utils import tracing
    with _lock:
        qid = query_id or _last_qid
        q = _queries.get(qid) if qid else None
        root = q["root"] if q else None
        session = q.get("session") if q else None
        records = {p: dict(r) for p, r in q["records"].items()} if q \
            else {}
    if qid is None or q is None:
        return ("EXPLAIN ANALYZE: no recorded query "
                "(run with tracing_level >= 1)")
    for p in _critical_paths(records):
        records[p]["critical"] = True
    lines = []
    wall = tracing.query_wall_s(qid)
    if wall is None and records:
        wall = max(r["wall_s"] for r in records.values())
    header = f"EXPLAIN ANALYZE  query={qid}"
    if session:
        header += f"  session={session}"
    if wall is not None:
        header += f"  wall={wall:.3f}s"
    lines.append(header)
    if root is None:
        for rec in sorted(records.values(),
                          key=lambda r: _pathkey(r["path"])):
            lines.append(f"[{rec['path']}] {rec['op']}  {_annotate(rec)}")
        return "\n".join(lines)

    def walk(n: L.Node, prefix: str, tail: bool, top: bool) -> None:
        path = getattr(n, "_explain_path", None)
        rec = records.get(path) if path else None
        conn = "" if top else ("└─ " if tail else "├─ ")
        lines.append(f"{prefix}{conn}{_node_label(n)} [{path}]  "
                     f"{_annotate(rec)}")
        child_prefix = prefix if top else \
            prefix + ("   " if tail else "│  ")
        kids = list(n.children)
        for i, c in enumerate(kids):
            walk(c, child_prefix, i == len(kids) - 1, False)

    walk(root, "", True, True)
    return "\n".join(lines)
