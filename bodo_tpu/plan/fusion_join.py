"""Fused join groups: the probe side of a hash join — and, over 1D
input with a terminal decomposable aggregate, the partial-agg bucket
shuffle — compiled INTO the whole-stage fusion program.

plan/fusion.py fuses [Filter|Projection]+ chains (+ an optional dense
aggregate) but stops at every Join and every shuffle: those dispatch
per-operator, each with its own host count sync, and the BENCH hot
profiles show they are the remaining two-thirds of the flat tax on the
taxi/TPC-H pipelines. This module extends group formation across both
boundaries:

  group shape       [below-chain -> Join(probe side) -> above-chain ->
                    optional Aggregate], claimed by
                    `try_join_group` (called by
                    `fusion.plan_fusion_groups` BEFORE the plain chain
                    grouper so the above-join chain isn't claimed away).
                    The build (right) child executes normally — it is
                    an input, not a member.

  device-resident   the build side's encoded key codes + slot-owner LUT
  build tables      (ops/hashtable.py scatter-claim table) are built
                    ONCE per distinct build-key buffer identity and
                    kept on device in a process-wide LRU
                    (`build_hash_table`); repeat probes — streaming
                    batches against one build, a build subplan shared
                    by several joins, bench probe loops — skip the
                    build entirely. The per-node hash join
                    (relational._join_hash_try) draws from the SAME
                    cache, and every cached LUT is tracked in the
                    device-buffer ledger (xla_observatory) under op
                    ``join_build_lut``.

  fused probe body  the below-chain runs lazily (fusion._chain_body),
                    probe keys encode with the SAME aligned layout as
                    the build (`encode_columns_aligned` with an
                    all-True null-column layout, so build entries are
                    probe-independent), `probe_slots` walks the
                    double-hash sequence, build columns gather by the
                    hit index, and the above-chain continues over the
                    JOINED tree with the hit mask ANDed in (inner) —
                    ONE compaction for the whole region, or zero when
                    a left join has no filters.

  in-program        a decomposable Aggregate over a 1D probe traces the
  shuffle           whole two-phase groupby INSIDE the shard_map body:
                    per-shard partial agg (ops/groupby.groupby_local)
                    -> fixed-capacity bucket shuffle
                    (parallel/shuffle.shuffle_partials, whose
                    `lax.all_to_all` now lives inside the compiled
                    program, with the Pallas one-hot MXU bucket
                    histogram when the kernel gate is open) -> combine
                    + finalize. The aggregate need NOT be terminal:
                    [Filter|Projection] members ABOVE it (the `post`
                    chain) trace over the finalized groups inside the
                    same program, so the shuffle sits mid-program. The
                    overflow flag collapses into the group's single
                    host count sync; the host grows the bucket capacity
                    and recompiles on overflow (×4 up to the
                    always-safe bound).

  1D build sides    a genuinely big 1D build (broadcast decision says
                    no host gather) no longer falls back: each shard
                    `lax.all_gather`s the build key/emit columns inside
                    the program and builds the claim table as
                    replicated compute; the dup-keys/claim-exhausted
                    flag folds into the group's one sync, and the
                    manifest declares the in-program ``all_gather``.

  lockstep / comm   the group manifest declares its in-program
                    collectives (`register_fusion_manifest(...,
                    in_program=("all_to_all",))`); a multi-shard
                    dispatch is sequence-numbered as ONE composite
                    collective via `lockstep.pre_fused`, and the comm
                    observatory attributes an ``all_to_all`` accounting
                    row at site ``fused[<fp>]`` from the manifest
                    (`comm.record_in_program`) since the in-program
                    collective never passes a host dispatch hook.

Failure policy matches plan/fusion.py: build/trace problems raise
FusionFallback (per-node re-execution, negative-cached by structural
signature); runtime faults — OOM, degradable collectives, armed chaos
faults — propagate so the stage-boundary envelope degrades the group
to a replicated re-run (the REP chain program + host aggregate).
Donation is deliberately NOT used in fused join programs: an
unresolved-probe fallback after a donating dispatch would leave the
input node's cache pointing at freed buffers. Build-side reuse is the
device-resident cache, proven by the ledger + hit counters, not by
probe donation.

Disable with `BODO_TPU_FUSION_JOIN=0` / `set_config(fusion_join=False)`
(plain chain fusion keeps working); the build cache is bounded by
`BODO_TPU_JOIN_BUILD_CACHE` entries.
"""

from __future__ import annotations

import time as _time
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from bodo_tpu.analysis import lockstep
from bodo_tpu.analysis import progcheck
from bodo_tpu.config import config
from bodo_tpu.ops import hashtable as HT
from bodo_tpu.ops import kernels as K
from bodo_tpu.ops import pallas_kernels as PK
from bodo_tpu.parallel import collectives as C
from bodo_tpu.parallel import mesh as mesh_mod
from bodo_tpu.plan import expr as E
from bodo_tpu.plan import fusion as F
from bodo_tpu.plan import logical as L
from bodo_tpu.table import dtypes as dt
from bodo_tpu.table.table import (Column, ONED, REP, Table,
                                  round_capacity)
from bodo_tpu.runtime import xla_observatory as xobs
from bodo_tpu.utils.logging import log

# NOTE: same import rule as plan/fusion.py — relational, physical and
# parallel/shuffle import the fusion layer at module level, so they may
# only be imported INSIDE functions here.

_stats = {"groups_planned": 0, "groups_executed": 0, "partial": 0,
          "fallbacks": 0, "agg_inprogram": 0, "shuffle_retries": 0,
          "post_chain_fused": 0, "build_gather_inprogram": 0}

# device-resident build cache accounting (process-wide)
_cstats = {"hits": 0, "misses": 0, "builds": 0, "negative": 0,
           "negative_hits": 0, "evictions": 0}


def stats() -> dict:
    out = dict(_stats)
    out["build_cache"] = build_cache_stats()
    return out


def reset_stats() -> None:
    for k in _stats:
        _stats[k] = 0
    for k in _cstats:
        _cstats[k] = 0


# ---------------------------------------------------------------------------
# group formation
# ---------------------------------------------------------------------------

class JoinGroup:
    """One fusable [chain -> Join -> chain -> agg? -> chain?] region.

    below    [Filter|Projection] members UNDER the join's probe (left)
             child, bottom-up (below[0] consumes the input node)
    join     the L.Join member (how in inner/left, hash-probe eligible)
    above    [Filter|Projection] members over the joined schema,
             bottom-up
    agg      optional Aggregate (group root unless a post chain sits
             over it)
    post     [Filter|Projection] members OVER the aggregate's output
             schema, bottom-up — the non-terminal-shuffle extension:
             the in-program bucket shuffle is no longer forced to sit
             at the group root; the post chain traces over the
             finalized groups inside the SAME program
    input    plan node feeding the below chain (executed normally)
    build    the join's right child (executed normally — its table is
             the build side, cached device-resident, NOT a member)

    API-compatible with fusion.FusionGroup where the shared machinery
    needs it (`members`, `member_ops`, `root`, `input`, `donate_ok`) so
    `fusion._finish_group` handles both.
    """

    __slots__ = ("below", "join", "above", "agg", "post", "root",
                 "input", "build", "donate_ok")

    def __init__(self, below, join, above, agg, input_node, post=()):
        self.below = list(below)
        self.join = join
        self.above = list(above)
        self.agg = agg
        self.post = list(post)
        if self.post:
            self.root = self.post[-1]
        elif agg is not None:
            self.root = agg
        else:
            self.root = self.above[-1] if self.above else join
        self.input = input_node
        self.build = join.right
        # fused join programs never donate: an unresolved-probe fallback
        # after donation would leave input._cached on freed buffers
        self.donate_ok = False

    @property
    def members(self):
        """Members root-first (display order)."""
        out = list(reversed(self.post))
        if self.agg is not None:
            out.append(self.agg)
        out.extend(reversed(self.above))
        out.append(self.join)
        out.extend(reversed(self.below))
        return out

    def member_ops(self) -> Tuple[str, ...]:
        return tuple(type(m).__name__ for m in self.members)


def _post_agg_claimable(aggnode: L.Node, parents) -> bool:
    """Plan-time gate for claiming chain members ABOVE an aggregate
    (the non-terminal-shuffle shape): only worth it when the aggregate
    can decompose into the in-program shuffle — runtime still checks
    the probe distribution; when this returns False the plain chain
    grouper keeps the post chain and the terminal-agg shape applies."""
    from bodo_tpu.ops.groupby import DECOMPOSE
    if aggnode._cached is not None or not F._agg_fusable(aggnode):
        return False
    if parents.get(id(aggnode), 0) != 1:
        return False
    return all(op in DECOMPOSE for _, op, _ in aggnode.aggs)


def try_join_group(node: L.Node, parents, claimed) -> Optional[JoinGroup]:
    """Claim a [below-chain -> Join -> above-chain -> agg? ->
    post-chain?] region rooted at `node`, or None when no join-crossing
    group forms here (the caller then tries the plain chain grouper).
    Same interior rules as fusion._try_group: members must be
    single-parent and unmaterialized."""
    if not (config.fusion and config.fusion_join):
        return None
    agg = None
    post_td: List[L.Node] = []  # top-down while walking
    top = node
    if isinstance(node, L.Aggregate):
        if not F._agg_fusable(node) or node._cached is not None:
            return None
        agg = node
        top = node.child
        if parents.get(id(top), 0) != 1 or top._cached is not None:
            return None
    above_td: List[L.Node] = []  # top-down while walking
    cur = top
    while isinstance(cur, (L.Filter, L.Projection)) and \
            cur._cached is None and F._node_fusable(cur):
        if cur is not node and parents.get(id(cur), 0) != 1:
            break
        above_td.append(cur)
        cur = cur.child
    if agg is None and above_td and isinstance(cur, L.Aggregate) and \
            _post_agg_claimable(cur, parents):
        # the walked members sit ABOVE a decomposable aggregate: they
        # become the POST chain (traced over the finalized groups, after
        # the in-program shuffle) and the above-join chain walk restarts
        # under the aggregate
        agg = cur
        post_td = above_td
        above_td = []
        cur = cur.child
        if parents.get(id(cur), 0) != 1 or cur._cached is not None:
            return None
        while isinstance(cur, (L.Filter, L.Projection)) and \
                cur._cached is None and F._node_fusable(cur):
            if parents.get(id(cur), 0) != 1:
                break
            above_td.append(cur)
            cur = cur.child
    if not isinstance(cur, L.Join):
        return None
    join = cur
    if join.how not in ("inner", "left") or not join.left_on or \
            join._cached is not None:
        return None
    if join is not node and parents.get(id(join), 0) != 1:
        return None
    # plan-time key dtype identity: the fused body requires structurally
    # identical encodes on both sides (the per-node path casts/unifies;
    # fusing a cast-needing join would silently change key equality)
    try:
        ls, rs = join.left.schema, join.right.schema
        for lk, rk in zip(join.left_on, join.right_on):
            if ls[lk] is not rs[rk]:
                return None
    except Exception:  # noqa: BLE001 - unknown schema -> not fusable
        return None
    below_td: List[L.Node] = []
    cur = join.left
    while isinstance(cur, (L.Filter, L.Projection)) and \
            cur._cached is None and F._node_fusable(cur):
        if parents.get(id(cur), 0) != 1:
            break
        below_td.append(cur)
        cur = cur.child
    if agg is None and not above_td and not below_td:
        return None  # a lone join fuses nothing
    g = JoinGroup(list(reversed(below_td)), join,
                  list(reversed(above_td)), agg, cur,
                  list(reversed(post_td)))
    if any(id(m) in claimed for m in g.members):
        return None  # defensive: overlapping walk already claimed one
    _stats["groups_planned"] += 1
    return g


def _suffix_maps(lnames, rnames, left_on, right_on, suffixes):
    """relational._suffix_columns on bare name lists (the fused planner
    works over schemas, not Tables): returns (lmap, rmap); right-side
    key columns merged into an equally-named left key are dropped."""
    overlap = (set(lnames) & set(rnames)) - (set(left_on) & set(right_on))
    lmap = {n: (n + suffixes[0] if n in overlap else n) for n in lnames}
    rmap = {n: (n + suffixes[1] if n in overlap else n) for n in rnames
            if not (n in right_on and left_on[right_on.index(n)] == n)}
    return lmap, rmap


# ---------------------------------------------------------------------------
# device-resident build-side hash tables
# ---------------------------------------------------------------------------

# build-key buffer identity -> {"codes", "owner", "refs", "hits"} entry,
# or None (negative verdict: duplicate build keys / unresolved claim).
# Entries hold strong refs to the source key buffers so id() identity
# stays meaningful for the entry's lifetime.
_build_cache: "OrderedDict[tuple, Optional[dict]]" = OrderedDict()

# build-program cache keyed ("joinbuild", key dtypes, T, layout):
# registered with the program observatory like every other kernel cache
from bodo_tpu.utils.kernel_cache import KernelCache  # noqa: E402
_build_jit_cache = KernelCache(maxsize=config.kernel_cache_size,
                         subsystem="fusion_join")


def _build_key(right: Table, right_on, null_cols, null_equal) -> tuple:
    cols = [right.column(k) for k in right_on]
    return (tuple(id(c.data) for c in cols),
            tuple(c.dtype.name for c in cols),
            tuple(c.valid is not None for c in cols),
            bool(null_equal), tuple(null_cols),
            int(right.nrows), int(right.capacity))


def _cache_put(key, ent) -> None:
    _build_cache[key] = ent
    _build_cache.move_to_end(key)
    limit = max(int(config.join_build_cache_size), 1)
    while len(_build_cache) > limit:
        _build_cache.popitem(last=False)
        _cstats["evictions"] += 1


def build_hash_table(right: Table, right_on, null_cols,
                     null_equal: bool) -> Optional[Tuple]:
    """Device-resident build: (codes, owner) for `right`'s key columns
    over a claim table of size `HT.table_size(right.capacity)`,
    LRU-cached by key-buffer identity so repeat probes against the same
    build table skip the build (and its host dup-check sync) entirely.
    Returns None when the build side has duplicate keys or the claim
    rounds exhausted (cached negatively — the caller's sort join owns
    that case). One host sync per MISS, zero per hit."""
    key = _build_key(right, right_on, null_cols, null_equal)
    if key in _build_cache:
        ent = _build_cache[key]
        _build_cache.move_to_end(key)
        if ent is None:
            _cstats["negative_hits"] += 1
            return None
        ent["hits"] += 1
        _cstats["hits"] += 1
        return ent["codes"], ent["owner"]
    _cstats["misses"] += 1
    nk = len(right_on)
    T = HT.table_size(right.capacity)
    kcols = [right.column(k) for k in right_on]
    sig = ("joinbuild",
           tuple((c.dtype.name, c.valid is not None) for c in kcols),
           nk, bool(null_equal), T, tuple(null_cols))
    fn = _build_jit_cache.get(sig)
    built_fresh = fn is None
    if built_fresh:
        ncols = tuple(null_cols)

        def bbody(arrays, count):
            cap = arrays[0][0].shape[0]
            codes, null_ok = HT.encode_columns_aligned(arrays, ncols,
                                                       null_equal)
            ok = K.row_mask(count, cap)
            if null_ok is not None:
                ok = ok & null_ok
            slot, owner, _r, unresolved = HT.claim_slots(codes, ok, T)
            cnt = jnp.zeros(T, jnp.int32).at[
                jnp.where(slot >= 0, slot, T)].add(1, mode="drop")
            dup = jnp.any(cnt > 1)
            return codes, owner, dup | unresolved

        fn = jax.jit(bbody)
        _build_jit_cache[sig] = fn
    karrays = tuple((c.data, c.valid) for c in kcols)
    if built_fresh:
        # the cached slot-owner LUT outlives this dispatch: donation of
        # any build input would leave the cache pointing at freed
        # buffers, so "never donate" is a checked contract here
        h = _build_jit_cache.handle_for(sig)
        progcheck.check_jit(fn, (karrays, jnp.asarray(right.nrows)),
                            program="joinbuild",
                            subsystem="fusion_join",
                            forbid_donation=True, obs_handle=h)
        progcheck.mark_checked(h)
    bcodes, owner, bad = fn(karrays, jnp.asarray(right.nrows))
    _cstats["builds"] += 1
    # the one budgeted sync per build MISS (dup-key verdict)
    if bool(jax.device_get(bad)):  # dispatch-boundary
        _cstats["negative"] += 1
        _cache_put(key, None)
        return None
    # the slot-owner LUT is the device-resident artifact probes reuse:
    # ledger-track it so the HBM observatory (and the donation verifier's
    # reuse proof in tests) can see the one buffer shared across probes
    xobs.track_buffer(owner, "join_build_lut")
    _cache_put(key, {"codes": bcodes, "owner": owner, "hits": 0,
                     "refs": tuple(c.data for c in kcols)})
    return bcodes, owner


def prime_build(right: Table, right_on, null_equal: bool = True) -> bool:
    """Opportunistically warm the build cache (streaming executors call
    this when a join's build side finalizes, so the first probe batch
    already hits). Uses the probe-independent all-True null layout —
    the same layout every probe path keys with. Best-effort: never
    raises; returns True when an entry (positive or negative) exists."""
    if not (config.fusion_join and config.hash_join):
        return False
    try:
        if right.distribution != REP or right.nrows == 0 or not right_on:
            return False
        null_cols = (True,) * len(right_on)
        build_hash_table(right, list(right_on), null_cols, null_equal)
        return True
    except Exception:  # noqa: BLE001 - priming must never break a query
        return False


def build_cache_stats() -> dict:
    out = dict(_cstats)
    out["size"] = len(_build_cache)
    out["entry_hits"] = {i: e["hits"] for i, (_k, e) in
                        enumerate(_build_cache.items())
                        if e is not None}
    return out


def cached_build_entry(right: Table, right_on, null_cols=None,
                       null_equal: bool = True) -> Optional[dict]:
    """Introspection for tests/doctor: the live cache entry for this
    build table (None when absent or negative)."""
    if null_cols is None:
        null_cols = (True,) * len(right_on)
    return _build_cache.get(
        _build_key(right, list(right_on), tuple(null_cols), null_equal))


def clear_build_cache() -> None:
    _build_cache.clear()
    _build_jit_cache.clear()


# ---------------------------------------------------------------------------
# fused probe body
# ---------------------------------------------------------------------------

def _make_probe_body(below_meta, in_names, left_on, null_cols,
                     null_equal, T, how, lmap, below_names, build_emit,
                     rmap, above_meta):
    """Traced region [below-chain -> encode -> probe -> gather ->
    above-chain]: returns (joined tree, live mask, probe-unresolved
    flag). Shared by the chain-exit and fused-aggregate program
    variants."""

    @F.fusion_stage
    def body(ptree, pcount, bvals, bcodes, owner):
        cur, mask = F._chain_body(below_meta, in_names, ptree, pcount)
        keys = [cur[k] for k in left_on]
        codes, null_ok = HT.encode_columns_aligned(keys, null_cols,
                                                   null_equal)
        live = mask if null_ok is None else (mask & null_ok)
        idx, p_unres = HT.probe_slots(bcodes, owner, codes, live, T)
        hit = idx >= 0
        safe = jnp.maximum(idx, 0)
        joined = {lmap[n]: cur[n] for n in below_names}
        for n in build_emit:
            d, v = bvals[n]
            od = d[safe]
            ov = hit if v is None else (hit & v[safe])
            joined[rmap[n]] = (od, ov)
        if how == "inner":
            mask = mask & hit
        # left join: unmatched probe rows stay live with all build
        # columns invalid (ov already False where hit is False)
        cur2, mask2 = F._chain_body_masked(above_meta, joined, mask)
        return cur2, mask2, p_unres

    return body


def _flatten_tree(cur, names):
    flat = []
    for n in names:
        d, v = cur[n]
        flat.append(d)
        flat.append(v)
    return tuple(flat)


def _make_build_gather(right_on, need, null_cols, null_equal, T, S,
                       cap_shard, ax):
    """In-program build over a 1D build side: each shard all_gathers the
    build's key/emit columns (rank-order concat; padding resolved by
    the gathered per-shard counts) and builds the claim table as
    replicated compute — the collective lives INSIDE the compiled
    program, replacing the host gather the per-node broadcast join
    would do. Returns (gathered tree, codes, owner LUT, bad flag);
    `bad` (duplicate keys / claim rounds exhausted) folds into the
    group's single host sync."""

    @F.fusion_stage
    def gather_build(btree, bcounts):
        allc = C.all_gather_rows(bcounts, ax)            # [S]
        row = jnp.arange(S * cap_shard)
        ok = (row % cap_shard) < allc[row // cap_shard]
        gathered = {}
        for n in need:
            d, v = btree[n]
            gd = C.all_gather_rows(d, ax)
            gv = None if v is None else C.all_gather_rows(v, ax)
            gathered[n] = (gd, gv)
        keys = [gathered[k] for k in right_on]
        codes, null_ok = HT.encode_columns_aligned(keys, null_cols,
                                                   null_equal)
        bok = ok if null_ok is None else (ok & null_ok)
        slot, owner, _r, unresolved = HT.claim_slots(codes, bok, T)
        cnt = jnp.zeros(T, jnp.int32).at[
            jnp.where(slot >= 0, slot, T)].add(1, mode="drop")
        bad = jnp.any(cnt > 1) | unresolved
        return gathered, codes, owner, bad

    return gather_build


# ---------------------------------------------------------------------------
# group execution (called from physical._exec_inner)
# ---------------------------------------------------------------------------

def execute_join_group(group: JoinGroup, exec_child) -> Optional[Table]:
    """Execute one fused join group: run the input and build nodes
    normally, then dispatch the whole probe region as one compiled
    program. Returns the group ROOT's result, or None to fall back to
    per-node execution. Runtime faults propagate to the resilience
    envelope (a degraded re-run gathers the probe and re-dispatches the
    REP program, finishing any aggregate host-side)."""
    from bodo_tpu.plan import physical
    from bodo_tpu.utils import tracing

    t = exec_child(group.input)
    b = exec_child(group.build)
    force_rep = getattr(physical._degrade_tls, "force_rep", False)
    if force_rep:
        if t.distribution == ONED:
            t = t.gather()
        if b.distribution == ONED:
            b = b.gather()
    if config.plan_validate:
        from bodo_tpu.analysis.plan_validator import (
            PlanInvariantError, check_fusion_boundary)
        try:
            check_fusion_boundary(group.input, t.distribution,
                                  force_rep=force_rep)
        except PlanInvariantError:
            _stats["fallbacks"] += 1
            return None

    with tracing.event("fused_join_group",
                       members=len(group.members)) as ev:
        try:
            out = _run_join_group(t, b, group)
        except F.FusionFallback as e:
            _stats["fallbacks"] += 1
            log(2, f"fused-join fallback "
                   f"({len(group.members)} members): {e}")
            return None
        _stats["groups_executed"] += 1
        F._finish_group(group, t, out)
        info = group.root._fusion_info
        if info is not None:
            # surface the collectives the program subsumed in EXPLAIN
            # ANALYZE next to the absorbed plan members, matching what
            # the manifest declares for this group
            coll = []
            if getattr(out, "_fusion_build_gather", False):
                coll.append("all_gather")
            if getattr(out, "_fusion_join_inprogram", False):
                info["members"] = tuple(info["members"]) + ("Shuffle",)
                coll.append("all_to_all")
            if coll:
                info["in_program_collectives"] = tuple(coll)
        if ev is not None:
            ev["rows"] = out.nrows
    return out


def _plan_fused_agg(t: Table, agg: L.Aggregate, out_schema, out_dicts):
    """Gate + static plan for tracing the two-phase aggregate (partial
    -> in-program bucket shuffle -> combine -> finalize) inside the
    probe program. Returns a plan dict, or None -> partial fusion (the
    chain+join program runs, relational.groupby_agg finishes)."""
    if t.distribution != ONED:
        return None  # REP aggregate has no shuffle to absorb
    from bodo_tpu.ops.groupby import DECOMPOSE
    from bodo_tpu.parallel.shuffle import _plan_decomposition
    kn = list(agg.keys)
    specs = tuple(op for _, op, _ in agg.aggs)
    vn = [c for c, _, _ in agg.aggs]
    if not kn or any(op not in DECOMPOSE for op in specs):
        return None
    for n in kn + vn:
        d = out_schema.get(n)
        if d is None or dt.is_decimal(d):
            return None
        if d is dt.STRING and n not in out_dicts and n in vn:
            return None
    for c in vn:
        if out_schema[c] is dt.STRING:
            return None  # string value aggs finalize host-side
    try:
        partial_specs, combine_specs, layout = _plan_decomposition(specs)
    except NotImplementedError:
        return None
    value_dtypes = tuple(str(np.dtype(out_schema[c].numpy)) for c in vn)
    return {"kn": kn, "vn": vn, "specs": specs,
            "partial_specs": partial_specs,
            "combine_specs": combine_specs, "layout": layout,
            "value_dtypes": value_dtypes}


def _run_join_group(t: Table, b: Table, group: JoinGroup) -> Table:
    """Build (cached) + compile (cached) + dispatch the fused join
    program; raises FusionFallback on build/trace failure."""
    from bodo_tpu import relational as R

    if not t.names or not b.names:
        raise F.FusionFallback("empty schema")
    if not config.hash_join:
        raise F.FusionFallback("hash join disabled")
    build_inprogram = False
    if b.distribution == ONED:
        # same runtime broadcast decision as the per-node path: a small
        # sharded build side replicates (one gather) so the probe never
        # shuffles; a genuinely big 1D build over a 1D probe gathers
        # INSIDE the program (lax.all_gather in the shard_map body) and
        # builds the claim table as replicated compute
        from bodo_tpu.plan import adaptive
        if adaptive.join_broadcast_decision(b, t):
            b = b.gather()
        elif t.distribution == ONED and t.num_shards > 1:
            build_inprogram = True
    if b.distribution != REP and not build_inprogram:
        raise F.FusionFallback("1D build side")
    if b.nrows == 0:
        raise F.FusionFallback("empty build side")
    join = group.join
    left_on, right_on = list(join.left_on), list(join.right_on)
    nk = len(left_on)
    how, null_equal, suffixes = join.how, join.null_equal, join.suffixes
    agg = group.agg

    fp_sig = ("fusedjoin", F._struct_sig(t), F._struct_sig(b),
              F._steps_sig(group.below), F._steps_sig(group.above),
              tuple(left_on), tuple(right_on), how, null_equal,
              t.distribution,
              (tuple(agg.keys), tuple(agg.aggs)) if agg else None,
              F._steps_sig(group.post), build_inprogram)
    if fp_sig in F._failed:
        raise F.FusionFallback("negative-cached")

    try:
        (below_meta, below_names, below_schema, below_dicts,
         _below_compose) = F._chain_meta(t, group.below)
    except Exception as e:  # noqa: BLE001 - build failure -> unfused
        F._failed.add(fp_sig)
        raise F.FusionFallback(str(e)) from e

    # runtime key compatibility: the plan-time gate checked schema
    # dtypes, but dictionary unification / dtype promotion happen at
    # runtime in the per-node path — the fused body does neither
    for lk, rk in zip(left_on, right_on):
        ldt = below_schema.get(lk)
        bc = b.columns.get(rk)
        if ldt is None or bc is None:
            raise F.FusionFallback("join key missing from chain output")
        if ldt is not bc.dtype:
            raise F.FusionFallback("join key dtype mismatch")
        if ldt is dt.STRING and below_dicts.get(lk) is not bc.dictionary:
            # dict-encoded keys compare by code: only sound when both
            # sides share ONE dictionary object (per-node unifies)
            raise F.FusionFallback("join key dictionaries differ")

    # probe-independent null layout: a null code column is always legal
    # (zeros when a side can't produce nulls), and keying the build
    # cache on it makes entries reusable across every probe shape
    null_cols = (True,) * nk

    lmap, rmap = _suffix_maps(below_names, list(b.names), left_on,
                              right_on, suffixes)
    build_emit = [n for n in b.names if n in rmap]
    joined_schema = {lmap[n]: below_schema[n] for n in below_names}
    joined_dicts = {lmap[n]: below_dicts[n] for n in below_names
                    if n in below_dicts}
    for n in build_emit:
        c = b.columns[n]
        joined_schema[rmap[n]] = c.dtype
        if c.dictionary is not None:
            joined_dicts[rmap[n]] = c.dictionary
    try:
        (above_meta, out_names, out_schema, out_dicts,
         _above_compose) = F._chain_meta_from(joined_schema,
                                              joined_dicts, group.above)
    except Exception as e:  # noqa: BLE001 - build failure -> unfused
        F._failed.add(fp_sig)
        raise F.FusionFallback(str(e)) from e

    T = HT.table_size(b.capacity)
    if build_inprogram:
        bcodes = owner = None  # built inside the program
    else:
        built = build_hash_table(b, right_on, null_cols, null_equal)
        if built is None:
            raise F.FusionFallback("duplicate build keys")
        bcodes, owner = built

    agg_plan = None
    if agg is not None:
        agg_plan = _plan_fused_agg(t, agg, out_schema, out_dicts)
        if agg_plan is not None:
            missing = [n for n in agg_plan["kn"] + agg_plan["vn"]
                       if n not in out_names]
            if missing:
                agg_plan = None

    post_meta = post_names = post_schema = post_dicts = None
    if group.post:
        if agg_plan is None:
            # the post chain was claimed on the promise of the
            # in-program aggregate; without it (REP probe, gate miss)
            # the per-node path owns the region. Data-dependent — no
            # negative cache.
            raise F.FusionFallback(
                "post-agg chain without in-program aggregate")
        agg_schema = dict(agg.schema)
        agg_dicts = {k: out_dicts[k] for k in agg_plan["kn"]
                     if k in out_dicts}
        try:
            (post_meta, post_names, post_schema, post_dicts,
             _post_compose) = F._chain_meta_from(agg_schema, agg_dicts,
                                                 group.post)
        except Exception as e:  # noqa: BLE001 - build failure -> unfused
            F._failed.add(fp_sig)
            raise F.FusionFallback(str(e)) from e

    in_names = list(t.names)
    body = _make_probe_body(below_meta, in_names, left_on, null_cols,
                            null_equal, T, how, lmap, below_names,
                            build_emit, rmap, above_meta)
    fp = F._group_fp(fp_sig)
    multi = t.distribution == ONED and t.num_shards > 1

    if build_inprogram:
        bneed = list(dict.fromkeys(right_on + build_emit))
        gb = _make_build_gather(right_on, bneed, null_cols, null_equal,
                                T, t.num_shards, b.shard_capacity,
                                config.data_axis)
        probe_body = body

        @F.fusion_stage
        def body(ptree, pcount, btree, bcounts):
            bvals_g, bcodes_g, owner_g, bbad = gb(btree, bcounts)
            cur2, mask2, p_unres = probe_body(ptree, pcount, bvals_g,
                                              bcodes_g, owner_g)
            return cur2, mask2, p_unres | bbad

        bargs = (b.select(bneed).device_data(), b.counts_device())
        bspecs = (P(config.data_axis), P(config.data_axis))
    else:
        bargs = (b.select(build_emit).device_data(), bcodes, owner)
        bspecs = (P(), P(), P())

    if agg_plan is not None:
        out = _dispatch_agg(t, b, group, body, bargs, bspecs, agg_plan,
                            out_schema, out_dicts, post_meta,
                            post_names, post_schema, post_dicts, fp,
                            fp_sig, multi, build_inprogram)
    else:
        chained = _dispatch_chain(t, b, group, body, bargs, bspecs,
                                  out_names, out_schema, out_dicts, fp,
                                  fp_sig, multi, build_inprogram)
        if agg is not None:
            # partial fusion: the chain+probe fused, the aggregate (REP
            # input, non-decomposable op, or gate miss) finishes per-op
            _stats["partial"] += 1
            out = R.groupby_agg(chained, agg.keys, agg.aggs)
            for attr in ("_fusion_compiled", "_fusion_compile_s",
                         "_fusion_donated"):
                setattr(out, attr, getattr(chained, attr, False))
        else:
            out = chained
    if build_inprogram:
        _stats["build_gather_inprogram"] += 1
    return out


def _register_manifest(group: JoinGroup, fp: str, multi: bool,
                       inprogram: bool, gather: bool = False) -> None:
    ops = (F._member_kinds(group.below) + ("join",)
           + F._member_kinds(group.above,
                             group.agg if inprogram else None))
    if inprogram:
        ops = ops + ("shuffle",) + F._member_kinds(group.post)
    coll = (("all_gather",) if gather else ()) + \
        (("all_to_all",) if inprogram else ())
    lockstep.register_fusion_manifest(fp, ops, 1 if multi else 0,
                                      in_program=coll)


def _pre_dispatch(fp: str, multi: bool) -> float:
    """Host-level fault point + composite-collective sequencing (the
    fused program subsumes its members' dispatches — the GROUP is the
    unit chaos tests arm and lockstep peers must agree on)."""
    if not multi:
        return 0.0
    from bodo_tpu.runtime.resilience import maybe_inject
    maybe_inject("collective")
    return lockstep.pre_fused(fp)


def _dispatch_chain(t, b, group, body, bargs, bspecs, out_names,
                    out_schema, out_dicts, fp, fp_sig, multi,
                    build_inprogram) -> Table:
    """Chain-exit variant: fused program returns the joined/filtered
    columns (one compaction, or zero for a filter-less left join)."""
    from bodo_tpu import relational as R
    from bodo_tpu.parallel.shuffle import _mesh_key

    m = mesh_mod.get_mesh()
    has_filter = any(isinstance(s, L.Filter)
                     for s in group.below + group.above)
    compact_needed = has_filter or group.join.how == "inner"
    rorder = list(group.join.right_on) + \
        [n for n in b.names if n not in group.join.right_on]
    sig = ("fusedjoin", _mesh_key(m), R._sig(t),
           R._sig(b.select(rorder)), F._steps_sig(group.below),
           F._steps_sig(group.above), tuple(group.join.left_on),
           tuple(group.join.right_on), group.join.how,
           group.join.null_equal, t.distribution, compact_needed,
           build_inprogram)
    fn = F._programs.lookup(sig)
    compiled = fn is None
    if compiled:
        F._budget_compile(sig)

        def fused(ptree, pcount, bargs_):
            cur2, mask2, p_unres = body(ptree, pcount, *bargs_)
            flat = _flatten_tree(cur2, out_names)
            if compact_needed:
                out, cnt = K.compact(mask2, flat)
            else:
                out, cnt = flat, pcount
            return out, cnt, p_unres

        if t.distribution == ONED:
            ax = config.data_axis

            def sharded(ptree, pcounts, bargs_):
                out, cnt, unres = fused(ptree, pcounts[0], bargs_)
                return out, cnt[None], unres[None]
            fn = jax.jit(C.smap(
                sharded, in_specs=(P(ax), P(ax), bspecs),
                out_specs=(P(ax), P(ax), P(ax)), mesh=m))
        else:
            fn = jax.jit(fused)
        _register_manifest(group, fp, multi, inprogram=False,
                           gather=build_inprogram)
        if t.distribution == ONED:
            _ck_args = (t.device_data(), t.counts_device(), bargs)
        else:
            _ck_args = (t.device_data(), jnp.asarray(t.nrows), bargs)
        progcheck.check_jit(
            fn, _ck_args, program=f"fused:{fp}", subsystem="fusion_join",
            declared_collectives=(("all_gather",) if build_inprogram
                                  else None) if multi else None)

    from bodo_tpu.runtime import memory_governor as _mg
    w = _pre_dispatch(fp, multi)
    t0 = _time.perf_counter()
    try:
        with _mg.preadmission_charge(f"fused:{fp}"):
            if t.distribution == ONED:
                out, cnts, unres = fn(t.device_data(),
                                      t.counts_device(), bargs)
                cnts_h, unres_h = jax.device_get((cnts, unres))  # dispatch-boundary
                counts = np.asarray(cnts_h).reshape(-1).astype(np.int64)
                bad = bool(np.asarray(unres_h).any())
            else:
                out, cnt, unres = fn(t.device_data(),
                                     jnp.asarray(t.nrows), bargs)
                cnt_h, unres_h = jax.device_get((cnt, unres))  # dispatch-boundary
                counts = None
                nrows = int(cnt_h)
                bad = bool(unres_h)
    except Exception as e:  # noqa: BLE001 - classified below
        F._classify_dispatch_error(e, fp_sig, compiled)
        raise F.FusionFallback(str(e)) from e
    dt_s = _time.perf_counter() - t0
    if compiled:
        F._programs[sig] = fn
        F._programs.record_compile("fused_join", dt_s)
        progcheck.mark_checked(F._programs.handle_for(sig))
    if multi and build_inprogram:
        from bodo_tpu.parallel import comm
        comm.record_in_program(fp, bytes_in=comm.table_bytes(b),
                               wall_s=dt_s, wait_s=w)
    if bad:
        # data-dependent: probe-round exhaustion (sort join owns it) or
        # a bad in-program build (duplicate keys / claim exhaustion) —
        # no negative cache, a different batch may resolve fine
        raise F.FusionFallback("probe unresolved or bad build")

    cols: Dict[str, Column] = {}
    for i, n in enumerate(out_names):
        cols[n] = Column(out[2 * i], out[2 * i + 1], out_schema[n],
                         out_dicts.get(n))
    if counts is not None:
        res = Table(cols, int(counts.sum()), ONED, counts)
    else:
        res = Table(cols, nrows, REP, None)
    res._fusion_compiled = compiled  # type: ignore[attr-defined]
    res._fusion_compile_s = dt_s if compiled else 0.0
    res._fusion_donated = False  # type: ignore[attr-defined]
    res._fusion_build_gather = build_inprogram  # type: ignore[attr-defined]
    return R.rebucket(res)


def _dispatch_agg(t, b, group, body, bargs, bspecs, agg_plan,
                  out_schema, out_dicts, post_meta, post_names,
                  post_schema, post_dicts, fp, fp_sig, multi,
                  build_inprogram) -> Table:
    """Fully-fused variant over a 1D probe: the two-phase aggregate —
    partial agg, fixed-capacity bucket shuffle (`lax.all_to_all` INSIDE
    the shard_map body), combine, finalize — traces into the same
    program as the chain+probe, and a non-empty POST chain (the
    non-terminal-shuffle shape) continues over the finalized groups
    inside that program too. One host sync carries (group counts,
    shuffle overflow, probe unresolved); on overflow the host grows the
    bucket capacity ×4 (to the always-safe bound) and recompiles."""
    from bodo_tpu import relational as R
    from bodo_tpu.ops.groupby import (DECOMPOSE, agg_dtype,
                                      groupby_local)
    from bodo_tpu.parallel.shuffle import (_finalize, _mesh_key,
                                           shuffle_partials)
    import types as _types

    agg = group.agg
    kn, vn = agg_plan["kn"], agg_plan["vn"]
    specs = agg_plan["specs"]
    partial_specs = agg_plan["partial_specs"]
    combine_specs = agg_plan["combine_specs"]
    layout = agg_plan["layout"]
    value_dtypes = agg_plan["value_dtypes"]
    nkk = len(kn)
    need = list(dict.fromkeys(kn + vn))

    m = mesh_mod.get_mesh()
    ax = config.data_axis
    S = m.shape[ax]
    cap_shard = max(t.shard_capacity, 1)
    safe_cap = round_capacity(cap_shard)
    bucket_cap = min(round_capacity(
        int(config.shuffle_skew_factor * cap_shard / max(S, 1)) + 64),
        safe_cap)
    rorder = list(group.join.right_on) + \
        [n for n in b.names if n not in group.join.right_on]
    base_sig = ("fusedjoinagg", _mesh_key(m), R._sig(t),
                R._sig(b.select(rorder)), F._steps_sig(group.below),
                F._steps_sig(group.above), tuple(group.join.left_on),
                tuple(group.join.right_on), group.join.how,
                group.join.null_equal, tuple(kn), tuple(agg.aggs),
                F._steps_sig(group.post), build_inprogram)

    while True:
        final_cap = S * bucket_cap
        sig = base_sig + (bucket_cap, final_cap)
        fn = F._programs.lookup(sig)
        compiled = fn is None
        if compiled:
            F._budget_compile(sig)
            bc_static, fc_static = bucket_cap, final_cap

            @F.fusion_stage
            def sharded(ptree, pcounts, bargs_):
                cur2, mask2, p_unres = body(ptree, pcounts[0], *bargs_)
                flat = _flatten_tree(cur2, need)
                packed, cnt = K.compact(mask2, flat)
                pairs = {n: (packed[2 * i], packed[2 * i + 1])
                         for i, n in enumerate(need)}
                keys = tuple(pairs[n] for n in kn)
                values = [pairs[c] for c in vn]
                p_inputs = keys + tuple(
                    values[i] for i, op in enumerate(specs)
                    for _ in DECOMPOSE[op])
                cap = mask2.shape[0]
                pk, pv, ng = groupby_local(p_inputs, cnt, partial_specs,
                                           cap, nkk)
                rk, rv, cnt2, ovf = shuffle_partials(
                    pk, pv, nkk, S, bc_static, ng, ax)
                fk, fv, ng2 = groupby_local(rk + rv, cnt2,
                                            combine_specs, fc_static,
                                            nkk)
                finals = []
                for i, op in enumerate(specs):
                    off, nparts = layout[i]
                    finals.append(_finalize(
                        op, fv[off:off + nparts],
                        jnp.dtype(value_dtypes[i])))
                if post_meta is None:
                    return ((fk, tuple(finals)), ng2[None], ovf[None],
                            p_unres[None])
                # non-terminal shuffle: cast the finalized groups to
                # their logical dtypes (same rules as the host exit
                # path / relational._agg_out_col — no decimals, the
                # plan gate rejects them) and run the post chain over
                # them, all still inside the program
                tree = {}
                for kname, (kd, kv) in zip(kn, fk):
                    kdt = out_schema[kname]
                    if kdt is dt.STRING:
                        kd = kd.astype(jnp.int32)
                    elif kdt.kind == "b":
                        kd = kd.astype(bool)
                    elif kd.dtype != kdt.numpy:
                        kd = kd.astype(kdt.numpy)
                    tree[kname] = (kd, kv)
                for (cname, op, oname), (vd, vv) in zip(agg.aggs,
                                                        finals):
                    rdt = agg_dtype(op, out_schema[cname])
                    if vd.dtype != rdt.numpy:
                        vd = vd.astype(rdt.numpy)
                    tree[oname] = (vd, vv)
                gmask = K.row_mask(ng2, fc_static)
                cur3, mask3 = F._chain_body_masked(post_meta, tree,
                                                   gmask)
                outp, ng3 = K.compact(mask3,
                                      _flatten_tree(cur3, post_names))
                return (outp, ng3[None], ovf[None], p_unres[None])

            fn = jax.jit(C.smap(
                sharded, in_specs=(P(ax), P(ax), bspecs),
                out_specs=(P(ax), P(ax), P(ax), P(ax)), mesh=m))
            _register_manifest(group, fp, multi, inprogram=True,
                               gather=build_inprogram)
            progcheck.check_jit(
                fn, (t.device_data(), t.counts_device(), bargs),
                program=f"fused:{fp}", subsystem="fusion_join",
                declared_collectives=((("all_gather",)
                                       if build_inprogram else ())
                                      + ("all_to_all",))
                if multi else None)

        from bodo_tpu.runtime import memory_governor as _mg
        w = _pre_dispatch(fp, multi)
        t0 = _time.perf_counter()
        try:
            with _mg.preadmission_charge(f"fused:{fp}"):
                res_out, ngs, ovf, unres = fn(
                    t.device_data(), t.counts_device(), bargs)
                ngs_h, ovf_h, unres_h = jax.device_get(  # dispatch-boundary
                    (ngs, ovf, unres))
        except Exception as e:  # noqa: BLE001 - classified below
            F._classify_dispatch_error(e, fp_sig, compiled)
            raise F.FusionFallback(str(e)) from e
        dt_s = _time.perf_counter() - t0
        if compiled:
            F._programs[sig] = fn
            F._programs.record_compile("fused_join", dt_s)
            progcheck.mark_checked(F._programs.handle_for(sig))
        if multi:
            from bodo_tpu.parallel import comm
            comm.record_in_program(fp, bytes_in=comm.table_bytes(t),
                                   wall_s=dt_s, wait_s=w)
        if bool(np.asarray(unres_h).any()):
            raise F.FusionFallback("probe unresolved or bad build")
        if bool(np.asarray(ovf_h).any()):
            if bucket_cap >= safe_cap:
                raise F.FusionFallback(
                    "shuffle overflow at safe capacity")
            bucket_cap = min(bucket_cap * 4, safe_cap)
            _stats["shuffle_retries"] += 1
            continue
        break

    _stats["agg_inprogram"] += 1
    counts = np.asarray(ngs_h).reshape(-1).astype(np.int64)
    cols: Dict[str, Column] = {}
    if post_meta is not None:
        _stats["post_chain_fused"] += 1
        for i, n in enumerate(post_names):
            cols[n] = Column(res_out[2 * i], res_out[2 * i + 1],
                             post_schema[n], post_dicts.get(n))
    else:
        fk, finals = res_out
        for kname, (kd, kv) in zip(kn, fk):
            kdt = out_schema[kname]
            if kdt is dt.STRING:
                kd = kd.astype(np.int32)
            elif kdt.kind == "b":
                kd = kd.astype(bool)
            elif kd.dtype != kdt.numpy:
                kd = kd.astype(kdt.numpy)
            cols[kname] = Column(kd, kv, kdt, out_dicts.get(kname))
        for (cname, op, oname), (vd, vv) in zip(agg.aggs, finals):
            src = _types.SimpleNamespace(dtype=out_schema[cname],
                                         dictionary=out_dicts.get(cname))
            cols[oname] = R._agg_out_col(src, op, vd, vv)
    res = R.shrink_to_fit(Table(cols, int(counts.sum()), ONED, counts))
    res._fusion_compiled = compiled  # type: ignore[attr-defined]
    res._fusion_compile_s = dt_s if compiled else 0.0
    res._fusion_donated = False  # type: ignore[attr-defined]
    res._fusion_join_inprogram = True  # type: ignore[attr-defined]
    res._fusion_build_gather = build_inprogram  # type: ignore[attr-defined]
    # the in-program shuffle's bucket histogram routes through the
    # Pallas one-hot MXU accumulate when the kernel gate is open
    if (PK.use_pallas() or PK.FORCE_INTERPRET) and \
            (S + 1) <= PK.MAX_MATMUL_SLOTS:
        res._fusion_pallas = True  # type: ignore[attr-defined]
    return res
