"""Adaptive query execution: runtime statistics feedback.

The optimizer plans from plan/stats.py textbook estimates; this module
closes the loop at stage boundaries, where actual cardinalities are free
(every materialized stage already knows its row counts):

  * observation — plan/physical._exec and both streaming executors
    report each completed stage's rows/bytes here. The per-stage q-error
    (max(est/actual, actual/est)) feeds the tracing profile / bench
    JSON, observed rows override ``stats.estimate()`` for every subplan
    not yet executed, and fingerprint-stable subplans persist to the
    stats store (runtime/stats_store.py) for future processes.
  * broadcast promote/demote — the broadcast-vs-shuffle join decision in
    relational.join_tables re-evaluates against the memory governor's
    derived budget: a build side whose OBSERVED bytes fit
    aqe_bcast_frac x budget broadcasts even when the rows heuristic
    planned a full shuffle, and an oversized planned broadcast demotes
    to a shuffle join (the reference decides this statically at plan
    time; on TPU the all_to_all is expensive enough that the runtime
    correction pays for itself).
  * skew splits — before a sharded join pays an all_to_all, the probe
    key distribution is sampled; hot keys above aqe_skew_frac split off
    and broadcast-join against their (small) build subset so the shuffle
    carries only the cold remainder.
  * batch coalescing — undersized streaming batches (post-filter) merge
    until they reach aqe_coalesce_frac of the nominal batch size, so
    per-batch kernels don't run near-empty.
  * mid-plan re-optimization — inner-join chains re-run
    ``optimizer.reorder_joins`` once their leaf relations have observed
    cardinalities; a changed order re-plans the not-yet-executed joins
    (leaf results stay memoized on their nodes, so nothing re-executes).

Degraded replicated re-runs (runtime/resilience.py) are execution-path
artifacts, not data properties — observation is suspended while one is
in flight so they cannot poison the stats store.

Default-on via ``set_config(aqe=...)`` / ``BODO_TPU_AQE``; every
decision lands in an ``aqe:*`` counter (tracing.profile / dump / bench).
"""

from __future__ import annotations

import threading
from collections import defaultdict
from typing import Dict, List, Optional, Tuple

import numpy as np

from bodo_tpu.config import config

_lock = threading.Lock()
_counters: Dict[str, int] = defaultdict(int)
_observed: Dict[tuple, float] = {}
_qerr: List[dict] = []
_MAX_QERR = 512
_MAX_OBSERVED = 4096
_injector = None  # test hook: fn(node) -> Optional[rows]


def enabled() -> bool:
    return bool(config.aqe)


def _suspended() -> bool:
    """True while a degraded replicated re-run is in flight — its
    execution shape is an artifact of the failure, not of the data."""
    from bodo_tpu.plan import physical
    return bool(getattr(physical._degrade_tls, "force_rep", False))


def count(name: str, n: int = 1) -> None:
    with _lock:
        _counters[name] += n


def reset() -> None:
    """Clear decisions / q-errors / in-process observations (tests)."""
    with _lock:
        _counters.clear()
        _qerr.clear()
        _observed.clear()


def set_estimate_injector(fn) -> None:
    """Test hook: ``fn(node) -> Optional[rows]`` forces mis-estimates so
    tests can assert each adaptive correction actually triggers. None
    uninstalls."""
    global _injector
    with _lock:
        _injector = fn


# ---------------------------------------------------------------------------
# stats.estimate() override (observed > injected > persisted)
# ---------------------------------------------------------------------------

def estimate_override(node) -> Optional[float]:
    """Installed as plan.stats._runtime_override: returns observed rows
    for a subplan, or None to keep the structural estimate."""
    if not enabled():
        return None
    try:
        key = node.key()
    except Exception:
        return None
    with _lock:
        got = _observed.get(key)
    if got is not None:
        return got
    if _injector is not None:
        inj = _injector(node)
        if inj is not None:
            return float(inj)
    try:
        from bodo_tpu.runtime import stats_store
        return stats_store.get_store().lookup(stats_store.fingerprint(node))
    except Exception:
        return None


# ---------------------------------------------------------------------------
# stage-boundary observation
# ---------------------------------------------------------------------------

def observe_stage(node, table) -> None:
    """Record a completed stage's actual cardinality (called from
    plan/physical._exec after each stage materializes). First
    observation of a plan key also records its q-error against the
    estimate the planner would have used."""
    if not enabled() or _suspended():
        return
    try:
        actual = int(table.nrows)
        from bodo_tpu.plan import stats as stats_mod
        est, _ = stats_mod.estimate(node)
        key = node.key()
    except Exception:
        return
    with _lock:
        first = key not in _observed
        if first and len(_observed) >= _MAX_OBSERVED:
            _observed.clear()  # unbounded plans: drop, don't leak
        _observed[key] = float(actual)
        if first and len(_qerr) < _MAX_QERR:
            q = max(max(est, 1.0) / max(actual, 1.0),
                    max(actual, 1.0) / max(est, 1.0))
            _qerr.append({"stage": type(node).__name__,
                          "est": float(est), "actual": actual,
                          "q": float(q)})
    try:
        from bodo_tpu.runtime import stats_store
        from bodo_tpu.runtime.memory_governor import table_device_bytes
        stats_store.get_store().record(
            stats_store.fingerprint(node), actual,
            table_device_bytes(table))
    except Exception:
        pass


def observe_batch(table) -> None:
    """Streaming executors report every pushed batch (fill statistics
    show up as aqe:stream:* counters)."""
    if not enabled():
        return
    with _lock:
        _counters["stream:batches"] += 1
        _counters["stream:rows"] += int(table.nrows)


def observe_shuffle(t, key_cols) -> None:
    """Sample a shuffle's key distribution (the per-key skew sketch at
    the all_to_all boundary); a dominant key bumps aqe:skew:detected."""
    if not enabled() or _suspended():
        return
    if t.nrows < max(config.aqe_skew_min_rows, 1) or len(key_cols) != 1:
        return
    try:
        c = t.column(key_cols[0])
        if c.dictionary is None and \
                np.dtype(c.dtype.numpy).kind not in "iu":
            return
        vals, n = _sample_key(t, key_cols[0], 4096)
        if n == 0:
            return
        _, cnts = np.unique(vals, return_counts=True)
        if float(cnts.max()) / float(n) >= config.aqe_skew_frac:
            count("skew:detected")
    except Exception:
        return


def _sample_key(t, name: str, m: int) -> Tuple[np.ndarray, int]:
    """Host sample of a 1D table's key column: a prefix slice per shard
    (biased only when rows are key-sorted — fine for a sketch). Returns
    (non-null sampled values, total sampled rows incl. nulls)."""
    import jax
    c = t.column(name)
    per = t.shard_capacity
    take = max(m // max(t.num_shards, 1), 32)
    datas, valids = [], []
    total = 0
    for s in range(t.num_shards):
        n = min(int(t.counts[s]), take)
        if n <= 0:
            continue
        sl = slice(s * per, s * per + n)
        datas.append(np.asarray(jax.device_get(c.data[sl])))
        if c.valid is not None:
            valids.append(np.asarray(jax.device_get(c.valid[sl])))
        total += n
    if not datas:
        return np.empty(0), 0
    d = np.concatenate(datas)
    if c.valid is not None:
        d = d[np.concatenate(valids)]
    return d, total


# ---------------------------------------------------------------------------
# broadcast promote / demote
# ---------------------------------------------------------------------------

def _budget() -> int:
    if not config.mem_governor:
        return 0
    try:
        from bodo_tpu.runtime.memory_governor import governor
        return int(governor().derived_budget())
    except Exception:
        return 0


def _table_bytes(t) -> int:
    try:
        from bodo_tpu.runtime.memory_governor import table_device_bytes
        return int(table_device_bytes(t))
    except Exception:
        return 0


def join_broadcast_decision(build, probe) -> bool:
    """The broadcast-vs-shuffle gate for a 1D-both join (True = gather
    the build side, skipping both shuffles). With AQE off this is the
    legacy rows-only heuristic; with AQE on the observed build BYTES
    are checked against the governor budget, promoting large-but-narrow
    builds past the rows threshold and demoting wide ones under it."""
    static = (build.nrows <= config.bcast_join_threshold
              and probe.nrows > 4 * build.nrows)
    if not enabled() or _suspended():
        return static
    if probe.nrows <= 4 * build.nrows:
        return False  # probe too small for a broadcast to pay off
    budget = _budget()
    if budget <= 0:
        return static
    fits = _table_bytes(build) <= config.aqe_bcast_frac * budget
    if fits and not static:
        count("join:promote_broadcast")
    elif static and not fits:
        count("join:demote_broadcast")
    return fits


def should_demote_broadcast(build) -> bool:
    """A REPLICATED build side planned for a broadcast join whose
    observed bytes blow the budget: shard it (shuffle join) instead of
    keeping a full copy per device."""
    if not enabled() or _suspended():
        return False
    budget = _budget()
    if budget <= 0:
        return False
    from bodo_tpu.parallel import mesh as mesh_mod
    if mesh_mod.num_shards() <= 1 or \
            build.nrows < mesh_mod.num_shards():
        return False
    if _table_bytes(build) <= config.aqe_bcast_frac * budget:
        return False
    count("join:demote_broadcast")
    return True


# ---------------------------------------------------------------------------
# hot-key split before the join shuffle
# ---------------------------------------------------------------------------

def try_skew_split_join(left, right, left_on, right_on, how, suffixes,
                        null_equal: bool):
    """Break shuffle skew: sample the probe's join key; rows carrying a
    hot key (>= aqe_skew_frac of the sample) split off and broadcast-
    join against the hot subset of the build side, while the cold
    remainder takes the normal shuffle join. The two halves append
    shard-wise (every probe row lands in exactly one half, so inner/left
    semantics — including null and unmatched keys, which stay cold —
    are preserved). Returns the joined Table or None (not applicable)."""
    if not enabled() or _suspended():
        return None
    if how not in ("inner", "left") or len(left_on) != 1:
        return None
    if left.nrows < max(config.aqe_skew_min_rows, 1):
        return None
    from bodo_tpu.parallel import mesh as mesh_mod
    if mesh_mod.num_shards() <= 1:
        return None
    lk, rk = left_on[0], right_on[0]
    try:
        c = left.column(lk)
        # integer-typed, null-free probe keys only: the hot/cold Expr
        # masks have no Kleene-logic form, so a nullable key would drop
        # its null rows from BOTH halves
        if c.valid is not None or c.dictionary is not None or \
                np.dtype(c.dtype.numpy).kind not in "iu":
            return None
        vals, n = _sample_key(left, lk, 8192)
        if n == 0:
            return None
        uniq, cnts = np.unique(vals, return_counts=True)
        hot = uniq[cnts.astype(np.float64) / n >= config.aqe_skew_frac]
    except Exception:
        return None
    if hot.size == 0 or hot.size > 4:
        return None
    count("skew:detected")

    from bodo_tpu import relational as R
    from bodo_tpu.plan.expr import ColRef, IsIn, UnOp
    hotvals = tuple(np.asarray(hot).tolist())
    hot_pred = IsIn(ColRef(lk), hotvals)
    right_hot = R.filter_table(right, IsIn(ColRef(rk), hotvals))
    if right_hot.nrows > config.bcast_join_threshold:
        count("skew:bailed")  # build itself is hot: broadcast too big
        return None
    left_hot = R.filter_table(left, hot_pred)
    if left_hot.nrows == 0:
        return None  # sample found heat the full data doesn't have
    left_cold = R.filter_table(left, UnOp("~", hot_pred))
    count("skew:split_join")
    hot_out = R.join_tables(left_hot, right_hot.gather(), left_on,
                            right_on, how, suffixes,
                            null_equal=null_equal)
    if left_cold.nrows == 0:
        return hot_out
    cold_out = R._join_sharded(left_cold, right, left_on, right_on, how,
                               suffixes, null_equal=null_equal)
    return _append_splits(hot_out, cold_out)


def _append_splits(a, b):
    """Union the hot/cold join halves, shard-wise when possible."""
    from bodo_tpu import relational as R
    from bodo_tpu.table.table import ONED
    if set(a.names) == set(b.names) and a.names != b.names:
        b = b.select(a.names)
    if a.distribution == ONED and b.distribution == ONED:
        try:
            from bodo_tpu.plan.streaming_sharded import (
                _dicts_compatible, append_sharded)
            if _dicts_compatible(a, b):
                return append_sharded(a, b)
        except Exception:
            pass
    return R.concat_tables([a, b])


# ---------------------------------------------------------------------------
# streaming-batch coalescing
# ---------------------------------------------------------------------------

def coalesce_batches(src, sharded: bool):
    """Merge consecutive undersized streaming batches (post-filter) so
    downstream per-batch kernels see reasonably full batches instead of
    a long tail of near-empty ones. Order-preserving; an unmergeable
    pair (dict drift, schema drift) flushes and starts over."""
    if not enabled() or config.aqe_coalesce_frac <= 0:
        yield from src
        return
    target = max(int(config.streaming_batch_size
                     * min(config.aqe_coalesce_frac, 1.0)), 1)
    pend = None
    for b in src:
        if pend is not None:
            merged = _merge_batches(pend, b, sharded)
            if merged is None:
                yield pend
                pend = None
            else:
                count("stream:coalesced")
                pend = merged
                if pend.nrows >= target:
                    yield pend
                    pend = None
                continue
        if b.nrows >= target:
            yield b
        else:
            pend = b
    if pend is not None:
        yield pend


def _merge_batches(a, b, sharded: bool):
    if a.names != b.names:
        return None
    try:
        if sharded:
            from bodo_tpu.plan.streaming_sharded import (
                _dicts_compatible, append_sharded)
            from bodo_tpu.table.table import ONED
            if a.distribution != ONED or b.distribution != ONED or \
                    not _dicts_compatible(a, b):
                return None
            return append_sharded(a, b)
        from bodo_tpu import relational as R
        return R.concat_tables([a, b])
    except Exception:
        return None


# ---------------------------------------------------------------------------
# mid-plan re-optimization
# ---------------------------------------------------------------------------

def maybe_reoptimize_join(node, exec_cb):
    """Re-run the greedy join ordering once the chain's leaf relations
    have OBSERVED cardinalities: the leaves execute first (they are
    needed under any order, and their results memoize on the nodes),
    then ``optimizer.reorder_joins`` re-plans with observations
    overriding the estimates. Returns the replacement subplan when the
    order changed, else None."""
    if not enabled() or _suspended():
        return None
    if getattr(node, "_aqe_reopt", False):
        return None
    node._aqe_reopt = True
    from bodo_tpu.plan import logical as L
    if node.how != "inner":
        return None

    rels: list = []

    def chain(n) -> None:
        if isinstance(n, L.Join) and n.how == "inner" and \
                n.null_equal == node.null_equal and \
                n.suffixes == node.suffixes:
            chain(n.left)
            rels.append(n.right)
        else:
            rels.append(n)

    chain(node)
    if len(rels) < 3:
        return None
    for r in rels:
        exec_cb(r)
    from bodo_tpu.plan import optimizer
    new = optimizer.reorder_joins(node)
    if new is node:
        return None
    try:
        if new.key() == node.key():
            return None
    except Exception:
        return None
    _mark_reoptimized(new)
    count("reoptimize:join_order")
    return new


def _mark_reoptimized(n) -> None:
    from bodo_tpu.plan import logical as L
    if isinstance(n, L.Join):
        n._aqe_reopt = True
    for c in n.children:
        _mark_reoptimized(c)


# ---------------------------------------------------------------------------
# reporting
# ---------------------------------------------------------------------------

def stats() -> dict:
    """Decision counters + per-query q-error summary (tracing dump /
    profile aqe:* rows and the bench JSON `aqe` section)."""
    with _lock:
        qs = sorted(e["q"] for e in _qerr)
        qe: dict = {"count": len(qs)}
        if qs:
            qe.update({
                "mean": round(sum(qs) / len(qs), 3),
                "p50": round(qs[len(qs) // 2], 3),
                "p90": round(qs[min(int(len(qs) * 0.9), len(qs) - 1)], 3),
                "max": round(qs[-1], 3),
                "worst": [
                    {"stage": e["stage"], "est": round(e["est"], 1),
                     "actual": e["actual"], "q": round(e["q"], 3)}
                    for e in sorted(_qerr, key=lambda e: -e["q"])[:5]],
            })
        return {"enabled": enabled(),
                "decisions": {k: int(v)
                              for k, v in sorted(_counters.items())},
                "q_error": qe}


# install the estimate override once, at import (physical.py imports
# this module, so any execution path activates it; the hook itself
# checks config.aqe per call)
from bodo_tpu.plan import stats as _stats_mod  # noqa: E402

_stats_mod._runtime_override = estimate_override
