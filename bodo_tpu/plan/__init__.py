"""Logical plan layer: expression IR, plan nodes, optimizer.

Analogue of the reference's LazyPlan node set and expression nodes
(bodo/pandas/plan.py:44-1060) — but optimized by our own rules instead of
the vendored DuckDB optimizer.
"""
