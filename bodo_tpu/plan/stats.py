"""Cardinality estimation for plan nodes.

Replaces the role of the reference's vendored-DuckDB cost model
(bodo/pandas/plan.py get_plan_cardinality, _plan.cpp) with a compact
estimator: exact row counts from scan metadata (parquet footers are
free), textbook selectivity factors for predicates, and the
|L|·|R|/max(ndv) join formula with ndv(key) approximated by the raw row
count of the smaller (primary-key) side.

`estimate(node)` returns (est_rows, raw_rows): est is the post-filter
expectation, raw the unfiltered size of the underlying relation —
the pair is what the greedy join-ordering needs to tell "small because
the table is small" from "small because a filter is selective".
"""

from __future__ import annotations

from typing import Dict, Set, Tuple

from bodo_tpu.plan import logical as L
from bodo_tpu.plan.expr import (BinOp, Expr, IsIn, StrPredicate, UnOp)

# runtime-stats override installed by plan/adaptive.py:
# fn(node) -> Optional[observed rows]; estimate() consults it first so
# executed subplans feed their ACTUAL cardinality back into planning.
_runtime_override = None

# cached row counts keyed by the dataset's content signature (resolved
# file list + mtimes) — an overwritten dataset changes signature and
# naturally misses instead of reusing stale counts
_pq_rows_cache: Dict[Tuple, int] = {}
_warned_unknown: Set[str] = set()


def _dataset_sig(path) -> Tuple[Tuple, Tuple]:
    """(files, (mtime, size) stamps) of a parquet dataset — the
    row-count cache key and the persistent stats store's content
    signature. Built from the I/O layer's shared file signatures
    (io/parquet.file_signature), the same identity that keys the footer
    cache, so one stat() serves pushdown, planning, and AQE."""
    from bodo_tpu.io.parquet import dataset_signature
    sigs = dataset_signature(path)
    return (tuple(s[0] for s in sigs),
            tuple((s[1], s[2]) for s in sigs))


def _note_unknown(path) -> None:
    """One-time note (tracing + verbose log) when the 1M-row unknown
    fallback fires — a silently wrong scan estimate is the single worst
    input to join ordering."""
    key = str(path)
    if key in _warned_unknown:
        return
    _warned_unknown.add(key)
    from bodo_tpu.utils import tracing
    from bodo_tpu.utils.logging import log
    with tracing.event("stats_unknown_fallback", path=key):
        pass
    log(1, f"stats: no row count for {key}; assuming 1,000,000 rows")


def _parquet_rows(path) -> int:
    try:
        sig = _dataset_sig(path)
    except Exception:
        _note_unknown(path)
        return 1_000_000  # unknown: assume big; don't cache the guess
    hit = _pq_rows_cache.get(sig)
    if hit is not None:
        return hit
    try:
        # footers come from the shared cache — a plan whose scan already
        # read the data pays nothing here
        from bodo_tpu.io.parquet import footer_metadata
        n = sum(footer_metadata(f, sig=(f, *stamp)).num_rows
                for f, stamp in zip(sig[0], sig[1]))
    except Exception:
        _note_unknown(path)
        return 1_000_000
    _pq_rows_cache[sig] = n
    return n


def selectivity(e: Expr) -> float:
    """Textbook predicate selectivity factors (System R defaults)."""
    if isinstance(e, BinOp):
        if e.op == "&":
            return selectivity(e.left) * selectivity(e.right)
        if e.op == "|":
            sl, sr = selectivity(e.left), selectivity(e.right)
            return min(1.0, sl + sr - sl * sr)
        if e.op == "==":
            return 0.1
        if e.op in ("<", "<=", ">", ">="):
            return 0.3
        if e.op == "!=":
            return 0.9
    if isinstance(e, IsIn):
        return min(1.0, 0.1 * max(len(e.values), 1))
    if isinstance(e, StrPredicate):
        if e.kind == "eq_any":
            return min(1.0, 0.1 * max(len(e.pattern), 1))
        return 0.25
    if isinstance(e, UnOp) and e.op == "~":
        return max(0.0, 1.0 - selectivity(e.operand))
    return 0.25


def estimate(node: L.Node) -> Tuple[float, float]:
    """(estimated rows, raw underlying rows). When the adaptive layer
    has OBSERVED this subplan's cardinality (this process or the
    persistent stats store), the observation replaces the estimated
    component; the raw component keeps its structural meaning (ndv proxy
    for join_estimate) except for sources, where raw == rows."""
    if _runtime_override is not None:
        ov = _runtime_override(node)
        if ov is not None:
            est = max(float(ov), 1.0)
            if isinstance(node, (L.ReadParquet, L.ReadCsv, L.FromPandas)):
                return est, est
            return est, _estimate_impl(node)[1]
    return _estimate_impl(node)


def _estimate_impl(node: L.Node) -> Tuple[float, float]:
    if isinstance(node, L.ReadParquet):
        n = float(_parquet_rows(node.path))
        return n, n
    if isinstance(node, L.ReadCsv):
        return 100_000.0, 100_000.0  # csv has no cheap footer
    if isinstance(node, L.FromPandas):
        n = float(node.table.nrows)
        return n, n
    if isinstance(node, L.Filter):
        est, raw = estimate(node.child)
        return max(est * selectivity(node.predicate), 1.0), raw
    if isinstance(node, (L.Projection, L.Window, L.RankWindow,
                         L.AggWindow, L.Sort)):
        return estimate(node.child)
    if isinstance(node, L.Limit):
        est, raw = estimate(node.child)
        return min(float(node.n), est), raw
    if isinstance(node, (L.Aggregate, L.Distinct)):
        est, raw = estimate(node.child)
        return max(est ** 0.75, 1.0), max(est ** 0.75, 1.0)
    if isinstance(node, L.Reduce):
        return 1.0, 1.0
    if isinstance(node, L.Union):
        parts = [estimate(c) for c in node.children]
        return sum(p[0] for p in parts), sum(p[1] for p in parts)
    if isinstance(node, L.Join):
        le, lr = estimate(node.left)
        re_, rr = estimate(node.right)
        if node.how == "cross":
            return max(le * re_, 1.0), max(lr, rr)
        return join_estimate(le, lr, re_, rr), max(lr, rr)
    return 10_000.0, 10_000.0  # unknown node: neutral guess


def join_estimate(a_est: float, a_raw: float,
                  b_est: float, b_raw: float) -> float:
    """|A ⋈ B| ≈ |A|·|B| / max(ndv(key)); ndv(key) ≈ rows of the smaller
    raw side (its key is the primary key in the common FK-join shape)."""
    ndv = max(min(a_raw, b_raw), 1.0)
    return max(a_est * b_est / ndv, 1.0)
