"""Whole-stage fusion: adjacent plan stages compiled into ONE program.

Without fusion every relational op dispatches its own jitted kernel
with a host round-trip between plan nodes: a filter compacts its rows,
syncs the surviving count to the host, re-buckets, and only then does
the next projection or aggregate trace over the materialized
intermediate. The per-stage count syncs and intermediate buffers are
the flat tax the BENCH hot profiles show across the taxi/TPC-H
pipelines — the same observation that drives XLA whole-program fusion
in JAX and HPAT's whole-function parallel compilation: adjacent
operators should compile together so intermediates never materialize.

This module implements the plan-level version of that inversion:

  group formation   `plan_fusion_groups` walks the optimized plan and
                    greedily claims maximal chains of pipeline-
                    compatible nodes — [Filter|Projection]+ with an
                    optional dense-aggregate root — into FusionGroups.
                    Interior members must be single-parent and
                    unmaterialized (a shared or cached subplan keeps
                    its own dispatch so other consumers still hit it).

  fused body        inside the compiled program the chain is LAZY: the
                    column tree and a row-validity mask travel through
                    the steps together. Filters AND into the mask (no
                    per-filter compaction, no count sync); projections
                    evaluate element-wise on the uncompacted tree (dead
                    rows compute garbage harmlessly, exactly like the
                    eval-then-mask order of relational.filter_table).
                    One `K.compact` runs at group exit — or ZERO when a
                    terminal dense Aggregate consumes the mask directly
                    via `relational.dense_agg_tail`, which routes the
                    MXU one-hot-matmul accumulate
                    (`ops/pallas_kernels.dense_accumulate`) into the
                    pipeline when the gate admits it.

  sharding          derived from the shardcheck REP/DIST lattice
                    (`analysis/plan_validator.check_fusion_boundary`
                    cross-checks the runtime input against it): REP
                    input -> plain `jax.jit`; 1D input -> the program
                    wraps in `shard_map` with explicit P(data_axis)
                    in/out specs, one count sync for the WHOLE group.
                    A fused terminal aggregate requires REP input; over
                    1D input the group degrades to partial fusion (the
                    chain fuses, `relational.groupby_agg` finishes).

  donation          on accelerator backends the input tree is donated
                    (`donate_argnums`) when the input node is the
                    group's only consumer and not user-owned
                    (FromPandas buffers belong to the caller), so even
                    the group input buffer is recycled in-program.

  caching           compiled groups live in a FusionProgramCache keyed
                    by the group signature (op sequence + input
                    schema/dictionary fingerprints + distribution +
                    agg spec); compile time feeds the shared
                    bodo_tpu_jit_compile_seconds histogram.

  observability     the group root records a `fusion` annotation in
                    EXPLAIN ANALYZE (member ops, cache hit, compile
                    seconds, rows in/out); interior members record a
                    `fused->root` marker. AQE stage-boundary
                    observation still fires at the group edge (the
                    root's result is a normal stage result).

  lockstep          collectives fused INSIDE a program can no longer
                    fingerprint per-op at dispatch, so each compiled
                    group registers a manifest with
                    `analysis/lockstep.register_fusion_manifest` and a
                    multi-shard dispatch is sequence-numbered as ONE
                    composite collective via `lockstep.pre_fused`.

Failure policy (the chaos-test contract): build/trace-time problems —
unfusable expression shapes, schema walk failures, trace errors that
are neither OOM nor degradable — fall back silently to per-node
execution and negative-cache the group signature. RUNTIME dispatch
errors propagate so `physical._exec_with_oom_retry` and
`physical._try_degrade` classify them exactly as they would an unfused
stage; under a degraded (force-replicated) re-run the group gathers
its 1D input and re-dispatches the REP program.

Join-probe and shuffle boundaries fuse too: `plan_fusion_groups`
tries `plan/fusion_join.try_join_group` first, so a
[chain -> Join -> chain -> agg] region compiles into one program with
the hash-probe (against a device-resident cached build table) and —
for 1D probes with a terminal decomposable aggregate — the
partial-agg hash shuffle (`lax.all_to_all`) traced INSIDE the
shard_map body. See plan/fusion_join.py.

Disable with `BODO_TPU_FUSION=0` / `set_config(fusion=False)`; the
process-wide compile budget (`BODO_TPU_FUSION_MAX_COMPILES`) bounds
how many distinct programs one process may pin before new signatures
run unfused.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import time as _time
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from bodo_tpu.analysis import lockstep
from bodo_tpu.analysis import progcheck
from bodo_tpu.config import config
from bodo_tpu.ops import kernels as K
from bodo_tpu.parallel import collectives as C
from bodo_tpu.parallel import mesh as mesh_mod
from bodo_tpu.plan import expr as E
from bodo_tpu.plan import logical as L
from bodo_tpu.table import dtypes as dt
from bodo_tpu.table.table import Column, ONED, REP, Table
from bodo_tpu.runtime import xla_observatory as xobs
from bodo_tpu.utils.kernel_cache import FusionProgramCache
from bodo_tpu.utils.logging import log

# NOTE: bodo_tpu.relational imports this module at module level (for
# @fusion_stage), so relational/physical/shuffle may only be imported
# INSIDE functions here — a module-level import would cycle.

def _describe_sig(key):
    """Split a fusion-program signature into named facets so the
    program registry can attribute a retrace to the facet that changed
    (mesh vs schema vs plan steps vs donation flag)."""
    base = key[0]
    if base == "fusedchain" and len(key) == 6:
        _, mesh, schema, steps, dist, donate = key
        return base, {"mesh": xobs._short(mesh),
                      "schema": xobs._short(schema),
                      "dtype": tuple(c[1] for c in schema),
                      "steps": xobs._short(steps), "dist": dist,
                      "donate": bool(donate)}
    if base == "fusedagg" and len(key) == 9:
        (_, schema, steps, kn, aggs, sizes, los, use_mxu,
         donate) = key
        return base, {"schema": xobs._short(schema),
                      "dtype": tuple(c[1] for c in schema),
                      "steps": xobs._short((steps, kn, aggs)),
                      "shape": tuple(sizes),
                      "static": (xobs._short(los), bool(use_mxu)),
                      "donate": bool(donate)}
    return str(base), xobs.facets_from_sig(key)


_programs = FusionProgramCache(maxsize=config.kernel_cache_size,
                               subsystem="fusion",
                               describe=_describe_sig)

_stats = {"groups_planned": 0, "groups_executed": 0, "stream_chains": 0,
          "partial_agg": 0, "fallbacks": 0, "donated": 0,
          "budget_spent": 0,
          # scan batches entering fused chains straight off the device
          # decode path (io/device_decode.py) — no host round-trip
          # between ingest and the compiled chain body
          "device_scan_batches": 0}

# structural signatures whose trace failed: don't re-trace every query
_failed: set = set()

# XLA:CPU's JIT crashes once a process pins thousands of distinct
# compiled executables (the leak runtests.py works around by grouping
# test modules into subprocesses). Fusion programs draw from the same
# pool on top of the per-op kernels, so new-signature compiles stop
# after a process-wide budget; later groups run unfused, which is
# always correct. <0 disables the budget.
_max_compiles = int(os.environ.get("BODO_TPU_FUSION_MAX_COMPILES",
                                   "128"))
_n_compiles = 0


def _budget_compile(sig) -> None:
    """Consume one unit of the process-wide fusion compile budget, or
    raise FusionFallback once it is spent. When
    BODO_TPU_FUSION_COMPILE_LOG names a file, the signature is appended
    before the compile — the log survives an XLA compiler crash, which
    in-process stats do not."""
    global _n_compiles
    if _n_compiles >= _max_compiles >= 0 \
            or not xobs.try_spend("fusion"):
        _stats["budget_spent"] += 1
        raise FusionFallback("fusion compile budget spent")
    _n_compiles += 1
    path = os.environ.get("BODO_TPU_FUSION_COMPILE_LOG")
    if path:
        with open(path, "a") as f:
            f.write(repr(sig)[:500] + "\n")


def stats() -> dict:
    out = dict(_stats)
    out.update(_programs.stats())
    return out


def reset_stats() -> None:
    for k in _stats:
        _stats[k] = 0
    _programs.reset_stats()
    _failed.clear()


def clear_programs() -> None:
    """Drop every cached fusion program and return its compile budget:
    releasing the program references is what frees the underlying
    executables, so a caller starting from an empty cache (tests,
    long-lived sessions recycling state) gets the full budget back."""
    global _n_compiles
    _programs.clear()
    _n_compiles = 0
    xobs.reset_budget("fusion")


class FusionFallback(Exception):
    """Internal control flow: this group/chain cannot fuse (build or
    trace failure) — the caller falls back to per-node execution.
    Never escapes the fusion layer."""


def fusion_stage(fn):
    """Mark a function as a fusion-eligible traced stage body: it runs
    (or may run) INSIDE a compiled fusion program, where host sync —
    `jax.device_get`, `.to_pandas()`, `block_until_ready` — is illegal.
    The shardcheck `fusion-host-call` lint rule audits every function
    carrying this decorator."""
    fn.__fusion_stage__ = True
    return fn


# ---------------------------------------------------------------------------
# fusability gates
# ---------------------------------------------------------------------------

# expressions whose evaluation is host-side by construction (dictionary
# rewrites / host formatting in relational.assign_columns) — they can
# never run inside a compiled body
_HOST_EXPRS = (E.DictMap, E.ToChar, E.StrConcat, E.StrToList, E.NestedFn)


def _expr_fusable(e: E.Expr, schema) -> bool:
    """Can `e` evaluate inside a fused body with plain eval_expr?
    String-PRODUCING outputs are only fusable as bare column passthrough
    (the dictionary re-attaches host-side from the input column);
    string-CONSUMING nodes (StrPredicate/StrLen/...) bake their host
    LUT at trace time and are fine."""
    if E.contains_expr(e, _HOST_EXPRS):
        return False
    if isinstance(e, E.CodeLUT) or E.codelut_misplaced(e):
        return False
    try:
        d = E.infer_dtype(e, schema)
    except Exception:  # noqa: BLE001 - unknown shape -> not fusable
        return False
    if d is dt.STRING and not isinstance(e, E.ColRef):
        return False
    if getattr(d, "kind", "") in ("list", "struct", "map") and \
            not isinstance(e, E.ColRef):
        return False
    return True


def _node_fusable(node: L.Node) -> bool:
    if isinstance(node, L.Filter):
        return _expr_fusable(node.predicate, node.child.schema)
    if isinstance(node, L.Projection):
        return all(_expr_fusable(e, node.child.schema)
                   for _, e in node.exprs)
    return False


# ops the dense aggregate tail cannot finish in one segment pass
_UNFUSABLE_AGG = ("nunique", "mode", "median")


def _agg_fusable(node: L.Aggregate) -> bool:
    if not node.keys:
        return False
    for _, op, _ in node.aggs:
        if op in _UNFUSABLE_AGG or op.startswith(("q:", "quantile_",
                                                  "listagg")):
            return False
    return True


# ---------------------------------------------------------------------------
# group formation
# ---------------------------------------------------------------------------

class FusionGroup:
    """One fusable region of the plan.

    chain    [Filter|Projection] members, BOTTOM-UP (chain[0] consumes
             the input node's table)
    agg      optional terminal Aggregate (the group root when present)
    root     the member whose _exec dispatch runs the whole group
    input    the plan node below the group (executed normally)
    donate_ok  the input node has no consumer outside this group and
             its buffers are engine-owned (not FromPandas) — the
             compiled program may donate them
    """

    __slots__ = ("chain", "agg", "root", "input", "donate_ok")

    def __init__(self, chain, agg, input_node, donate_ok):
        self.chain = list(chain)
        self.agg = agg
        self.root = agg if agg is not None else self.chain[-1]
        self.input = input_node
        self.donate_ok = bool(donate_ok)

    @property
    def members(self):
        """Members root-first (display order)."""
        out = ([self.agg] if self.agg is not None else [])
        out.extend(reversed(self.chain))
        return out

    def member_ops(self) -> Tuple[str, ...]:
        return tuple(type(m).__name__ for m in self.members)


def plan_fusion_groups(root: L.Node) -> List[FusionGroup]:
    """Annotate the (optimized) plan with fusion groups and return
    them. Clears stale annotations from prior executions on EVERY node
    first — plan nodes are reused across queries via the session result
    cache, and a leftover group from a differently-shaped walk must
    never dispatch."""
    nodes: List[L.Node] = []
    seen = set()
    stack = [root]
    while stack:
        n = stack.pop()
        if id(n) in seen:
            continue
        seen.add(id(n))
        nodes.append(n)
        stack.extend(n.children)
    parents: Dict[int, int] = {}
    for n in nodes:
        n._fusion_group = None
        n._fusion_info = None
        for c in n.children:
            parents[id(c)] = parents.get(id(c), 0) + 1
    if not config.fusion:
        return []
    groups: List[FusionGroup] = []
    claimed = set()
    for n in nodes:  # roots precede their descendants (DFS preorder)
        if id(n) in claimed:
            continue
        g = None
        if config.fusion_join:
            # join groups first: a [chain -> Join -> chain -> agg]
            # region fuses across the join-probe boundary
            # (plan/fusion_join.py); the plain chain grouper below
            # would otherwise claim the above-join chain for itself
            from bodo_tpu.plan import fusion_join
            g = fusion_join.try_join_group(n, parents, claimed)
        if g is None:
            g = _try_group(n, parents)
        if g is None:
            continue
        for m in g.members:
            claimed.add(id(m))
        n._fusion_group = g
        groups.append(g)
    _stats["groups_planned"] += len(groups)
    return groups


def _try_group(node: L.Node, parents) -> Optional[FusionGroup]:
    agg = None
    top = node
    if isinstance(node, L.Aggregate) and _agg_fusable(node) and \
            node._cached is None:
        agg = node
        top = node.child
        # the chain below an agg root is interior: single-parent,
        # unmaterialized
        if parents.get(id(top), 0) != 1 or top._cached is not None:
            return None
    chain_td: List[L.Node] = []  # top-down while walking
    cur = top
    while isinstance(cur, (L.Filter, L.Projection)) and \
            cur._cached is None and _node_fusable(cur):
        if cur is not node and parents.get(id(cur), 0) != 1:
            break  # interior member shared by another parent
        chain_td.append(cur)
        cur = cur.child
    if agg is not None:
        if not chain_td:
            return None  # bare aggregate: nothing to fuse with
    elif len(chain_td) < 2:
        return None  # a lone filter/projection fuses nothing
    input_node = cur
    donate_ok = (parents.get(id(input_node), 0) == 1
                 and not isinstance(input_node, L.FromPandas))
    return FusionGroup(list(reversed(chain_td)), agg, input_node,
                       donate_ok)


def stream_chain(node: L.Node):
    """Maximal fusable [Filter|Projection]+ chain rooted at `node` for
    the streaming executors' per-batch bodies. Returns (steps bottom-up,
    source node) or None when fewer than two stages fuse. Unlike plan
    groups, materialization/sharing is irrelevant: the streaming
    compiler already recomputes these stages per batch."""
    if not config.fusion:
        return None
    chain_td: List[L.Node] = []
    cur = node
    while isinstance(cur, (L.Filter, L.Projection)) and _node_fusable(cur):
        chain_td.append(cur)
        cur = cur.child
    if len(chain_td) < 2:
        return None
    return list(reversed(chain_td)), cur


# ---------------------------------------------------------------------------
# host-side metadata walk
# ---------------------------------------------------------------------------

def _subst(e: E.Expr, mapping: Dict[str, E.Expr]) -> E.Expr:
    """Substitute ColRefs through `mapping` (generic walk over the
    frozen Expr dataclasses) — composes a chain step's expression back
    into an expression over the group INPUT schema, which is what
    expr_range and the dense-agg key planner reason over."""
    if isinstance(e, E.ColRef):
        return mapping[e.name]
    if not dataclasses.is_dataclass(e):
        return e
    changes = {}
    for f in dataclasses.fields(e):
        v = getattr(e, f.name)
        if isinstance(v, E.Expr):
            nv = _subst(v, mapping)
            if nv is not v:
                changes[f.name] = nv
        elif isinstance(v, tuple) and any(isinstance(x, E.Expr)
                                          for x in v):
            changes[f.name] = tuple(
                _subst(x, mapping) if isinstance(x, E.Expr) else x
                for x in v)
    return dataclasses.replace(e, **changes) if changes else e


def _chain_meta(t: Table, steps):
    """Walk the chain on host: per-step (kind, payload, schema, dicts)
    snapshots for the traced body, plus the final column order, schema,
    dictionaries and input-composed expressions (for vrange/agg-range
    derivation)."""
    schema = {n: c.dtype for n, c in t.columns.items()}
    dicts = {n: c.dictionary for n, c in t.columns.items()
             if c.dictionary is not None}
    return _chain_meta_from(schema, dicts, steps)


def _chain_meta_from(schema, dicts, steps):
    """Schema-level `_chain_meta`: the fused-join planner
    (plan/fusion_join.py) walks the ABOVE-join chain over the JOINED
    schema, which exists only as names/dtypes/dictionaries at plan time
    — there is no host Table to hand to `_chain_meta`."""
    schema = dict(schema)
    dicts = dict(dicts)
    compose: Dict[str, E.Expr] = {n: E.ColRef(n) for n in schema}
    meta = []
    for s in steps:
        if isinstance(s, L.Filter):
            meta.append(("filter", s.predicate, dict(schema), dict(dicts)))
        else:
            meta.append(("project", tuple(s.exprs), dict(schema),
                         dict(dicts)))
            ns: Dict[str, dt.DType] = {}
            ndic: Dict[str, np.ndarray] = {}
            ncomp: Dict[str, E.Expr] = {}
            for n, e in s.exprs:
                d = E.infer_dtype(e, schema)
                ns[n] = d
                if isinstance(e, E.ColRef) and e.name in dicts:
                    ndic[n] = dicts[e.name]
                ncomp[n] = _subst(e, compose)
            schema, dicts, compose = ns, ndic, ncomp
    return meta, list(schema), schema, dicts, compose


def _steps_sig(steps) -> Tuple:
    out = []
    for s in steps:
        if isinstance(s, L.Filter):
            out.append(("filter", s.predicate.key()))
        else:
            out.append(("project",
                        tuple((n, e.key()) for n, e in s.exprs)))
    return tuple(out)


def _struct_sig(t: Table) -> Tuple:
    """Cross-rank-stable input signature for the lockstep group
    fingerprint: relational._sig's dictionary fingerprints use python
    hash() (randomized per process), so they are per-process cache
    detail, not identity."""
    return tuple((n, c.dtype.name, c.valid is not None)
                 for n, c in t.columns.items())


def _group_fp(fp_sig) -> str:
    """12-hex structural fingerprint, identical on every rank for the
    same plan shape (sha1, not hash(): python hashing is seeded)."""
    return hashlib.sha1(repr(fp_sig).encode()).hexdigest()[:12]


# ---------------------------------------------------------------------------
# traced bodies
# ---------------------------------------------------------------------------

@fusion_stage
def _chain_body(meta, in_names, tree, count):
    """Traced fused-chain body: carry (tree, mask) through the steps
    lazily — filters AND into the mask without compacting, projections
    evaluate element-wise on the uncompacted tree. Returns the final
    column tree and the live-row mask; the caller decides whether to
    compact (chain exit) or feed the mask to the dense aggregate tail
    (zero compactions)."""
    cap = tree[in_names[0]][0].shape[0]
    mask = K.row_mask(count, cap)
    return _chain_body_masked(meta, tree, mask)


@fusion_stage
def _chain_body_masked(meta, tree, mask):
    """`_chain_body` with a caller-supplied initial mask: fused-join
    programs thread the probe side's live-row mask (already ANDed with
    the join hit mask for inner joins) into the above-join chain, so
    the whole below-chain -> probe -> above-chain region shares ONE
    lazy (tree, mask) carry and at most one compaction."""
    cap = mask.shape[0]
    cur = dict(tree)
    for kind, payload, schema, dicts in meta:
        if kind == "filter":
            d, v = E.eval_expr(payload, cur, dicts, schema)
            if v is not None:
                d = d & v
            mask = mask & d
        else:
            new = {}
            for n, e in payload:
                if isinstance(e, E.ColRef):
                    new[n] = cur[e.name]
                    continue
                d, v = E.eval_expr(e, cur, dicts, schema)
                if d.ndim == 0:  # literal projection -> broadcast
                    d = jnp.broadcast_to(d, (cap,))
                new[n] = (d, v)
            cur = new
    return cur, mask


def _compile_chain(meta, in_names, out_names):
    """REP fused-chain program: (tree, count) -> (flat pairs, count).
    Outputs are POSITIONAL (out_names order) — dict pytrees come back
    from jit alphabetized, and a fused group must not silently reorder
    the root's schema."""

    has_filter = any(kind == "filter" for kind, _, _, _ in meta)

    def fused(tree, count):
        cur, mask = _chain_body(meta, in_names, tree, count)
        flat = []
        for n in out_names:
            d, v = cur[n]
            flat.append(d)
            flat.append(v)
        if not has_filter:
            # projection-only chain: the mask is still the trivial row
            # mask, so compaction would be a full-table copy the unfused
            # path never pays — pass the columns through untouched
            return tuple(flat), count
        out, cnt = K.compact(mask, tuple(flat))
        return out, cnt

    return fused


# ---------------------------------------------------------------------------
# chain execution (shared by plan groups and streaming batches)
# ---------------------------------------------------------------------------

def _run_chain(t: Table, steps, donate: bool = False) -> Table:
    """Dispatch the fused [Filter|Projection]+ chain over `t` as one
    compiled program (REP: jit; 1D: shard_map with one count sync).
    Raises FusionFallback on build/trace failure; runtime errors
    propagate for the resilience envelope."""
    from bodo_tpu import relational as R

    if not t.names:
        raise FusionFallback("empty schema")
    fp_sig = ("fusedchain", _struct_sig(t), _steps_sig(steps),
              t.distribution)
    if fp_sig in _failed:
        raise FusionFallback("negative-cached")
    try:
        meta, out_names, out_schema, out_dicts, compose = \
            _chain_meta(t, steps)
    except Exception as e:  # noqa: BLE001 - build failure -> unfused
        _failed.add(fp_sig)
        raise FusionFallback(str(e)) from e
    # Filter-less chains never change row alignment, so any output that
    # composes to a bare input ColRef can alias the input column around
    # the program — returning it from jit would make XLA copy the whole
    # buffer, a cost the unfused path never pays on passthroughs.
    has_filter = any(k == "filter" for k, _, _, _ in meta)
    passthrough: Dict[str, str] = {}
    if not has_filter:
        for n in out_names:
            ce = compose.get(n)
            if isinstance(ce, E.ColRef) and ce.name in t.columns:
                passthrough[n] = ce.name
    jit_names = [n for n in out_names if n not in passthrough]
    if not jit_names:
        # pure rename/reorder chain: no device work at all
        cols = {n: t.columns[passthrough[n]] for n in out_names}
        res = Table(cols, t.nrows, t.distribution, t.counts)
        res._fusion_compiled = False  # type: ignore[attr-defined]
        res._fusion_compile_s = 0.0  # type: ignore[attr-defined]
        res._fusion_donated = False  # type: ignore[attr-defined]
        return R.rebucket(res)
    m = mesh_mod.get_mesh()
    from bodo_tpu.parallel.shuffle import _mesh_key
    # donation is only sound when compaction makes fresh output buffers;
    # a filter-less chain aliases passthrough inputs into its output
    donate = bool(donate) and has_filter and t.distribution == REP and \
        jax.default_backend() in ("tpu", "gpu")
    sig = ("fusedchain", _mesh_key(m), R._sig(t), _steps_sig(steps),
           t.distribution, donate)
    fp = _group_fp(fp_sig)
    fn = _programs.lookup(sig)
    compiled = fn is None
    if compiled:
        _budget_compile(sig)
        in_names = list(t.names)
        if t.distribution == ONED:
            ax = config.data_axis
            body = _compile_chain(meta, in_names, jit_names)

            def sharded(tree, counts):
                out, cnt = body(tree, counts[0])
                return out, cnt[None]
            fn = jax.jit(C.smap(sharded, in_specs=(P(ax), P(ax)),
                                out_specs=(P(ax), P(ax)), mesh=m))
        else:
            fn = jax.jit(_compile_chain(meta, in_names, jit_names),
                         donate_argnums=(0,) if donate else ())
        lockstep.register_fusion_manifest(
            fp, _member_kinds(steps),
            1 if t.distribution == ONED and t.num_shards > 1 else 0)
        # static verification BEFORE first dispatch: collective
        # manifest + rank-invariance, donation audit, HBM estimate
        progcheck.check_jit(
            fn,
            (t.device_data(), t.counts_device())
            if t.distribution == ONED
            else (t.device_data(), jnp.asarray(t.nrows)),
            program=f"fused:{fp}", subsystem="fusion")

    # host-level fault point + composite-collective sequencing: the
    # fused program subsumes its members' dispatches, so the GROUP is
    # the unit chaos tests arm and peers must agree on
    if t.distribution == ONED and t.num_shards > 1:
        from bodo_tpu.runtime.resilience import maybe_inject
        maybe_inject("collective")
        lockstep.pre_fused(fp)

    from bodo_tpu.runtime import memory_governor as _mg
    t0 = _time.perf_counter()
    try:
        with _mg.preadmission_charge(f"fused:{fp}"):
            if t.distribution == ONED:
                out, cnts = fn(t.device_data(), t.counts_device())
                counts = np.asarray(jax.device_get(cnts)).reshape(-1) \
                    .astype(np.int64)
            else:
                out, cnt = fn(t.device_data(), jnp.asarray(t.nrows))
                counts = None
                nrows = int(jax.device_get(cnt))
    except Exception as e:  # noqa: BLE001 - classified below
        _classify_dispatch_error(e, fp_sig, compiled)
        raise FusionFallback(str(e)) from e
    dt_s = _time.perf_counter() - t0
    if compiled:
        _programs[sig] = fn
        progcheck.mark_checked(_programs.handle_for(sig))
        _programs.record_compile("fused_stage", dt_s)
    if donate:
        _stats["donated"] += 1

    cols: Dict[str, Column] = {}
    jit_idx = {n: i for i, n in enumerate(jit_names)}
    for n in out_names:
        src = passthrough.get(n)
        if src is not None:
            cols[n] = t.columns[src]
            continue
        i = jit_idx[n]
        vr = E.expr_range(compose[n], t.columns)
        cols[n] = Column(out[2 * i], out[2 * i + 1], out_schema[n],
                         out_dicts.get(n), vr)
    if counts is not None:
        res = Table(cols, int(counts.sum()), ONED, counts)
    else:
        res = Table(cols, nrows, REP, None)
    res._fusion_compiled = compiled  # type: ignore[attr-defined]
    res._fusion_compile_s = dt_s if compiled else 0.0
    res._fusion_donated = donate  # type: ignore[attr-defined]
    return R.rebucket(res)


def _member_kinds(steps, agg=None) -> Tuple[str, ...]:
    out = tuple("filter" if isinstance(s, L.Filter) else "project"
                for s in steps)
    if agg is not None:
        out = out + ("aggregate",)
    return out


def _classify_dispatch_error(e: Exception, fp_sig, compiled: bool) -> None:
    """First-call errors mix trace/compile failures with genuine
    runtime faults (jit compiles lazily). OOM and degradable errors
    must reach the resilience envelope untouched; anything else on a
    fresh program is a build failure -> negative-cache and fall back."""
    from bodo_tpu.runtime import resilience
    from bodo_tpu.runtime.memory_governor import governor
    if resilience.is_degradable(e) or governor().is_oom(e):
        raise e
    if not compiled:
        # a previously-working program failing at dispatch is a runtime
        # fault, not a build problem — propagate for classification
        raise e
    _failed.add(fp_sig)


# ---------------------------------------------------------------------------
# fused terminal aggregate planning (host side)
# ---------------------------------------------------------------------------

def _plan_dense_agg(t: Table, agg: L.Aggregate, out_schema, out_dicts,
                    compose):
    """Derive dense-slot ranges for the fused aggregate's keys from the
    chain metadata: dictionary sizes for strings, 0/1 for bools, static
    expr_range over the input-composed key expression, and a device
    min/max reduce on the INPUT table for bare passthrough ints (a
    superset range is sound — empty slots compact away). Returns
    (sizes, los, n_slots, use_mxu) or None -> partial fusion."""
    from bodo_tpu import relational as R
    kn = list(agg.keys)
    ranges: List[Optional[Tuple[int, int]]] = []
    reduce_cols: List[Tuple[int, str]] = []
    for i, k in enumerate(kn):
        kdt = out_schema.get(k)
        ce = compose.get(k)
        if kdt is None or ce is None:
            return None
        if kdt is dt.STRING:
            dic = out_dicts.get(k)
            if dic is None:
                return None
            ranges.append((0, max(len(dic) - 1, 0)))
        elif kdt.kind == "b":
            ranges.append((0, 1))
        elif kdt.kind in ("i", "u") or kdt is dt.DATE:
            r = E.expr_range(ce, t.columns)
            if r is not None:
                ranges.append((int(r[0]), int(r[1])))
            elif isinstance(ce, E.ColRef):
                reduce_cols.append((i, ce.name))
                ranges.append(None)
            else:
                return None
        else:
            return None
    if reduce_cols:
        exact, _ = R._key_ranges(t, [nm for _, nm in reduce_cols],
                                 use_bounds=False)
        for (i, _), r in zip(reduce_cols, exact):
            if r is None:
                return None
            ranges[i] = (int(r[0]), int(r[1]))
    sizes = tuple(hi - lo + 1 for lo, hi in ranges)
    los = tuple(lo for lo, _ in ranges)
    n_slots = 1
    for s in sizes:
        n_slots *= int(s)
        if n_slots > config.dense_groupby_max_slots:
            return None
    if not (0 < n_slots <= config.dense_groupby_max_slots
            and n_slots <= 2 * max(t.nrows, 1)):
        return None
    from bodo_tpu.ops import pallas_kernels as PK
    specs = tuple(op for _, op, _ in agg.aggs)
    val_dtypes = []
    for c, _, _ in agg.aggs:
        vdt = out_schema.get(c)
        if vdt is None:
            return None
        val_dtypes.append(vdt.numpy)
    use_mxu = ((PK.use_pallas() or PK.FORCE_INTERPRET)
               and n_slots <= PK.MAX_MATMUL_SLOTS
               and R.dense_mxu_ok(t.capacity, val_dtypes, specs))
    return sizes, los, n_slots, use_mxu


def _run_fused_agg(t: Table, group: FusionGroup, donate: bool):
    """Fully-fused group with a terminal dense Aggregate over REP
    input: zero intermediate compactions — the chain's mask feeds
    relational.dense_agg_tail directly. Returns a Table, or None when
    the dense gate misses (caller partially fuses)."""
    from bodo_tpu import relational as R

    steps, agg = group.chain, group.agg
    fp_sig = ("fusedagg", _struct_sig(t), _steps_sig(steps),
              tuple(agg.keys), tuple(agg.aggs))
    if fp_sig in _failed:
        raise FusionFallback("negative-cached")
    try:
        meta, out_names, out_schema, out_dicts, compose = \
            _chain_meta(t, steps)
        plan = _plan_dense_agg(t, agg, out_schema, out_dicts, compose)
    except FusionFallback:
        raise
    except Exception as e:  # noqa: BLE001 - build failure -> unfused
        _failed.add(fp_sig)
        raise FusionFallback(str(e)) from e
    if plan is None:
        return None
    sizes, los, n_slots, use_mxu = plan
    kn = list(agg.keys)
    vn = [c for c, _, _ in agg.aggs]
    specs = tuple(op for _, op, _ in agg.aggs)
    donate = bool(donate) and jax.default_backend() in ("tpu", "gpu")
    sig = ("fusedagg", R._sig(t), _steps_sig(steps), tuple(kn),
           tuple(agg.aggs), sizes, los, use_mxu, donate)
    fp = _group_fp(fp_sig)
    fn = _programs.lookup(sig)
    compiled = fn is None
    if compiled:
        _budget_compile(sig)
        in_names = list(t.names)
        need = list(dict.fromkeys(kn + vn))

        @fusion_stage
        def fused(tree, count):
            cur, mask = _chain_body(meta, in_names, tree, count)
            atree = {n: cur[n] for n in need}
            return R.dense_agg_tail(atree, mask, kn, vn, specs, sizes,
                                    los, n_slots, use_mxu)

        fn = jax.jit(fused, donate_argnums=(0,) if donate else ())
        lockstep.register_fusion_manifest(
            fp, _member_kinds(steps, agg), 0)
        progcheck.check_jit(
            fn, (t.device_data(), jnp.asarray(t.nrows)),
            program=f"fused:{fp}", subsystem="fusion")
    from bodo_tpu.runtime import memory_governor as _mg
    t0 = _time.perf_counter()
    try:
        with _mg.preadmission_charge(f"fused:{fp}"):
            out_keys, out_vals, ng = fn(t.device_data(),
                                        jnp.asarray(t.nrows))
        nrows = int(jax.device_get(ng))
    except Exception as e:  # noqa: BLE001 - classified below
        from bodo_tpu.runtime import resilience
        from bodo_tpu.runtime.memory_governor import governor
        if resilience.is_degradable(e) or governor().is_oom(e):
            raise
        if not compiled:
            raise  # cached program failing at dispatch = runtime fault
        if use_mxu:
            # pallas kernel failed on this backend: XLA scatter path for
            # the rest of the process (mirrors _groupby_agg_dense). No
            # negative cache — the retry signature has use_mxu=False.
            from bodo_tpu.ops import pallas_kernels as PK
            PK.disable_runtime("fused dense-agg matmul kernel failed")
            _programs.pop(sig, None)
        else:
            _failed.add(fp_sig)
        raise FusionFallback(str(e)) from e
    dt_s = _time.perf_counter() - t0
    if compiled:
        _programs[sig] = fn
        progcheck.mark_checked(_programs.handle_for(sig))
        _programs.record_compile("fused_stage", dt_s)
    if donate:
        _stats["donated"] += 1

    import types as _types
    cols: Dict[str, Column] = {}
    for kname, kd in zip(kn, out_keys):
        kdt = out_schema[kname]
        if kdt is dt.STRING:
            kd = kd.astype(np.int32)
        elif kdt.kind == "b":
            kd = kd.astype(bool)
        elif kd.dtype != kdt.numpy:
            kd = kd.astype(kdt.numpy)
        cols[kname] = Column(kd, None, kdt, out_dicts.get(kname))
    for (cname, op, oname), (vd, vv) in zip(agg.aggs, out_vals):
        src = _types.SimpleNamespace(dtype=out_schema[cname],
                                     dictionary=out_dicts.get(cname))
        cols[oname] = R._agg_out_col(src, op, vd, vv)
    res = R.shrink_to_fit(Table(cols, nrows, REP, None))
    res._fusion_compiled = compiled  # type: ignore[attr-defined]
    res._fusion_compile_s = dt_s if compiled else 0.0
    res._fusion_donated = donate  # type: ignore[attr-defined]
    res._fusion_pallas = use_mxu  # type: ignore[attr-defined]
    return res


# ---------------------------------------------------------------------------
# plan-group execution (called from physical._exec_inner)
# ---------------------------------------------------------------------------

def execute_group(group: FusionGroup, exec_child) -> Optional[Table]:
    """Execute one fusion group: run the input node normally, then
    dispatch the whole group as one compiled program. Returns the group
    ROOT's result table, or None to fall back to per-node execution.
    Runtime faults (OOM, degradable collectives, armed chaos faults)
    propagate — the stage-boundary envelope in physical.py owns them."""
    from bodo_tpu.plan import physical
    from bodo_tpu.utils import tracing

    t = exec_child(group.input)
    force_rep = getattr(physical._degrade_tls, "force_rep", False)
    if force_rep and t.distribution == ONED:
        # degraded re-run: dispatch the REP program over a gathered
        # copy; the input node's cached 1D table stays untouched
        # (snapshot/restore is _try_degrade's job)
        t = t.gather()
    if config.plan_validate:
        from bodo_tpu.analysis.plan_validator import (
            PlanInvariantError, check_fusion_boundary)
        try:
            check_fusion_boundary(group.input, t.distribution,
                                  force_rep=force_rep)
        except PlanInvariantError:
            _stats["fallbacks"] += 1
            return None
    donate = group.donate_ok and not force_rep

    with tracing.event("fused_group", members=len(group.chain)
                       + (1 if group.agg else 0)) as ev:
        try:
            if group.agg is not None and t.distribution == REP:
                out = _run_fused_agg(t, group, donate)
                if out is not None:
                    _finish_group(group, t, out)
                    if ev is not None:
                        ev["rows"] = out.nrows
                    return out
                _stats["partial_agg"] += 1
            # chain-only group, or partial fusion: fuse the chain and
            # let relational.groupby_agg finish a 1D/over-budget agg
            chained = _run_chain(t, group.chain, donate=donate)
        except FusionFallback as e:
            _stats["fallbacks"] += 1
            log(2, f"fusion fallback ({len(group.chain)} stages): {e}")
            return None
        if group.agg is not None:
            from bodo_tpu import relational as R
            out = R.groupby_agg(chained, group.agg.keys, group.agg.aggs)
            out._fusion_compiled = getattr(
                chained, "_fusion_compiled", False)
            out._fusion_compile_s = getattr(
                chained, "_fusion_compile_s", 0.0)
            out._fusion_donated = getattr(
                chained, "_fusion_donated", False)
        else:
            out = chained
        _finish_group(group, t, out)
        if ev is not None:
            ev["rows"] = out.nrows
    return out


def _finish_group(group: FusionGroup, t: Table, out: Table) -> None:
    """Post-dispatch bookkeeping: donation invalidation, EXPLAIN
    annotations, stats."""
    from bodo_tpu.plan import physical
    _stats["groups_executed"] += 1
    donated = getattr(out, "_fusion_donated", False)
    if donated:
        # the program consumed the input buffers: drop both caches so
        # an OOM retry recomputes the input from ITS children instead
        # of touching dead memory. The ledger confirms XLA actually
        # freed the donated buffers (vs silently copying).
        xobs.verify_donation(t)
        group.input._cached = None
        physical._result_cache.pop(group.input.key(), None)
    xobs.track_table(out, "fused_stage")
    compiled = bool(getattr(out, "_fusion_compiled", False))
    info = {
        "members": group.member_ops(),
        "cache_hit": not compiled,
        "compile_s": round(float(getattr(out, "_fusion_compile_s", 0.0)),
                           6),
        "rows_in": int(t.nrows),
        "rows_out": int(out.nrows),
    }
    if getattr(out, "_fusion_pallas", False):
        info["pallas"] = True
    group.root._fusion_info = info
    from bodo_tpu.utils import tracing
    if tracing.is_tracing():
        from bodo_tpu.plan import explain
        root_path = getattr(group.root, "_explain_path", None)
        for m in group.members:
            if m is group.root:
                continue
            # rows=0: interior results never materialize — that is the
            # point of the fusion
            explain.record(m, rows=0, wall_s=0.0,
                           fusion={"fused_into": root_path or "?"})
            # instant event so `tracing.profile()` still counts every
            # absorbed operator kind; the wall time lives on the root
            with tracing.event(type(m).__name__, fused=1):
                pass


# ---------------------------------------------------------------------------
# streaming per-batch fused chains
# ---------------------------------------------------------------------------

def fused_batches(steps, src, sharded: bool = False):
    """Map a batch iterator through the fused chain, one compiled
    program per batch signature (batches share it after the first).
    On the first build failure the WHOLE stream falls back to per-node
    stages; runtime faults propagate as usual."""
    _stats["stream_chains"] += 1

    def _unfused(b: Table) -> Table:
        from bodo_tpu import relational as R
        from bodo_tpu.plan.physical import apply_projection
        for s in steps:
            if isinstance(s, L.Filter):
                b = R.filter_table(b, s.predicate)
            else:
                b = apply_projection(b, s.exprs)
        return b

    def gen():
        fused_ok = True
        for b in src:
            if getattr(b, "_device_decoded", False):
                # scan batch arrived straight off the device decode
                # path: ingest -> fused chain with no host round-trip
                _stats["device_scan_batches"] += 1
            if fused_ok:
                try:
                    yield _run_chain(b, steps)
                    continue
                except FusionFallback:
                    fused_ok = False
                    _stats["fallbacks"] += 1
            yield _unfused(b)

    return gen()
