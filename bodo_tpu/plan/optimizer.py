"""Logical plan optimizer.

Replaces the reference's vendored DuckDB optimizer
(bodo/pandas/vendor/duckdb + plan_optimizer.pyx) with our own rule set
(SURVEY.md §7 M2: "a small logical optimizer... replacing vendored
DuckDB"). Rules:

  1. column pruning / projection pushdown — scans read only the columns
     any ancestor needs (the reference gets this from DuckDB + its
     TableColumnDelPass; here it lands directly in ReadParquet.columns).
  2. filter pushdown — filters slide below projections (with expression
     inlining) and joins (to the side that owns the columns), and merge
     with adjacent filters.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Set

from bodo_tpu.plan import logical as L
from bodo_tpu.plan.expr import (BinOp, Cast, ColRef, DictMap, DtField, Expr,
                                IsIn, Lit, RowUDF, StrLen, StrPredicate,
                                UnOp, Where, expr_columns)


def optimize(node: L.Node) -> L.Node:
    node = push_filters(node)
    node = reorder_joins(node)
    node = prune_columns(node, None)
    return node


# ---------------------------------------------------------------------------
# join reordering (frame-path merge chains)
# ---------------------------------------------------------------------------

def reorder_joins(node: L.Node) -> L.Node:
    """Greedy stats-driven reordering of left-deep INNER equi-join
    chains — the frame-path analogue of the SQL planner's join-graph
    ordering (reference: the vendored DuckDB join-order optimizer the
    frame path gets via bodo/pandas/plan.py get_plan_cardinality).
    pandas `merge` chains run in user order otherwise.

    Conservative: only chains of >= 3 relations, all inner, same
    null_equal, where every cross-relation shared column name is a
    consumed equal-name join key (so suffix logic can never fire
    differently under a new order). A final projection restores the
    original column order.

    The MAXIMAL chain is collected top-down BEFORE recursing, so a
    4-relation merge chain reorders as one unit (recursing first would
    reorder the inner 3-chain, wrap it in an order-restoring projection,
    and hide it from the outer pass)."""
    if not (isinstance(node, L.Join) and node.how == "inner"):
        return _rebuild(node, [reorder_joins(c) for c in node.children])

    rels: list = []
    edges: list = []  # (ri, rj, key_i, key_j)
    null_eq = node.null_equal
    orig_schema = list(node.schema)

    def collect(n) -> bool:
        if isinstance(n, L.Join) and n.how == "inner" and \
                n.null_equal == null_eq and \
                n.suffixes == node.suffixes:
            if not collect(n.left):
                return False
            ridx = len(rels)
            rels.append(n.right)
            for lk, rk in zip(n.left_on, n.right_on):
                # attribute the left key to its single owning relation
                # in the left subtree (suffixed/ambiguous names bail)
                cand = [i for i in range(ridx) if lk in rels[i].schema]
                if len(cand) != 1:
                    return False
                edges.append((cand[0], ridx, lk, rk))
            return True
        rels.append(n)
        return True

    def bail():
        # not reorderable as a unit: recurse into children normally
        # (sub-chains may still reorder on their own)
        return _rebuild(node, [reorder_joins(c) for c in node.children])

    if not collect(node) or len(rels) < 3:
        return bail()

    # suffix-safety: a name shared by two relations must be an
    # equal-name join key on an edge between exactly those relations
    key_names = {(e[0], e[1], e[2]) for e in edges if e[2] == e[3]}
    for i in range(len(rels)):
        for j in range(i + 1, len(rels)):
            shared = set(rels[i].schema) & set(rels[j].schema)
            for name in shared:
                if (i, j, name) not in key_names and \
                        (j, i, name) not in key_names:
                    return bail()

    # recurse into the chain LEAVES only (they are not part of the chain)
    rels = [reorder_joins(r) for r in rels]

    from bodo_tpu.plan.stats import estimate, join_estimate
    ests = [estimate(r) for r in rels]
    start = min(range(len(rels)), key=lambda i: ests[i][0])
    used = {start}
    plan = rels[start]
    cur_est, cur_raw = ests[start]
    consumed: set = set()
    while len(used) < len(rels):
        best = None
        for i in range(len(rels)):
            if i in used:
                continue
            kl, kr, ids = [], [], []
            for eid, (ri, rj, fi, fj) in enumerate(edges):
                if eid in consumed:
                    continue
                if ri in used and rj == i:
                    kl.append(fi)
                    kr.append(fj)
                    ids.append(eid)
                elif rj in used and ri == i:
                    kl.append(fj)
                    kr.append(fi)
                    ids.append(eid)
            if kl:
                out = join_estimate(cur_est, cur_raw, *ests[i])
                if best is None or out < best[0]:
                    best = (out, i, kl, kr, ids)
        if best is None:
            return bail()  # disconnected chain: keep user order
        out, i, kl, kr, ids = best
        plan = L.Join(plan, rels[i], kl, kr, "inner",
                      suffixes=node.suffixes, null_equal=null_eq)
        cur_est, cur_raw = out, max(cur_raw, ests[i][1])
        used.add(i)
        consumed.update(ids)

    if set(plan.schema) != set(orig_schema):
        return bail()  # suffix/drop divergence — bail to user order
    if list(plan.schema) != orig_schema:
        plan = L.Projection(plan, [(c, ColRef(c)) for c in orig_schema])
    return plan


# ---------------------------------------------------------------------------
# filter pushdown
# ---------------------------------------------------------------------------

def _substitute(e: Expr, mapping: Dict[str, Expr]) -> Expr:
    if isinstance(e, ColRef):
        return mapping.get(e.name, e)
    if isinstance(e, Lit):
        return e
    if isinstance(e, BinOp):
        return BinOp(e.op, _substitute(e.left, mapping),
                     _substitute(e.right, mapping))
    if isinstance(e, UnOp):
        return UnOp(e.op, _substitute(e.operand, mapping))
    if isinstance(e, Cast):
        return Cast(_substitute(e.operand, mapping), e.to)
    if isinstance(e, DtField):
        return DtField(e.field, _substitute(e.operand, mapping))
    if isinstance(e, IsIn):
        return IsIn(_substitute(e.operand, mapping), e.values)
    if isinstance(e, StrPredicate):
        return StrPredicate(e.kind, e.pattern, _substitute(e.operand, mapping))
    if isinstance(e, RowUDF):
        if e.operand is None:
            raise TypeError("row-mode UDF cannot be substituted")
        return RowUDF(e.func, e.out_dtype, _substitute(e.operand, mapping))
    if isinstance(e, DictMap):
        return DictMap(e.kind, e.params, _substitute(e.operand, mapping))
    if isinstance(e, StrLen):
        return StrLen(_substitute(e.operand, mapping))
    if isinstance(e, Where):
        return Where(_substitute(e.cond, mapping),
                     _substitute(e.iftrue, mapping),
                     _substitute(e.iffalse, mapping))
    # generic frozen-dataclass walk for the remaining node kinds (SQL
    # kernel-library exprs: MathFn/CodeLUT/StrConcat/DateAdd/...)
    import dataclasses
    if dataclasses.is_dataclass(e):
        changes = {}
        for f in dataclasses.fields(e):
            v = getattr(e, f.name)
            if isinstance(v, Expr):
                changes[f.name] = _substitute(v, mapping)
            elif isinstance(v, tuple) and any(isinstance(x, Expr)
                                              for x in v):
                changes[f.name] = tuple(
                    _substitute(x, mapping) if isinstance(x, Expr) else x
                    for x in v)
        return dataclasses.replace(e, **changes) if changes else e
    raise TypeError(f"substitute: {e}")


def push_filters(node: L.Node) -> L.Node:
    if isinstance(node, L.Filter):
        child = node.child
        pred = node.predicate
        if isinstance(child, L.Filter):
            # merge adjacent filters, keep pushing
            return push_filters(L.Filter(child.child,
                                         BinOp("&", child.predicate, pred)))
        if isinstance(child, L.Projection) and "*" not in expr_columns(pred):
            mapping = {n: e for n, e in child.exprs}
            pushed = L.Filter(push_filters(child.child),
                              _substitute(pred, mapping))
            return L.Projection(push_filters(pushed), child.exprs)
        if isinstance(child, L.Join):
            cols = expr_columns(pred)
            lcols = set(child.left.schema)
            rcols = set(child.right.schema)
            # only push when the names are unambiguous pass-throughs, and
            # only INTO a side the join preserves 1:1 (pushing into the
            # null-padded side of an outer/right join changes results)
            if cols <= lcols and not (cols & rcols) and \
                    child.how in ("inner", "left", "cross"):
                nl = push_filters(L.Filter(child.left, pred))
                return L.Join(nl, push_filters(child.right), child.left_on,
                              child.right_on, child.how, child.suffixes,
                              child.null_equal)
            if cols <= rcols and not (cols & lcols) and \
                    child.how in ("inner", "right", "cross"):
                nr = push_filters(L.Filter(child.right, pred))
                return L.Join(push_filters(child.left), nr, child.left_on,
                              child.right_on, child.how, child.suffixes,
                              child.null_equal)
        if isinstance(child, L.NonEquiJoin):
            # names are disjoint by construction; push into a preserved
            # side (inner: both; left: probe side only)
            cols = expr_columns(pred)
            if cols <= set(child.left.schema):
                nl = push_filters(L.Filter(child.left, pred))
                return L.NonEquiJoin(nl, push_filters(child.right),
                                     child.pred, child.how)
            if cols <= set(child.right.schema) and child.how == "inner":
                nr = push_filters(L.Filter(child.right, pred))
                return L.NonEquiJoin(push_filters(child.left), nr,
                                     child.pred, child.how)
        return L.Filter(push_filters(child), pred)
    # recurse
    return _rebuild(node, [push_filters(c) for c in node.children])


# ---------------------------------------------------------------------------
# column pruning
# ---------------------------------------------------------------------------

def prune_columns(node: L.Node, required: Optional[Set[str]]) -> L.Node:
    """required=None means 'all output columns are needed'."""
    if isinstance(node, (L.ReadParquet, L.ReadCsv)):
        if required is not None and set(node.schema) - required:
            cols = [n for n in node.schema if n in required]
            if not cols:  # keep one column — row counts need a spine
                cols = [next(iter(node.schema))]
            if isinstance(node, L.ReadParquet):
                return L.ReadParquet(node.path, cols)
            return L.ReadCsv(node.path, cols, node.parse_dates,
                             schema={n: node.schema[n] for n in cols})
        return node
    if isinstance(node, L.FromPandas):
        if required is not None and set(node.schema) - required:
            cols = [n for n in node.schema if n in required]
            if not cols:
                cols = [next(iter(node.schema))]
            pruned = L.FromPandas(node.table.select(cols))
            return pruned
        return node
    if isinstance(node, L.Projection):
        exprs = node.exprs if required is None else \
            [(n, e) for n, e in node.exprs if n in required]
        if not exprs:  # keep a spine column for row counts
            exprs = node.exprs[:1]
        need = set()
        for _, e in exprs:
            need |= expr_columns(e)
        if "*" in need:  # a RowUDF may read any column
            need = None
        return L.Projection(prune_columns(node.child, need), exprs)
    if isinstance(node, L.Filter):
        pcols = expr_columns(node.predicate)
        need = None if (required is None or "*" in pcols) else \
            (set(required) | pcols)
        return L.Filter(prune_columns(node.child, need), node.predicate)
    if isinstance(node, L.Aggregate):
        aggs = node.aggs if required is None else \
            [a for a in node.aggs if a[2] in required or a[2] in node.keys]
        need = set(node.keys) | {c for c, _, _ in aggs}
        return L.Aggregate(prune_columns(node.child, need), node.keys, aggs)
    if isinstance(node, L.Reduce):
        need = {c for c, _, _ in node.aggs}
        return L.Reduce(prune_columns(node.child, need), node.aggs)
    if isinstance(node, L.Join):
        lneed = rneed = None
        if required is not None:
            # un-suffix required names back to source columns
            overlap = (set(node.left.schema) & set(node.right.schema)) - \
                (set(node.left_on) & set(node.right_on))
            lneed, rneed = set(node.left_on), set(node.right_on)
            for n in node.left.schema:
                out = n + node.suffixes[0] if n in overlap else n
                if out in required:
                    lneed.add(n)
            for n in node.right.schema:
                out = n + node.suffixes[1] if n in overlap else n
                if out in required:
                    rneed.add(n)
        return L.Join(prune_columns(node.left, lneed),
                      prune_columns(node.right, rneed),
                      node.left_on, node.right_on, node.how, node.suffixes,
                      node.null_equal)
    if isinstance(node, L.NonEquiJoin):
        lneed = rneed = None
        if required is not None:
            need = set(required) | expr_columns(node.pred)
            lneed = {n for n in node.left.schema if n in need}
            rneed = {n for n in node.right.schema if n in need}
        return L.NonEquiJoin(prune_columns(node.left, lneed),
                             prune_columns(node.right, rneed),
                             node.pred, node.how)
    if isinstance(node, L.Sort):
        need = None if required is None else \
            (set(required) | set(node.by))
        return L.Sort(prune_columns(node.child, need), node.by,
                      node.ascending, node.na_last)
    if isinstance(node, L.Distinct):
        need = None if required is None else \
            (set(required) | set(node.subset))
        return L.Distinct(prune_columns(node.child, need), node.subset)
    if isinstance(node, L.Limit):
        return L.Limit(prune_columns(node.child, required), node.n)
    if isinstance(node, L.Union):
        # same required set on every arm keeps schemas aligned
        return L.Union([prune_columns(c, required) for c in node.children])
    return _rebuild(node, [prune_columns(c, None) for c in node.children])


def _rebuild(node: L.Node, children) -> L.Node:
    if children == node.children:
        return node
    import copy
    new = copy.copy(node)
    new.children = children
    return new
