"""Flight-recorder bundle triage: ``python -m bodo_tpu.doctor <bundle>``.

A bundle (runtime/telemetry.py ``dump_bundle``) is a self-contained
directory; this module answers the three questions a gang post-mortem
starts with, without the operator opening a single JSON file:

  1. WHERE did the gang stop — the stuck collective fingerprint
     (op@file:line) and the lagging/divergent rank, reconstructed from
     the per-rank lockstep side-channel logs;
  2. WAS it memory — the RSS / governor-spill timeline from the
     telemetry ring, rendered as a sparkline around the failure;
  3. WAS a rank dragging — per-dispatch arrival-skew triage from the
     lockstep timestamps names the straggler rank and the dominant
     collective site; with a merged trace in the bundle the
     critical-path analyzer's comm-vs-compute verdict is embedded too;
  4. WHAT was it running — the slowest recorded queries with their
     EXPLAIN ANALYZE trees.

``triage(bundle)`` returns the machine-readable verdict; ``render``
prints the human one. With no bundle argument the CLI picks the newest
bundle in the flight-recorder directory.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
from typing import Dict, List, Optional, Tuple

_LOCKSTEP_RE = re.compile(r"^lockstep_(?:e(\d+)_)?(\d+)\.log$")
_SHARD_RE = re.compile(r"^trace_shard_(\d+)\.json$")
_SPARK = " ▁▂▃▄▅▆▇█"


def _read_json(path: str):
    try:
        with open(path, "r") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _parse_lockstep_logs(
        bundle: str) -> Tuple[Dict[int, Dict[int, str]],
                              Dict[int, Dict[int, float]]]:
    """({rank: {seq: fingerprint}}, {rank: {seq: arrival_ts}}) from the
    copied side-channel logs. Lines are ``seq\\tfingerprint`` with an
    optional third arrival-timestamp field (newer logs); the timestamp
    map only carries entries whose line had one.

    Elastic re-meshes namespace the logs by mesh epoch
    (``lockstep_e<epoch>_<rank>.log``); the triage uses the HIGHEST
    epoch present — the mesh the gang died in — so pre-shrink streams
    from retired epochs don't masquerade as divergence."""
    by_epoch: Dict[int, Dict[int, str]] = {}
    try:
        names = os.listdir(bundle)
    except OSError:
        return {}, {}
    for name in names:
        m = _LOCKSTEP_RE.match(name)
        if not m:
            continue
        epoch = int(m.group(1) or 0)
        by_epoch.setdefault(epoch, {})[int(m.group(2))] = name
    logs: Dict[int, Dict[int, str]] = {}
    arrivals: Dict[int, Dict[int, float]] = {}
    if not by_epoch:
        return logs, arrivals
    for rank, name in by_epoch[max(by_epoch)].items():
        entries: Dict[int, str] = {}
        stamps: Dict[int, float] = {}
        try:
            with open(os.path.join(bundle, name), "r") as f:
                for line in f:
                    if "\t" not in line:
                        continue
                    parts = line.rstrip("\n").split("\t")
                    try:
                        seq = int(parts[0])
                    except ValueError:
                        continue
                    entries[seq] = parts[1]
                    if len(parts) > 2:
                        try:
                            stamps[seq] = float(parts[2])
                        except ValueError:
                            pass
        except OSError:
            continue
        logs[rank] = entries
        arrivals[rank] = stamps
    return logs, arrivals


def _triage_lockstep(logs: Dict[int, Dict[int, str]]) -> Optional[dict]:
    """Name the stuck collective and the lagging/divergent rank from
    the per-rank dispatch streams.

    * divergence: the first sequence number at which two ranks logged
      DIFFERENT fingerprints — mismatched control flow;
    * wedge: the rank(s) whose stream stops earliest; the "stuck
      collective" is what the leading ranks dispatched at the first
      sequence number the laggard never reached (every live peer is
      blocked inside it waiting for the laggard).
    """
    if not logs:
        return None
    heads = {r: (max(e) if e else 0) for r, e in logs.items()}
    head = max(heads.values())
    out: dict = {"heads": {str(r): h for r, h in sorted(heads.items())},
                 "head": head}
    for seq in range(1, head + 1):
        fps = {r: e[seq] for r, e in logs.items() if seq in e}
        if len(set(fps.values())) > 1:
            out["divergence"] = {
                "seq": seq,
                "fingerprints": {str(r): fp
                                 for r, fp in sorted(fps.items())}}
            break
    lag = min(heads.values())
    if lag < head:
        lagging = sorted(r for r, h in heads.items() if h == lag)
        out["lagging_ranks"] = lagging
        out["lagging_rank"] = lagging[0]
        out["lagging_last"] = logs[lagging[0]].get(lag)
        stuck = sorted({e[lag + 1] for e in logs.values()
                        if lag + 1 in e})
        if stuck:
            out["stuck_seq"] = lag + 1
            out["stuck_collective"] = stuck[0]
    return out


def _triage_comm(logs: Dict[int, Dict[int, str]],
                 arrivals: Dict[int, Dict[int, float]],
                 skew_floor: float = 0.01) -> Optional[dict]:
    """Arrival-skew attribution from the lockstep timestamps: for every
    dispatch sequence number that at least two ranks stamped, the rank
    arriving LAST is the one its peers waited for. Sums that lateness
    per rank to name the straggler, and per collective fingerprint to
    name the dominant site. Needs 3-field logs (older 2-field logs have
    no stamps → None)."""
    ranks = [r for r, st in arrivals.items() if st]
    if len(ranks) < 2:
        return None
    seqs = set()
    for r in ranks:
        seqs.update(arrivals[r])
    late_by_rank: Dict[int, float] = {r: 0.0 for r in ranks}
    last_count: Dict[int, int] = {r: 0 for r in ranks}
    site_skew: Dict[str, float] = {}
    n_skewed = 0
    for seq in sorted(seqs):
        stamped = {r: arrivals[r][seq] for r in ranks
                   if seq in arrivals[r]}
        if len(stamped) < 2:
            continue
        first = min(stamped.values())
        last_rank = max(stamped, key=lambda r: (stamped[r], r))
        skew = stamped[last_rank] - first
        late_by_rank[last_rank] += skew
        if skew > skew_floor:
            n_skewed += 1
            last_count[last_rank] += 1
            fp = logs.get(last_rank, {}).get(seq)
            if fp:
                site_skew[fp] = site_skew.get(fp, 0.0) + skew
    straggler = max(late_by_rank,
                    key=lambda r: (late_by_rank[r], -r))
    total_late = sum(late_by_rank.values())
    out = {
        "late_s_by_rank": {str(r): round(v, 6)
                           for r, v in sorted(late_by_rank.items())},
        "straggler_rank": straggler,
        "straggler_late_s": round(late_by_rank[straggler], 6),
        "n_skewed_dispatches": n_skewed,
        # confident: one rank owns most of the observed lateness and
        # the total is above scheduler-jitter noise
        "confident": total_late > skew_floor
        and late_by_rank[straggler] > 0.5 * total_late,
    }
    if site_skew:
        dom = max(site_skew, key=lambda s: (site_skew[s], s))
        out["dominant_site"] = dom
        out["dominant_site_skew_s"] = round(site_skew[dom], 6)
    return out


def _triage_memory(telemetry: Optional[dict]) -> Optional[dict]:
    samples = (telemetry or {}).get("samples") or []
    if not samples:
        return None
    rss = [int(s.get("rss_bytes", 0)) for s in samples]
    out: dict = {
        "samples": len(samples),
        "rss_last_bytes": rss[-1],
        "rss_peak_bytes": max(rss),
        "rss_series": rss[-60:],
    }
    mems = [s.get("mem") for s in samples if s.get("mem")]
    if mems:
        last = mems[-1]
        out["budget_bytes"] = last.get("budget_bytes", 0)
        out["spilled_bytes"] = last.get("spilled_bytes", 0)
        out["n_spills"] = last.get("n_spills", 0)
        out["oom_retries"] = last.get("oom_retries", 0)
        peak = max(m.get("peak_bytes", 0) for m in mems)
        out["operator_peak_bytes"] = peak
        if out["budget_bytes"]:
            out["peak_occupancy_frac"] = round(
                peak / out["budget_bytes"], 4)
    return out


def _triage_fleet(telemetry: Optional[dict]) -> Optional[dict]:
    """Fleet-controller triage from the bundle's telemetry samples:
    name the gangs that were shed/degraded/backed-off or dead at the
    last sample, plus routing/peering health counters."""
    samples = (telemetry or {}).get("samples") or []
    fleets = [s.get("fleet") for s in samples if s.get("fleet")]
    if not fleets:
        return None
    last = fleets[-1]
    gangs = last.get("gangs") or {}
    out: dict = {
        "gangs": len(gangs),
        "by_state": {},
        "rerouted": int(last.get("rerouted", 0)),
        "scrape_failures": int(last.get("scrape_failures", 0)),
        "peer_hits": int(last.get("peer_hits", 0)),
        "invalidations_broadcast": int(
            last.get("invalidations_broadcast", 0)),
    }
    unhealthy = []
    for gid, g in sorted(gangs.items()):
        state = g.get("state", "unknown")
        out["by_state"][state] = out["by_state"].get(state, 0) + 1
        if state != "ok":
            unhealthy.append({"gang": gid, "state": state,
                              "reason": g.get("reason")})
    if unhealthy:
        out["unhealthy_gangs"] = unhealthy
    return out


def _triage_views(telemetry: Optional[dict]) -> Optional[dict]:
    """Materialized-view triage from the bundle's telemetry samples:
    name the lagging view (worst staleness), surface the refresh mix
    and any maintenance rejections — a subscriber observing stale
    results traces back to either a rejected refresh (queue pressure)
    or a watcher that stopped ticking."""
    samples = (telemetry or {}).get("samples") or []
    vws = [s.get("views") for s in samples if s.get("views")]
    if not vws:
        return None
    last = vws[-1]
    out: dict = {
        "n_views": int(last.get("n_views", 0)),
        "dag_depth": int(last.get("dag_depth", 0)),
        "subscriptions": int(last.get("subscriptions", 0)),
        "refreshes_incremental": int(
            last.get("refreshes_incremental", 0)),
        "refreshes_full": int(last.get("refreshes_full", 0)),
        "refresh_ratio": float(last.get("refresh_ratio", 0.0)),
        "staleness_p99_s": float(last.get("staleness_p99_s", 0.0)),
    }
    if last.get("lagging_view"):
        out["lagging_view"] = last["lagging_view"]
    stale_series = [float(v.get("staleness_p99_s", 0.0)) for v in vws]
    if max(stale_series) > 0:
        out["staleness_peak_s"] = round(max(stale_series), 4)
    return out


def _triage_elastic(bundle: str, manifest: dict,
                    telemetry: Optional[dict]) -> Optional[dict]:
    """Elastic shrink-grow triage: the bundle's ``remesh.json`` (copied
    from the gang dir) is the recovery control record — which workers
    were evicted and why, the surviving mesh, and the checkpoint stage
    the suffix resumed from. Falls back to the last telemetry sample's
    ``elastic`` serving block when the bundle predates a re-mesh."""
    out: dict = {}
    rm = _read_json(os.path.join(bundle, "remesh.json"))
    if rm:
        out["epoch"] = rm.get("epoch", 0)
        out["evicted_workers"] = rm.get("evicted", [])
        out["resume_stage"] = rm.get("resume_stage")
        out["reason"] = rm.get("reason")
        out["survivors"] = sorted(
            int(w) for w in (rm.get("workers") or {}))
    ranks = manifest.get("ranks") or {}
    reasons = {int(r): d["evicted_reason"] for r, d in ranks.items()
               if d.get("evicted_reason")}
    if reasons:
        out["evicted_reasons"] = {str(r): v
                                  for r, v in sorted(reasons.items())}
    samples = (telemetry or {}).get("samples") or []
    els = [s.get("elastic") for s in samples if s.get("elastic")]
    if els:
        last = els[-1]
        out.setdefault("epoch", last.get("epoch", 0))
        out["capacity_frac"] = last.get("capacity_frac")
        out["shrinks"] = last.get("shrinks")
        out["grows"] = last.get("grows")
        out["resumes"] = last.get("resumes")
        if last.get("last_mttr_s") is not None:
            out["last_mttr_s"] = last["last_mttr_s"]
    return out or None


def _triage_xla(bundle: str) -> Optional[dict]:
    """Compile & device-memory triage from the bundle's registry dump:
    name the storming signature, rank retrace causes, surface the
    compile-cost hot list and the leaking creation site (if any)."""
    reg = _read_json(os.path.join(bundle, "xla_registry.json"))
    if not reg:
        return None
    summary = reg.get("summary") or {}
    out: dict = {
        "executables": summary.get("executables", 0),
        "compiles": summary.get("compiles", 0),
        "compile_s": summary.get("compile_s", 0.0),
        "retraces": summary.get("retraces", {}),
    }
    st = summary.get("storm") or {}
    if st.get("storming"):
        out["storm"] = {"signature": st.get("signature"),
                        "compiles_in_window": st.get(
                            "compiles_in_window"),
                        "window_s": st.get("window_s")}
    progs = reg.get("programs") or []
    hot = sorted(progs, key=lambda p: -float(p.get("compile_s", 0.0)))
    out["top_compile_cost"] = [
        {"subsystem": p.get("subsystem"), "base": p.get("base"),
         "compile_s": p.get("compile_s", 0.0),
         "dispatches": p.get("dispatches", 0),
         "retrace_cause": p.get("retrace_cause")}
        for p in hot[:5] if float(p.get("compile_s", 0.0)) > 0]
    leaks = reg.get("leaks") or {}
    if leaks.get("live_bytes"):
        by_op = leaks.get("by_op") or {}
        out["leak"] = {"live_bytes": leaks["live_bytes"],
                       "live_buffers": leaks.get("live_buffers", 0),
                       "by_op": by_op}
        if by_op:
            top = next(iter(by_op))
            out["leak"]["dominant_site"] = top
    led = summary.get("ledger") or {}
    if led.get("donation"):
        out["donation"] = led["donation"]
    return out


def _triage_progcheck(bundle: str) -> Optional[dict]:
    """Static-verifier triage: which programs carry violations (naming
    the offending eqn path), which are rank-variant, and the largest
    static HBM peak estimates. Reads the bundle's progcheck.json dump,
    falling back to the per-program verdicts in xla_registry.json."""
    pc = _read_json(os.path.join(bundle, "progcheck.json"))
    out: dict = {}
    if pc:
        st = pc.get("stats") or {}
        out["programs"] = st.get("programs", 0)
        viols = []
        for v in pc.get("violations") or []:
            viols.append({"program": v.get("program"),
                          "rule": v.get("rule"),
                          "eqn": v.get("eqn"),
                          "message": v.get("message")})
        out["violations"] = viols
        mans = pc.get("manifests") or {}
        out["rank_variant"] = sorted(
            p for p, m in mans.items()
            if not m.get("rank_invariant", True))
        hbm = sorted(((p, int(m.get("hbm_bytes", 0)))
                      for p, m in mans.items()), key=lambda kv: -kv[1])
        out["hbm_top"] = [{"program": p, "hbm_bytes": b}
                          for p, b in hbm[:3] if b > 0]
        return out if out.get("programs") else None
    reg = _read_json(os.path.join(bundle, "xla_registry.json"))
    if not reg:
        return None
    checked = [p for p in (reg.get("programs") or [])
               if p.get("progcheck")]
    if not checked:
        return None
    out["programs"] = len(checked)
    out["violations"] = [
        {"program": f"{p.get('subsystem')}:{p.get('base')}",
         "rule": v.get("rule"), "eqn": v.get("eqn"), "message": ""}
        for p in checked for v in p["progcheck"].get("violations", [])]
    out["rank_variant"] = sorted(
        f"{p.get('subsystem')}:{p.get('base')}" for p in checked
        if not p["progcheck"].get("rank_invariant", True))
    hbm = sorted(checked, key=lambda p: -int(
        p["progcheck"].get("hbm_bytes", 0)))
    out["hbm_top"] = [
        {"program": f"{p.get('subsystem')}:{p.get('base')}",
         "hbm_bytes": int(p["progcheck"].get("hbm_bytes", 0))}
        for p in hbm[:3] if int(p["progcheck"].get("hbm_bytes", 0)) > 0]
    return out


def triage(bundle: str) -> dict:
    """Machine-readable triage of one flight-recorder bundle."""
    if not os.path.isdir(bundle):
        raise FileNotFoundError(f"not a bundle directory: {bundle}")
    manifest = _read_json(os.path.join(bundle, "manifest.json")) or {}
    out: dict = {
        "bundle": os.path.abspath(bundle),
        "reason": manifest.get("reason", "unknown"),
        "time": manifest.get("iso_time"),
        "faults_armed": manifest.get("faults_armed", []),
    }
    if manifest.get("gang_id"):
        out["gang_id"] = manifest["gang_id"]
    ranks = manifest.get("ranks") or {}
    if ranks:
        out["ranks"] = ranks
        out["dead_ranks"] = sorted(
            int(r) for r, d in ranks.items()
            if d.get("state") in ("dead",))
        out["hung_ranks"] = sorted(
            int(r) for r, d in ranks.items()
            if d.get("state") in ("hung", "timeout"))
        # shrink-evicted ranks left the mesh deliberately (elastic
        # recovery) — a distinct class from dead/hung, not a failure
        out["evicted_ranks"] = sorted(
            int(r) for r, d in ranks.items()
            if d.get("state") == "evicted" or d.get("evicted"))
    logs, arrivals = _parse_lockstep_logs(bundle)
    out["lockstep"] = _triage_lockstep(logs)
    out["comm"] = _triage_comm(logs, arrivals)
    telem = _read_json(os.path.join(bundle, "telemetry.json"))
    out["memory"] = _triage_memory(telem)
    out["fleet"] = _triage_fleet(telem)
    out["views"] = _triage_views(telem)
    out["elastic"] = _triage_elastic(bundle, manifest, telem)
    out["xla"] = _triage_xla(bundle)
    out["progcheck"] = _triage_progcheck(bundle)
    slow = _read_json(os.path.join(bundle, "slow_queries.json")) or []
    out["slow_queries"] = [{"query_id": q.get("query_id"),
                            "wall_s": q.get("wall_s")} for q in slow]
    try:
        names = sorted(os.listdir(bundle))
    except OSError:
        names = []
    out["trace_shards"] = sorted(
        int(m.group(1)) for m in (_SHARD_RE.match(n) for n in names)
        if m)
    out["has_merged_trace"] = "trace_merged.json" in names
    if out["has_merged_trace"]:
        try:
            from bodo_tpu.analysis import critical_path
            trace = _read_json(
                os.path.join(bundle, "trace_merged.json"))
            if trace:
                out["critical_path"] = critical_path.analyze(trace)
        except Exception:  # noqa: BLE001 - triage is best-effort
            pass
    out["stack_dumps"] = [n for n in names
                          if n == "stacks.txt"
                          or n.startswith("stacks_")]
    return out


def _spark(vals: List[int]) -> str:
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    rng = (hi - lo) or 1
    return "".join(_SPARK[int((v - lo) / rng * (len(_SPARK) - 1))]
                   for v in vals)


def _fmt_bytes(n) -> str:
    v = float(n or 0)
    for unit in ("B", "KB", "MB", "GB"):
        if v < 1024 or unit == "GB":
            return f"{int(v)}B" if unit == "B" else f"{v:.1f}{unit}"
        v /= 1024
    return f"{v:.1f}GB"  # pragma: no cover


def render(t: dict) -> str:
    """Human-readable triage report."""
    lines = [f"FLIGHT RECORDER TRIAGE  {t['bundle']}",
             f"reason: {t['reason']}"
             + (f"  at {t['time']}" if t.get("time") else "")
             + (f"  gang {t['gang_id']}" if t.get("gang_id") else "")]
    if t.get("faults_armed"):
        lines.append(f"faults armed: {', '.join(t['faults_armed'])}")
    for r, d in sorted(t.get("ranks", {}).items(), key=lambda kv:
                       int(kv[0])):
        line = f"  rank {r}: {d.get('state')}"
        if d.get("returncode") is not None:
            line += f" rc={d['returncode']}"
        if d.get("evicted_reason"):
            line += f" (evicted: {d['evicted_reason']})"
        lines.append(line)
    ls = t.get("lockstep")
    if ls:
        lines.append("lockstep:")
        heads = ", ".join(f"rank {r} @ #{h}"
                          for r, h in ls["heads"].items())
        lines.append(f"  dispatch heads: {heads}")
        div = ls.get("divergence")
        if div:
            fps = "; ".join(f"rank {r}: {fp}"
                            for r, fp in div["fingerprints"].items())
            lines.append(f"  DIVERGENCE at dispatch #{div['seq']}: "
                         f"{fps}")
        if "lagging_rank" in ls:
            last = ls.get("lagging_last") or "nothing"
            lines.append(
                f"  lagging rank: {ls['lagging_rank']} stopped at "
                f"#{ls['heads'][str(ls['lagging_rank'])]} ({last})")
            if "stuck_collective" in ls:
                lines.append(
                    f"  stuck collective: {ls['stuck_collective']} "
                    f"(dispatch #{ls['stuck_seq']} — peers are inside "
                    f"it waiting for rank {ls['lagging_rank']})")
    elif ls is None and t.get("reason", "").startswith("spawn"):
        lines.append("lockstep: no side-channel logs in bundle "
                     "(run with BODO_TPU_LOCKSTEP=1 to fingerprint "
                     "collective dispatches)")
    cm = t.get("comm")
    if cm:
        lines.append("comm skew:")
        lates = ", ".join(f"rank {r}: {v:.3f}s"
                          for r, v in cm["late_s_by_rank"].items())
        lines.append(f"  arrival lateness by rank: {lates}")
        verdict = "" if cm.get("confident") else " (low confidence)"
        lines.append(
            f"  STRAGGLER: rank {cm['straggler_rank']} arrived last "
            f"at {cm['n_skewed_dispatches']} skewed dispatches, "
            f"peers waited {cm['straggler_late_s']:.3f}s for it"
            f"{verdict}")
        if cm.get("dominant_site"):
            lines.append(
                f"  dominant collective: {cm['dominant_site']} "
                f"({cm['dominant_site_skew_s']:.3f}s of skew)")
    cp = (t.get("critical_path") or {}).get("overall")
    if cp:
        lines.append(
            f"critical path: {len(cp['path'])} spans, "
            f"wall={cp['wall_us'] / 1e6:.3f}s, "
            f"comm={cp['comm_us'] / 1e6:.3f}s "
            f"({cp['comm_frac']:.0%} of path)")
        st = (t.get("critical_path") or {}).get("straggler")
        if st:
            lines.append(
                f"  trace straggler: rank {st['straggler_rank']} "
                f"(peer-wait skew {st['skew_s']:.3f}s"
                + (f", dominated by {st['dominant_site']}"
                   if st.get("dominant_site") else "") + ")")
    mem = t.get("memory")
    if mem:
        lines.append("memory:")
        lines.append(f"  rss timeline: {_spark(mem['rss_series'])} "
                     f"(peak {_fmt_bytes(mem['rss_peak_bytes'])}, "
                     f"last {_fmt_bytes(mem['rss_last_bytes'])})")
        if mem.get("budget_bytes"):
            occ = mem.get("peak_occupancy_frac", 0.0)
            lines.append(
                f"  governor: budget "
                f"{_fmt_bytes(mem['budget_bytes'])}, operator peak "
                f"{_fmt_bytes(mem.get('operator_peak_bytes', 0))} "
                f"({occ:.0%}), spilled "
                f"{_fmt_bytes(mem.get('spilled_bytes', 0))} in "
                f"{mem.get('n_spills', 0)} spills, "
                f"{mem.get('oom_retries', 0)} OOM retries")
    el = t.get("elastic")
    if el:
        lines.append("elastic:")
        bits = []
        if el.get("epoch"):
            bits.append(f"mesh epoch {el['epoch']}")
        if el.get("evicted_workers"):
            reasons = el.get("evicted_reasons") or {}
            who = ", ".join(
                f"worker {w}"
                + (f" ({reasons[str(w)]})" if str(w) in reasons else "")
                for w in el["evicted_workers"])
            bits.append(f"EVICTED {who}")
        if el.get("survivors"):
            bits.append(f"survivors {el['survivors']}")
        if el.get("resume_stage") is not None:
            bits.append(f"resumed from stage {el['resume_stage']}")
        if bits:
            lines.append("  " + "; ".join(bits))
        counters = []
        for k in ("shrinks", "grows", "resumes"):
            if el.get(k):
                counters.append(f"{el[k]} {k}")
        if el.get("capacity_frac") is not None \
                and el["capacity_frac"] < 1.0:
            counters.append(f"capacity {el['capacity_frac']:.0%}")
        if el.get("last_mttr_s") is not None:
            counters.append(f"last MTTR {el['last_mttr_s']:.2f}s")
        if counters:
            lines.append("  " + ", ".join(counters))
    fl = t.get("fleet")
    if fl:
        lines.append("fleet:")
        states = ", ".join(f"{k}: {v}" for k, v in
                           sorted(fl.get("by_state", {}).items()))
        lines.append(
            f"  {fl['gangs']} gangs ({states}); "
            f"{fl.get('rerouted', 0)} rerouted submits, "
            f"{fl.get('scrape_failures', 0)} scrape failures, "
            f"{fl.get('peer_hits', 0)} peer cache hits, "
            f"{fl.get('invalidations_broadcast', 0)} invalidation "
            f"broadcasts")
        for g in fl.get("unhealthy_gangs", []):
            reason = f" ({g['reason']})" if g.get("reason") else ""
            lines.append(f"  UNHEALTHY GANG {g['gang']}: "
                         f"{g['state']}{reason}")
    vw = t.get("views")
    if vw:
        lines.append("materialized views:")
        lines.append(
            f"  {vw['n_views']} views (DAG depth {vw['dag_depth']}), "
            f"{vw['subscriptions']} subscriptions; refreshes: "
            f"{vw['refreshes_incremental']} incremental / "
            f"{vw['refreshes_full']} full "
            f"(ratio {vw['refresh_ratio']:.2f})")
        if vw.get("lagging_view") and (
                vw.get("staleness_p99_s", 0.0) > 0
                or vw.get("staleness_peak_s")):
            peak = vw.get("staleness_peak_s",
                          vw.get("staleness_p99_s", 0.0))
            lines.append(
                f"  LAGGING VIEW {vw['lagging_view']!r}: staleness "
                f"p99 {vw['staleness_p99_s']:.3f}s "
                f"(peak {peak:.3f}s across samples)")
    x = t.get("xla")
    if x:
        lines.append("xla observatory:")
        lines.append(
            f"  {x.get('executables', 0)} executables, "
            f"{x.get('compiles', 0)} compiles "
            f"({float(x.get('compile_s', 0.0)):.3f}s wall)")
        st = x.get("storm")
        if st:
            lines.append(
                f"  RECOMPILE STORM: {st['signature']} compiled "
                f"{st['compiles_in_window']}x in the last "
                f"{st['window_s']:.0f}s — every dispatch is paying "
                f"trace+compile")
        rt = x.get("retraces") or {}
        if rt:
            causes = ", ".join(
                f"{c}: {n}" for c, n in
                sorted(rt.items(), key=lambda kv: -kv[1]))
            lines.append(f"  retrace causes: {causes}")
        for p in x.get("top_compile_cost", [])[:3]:
            bit = (f"  compile hot: {p['subsystem']}:{p['base']} "
                   f"{float(p['compile_s']):.3f}s, "
                   f"{p['dispatches']} dispatches")
            if p.get("retrace_cause"):
                bit += f" (retraced: {p['retrace_cause']})"
            lines.append(bit)
        leak = x.get("leak")
        if leak:
            lines.append(
                f"  LIVE DEVICE BYTES: "
                f"{_fmt_bytes(leak['live_bytes'])} in "
                f"{leak['live_buffers']} buffers"
                + (f", dominated by '{leak['dominant_site']}'"
                   if leak.get("dominant_site") else ""))
        don = x.get("donation")
        if don and don.get("copied"):
            lines.append(
                f"  donation: {don.get('verified', 0)} verified, "
                f"{don['copied']} dispatches COPIED instead of "
                f"donating (double memory on those inputs)")
    pc = t.get("progcheck")
    if pc:
        lines.append("progcheck (static program verification):")
        lines.append(f"  {pc.get('programs', 0)} programs verified")
        for v in pc.get("violations", []):
            lines.append(
                f"  VIOLATION [{v.get('rule')}] program "
                f"{v.get('program')!r} at {v.get('eqn') or '?'}"
                + (f": {v['message']}" if v.get("message") else ""))
        if pc.get("rank_variant"):
            lines.append(
                "  RANK-VARIANT programs (collective under "
                "rank-derived control flow): "
                + ", ".join(pc["rank_variant"]))
        if pc.get("hbm_top"):
            tops = ", ".join(
                f"{h['program']} {_fmt_bytes(h['hbm_bytes'])}"
                for h in pc["hbm_top"])
            lines.append(f"  static HBM peak estimates: {tops}")
    if t.get("slow_queries"):
        lines.append("slow queries:")
        for q in t["slow_queries"]:
            lines.append(f"  {q['query_id']}  "
                         f"wall={float(q['wall_s'] or 0.0):.3f}s")
    shards = t.get("trace_shards", [])
    bits = [f"trace shards from ranks {shards}" if shards
            else "no trace shards"]
    if t.get("has_merged_trace"):
        bits.append("merged multi-rank timeline present")
    if t.get("stack_dumps"):
        bits.append(f"stacks: {', '.join(t['stack_dumps'])}")
    lines.append("artifacts: " + "; ".join(bits))
    return "\n".join(lines)


def _latest_bundle() -> Optional[str]:
    from bodo_tpu.runtime import telemetry
    base = telemetry.flight_dir()
    try:
        cands = [os.path.join(base, n) for n in os.listdir(base)
                 if n.startswith("bundle_")]
    except OSError:
        return None
    cands = [c for c in cands if os.path.isdir(c)]
    return max(cands, key=os.path.getmtime) if cands else None


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m bodo_tpu.doctor",
        description="Triage a flight-recorder bundle.")
    ap.add_argument("bundle", nargs="?", default=None,
                    help="bundle directory (default: newest bundle in "
                         "the flight-recorder dir)")
    ap.add_argument("--json", action="store_true",
                    help="print the machine-readable triage dict")
    args = ap.parse_args(argv)
    bundle = args.bundle or _latest_bundle()
    if bundle is None:
        print("doctor: no bundle given and no bundles found",
              file=sys.stderr)
        return 2
    try:
        t = triage(bundle)
    except FileNotFoundError as e:
        print(f"doctor: {e}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(t, indent=2, sort_keys=True, default=str))
    else:
        print(render(t))
    return 0


if __name__ == "__main__":
    sys.exit(main())
