"""Flight-recorder bundle triage: ``python -m bodo_tpu.doctor <bundle>``.

A bundle (runtime/telemetry.py ``dump_bundle``) is a self-contained
directory; this module answers the three questions a gang post-mortem
starts with, without the operator opening a single JSON file:

  1. WHERE did the gang stop — the stuck collective fingerprint
     (op@file:line) and the lagging/divergent rank, reconstructed from
     the per-rank lockstep side-channel logs;
  2. WAS it memory — the RSS / governor-spill timeline from the
     telemetry ring, rendered as a sparkline around the failure;
  3. WHAT was it running — the slowest recorded queries with their
     EXPLAIN ANALYZE trees.

``triage(bundle)`` returns the machine-readable verdict; ``render``
prints the human one. With no bundle argument the CLI picks the newest
bundle in the flight-recorder directory.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
from typing import Dict, List, Optional

_LOCKSTEP_RE = re.compile(r"^lockstep_(\d+)\.log$")
_SHARD_RE = re.compile(r"^trace_shard_(\d+)\.json$")
_SPARK = " ▁▂▃▄▅▆▇█"


def _read_json(path: str):
    try:
        with open(path, "r") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _parse_lockstep_logs(bundle: str) -> Dict[int, Dict[int, str]]:
    """{rank: {seq: fingerprint}} from the copied side-channel logs."""
    logs: Dict[int, Dict[int, str]] = {}
    try:
        names = os.listdir(bundle)
    except OSError:
        return logs
    for name in names:
        m = _LOCKSTEP_RE.match(name)
        if not m:
            continue
        entries: Dict[int, str] = {}
        try:
            with open(os.path.join(bundle, name), "r") as f:
                for line in f:
                    if "\t" not in line:
                        continue
                    s, fp = line.rstrip("\n").split("\t", 1)
                    try:
                        entries[int(s)] = fp
                    except ValueError:
                        continue
        except OSError:
            continue
        logs[int(m.group(1))] = entries
    return logs


def _triage_lockstep(logs: Dict[int, Dict[int, str]]) -> Optional[dict]:
    """Name the stuck collective and the lagging/divergent rank from
    the per-rank dispatch streams.

    * divergence: the first sequence number at which two ranks logged
      DIFFERENT fingerprints — mismatched control flow;
    * wedge: the rank(s) whose stream stops earliest; the "stuck
      collective" is what the leading ranks dispatched at the first
      sequence number the laggard never reached (every live peer is
      blocked inside it waiting for the laggard).
    """
    if not logs:
        return None
    heads = {r: (max(e) if e else 0) for r, e in logs.items()}
    head = max(heads.values())
    out: dict = {"heads": {str(r): h for r, h in sorted(heads.items())},
                 "head": head}
    for seq in range(1, head + 1):
        fps = {r: e[seq] for r, e in logs.items() if seq in e}
        if len(set(fps.values())) > 1:
            out["divergence"] = {
                "seq": seq,
                "fingerprints": {str(r): fp
                                 for r, fp in sorted(fps.items())}}
            break
    lag = min(heads.values())
    if lag < head:
        lagging = sorted(r for r, h in heads.items() if h == lag)
        out["lagging_ranks"] = lagging
        out["lagging_rank"] = lagging[0]
        out["lagging_last"] = logs[lagging[0]].get(lag)
        stuck = sorted({e[lag + 1] for e in logs.values()
                        if lag + 1 in e})
        if stuck:
            out["stuck_seq"] = lag + 1
            out["stuck_collective"] = stuck[0]
    return out


def _triage_memory(telemetry: Optional[dict]) -> Optional[dict]:
    samples = (telemetry or {}).get("samples") or []
    if not samples:
        return None
    rss = [int(s.get("rss_bytes", 0)) for s in samples]
    out: dict = {
        "samples": len(samples),
        "rss_last_bytes": rss[-1],
        "rss_peak_bytes": max(rss),
        "rss_series": rss[-60:],
    }
    mems = [s.get("mem") for s in samples if s.get("mem")]
    if mems:
        last = mems[-1]
        out["budget_bytes"] = last.get("budget_bytes", 0)
        out["spilled_bytes"] = last.get("spilled_bytes", 0)
        out["n_spills"] = last.get("n_spills", 0)
        out["oom_retries"] = last.get("oom_retries", 0)
        peak = max(m.get("peak_bytes", 0) for m in mems)
        out["operator_peak_bytes"] = peak
        if out["budget_bytes"]:
            out["peak_occupancy_frac"] = round(
                peak / out["budget_bytes"], 4)
    return out


def triage(bundle: str) -> dict:
    """Machine-readable triage of one flight-recorder bundle."""
    if not os.path.isdir(bundle):
        raise FileNotFoundError(f"not a bundle directory: {bundle}")
    manifest = _read_json(os.path.join(bundle, "manifest.json")) or {}
    out: dict = {
        "bundle": os.path.abspath(bundle),
        "reason": manifest.get("reason", "unknown"),
        "time": manifest.get("iso_time"),
        "faults_armed": manifest.get("faults_armed", []),
    }
    ranks = manifest.get("ranks") or {}
    if ranks:
        out["ranks"] = ranks
        out["dead_ranks"] = sorted(
            int(r) for r, d in ranks.items()
            if d.get("state") in ("dead",))
        out["hung_ranks"] = sorted(
            int(r) for r, d in ranks.items()
            if d.get("state") in ("hung", "timeout"))
    out["lockstep"] = _triage_lockstep(_parse_lockstep_logs(bundle))
    out["memory"] = _triage_memory(
        _read_json(os.path.join(bundle, "telemetry.json")))
    slow = _read_json(os.path.join(bundle, "slow_queries.json")) or []
    out["slow_queries"] = [{"query_id": q.get("query_id"),
                            "wall_s": q.get("wall_s")} for q in slow]
    try:
        names = sorted(os.listdir(bundle))
    except OSError:
        names = []
    out["trace_shards"] = sorted(
        int(m.group(1)) for m in (_SHARD_RE.match(n) for n in names)
        if m)
    out["has_merged_trace"] = "trace_merged.json" in names
    out["stack_dumps"] = [n for n in names
                          if n == "stacks.txt"
                          or n.startswith("stacks_")]
    return out


def _spark(vals: List[int]) -> str:
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    rng = (hi - lo) or 1
    return "".join(_SPARK[int((v - lo) / rng * (len(_SPARK) - 1))]
                   for v in vals)


def _fmt_bytes(n) -> str:
    v = float(n or 0)
    for unit in ("B", "KB", "MB", "GB"):
        if v < 1024 or unit == "GB":
            return f"{int(v)}B" if unit == "B" else f"{v:.1f}{unit}"
        v /= 1024
    return f"{v:.1f}GB"  # pragma: no cover


def render(t: dict) -> str:
    """Human-readable triage report."""
    lines = [f"FLIGHT RECORDER TRIAGE  {t['bundle']}",
             f"reason: {t['reason']}"
             + (f"  at {t['time']}" if t.get("time") else "")]
    if t.get("faults_armed"):
        lines.append(f"faults armed: {', '.join(t['faults_armed'])}")
    for r, d in sorted(t.get("ranks", {}).items(), key=lambda kv:
                       int(kv[0])):
        line = f"  rank {r}: {d.get('state')}"
        if d.get("returncode") is not None:
            line += f" rc={d['returncode']}"
        lines.append(line)
    ls = t.get("lockstep")
    if ls:
        lines.append("lockstep:")
        heads = ", ".join(f"rank {r} @ #{h}"
                          for r, h in ls["heads"].items())
        lines.append(f"  dispatch heads: {heads}")
        div = ls.get("divergence")
        if div:
            fps = "; ".join(f"rank {r}: {fp}"
                            for r, fp in div["fingerprints"].items())
            lines.append(f"  DIVERGENCE at dispatch #{div['seq']}: "
                         f"{fps}")
        if "lagging_rank" in ls:
            last = ls.get("lagging_last") or "nothing"
            lines.append(
                f"  lagging rank: {ls['lagging_rank']} stopped at "
                f"#{ls['heads'][str(ls['lagging_rank'])]} ({last})")
            if "stuck_collective" in ls:
                lines.append(
                    f"  stuck collective: {ls['stuck_collective']} "
                    f"(dispatch #{ls['stuck_seq']} — peers are inside "
                    f"it waiting for rank {ls['lagging_rank']})")
    elif ls is None and t.get("reason", "").startswith("spawn"):
        lines.append("lockstep: no side-channel logs in bundle "
                     "(run with BODO_TPU_LOCKSTEP=1 to fingerprint "
                     "collective dispatches)")
    mem = t.get("memory")
    if mem:
        lines.append("memory:")
        lines.append(f"  rss timeline: {_spark(mem['rss_series'])} "
                     f"(peak {_fmt_bytes(mem['rss_peak_bytes'])}, "
                     f"last {_fmt_bytes(mem['rss_last_bytes'])})")
        if mem.get("budget_bytes"):
            occ = mem.get("peak_occupancy_frac", 0.0)
            lines.append(
                f"  governor: budget "
                f"{_fmt_bytes(mem['budget_bytes'])}, operator peak "
                f"{_fmt_bytes(mem.get('operator_peak_bytes', 0))} "
                f"({occ:.0%}), spilled "
                f"{_fmt_bytes(mem.get('spilled_bytes', 0))} in "
                f"{mem.get('n_spills', 0)} spills, "
                f"{mem.get('oom_retries', 0)} OOM retries")
    if t.get("slow_queries"):
        lines.append("slow queries:")
        for q in t["slow_queries"]:
            lines.append(f"  {q['query_id']}  "
                         f"wall={float(q['wall_s'] or 0.0):.3f}s")
    shards = t.get("trace_shards", [])
    bits = [f"trace shards from ranks {shards}" if shards
            else "no trace shards"]
    if t.get("has_merged_trace"):
        bits.append("merged multi-rank timeline present")
    if t.get("stack_dumps"):
        bits.append(f"stacks: {', '.join(t['stack_dumps'])}")
    lines.append("artifacts: " + "; ".join(bits))
    return "\n".join(lines)


def _latest_bundle() -> Optional[str]:
    from bodo_tpu.runtime import telemetry
    base = telemetry.flight_dir()
    try:
        cands = [os.path.join(base, n) for n in os.listdir(base)
                 if n.startswith("bundle_")]
    except OSError:
        return None
    cands = [c for c in cands if os.path.isdir(c)]
    return max(cands, key=os.path.getmtime) if cands else None


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m bodo_tpu.doctor",
        description="Triage a flight-recorder bundle.")
    ap.add_argument("bundle", nargs="?", default=None,
                    help="bundle directory (default: newest bundle in "
                         "the flight-recorder dir)")
    ap.add_argument("--json", action="store_true",
                    help="print the machine-readable triage dict")
    args = ap.parse_args(argv)
    bundle = args.bundle or _latest_bundle()
    if bundle is None:
        print("doctor: no bundle given and no bundles found",
              file=sys.stderr)
        return 2
    try:
        t = triage(bundle)
    except FileNotFoundError as e:
        print(f"doctor: {e}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(t, indent=2, sort_keys=True, default=str))
    else:
        print(render(t))
    return 0


if __name__ == "__main__":
    sys.exit(main())
