"""GroupBy object (reference: bodo/pandas/groupby.py,
bodo/hiframes/pd_groupby_ext.py:96 DataFrameGroupByType surface)."""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple, Union

import pandas as pd

from bodo_tpu.plan import logical as L
from bodo_tpu.utils.logging import warn_fallback

_AGG_OPS = ("sum", "mean", "count", "min", "max", "var", "std", "size",
            "first", "last", "nunique", "prod", "median")


class BodoGroupBy:
    def __init__(self, df, keys: List[str], as_index: bool = True,
                 selection: Optional[List[str]] = None):
        self._df = df
        self._keys = keys
        self._as_index = as_index
        self._selection = selection
        self._single = False

    def __getitem__(self, key):
        sel = [key] if isinstance(key, str) else list(key)
        g = BodoGroupBy(self._df, self._keys, self._as_index, sel)
        g._single = isinstance(key, str)
        return g

    # ---- agg spec normalization -------------------------------------------
    def _value_cols(self) -> List[str]:
        if self._selection is not None:
            return self._selection
        return [n for n in self._df._plan.schema if n not in self._keys]

    def agg(self, arg=None, **named):
        aggs: List[Tuple[str, str, str]] = []
        if arg is None and named:
            # named aggregation: out=("col", "op")
            for out, (col, op) in named.items():
                aggs.append((col, op, out))
        elif isinstance(arg, dict):
            for col, ops in arg.items():
                if isinstance(ops, str):
                    aggs.append((col, ops, col))
                else:
                    for op in ops:
                        aggs.append((col, op, f"{col}_{op}"))
        elif isinstance(arg, str):
            for col in self._value_cols():
                aggs.append((col, arg, col))
        elif isinstance(arg, (list, tuple)):
            for col in self._value_cols():
                for op in arg:
                    aggs.append((col, op, f"{col}_{op}"))
        else:
            warn_fallback("groupby.agg", f"unsupported spec {type(arg)}")
            gb = self._df.to_pandas().groupby(self._keys,
                                              as_index=self._as_index)
            if self._selection:
                gb = gb[self._selection]
            return gb.agg(arg, **named)
        return self._run(aggs)

    aggregate = agg

    def _run(self, aggs):
        from bodo_tpu.pandas_api.frame import BodoDataFrame
        node = L.Aggregate(self._df._plan, self._keys, aggs)
        single = aggs[0][2] if (self._single and len(aggs) == 1) else None
        if self._as_index:
            # key columns become the result's index — still ordinary
            # device columns in the plan, converted only at to_pandas()
            index = [(k, k) for k in self._keys]
            if single is not None:
                from bodo_tpu.plan.expr import ColRef
                from bodo_tpu.pandas_api.series import BodoSeries
                return BodoSeries(node, ColRef(single), single,
                                  index=index)
            return BodoDataFrame(node, index=index)
        return BodoDataFrame(node)

    def _simple(self, op):
        if op == "size":
            aggs = [(self._keys[0], "size", "size")]
        else:
            aggs = [(c, op, c) for c in self._value_cols()
                    if op in ("count", "nunique", "first", "last", "mode")
                    or _numericish(self._df._plan.schema[c])]
        return self._run(aggs)

    def sum(self): return self._simple("sum")
    def mean(self): return self._simple("mean")
    def count(self): return self._simple("count")
    def min(self): return self._simple("min")
    def max(self): return self._simple("max")

    def var(self, ddof=1):
        from bodo_tpu.pandas_api.series import _ddof_op
        return self._simple(_ddof_op("var", ddof))

    def std(self, ddof=1):
        from bodo_tpu.pandas_api.series import _ddof_op
        return self._simple(_ddof_op("std", ddof))
    def first(self): return self._simple("first")
    def last(self): return self._simple("last")
    def nunique(self): return self._simple("nunique")
    def prod(self): return self._simple("prod")
    def median(self): return self._simple("median")
    def skew(self): return self._simple("skew")
    def kurt(self): return self._simple("kurt")
    kurtosis = kurt

    def quantile(self, q=0.5):
        if not isinstance(q, (int, float)):
            warn_fallback("groupby.quantile", "list of quantiles")
            gb = self._df.to_pandas().groupby(self._keys,
                                              as_index=self._as_index)
            if self._selection:
                gb = gb[self._selection[0] if len(self._selection) == 1
                        else self._selection]
            return gb.quantile(q)
        return self._simple(f"quantile_{float(q)}")

    # ---- transform-shaped (row-aligned) window functions ------------------
    _RANK_METHODS = {"first": "row_number", "min": "rank",
                     "dense": "dense_rank"}

    def rank(self, method: str = "min", ascending: bool = True):
        """Within-group rank of the selected column (SQL semantics for
        nulls: they rank together rather than producing NaN)."""
        if method not in self._RANK_METHODS or not self._single:
            warn_fallback("groupby.rank", f"method={method!r} or "
                          "multi-column selection")
            gb = self._df.to_pandas().groupby(self._keys)
            if self._selection:
                gb = gb[self._selection[0] if len(self._selection) == 1
                        else self._selection]
            return gb.rank(method=method, ascending=ascending)
        col = self._selection[0]
        return self._rank_window(self._RANK_METHODS[method], 0, [col],
                                 ascending)

    def cumcount(self):
        return self._rank_window("cumcount", 0, [], True)

    def ntile(self, n: int):
        """SQL NTILE(n) over the group in original row order."""
        return self._rank_window("ntile", int(n), [], True)

    def _rank_window(self, op: str, param: int, order_by, ascending: bool):
        from bodo_tpu.plan.expr import ColRef

        from bodo_tpu.pandas_api.series import BodoSeries
        out = f"__{op}"
        node = L.RankWindow(self._df._plan, self._keys, order_by,
                            [ascending] * len(order_by),
                            [(op, param, out)])
        return BodoSeries(node, ColRef(out), op)

    # pandas transform('first'/'last') skip nulls (unlike SQL
    # FIRST_VALUE), so only the null-agnostic aggs map onto AggWindow;
    # sum0 = pandas sum semantics (all-null group sums to 0, not NULL)
    _TRANSFORM_OPS = {"sum": "sum0", "mean": "mean", "count": "count",
                      "min": "min", "max": "max"}

    def transform(self, op):
        """Row-aligned per-group aggregate (groupby.transform('sum') etc.)
        via the AggWindow whole-partition frame — no gather, same kernel
        as SQL SUM(...) OVER (PARTITION BY ...)."""
        if not isinstance(op, str) or op not in self._TRANSFORM_OPS:
            warn_fallback("groupby.transform", f"op {op!r}")
            gb = self._df.to_pandas().groupby(self._keys)
            if self._selection:
                gb = gb[self._selection[0] if len(self._selection) == 1
                        else self._selection]
            return gb.transform(op)
        return self._agg_window(
            lambda c, tmp: (self._TRANSFORM_OPS[op], c, ("all",), 0, tmp),
            "__tf", op)

    def shift(self, periods: int = 1):
        """Within-group shift (LEAD/LAG) in original row order."""
        op = "lag" if periods >= 0 else "lead"
        off = abs(int(periods))
        return self._agg_window(
            lambda c, tmp: (op, c, ("all",), off, tmp), "__sh", "shift")

    def _agg_window(self, spec_of, prefix: str, label: str):
        """Shared AggWindow tail for the row-aligned group ops: build one
        spec per value column, then unwrap to a Series (single selection)
        or a renamed frame."""
        cols = self._value_cols()
        specs = [spec_of(c, f"{prefix}_{c}") for c in cols]
        node = L.AggWindow(self._df._plan, self._keys, [], [], specs)
        if self._single:
            from bodo_tpu.plan.expr import ColRef

            from bodo_tpu.pandas_api.series import BodoSeries
            return BodoSeries(node, ColRef(f"{prefix}_{cols[0]}"), label)
        from bodo_tpu.pandas_api.frame import BodoDataFrame
        out = BodoDataFrame(node)
        return out[[f"{prefix}_{c}" for c in cols]].rename(
            columns={f"{prefix}_{c}": c for c in cols})

    def apply(self, func, *args, **kwargs):
        """Per-group Python UDF (reference: bodo/hiframes/pd_groupby_ext.py
        apply support). Distributed execution: one hash shuffle co-locates
        every group on a shard (`relational.shuffle_by_key`), then the UDF
        runs rank-local per shard — the same shuffle-then-local-UDF model
        as the reference's groupby.apply under JIT. Results concatenate
        and sort to pandas' group order."""
        import bodo_tpu.relational as R
        from bodo_tpu.plan.physical import execute
        t = execute(self._df._plan)
        if t.distribution != "REP" and t.num_shards > 1:
            # carry the global row id through the shuffle so per-shard
            # frames keep ORIGINAL row labels — transform-like UDF
            # results (same-length Series) then reassemble in pandas'
            # original row order instead of interleaving local indexes
            t2 = R.window_table(t, [(t.names[0], "rowid", None, "__rid")])
            t2 = R.shuffle_by_key(t2, self._keys)
            frames = [f.set_index("__rid").rename_axis(None)
                      for f in R.shard_frames(t2)]
        else:
            frames = [t.to_pandas()]
        sel = None
        if self._selection is not None:
            sel = self._selection[0] if self._single else self._selection
        parts = []
        for f in frames:
            if not len(f):
                continue
            gb = f.groupby(self._keys, as_index=True)
            if sel is not None:
                gb = gb[sel]
            parts.append(gb.apply(func, *args, **kwargs))
        if not parts:
            gb = pd.DataFrame(columns=list(self._df._plan.schema)
                              ).groupby(self._keys)
            if sel is not None:
                gb = gb[sel]
            return gb.apply(func, *args, **kwargs)
        res = pd.concat(parts)
        res = res.sort_index(level=list(range(len(self._keys)))
                             if res.index.nlevels > 1 else None,
                             kind="stable")
        if not self._as_index:
            res = res.reset_index()
        return res

    def size(self):
        res = self._run([(self._keys[0], "size", "size")])
        if self._as_index:
            from bodo_tpu.plan.expr import ColRef
            from bodo_tpu.pandas_api.series import BodoSeries
            # pandas: SeriesGroupBy.size keeps the column name,
            # DataFrameGroupBy.size is unnamed
            name = self._selection[0] if self._single else None
            if not isinstance(res, BodoSeries):
                res = BodoSeries(res._plan, ColRef("size"), "size",
                                 index=res._index)
            return res.to_pandas().rename(name)
        return res

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        if name in self._df._plan.schema:
            return self[name]
        warn_fallback(f"groupby.{name}", "not yet lazy")
        gb = self._df.to_pandas().groupby(self._keys, as_index=self._as_index)
        if self._selection:
            sel = self._selection[0] if len(self._selection) == 1 \
                else self._selection
            gb = gb[sel]
        return getattr(gb, name)


def _numericish(t) -> bool:
    return t.kind in ("i", "u", "f", "b")
