"""BodoDataFrame: the lazy dataframe (reference bodo/pandas/frame.py:117).

Every method builds a plan node; unsupported surface falls back to real
pandas with a warning (the reference's check_args_fallback design —
bodo/pandas/utils.py:346 — is replicated by the __getattr__ fallback that
materializes and delegates, re-wrapping frame results lazily).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Union

import numpy as np
import pandas as pd

from bodo_tpu.plan import logical as L
from bodo_tpu.plan.expr import ColRef, Expr, Lit
from bodo_tpu.pandas_api.series import BodoSeries
from bodo_tpu.table import dtypes as dt
from bodo_tpu.utils.logging import warn_fallback


class BodoDataFrame:
    def __init__(self, plan: L.Node, index=None):
        object.__setattr__(self, "_plan", plan)
        # index: [(plan_column, display_name)] — the engine's index is an
        # ordinary device column threaded through the plan; only the
        # final to_pandas() turns it into a pandas index (reference
        # analogue: bodo/hiframes/pd_index_ext.py index objects, redesigned
        # as index-as-column so kernels never special-case it)
        object.__setattr__(self, "_index", list(index) if index else [])
        # plans this frame has pointed at (mutated by __setitem__), with the
        # columns overwritten since: a Series captured from an older plan
        # stays usable as long as none of its referenced columns changed
        object.__setattr__(self, "_history", {id(plan): set()})

    def _index_cols(self) -> List[str]:
        return [c for c, _ in self._index]

    def _data_cols(self) -> List[str]:
        idx = set(self._index_cols())
        return [n for n in self._plan.schema if n not in idx]

    # ---- schema ----------------------------------------------------------
    @property
    def columns(self) -> pd.Index:
        return pd.Index(self._data_cols())

    @property
    def dtypes(self) -> pd.Series:
        out = {}
        for n in self._data_cols():
            t = self._plan.schema[n]
            out[n] = np.dtype("O") if t is dt.STRING else np.dtype(t.np_dtype)
        return pd.Series(out)

    @property
    def shape(self):
        return (len(self), len(self._data_cols()))

    # ---- selection -------------------------------------------------------
    def __getitem__(self, key):
        if isinstance(key, str):
            if key not in self._plan.schema or \
                    key in set(self._index_cols()):
                raise KeyError(key)
            return BodoSeries(self._plan, ColRef(key), key,
                              index=self._index)
        if isinstance(key, list):
            exprs = [(n, ColRef(n)) for n in key]
            exprs += [(c, ColRef(c)) for c in self._index_cols()
                      if c not in key]
            return BodoDataFrame(L.Projection(self._plan, exprs),
                                 index=self._index)
        if isinstance(key, BodoSeries):
            try:
                e = self._expr_of(key)
            except ValueError:
                raise ValueError("boolean mask must come from this frame")
            return BodoDataFrame(L.Filter(self._plan, e),
                                 index=self._index)
        raise TypeError(f"unsupported key: {key!r}")

    def __setitem__(self, name: str, value):
        if name in set(self._index_cols()):
            # pandas creates a data column distinct from the index; move
            # the index to a reserved backing column first so the assign
            # can't corrupt it
            exprs = []
            new_index = []
            for c, disp in self._index:
                if c == name:
                    exprs.append((f"__idx_{c}", ColRef(c)))
                    new_index.append((f"__idx_{c}", disp))
                else:
                    exprs.append((c, ColRef(c)))
                    new_index.append((c, disp))
            exprs += [(n, ColRef(n)) for n in self._data_cols()]
            object.__setattr__(self, "_plan",
                               L.Projection(self._plan, exprs))
            object.__setattr__(self, "_index", new_index)
            hist = object.__getattribute__(self, "_history")
            hist[id(self._plan)] = set()
        if isinstance(value, (list, np.ndarray, pd.Series)) and \
                not isinstance(value, BodoSeries):
            # positional data needs host alignment — fallback semantics
            warn_fallback("DataFrame.__setitem__", "raw array value")
            pdf = self._execute().to_pandas()  # raw cols incl. index
            pdf[name] = np.asarray(value)
            plan = L.FromPandas(pdf)
        else:
            plan = None
            if isinstance(value, BodoSeries):
                plan = self._try_absorb_window(name, value)
            if plan is None:
                plan = self._assign_plan({name: value})
        hist = object.__getattribute__(self, "_history")
        for dirty in hist.values():
            dirty.add(name)
        hist[id(plan)] = set()
        object.__setattr__(self, "_plan", plan)

    def __getattr__(self, name):
        plan = object.__getattribute__(self, "_plan")
        index = object.__getattribute__(self, "_index")
        if name in plan.schema and \
                name not in {c for c, _ in index}:
            return BodoSeries(plan, ColRef(name), name, index=index)
        if not name.startswith("_") and hasattr(pd.DataFrame, name):
            warn_fallback(f"DataFrame.{name}", "not yet lazy")
            attr = getattr(self.to_pandas(), name)
            if callable(attr):
                def wrapped(*a, **k):
                    res = attr(*a, **k)
                    if isinstance(res, pd.DataFrame) and isinstance(
                            res.index, pd.RangeIndex):
                        # re-wrap lazily; frames with meaningful indexes
                        # (describe etc.) stay plain pandas
                        return BodoDataFrame(L.FromPandas(res))
                    return res
                return wrapped
            return attr
        raise AttributeError(name)

    def _try_absorb_window(self, name: str, s) -> "L.Node | None":
        """df[name] = df[col].cumsum()/shift(...): the series' plan is a
        Window wrapped around (a projection of) this frame's plan — a
        row-aligned derivation, so it can be rebuilt on the full frame
        instead of rejecting it as foreign (pandas aligns by index; the
        engine's analogue is row alignment through row-preserving nodes)."""
        vp = s._plan
        if not isinstance(vp, L.Window) or len(vp.specs) != 1:
            return None
        wcol, op, param, out = vp.specs[0]
        if not (isinstance(s._expr, ColRef) and s._expr.name == out):
            return None
        child = vp.child
        if child is self._plan:
            inner = ColRef(wcol)
        elif isinstance(child, L.Projection) and child.child is self._plan:
            inner = dict(child.exprs).get(wcol)
            if inner is None:
                return None
        else:
            return None
        tmp = f"__win_in_{name}"
        keep = [(n, ColRef(n)) for n in self._plan.schema]
        p2 = L.Projection(self._plan, keep + [(tmp, inner)])
        wout = f"__w_{tmp}"
        w2 = L.Window(p2, [(tmp, op, param, wout)])
        exprs = [(n, ColRef(wout) if n == name else ColRef(n))
                 for n in self._plan.schema]
        if name not in self._plan.schema:
            exprs.append((name, ColRef(wout)))
        return L.Projection(w2, exprs)

    def _expr_of(self, value) -> Expr:
        if isinstance(value, BodoSeries):
            hist = object.__getattribute__(self, "_history")
            if value._plan is self._plan:
                return value._expr
            dirty = hist.get(id(value._plan))
            if dirty is not None:
                from bodo_tpu.plan.expr import expr_columns
                stale = expr_columns(value._expr) & dirty
                if stale:
                    raise ValueError(
                        f"Series references columns overwritten since it was "
                        f"captured: {sorted(stale)}")
                return value._expr
            raise ValueError("column must come from this frame")
        return Lit(value)

    def _assign_plan(self, new: Dict[str, object]) -> L.Node:
        exprs = [(n, ColRef(n)) for n in self._plan.schema]
        names = {n for n, _ in exprs}
        for n, v in new.items():
            e = self._expr_of(v)
            if n in names:
                exprs = [(nn, e if nn == n else ee) for nn, ee in exprs]
            else:
                exprs.append((n, e))
        return L.Projection(self._plan, exprs)

    def assign(self, **kwargs) -> "BodoDataFrame":
        """Add columns. Series values may come from this frame (evaluated
        over the in-progress projection chain — all original columns pass
        through by name); callables receive the frame built so far."""
        plan = self._plan
        allowed = {id(self._plan)}
        for n, v in kwargs.items():
            if callable(v):
                v = v(BodoDataFrame(plan))
            if isinstance(v, BodoSeries):
                if id(v._plan) not in allowed:
                    raise ValueError("column must come from this frame")
                e = v._expr
            else:
                e = Lit(v)
            exprs = [(nn, ColRef(nn)) for nn in plan.schema if nn != n]
            exprs.append((n, e))
            plan = L.Projection(plan, exprs)
            allowed.add(id(plan))
        return BodoDataFrame(plan, index=self._index)

    def melt(self, id_vars=None, value_vars=None, var_name="variable",
             value_name="value") -> "BodoDataFrame":
        """Unpivot columns to rows: one constant-dictionary `variable`
        column per source column, concatenated on device (reference:
        bodo/hiframes/pd_dataframe_ext.py melt overload)."""
        import jax.numpy as jnp
        import numpy as np

        from bodo_tpu import relational as R
        from bodo_tpu.plan.physical import execute
        from bodo_tpu.table import dtypes as dt
        from bodo_tpu.table.table import Column, Table
        id_vars = [id_vars] if isinstance(id_vars, str) else \
            list(id_vars or [])
        schema = self._plan.schema
        value_vars = [value_vars] if isinstance(value_vars, str) else \
            list(value_vars or [c for c in schema if c not in id_vars])
        t = execute(self._plan)
        pieces = []
        for v in value_vars:
            cols = {c: t.columns[c] for c in id_vars}
            cols[var_name] = Column(
                jnp.zeros((t.capacity,), jnp.int32), None, dt.STRING,
                np.array([v], dtype=str))
            cols[value_name] = t.columns[v]
            pieces.append(Table(cols, t.nrows, t.distribution, t.counts))
        out = R.concat_tables(pieces)
        return BodoDataFrame(L.FromPandas(out))

    def pivot_table(self, values=None, index=None, columns=None,
                    aggfunc="mean"):
        """Device-side groupby on (index, columns), host-side reshape of
        the (small) aggregated result — returns plain pandas (pivoted
        frames carry a meaningful index, which Tables don't model)."""
        from bodo_tpu.plan.physical import execute
        if index is None or columns is None or not isinstance(values, str):
            raise NotImplementedError(
                "pivot_table needs explicit string/list index and columns "
                "and a single string values column")
        idx = [index] if isinstance(index, str) else list(index)
        col = [columns] if isinstance(columns, str) else list(columns)
        node = L.Aggregate(self._plan, idx + col,
                           [(values, aggfunc, "__v")])
        pdf = BodoDataFrame(node).to_pandas()
        return pdf.pivot(index=idx, columns=col, values="__v") \
            .rename_axis(columns=None if len(col) == 1 else col)

    def to_parquet(self, path: str, index: bool = False) -> None:
        """Write to parquet: streaming row groups when the plan is a
        streamable chain, per-shard part files for 1D tables, one file
        otherwise. Tables are index-free; index=True has nothing to
        write."""
        if index:
            warn_fallback("DataFrame.to_parquet",
                          "index=True — tables are index-free")
        from bodo_tpu.config import config
        from bodo_tpu.io.parquet import write_parquet
        from bodo_tpu.plan.optimizer import optimize
        from bodo_tpu.plan.physical import execute
        plan = optimize(self._plan)
        if config.stream_exec:
            from bodo_tpu.plan import streaming
            if streaming.stream_to_parquet(plan, path):
                return
        write_parquet(execute(plan, optimize_first=False), path)

    def to_iceberg(self, table_path: str, mode: str = "append") -> int:
        """Write to a local-warehouse Iceberg table (reference:
        bodo/pandas/frame.py:507 to_iceberg). Returns the snapshot id."""
        from bodo_tpu.io.iceberg import write_iceberg
        return write_iceberg(self._execute(), table_path, mode=mode)

    def explode(self, column: str) -> "BodoDataFrame":
        """Row-expand a list column (reference: bodo/libs/_lateral.cpp
        lateral flatten; pandas df.explode). Pandas' repeated index is
        not reproduced — rows come back 0..n-1 like reset_index(drop)."""
        from bodo_tpu.plan.physical import execute
        from bodo_tpu.table import nested as _nested
        t = execute(self._plan)
        return BodoDataFrame(L.FromPandas(_nested.explode_table(t, column)))

    def drop(self, columns=None, **kw) -> "BodoDataFrame":
        if columns is None:
            warn_fallback("DataFrame.drop", "only columns= supported")
            return BodoDataFrame(L.FromPandas(self.to_pandas().drop(**kw)))
        cols = [columns] if isinstance(columns, str) else list(columns)
        keep = [n for n in self._plan.schema if n not in cols]
        return self[keep]

    def rename(self, columns: Optional[Dict[str, str]] = None, copy=None,
               **kw) -> "BodoDataFrame":
        if columns is None:
            warn_fallback("DataFrame.rename", "only columns= supported")
            return BodoDataFrame(L.FromPandas(
                self.to_pandas().rename(**kw)))
        exprs = [(columns.get(n, n), ColRef(n)) for n in self._plan.schema]
        return BodoDataFrame(L.Projection(self._plan, exprs))

    def apply(self, func, axis=0, raw=False, args=(), **kwargs):
        """axis=1 row UDFs compile to a vmapped device kernel (the
        reference's compiled-UDF path, README-quickstart workload); anything
        else falls back to pandas."""
        if axis == 1 and not args and not kwargs:
            from bodo_tpu.pandas_api.series import validate_expr_trace
            from bodo_tpu.plan.expr import RowUDF
            from bodo_tpu.table import dtypes as dtl
            traced = validate_expr_trace(RowUDF(func, None),
                                         self._plan.schema)
            if traced is not None:
                return BodoSeries(self._plan,
                                  RowUDF(func, dtl.from_numpy(traced)), None)
        warn_fallback("DataFrame.apply", "uncompilable UDF or axis=0")
        return self.to_pandas().apply(func, axis=axis, raw=raw, args=args,
                                      **kwargs)

    # ---- relational ops ----------------------------------------------------
    def merge(self, right: "BodoDataFrame", on=None, left_on=None,
              right_on=None, how: str = "inner",
              suffixes=("_x", "_y")) -> "BodoDataFrame":
        if how == "cross":
            if on is not None or left_on is not None or right_on is not None:
                raise ValueError("cross merge takes no join keys")
            left_on = right_on = []
        else:
            if on is not None:
                left_on = right_on = [on] if isinstance(on, str) \
                    else list(on)
            if left_on is None or right_on is None:
                raise ValueError("merge requires on= or left_on=/right_on=")
            left_on = [left_on] if isinstance(left_on, str) \
                else list(left_on)
            right_on = [right_on] if isinstance(right_on, str) \
                else list(right_on)
        return BodoDataFrame(L.Join(self._plan, right._plan, left_on,
                                    right_on, how, suffixes))

    def groupby(self, by, as_index: bool = True, dropna: bool = True,
                sort: bool = True):
        from bodo_tpu.pandas_api.groupby import BodoGroupBy
        keys = [by] if isinstance(by, str) else list(by)
        return BodoGroupBy(self, keys, as_index=as_index)

    def sort_values(self, by, ascending=True, na_position: str = "last",
                    kind=None, ignore_index: bool = True) -> "BodoDataFrame":
        by = [by] if isinstance(by, str) else list(by)
        asc = [ascending] * len(by) if isinstance(ascending, bool) \
            else list(ascending)
        return BodoDataFrame(L.Sort(self._plan, by, asc,
                                    na_last=(na_position == "last")),
                             index=self._index)

    def drop_duplicates(self, subset=None) -> "BodoDataFrame":
        subset = [subset] if isinstance(subset, str) else \
            (list(subset) if subset else None)
        return BodoDataFrame(L.Distinct(self._plan, subset),
                             index=self._index)

    def head(self, n: int = 5) -> "BodoDataFrame":
        return BodoDataFrame(L.Limit(self._plan, n), index=self._index)

    # ---- index -------------------------------------------------------------
    def set_index(self, keys, drop: bool = True,
                  append: bool = False) -> "BodoDataFrame":
        """Designate column(s) as the index. The data stays a device
        column in the plan; nothing materializes (reference analogue:
        bodo/hiframes/pd_index_ext.py set_index)."""
        keys = [keys] if isinstance(keys, str) else list(keys)
        for k in keys:
            if k not in self._plan.schema or k in set(self._index_cols()):
                raise KeyError(k)
        if not drop:
            # keep the column as data too: alias a copy for the index
            exprs = [(n, ColRef(n)) for n in self._plan.schema]
            exprs += [(f"__idx_{k}", ColRef(k)) for k in keys]
            index = (self._index if append else []) + \
                [(f"__idx_{k}", k) for k in keys]
            return BodoDataFrame(L.Projection(self._plan, exprs),
                                 index=index)
        if self._index and not append:
            # pandas drops the previous index entirely — project it away
            # so it doesn't resurface as a data column
            exprs = [(n, ColRef(n)) for n in self._data_cols()]
            return BodoDataFrame(L.Projection(self._plan, exprs),
                                 index=[(k, k) for k in keys])
        index = (self._index if append else []) + [(k, k) for k in keys]
        return BodoDataFrame(self._plan, index=index)

    def reset_index(self, drop: bool = False) -> "BodoDataFrame":
        if not self._index:
            return BodoDataFrame(self._plan)
        if drop:
            exprs = [(n, ColRef(n)) for n in self._data_cols()]
            return BodoDataFrame(L.Projection(self._plan, exprs))
        exprs = []
        for i, (c, disp) in enumerate(self._index):
            name = disp if disp is not None else (
                "index" if len(self._index) == 1 else f"level_{i}")
            exprs.append((name, ColRef(c)))
        exprs += [(n, ColRef(n)) for n in self._data_cols()]
        return BodoDataFrame(L.Projection(self._plan, exprs))

    def sort_index(self, ascending: bool = True) -> "BodoDataFrame":
        if not self._index:
            return self
        by = self._index_cols()
        return BodoDataFrame(
            L.Sort(self._plan, by, [ascending] * len(by)),
            index=self._index)

    @property
    def index(self) -> pd.Index:
        return self.to_pandas().index

    # ---- materialization ---------------------------------------------------
    def _execute(self):
        from bodo_tpu.plan.physical import execute
        return execute(self._plan)

    def explain_analyze(self) -> str:
        """Execute this frame's plan under a query span and render the
        EXPLAIN ANALYZE tree (observed rows/bytes/wall/AQE decisions per
        node). Requires tracing (set_config(tracing_level=1))."""
        from bodo_tpu.plan import explain
        from bodo_tpu.utils import tracing
        with tracing.query_span() as qid:
            self._execute()
        return explain.explain_analyze(qid)

    def to_pandas(self) -> pd.DataFrame:
        pdf = self._execute().to_pandas()
        if not self._index:
            return pdf
        icols = self._index_cols()
        pdf = pdf.set_index(icols)[self._data_cols()]
        pdf.index.names = [d for _, d in self._index]
        return pdf

    def __len__(self) -> int:
        return self._execute().nrows

    def __repr__(self) -> str:  # pragma: no cover
        head = self.head(10).to_pandas()
        n = len(self)
        return repr(head) + f"\n[{n} rows x {len(self._data_cols())} columns]"

    def __setattr__(self, name, value):  # guard accidental attr writes
        if name.startswith("_"):
            object.__setattr__(self, name, value)
        else:
            self[name] = value
