"""BodoSeries: lazy column expression bound to a plan.

Analogue of the reference's BodoSeries (bodo/pandas/series.py) with the
str/dt accessors (reference: series_str_impl.py, series_dt_impl.py
surfaces). A Series is (plan, expr): arithmetic composes expressions
without execution; reductions execute a Reduce node; comparisons against
strings rewrite to dictionary-code predicates (StrPredicate) since raw
strings never reach the device.
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np
import pandas as pd

from bodo_tpu.plan import logical as L
from bodo_tpu.plan.expr import (BinOp, Cast, ColRef, DtField, Expr, IsIn,
                                Lit, StrPredicate, UnOp, Where, infer_dtype)
from bodo_tpu.table import dtypes as dt
from bodo_tpu.utils.logging import warn_fallback

_REDUCTIONS = ("sum", "mean", "min", "max", "count", "var", "std", "prod")


def validate_expr_trace(expr: Expr, schema):
    """Cheaply check an expression (e.g. a UDF) traces on this schema by
    evaluating it on a 4-row zero tree. Returns the traced output numpy
    dtype on success, None on failure."""
    import jax.numpy as jnp

    from bodo_tpu.plan.expr import eval_expr
    try:
        tree = {n: (jnp.zeros(4, dtype=t.numpy), None)
                for n, t in schema.items()}
        dicts = {n: np.array(["a"], dtype=str) for n, t in schema.items()
                 if t is dt.STRING}
        out, _ = eval_expr(expr, tree, dicts, schema)
        return np.dtype(out.dtype)
    except Exception:
        return None


def _ddof_op(op: str, ddof: int) -> str:
    """var/std with ddof 0/1 map to dedicated ops; others are unsupported."""
    if ddof == 1:
        return op
    if ddof == 0:
        return op + "0"
    raise NotImplementedError(f"{op} with ddof={ddof} (only 0 and 1)")


class BodoSeries:
    def __init__(self, plan: L.Node, expr: Expr, name: str = None,
                 index=None):
        self._plan = plan
        self._expr = expr
        # [(plan_column, display_name)] — same index-as-column threading
        # as BodoDataFrame (set by indexed frames / groupby as_index)
        self._index = list(index) if index else []
        self._name = name if name is not None else (
            expr.name if isinstance(expr, ColRef) else None)
        self._categorical = False  # astype('category') materialization flag

    # ---- dtype ------------------------------------------------------------
    @property
    def _dtype(self) -> dt.DType:
        return infer_dtype(self._expr, self._plan.schema)

    @property
    def dtype(self):
        d = self._dtype
        return np.dtype(d.np_dtype) if d is not dt.STRING else np.dtype("O")

    @property
    def name(self):
        return self._name

    # ---- expression building ----------------------------------------------
    def _wrap(self, expr: Expr, name=None) -> "BodoSeries":
        return BodoSeries(self._plan, expr, name or self._name,
                          index=self._index)

    def _coerce(self, other):
        """Other operand → Expr (string literals become predicates at the
        comparison level, handled in _cmp)."""
        if isinstance(other, BodoSeries):
            if other._plan is not self._plan:
                raise ValueError(
                    "cannot combine Series from different frames lazily; "
                    "merge the frames first")
            return other._expr
        if isinstance(other, pd.Timestamp):
            return Lit(np.datetime64(other.to_datetime64()))
        return Lit(other)

    def _bin(self, op, other, reverse=False):
        o = self._coerce(other)
        e = BinOp(op, o, self._expr) if reverse else BinOp(op, self._expr, o)
        return self._wrap(e, None)

    def _cmp(self, op, other):
        # string comparison → dictionary predicate
        if isinstance(other, str) and self._dtype is dt.STRING:
            if op == "==":
                return self._wrap(StrPredicate("eq_any", (other,), self._expr))
            if op == "!=":
                return self._wrap(UnOp("~", StrPredicate(
                    "eq_any", (other,), self._expr)))
            raise TypeError(f"string ordering comparison {op} unsupported")
        return self._bin(op, other)

    def __add__(self, o): return self._bin("+", o)
    def __radd__(self, o): return self._bin("+", o, True)
    def __sub__(self, o): return self._bin("-", o)
    def __rsub__(self, o): return self._bin("-", o, True)
    def __mul__(self, o): return self._bin("*", o)
    def __rmul__(self, o): return self._bin("*", o, True)
    def __truediv__(self, o): return self._bin("/", o)
    def __rtruediv__(self, o): return self._bin("/", o, True)
    def __floordiv__(self, o): return self._bin("//", o)
    def __mod__(self, o): return self._bin("%", o)
    def __pow__(self, o): return self._bin("**", o)
    def __eq__(self, o): return self._cmp("==", o)  # type: ignore[override]
    def __ne__(self, o): return self._cmp("!=", o)  # type: ignore[override]
    def __lt__(self, o): return self._cmp("<", o)
    def __le__(self, o): return self._cmp("<=", o)
    def __gt__(self, o): return self._cmp(">", o)
    def __ge__(self, o): return self._cmp(">=", o)
    def __and__(self, o): return self._bin("&", o)
    def __or__(self, o): return self._bin("|", o)
    def __invert__(self): return self._wrap(UnOp("~", self._expr))
    def __neg__(self): return self._wrap(UnOp("neg", self._expr))
    def __abs__(self): return self._wrap(UnOp("abs", self._expr))
    def abs(self): return self._wrap(UnOp("abs", self._expr))
    __hash__ = None  # type: ignore[assignment]

    def isin(self, values):
        vals = tuple(values)
        if self._dtype is dt.STRING:
            return self._wrap(StrPredicate("eq_any", vals, self._expr))
        return self._wrap(IsIn(self._expr, vals))

    def isna(self): return self._wrap(UnOp("isna", self._expr))
    def notna(self): return self._wrap(UnOp("notna", self._expr))
    def fillna(self, v): return self._wrap(
        Where(UnOp("isna", self._expr), Lit(v), self._expr))

    def astype(self, dtype) -> "BodoSeries":
        if dtype in ("category", "Category") or (
                isinstance(dtype, pd.CategoricalDtype)):
            if self._dtype is not dt.STRING:
                warn_fallback("Series.astype", "category of non-string")
                return self.to_pandas().astype("category")
            # strings are already dict-encoded — categorical is a
            # materialization flag, not a representation change
            # (reference: bodo/hiframes/pd_categorical_ext.py)
            out = self._wrap(self._expr)
            out._categorical = True
            return out
        return self._wrap(Cast(self._expr, dt.from_numpy(np.dtype(dtype))))

    def where(self, cond, other) -> "BodoSeries":
        c = cond._expr if isinstance(cond, BodoSeries) else Lit(cond)
        o = other._expr if isinstance(other, BodoSeries) else Lit(other)
        return self._wrap(Where(c, self._expr, o))

    # ---- window / cumulative --------------------------------------------
    def _window(self, op: str, param=None):
        if self._dtype.kind not in ("i", "u", "f", "b"):
            # temporal/string physical reprs would round-trip through
            # float64 (lossy above 2^53 ns) — use genuine pandas
            warn_fallback(f"Series.{op}", f"{self._dtype.name} dtype")
            pds = self.to_pandas()
            if op.startswith("rolling_"):
                return getattr(pds.rolling(param), op[len("rolling_"):])()
            if op in ("shift", "diff"):
                return getattr(pds, op)(param)
            return getattr(pds, op)()
        name = self._name or "_val"
        base = self._as_projection(name)
        out = f"__w_{name}"
        node = L.Window(base, [(name, op, param, out)])
        return BodoSeries(node, ColRef(out), self._name)

    def cumsum(self): return self._window("cumsum")
    def cumprod(self): return self._window("cumprod")
    def cummax(self): return self._window("cummax")
    def cummin(self): return self._window("cummin")

    def shift(self, periods: int = 1):
        if periods < 1:
            warn_fallback("Series.shift", "non-positive periods")
            return self.to_pandas().shift(periods)
        return self._window("shift", periods)

    def diff(self, periods: int = 1):
        if periods < 1:
            warn_fallback("Series.diff", "non-positive periods")
            return self.to_pandas().diff(periods)
        return self._window("diff", periods)

    def rolling(self, window: int, min_periods=None):
        if min_periods is not None and min_periods != window:
            warn_fallback("Series.rolling", "min_periods != window")
            return self.to_pandas().rolling(window, min_periods=min_periods)
        return _Rolling(self, window)

    # ---- accessors ----------------------------------------------------------
    @property
    def dt(self):
        return _DtAccessor(self)

    @property
    def ai(self):
        from bodo_tpu.ai.series import AiAccessor
        return AiAccessor(self)

    @property
    def str(self):
        return _StrAccessor(self)

    @property
    def cat(self):
        return _CatAccessor(self)

    @property
    def list(self):
        return _ListAccessor(self)

    @property
    def struct(self):
        return _StructAccessor(self)

    def _nested_column(self):
        """Materialize this series' column (nested accessors are eager —
        they need the host dictionary)."""
        from bodo_tpu.plan.physical import execute
        name = self._name or "_val"
        t = execute(self._as_projection(name))
        return t, t.column(name), name

    def _wrap_column(self, t, col, name) -> "BodoSeries":
        from bodo_tpu.table.table import Table
        out = Table({name: col}, t.nrows, t.distribution, t.counts)
        return BodoSeries(L.FromPandas(out), ColRef(name), self._name)

    # ---- reductions ---------------------------------------------------------
    def _reduce(self, op):
        name = self._name or "_val"
        node = L.Reduce(self._as_projection(name), [(name, op, name)])
        from bodo_tpu.plan.physical import execute
        t = execute(node)
        return t.to_pandas()[name].iloc[0]

    def sum(self): return self._reduce("sum")
    def mean(self): return self._reduce("mean")
    def min(self): return self._reduce("min")
    def max(self): return self._reduce("max")
    def count(self): return self._reduce("count")
    def prod(self): return self._reduce("prod")

    def var(self, ddof: int = 1):
        return self._reduce(_ddof_op("var", ddof))

    def std(self, ddof: int = 1):
        return self._reduce(_ddof_op("std", ddof))

    def median(self):
        return self._reduce("median")

    def quantile(self, q=0.5):
        if not isinstance(q, (int, float)):
            warn_fallback("Series.quantile", "list of quantiles")
            return self.to_pandas().quantile(q)
        return self._reduce(f"quantile_{float(q)}")

    def sort_values(self, ascending: bool = True) -> "BodoSeries":
        name = self._name or "_val"
        node = L.Sort(self._as_projection(name), [name], [bool(ascending)])
        return BodoSeries(node, ColRef(name), self._name)

    def nlargest(self, n: int = 5) -> pd.Series:
        return self.sort_values(ascending=False).head(n)

    def nsmallest(self, n: int = 5) -> pd.Series:
        return self.sort_values(ascending=True).head(n)

    def nunique(self):
        name = self._name or "_val"
        node = L.Aggregate(self._as_projection(name), [name], [])
        from bodo_tpu.plan.physical import execute
        return execute(node).nrows

    def unique(self):
        name = self._name or "_val"
        node = L.Aggregate(self._as_projection(name), [name], [])
        from bodo_tpu.plan.physical import execute
        return execute(node).to_pandas()[name].to_numpy()

    def value_counts(self, ascending: bool = False):
        name = self._name or "_val"
        proj = self._as_projection(name)
        agg = L.Aggregate(proj, [name], [(name, "size", "count")])
        srt = L.Sort(agg, ["count"], [ascending])
        from bodo_tpu.plan.physical import execute
        pdf = execute(srt).to_pandas()
        s = pd.Series(pdf["count"].to_numpy(),
                      index=pd.Index(pdf[name], name=name), name="count")
        return s

    # ---- materialization ------------------------------------------------
    def _as_projection(self, name: Optional[str] = None) -> L.Node:
        name = name or self._name or "_val"
        exprs = [(name, self._expr)]
        exprs += [(c, ColRef(c)) for c, _ in self._index if c != name]
        return L.Projection(self._plan, exprs)

    def _finish(self, t, name: str) -> pd.Series:
        pdf = t.to_pandas()
        if self._index:
            icols = [c for c, _ in self._index if c != name]
            if icols:
                pdf = pdf.set_index(icols)
                pdf.index.names = [d for (c, d) in self._index if c != name]
        out = pdf[name].rename(self._name)
        if self._categorical:
            out = out.astype("category")
        return out

    def to_pandas(self) -> pd.Series:
        from bodo_tpu.plan.physical import execute
        name = self._name or "_val"
        return self._finish(execute(self._as_projection(name)), name)

    def head(self, n: int = 5) -> pd.Series:
        from bodo_tpu.plan.physical import execute
        name = self._name or "_val"
        return self._finish(execute(L.Limit(self._as_projection(name), n)),
                            name)

    def reset_index(self, drop: bool = False):
        if drop or not self._index:
            return BodoSeries(self._plan, self._expr, self._name)
        from bodo_tpu.pandas_api.frame import BodoDataFrame
        name = self._name or "_val"
        exprs = []
        for i, (c, disp) in enumerate(self._index):
            out = disp if disp is not None else (
                "index" if len(self._index) == 1 else f"level_{i}")
            exprs.append((out, ColRef(c)))
        exprs.append((name, self._expr))
        return BodoDataFrame(L.Projection(self._plan, exprs))

    def sort_index(self, ascending: bool = True) -> "BodoSeries":
        if not self._index:
            return self
        by = [c for c, _ in self._index]
        node = L.Sort(self._plan, by, [ascending] * len(by))
        return BodoSeries(node, self._expr, self._name, index=self._index)

    @property
    def index(self) -> pd.Index:
        return self.to_pandas().index

    def __array__(self, dtype=None, copy=None):
        return np.asarray(self.to_pandas(), dtype=dtype)

    def __len__(self):
        from bodo_tpu.plan.physical import execute
        return execute(self._plan).nrows

    def __repr__(self):  # pragma: no cover
        return f"BodoSeries(name={self._name}, dtype={self._dtype.name})\n" \
            + repr(self.head(10))

    def map(self, arg):
        """dict mappings compile to a device Where-chain; numeric callables
        compile to a vmapped kernel; string mappers fall back to pandas."""
        if isinstance(arg, dict) and len(arg) <= 64 and \
                self._dtype is not dt.STRING:
            vals = list(arg.items())
            default = Lit(np.nan)
            expr: Expr = default
            for k, v in reversed(vals):
                expr = Where(BinOp("==", self._expr, Lit(k)), Lit(v), expr)
            return self._wrap(expr)
        if callable(arg) and self._dtype.kind in ("i", "u", "f", "b"):
            from bodo_tpu.plan.expr import RowUDF
            e = RowUDF(arg, None, self._expr)
            traced = validate_expr_trace(e, self._plan.schema)
            if traced is not None:
                return self._wrap(RowUDF(arg, dt.from_numpy(traced),
                                         self._expr))
        warn_fallback("Series.map", "uncompilable or string mapper")
        return self.to_pandas().map(arg)

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        if hasattr(pd.Series, name):
            warn_fallback(f"Series.{name}", "not yet lazy")
            attr = getattr(self.to_pandas(), name)
            return attr
        raise AttributeError(name)


class _Rolling:
    """Series.rolling(w) — fixed windows, halo-exchange across shards."""

    def __init__(self, s: BodoSeries, window: int):
        self._s = s
        self._w = window

    def _agg(self, op):
        return self._s._window(f"rolling_{op}", self._w)

    def sum(self): return self._agg("sum")
    def mean(self): return self._agg("mean")
    def min(self): return self._agg("min")
    def max(self): return self._agg("max")
    def count(self): return self._agg("count")


class _ListAccessor:
    """Series.list — list-column element access (pandas ArrowDtype
    .list accessor surface; reference bodo/libs/array_item_arr_ext.py).
    Eager: kernels are host-dictionary LUTs gathered on device."""

    def __init__(self, s: BodoSeries):
        if s._dtype.kind not in ("list", "map"):
            raise AttributeError(".list requires a list column")
        self._s = s

    def len(self) -> BodoSeries:
        from bodo_tpu.table import nested as N
        from bodo_tpu.table.table import Column
        t, col, name = self._s._nested_column()
        data, valid = N.list_lengths(col)
        return self._s._wrap_column(t, Column(data, valid, dt.INT64, None),
                                    name)

    def __getitem__(self, i: int) -> BodoSeries:
        return self.get(i)

    def get(self, i: int) -> BodoSeries:
        if self._s._dtype.kind == "map":
            raise NotImplementedError(
                ".list.get on a map column — use .struct.field(key)")
        from bodo_tpu.table import nested as N
        t, col, name = self._s._nested_column()
        return self._s._wrap_column(t, N.list_get(col, int(i)), name)


class _StructAccessor:
    """Series.struct — struct field projection (pandas ArrowDtype
    .struct accessor surface; reference bodo/libs/struct_arr_ext.py)."""

    def __init__(self, s: BodoSeries):
        if s._dtype.kind not in ("struct", "map"):
            raise AttributeError(".struct requires a struct column")
        self._s = s

    def field(self, name: str) -> BodoSeries:
        from bodo_tpu.table import nested as N
        t, col, cname = self._s._nested_column()
        if col.dtype.kind == "map":
            out = N.map_get(col, name)
        else:
            out = N.struct_field(col, name)
        res = self._s._wrap_column(t, out, cname)
        res._name = name
        return res


class _CatAccessor:
    """Series.cat — categorical introspection over the dict encoding
    (reference: bodo/hiframes/pd_categorical_ext.py). Strings are
    dictionary-encoded with a sorted dictionary, so the dictionary IS
    the category array.

    Divergence from pandas (intentional): the dictionary persists
    through filters, so after a filter removes every row of some
    category, `.cat.codes`/`.cat.categories` still reflect the FULL
    dictionary while pandas' `astype('category')` renumbers codes over
    the remaining uniques. This matches the engine-wide rule that
    dictionaries are value domains, not observed-value sets (same as
    the reference's dict-encoded arrays, bodo/libs/dict_arr_ext.py)."""

    def __init__(self, s: BodoSeries):
        if s._dtype is not dt.STRING:
            raise AttributeError(".cat requires a string/categorical series")
        self._s = s

    @property
    def codes(self) -> BodoSeries:
        from bodo_tpu.plan.expr import StrCodes
        return self._s._wrap(StrCodes(self._s._expr))

    @property
    def categories(self) -> pd.Index:
        from bodo_tpu.plan.physical import execute
        name = self._s._name or "_val"
        t = execute(self._s._as_projection(name))
        d = t.column(name).dictionary
        return pd.Index(d if d is not None else [], dtype=object)

    def as_ordered(self):  # dictionary order is sorted already
        return self._s


class _DtAccessor:
    """Series.dt — datetime field extraction (device kernels)."""

    def __init__(self, s: BodoSeries):
        self._s = s

    def __getattr__(self, field):
        from bodo_tpu.ops.datetime import FIELDS
        if field in FIELDS:
            return self._s._wrap(DtField(field, self._s._expr))
        raise AttributeError(f"dt.{field} not supported")

    def isocalendar(self):  # pragma: no cover
        raise NotImplementedError


class _StrAccessor:
    """Series.str — predicates evaluate on the host dictionary (LUT)."""

    def __init__(self, s: BodoSeries):
        self._s = s

    def contains(self, pat, regex: bool = False):
        kind = "match" if regex else "contains"
        pat_ = (".*" + pat,) if regex else (pat,)
        return self._s._wrap(StrPredicate(kind, pat_, self._s._expr))

    def startswith(self, pat):
        pats = (pat,) if isinstance(pat, str) else tuple(pat)
        return self._s._wrap(StrPredicate("startswith", pats, self._s._expr))

    def endswith(self, pat):
        pats = (pat,) if isinstance(pat, str) else tuple(pat)
        return self._s._wrap(StrPredicate("endswith", pats, self._s._expr))

    def match(self, pat):
        return self._s._wrap(StrPredicate("match", (pat,), self._s._expr))

    # ---- dictionary transforms (host LUT, device code remap) -------------
    def _map(self, kind, *params):
        from bodo_tpu.plan.expr import DictMap
        return self._s._wrap(DictMap(kind, tuple(params), self._s._expr))

    def upper(self): return self._map("upper")
    def lower(self): return self._map("lower")
    def title(self): return self._map("title")
    def capitalize(self): return self._map("capitalize")
    def strip(self, to_strip=None):
        return self._map("strip", *(() if to_strip is None else (to_strip,)))
    def lstrip(self, to_strip=None):
        return self._map("lstrip",
                         *(() if to_strip is None else (to_strip,)))
    def rstrip(self, to_strip=None):
        return self._map("rstrip",
                         *(() if to_strip is None else (to_strip,)))
    def replace(self, old, new, regex: bool = False):
        if regex:
            warn_fallback("Series.str.replace", "regex=True")
            return self._s.to_pandas().str.replace(old, new, regex=True)
        return self._map("replace", old, new)
    def slice(self, start=None, stop=None):
        return self._map("slice", start, stop)
    def zfill(self, width: int): return self._map("zfill", width)

    def len(self):
        from bodo_tpu.plan.expr import StrLen
        return self._s._wrap(StrLen(self._s._expr))

    def fullmatch(self, pat):
        return self._s._wrap(StrPredicate("fullmatch", (pat,),
                                          self._s._expr))

    def isin(self, values):
        return self._s._wrap(StrPredicate("eq_any", tuple(values),
                                          self._s._expr))

    def pad(self, width: int, side: str = "left", fillchar: str = " "):
        kind = {"left": "rjust", "right": "ljust", "both": "center"}[side]
        return self._map(kind, width, fillchar)

    def ljust(self, width: int, fillchar: str = " "):
        return self._map("ljust", width, fillchar)

    def rjust(self, width: int, fillchar: str = " "):
        return self._map("rjust", width, fillchar)

    def center(self, width: int, fillchar: str = " "):
        return self._map("center", width, fillchar)

    def repeat(self, repeats: int):
        return self._map("repeat", int(repeats))

    def get(self, i: int):
        return self._map("get", int(i))

    def find(self, sub: str):
        from bodo_tpu.plan.expr import BinOp, Lit, StrHostFn
        # pandas find is 0-based with -1 absent; position is 1-based/0
        return self._s._wrap(BinOp(
            "-", StrHostFn("position", (sub,), self._s._expr), Lit(1)))

    def count(self, pat: str):
        from bodo_tpu.plan.expr import StrHostFn
        return self._s._wrap(StrHostFn("regexp_count", (pat,),
                                       self._s._expr))

    def cat(self, others=None, sep: str = ""):
        from bodo_tpu.plan.expr import StrConcat
        if others is None:
            warn_fallback("Series.str.cat", "reduction form")
            return self._s.to_pandas().str.cat(sep=sep)
        parts = [self._s._expr]
        olist = others if isinstance(others, (list, tuple)) else [others]
        for o in olist:
            if sep:
                parts.append(sep)
            parts.append(o._expr if isinstance(o, BodoSeries) else str(o))
        return self._s._wrap(StrConcat(tuple(parts)))

    def split(self, pat=None, n: int = -1, expand: bool = False):
        """Split on the host dictionary: each output part is a new
        dict-encoded column sharing the original codes (reference:
        bodo/libs/dict_arr_ext.py str_split). expand=False returns a
        dict-encoded list<string> column (table/nested.py design)."""
        if not expand:
            from bodo_tpu.plan.expr import StrToList
            return self._s._wrap(StrToList((pat, n), self._s._expr))
        import numpy as np

        from bodo_tpu.pandas_api.frame import BodoDataFrame
        from bodo_tpu.plan import logical as L
        from bodo_tpu.plan.physical import execute
        from bodo_tpu.table.table import Column, Table
        name = self._s._name or "_val"
        t = execute(self._s._as_projection(name))
        src = t.column(name)
        dic = src.dictionary if src.dictionary is not None else \
            np.array([], dtype=str)
        parts = [s.split(pat) if n <= 0 else s.split(pat, n) for s in dic]
        width = max((len(p) for p in parts), default=0)
        import jax.numpy as jnp
        cols = {}
        for i in range(width):
            vals = np.array([p[i] if i < len(p) else "" for p in parts],
                            dtype=str)
            uniq, inv = (np.unique(vals, return_inverse=True)
                         if len(vals) else (np.array([], dtype=str),
                                            np.zeros(0, np.int64)))
            lut = jnp.asarray(inv.astype(np.int32) if len(inv)
                              else np.zeros(1, np.int32))
            has = np.array([i < len(p) for p in parts], dtype=bool)
            hlut = jnp.asarray(has if len(has) else np.zeros(1, bool))
            codes = jnp.clip(src.data, 0, max(len(dic) - 1, 0))
            valid = hlut[codes]
            if src.valid is not None:
                valid = valid & src.valid
            cols[str(i)] = Column(lut[codes], valid, src.dtype, uniq)
        out = Table(cols, t.nrows, t.distribution, t.counts)
        return BodoDataFrame(L.FromPandas(out))

    def __getattr__(self, name):
        if hasattr(pd.Series.str, name):
            warn_fallback(f"Series.str.{name}", "not yet lazy")
            return getattr(self._s.to_pandas().str, name)
        raise AttributeError(name)
