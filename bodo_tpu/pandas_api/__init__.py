"""Lazy drop-in dataframe library (the `bodo.pandas` analogue).

Mirrors the reference's lazy frontend (bodo/pandas/ — BodoDataFrame
frame.py:117, BodoSeries series.py, read entry points base.py:74-392):
every operation builds a logical plan node; execution triggers on
materialization points (to_pandas/len/repr/write). Unsupported APIs fall
back to real pandas with a warning (check_args_fallback semantics,
bodo/pandas/utils.py:346).
"""

from bodo_tpu.pandas_api.frame import BodoDataFrame
from bodo_tpu.pandas_api.series import BodoSeries
from bodo_tpu.plan import logical as L

__all__ = ["BodoDataFrame", "BodoSeries", "read_parquet", "read_csv",
           "from_pandas", "concat"]


def read_parquet(path, columns=None) -> BodoDataFrame:
    return BodoDataFrame(L.ReadParquet(path, columns))


def read_csv(path, columns=None, parse_dates=None) -> BodoDataFrame:
    return BodoDataFrame(L.ReadCsv(path, columns, parse_dates))


def from_pandas(df) -> BodoDataFrame:
    return BodoDataFrame(L.FromPandas(df))


def read_iceberg(table_path, columns=None, snapshot_id=None
                 ) -> BodoDataFrame:
    """Local-warehouse Iceberg table → lazy frame (reference:
    bodo/pandas/base.py:313 read_iceberg; filesystem catalogs only —
    io/iceberg.py). Only the METADATA is read here: the snapshot's data
    files become a lazy parquet scan, so column pruning and filter
    pushdown still reach the file reads."""
    from bodo_tpu.io.iceberg import (_current_metadata, _data_files,
                                     _snapshot)
    meta, _ = _current_metadata(table_path)
    files = _data_files(table_path, _snapshot(meta, snapshot_id))
    return BodoDataFrame(L.ReadParquet(tuple(files), columns))


def concat(frames, ignore_index: bool = True) -> BodoDataFrame:
    """Row-wise concat of schema-compatible lazy frames (pd.concat
    analogue; UNION ALL underneath)."""
    import pandas as pd
    plans = []
    for f in frames:
        if isinstance(f, pd.DataFrame):
            f = from_pandas(f)
        plans.append(f._plan)
    if len(plans) == 1:
        return BodoDataFrame(plans[0])
    return BodoDataFrame(L.Union(plans))
