"""Lazy drop-in dataframe library (the `bodo.pandas` analogue).

Mirrors the reference's lazy frontend (bodo/pandas/ — BodoDataFrame
frame.py:117, BodoSeries series.py, read entry points base.py:74-392):
every operation builds a logical plan node; execution triggers on
materialization points (to_pandas/len/repr/write). Unsupported APIs fall
back to real pandas with a warning (check_args_fallback semantics,
bodo/pandas/utils.py:346).
"""

from bodo_tpu.pandas_api.frame import BodoDataFrame
from bodo_tpu.pandas_api.series import BodoSeries
from bodo_tpu.plan import logical as L

__all__ = ["BodoDataFrame", "BodoSeries", "read_parquet", "read_csv",
           "from_pandas", "concat"]


def read_parquet(path, columns=None) -> BodoDataFrame:
    return BodoDataFrame(L.ReadParquet(path, columns))


def read_csv(path, columns=None, parse_dates=None) -> BodoDataFrame:
    return BodoDataFrame(L.ReadCsv(path, columns, parse_dates))


def from_pandas(df) -> BodoDataFrame:
    return BodoDataFrame(L.FromPandas(df))


def read_iceberg(table_path, columns=None, snapshot_id=None
                 ) -> BodoDataFrame:
    """Local-warehouse Iceberg table → lazy frame (reference:
    bodo/pandas/base.py:313 read_iceberg; filesystem catalogs only —
    io/iceberg.py)."""
    from bodo_tpu.io.iceberg import read_iceberg as _ri
    return BodoDataFrame(L.FromPandas(
        _ri(table_path, columns=columns, snapshot_id=snapshot_id)))


def concat(frames, ignore_index: bool = True) -> BodoDataFrame:
    """Row-wise concat of schema-compatible lazy frames (pd.concat
    analogue; UNION ALL underneath)."""
    import pandas as pd
    plans = []
    for f in frames:
        if isinstance(f, pd.DataFrame):
            f = from_pandas(f)
        plans.append(f._plan)
    if len(plans) == 1:
        return BodoDataFrame(plans[0])
    return BodoDataFrame(L.Union(plans))
