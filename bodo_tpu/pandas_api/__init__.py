"""Lazy drop-in dataframe library (the `bodo.pandas` analogue).

Mirrors the reference's lazy frontend (bodo/pandas/ — BodoDataFrame
frame.py:117, BodoSeries series.py, read entry points base.py:74-392):
every operation builds a logical plan node; execution triggers on
materialization points (to_pandas/len/repr/write). Unsupported APIs fall
back to real pandas with a warning (check_args_fallback semantics,
bodo/pandas/utils.py:346).
"""

from bodo_tpu.pandas_api.frame import BodoDataFrame
from bodo_tpu.pandas_api.series import BodoSeries
from bodo_tpu.plan import logical as L

__all__ = ["BodoDataFrame", "BodoSeries", "read_parquet", "read_csv",
           "read_json", "from_pandas", "concat"]


def read_parquet(path, columns=None) -> BodoDataFrame:
    return BodoDataFrame(L.ReadParquet(path, columns))


def read_csv(path, columns=None, parse_dates=None, chunksize=None,
             iterator=False):
    """Lazy CSV scan. With `chunksize` (or `iterator=True`) returns an
    iterator of pandas DataFrames parsed chunk-at-a-time with bounded
    host memory (pandas TextFileReader analogue; reference:
    bodo/io/csv_iterator_ext.py)."""
    if chunksize is not None or iterator:
        if chunksize is not None and chunksize < 1:
            raise ValueError(
                f"chunksize must be >= 1, got {chunksize}")
        from bodo_tpu.io.csv import read_csv_chunked
        return read_csv_chunked(path,
                                1_000_000 if chunksize is None
                                else chunksize,
                                columns, parse_dates)
    return BodoDataFrame(L.ReadCsv(path, columns, parse_dates))


def read_json(path, columns=None, chunksize=None):
    """JSON-lines scan. With `chunksize`, an iterator of pandas
    DataFrames (byte-range chunked parse, bounded host memory);
    otherwise an eager whole-file read into a lazy frame (reference:
    bodo/ir/json_ext.py)."""
    if chunksize is not None:
        from bodo_tpu.io.json import read_json_chunked
        return read_json_chunked(path, chunksize, columns)
    from bodo_tpu.io.json import read_json as _rj
    t = _rj(path, columns)
    return BodoDataFrame(L.FromPandas(t))


def from_pandas(df) -> BodoDataFrame:
    return BodoDataFrame(L.FromPandas(df))


def read_iceberg(table_path, columns=None, snapshot_id=None
                 ) -> BodoDataFrame:
    """Local-warehouse Iceberg table → lazy frame (reference:
    bodo/pandas/base.py:313 read_iceberg; filesystem catalogs only —
    io/iceberg.py). Only the METADATA is read here: the snapshot's data
    files become a lazy parquet scan, so column pruning and filter
    pushdown still reach the file reads."""
    from bodo_tpu.io.iceberg import (_current_metadata, _data_files,
                                     _snapshot)
    meta, _ = _current_metadata(table_path)
    files = _data_files(table_path, _snapshot(meta, snapshot_id))
    return BodoDataFrame(L.ReadParquet(tuple(files), columns))


def concat(frames, ignore_index: bool = True) -> BodoDataFrame:
    """Row-wise concat of schema-compatible lazy frames (pd.concat
    analogue; UNION ALL underneath)."""
    import pandas as pd
    plans = []
    for f in frames:
        if isinstance(f, pd.DataFrame):
            f = from_pandas(f)
        plans.append(f._plan)
    if len(plans) == 1:
        return BodoDataFrame(plans[0])
    return BodoDataFrame(L.Union(plans))
