"""Shared NYC-taxi-shaped workload: data generator, pandas oracle, and the
bodo_tpu pipeline. Used by the e2e test, bench.py, and __graft_entry__.py.

Mirrors the reference benchmark get_monthly_travels_weather
(reference: benchmarks/nyc_taxi/bodo/nyc_taxi_precipitation.py): csv+parquet
read, datetime field extraction, inner merge on date, derived bool/bucket
columns, 6-key groupby with count+mean, multi-key sort.
"""

import numpy as np
import pandas as pd

TIME_BUCKETS = ["morning", "midday", "afternoon", "evening", "other"]


def gen_taxi_data(n_rows: int, out_parquet: str, out_csv: str, seed: int = 0):
    r = np.random.default_rng(seed)
    start = np.datetime64("2024-01-01T00:00:00")
    pickup = start + r.integers(0, 180 * 24 * 3600, n_rows).astype(
        "timedelta64[s]")
    df = pd.DataFrame({
        "hvfhs_license_num": r.choice(["HV0002", "HV0003", "HV0004",
                                       "HV0005"], n_rows),
        "PULocationID": r.integers(1, 180, n_rows).astype(np.int64),
        "DOLocationID": r.integers(1, 180, n_rows).astype(np.int64),
        "trip_miles": (r.gamma(2.0, 2.5, n_rows)).astype(np.float64),
        "pickup_datetime": pd.Series(pickup.astype("datetime64[ns]")),
    })
    df.to_parquet(out_parquet)
    dates = pd.date_range("2024-01-01", "2024-06-30", freq="D")
    weather = pd.DataFrame({
        "DATE": dates.strftime("%Y-%m-%d"),
        "PRCP": np.round(np.random.default_rng(seed + 1)
                         .gamma(0.5, 0.3, len(dates)), 2),
    })
    weather.to_csv(out_csv, index=False)
    return df, weather


def pandas_pipeline(trips_path: str, weather_path: str) -> pd.DataFrame:
    """The pandas oracle (the reference benchmark body, pandas flavor)."""
    weather = pd.read_csv(weather_path, parse_dates=["DATE"])
    weather = weather.rename(columns={"DATE": "date", "PRCP": "precipitation"})
    trips = pd.read_parquet(trips_path)
    weather["date"] = weather["date"].dt.date
    trips["date"] = trips["pickup_datetime"].dt.date
    trips["month"] = trips["pickup_datetime"].dt.month
    trips["hour"] = trips["pickup_datetime"].dt.hour
    trips["weekday"] = trips["pickup_datetime"].dt.dayofweek.isin(
        [0, 1, 2, 3, 4])
    m = trips.merge(weather, on="date", how="inner")
    m["date_with_precipitation"] = m["precipitation"] > 0.1

    def bucket(t):
        if t in (8, 9, 10):
            return "morning"
        if t in (11, 12, 13, 14, 15):
            return "midday"
        if t in (16, 17, 18):
            return "afternoon"
        if t in (19, 20, 21):
            return "evening"
        return "other"

    m["time_bucket"] = m.hour.map(bucket)
    keys = ["PULocationID", "DOLocationID", "month", "weekday",
            "date_with_precipitation", "time_bucket"]
    out = m.groupby(keys, as_index=False).agg(
        trip_count=("hvfhs_license_num", "count"),
        avg_miles=("trip_miles", "mean"))
    return out.sort_values(keys).reset_index(drop=True)


def frontend_pipeline(trips_path: str, weather_path: str) -> pd.DataFrame:
    """The same workload through the lazy pandas frontend — written to
    mirror the reference benchmark's dataframe-library flavor nearly
    line-for-line (reference: benchmarks/nyc_taxi/bodo/
    nyc_taxi_precipitation.py get_monthly_travels_weather)."""
    import bodo_tpu.pandas_api as bd

    weather = bd.read_csv(weather_path, parse_dates=["DATE"])
    weather = weather.rename(columns={"DATE": "date", "PRCP": "precipitation"})
    trips = bd.read_parquet(trips_path)

    weather["date"] = weather["date"].dt.date
    trips["date"] = trips["pickup_datetime"].dt.date
    trips["month"] = trips["pickup_datetime"].dt.month
    trips["hour"] = trips["pickup_datetime"].dt.hour
    trips["weekday"] = trips["pickup_datetime"].dt.dayofweek.isin(
        [0, 1, 2, 3, 4])

    m = trips.merge(weather, on="date", how="inner")
    m["date_with_precipitation"] = m["precipitation"] > 0.1
    m["time_bucket"] = m["hour"].map({8: 0, 9: 0, 10: 0,
                                      11: 1, 12: 1, 13: 1, 14: 1, 15: 1,
                                      16: 2, 17: 2, 18: 2,
                                      19: 3, 20: 3, 21: 3}).fillna(4.0) \
        .astype("int32")
    keys = ["PULocationID", "DOLocationID", "month", "weekday",
            "date_with_precipitation", "time_bucket"]
    out = m.groupby(keys, as_index=False).agg(
        trip_count=("hvfhs_license_num", "count"),
        avg_miles=("trip_miles", "mean"))
    res = out.to_pandas()
    bucket_names = np.array(["morning", "midday", "afternoon", "evening",
                             "other"])
    res["time_bucket"] = bucket_names[res["time_bucket"]]
    # sort after mapping so bucket order matches the pandas oracle
    # (alphabetical names, not integer codes)
    return res.sort_values(keys).reset_index(drop=True)


def bodo_tpu_pipeline(trips_path: str, weather_path: str, shard: bool = True):
    """Same workload on the bodo_tpu relational layer. Returns a Table."""
    import bodo_tpu.relational as R
    from bodo_tpu.io import read_csv, read_parquet
    from bodo_tpu.plan.expr import ColRef as c, DtField, IsIn, Lit, Where

    weather = read_csv(weather_path, parse_dates=["DATE"])
    trips = read_parquet(trips_path)
    if shard:
        trips = trips.shard()

    weather = R.assign_columns(weather, {
        "date": DtField("date", c("DATE")),
        "precipitation": c("PRCP"),
    }).select(["date", "precipitation"])

    trips = R.assign_columns(trips, {
        "date": DtField("date", c("pickup_datetime")),
        "month": DtField("month", c("pickup_datetime")),
        "hour": DtField("hour", c("pickup_datetime")),
        "weekday": IsIn(DtField("dayofweek", c("pickup_datetime")),
                        (0, 1, 2, 3, 4)),
    })

    m = R.join_tables(trips, weather, ["date"], ["date"], "inner")
    m = R.assign_columns(m, {
        "date_with_precipitation": c("precipitation") > 0.1,
    })
    code = R.category_code
    h = c("hour")
    bucket_codes = Where(
        IsIn(h, (8, 9, 10)), Lit(code(TIME_BUCKETS, "morning")),
        Where(IsIn(h, (11, 12, 13, 14, 15)), Lit(code(TIME_BUCKETS, "midday")),
              Where(IsIn(h, (16, 17, 18)), Lit(code(TIME_BUCKETS, "afternoon")),
                    Where(IsIn(h, (19, 20, 21)),
                          Lit(code(TIME_BUCKETS, "evening")),
                          Lit(code(TIME_BUCKETS, "other"))))))
    m = R.assign_categorical(m, "time_bucket", bucket_codes, TIME_BUCKETS)

    keys = ["PULocationID", "DOLocationID", "month", "weekday",
            "date_with_precipitation", "time_bucket"]
    out = R.groupby_agg(m, keys, [
        ("hvfhs_license_num", "count", "trip_count"),
        ("trip_miles", "mean", "avg_miles"),
    ])
    out = R.sort_table(out, keys)
    return out
