"""TPC-H workload: schema-faithful data generator + the 22 queries.

Mirrors the reference's TPC-H harnesses (reference: benchmarks/tpch/,
e2e-tests/tpch/ — per-query files q1.py..q22.py). The generator produces
referentially consistent tables at a row-scale factor; queries are the
standard TPC-H texts (spec is public) with scale-appropriate parameters.
"""

from __future__ import annotations

import numpy as np
import pandas as pd

NATIONS = ["ALGERIA", "ARGENTINA", "BRAZIL", "CANADA", "EGYPT", "ETHIOPIA",
           "FRANCE", "GERMANY", "INDIA", "INDONESIA", "IRAN", "IRAQ",
           "JAPAN", "JORDAN", "KENYA", "MOROCCO", "MOZAMBIQUE", "PERU",
           "CHINA", "ROMANIA", "SAUDI ARABIA", "VIETNAM", "RUSSIA",
           "UNITED KINGDOM", "UNITED STATES"]
REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
NATION_REGION = [0, 1, 1, 1, 4, 0, 3, 3, 2, 2, 4, 4, 2, 4, 0, 0, 0, 1, 2,
                 3, 4, 2, 3, 3, 1]
SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "MACHINERY", "HOUSEHOLD"]
PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]
SHIPMODES = ["REG AIR", "AIR", "RAIL", "SHIP", "TRUCK", "MAIL", "FOB"]
TYPES = [f"{a} {b} {c}" for a in ("STANDARD", "SMALL", "MEDIUM", "LARGE",
                                  "ECONOMY", "PROMO")
         for b in ("ANODIZED", "BURNISHED", "PLATED", "POLISHED", "BRUSHED")
         for c in ("TIN", "NICKEL", "BRASS", "STEEL", "COPPER")]
CONTAINERS = [f"{a} {b}" for a in ("SM", "LG", "MED", "JUMBO", "WRAP")
              for b in ("CASE", "BOX", "BAG", "JAR", "PKG", "PACK", "CAN",
                        "DRUM")]


def gen_tpch(n_orders: int = 1500, seed: int = 0):
    """Generate a consistent TPC-H dataset (~n_orders orders; lineitem is
    ~4x that). Row counts scale like the spec's relative sizes."""
    r = np.random.default_rng(seed)
    n_cust = max(10, n_orders // 10)
    n_part = max(20, n_orders // 5)
    n_supp = max(5, n_orders // 100)

    region = pd.DataFrame({
        "r_regionkey": np.arange(5, dtype=np.int64),
        "r_name": REGIONS,
        "r_comment": [f"region {i}" for i in range(5)],
    })
    nation = pd.DataFrame({
        "n_nationkey": np.arange(len(NATIONS), dtype=np.int64),
        "n_name": NATIONS,
        "n_regionkey": np.asarray(NATION_REGION, dtype=np.int64),
        "n_comment": [f"nation {i}" for i in range(len(NATIONS))],
    })
    supplier = pd.DataFrame({
        "s_suppkey": np.arange(n_supp, dtype=np.int64),
        "s_name": [f"Supplier#{i:09d}" for i in range(n_supp)],
        "s_address": [f"addr{i}" for i in range(n_supp)],
        "s_nationkey": r.integers(0, len(NATIONS), n_supp),
        "s_phone": [f"{r.integers(10, 35)}-{i:07d}" for i in range(n_supp)],
        "s_acctbal": np.round(r.uniform(-999, 9999, n_supp), 2),
        "s_comment": r.choice(["reliable", "slow Customer Complaints",
                               "quick", "steady"], n_supp),
    })
    part = pd.DataFrame({
        "p_partkey": np.arange(n_part, dtype=np.int64),
        "p_name": [f"{r.choice(['green','blue','red','ivory','misty'])} "
                   f"{r.choice(['almond','tomato','salmon','olive'])} part{i}"
                   for i in range(n_part)],
        "p_mfgr": [f"Manufacturer#{r.integers(1, 6)}" for _ in range(n_part)],
        "p_brand": [f"Brand#{r.integers(1, 6)}{r.integers(1, 6)}"
                    for _ in range(n_part)],
        "p_type": r.choice(TYPES, n_part),
        "p_size": r.integers(1, 51, n_part),
        "p_container": r.choice(CONTAINERS, n_part),
        "p_retailprice": np.round(r.uniform(900, 2000, n_part), 2),
        "p_comment": [f"part comment {i}" for i in range(n_part)],
    })
    n_ps = n_part * 4
    partsupp = pd.DataFrame({
        "ps_partkey": np.repeat(np.arange(n_part, dtype=np.int64), 4),
        "ps_suppkey": r.integers(0, n_supp, n_ps),
        "ps_availqty": r.integers(1, 10000, n_ps),
        "ps_supplycost": np.round(r.uniform(1, 1000, n_ps), 2),
        "ps_comment": [f"ps comment {i}" for i in range(n_ps)],
    }).drop_duplicates(["ps_partkey", "ps_suppkey"]).reset_index(drop=True)
    customer = pd.DataFrame({
        "c_custkey": np.arange(n_cust, dtype=np.int64),
        "c_name": [f"Customer#{i:09d}" for i in range(n_cust)],
        "c_address": [f"caddr{i}" for i in range(n_cust)],
        "c_nationkey": r.integers(0, len(NATIONS), n_cust),
        "c_phone": [f"{r.integers(10, 35)}-{i:07d}" for i in range(n_cust)],
        "c_acctbal": np.round(r.uniform(-999, 9999, n_cust), 2),
        "c_mktsegment": r.choice(SEGMENTS, n_cust),
        "c_comment": [f"customer comment {i}" for i in range(n_cust)],
    })
    odate = (np.datetime64("1992-01-01") +
             r.integers(0, 2405, n_orders).astype("timedelta64[D]"))
    orders = pd.DataFrame({
        "o_orderkey": np.arange(n_orders, dtype=np.int64),
        "o_custkey": r.integers(0, n_cust, n_orders),
        "o_orderstatus": r.choice(["O", "F", "P"], n_orders),
        "o_totalprice": np.round(r.uniform(850, 500000, n_orders), 2),
        "o_orderdate": pd.Series(odate.astype("datetime64[ns]")),
        "o_orderpriority": r.choice(PRIORITIES, n_orders),
        "o_clerk": [f"Clerk#{r.integers(1, 1000):09d}"
                    for _ in range(n_orders)],
        "o_shippriority": np.zeros(n_orders, dtype=np.int64),
        "o_comment": r.choice(["fast", "slow special requests deposit",
                               "normal", "special packages requests"],
                              n_orders),
    })
    nl = r.integers(1, 8, n_orders)
    okeys = np.repeat(orders.o_orderkey.to_numpy(), nl)
    n_li = len(okeys)
    ship_delay = r.integers(1, 122, n_li).astype("timedelta64[D]")
    o_dates = np.repeat(odate, nl)
    sdate = o_dates + ship_delay
    cdate = sdate + r.integers(1, 31, n_li).astype("timedelta64[D]")
    rdate = sdate + r.integers(1, 31, n_li).astype("timedelta64[D]")
    lineitem = pd.DataFrame({
        "l_orderkey": okeys,
        "l_partkey": r.integers(0, n_part, n_li),
        "l_suppkey": r.integers(0, n_supp, n_li),
        "l_linenumber": np.concatenate(
            [np.arange(1, k + 1) for k in nl]).astype(np.int64),
        "l_quantity": r.integers(1, 51, n_li).astype(np.float64),
        "l_extendedprice": np.round(r.uniform(900, 100000, n_li), 2),
        "l_discount": np.round(r.uniform(0, 0.10, n_li), 2),
        "l_tax": np.round(r.uniform(0, 0.08, n_li), 2),
        "l_returnflag": r.choice(["R", "A", "N"], n_li),
        "l_linestatus": r.choice(["O", "F"], n_li),
        "l_shipdate": pd.Series(sdate.astype("datetime64[ns]")),
        "l_commitdate": pd.Series(cdate.astype("datetime64[ns]")),
        "l_receiptdate": pd.Series(rdate.astype("datetime64[ns]")),
        "l_shipinstruct": r.choice(["DELIVER IN PERSON", "COLLECT COD",
                                    "NONE", "TAKE BACK RETURN"], n_li),
        "l_shipmode": r.choice(SHIPMODES, n_li),
        "l_comment": [f"li {i}" for i in range(n_li)],
    })
    return {"region": region, "nation": nation, "supplier": supplier,
            "part": part, "partsupp": partsupp, "customer": customer,
            "orders": orders, "lineitem": lineitem}


# Queries the engine cannot yet plan (kept beside QUERIES so the bench
# and the test suite share one source of truth). Currently empty — Q21's
# non-equality correlated EXISTS is handled by the row-id decorrelation.
UNSUPPORTED = {}

# The 22 standard TPC-H queries (spec text, standard parameters).
QUERIES = {
1: """
select l_returnflag, l_linestatus,
       sum(l_quantity) as sum_qty,
       sum(l_extendedprice) as sum_base_price,
       sum(l_extendedprice * (1 - l_discount)) as sum_disc_price,
       sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) as sum_charge,
       avg(l_quantity) as avg_qty,
       avg(l_extendedprice) as avg_price,
       avg(l_discount) as avg_disc,
       count(*) as count_order
from lineitem
where l_shipdate <= date '1998-12-01' - interval '90' day
group by l_returnflag, l_linestatus
order by l_returnflag, l_linestatus
""",
2: """
select s_acctbal, s_name, n_name, p_partkey, p_mfgr, s_address, s_phone,
       s_comment
from part, supplier, partsupp, nation, region
where p_partkey = ps_partkey and s_suppkey = ps_suppkey
  and p_size = 15 and p_type like '%BRASS'
  and s_nationkey = n_nationkey and n_regionkey = r_regionkey
  and r_name = 'EUROPE'
  and ps_supplycost = (
      select min(ps_supplycost)
      from partsupp, supplier, nation, region
      where p_partkey = ps_partkey and s_suppkey = ps_suppkey
        and s_nationkey = n_nationkey and n_regionkey = r_regionkey
        and r_name = 'EUROPE')
order by s_acctbal desc, n_name, s_name, p_partkey
limit 100
""",
3: """
select l_orderkey, sum(l_extendedprice * (1 - l_discount)) as revenue,
       o_orderdate, o_shippriority
from customer, orders, lineitem
where c_mktsegment = 'BUILDING' and c_custkey = o_custkey
  and l_orderkey = o_orderkey and o_orderdate < date '1995-03-15'
  and l_shipdate > date '1995-03-15'
group by l_orderkey, o_orderdate, o_shippriority
order by revenue desc, o_orderdate
limit 10
""",
4: """
select o_orderpriority, count(*) as order_count
from orders
where o_orderdate >= date '1993-07-01'
  and o_orderdate < date '1993-07-01' + interval '3' month
  and exists (select * from lineitem
              where l_orderkey = o_orderkey and l_commitdate < l_receiptdate)
group by o_orderpriority
order by o_orderpriority
""",
5: """
select n_name, sum(l_extendedprice * (1 - l_discount)) as revenue
from customer, orders, lineitem, supplier, nation, region
where c_custkey = o_custkey and l_orderkey = o_orderkey
  and l_suppkey = s_suppkey and c_nationkey = s_nationkey
  and s_nationkey = n_nationkey and n_regionkey = r_regionkey
  and r_name = 'ASIA' and o_orderdate >= date '1994-01-01'
  and o_orderdate < date '1994-01-01' + interval '1' year
group by n_name
order by revenue desc
""",
6: """
select sum(l_extendedprice * l_discount) as revenue
from lineitem
where l_shipdate >= date '1994-01-01'
  and l_shipdate < date '1994-01-01' + interval '1' year
  and l_discount between 0.05 and 0.07 and l_quantity < 24
""",
7: """
select supp_nation, cust_nation, l_year, sum(volume) as revenue
from (select n1.n_name as supp_nation, n2.n_name as cust_nation,
             extract(year from l_shipdate) as l_year,
             l_extendedprice * (1 - l_discount) as volume
      from supplier, lineitem, orders, customer, nation n1, nation n2
      where s_suppkey = l_suppkey and o_orderkey = l_orderkey
        and c_custkey = o_custkey and s_nationkey = n1.n_nationkey
        and c_nationkey = n2.n_nationkey
        and ((n1.n_name = 'FRANCE' and n2.n_name = 'GERMANY')
             or (n1.n_name = 'GERMANY' and n2.n_name = 'FRANCE'))
        and l_shipdate between date '1995-01-01' and date '1996-12-31'
     ) shipping
group by supp_nation, cust_nation, l_year
order by supp_nation, cust_nation, l_year
""",
8: """
select o_year,
       sum(case when nation = 'BRAZIL' then volume else 0 end) / sum(volume)
         as mkt_share
from (select extract(year from o_orderdate) as o_year,
             l_extendedprice * (1 - l_discount) as volume,
             n2.n_name as nation
      from part, supplier, lineitem, orders, customer,
           nation n1, nation n2, region
      where p_partkey = l_partkey and s_suppkey = l_suppkey
        and l_orderkey = o_orderkey and o_custkey = c_custkey
        and c_nationkey = n1.n_nationkey and n1.n_regionkey = r_regionkey
        and r_name = 'AMERICA' and s_nationkey = n2.n_nationkey
        and o_orderdate between date '1995-01-01' and date '1996-12-31'
        and p_type = 'ECONOMY ANODIZED STEEL'
     ) all_nations
group by o_year
order by o_year
""",
9: """
select nation, o_year, sum(amount) as sum_profit
from (select n_name as nation, extract(year from o_orderdate) as o_year,
             l_extendedprice * (1 - l_discount)
               - ps_supplycost * l_quantity as amount
      from part, supplier, lineitem, partsupp, orders, nation
      where s_suppkey = l_suppkey and ps_suppkey = l_suppkey
        and ps_partkey = l_partkey and p_partkey = l_partkey
        and o_orderkey = l_orderkey and s_nationkey = n_nationkey
        and p_name like '%green%'
     ) profit
group by nation, o_year
order by nation, o_year desc
""",
10: """
select c_custkey, c_name, sum(l_extendedprice * (1 - l_discount)) as revenue,
       c_acctbal, n_name, c_address, c_phone, c_comment
from customer, orders, lineitem, nation
where c_custkey = o_custkey and l_orderkey = o_orderkey
  and o_orderdate >= date '1993-10-01'
  and o_orderdate < date '1993-10-01' + interval '3' month
  and l_returnflag = 'R' and c_nationkey = n_nationkey
group by c_custkey, c_name, c_acctbal, c_phone, n_name, c_address, c_comment
order by revenue desc
limit 20
""",
11: """
select ps_partkey, sum(ps_supplycost * ps_availqty) as value
from partsupp, supplier, nation
where ps_suppkey = s_suppkey and s_nationkey = n_nationkey
  and n_name = 'GERMANY'
group by ps_partkey
having sum(ps_supplycost * ps_availqty) > (
    select sum(ps_supplycost * ps_availqty) * 0.0001
    from partsupp, supplier, nation
    where ps_suppkey = s_suppkey and s_nationkey = n_nationkey
      and n_name = 'GERMANY')
order by value desc
""",
12: """
select l_shipmode,
       sum(case when o_orderpriority = '1-URGENT'
                  or o_orderpriority = '2-HIGH' then 1 else 0 end)
         as high_line_count,
       sum(case when o_orderpriority <> '1-URGENT'
                 and o_orderpriority <> '2-HIGH' then 1 else 0 end)
         as low_line_count
from orders, lineitem
where o_orderkey = l_orderkey and l_shipmode in ('MAIL', 'SHIP')
  and l_commitdate < l_receiptdate and l_shipdate < l_commitdate
  and l_receiptdate >= date '1994-01-01'
  and l_receiptdate < date '1994-01-01' + interval '1' year
group by l_shipmode
order by l_shipmode
""",
13: """
select c_count, count(*) as custdist
from (select c_custkey, count(o_orderkey) as c_count
      from customer left outer join orders
           on c_custkey = o_custkey
           and o_comment not like '%special%requests%'
      group by c_custkey
     ) c_orders
group by c_count
order by custdist desc, c_count desc
""",
14: """
select 100.00 * sum(case when p_type like 'PROMO%'
                         then l_extendedprice * (1 - l_discount)
                         else 0 end)
       / sum(l_extendedprice * (1 - l_discount)) as promo_revenue
from lineitem, part
where l_partkey = p_partkey
  and l_shipdate >= date '1995-09-01'
  and l_shipdate < date '1995-09-01' + interval '1' month
""",
15: """
with revenue0 as (
    select l_suppkey as supplier_no,
           sum(l_extendedprice * (1 - l_discount)) as total_revenue
    from lineitem
    where l_shipdate >= date '1996-01-01'
      and l_shipdate < date '1996-01-01' + interval '3' month
    group by l_suppkey)
select s_suppkey, s_name, s_address, s_phone, total_revenue
from supplier, revenue0
where s_suppkey = supplier_no
  and total_revenue = (select max(total_revenue) from revenue0)
order by s_suppkey
""",
16: """
select p_brand, p_type, p_size, count(distinct ps_suppkey) as supplier_cnt
from partsupp, part
where p_partkey = ps_partkey and p_brand <> 'Brand#45'
  and p_type not like 'MEDIUM POLISHED%'
  and p_size in (49, 14, 23, 45, 19, 3, 36, 9)
  and ps_suppkey not in (select s_suppkey from supplier
                         where s_comment like '%Customer%Complaints%')
group by p_brand, p_type, p_size
order by supplier_cnt desc, p_brand, p_type, p_size
""",
17: """
select sum(l_extendedprice) / 7.0 as avg_yearly
from lineitem, part
where p_partkey = l_partkey and p_brand = 'Brand#23'
  and p_container = 'MED BOX'
  and l_quantity < (select 0.2 * avg(l_quantity) from lineitem
                    where l_partkey = p_partkey)
""",
18: """
select c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice,
       sum(l_quantity) as total_qty
from customer, orders, lineitem
where o_orderkey in (select l_orderkey from lineitem
                     group by l_orderkey
                     having sum(l_quantity) > 150)
  and c_custkey = o_custkey and o_orderkey = l_orderkey
group by c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice
order by o_totalprice desc, o_orderdate
limit 100
""",
19: """
select sum(l_extendedprice * (1 - l_discount)) as revenue
from lineitem, part
where (p_partkey = l_partkey and p_brand = 'Brand#12'
       and p_container in ('SM CASE', 'SM BOX', 'SM PACK', 'SM PKG')
       and l_quantity >= 1 and l_quantity <= 11
       and p_size between 1 and 5
       and l_shipmode in ('AIR', 'AIR REG')
       and l_shipinstruct = 'DELIVER IN PERSON')
   or (p_partkey = l_partkey and p_brand = 'Brand#23'
       and p_container in ('MED BAG', 'MED BOX', 'MED PKG', 'MED PACK')
       and l_quantity >= 10 and l_quantity <= 20
       and p_size between 1 and 10
       and l_shipmode in ('AIR', 'AIR REG')
       and l_shipinstruct = 'DELIVER IN PERSON')
   or (p_partkey = l_partkey and p_brand = 'Brand#34'
       and p_container in ('LG CASE', 'LG BOX', 'LG PACK', 'LG PKG')
       and l_quantity >= 20 and l_quantity <= 30
       and p_size between 1 and 15
       and l_shipmode in ('AIR', 'AIR REG')
       and l_shipinstruct = 'DELIVER IN PERSON')
""",
20: """
select s_name, s_address
from supplier, nation
where s_suppkey in (
    select ps_suppkey from partsupp
    where ps_partkey in (select p_partkey from part
                         where p_name like 'green%')
      and ps_availqty > (select 0.5 * sum(l_quantity) from lineitem
                         where l_partkey = ps_partkey
                           and l_suppkey = ps_suppkey
                           and l_shipdate >= date '1994-01-01'
                           and l_shipdate < date '1994-01-01'
                                             + interval '1' year))
  and s_nationkey = n_nationkey and n_name = 'CANADA'
order by s_name
""",
21: """
select s_name, count(*) as numwait
from supplier, lineitem l1, orders, nation
where s_suppkey = l1.l_suppkey and o_orderkey = l1.l_orderkey
  and o_orderstatus = 'F' and l1.l_receiptdate > l1.l_commitdate
  and exists (select * from lineitem l2
              where l2.l_orderkey = l1.l_orderkey
                and l2.l_suppkey <> l1.l_suppkey)
  and not exists (select * from lineitem l3
                  where l3.l_orderkey = l1.l_orderkey
                    and l3.l_suppkey <> l1.l_suppkey
                    and l3.l_receiptdate > l3.l_commitdate)
  and s_nationkey = n_nationkey and n_name = 'SAUDI ARABIA'
group by s_name
order by numwait desc, s_name
limit 100
""",
22: """
select cntrycode, count(*) as numcust, sum(c_acctbal) as totacctbal
from (select substring(c_phone from 1 for 2) as cntrycode, c_acctbal
      from customer
      where substring(c_phone from 1 for 2) in
            ('13', '31', '23', '29', '30', '18', '17')
        and c_acctbal > (select avg(c_acctbal) from customer
                         where c_acctbal > 0.00
                           and substring(c_phone from 1 for 2) in
                               ('13', '31', '23', '29', '30', '18', '17'))
        and not exists (select * from orders
                        where o_custkey = c_custkey)
     ) custsale
group by cntrycode
order by cntrycode
""",
}


# ---------------------------------------------------------------------------
# sqlite oracle helpers (shared by tests/test_tpch.py and bench.py --suite
# tpch): translate the standard query texts into sqlite's dialect so the
# stdlib engine can serve as a differential baseline (the reference's
# differential-oracle strategy, SURVEY.md §4).
# ---------------------------------------------------------------------------

import re as _re  # noqa: E402


def fold_intervals(sql: str) -> str:
    """date 'X' ± interval 'N' unit → folded literal (sqlite has neither)."""
    pat = _re.compile(
        r"date\s+'([0-9-]+)'\s*([+-])\s*interval\s+'(\d+)'\s+(\w+)")

    def repl(m):
        d = np.datetime64(m.group(1))
        n = int(m.group(3))
        sign = 1 if m.group(2) == "+" else -1
        unit = m.group(4).lower().rstrip("s")
        if unit in ("year", "month"):
            months = n * (12 if unit == "year" else 1) * sign
            out = (d.astype("datetime64[M]") + months).astype("datetime64[D]")
        else:
            days = {"day": 1}[unit] * n * sign
            out = d + np.timedelta64(days, "D")
        return f"date '{out}'"

    prev = None
    while prev != sql:
        prev = sql
        sql = pat.sub(repl, sql)
    return sql


def to_sqlite(sql: str) -> str:
    sql = fold_intervals(sql)
    sql = _re.sub(r"date\s+'([0-9-]+)'", r"'\1'", sql)
    sql = _re.sub(r"extract\s*\(\s*year\s+from\s+([A-Za-z_0-9.]+)\s*\)",
                  r"CAST(strftime('%Y', \1) AS INTEGER)", sql)
    sql = _re.sub(r"substring\s*\(\s*([A-Za-z_0-9.]+)\s+from\s+(\d+)\s+"
                  r"for\s+(\d+)\s*\)", r"substr(\1, \2, \3)", sql)
    return sql


def sqlite_connection(data):
    """Load a gen_tpch() dict into an in-memory sqlite DB."""
    import sqlite3
    conn = sqlite3.connect(":memory:")
    for name, df in data.items():
        df2 = df.copy()
        for c in df2.columns:
            if df2[c].dtype.kind == "M":
                df2[c] = df2[c].dt.strftime("%Y-%m-%d")
        df2.to_sql(name, conn, index=False)
    return conn
