"""Benchmark/e2e workloads (north-star configs from BASELINE.md)."""
