"""Parquet reader/writer with multi-process row-group sharding.

Analogue of the reference's parallel parquet I/O (bodo/io/parquet_pio.py,
parquet_reader.cpp — row-group assignment across ranks, column pruning
pushdown; bodo/ir/parquet_ext.py:340). In the TPU runtime each host
process reads its contiguous slice of row groups (`jax.process_index`
replaces MPI rank), converts via the Arrow bridge, and the caller shards
rows over the local mesh.
"""

from __future__ import annotations

import glob as globmod
import os

import numpy as np
from typing import Optional, Sequence

import pyarrow as pa
import pyarrow.parquet as pq

from bodo_tpu.io.arrow_bridge import arrow_to_table, table_to_arrow
from bodo_tpu.runtime import resilience
from bodo_tpu.table.table import Table


def _is_remote(path: str) -> bool:
    return "://" in path and not path.startswith("file://")


def _fs_of(path: str):
    """fsspec filesystem for a remote URL (reference: bodo/io/fs_io.py —
    s3/gcs/hdfs resolution; here any fsspec scheme, e.g. s3://, gs://,
    memory://)."""
    import fsspec
    fs, _ = fsspec.core.url_to_fs(path)
    return fs


def _dataset_files(path):
    if isinstance(path, (list, tuple)):
        # pre-resolved file list (Iceberg manifests, multi-file scans)
        return list(path)
    if _is_remote(path):
        fs = _fs_of(path)
        scheme = path.split("://", 1)[0]
        p = fs._strip_protocol(path)
        if fs.isdir(p):
            files = sorted(fs.glob(p.rstrip("/") + "/**/*.parquet"))
        elif any(ch in p for ch in "*?["):
            files = sorted(fs.glob(p))
        else:
            files = [p]
        if not files:
            raise FileNotFoundError(f"no parquet files match {path}")
        return [f"{scheme}://{f}" for f in files]
    if os.path.isdir(path):
        files = sorted(globmod.glob(os.path.join(path, "**", "*.parquet"),
                                    recursive=True))
    elif any(ch in path for ch in "*?["):
        files = sorted(globmod.glob(path))
    else:
        files = [path]
    if not files:
        raise FileNotFoundError(f"no parquet files match {path}")
    return files


def split_rg_fragment(path: str):
    """Split a row-group fragment path ``file.parquet#rg=LO-HI`` into
    (base_path, (lo, hi)) — or (path, None) for a plain path. Fragments
    name the trailing row groups of an in-place grown file (see
    classify_change): they flow through explicit file lists exactly like
    paths, and every consumer that opens/stats a file strips them here."""
    if isinstance(path, str) and "#rg=" in path:
        base, _, spec = path.rpartition("#rg=")
        lo, _, hi = spec.partition("-")
        try:
            return base, (int(lo), int(hi))
        except ValueError:
            return path, None
    return path, None


def _open_one(path: str):
    """File-like handle for local or fsspec-remote paths. Remote handles
    must be closed by the caller — prefer `_opened` below."""
    if _is_remote(path):
        return _fs_of(path).open(path.split("://", 1)[1], "rb")
    return path


import contextlib  # noqa: E402


@contextlib.contextmanager
def _opened(path: str):
    """Context-managed _open_one: closes remote handles on exit."""
    src = _open_one(path)
    try:
        yield src
    finally:
        if hasattr(src, "close"):
            src.close()


# ---------------------------------------------------------------------------
# footer/metadata cache
# ---------------------------------------------------------------------------
# One footer parse per (path, mtime, size): the reader previously opened
# every file twice in the multi-process path (once to count row groups,
# once to decode) and _attach_footer_ranges re-read every footer after
# the decode. The cache also serves the AQE stats-store fingerprint
# (runtime/stats_store.py) and the planner's row-count estimate
# (plan/stats.py), so a whole plan costs one footer read per file.

_FOOTER_CACHE_MAX = 256
_footer_cache: dict = {}   # signature -> pq.FileMetaData (insertion-ordered)
import threading as _threading  # noqa: E402

_footer_lock = _threading.Lock()


def file_signature(path: str):
    """(path, mtime_ns, size) identity of one file — the cache key and
    the stats-store fingerprint component. Remote paths resolve via
    fsspec info (mtime falls back to a created/LastModified stamp).
    Row-group fragment paths stat the base file but keep the fragment in
    the returned identity, so a delta scan signs distinctly from a full
    scan of the same file."""
    ident = path
    path, _rg = split_rg_fragment(path)
    if _is_remote(path):
        info = _fs_of(path).info(path.split("://", 1)[1])
        stamp = info.get("mtime") or info.get("LastModified") \
            or info.get("created")
        if hasattr(stamp, "timestamp"):
            stamp = stamp.timestamp()
        try:
            stamp = int(float(stamp) * 1e9)
        except (TypeError, ValueError):
            stamp = 0
        return (ident, stamp, int(info.get("size") or 0))
    st = os.stat(path)
    return (ident, st.st_mtime_ns, st.st_size)


def footer_metadata(path: str, sig=None):
    """Cached parquet footer (pq.FileMetaData) for `path`, keyed on its
    current (path, mtime, size) signature — an overwritten file misses
    and re-reads. Fragment paths share the base file's cache entry."""
    from bodo_tpu.runtime import io_pool
    path, _rg = split_rg_fragment(path)
    if sig is None:
        sig = file_signature(path)
    with _footer_lock:
        md = _footer_cache.get(sig)
        if md is not None:
            io_pool.count("footer_hits")
            return md
    with _opened(path) as src:
        md = pq.ParquetFile(src).metadata
    with _footer_lock:
        io_pool.count("footer_misses")
        _footer_cache[sig] = md
        while len(_footer_cache) > _FOOTER_CACHE_MAX:
            _footer_cache.pop(next(iter(_footer_cache)))
    return md


def clear_footer_cache() -> None:
    with _footer_lock:
        _footer_cache.clear()


def _raw_range(path: str, start: int, size: int) -> bytes:
    """Read one raw byte range (a column chunk's pages, offsets straight
    from the cached footer) — what the device-decode path ships instead
    of decoded tables. Local paths use seek/read; remote handles go
    through fsspec, which serves ranged reads from its block cache."""
    with _opened(path) as src:
        if hasattr(src, "seek"):
            src.seek(start)
            return src.read(size)
        with open(src, "rb") as f:
            f.seek(start)
            return f.read(size)


def dataset_signature(path):
    """Fingerprint of a whole dataset: tuple of per-file signatures.
    Shared by the AQE stats store so persisted cardinalities invalidate
    when any file changes."""
    return tuple(file_signature(f) for f in _dataset_files(path))


def _grown_file_delta(old_sig, new_sig):
    """Detect an in-place GROWN file: same path, size strictly larger,
    and the old footer's row groups a byte-identical prefix of the new
    footer (row counts, byte sizes, and column-chunk offsets all equal).
    Returns the ``path#rg=LO-HI`` fragment naming the new trailing row
    groups, or None when growth cannot be proven — the caller then
    treats the change as a mutate; never a stale partial.

    Requires the OLD footer to still be cached under its old signature
    (_footer_cache keeps footers per (path, mtime, size), so a prior
    scan's footer survives the rewrite); without it there is nothing to
    compare against and the answer is conservatively None."""
    path = split_rg_fragment(old_sig[0])[0]
    if new_sig[2] <= old_sig[2]:
        return None  # shrunk or same size: not a pure tail-append
    with _footer_lock:
        old_md = _footer_cache.get(old_sig)
    if old_md is None:
        return None
    try:
        new_md = footer_metadata(path, sig=new_sig)
    except Exception:
        return None
    o, n = old_md.num_row_groups, new_md.num_row_groups
    if n <= o:
        return None
    for rg in range(o):
        a, b = old_md.row_group(rg), new_md.row_group(rg)
        if a.num_rows != b.num_rows or \
                a.total_byte_size != b.total_byte_size or \
                a.num_columns != b.num_columns:
            return None
        for ci in range(a.num_columns):
            ca, cb = a.column(ci), b.column(ci)
            if ca.path_in_schema != cb.path_in_schema or \
                    ca.file_offset != cb.file_offset or \
                    ca.total_compressed_size != cb.total_compressed_size:
                return None
    return f"{path}#rg={o}-{n}"


def classify_change(old_sigs, new_sigs):
    """Classify the delta between two ``dataset_signature()`` results:

        ("same", ())       — byte-identical signatures
        ("append", files)  — old data is untouched and new rows only
                             appeared AFTER it: added files and/or
                             in-place grown files whose old row groups
                             are a byte-identical prefix (those appear
                             as ``path#rg=LO-HI`` fragments naming the
                             new trailing row groups); `files` are in
                             the NEW scan order
        ("mutate", paths)  — anything else (rewrite, delete, touch);
                             `paths` are the files that changed in
                             place (empty when files were deleted),
                             feeding partition-level invalidation in
                             the result cache

    Drives the result cache's incremental append maintenance
    (runtime/result_cache.py): "append" means the cached result is still
    a correct partial and only the delta files need scanning."""
    old_by = {s[0]: s for s in old_sigs}
    changed = []
    grown = {}  # path -> "#rg=" delta fragment
    for s in new_sigs:
        prev = old_by.get(s[0])
        if prev is not None and prev != s:
            frag = _grown_file_delta(prev, s)
            if frag is None:
                changed.append(s[0])
            else:
                grown[s[0]] = frag
    new_paths = {s[0] for s in new_sigs}
    deleted = any(p not in new_paths for p in old_by)
    if changed or deleted:
        return ("mutate", tuple(changed) if not deleted else ())
    delta = []
    for s in new_sigs:
        if s[0] not in old_by:
            delta.append(s[0])
        elif s[0] in grown:
            delta.append(grown[s[0]])
    return ("append", tuple(delta)) if delta else ("same", ())


def dataset_nbytes(path) -> int:
    """Total on-disk bytes of a dataset (0 when unknown) — sizes the
    read_parquet admission reservation in plan/physical.py."""
    try:
        return sum(sig[2] for sig in dataset_signature(path))
    except Exception:
        return 0


def _attach_footer_ranges(t, files, row_groups=None) -> None:
    """Column.vrange from parquet row-group statistics (free from the
    cached footer — the reference planner reads the same stats for
    pushdown, bodo/io/parquet_pio.py). Integer and timestamp columns
    only; any file/row-group without stats clears that column's bound.
    `row_groups` (optional dict file -> row-group indices) restricts
    stats to the row groups actually read — a process's stripe must not
    claim exact bounds from rows it never loaded."""
    import numpy as np

    from bodo_tpu.table import dtypes as dt
    ranges: dict = {}
    try:
        for f in files:
            f, rg_win = split_rg_fragment(f)
            if row_groups is not None and f not in row_groups:
                continue
            md = footer_metadata(f)
            rgs = (row_groups[f] if row_groups is not None
                   else range(*rg_win) if rg_win is not None
                   else range(md.num_row_groups))
            for rg in rgs:
                g = md.row_group(rg)
                for ci in range(g.num_columns):
                    col = g.column(ci)
                    name = col.path_in_schema
                    if "." in name or name not in t.columns:
                        continue
                    st = col.statistics
                    if st is None or not st.has_min_max:
                        ranges[name] = None
                        continue
                    lo, hi = st.min, st.max
                    import datetime as _dtm
                    if isinstance(lo, (int, np.integer)):
                        lo, hi = int(lo), int(hi)
                    elif isinstance(lo, _dtm.datetime):
                        lo = int(np.datetime64(lo, "ns").astype(np.int64))
                        hi = int(np.datetime64(hi, "ns").astype(np.int64))
                    elif isinstance(lo, _dtm.date):  # DATE: days
                        lo = int(np.datetime64(lo, "D").astype(np.int64))
                        hi = int(np.datetime64(hi, "D").astype(np.int64))
                    else:
                        ranges[name] = None
                        continue
                    if name in ranges:
                        if ranges[name] is not None:
                            ranges[name] = (min(ranges[name][0], lo),
                                            max(ranges[name][1], hi))
                    else:
                        ranges[name] = (lo, hi)
    except Exception:  # stats are an optimization — never fail the read
        return
    for name, r in ranges.items():
        c = t.columns.get(name)
        if r is not None and c is not None and \
                c.dtype.kind in ("i", "u", "dt", "date"):
            c.vrange = (r[0], r[1], True)  # scan stats are data-exact


from bodo_tpu.utils.tracing import traced_table_op as _traced


@_traced
def read_parquet(path: str, columns: Optional[Sequence[str]] = None,
                 process_index: Optional[int] = None,
                 process_count: Optional[int] = None) -> Table:
    """Read parquet into a replicated Table (caller shards over the mesh).

    In a multi-host launch, each process reads only its contiguous
    stripe of row groups. Filesystem flakes (transient OSErrors, armed
    `io.read` faults) are absorbed by the shared retry envelope.
    """
    return resilience.retry_call(
        lambda: _read_parquet_once(path, columns, process_index,
                                   process_count),
        label="read_parquet", point="io.read")


def _device_decode_enabled() -> bool:
    from bodo_tpu.config import config
    return bool(getattr(config, "device_decode", False))


def _scan_units(files):
    """(file, row_group, total_byte_size) scan units, footers from the
    cache (each file's footer parsed at most once per mtime). A
    ``#rg=LO-HI`` fragment restricts its file to that row-group window;
    units always carry the BASE path so every downstream consumer
    (decode, device route, stats attach) opens real files."""
    units = []
    for f in files:
        base, rg_win = split_rg_fragment(f)
        md = footer_metadata(base)
        rgs = range(md.num_row_groups) if rg_win is None else \
            range(max(rg_win[0], 0), min(rg_win[1], md.num_row_groups))
        units.extend((base, rg, md.row_group(rg).total_byte_size)
                     for rg in rgs)
    return units


def _stripe_by_bytes(weights, pi: int, pc: int):
    """Contiguous [lo, hi) slice of units owned by process `pi`, striped
    by BYTE weight rather than unit count (the reference's scan-unit
    distribution weighs row groups the same way — a dataset whose last
    file holds one fat row group must not land entirely on one rank).
    Unit i belongs to the process whose 1/pc-band contains the unit's
    byte midpoint; the owner is nondecreasing in i, so each process gets
    a contiguous run and the union over processes is an exact partition."""
    total = sum(weights)
    if total <= 0:  # degenerate/statless footers: unit-count striping
        from bodo_tpu.io import stripe
        return stripe(len(weights), pi, pc)
    lo = hi = None
    acc = 0
    for i, w in enumerate(weights):
        owner = min(int(pc * (acc + w / 2.0) / total), pc - 1)
        acc += w
        if owner == pi:
            if lo is None:
                lo = i
            hi = i + 1
    return (0, 0) if lo is None else (lo, hi)


def _decode_row_group(unit, columns):
    """Pool task: decode one (file, row_group) with the cached footer —
    the file opens once for data pages only. Fires the io.read fault
    point so armed chaos reaches pool threads too."""
    from bodo_tpu.runtime import io_pool
    f, rg, _ = unit
    resilience.maybe_inject("io.read")
    with _opened(f) as src:
        pf = pq.ParquetFile(src, metadata=footer_metadata(f))
        at = pf.read_row_group(
            rg, columns=list(columns) if columns else None)
    io_pool.count("host_decode_bytes", int(at.nbytes))
    return at


def _read_units(units, columns):
    """Decode scan units into one arrow table: pool map with ordered
    reassembly (byte-identical to a serial read) when the pool has >1
    worker and there is >1 unit; serial otherwise."""
    from bodo_tpu.runtime import io_pool
    if len(units) > 1 and io_pool.io_thread_count() > 1:
        io_pool.count("parallel_reads")
        tables = list(io_pool.pool_map_ordered(
            lambda u: _decode_row_group(u, columns), units))
    else:
        tables = [_decode_row_group(u, columns) for u in units]
    return pa.concat_tables(tables) if len(tables) > 1 else tables[0]


def _read_parquet_once(path, columns, process_index, process_count) -> Table:
    import jax
    pi = process_index if process_index is not None else jax.process_index()
    pc_ = process_count if process_count is not None else jax.process_count()
    # an explicit file list (e.g. resolved from Iceberg manifests) skips
    # directory discovery but keeps the striping/remote machinery
    files = list(path) if isinstance(path, (list, tuple)) \
        else _dataset_files(path)

    units = _scan_units(files)
    if pc_ == 1:
        lo, hi = 0, len(units)
    else:
        # row-group assignment across processes (reference:
        # parquet_reader.cpp get_scan_units distribution), byte-weighted
        lo, hi = _stripe_by_bytes([u[2] for u in units], pi, pc_)
    mine = units[lo:hi]
    t = None
    if mine:
        # device route first: pool workers ship raw page bytes, jitted
        # programs decode on-chip; columns the programs don't cover fall
        # back to host per column INSIDE the route. None means the whole
        # dataset can't take the route (exotic layout) — classic path.
        if _device_decode_enabled():
            from bodo_tpu.io import device_decode as _dd
            t = _dd.read_units_table(mine, columns)
        if t is None:
            at = _read_units(mine, columns)
    elif units:  # fewer units than processes: empty slice, schema kept
        at = _decode_row_group(units[0], columns).slice(0, 0)
    else:
        with _opened(split_rg_fragment(files[0])[0]) as src:
            at = pq.read_table(src, columns=list(columns) if columns
                               else None).slice(0, 0)
    if t is None:
        t = arrow_to_table(at)
    # runtime contract check: a scan always materializes replicated on
    # this host (the caller shards), on both decode routes
    from bodo_tpu.analysis.plan_validator import check_kernel_result
    check_kernel_result("read_parquet", t.distribution)
    # footer stats attach on EVERY path (the multi-process return used
    # to skip them, losing min/max pushdown on multi-host reads), but
    # restricted to the row groups this process actually read — whole-
    # dataset bounds would be marked exact yet possibly unattained here.
    own = {}
    for f, rg, _w in mine:
        own.setdefault(f, []).append(rg)
    if own:
        _attach_footer_ranges(t, files, row_groups=own)
    return t


def write_parquet(t: Table, path: str, index: bool = False) -> None:
    """Write a Table to parquet.

    REP tables write one file. 1D tables write a DIRECTORY of per-shard
    part files with no gather — each shard's rows leave the device
    straight into its own file (the reference's parallel writer,
    bodo/io/parquet_write.cpp: one file per rank under a directory; in a
    multi-host launch each process writes only its addressable shards).
    """
    if t.distribution != "1D" or t.num_shards == 1:
        if os.path.isdir(path):
            _clear_part_dir(path)  # prior sharded write left a directory
            os.rmdir(path)
        at = table_to_arrow(t)
        # idempotent: a retry replays a whole-file overwrite of the same
        # path, so a torn first attempt is simply rewritten
        # shardcheck: ignore[retry-non-idempotent]
        resilience.retry_call(lambda: pq.write_table(at, path),
                              label="write_parquet", point="io.write")
        return
    import jax

    # destination hygiene: a prior single-file write leaves a regular
    # file; a prior wider-mesh write leaves extra part files that the
    # recursive reader glob would silently concatenate with the new
    # ones. Only process 0 cleans, and everyone barriers BEFORE any rank
    # writes, so cleanup can never race a peer's fresh part file.
    if jax.process_index() == 0:
        if os.path.isfile(path):
            os.unlink(path)
        os.makedirs(path, exist_ok=True)
        _clear_part_dir(path)
    if jax.process_count() > 1:
        _global_barrier("bodo_tpu_pq_write_clean")
    per = t.shard_capacity
    # iterate ADDRESSABLE shards only: every process writes exactly the
    # shards it owns, with no cross-process data movement (touching a
    # non-addressable region of a global array would force a collective
    # and deadlock against peers writing different shards)
    local: dict = {}  # shard index -> {col: host array}
    for name, c in t.columns.items():
        for sh in c.data.addressable_shards:
            start = sh.index[0].start or 0
            local.setdefault(start // per, {})[name] = \
                np.asarray(sh.data)
        if c.valid is not None:
            for sh in c.valid.addressable_shards:
                start = sh.index[0].start or 0
                local[start // per][f"__valid__{name}"] = \
                    np.asarray(sh.data)
    for shard in sorted(local):
        data = local[shard]
        n = int(t.counts[shard])
        piece = _host_piece(t, data, n)
        at = table_to_arrow(piece)
        dest = os.path.join(path, f"part-{shard:05d}.parquet")
        # idempotent: the part path is deterministic per shard and the
        # retry overwrites the whole file, never appends
        # shardcheck: ignore[retry-non-idempotent]
        resilience.retry_call(lambda: pq.write_table(at, dest),
                              label="write_parquet", point="io.write")


def _global_barrier(name: str) -> None:
    """Cross-process barrier. Prefers the device collective (overlaps
    with other device work); falls back to the coordination-service
    barrier where the backend can't run multiprocess computations
    (e.g. the CPU backend in older jaxlibs)."""
    from jax.experimental import multihost_utils
    try:
        multihost_utils.sync_global_devices(name)
    except Exception:
        from jax._src import distributed
        client = getattr(distributed.global_state, "client", None)
        if client is None:
            raise
        client.wait_at_barrier(name, 60_000)


def _clear_part_dir(path: str) -> None:
    """Remove our own part files from a destination directory. Refuses
    directories containing anything else (don't delete user data)."""
    others = [f for f in os.listdir(path)
              if not (f.startswith("part-") and f.endswith(".parquet"))]
    if others:
        raise ValueError(
            f"refusing to overwrite {path}: directory contains non-part "
            f"files {others[:3]}")
    for f in globmod.glob(os.path.join(path, "part-*.parquet")):
        os.unlink(f)


def _host_piece(t: Table, data: dict, n: int) -> Table:
    """Rebuild one shard's live rows as a REP table from host arrays."""
    import jax.numpy as jnp

    from bodo_tpu.table.table import Column, Table as _T, round_capacity
    cap = round_capacity(max(n, 1))
    cols = {}
    for name, c in t.columns.items():
        host = data[name]
        padded = np.zeros((cap,), dtype=host.dtype)
        padded[:n] = host[:n]
        valid = None
        if c.valid is not None:
            hv = data[f"__valid__{name}"]
            pv = np.zeros((cap,), dtype=bool)
            pv[:n] = hv[:n]
            valid = jnp.asarray(pv)
        cols[name] = Column(jnp.asarray(padded), valid, c.dtype,
                            c.dictionary)
    return _T(cols, n, "REP", None)


class StreamingParquetWriter:
    """Batch-at-a-time parquet sink (reference:
    bodo/io/stream_parquet_write.py ParquetWriter): each pushed batch
    appends one row group; device memory stays O(batch)."""

    def __init__(self, path: str):
        self._path = path
        self._writer = None

    def push(self, t: Table) -> None:
        if t.nrows == 0 and self._writer is not None:
            return
        at = table_to_arrow(t)
        if self._writer is None:
            if os.path.isdir(self._path):  # prior sharded write
                _clear_part_dir(self._path)
                os.rmdir(self._path)
            self._writer = pq.ParquetWriter(self._path, at.schema)
        # NOT under the retry envelope: an append to an open
        # ParquetWriter is stateful, so retrying a partially-completed
        # write_table could duplicate the batch or corrupt the file.
        # The injection point still fires so chaos runs cover this sink.
        resilience.maybe_inject("io.write")
        self._writer.write_table(at)

    def close(self) -> None:
        if self._writer is not None:
            self._writer.close()
            self._writer = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
