"""Parquet reader/writer with multi-process row-group sharding.

Analogue of the reference's parallel parquet I/O (bodo/io/parquet_pio.py,
parquet_reader.cpp — row-group assignment across ranks, column pruning
pushdown; bodo/ir/parquet_ext.py:340). In the TPU runtime each host
process reads its contiguous slice of row groups (`jax.process_index`
replaces MPI rank), converts via the Arrow bridge, and the caller shards
rows over the local mesh.
"""

from __future__ import annotations

import glob as globmod
import os
from typing import Optional, Sequence

import pyarrow as pa
import pyarrow.parquet as pq

from bodo_tpu.io.arrow_bridge import arrow_to_table, table_to_arrow
from bodo_tpu.table.table import Table


def _dataset_files(path: str):
    if os.path.isdir(path):
        files = sorted(globmod.glob(os.path.join(path, "**", "*.parquet"),
                                    recursive=True))
    elif any(ch in path for ch in "*?["):
        files = sorted(globmod.glob(path))
    else:
        files = [path]
    if not files:
        raise FileNotFoundError(f"no parquet files match {path}")
    return files


def read_parquet(path: str, columns: Optional[Sequence[str]] = None,
                 process_index: Optional[int] = None,
                 process_count: Optional[int] = None) -> Table:
    """Read parquet into a replicated Table (caller shards over the mesh).

    In a multi-host launch, each process reads only its contiguous
    stripe of row groups.
    """
    import jax
    pi = process_index if process_index is not None else jax.process_index()
    pc_ = process_count if process_count is not None else jax.process_count()
    files = _dataset_files(path)

    if pc_ == 1:
        at = pq.read_table(files if len(files) > 1 else files[0],
                           columns=list(columns) if columns else None)
        return arrow_to_table(at)

    # row-group assignment across processes (reference: parquet_reader.cpp
    # get_scan_units distribution); each file opened/parsed once
    handles = {f: pq.ParquetFile(f) for f in files}
    units = []  # (file, row_group)
    for f in files:
        units.extend((f, rg)
                     for rg in range(handles[f].metadata.num_row_groups))
    lo = (len(units) * pi) // pc_
    hi = (len(units) * (pi + 1)) // pc_
    tables = []
    for f, rg in units[lo:hi]:
        tables.append(handles[f].read_row_group(
            rg, columns=list(columns) if columns else None))
    if tables:
        at = pa.concat_tables(tables)
    else:
        at = pq.read_table(files[0], columns=list(columns) if columns
                           else None).slice(0, 0)
    return arrow_to_table(at)


def write_parquet(t: Table, path: str, index: bool = False) -> None:
    at = table_to_arrow(t)
    pq.write_table(at, path)
