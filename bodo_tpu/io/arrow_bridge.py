"""Arrow ⇄ device-table conversion.

Analogue of the reference's Arrow bridge (bodo/libs/_bodo_to_arrow.cpp,
bodo/io/arrow_reader.h TableBuilder): host Arrow columns become padded
device arrays + validity masks; strings are dictionary-encoded with a
lexicographically sorted dictionary (so device code order == string
order, see table/dtypes.py).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax.numpy as jnp
import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from bodo_tpu.table import dtypes as dt
from bodo_tpu.table.table import (Column, ONED, REP, Table,
                                  round_capacity)


def _pad(arr: np.ndarray, cap: int) -> np.ndarray:
    out = np.zeros((cap,) + arr.shape[1:], dtype=arr.dtype)
    out[: len(arr)] = arr
    return out


def _arrow_scalar_dtype(typ: pa.DataType) -> dt.DType:
    if pa.types.is_string(typ) or pa.types.is_large_string(typ):
        return dt.STRING
    if pa.types.is_boolean(typ):
        return dt.BOOL
    if pa.types.is_floating(typ):
        return dt.FLOAT64
    if pa.types.is_integer(typ):
        return dt.INT64
    return dt.FLOAT64


def _arrow_column(arr: pa.ChunkedArray, cap: int) -> Column:
    arr = arr.combine_chunks() if isinstance(arr, pa.ChunkedArray) else arr
    typ = arr.type
    n = len(arr)
    valid_np = None
    if arr.null_count:
        valid_np = ~np.asarray(arr.is_null())

    if pa.types.is_dictionary(typ) or pa.types.is_string(typ) or \
            pa.types.is_large_string(typ):
        if not pa.types.is_dictionary(typ):
            arr = pc.dictionary_encode(arr)
        darr = arr
        dictionary = np.asarray(darr.dictionary.to_pylist(), dtype=str) \
            if len(darr.dictionary) else np.array([], dtype=str)
        codes = darr.indices.to_numpy(zero_copy_only=False)
        codes = np.where(np.isnan(codes.astype(np.float64)), 0, codes) \
            if codes.dtype.kind == "f" else codes
        codes = codes.astype(np.int32)
        # sort the dictionary so code order == lexicographic order
        order = np.argsort(dictionary, kind="stable")
        rank = np.empty_like(order)
        rank[order] = np.arange(len(order))
        sorted_dict = dictionary[order]
        codes = rank[np.clip(codes, 0, max(len(dictionary) - 1, 0))] \
            if len(dictionary) else codes
        data = jnp.asarray(_pad(codes, cap))
        v = jnp.asarray(_pad(valid_np, cap)) if valid_np is not None else None
        return Column(data, v, dt.STRING, sorted_dict)

    if pa.types.is_list(typ) or pa.types.is_large_list(typ) or \
            pa.types.is_struct(typ) or pa.types.is_map(typ):
        # nested types dict-encode host-side (table/nested.py design)
        from bodo_tpu.table import nested as _nested
        pyvals = arr.to_pylist()
        if pa.types.is_struct(typ):
            fields = [(f.name, _arrow_scalar_dtype(f.type))
                      for f in typ]
            ndt = dt.struct_of(fields)
            vals = [None if v is None else
                    tuple(v.get(fn) for fn, _ in fields) for v in pyvals]
        elif pa.types.is_map(typ):
            ndt = dt.map_of(_arrow_scalar_dtype(typ.key_type),
                            _arrow_scalar_dtype(typ.item_type))
            vals = pyvals
        else:
            ndt = dt.list_of(_arrow_scalar_dtype(typ.value_type))
            vals = pyvals
        return _nested.encode_values(vals, ndt, capacity=cap)

    if pa.types.is_timestamp(typ):
        a64 = arr.cast(pa.timestamp("ns")).to_numpy(zero_copy_only=False)
        nat = np.isnat(a64)
        ticks = a64.view(np.int64).copy()
        if nat.any():
            ticks[nat] = 0
            valid_np = ~nat if valid_np is None else (valid_np & ~nat)
        return Column(jnp.asarray(_pad(ticks, cap)),
                      jnp.asarray(_pad(valid_np, cap))
                      if valid_np is not None else None,
                      dt.DATETIME, None)
    if pa.types.is_date(typ):
        days = arr.cast(pa.int32()).to_numpy(zero_copy_only=False)
        days = np.nan_to_num(days).astype(np.int32)
        return Column(jnp.asarray(_pad(days, cap)),
                      jnp.asarray(_pad(valid_np, cap))
                      if valid_np is not None else None,
                      dt.DATE, None)
    if pa.types.is_decimal(typ):
        # decimal128(p≤18, s) → scaled int64 exactly (SURVEY §2.9 plan;
        # reference runtime: bodo/libs/_decimal_ext.cpp)
        if typ.precision > 18:
            raise NotImplementedError(
                f"decimal precision {typ.precision} > 18 does not fit a "
                f"scaled int64")
        # decimal128 stores the scaled integer as int128 little-endian;
        # for precision ≤ 18 the low 64 bits ARE the two's-complement
        # value — read them straight from the buffer, no rescaling cast
        # (arr was combined to a single chunk at function entry)
        raw = np.frombuffer(arr.buffers()[1], dtype=np.int64)
        vals = raw.reshape(-1, 2)[arr.offset:arr.offset + len(arr), 0]
        vals = np.ascontiguousarray(vals)
        if valid_np is not None:
            vals = np.where(valid_np, vals, 0)
        return Column(jnp.asarray(_pad(vals, cap)),
                      jnp.asarray(_pad(valid_np, cap))
                      if valid_np is not None else None,
                      dt.decimal(typ.scale, precision=typ.precision), None)
    if pa.types.is_boolean(typ):
        vals = arr.to_numpy(zero_copy_only=False)
        if vals.dtype == object:
            vals = np.array([bool(x) if x is not None else False
                             for x in vals])
        vals = np.nan_to_num(vals.astype(np.float64)).astype(bool) \
            if vals.dtype.kind == "f" else vals.astype(bool)
        return Column(jnp.asarray(_pad(vals, cap)),
                      jnp.asarray(_pad(valid_np, cap))
                      if valid_np is not None else None, dt.BOOL, None)

    # numeric
    vals = arr.to_numpy(zero_copy_only=False)
    if valid_np is not None and vals.dtype.kind == "f" and \
            not pa.types.is_floating(typ):
        # ints with nulls densified to float by Arrow — restore exact ints
        vals = np.nan_to_num(vals)
    np_dtype = typ.to_pandas_dtype()
    vals = vals.astype(np_dtype)
    dtype = dt.from_numpy(np.dtype(np_dtype))
    if dtype.kind == "f":
        valid_np = None  # NaN carries the null
    return Column(jnp.asarray(_pad(vals, cap)),
                  jnp.asarray(_pad(valid_np, cap))
                  if valid_np is not None else None, dtype, None)


def arrow_to_table(at: pa.Table, columns: Optional[Sequence[str]] = None,
                   capacity: Optional[int] = None) -> Table:
    if columns is not None:
        at = at.select(list(columns))
    n = at.num_rows
    cap = capacity if capacity is not None else round_capacity(n)
    cols: Dict[str, Column] = {}
    for name in at.column_names:
        cols[name] = _arrow_column(at.column(name), cap)
    t = Table(cols, n, REP, None)
    from bodo_tpu.runtime import xla_observatory as xobs
    xobs.track_table(t, "arrow_ingest")
    return t


def table_to_arrow(t: Table) -> pa.Table:
    t = t.gather() if t.distribution == ONED else t
    import jax
    arrays = {}
    for name, col in t.columns.items():
        data = np.asarray(jax.device_get(col.data))[: t.nrows]
        valid = (np.asarray(jax.device_get(col.valid))[: t.nrows]
                 if col.valid is not None else None)
        mask = None if valid is None else ~valid
        if col.dtype is dt.STRING:
            dic = pa.array(col.dictionary if col.dictionary is not None
                           else np.array([], dtype=str))
            idx = np.clip(data, 0, max(len(dic) - 1, 0)).astype(np.int32)
            arr = pa.DictionaryArray.from_arrays(
                pa.array(idx, mask=mask), dic)
            arrays[name] = arr.cast(pa.string())
        elif col.dtype is dt.DATETIME:
            arrays[name] = pa.array(data.view("datetime64[ns]"), mask=mask)
        elif col.dtype is dt.DATE:
            arrays[name] = pa.array(data, type=pa.date32(), mask=mask)
        elif dt.is_decimal(col.dtype):
            arrays[name] = _decimal_from_int64(
                data, col.dtype.scale, mask,
                precision=col.dtype.precision)
        elif dt.is_nested(col.dtype):
            from bodo_tpu.table import nested as _nested
            objs = _nested.decode_column(col, t.nrows)
            if col.dtype.kind == "map":
                typ = pa.map_(_arrow_pa_type(col.dtype.key),
                              _arrow_pa_type(col.dtype.value))
            elif col.dtype.kind == "struct":
                typ = pa.struct([(fn, _arrow_pa_type(ft))
                                 for fn, ft in col.dtype.fields])
            else:
                typ = pa.list_(_arrow_pa_type(col.dtype.elem))
            arrays[name] = pa.array(list(objs), type=typ)
        else:
            arrays[name] = pa.array(data, mask=mask)
    return pa.table(arrays)


def _arrow_pa_type(t: dt.DType) -> pa.DataType:
    return {"str": pa.string(), "b": pa.bool_(), "f": pa.float64(),
            "i": pa.int64(), "u": pa.int64()}.get(t.kind, pa.float64())


def _decimal_from_int64(ints: np.ndarray, scale: int, mask,
                        precision: int = 18) -> pa.Array:
    """Exact int64-scaled → arrow decimal128(precision, scale): widen to
    the int128 little-endian pair buffer with numpy (hi = sign extension),
    no per-row Python objects — the inverse of the read path above.
    `precision` is the source schema's (carried on DecimalDType) so the
    round-trip doesn't widen the column type to 18."""
    n = len(ints)
    pair = np.empty((n, 2), dtype=np.int64)
    pair[:, 0] = ints
    pair[:, 1] = ints >> 63  # two's-complement sign extension
    data_buf = pa.py_buffer(np.ascontiguousarray(pair).tobytes())
    validity = None
    null_count = 0
    if mask is not None and mask.any():
        null_count = int(mask.sum())
        validity = pa.py_buffer(
            np.packbits(~mask, bitorder="little").tobytes())
    return pa.Array.from_buffers(pa.decimal128(precision, scale), n,
                                 [validity, data_buf],
                                 null_count=null_count)
