"""Host I/O: Arrow-based readers/writers feeding device tables.

Analogue of the reference's I/O layer (bodo/io/ — arrow_reader.h,
parquet_reader.cpp, csv_json_reader.cpp): pyarrow does the parsing on
host; columns are converted straight into the padded device layout with
dictionary-encoded strings.
"""

from bodo_tpu.io.arrow_bridge import arrow_to_table, table_to_arrow
from bodo_tpu.io.parquet import read_parquet, write_parquet
from bodo_tpu.io.csv import read_csv

__all__ = ["arrow_to_table", "table_to_arrow", "read_parquet",
           "write_parquet", "read_csv"]
