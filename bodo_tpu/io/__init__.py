"""Host I/O: Arrow-based readers/writers feeding device tables.

Analogue of the reference's I/O layer (bodo/io/ — arrow_reader.h,
parquet_reader.cpp, csv_json_reader.cpp): pyarrow does the parsing on
host; columns are converted straight into the padded device layout with
dictionary-encoded strings.
"""

def stripe(n: int, pi: int, pc: int):
    """Contiguous per-process stripe [lo, hi) — the one stripe-assignment
    invariant every distributed reader shares (reference:
    bodo/libs/distributed_api.py get_node_portion)."""
    return (n * pi) // pc, (n * (pi + 1)) // pc


from bodo_tpu.io.arrow_bridge import arrow_to_table, table_to_arrow  # noqa: E402
from bodo_tpu.io.csv import read_csv
from bodo_tpu.io.hdf5 import read_hdf5, write_hdf5
from bodo_tpu.io.np_io import fromfile, tofile
from bodo_tpu.io.parquet import read_parquet, write_parquet

__all__ = ["arrow_to_table", "table_to_arrow", "read_parquet",
           "write_parquet", "read_csv", "read_hdf5", "write_hdf5",
           "fromfile", "tofile"]
