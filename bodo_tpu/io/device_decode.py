"""Device-side Parquet decode: ship raw pages, decode on-chip.

The host/device inversion the engine applies to relational kernels,
applied to ingest (ROADMAP item 3): instead of pyarrow decoding every
page on host before ``device_put``, the I/O layer ships **raw column
chunk byte ranges** (offsets straight from the cached footer, PR 4) and
jitted XLA programs decode the common encodings directly into padded
device buffers:

  * PLAIN fixed-width (INT32/INT64/FLOAT/DOUBLE): little-endian byte
    assembly via shifts + same-width bitcast,
  * dictionary pages + RLE_DICTIONARY index streams: the host walks the
    RLE/bit-packed hybrid *run headers* (a handful of varints per page),
    the device expands runs and extracts bit-packed values with a
    searchsorted-over-run-starts gather, then maps codes through the
    dictionary (numeric gather on device; string dictionaries stay host
    arrays, codes remap through the sorted-rank LUT exactly like
    ``arrow_bridge``),
  * RLE/bit-packed booleans and PLAIN bit-packed booleans,
  * definition levels -> validity masks, with densely-packed non-null
    values scattered to row positions via a cumsum of the mask.

Exotic encodings (DELTA_BINARY_PACKED, BYTE_STREAM_SPLIT, non-dict
BYTE_ARRAY, INT96, FLBA, nested columns) transparently fall back to the
host pyarrow decode — per column, so one delta-encoded column does not
drag a whole row group back to host.

Work split (who runs where):

  * io_pool worker threads: raw range read, thrift page-header parse,
    per-page host decompression (snappy/gzip/zstd release the GIL in
    arrow), hybrid run-header walk. All O(pages), not O(values).
  * device: everything O(values) — bit unpack, run expansion, byte
    assembly, null scatter, dictionary gather — one jitted program per
    (encoding, dtype, page-shape bucket) cached in
    ``kernel_cache.DecodeProgramCache`` so page count, not page shape,
    drives dispatch cost. Shapes bucket to powers of two to bound the
    program population (XLA:CPU segfaults after thousands of pinned
    executables; see utils/kernel_cache.py).

Decode kernels are jitted ``jnp`` bodies rather than raw Pallas: the
decode is gather/cumsum/bitwise-bound (no MXU work), XLA lowers it well
on both CPU and TPU backends, and tier-1 runs on the CPU backend where
Pallas needs interpret mode. The bodies are decorated ``fusion_stage``
— they run inside compiled programs where host sync is illegal, and the
shardcheck fusion-host-call lint audits them like any fused stage.

Bit-identical parity with ``arrow_bridge._arrow_column`` is the
contract (tests/test_device_decode.py sweeps every encoding): float
nulls become NaN with no mask, int/bool/timestamp/date nulls become
0/False + mask, string nulls carry raw code 0 *before* the sorted-rank
remap, timestamps scale to ns ticks.
"""

from __future__ import annotations

import os
import struct
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

from bodo_tpu.analysis import progcheck
from bodo_tpu.config import config
from bodo_tpu.table import dtypes as dt
from bodo_tpu.table.table import Column, REP, Table, round_capacity

# ---------------------------------------------------------------------------
# format constants
# ---------------------------------------------------------------------------

# page types (parquet.thrift PageType)
_DATA_PAGE, _INDEX_PAGE, _DICT_PAGE, _DATA_PAGE_V2 = 0, 1, 2, 3
# encodings (parquet.thrift Encoding)
_PLAIN = 0
_PLAIN_DICTIONARY = 2
_RLE = 3
_BIT_PACKED = 4
_DELTA_BINARY_PACKED = 5
_DELTA_LENGTH_BYTE_ARRAY = 6
_DELTA_BYTE_ARRAY = 7
_RLE_DICTIONARY = 8
_BYTE_STREAM_SPLIT = 9

_DICT_ENCODINGS = (_PLAIN_DICTIONARY, _RLE_DICTIONARY)

# physical type -> (itemsize, assembled uint dtype)
_PHYS_WIDTH = {"INT32": 4, "INT64": 8, "FLOAT": 4, "DOUBLE": 8}

_MAX_BITWIDTH = 24  # 4-byte gather window in the bit extractor


class Unsupported(Exception):
    """Internal control flow: this chunk/page/file cannot decode on
    device — the caller falls back to the host pyarrow path. Never
    escapes this module."""


# ---------------------------------------------------------------------------
# thrift compact protocol (page headers only)
# ---------------------------------------------------------------------------
# Page headers are tiny (tens of bytes) TCompactProtocol structs; a
# minimal pure-python reader keeps the raw-page path dependency-free.
# Only the fields the decoder routes on are kept; everything else
# (statistics, crc, bloom offsets) is skipped structurally.

_CT_STOP = 0
_CT_TRUE, _CT_FALSE = 1, 2
_CT_BYTE, _CT_I16, _CT_I32, _CT_I64 = 3, 4, 5, 6
_CT_DOUBLE, _CT_BINARY, _CT_LIST, _CT_SET, _CT_MAP, _CT_STRUCT = \
    7, 8, 9, 10, 11, 12


def _uvarint(buf: bytes, off: int):
    out = shift = 0
    while True:
        b = buf[off]
        off += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, off
        shift += 7
        if shift > 63:
            raise Unsupported("varint overflow in page header")


def _zigzag(v: int) -> int:
    return (v >> 1) ^ -(v & 1)


def _skip_field(buf: bytes, off: int, ftype: int) -> int:
    if ftype in (_CT_TRUE, _CT_FALSE):
        return off
    if ftype == _CT_BYTE:
        return off + 1
    if ftype in (_CT_I16, _CT_I32, _CT_I64):
        return _uvarint(buf, off)[1]
    if ftype == _CT_DOUBLE:
        return off + 8
    if ftype == _CT_BINARY:
        n, off = _uvarint(buf, off)
        return off + n
    if ftype == _CT_STRUCT:
        return _skip_struct(buf, off)
    if ftype in (_CT_LIST, _CT_SET):
        head = buf[off]
        off += 1
        n = head >> 4
        if n == 15:
            n, off = _uvarint(buf, off)
        et = head & 0x0F
        for _ in range(n):
            off = _skip_field(buf, off, et)
        return off
    if ftype == _CT_MAP:
        n, off = _uvarint(buf, off)
        if n:
            kt, vt = buf[off] >> 4, buf[off] & 0x0F
            off += 1
            for _ in range(n):
                off = _skip_field(buf, off, kt)
                off = _skip_field(buf, off, vt)
        return off
    raise Unsupported(f"thrift compact type {ftype}")


def _field_header(buf: bytes, off: int, fid: int):
    """Read one compact-protocol field header. Returns
    (fid, ftype, off, stop)."""
    head = buf[off]
    off += 1
    if head == _CT_STOP:
        return fid, _CT_STOP, off, True
    delta = head >> 4
    ftype = head & 0x0F
    if delta:
        fid += delta
    else:
        z, off = _uvarint(buf, off)
        fid = _zigzag(z)
    return fid, ftype, off, False


def _skip_struct(buf: bytes, off: int) -> int:
    fid = 0
    while True:
        fid, ftype, off, stop = _field_header(buf, off, fid)
        if stop:
            return off
        off = _skip_field(buf, off, ftype)


@dataclass
class _PageHeader:
    type: int
    uncompressed_size: int
    compressed_size: int
    num_values: int = 0
    encoding: int = _PLAIN
    def_level_encoding: int = _RLE
    # DataPageHeaderV2 extras
    num_nulls: int = -1           # v2 records it; v1 = -1 (unknown)
    def_levels_byte_len: int = 0  # v2: uncompressed levels at page front
    v2_compressed: bool = True
    header_len: int = 0           # bytes consumed by the thrift header


def _parse_sub(buf, off, hdr, *, v2: bool) -> int:
    """DataPageHeader / DataPageHeaderV2 / DictionaryPageHeader."""
    fid = 0
    while True:
        fid, ftype, off, stop = _field_header(buf, off, fid)
        if stop:
            return off
        if ftype in (_CT_I16, _CT_I32, _CT_I64):
            z, off = _uvarint(buf, off)
            val = _zigzag(z)
        elif ftype in (_CT_TRUE, _CT_FALSE):
            val = ftype == _CT_TRUE
        else:
            off = _skip_field(buf, off, ftype)
            continue
        if fid == 1:
            hdr.num_values = val
        elif not v2:
            if fid == 2:
                hdr.encoding = val
            elif fid == 3:
                hdr.def_level_encoding = val
        else:
            if fid == 2:
                hdr.num_nulls = val
            elif fid == 4:
                hdr.encoding = val
            elif fid == 5:
                hdr.def_levels_byte_len = val
            elif fid == 6 and val != 0:
                raise Unsupported("repetition levels in v2 page")
            elif fid == 7:
                hdr.v2_compressed = bool(val)


def _parse_page_header(buf: bytes, off: int) -> _PageHeader:
    start = off
    hdr = _PageHeader(type=-1, uncompressed_size=0, compressed_size=0)
    fid = 0
    while True:
        fid, ftype, off, stop = _field_header(buf, off, fid)
        if stop:
            break
        if ftype in (_CT_I16, _CT_I32, _CT_I64):
            z, off = _uvarint(buf, off)
            val = _zigzag(z)
            if fid == 1:
                hdr.type = val
            elif fid == 2:
                hdr.uncompressed_size = val
            elif fid == 3:
                hdr.compressed_size = val
        elif ftype == _CT_STRUCT and fid in (5, 7):
            off = _parse_sub(buf, off, hdr, v2=False)
        elif ftype == _CT_STRUCT and fid == 8:
            hdr.v2_compressed = True
            off = _parse_sub(buf, off, hdr, v2=True)
        elif ftype in (_CT_TRUE, _CT_FALSE):
            pass
        else:
            off = _skip_field(buf, off, ftype)
    if hdr.type < 0 or hdr.compressed_size < 0:
        raise Unsupported("malformed page header")
    hdr.header_len = off - start
    return hdr


# ---------------------------------------------------------------------------
# decompression (host, per page — arrow codecs release the GIL)
# ---------------------------------------------------------------------------

_codec_cache: dict = {}
_codec_lock = threading.Lock()


def _codec(name: str):
    name = (name or "UNCOMPRESSED").lower()
    if name == "uncompressed":
        return None
    # parquet "LZ4" is the raw block format in every modern writer (the
    # frame-format legacy is what got LZ4 deprecated in the spec);
    # pa.Codec("lz4") is the FRAME codec, so map to lz4_raw. A true
    # legacy frame file fails decompress -> Unsupported -> host decode.
    if name == "lz4":
        name = "lz4_raw"
    with _codec_lock:
        c = _codec_cache.get(name)
    if c is None:
        import pyarrow as pa
        try:
            c = pa.Codec(name)
        except Exception as e:
            raise Unsupported(f"codec {name}: {e}") from e
        with _codec_lock:
            _codec_cache[name] = c
    return c


def _decompress(codec, raw: bytes, out_size: int) -> bytes:
    if codec is None:
        return raw
    try:
        return codec.decompress(raw,
                                decompressed_size=out_size).to_pybytes()
    except Exception as e:
        # wrong codec flavor / malformed page: demote to host decode,
        # which re-reads from the file through pyarrow (true corruption
        # still surfaces there as a real error)
        raise Unsupported(f"decompress: {e}") from e


# ---------------------------------------------------------------------------
# RLE / bit-packed hybrid: host run-header walk -> device run tables
# ---------------------------------------------------------------------------

@dataclass
class _RunTable:
    """Host-parsed hybrid runs. ``starts[i]`` is the output index where
    run i begins; RLE runs carry ``vals[i]``, bit-packed runs carry the
    absolute bit offset ``bits[i]`` of their first value in the page."""
    starts: np.ndarray   # int32 [n_runs]
    is_rle: np.ndarray   # bool  [n_runs]
    vals: np.ndarray     # int32 [n_runs]
    bits: np.ndarray     # int32 [n_runs]


def _parse_hybrid(buf: bytes, off: int, end: int, bw: int,
                  n: int, exact: bool = True) -> _RunTable:
    """Walk RLE/bit-packed hybrid run headers in buf[off:end] until n
    output values are covered. O(runs), not O(values) — the value work
    happens on device. ``exact=False`` tolerates a stream that ends
    early: dictionary-index and RLE-bool value streams store only the
    NON-NULL entries, so ``n`` (the page's row count) is an upper bound
    there and the stream simply runs out at the stored count."""
    starts: List[int] = []
    is_rle: List[int] = []
    vals: List[int] = []
    bits: List[int] = []
    vbw = (bw + 7) // 8
    count = 0
    while count < n:
        if off >= end:
            if exact:
                raise Unsupported("hybrid run stream truncated")
            break
        header, off = _uvarint(buf, off)
        if header & 1:  # bit-packed: (header >> 1) groups of 8 values
            groups = header >> 1
            if groups <= 0:
                raise Unsupported("empty bit-packed run")
            starts.append(count)
            is_rle.append(False)
            vals.append(0)
            bits.append(off * 8)
            off += groups * bw
            count += groups * 8
        else:  # RLE run: value in ceil(bw/8) LE bytes
            run = header >> 1
            if run <= 0:
                raise Unsupported("empty RLE run")
            v = int.from_bytes(buf[off:off + vbw], "little") if vbw else 0
            off += vbw
            starts.append(count)
            is_rle.append(True)
            vals.append(v)
            bits.append(0)
            count += run
        if off > end:
            raise Unsupported("hybrid run overruns page")
    return _RunTable(np.asarray(starts, np.int32),
                     np.asarray(is_rle, bool),
                     np.asarray(vals, np.int32),
                     np.asarray(bits, np.int32))


def _bucket(n: int, lo: int = 16) -> int:
    """Next power of two >= max(n, lo) — the shape-bucketing that keeps
    the decode-program population bounded."""
    b = lo
    while b < n:
        b <<= 1
    return b


def _pad_runs(rt: _RunTable, runs_bucket: int, sentinel: int) -> tuple:
    """Pad run tables to the bucket; sentinel starts never win the
    searchsorted, so padded runs are inert."""
    k = len(rt.starts)
    starts = np.full(runs_bucket, sentinel, np.int32)
    starts[:k] = rt.starts
    is_rle = np.zeros(runs_bucket, bool)
    is_rle[:k] = rt.is_rle
    vals = np.zeros(runs_bucket, np.int32)
    vals[:k] = rt.vals
    bits = np.zeros(runs_bucket, np.int32)
    bits[:k] = rt.bits
    return starts, is_rle, vals, bits


# ---------------------------------------------------------------------------
# jitted decode programs (cached per shape/encoding/dtype bucket)
# ---------------------------------------------------------------------------

from bodo_tpu.runtime import xla_observatory as xobs  # noqa: E402
from bodo_tpu.utils.kernel_cache import DecodeProgramCache  # noqa: E402


def _describe_spec(spec):
    """Facet split of a _PageSpec for the program registry: shape
    buckets are the churn-prone facet (a drifting page size shows up
    as shape-bucket-churn in retrace attribution)."""
    return f"device_decode:{spec.kind}", {
        "dtype": spec.out_dtype,
        "shape": (spec.byte_bucket, spec.n_bucket, spec.def_runs,
                  spec.val_runs, spec.dict_bucket),
        "static": (spec.itemsize, spec.bit_width, spec.has_defs,
                   spec.masked, spec.scale)}


_programs = DecodeProgramCache(subsystem="device_decode",
                               describe=_describe_spec)
_programs_lock = threading.Lock()

# XLA:CPU's JIT crashes once a process pins thousands of distinct
# executables (same failure mode the fusion compile budget guards).
# Decode programs draw from that pool too — shape bucketing keeps the
# signature count small in real scans, but a full single-process test
# run reads hundreds of tiny files with drifting shapes, so new-spec
# compiles stop after a process-wide budget; later pages decode on the
# host, which is always correct. <0 disables the budget.
_max_compiles = int(os.environ.get(
    "BODO_TPU_DEVICE_DECODE_MAX_COMPILES", "64"))
_n_compiles = 0


def decode_program_stats() -> dict:
    out = _programs.stats()
    left_local = (max(0, _max_compiles - _n_compiles)
                  if _max_compiles >= 0 else -1)
    left_pool = xobs.subsystem_budget_left("device_decode")
    lefts = [x for x in (left_local, left_pool) if x >= 0]
    out["budget_left"] = min(lefts) if lefts else -1
    return out


def clear_programs() -> None:
    """Drop every cached decode program and return the compile budget:
    releasing the program references is what frees the executables, so
    a caller starting clean gets the full budget back."""
    global _n_compiles
    with _programs_lock:
        _programs.clear()
        _n_compiles = 0
    xobs.reset_budget("device_decode")


@dataclass(frozen=True)
class _PageSpec:
    """Static configuration of one jitted page-decode program — the
    decode-program cache key (encoding kind, output dtype, and the
    power-of-two shape buckets)."""
    kind: str            # 'plain' | 'dict' | 'boolplain' | 'boolrle'
    out_dtype: str       # numpy dtype name of the decoded values
    itemsize: int        # physical width for 'plain' (0 otherwise)
    bit_width: int       # index/value bit width for hybrid kinds
    has_defs: bool       # definition levels present (optional column)
    masked: bool         # produce a validity mask + null scatter
    byte_bucket: int     # padded page-byte length
    n_bucket: int        # padded output value count
    def_runs: int        # padded def-level run count
    val_runs: int        # padded value-stream run count (hybrid kinds)
    dict_bucket: int     # padded dictionary length (numeric dict gather)
    scale: int           # timestamp unit -> ns multiplier (1 otherwise)


def _hybrid_expand_body(jnp, data, starts, is_rle, vals, bits, bw,
                        n_bucket):
    """Device run expansion: RLE runs broadcast their value, bit-packed
    runs extract bw bits at bits[run] + (i - start)*bw through a
    little-endian gather window just wide enough for the bit width.

    The output-index -> owning-run map exploits that the output domain
    is SORTED: scatter each run's index at its start position and take a
    running max. XLA:CPU lowers the obvious `searchsorted` to a
    per-element binary search (~60% of warm page-decode time at 256k
    values); the scatter+cummax is one cheap scan. Sentinel-padded runs
    scatter out of range and drop; duplicate starts (empty runs) resolve
    to the later run, matching searchsorted's 'right' side."""
    from jax import lax
    i = jnp.arange(n_bucket, dtype=jnp.int32)
    n_runs = starts.shape[0]
    r = lax.cummax(
        jnp.zeros(n_bucket, jnp.int32).at[starts].max(
            jnp.arange(n_runs, dtype=jnp.int32), mode="drop"))
    # per-run fields folded so the expansion gathers TWO run-table
    # columns, not four (each n_bucket-sized gather is ~0.35ms on the
    # CPU fallback): base = the run's bit offset rebased to i=0, and
    # rv = the RLE value or -1 for bit-packed runs (values are always
    # non-negative, so -1 is a free "take the unpacked bits" sentinel)
    rv = jnp.where(is_rle, vals, -1)[r]
    if bw > 0:
        bp = (bits - starts * bw)[r] + i * bw
        byte0 = bp >> 3
        nb = data.shape[0]
        # ceil((7 + bw) / 8) bytes cover any bit phase: 1 byte for the
        # def-level/bool bw=1 case, 2 for dict indexes up to 9 bits
        w = data[jnp.clip(byte0, 0, nb - 1)].astype(jnp.uint32)
        for k in range(1, (bw + 14) // 8):
            w = w | (data[jnp.clip(byte0 + k, 0, nb - 1)]
                     .astype(jnp.uint32) << (8 * k))
        packed = ((w >> (bp & 7).astype(jnp.uint32))
                  & ((1 << bw) - 1)).astype(jnp.int32)
    else:
        packed = jnp.zeros(n_bucket, jnp.int32)
    return jnp.where(rv >= 0, rv, packed)


def _hybrid_expand_route(jnp, data, starts, is_rle, vals, bits, bw,
                         n_bucket):
    """Run expansion through the Pallas hybrid kernel when its gate is
    open (ops/pallas_kernels.hybrid_expand — the RLE/bit-packed decode
    inner loop on-device), else the XLA searchsorted body. Traced inside
    the jitted page program, so engagement is per compiled spec."""
    from bodo_tpu.ops import pallas_kernels as PK
    try:
        out = PK.hybrid_expand(data, starts, is_rle, vals, bits, bw,
                               n_bucket)
    except Exception as e:  # trace failure -> permanent XLA fallback
        PK.disable_runtime(f"hybrid_expand: {e}")
        out = None
    if out is not None:
        from bodo_tpu.runtime import io_pool
        io_pool.count("pallas_expand_traced")
        return out
    return _hybrid_expand_body(jnp, data, starts, is_rle, vals, bits,
                               bw, n_bucket)


def _assemble_plain_body(jnp, lax, data, val_off, itemsize, out_dtype,
                         n_bucket):
    """PLAIN fixed-width: dynamic-slice the dense value region, assemble
    little-endian uints via shifts, bitcast to the physical dtype."""
    window = lax.dynamic_slice(data, (val_off,), (n_bucket * itemsize,))
    b = window.reshape(n_bucket, itemsize)
    if itemsize == 4:
        u = (b[:, 0].astype(jnp.uint32)
             | (b[:, 1].astype(jnp.uint32) << 8)
             | (b[:, 2].astype(jnp.uint32) << 16)
             | (b[:, 3].astype(jnp.uint32) << 24))
        phys = {"int32": jnp.int32, "uint32": jnp.uint32,
                "float32": jnp.float32}
    else:
        u = b[:, 0].astype(jnp.uint64)
        for k in range(1, 8):
            u = u | (b[:, k].astype(jnp.uint64) << (8 * k))
        phys = {"int64": jnp.int64, "uint64": jnp.uint64,
                "float64": jnp.float64}
    target = phys.get(out_dtype)
    if target is None:
        # narrow logical ints (int8/16, uint8/16) ride in INT32
        base = jnp.int32 if itemsize == 4 else jnp.int64
        return lax.bitcast_convert_type(u, base).astype(out_dtype)
    return lax.bitcast_convert_type(u, target)


def _build_page_program(spec: _PageSpec):
    """One jitted program decoding one page shape: def-level expansion,
    value decode, null scatter, dtype conversion — a single dispatch per
    page, no host round-trip. Traced-body rules apply (fusion_stage)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from bodo_tpu.plan.fusion import fusion_stage

    out_np = np.dtype(spec.out_dtype)
    fill_nan = out_np.kind == "f"

    @fusion_stage
    def _page_decode(data, n_values, dstarts, disrle, dvals, dbits,
                     vstarts, visrle, vvals, vbits, val_off, dictvals):
        i = jnp.arange(spec.n_bucket, dtype=jnp.int32)
        in_rows = i < n_values
        if spec.has_defs:
            levels = _hybrid_expand_route(
                jnp, data, dstarts, disrle, dvals, dbits, 1, spec.n_bucket)
            valid = (levels == 1) & in_rows
        else:
            valid = in_rows
        # densely-packed non-null values: row i reads packed slot
        # cumsum(valid)-1 (identity when no nulls)
        if spec.masked or spec.has_defs:
            pos = jnp.cumsum(valid.astype(jnp.int32)) - 1
            pos = jnp.clip(pos, 0, spec.n_bucket - 1)
        else:
            pos = i
        if spec.kind == "plain":
            dense = _assemble_plain_body(jnp, lax, data, val_off,
                                         spec.itemsize, spec.out_dtype,
                                         spec.n_bucket)
            vals_at = dense[pos]
        elif spec.kind == "dict":
            codes = _hybrid_expand_route(
                jnp, data, vstarts, visrle, vvals, vbits, spec.bit_width,
                spec.n_bucket)
            codes = codes[pos]
            # null rows carry raw code 0 (matches arrow_bridge's NaN->0
            # before the rank remap)
            codes = jnp.where(valid, codes, 0)
            if spec.dict_bucket:
                vals_at = dictvals[
                    jnp.clip(codes, 0, spec.dict_bucket - 1)]
            else:
                vals_at = codes.astype(jnp.int32)
        elif spec.kind == "boolplain":
            bits_i = val_off.astype(jnp.int32) * 8 + pos
            byte0 = bits_i >> 3
            nb = data.shape[0]
            vals_at = ((data[jnp.clip(byte0, 0, nb - 1)]
                        >> (bits_i & 7).astype(jnp.uint8)) & 1) > 0
        elif spec.kind == "boolrle":
            dense = _hybrid_expand_route(
                jnp, data, vstarts, visrle, vvals, vbits, 1, spec.n_bucket)
            vals_at = dense[pos] > 0
        else:  # pragma: no cover - spec construction guards this
            raise AssertionError(spec.kind)
        if spec.scale != 1:
            vals_at = vals_at * spec.scale
        vals_at = vals_at.astype(out_np)
        if fill_nan:
            # float nulls: NaN carries the null inside the row range,
            # zeros pad beyond it (mirrors _pad + NaN densification)
            out = jnp.where(valid, vals_at, jnp.asarray(np.nan, out_np))
            out = jnp.where(in_rows, out, jnp.zeros((), out_np))
        else:
            out = jnp.where(valid, vals_at, jnp.zeros((), out_np))
        n_nulls = jnp.sum(in_rows & ~valid).astype(jnp.int32)
        return out, valid, n_nulls

    # stored into _programs (DecodeProgramCache) by _page_program
    # under its lock  # shardcheck: ignore[unregistered-jit]
    return jax.jit(_page_decode)


def _page_program(spec: _PageSpec):
    global _n_compiles
    with _programs_lock:
        fn = _programs.lookup(spec)
        if fn is None:
            if _n_compiles >= _max_compiles >= 0 \
                    or not xobs.try_spend("device_decode"):
                raise Unsupported("decode compile budget spent")
            _n_compiles += 1
    if fn is not None:
        return fn, False
    fn = _build_page_program(spec)
    with _programs_lock:
        _programs[spec] = fn
    return fn, True


_ZERO_RUNS = 8  # run-table bucket floor


def _run_page_program(spec: _PageSpec, page_bytes: bytes, n_values: int,
                      def_runs: Optional[_RunTable],
                      val_runs: Optional[_RunTable],
                      val_off: int, dictvals: Optional[np.ndarray]):
    """Dispatch one page through its cached program; returns device
    (values[n_bucket], valid[n_bucket], n_nulls scalar)."""
    import jax.numpy as jnp

    data = np.zeros(spec.byte_bucket, np.uint8)
    data[:len(page_bytes)] = np.frombuffer(page_bytes, np.uint8)
    sentinel = spec.n_bucket + 1

    def runs_or_zero(rt, bucket):
        if rt is None:
            z = np.full(bucket, sentinel, np.int32)
            return (z, np.zeros(bucket, bool), np.zeros(bucket, np.int32),
                    np.zeros(bucket, np.int32))
        return _pad_runs(rt, bucket, sentinel)

    ds, dr, dv, db = runs_or_zero(def_runs, spec.def_runs)
    vs, vr, vv, vb = runs_or_zero(val_runs, spec.val_runs)
    if spec.dict_bucket and dictvals is not None:
        dpad = np.zeros(spec.dict_bucket, dictvals.dtype)
        dpad[:len(dictvals)] = dictvals
    else:
        dpad = np.zeros(max(spec.dict_bucket, 1),
                        np.dtype(spec.out_dtype) if spec.dict_bucket
                        else np.int32)
    fn, compiled = _page_program(spec)
    t0 = time.perf_counter()
    args_in = (jnp.asarray(data), np.int32(n_values),
               jnp.asarray(ds), jnp.asarray(dr), jnp.asarray(dv),
               jnp.asarray(db), jnp.asarray(vs), jnp.asarray(vr),
               jnp.asarray(vv), jnp.asarray(vb), np.int32(val_off),
               jnp.asarray(dpad))
    try:
        out = fn(*args_in)
    except Exception as e:
        # a pallas-routed page program can fail at backend compile time
        # (e.g. Mosaic rejecting the dynamic byte gathers): permanently
        # fall back and rebuild this spec on the XLA body once
        from bodo_tpu.ops import pallas_kernels as PK
        if PK._runtime_disabled and not PK.FORCE_INTERPRET:
            raise
        PK.disable_runtime(f"page program {spec.kind}: {e}")
        with _programs_lock:
            _programs.pop(spec)
        fn = _build_page_program(spec)
        with _programs_lock:
            _programs[spec] = fn
        out = fn(*args_in)
    if compiled:
        h = _programs.handle_for(spec)
        progcheck.check_jit(fn, args_in,
                            program=f"device_decode:{spec.kind}",
                            subsystem="device_decode", obs_handle=h)
        progcheck.mark_checked(h)
        with _programs_lock:
            _programs.record_compile(f"device_decode:{spec.kind}",
                                     time.perf_counter() - t0,
                                     handle=_programs.handle_for(spec))
    xobs.track_buffer(out[0], "device_decode")
    xobs.track_buffer(out[1], "device_decode")
    return out


# ---------------------------------------------------------------------------
# chunk planning (footer + arrow schema -> device route or fallback)
# ---------------------------------------------------------------------------

@dataclass
class _ColPlan:
    """Per-column decode plan derived from footer metadata alone (no
    data bytes touched yet)."""
    name: str
    leaf: int                 # leaf column index in the parquet schema
    phys: str                 # physical type
    codec_name: str
    max_def: int
    num_values: int
    start: int                # chunk byte range [start, start+size)
    size: int
    null_count: Optional[int]  # from chunk statistics (None = unknown)
    out_dtype: str            # numpy dtype of decoded values
    col_dtype: dt.DType       # logical table dtype
    scale: int = 1            # timestamp -> ns multiplier
    is_string: bool = False


def _arrow_out(field_type, phys: str):
    """Map an arrow field type to (np dtype name, table DType, ns scale,
    is_string) or raise Unsupported. Mirrors _arrow_column exactly."""
    import pyarrow as pa
    t = field_type
    if pa.types.is_dictionary(t):
        t = t.value_type
    if pa.types.is_string(t) or pa.types.is_large_string(t):
        if phys != "BYTE_ARRAY":
            raise Unsupported(f"string stored as {phys}")
        return "int32", dt.STRING, 1, True
    if phys == "BYTE_ARRAY":
        raise Unsupported("non-string BYTE_ARRAY")
    if pa.types.is_timestamp(t):
        scale = {"ns": 1, "us": 1000, "ms": 1_000_000,
                 "s": 1_000_000_000}.get(t.unit)
        if scale is None or phys != "INT64":
            raise Unsupported(f"timestamp unit {t.unit} phys {phys}")
        return "int64", dt.DATETIME, scale, False
    if pa.types.is_date32(t):
        return "int32", dt.DATE, 1, False
    if pa.types.is_boolean(t):
        return "bool", dt.BOOL, 1, False
    if pa.types.is_integer(t) or pa.types.is_floating(t):
        np_name = t.to_pandas_dtype().__name__
        return np_name, dt.from_numpy(np.dtype(np_name)), 1, False
    raise Unsupported(f"arrow type {t}")


def _plan_chunk(md, arrow_schema, rg: int, name: str) -> _ColPlan:
    """Decide whether one column chunk can decode on device; raises
    Unsupported to route it to the host fallback."""
    schema = md.schema
    leaf = None
    for i in range(md.num_columns):
        if schema.column(i).path == name:
            leaf = i
            break
    if leaf is None:
        raise Unsupported(f"no flat leaf for column {name!r} (nested?)")
    cs = schema.column(leaf)
    if cs.max_repetition_level > 0:
        raise Unsupported("repeated (nested) column")
    if cs.max_definition_level > 1:
        raise Unsupported("definition depth > 1 (nested optional)")
    col = md.row_group(rg).column(leaf)
    phys = col.physical_type
    if phys not in ("INT32", "INT64", "FLOAT", "DOUBLE", "BOOLEAN",
                    "BYTE_ARRAY"):
        raise Unsupported(f"physical type {phys}")
    for enc in col.encodings:
        if enc in ("DELTA_BINARY_PACKED", "DELTA_LENGTH_BYTE_ARRAY",
                   "DELTA_BYTE_ARRAY", "BYTE_STREAM_SPLIT"):
            raise Unsupported(f"encoding {enc}")
    try:
        field_type = arrow_schema.field(name).type
    except KeyError as e:
        raise Unsupported(f"no arrow field for {name!r}") from e
    out_dtype, col_dtype, scale, is_str = _arrow_out(field_type, phys)
    _codec(col.compression)  # raises Unsupported for unavailable codecs
    dpo = col.dictionary_page_offset
    start = col.data_page_offset
    if dpo is not None and 0 < dpo < start:
        start = dpo
    stats = col.statistics
    null_count = None
    if stats is not None and stats.has_null_count:
        null_count = int(stats.null_count)
    return _ColPlan(name=name, leaf=leaf, phys=phys,
                    codec_name=col.compression,
                    max_def=cs.max_definition_level,
                    num_values=col.num_values, start=start,
                    size=col.total_compressed_size,
                    null_count=null_count, out_dtype=out_dtype,
                    col_dtype=col_dtype, scale=scale, is_string=is_str)


# ---------------------------------------------------------------------------
# raw bundles: what the io_pool ships (bytes + parsed page descriptors)
# ---------------------------------------------------------------------------

@dataclass
class _Page:
    kind: str                 # 'plain' | 'dict' | 'boolplain' | 'boolrle'
    num_values: int
    data: bytes               # decompressed page payload
    def_runs: Optional[_RunTable]
    val_runs: Optional[_RunTable]
    val_off: int              # byte offset of dense PLAIN/bool values
    bit_width: int            # dict-index bit width
    has_defs: bool
    num_nulls: int            # -1 = unknown (v1 page, stats absent)


@dataclass
class _RawColumn:
    plan: _ColPlan
    pages: List[_Page] = field(default_factory=list)
    dictionary: Optional[np.ndarray] = None   # dict-page values (host)
    raw_bytes: int = 0


@dataclass
class RawRowGroup:
    """One row group's shipped payload: per-column raw pages for the
    device route plus pyarrow columns for host-fallback ones. ``nbytes``
    charges prefetch admission at compressed + decoded size."""
    file: str
    rg: int
    nrows: int
    device_cols: Dict[str, _RawColumn]
    host_cols: List[str]
    names: List[str]          # output column order
    compressed_bytes: int = 0
    decoded_bytes: int = 0
    host_table = None         # pa.Table for host_cols (set by fetch)

    @property
    def nbytes(self) -> int:
        return int(self.compressed_bytes + self.decoded_bytes)


def enabled() -> bool:
    """Device decode on? (config.device_decode / BODO_TPU_DEVICE_DECODE;
    default on — exotic shapes fall back per column.)"""
    try:
        return bool(config.device_decode)
    except Exception:
        return False


def _parse_string_dict(buf: bytes, n: int) -> np.ndarray:
    out = []
    off = 0
    for _ in range(n):
        (ln,) = struct.unpack_from("<I", buf, off)
        off += 4
        out.append(buf[off:off + ln].decode("utf-8"))
        off += ln
    return np.asarray(out, dtype=str) if out else np.array([], dtype=str)


def _split_chunk_pages(plan: _ColPlan, raw: bytes) -> _RawColumn:
    """Walk a column chunk's pages: parse headers, decompress payloads,
    pre-parse run tables. Raises Unsupported on any page the device
    programs can't decode (caller falls back to host for the column)."""
    codec = _codec(plan.codec_name)
    rc = _RawColumn(plan=plan, raw_bytes=len(raw))
    off = 0
    values_seen = 0
    while values_seen < plan.num_values:
        if off >= len(raw):
            raise Unsupported("chunk ended before all values")
        hdr = _parse_page_header(raw, off)
        off += hdr.header_len
        payload = raw[off:off + hdr.compressed_size]
        if len(payload) != hdr.compressed_size:
            raise Unsupported("page payload truncated")
        off += hdr.compressed_size
        if hdr.type == _DICT_PAGE:
            if rc.dictionary is not None:
                raise Unsupported("multiple dictionary pages")
            data = _decompress(codec, payload, hdr.uncompressed_size)
            if plan.is_string:
                rc.dictionary = _parse_string_dict(data, hdr.num_values)
            else:
                if plan.phys not in _PHYS_WIDTH:
                    raise Unsupported(f"dict of {plan.phys}")
                rc.dictionary = np.frombuffer(
                    data, dtype=_phys_np(plan.phys),
                    count=hdr.num_values)
            continue
        if hdr.type == _INDEX_PAGE:
            continue
        if hdr.type not in (_DATA_PAGE, _DATA_PAGE_V2):
            raise Unsupported(f"page type {hdr.type}")
        v2 = hdr.type == _DATA_PAGE_V2
        if v2:
            lvl_len = hdr.def_levels_byte_len
            levels = payload[:lvl_len]
            body = payload[lvl_len:]
            if hdr.v2_compressed:
                body = _decompress(codec, body,
                                   hdr.uncompressed_size - lvl_len)
            data = levels + body
            lvl_off, lvl_end = 0, lvl_len
            val_off = lvl_len
        else:
            data = _decompress(codec, payload, hdr.uncompressed_size)
            if plan.max_def > 0:
                if hdr.def_level_encoding != _RLE:
                    raise Unsupported("non-RLE definition levels")
                (lvl_len,) = struct.unpack_from("<I", data, 0)
                lvl_off, lvl_end = 4, 4 + lvl_len
                val_off = 4 + lvl_len
            else:
                lvl_off = lvl_end = val_off = 0
        def_runs = None
        if plan.max_def > 0:
            def_runs = _parse_hybrid(data, lvl_off, lvl_end, 1,
                                     hdr.num_values)
        page = _make_page(plan, hdr, data, val_off, def_runs)
        rc.pages.append(page)
        values_seen += hdr.num_values
    if values_seen != plan.num_values:
        raise Unsupported("page value counts disagree with footer")
    return rc


def _phys_np(phys: str) -> str:
    return {"INT32": "<i4", "INT64": "<i8", "FLOAT": "<f4",
            "DOUBLE": "<f8"}[phys]


def _make_page(plan: _ColPlan, hdr: _PageHeader, data: bytes,
               val_off: int, def_runs: Optional[_RunTable]) -> _Page:
    enc = hdr.encoding
    nn = hdr.num_values
    if enc in _DICT_ENCODINGS:
        bw = data[val_off] if val_off < len(data) else 0
        if bw > _MAX_BITWIDTH:
            raise Unsupported(f"dict index bit width {bw}")
        # n is an upper bound: with nulls the index stream stores only
        # the non-null entries (exact=False lets it run out early)
        val_runs = _parse_hybrid(data, val_off + 1, len(data), bw, nn,
                                 exact=False) \
            if nn else _RunTable(*(np.zeros(0, t) for t in
                                   (np.int32, bool, np.int32, np.int32)))
        return _Page("dict", nn, data, def_runs, val_runs, 0, bw,
                     plan.max_def > 0, hdr.num_nulls)
    if enc == _PLAIN:
        if plan.phys == "BOOLEAN":
            return _Page("boolplain", nn, data, def_runs, None, val_off,
                         1, plan.max_def > 0, hdr.num_nulls)
        if plan.is_string or plan.phys not in _PHYS_WIDTH:
            raise Unsupported("PLAIN variable-width values")
        return _Page("plain", nn, data, def_runs, None, val_off, 0,
                     plan.max_def > 0, hdr.num_nulls)
    if enc == _RLE and plan.phys == "BOOLEAN":
        (ln,) = struct.unpack_from("<I", data, val_off)
        val_runs = _parse_hybrid(data, val_off + 4, val_off + 4 + ln, 1,
                                 nn, exact=False) if nn else None
        return _Page("boolrle", nn, data, def_runs, val_runs, 0, 1,
                     plan.max_def > 0, hdr.num_nulls)
    raise Unsupported(f"data page encoding {enc}")


# ---------------------------------------------------------------------------
# fetch (pool side): raw ranges in, page bundles out
# ---------------------------------------------------------------------------

def fetch_row_group(f: str, rg: int, columns: Optional[Sequence[str]],
                    *, inject: bool = True) -> RawRowGroup:
    """Pool task: ship one row group as raw pages. Device-decodable
    columns carry decompressed page payloads + run tables; the rest are
    host-read via pyarrow right here (still on the pool thread). IO
    errors and armed ``io.read`` faults propagate to the caller's retry
    envelope."""
    from bodo_tpu.io.parquet import _raw_range, footer_metadata
    from bodo_tpu.runtime import io_pool, resilience

    if inject:
        resilience.maybe_inject("io.read")
    md = footer_metadata(f)
    arrow_schema = _arrow_schema_of(md)
    g = md.row_group(rg)
    names = list(columns) if columns else list(arrow_schema.names)
    bundle = RawRowGroup(file=f, rg=rg, nrows=g.num_rows,
                         device_cols={}, host_cols=[], names=names)
    for name in names:
        try:
            plan = _plan_chunk(md, arrow_schema, rg, name)
            raw = _raw_range(f, plan.start, plan.size)
            rc = _split_chunk_pages(plan, raw)
            if plan.is_string and rc.dictionary is None and \
                    plan.num_values > 0:
                raise Unsupported("string chunk without dictionary page")
            bundle.device_cols[name] = rc
            bundle.compressed_bytes += plan.size
            bundle.decoded_bytes += plan.num_values * \
                max(np.dtype(plan.out_dtype).itemsize, 1)
        except Unsupported:
            bundle.host_cols.append(name)
    if bundle.host_cols:
        import pyarrow.parquet as pq

        from bodo_tpu.io.parquet import _opened
        with _opened(f) as src:
            pf = pq.ParquetFile(src, metadata=md)
            bundle.host_table = pf.read_row_group(rg,
                                                  columns=bundle.host_cols)
        bundle.decoded_bytes += bundle.host_table.nbytes
        io_pool.count("host_decode_bytes", int(bundle.host_table.nbytes))
    io_pool.count("raw_bytes", int(bundle.compressed_bytes))
    return bundle


_arrow_schema_cache: dict = {}
_arrow_schema_lock = threading.Lock()


def _arrow_schema_of(md):
    # keyed by id(md), so each entry must PIN its metadata object: a
    # footer evicted from parquet._footer_cache can be freed and a new
    # file's FileMetaData allocated at the same address, which would
    # silently serve the old file's schema (wrong column set) here
    key = id(md)
    with _arrow_schema_lock:
        ent = _arrow_schema_cache.get(key)
    if ent is not None and ent[0] is md:
        return ent[1]
    sch = md.schema.to_arrow_schema()
    with _arrow_schema_lock:
        if len(_arrow_schema_cache) > 64:
            _arrow_schema_cache.clear()
        _arrow_schema_cache[key] = (md, sch)
    return sch


# ---------------------------------------------------------------------------
# decode (consumer side): bundles -> device Tables
# ---------------------------------------------------------------------------

def _decode_column(rc: _RawColumn, cap: int) -> Column:
    """Decode one column chunk's pages on device and assemble the padded
    column. One program dispatch per page; concat + pad stay on device."""
    import jax.numpy as jnp

    plan = rc.plan
    parts = []
    valid_parts = []
    null_scalars = []
    stats_clean = plan.null_count == 0
    dict_numeric = rc.dictionary is not None and not plan.is_string
    for pg in rc.pages:
        masked = plan.max_def > 0 and not stats_clean
        # stats prove zero nulls -> every def level is 1, so the level
        # expansion and the dense-position cumsum are identities: decode
        # as if the page had no def levels (the same stats trust that
        # already drops the validity mask via stats_clean above)
        skip_defs = pg.has_defs and stats_clean
        n_bucket = _bucket(pg.num_values, 128)
        if pg.kind == "plain":
            itemsize = _PHYS_WIDTH[plan.phys]
            byte_need = max(len(pg.data), pg.val_off + n_bucket * itemsize)
            dict_bucket = 0
        elif pg.kind == "dict":
            itemsize = 0
            byte_need = len(pg.data) + 4
            dict_bucket = _bucket(len(rc.dictionary), 16) \
                if dict_numeric else 0
        else:
            itemsize = 0
            byte_need = max(len(pg.data), pg.val_off + n_bucket // 8 + 8)
            dict_bucket = 0
        spec = _PageSpec(
            kind=pg.kind,
            out_dtype=("int32" if plan.is_string else plan.out_dtype),
            itemsize=itemsize, bit_width=pg.bit_width,
            has_defs=pg.has_defs and not skip_defs, masked=masked,
            byte_bucket=_bucket(byte_need, 4096),
            n_bucket=n_bucket,
            def_runs=_bucket(len(pg.def_runs.starts), _ZERO_RUNS)
            if pg.def_runs is not None and not skip_defs else _ZERO_RUNS,
            val_runs=_bucket(len(pg.val_runs.starts), _ZERO_RUNS)
            if pg.val_runs is not None else _ZERO_RUNS,
            dict_bucket=dict_bucket,
            scale=plan.scale)
        vals, valid, n_nulls = _run_page_program(
            spec, pg.data, pg.num_values,
            None if skip_defs else pg.def_runs, pg.val_runs,
            pg.val_off, rc.dictionary if dict_numeric else None)
        parts.append(vals[:pg.num_values])
        valid_parts.append(valid[:pg.num_values])
        null_scalars.append(n_nulls)
    out_np = np.dtype("int32" if plan.is_string else plan.out_dtype)
    if not parts:
        data = jnp.zeros(cap, out_np)
        valid_all = None
    else:
        data = jnp.concatenate(parts) if len(parts) > 1 else parts[0]
        n = data.shape[0]
        if n < cap:
            data = jnp.concatenate([data, jnp.zeros(cap - n, out_np)])
        else:
            data = data[:cap]
        valid_all = jnp.concatenate(valid_parts) \
            if len(valid_parts) > 1 else valid_parts[0]
        pad = cap - valid_all.shape[0]
        if pad > 0:
            valid_all = jnp.concatenate(
                [valid_all, jnp.zeros(pad, bool)])
        else:
            valid_all = valid_all[:cap]
    # mask presence must match arrow_bridge: floats never carry one
    # (NaN is the null), others only when the chunk actually has nulls
    valid_out = None
    if out_np.kind != "f" and plan.max_def > 0 and parts:
        if stats_clean:
            valid_out = None
        elif plan.null_count is not None and plan.null_count > 0:
            valid_out = valid_all
        else:
            total = sum(int(x) for x in np.asarray(
                jnp.stack(null_scalars)))
            valid_out = valid_all if total > 0 else None
    dictionary = None
    if plan.is_string:
        raw_dict = rc.dictionary if rc.dictionary is not None \
            else np.array([], dtype=str)
        order = np.argsort(raw_dict, kind="stable")
        rank = np.empty_like(order)
        rank[order] = np.arange(len(order))
        dictionary = raw_dict[order] if len(raw_dict) else raw_dict
        if len(raw_dict):
            # rank remap applies to live rows only; the pad region stays
            # raw zero, matching arrow_bridge's _pad(np.zeros)
            lut = jnp.asarray(rank.astype(np.int32))
            clipped = jnp.clip(data, 0, len(raw_dict) - 1)
            # string-dict index gather: Pallas dictionary kernel when
            # the gate is open (ranks < dict length — always f32-exact)
            from bodo_tpu.ops import pallas_kernels as PK
            from bodo_tpu.runtime import io_pool
            remapped = PK.dict_gather(clipped, lut)
            if remapped is not None:
                io_pool.count("pallas_dict_gather")
            else:
                remapped = lut[clipped]
            live = jnp.arange(cap, dtype=jnp.int32) < plan.num_values
            data = jnp.where(live, remapped, 0).astype(jnp.int32)
    return Column(data, valid_out, plan.col_dtype, dictionary)


def decode_row_group(bundle: RawRowGroup,
                     capacity: Optional[int] = None) -> Table:
    """Decode one shipped row group into a REP Table: device programs
    for planned columns, ``arrow_bridge`` for host-fallback ones (same
    capacity, so the merged table is indistinguishable from a host
    read)."""
    from bodo_tpu.io.arrow_bridge import _arrow_column
    from bodo_tpu.runtime import io_pool

    t0 = time.perf_counter()
    cap = capacity if capacity is not None else round_capacity(
        bundle.nrows)
    cols: Dict[str, Column] = {}
    n_pages = 0
    dev_bytes = 0
    for name in bundle.names:
        rc = bundle.device_cols.get(name)
        if rc is not None:
            try:
                cols[name] = _decode_column(rc, cap)
                n_pages += len(rc.pages)
                dev_bytes += rc.plan.num_values * \
                    max(np.dtype(rc.plan.out_dtype).itemsize, 1)
                continue
            except Exception:
                # decode surprise: demote this column to host (the raw
                # chunk bytes aren't a pyarrow input, so re-read it)
                bundle.host_cols.append(name)
                io_pool.count("device_decode_errors")
        cols[name] = None  # host-filled below
    missing = [n for n, c in cols.items() if c is None]
    if missing:
        at = bundle.host_table
        have = set() if at is None else set(at.column_names)
        need = [n for n in missing if n not in have]
        if need:
            import pyarrow.parquet as pq

            from bodo_tpu.io.parquet import _opened, footer_metadata
            with _opened(bundle.file) as src:
                pf = pq.ParquetFile(src,
                                    metadata=footer_metadata(bundle.file))
                extra = pf.read_row_group(bundle.rg, columns=need)
            io_pool.count("host_decode_bytes", int(extra.nbytes))
            at = extra if at is None else _merge_tables(at, extra)
        for n in missing:
            cols[n] = _arrow_column(at.column(n), cap)
    t = Table(cols, bundle.nrows, REP, None)
    t._device_decoded = bool(bundle.device_cols)
    io_pool.count("device_decode_pages", n_pages)
    io_pool.count("device_decode_bytes", dev_bytes)
    io_pool.count("device_decode_cols", len(bundle.device_cols))
    io_pool.count("device_fallback_cols", len(set(bundle.host_cols)))
    io_pool.add_time("device_decode_s", time.perf_counter() - t0)
    return t


def _merge_tables(a, b):
    import pyarrow as pa
    arrays = {n: a.column(n) for n in a.column_names}
    arrays.update({n: b.column(n) for n in b.column_names})
    return pa.table(arrays)


# ---------------------------------------------------------------------------
# REP-table concat with dictionary unification
# ---------------------------------------------------------------------------

def concat_tables_rep(tables: List[Table]) -> Table:
    """Concatenate per-row-group REP tables on device, unioning string
    dictionaries (host LUT, device gather — the streaming DictTracker's
    remap, applied once at assembly)."""
    import jax.numpy as jnp

    if len(tables) == 1:
        return tables[0]
    n_total = sum(t.nrows for t in tables)
    cap = round_capacity(n_total)
    names = list(tables[0].columns)
    cols: Dict[str, Column] = {}
    for name in names:
        parts = [t.columns[name] for t in tables]
        dtype = parts[0].dtype
        if any(p.dtype is not dtype for p in parts):
            raise Unsupported(f"dtype drift across row groups: {name}")
        union = None
        if dtype is dt.STRING:
            dicts = [p.dictionary if p.dictionary is not None
                     else np.array([], str) for p in parts]
            union = dicts[0]
            for d in dicts[1:]:
                if d is not union and (len(union) != len(d)
                                       or not np.array_equal(union, d)):
                    union = np.union1d(union, d)
        datas, valids = [], []
        any_valid = any(p.valid is not None for p in parts)
        for t, p in zip(tables, parts):
            d = p.data[:t.nrows]
            if union is not None and p.dictionary is not None and \
                    union is not p.dictionary and len(p.dictionary):
                lut = np.searchsorted(
                    union, p.dictionary).astype(np.int32)
                d = jnp.asarray(lut)[jnp.clip(
                    d, 0, len(p.dictionary) - 1)]
            datas.append(d)
            if any_valid:
                valids.append(p.valid[:t.nrows] if p.valid is not None
                              else jnp.ones(t.nrows, bool))
        data = jnp.concatenate(datas) if len(datas) > 1 else datas[0]
        pad = cap - data.shape[0]
        if pad > 0:
            data = jnp.concatenate(
                [data, jnp.zeros(pad, data.dtype)])
        valid = None
        if any_valid:
            valid = jnp.concatenate(valids) if len(valids) > 1 \
                else valids[0]
            if pad > 0:
                valid = jnp.concatenate([valid, jnp.zeros(pad, bool)])
            if dtype is dt.STRING and union is not None and len(union):
                # arrow's oracle convention: null slots carry the code
                # of the column's FIRST non-null value (encounter-order
                # dictionary[0]); per-chunk decode filled rank(chunk's
                # own first value) instead, which only matches for the
                # first row group. Recover the global fill from the
                # first live row so multi-row-group reads stay
                # bit-identical to a host read.
                null_code = data[jnp.argmax(valid)]
                live = jnp.arange(cap, dtype=jnp.int32) < n_total
                data = jnp.where(valid | ~live, data, null_code)
        cols[name] = Column(data, valid, dtype, union)
    out = Table(cols, n_total, REP, None)
    out._device_decoded = any(getattr(t, "_device_decoded", False)
                              for t in tables)
    return out


# ---------------------------------------------------------------------------
# read-path entry points
# ---------------------------------------------------------------------------

def worth_device_decode(units) -> bool:
    """Size gate for the device route: estimated decoded bytes (footer
    row-group totals) must clear config.device_decode_min_bytes. Small
    reads stay on host — dispatch overhead dominates, and each novel
    page shape would pin another XLA executable for nothing."""
    from bodo_tpu.io.parquet import footer_metadata

    min_b = int(getattr(config, "device_decode_min_bytes", 0))
    if min_b <= 0:
        return True
    est = 0
    for unit in units:
        f, rg = unit[0], unit[1]
        est += footer_metadata(f).row_group(rg).total_byte_size
        if est >= min_b:
            return True
    return False


def read_units_table(units, columns) -> Optional[Table]:
    """Device route for io/parquet._read_parquet_once: pool workers ship
    raw page bundles (ordered), the consumer decodes on device. Returns
    None when the dataset can't take the device route at all (caller
    re-reads via the classic host path); IO/injection errors propagate."""
    from bodo_tpu.runtime import io_pool

    if not worth_device_decode(units):
        return None

    def fetch(unit):
        f, rg, _w = unit
        return fetch_row_group(f, rg, columns)

    try:
        if len(units) > 1 and io_pool.io_thread_count() > 1:
            io_pool.count("parallel_reads")
            bundles = list(io_pool.pool_map_ordered(fetch, units))
        else:
            bundles = [fetch(u) for u in units]
        tables = [decode_row_group(b) for b in bundles]
        return concat_tables_rep(tables)
    except Unsupported:
        return None


def raw_bundles(path, columns, units=None):
    """Generator of RawRowGroup bundles for the streaming source; each
    fetch runs under the shared retry envelope. ``nbytes`` on each item
    charges prefetch admission at compressed + decoded size."""
    from bodo_tpu.io.parquet import _dataset_files, footer_metadata
    from bodo_tpu.runtime import resilience

    if units is None:
        units = []
        for f in _dataset_files(path):
            md = footer_metadata(f)
            units.extend((f, rg) for rg in range(md.num_row_groups))
    # label matches the host streaming route's per-pull envelope: the
    # "streaming parquet reads retry" contract is route-independent
    for f, rg in units:
        yield resilience.retry_call(
            lambda f=f, rg=rg: fetch_row_group(f, rg, columns),
            label="parquet_batch", point="io.read")


def decoded_batches(bundles, batch_rows: int):
    """Decode shipped bundles and re-slice to fixed-capacity batches
    (one compiled shape downstream). Row-group remainders carry over
    into the next group, preserving the parquet_batches contract that
    every batch except the stream's last holds exactly batch_rows rows.
    Dictionary drift across row groups is the streaming DictTracker's
    job — batches keep their chunk dictionary here."""
    from bodo_tpu.plan.streaming import table_batches

    carry = None
    for bundle in bundles:
        t = decode_row_group(bundle)
        if carry is not None:
            t = concat_tables_rep([carry, t])
            carry = None
        flag = getattr(t, "_device_decoded", False)
        out = list(table_batches(t, batch_rows))
        for b in out:
            b._device_decoded = flag
        if out and out[-1].nrows < batch_rows:
            carry = out.pop()
        yield from out
    if carry is not None:
        yield carry
