"""NumPy binary IO (reference: bodo/io/np_io.py fromfile/tofile —
distributed flat-binary reads with per-rank offsets)."""

from __future__ import annotations

import os
from typing import Optional

import numpy as np


def fromfile(path: str, dtype, count: int = -1,
             process_index: Optional[int] = None,
             process_count: Optional[int] = None) -> np.ndarray:
    """Each process reads its contiguous stripe of a flat binary file
    (the reference's get_node_portion + seek/read pattern)."""
    import jax

    from bodo_tpu.io import stripe
    pi = process_index if process_index is not None else jax.process_index()
    pc = process_count if process_count is not None else jax.process_count()
    item = np.dtype(dtype).itemsize
    total = os.path.getsize(path) // item if count < 0 else count
    lo, hi = stripe(total, pi, pc)
    with open(path, "rb") as f:
        f.seek(lo * item)
        return np.fromfile(f, dtype=dtype, count=hi - lo)


def tofile(arr, path: str) -> None:
    """Write an array (gathering sharded jax arrays host-side first)."""
    import jax
    if isinstance(arr, jax.Array):
        arr = np.asarray(jax.device_get(arr))
    np.asarray(arr).tofile(path)
