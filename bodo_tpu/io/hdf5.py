"""HDF5 IO (reference: bodo/io/_hdf5.cpp + h5_api.py).

Datasets map to Table columns; a multi-host launch reads contiguous row
stripes per process (h5py slicing replaces the reference's parallel-HDF5
MPI driver — the TPU runtime's IO parallelism is per-process striping,
not MPI-IO)."""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np

from bodo_tpu.table.table import Column, Table


def read_hdf5(path: str, keys: Optional[Sequence[str]] = None,
              process_index: Optional[int] = None,
              process_count: Optional[int] = None) -> Table:
    """Read 1-D datasets (same length) from an HDF5 file into a Table."""
    import h5py

    import jax
    pi = process_index if process_index is not None else jax.process_index()
    pc = process_count if process_count is not None else jax.process_count()
    from bodo_tpu.io import stripe
    with h5py.File(path, "r") as f:
        names = list(keys) if keys else \
            [k for k in f.keys()
             if isinstance(f[k], h5py.Dataset) and f[k].ndim == 1]
        if not names:
            raise ValueError(f"no 1-D datasets in {path}")
        n = f[names[0]].shape[0]
        lo, hi = stripe(n, pi, pc)
        cols: Dict[str, Column] = {}
        for k in names:
            ds = f[k]
            if ds.shape[0] != n:
                raise ValueError(f"dataset {k} length {ds.shape[0]} != {n}")
            arr = np.asarray(ds[lo:hi])
            if arr.dtype.kind == "S":  # bytes → str
                arr = arr.astype(str)
            logical = ds.attrs.get("bodo_tpu_dtype")
            if logical is not None:
                arr = arr.view(np.dtype(logical))
            cols[k] = Column.from_numpy(arr)
    return Table(cols, hi - lo, "REP", None)


def write_hdf5(t: Table, path: str) -> None:
    """Write a Table's columns as HDF5 datasets (gathers 1D tables —
    HDF5 has no safe concurrent single-file writers without MPI-IO)."""
    import h5py
    t = t.gather() if t.distribution == "1D" else t
    df = t.to_pandas()
    with h5py.File(path, "w") as f:
        for c in df.columns:
            arr = df[c].to_numpy()
            logical = None
            if arr.dtype == object or str(arr.dtype).startswith("str"):
                arr = np.asarray(arr, dtype="S")
            elif arr.dtype.kind in ("M", "m"):
                logical = str(arr.dtype)  # restore on read
                arr = arr.view(np.int64)
            ds = f.create_dataset(str(c), data=arr)
            if logical is not None:
                ds.attrs["bodo_tpu_dtype"] = logical
