"""Minimal pure-Python Avro object-container codec.

Iceberg's manifest lists and manifest files are Avro container files
(reference reads them through pyiceberg/fastavro —
bodo/io/iceberg/read_metadata.py); neither package exists in this
environment, so this module implements the small, stable subset of the
Avro 1.x spec those files use: zigzag-varint primitives, records,
arrays, maps, unions, enums, fixed, null/deflate codecs. The DECODER is
schema-driven from the schema embedded in each file, so real Iceberg
metadata written by other engines parses fully; the ENCODER writes the
schemas this engine emits.
"""

from __future__ import annotations

import io
import json
import os
import struct
import zlib
from typing import Any, Dict, Iterator, List, Optional, Tuple

MAGIC = b"Obj\x01"


# ---------------------------------------------------------------------------
# primitive codecs
# ---------------------------------------------------------------------------

def _read_long(buf: io.BytesIO) -> int:
    shift = 0
    acc = 0
    while True:
        b = buf.read(1)
        if not b:
            raise EOFError("truncated varint")
        v = b[0]
        acc |= (v & 0x7F) << shift
        if not (v & 0x80):
            break
        shift += 7
    return (acc >> 1) ^ -(acc & 1)  # zigzag


def _write_long(out: io.BytesIO, n: int) -> None:
    n = (n << 1) ^ (n >> 63) if n < 0 else (n << 1)
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.write(bytes([b | 0x80]))
        else:
            out.write(bytes([b]))
            return


def _read_bytes(buf: io.BytesIO) -> bytes:
    n = _read_long(buf)
    return buf.read(n)


def _write_bytes(out: io.BytesIO, b: bytes) -> None:
    _write_long(out, len(b))
    out.write(b)


# ---------------------------------------------------------------------------
# schema-driven value codec
# ---------------------------------------------------------------------------

def _decode(schema, buf: io.BytesIO, names: Dict[str, Any]):
    if isinstance(schema, list):  # union
        idx = _read_long(buf)
        return _decode(schema[idx], buf, names)
    if isinstance(schema, dict):
        t = schema["type"]
        if t == "record":
            if schema.get("name"):
                names[schema["name"]] = schema
            out = {}
            for f in schema["fields"]:
                out[f["name"]] = _decode(f["type"], buf, names)
            return out
        if t == "array":
            items = []
            while True:
                n = _read_long(buf)
                if n == 0:
                    break
                if n < 0:
                    _read_long(buf)  # block byte size (skippable form)
                    n = -n
                for _ in range(n):
                    items.append(_decode(schema["items"], buf, names))
            return items
        if t == "map":
            out = {}
            while True:
                n = _read_long(buf)
                if n == 0:
                    break
                if n < 0:
                    _read_long(buf)
                    n = -n
                for _ in range(n):
                    k = _read_bytes(buf).decode()
                    out[k] = _decode(schema["values"], buf, names)
            return out
        if t == "enum":
            if schema.get("name"):
                names[schema["name"]] = schema
            return schema["symbols"][_read_long(buf)]
        if t == "fixed":
            if schema.get("name"):
                names[schema["name"]] = schema
            return buf.read(schema["size"])
        return _decode(t, buf, names)  # {"type": "string"} wrapper / alias
    if schema in names:
        return _decode(names[schema], buf, names)
    if schema == "null":
        return None
    if schema == "boolean":
        return buf.read(1) != b"\x00"
    if schema in ("int", "long"):
        return _read_long(buf)
    if schema == "float":
        return struct.unpack("<f", buf.read(4))[0]
    if schema == "double":
        return struct.unpack("<d", buf.read(8))[0]
    if schema == "bytes":
        return _read_bytes(buf)
    if schema == "string":
        return _read_bytes(buf).decode()
    raise ValueError(f"unsupported avro schema: {schema!r}")


def _encode(schema, value, out: io.BytesIO, names: Dict[str, Any]) -> None:
    if isinstance(schema, list):  # union: first matching branch
        for i, br in enumerate(schema):
            if _matches(br, value, names):
                _write_long(out, i)
                _encode(br, value, out, names)
                return
        raise TypeError(f"value {value!r} matches no union branch "
                        f"{schema!r}")
    if isinstance(schema, dict):
        t = schema["type"]
        if t == "record":
            if schema.get("name"):
                names[schema["name"]] = schema
            for f in schema["fields"]:
                _encode(f["type"], value.get(f["name"]), out, names)
            return
        if t == "array":
            if value:
                _write_long(out, len(value))
                for v in value:
                    _encode(schema["items"], v, out, names)
            _write_long(out, 0)
            return
        if t == "map":
            if value:
                _write_long(out, len(value))
                for k, v in value.items():
                    _write_bytes(out, k.encode())
                    _encode(schema["values"], v, out, names)
            _write_long(out, 0)
            return
        if t == "enum":
            _write_long(out, schema["symbols"].index(value))
            return
        if t == "fixed":
            out.write(value)
            return
        _encode(t, value, out, names)
        return
    if schema in names:
        _encode(names[schema], value, out, names)
        return
    if schema == "null":
        return
    if schema == "boolean":
        out.write(b"\x01" if value else b"\x00")
        return
    if schema in ("int", "long"):
        _write_long(out, int(value))
        return
    if schema == "float":
        out.write(struct.pack("<f", float(value)))
        return
    if schema == "double":
        out.write(struct.pack("<d", float(value)))
        return
    if schema == "bytes":
        _write_bytes(out, bytes(value))
        return
    if schema == "string":
        _write_bytes(out, str(value).encode())
        return
    raise ValueError(f"unsupported avro schema: {schema!r}")


def _matches(schema, value, names) -> bool:
    if isinstance(schema, dict):
        t = schema["type"]
        if t == "record":
            return isinstance(value, dict)
        if t == "array":
            return isinstance(value, list)
        if t == "map":
            return isinstance(value, dict)
        if t in ("enum",):
            return isinstance(value, str)
        if t == "fixed":
            return isinstance(value, (bytes, bytearray))
        return _matches(t, value, names)
    if schema in names:
        return _matches(names[schema], value, names)
    if schema == "null":
        return value is None
    if schema == "boolean":
        return isinstance(value, bool)
    if schema in ("int", "long"):
        return isinstance(value, int) and not isinstance(value, bool)
    if schema in ("float", "double"):
        return isinstance(value, (int, float)) and \
            not isinstance(value, bool)
    if schema == "bytes":
        return isinstance(value, (bytes, bytearray))
    if schema == "string":
        return isinstance(value, str)
    return False


# ---------------------------------------------------------------------------
# container file read / write
# ---------------------------------------------------------------------------

def read_avro(path: str) -> Tuple[Dict[str, Any], List[Any]]:
    """Read an Avro container file → (parsed schema, records)."""
    with open(path, "rb") as f:
        data = f.read()
    buf = io.BytesIO(data)
    if buf.read(4) != MAGIC:
        raise ValueError(f"{path}: not an Avro container file")
    meta_schema = {"type": "map", "values": "bytes"}
    meta = _decode(meta_schema, buf, {})
    schema = json.loads(meta["avro.schema"].decode())
    codec = meta.get("avro.codec", b"null").decode()
    if codec not in ("null", "deflate"):
        raise ValueError(f"{path}: unsupported avro codec {codec}")
    sync = buf.read(16)
    records: List[Any] = []
    while buf.tell() < len(data):
        try:
            n = _read_long(buf)
        except EOFError:
            break
        blen = _read_long(buf)
        block = buf.read(blen)
        if codec == "deflate":
            block = zlib.decompress(block, -15)
        bbuf = io.BytesIO(block)
        names: Dict[str, Any] = {}
        for _ in range(n):
            records.append(_decode(schema, bbuf, names))
        if buf.read(16) != sync:
            raise ValueError(f"{path}: bad avro sync marker")
    return schema, records


def write_avro(path: str, schema: Dict[str, Any], records: List[Any],
               metadata: Optional[Dict[str, bytes]] = None) -> None:
    """Write an Avro container file (null codec)."""
    out = io.BytesIO()
    out.write(MAGIC)
    meta = {"avro.schema": json.dumps(schema).encode(),
            "avro.codec": b"null"}
    if metadata:
        meta.update(metadata)
    _encode({"type": "map", "values": "bytes"}, meta, out, {})
    sync = os.urandom(16)
    out.write(sync)
    if records:
        body = io.BytesIO()
        names: Dict[str, Any] = {}
        for r in records:
            _encode(schema, r, body, names)
        _write_long(out, len(records))
        _write_bytes(out, body.getvalue())
        out.write(sync)
    with open(path, "wb") as f:
        f.write(out.getvalue())
