"""JSON (lines) reader — analogue of the reference's JSON connector
(bodo/io/_csv_json_reader.cpp, bodo/ir/json_ext.py:32)."""

from __future__ import annotations

from typing import Optional, Sequence

import pyarrow.json as pajson

from bodo_tpu.io.arrow_bridge import arrow_to_table
from bodo_tpu.table.table import Table


def read_json(path: str, columns: Optional[Sequence[str]] = None) -> Table:
    at = pajson.read_json(path)
    if columns:
        at = at.select(list(columns))
    return arrow_to_table(at)
