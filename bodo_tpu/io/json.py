"""JSON (lines) reader — analogue of the reference's JSON connector
(bodo/io/_csv_json_reader.cpp, bodo/ir/json_ext.py:32).

Whole-file `read_json` plus a chunked byte-range reader for JSON-lines:
record boundaries are newlines, so the CSV reader's newline-aligned
byte-range scheme applies unchanged; the first chunk's inferred schema
is pinned as an explicit_schema for every later chunk so dtypes cannot
drift mid-file."""

from __future__ import annotations

from typing import Optional, Sequence

import pyarrow.json as pajson

from bodo_tpu.io.arrow_bridge import arrow_to_table
from bodo_tpu.runtime import resilience
from bodo_tpu.table.table import Table


def read_json(path: str, columns: Optional[Sequence[str]] = None) -> Table:
    at = resilience.retry_call(lambda: pajson.read_json(path),
                               label="read_json", point="io.read")
    if columns:
        at = at.select(list(columns))
    return arrow_to_table(at)


def iter_json_arrow(path: str, columns: Optional[Sequence[str]] = None,
                    chunk_bytes: Optional[int] = None):
    """Yield one arrow Table per newline-aligned byte-range chunk of a
    JSON-lines file (one record per line)."""
    import io as _io

    from bodo_tpu.io.csv import CHUNK_BYTES, _newline_bounds

    if chunk_bytes is None:
        chunk_bytes = CHUNK_BYTES
    # JSON-lines has no header row: the first line is data
    _hdr, bounds = _newline_bounds(path, chunk_bytes, split_header=False)
    schema = None
    with open(path, "rb") as f:
        for s, e in zip(bounds, bounds[1:]):
            def _parse_chunk(s=s, e=e):
                f.seek(s)
                buf = f.read(e - s)
                po = (pajson.ParseOptions(explicit_schema=schema)
                      if schema is not None else pajson.ParseOptions())
                return pajson.read_json(_io.BytesIO(buf), parse_options=po)
            at = resilience.retry_call(_parse_chunk, label="read_json_chunk",
                                       point="io.read")
            if schema is None:
                schema = at.schema
            if columns:
                at = at.select(list(columns))
            yield at


def read_json_chunked(path: str, chunksize: int,
                      columns: Optional[Sequence[str]] = None,
                      chunk_bytes: Optional[int] = None):
    """Iterator of pandas DataFrames of exactly `chunksize` rows from a
    JSON-lines file, parsed chunk-at-a-time with bounded host memory."""
    from bodo_tpu.io.csv import slice_arrow_batches

    for at in slice_arrow_batches(
            iter_json_arrow(path, columns, chunk_bytes), chunksize):
        yield at.to_pandas()
