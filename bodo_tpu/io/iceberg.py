"""Iceberg-lite: local filesystem-catalog Iceberg tables, pure Python.

The reference reads/writes Iceberg through pyiceberg + its C++ connector
(reference: bodo/io/iceberg/ — read_metadata.py snapshot/manifest
resolution, write.py append commits, stream_iceberg_write.py). Neither
pyiceberg nor network catalogs exist in this environment, so this module
implements the filesystem-catalog subset of the Iceberg v2 spec
directly:

  - metadata: `metadata/v<N>.metadata.json` + `version-hint.text`
  - snapshots: manifest LIST (Avro) → manifest files (Avro) → parquet
    data files, parsed with the schema-driven pure-Python Avro codec
    (io/avro.py) — real manifests written by other engines decode too
  - reads feed the existing parquet machinery (column pruning pushed
    into each file read); time-travel by snapshot id
  - writes: per-call parquet part + new manifest + new manifest list +
    new metadata version committed via an atomic version-hint update

Catalog URLs, REST/Glue/SQL catalogs and deletion vectors are out of
scope (zero-egress environment); MERGE INTO arrives with the SQL DML
layer.
"""

from __future__ import annotations

import json
import os
import time
import uuid
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from bodo_tpu.io.avro import read_avro, write_avro
from bodo_tpu.table import dtypes as dt
from bodo_tpu.table.table import Table

# ---------------------------------------------------------------------------
# metadata resolution
# ---------------------------------------------------------------------------


def _meta_dir(table_path: str) -> str:
    return os.path.join(table_path, "metadata")


def _current_metadata(table_path: str) -> Tuple[dict, int]:
    """Load the current table metadata json → (metadata, version)."""
    md = _meta_dir(table_path)
    hint = os.path.join(md, "version-hint.text")
    version = None
    if os.path.exists(hint):
        with open(hint) as f:
            version = int(f.read().strip())
    elif os.path.isdir(md):
        vs = [int(f[1:].split(".")[0]) for f in os.listdir(md)
              if f.startswith("v") and f.endswith(".metadata.json")]
        if vs:
            version = max(vs)
    if version is None:
        raise FileNotFoundError(f"no Iceberg metadata under {md}")
    with open(os.path.join(md, f"v{version}.metadata.json")) as f:
        return json.load(f), version


def _local_path(p: str, table_path: str) -> str:
    if p.startswith("file://"):
        return p[len("file://"):]
    if os.path.isabs(p):
        return p
    return os.path.join(table_path, p)


def _snapshot(meta: dict, snapshot_id: Optional[int]) -> dict:
    snaps = meta.get("snapshots", [])
    if not snaps:
        raise ValueError("Iceberg table has no snapshots")
    if snapshot_id is None:
        cur = meta.get("current-snapshot-id")
        for s in snaps:
            if s["snapshot-id"] == cur:
                return s
        return snaps[-1]
    for s in snaps:
        if s["snapshot-id"] == snapshot_id:
            return s
    raise ValueError(f"snapshot {snapshot_id} not found "
                     f"(have {[s['snapshot-id'] for s in snaps]})")


def _data_files(table_path: str, snap: dict) -> List[str]:
    """Resolve a snapshot to its live parquet data files."""
    mlist = _local_path(snap["manifest-list"], table_path)
    _, entries = read_avro(mlist)
    files: List[str] = []
    for e in entries:
        if e.get("content", 0) != 0:
            # delete manifests (position/equality deletes) would require
            # applying delete files — silently reading past them would
            # return deleted rows
            raise NotImplementedError(
                "Iceberg table has row-level deletes (content!=0 "
                "manifests), which this reader does not apply")
        mpath = _local_path(e["manifest_path"], table_path)
        _, m_entries = read_avro(mpath)
        for me in m_entries:
            if me.get("status") == 2:  # DELETED
                continue
            df = me["data_file"]
            if df.get("content", 0) != 0:
                raise NotImplementedError(
                    "Iceberg delete files are not supported")
            files.append(_local_path(df["file_path"], table_path))
    return files


# ---------------------------------------------------------------------------
# read
# ---------------------------------------------------------------------------

def read_iceberg(table_path: str, columns: Optional[Sequence[str]] = None,
                 snapshot_id: Optional[int] = None) -> Table:
    """Read a local-warehouse Iceberg table (optionally at a historical
    snapshot) into a Table via the parquet stack."""
    meta, _ = _current_metadata(table_path)
    snap = _snapshot(meta, snapshot_id)
    files = _data_files(table_path, snap)
    if not files:
        raise ValueError("snapshot has no data files")
    # the resolved file list feeds the parquet stack directly (row-group
    # striping across processes, remote schemes, column pruning)
    from bodo_tpu.io.parquet import read_parquet
    return read_parquet(files, columns=columns)


def snapshots(table_path: str) -> List[dict]:
    """Snapshot history: [{snapshot-id, timestamp-ms, operation}]."""
    meta, _ = _current_metadata(table_path)
    return [{"snapshot-id": s["snapshot-id"],
             "timestamp-ms": s["timestamp-ms"],
             "operation": s.get("summary", {}).get("operation", "?")}
            for s in meta.get("snapshots", [])]


# ---------------------------------------------------------------------------
# write
# ---------------------------------------------------------------------------

_ICEBERG_TYPES = {"i": "long", "u": "long", "f": "double", "b": "boolean",
                  "M": "timestamptz", "m": "long"}


def _iceberg_schema(t: Table) -> dict:
    fields = []
    for i, (name, c) in enumerate(t.columns.items(), start=1):
        if c.dtype is dt.STRING:
            ty = "string"
        elif c.dtype.kind == "dec":
            prec = getattr(c.dtype, "precision", 18) or 18
            ty = f"decimal({prec}, {c.dtype.scale})"
        elif c.dtype is dt.DATETIME:
            ty = "timestamp"
        else:
            ty = _ICEBERG_TYPES.get(c.dtype.kind, "string")
        fields.append({"id": i, "name": name, "required": False,
                       "type": ty})
    return {"type": "struct", "schema-id": 0, "fields": fields}


_MANIFEST_SCHEMA = {
    "type": "record", "name": "manifest_entry", "fields": [
        {"name": "status", "type": "int"},
        {"name": "snapshot_id", "type": ["null", "long"], "default": None},
        {"name": "sequence_number", "type": ["null", "long"],
         "default": None},
        {"name": "data_file", "type": {
            "type": "record", "name": "r2", "fields": [
                {"name": "content", "type": "int"},
                {"name": "file_path", "type": "string"},
                {"name": "file_format", "type": "string"},
                {"name": "record_count", "type": "long"},
                {"name": "file_size_in_bytes", "type": "long"},
            ]}},
    ]}

_MANIFEST_LIST_SCHEMA = {
    "type": "record", "name": "manifest_file", "fields": [
        {"name": "manifest_path", "type": "string"},
        {"name": "manifest_length", "type": "long"},
        {"name": "partition_spec_id", "type": "int"},
        {"name": "content", "type": "int"},
        {"name": "sequence_number", "type": "long"},
        {"name": "min_sequence_number", "type": "long"},
        {"name": "added_snapshot_id", "type": "long"},
        {"name": "added_files_count", "type": "int"},
        {"name": "existing_files_count", "type": "int"},
        {"name": "deleted_files_count", "type": "int"},
        {"name": "added_rows_count", "type": "long"},
        {"name": "existing_rows_count", "type": "long"},
        {"name": "deleted_rows_count", "type": "long"},
    ]}


def write_iceberg(t: Table, table_path: str, mode: str = "append") -> int:
    """Create or append to a local-warehouse Iceberg table; returns the
    new snapshot id. Commit = write data + manifests + metadata vN+1,
    then flip version-hint (the filesystem-catalog commit protocol)."""
    assert mode in ("create", "append", "overwrite"), mode
    from bodo_tpu.io.parquet import write_parquet

    md = _meta_dir(table_path)
    data_dir = os.path.join(table_path, "data")
    os.makedirs(md, exist_ok=True)
    os.makedirs(data_dir, exist_ok=True)

    existing_meta: Optional[dict] = None
    version = 0
    if mode != "create":
        try:
            existing_meta, version = _current_metadata(table_path)
        except FileNotFoundError:
            existing_meta = None  # append to nothing = create
    elif os.path.exists(os.path.join(md, "version-hint.text")):
        raise FileExistsError(
            f"Iceberg table already exists at {table_path} "
            f"(use mode='append' or 'overwrite')")

    snap_id = int(time.time() * 1000) * 1000 + int(np.random.randint(1000))
    seq = (existing_meta.get("last-sequence-number", 0) + 1
           if existing_meta else 1)
    # manifests/metadata store ABSOLUTE paths (as real Iceberg writers
    # do) so reads resolve regardless of the caller's cwd-relative path
    part = os.path.abspath(os.path.join(
        data_dir, f"part-{uuid.uuid4().hex[:12]}.parquet"))
    gathered = t.gather() if t.distribution == "1D" else t
    write_parquet(gathered, part)
    fsize = os.path.getsize(part)

    # manifest for the new data file
    mpath = os.path.abspath(
        os.path.join(md, f"{uuid.uuid4().hex[:12]}-m0.avro"))
    write_avro(mpath, _MANIFEST_SCHEMA, [{
        "status": 1, "snapshot_id": snap_id, "sequence_number": seq,
        "data_file": {"content": 0, "file_path": part,
                      "file_format": "PARQUET",
                      "record_count": int(t.nrows),
                      "file_size_in_bytes": int(fsize)}}])

    # manifest list: prior manifests (append) + the new one
    entries: List[dict] = []
    if mode == "append" and existing_meta is not None and \
            existing_meta.get("current-snapshot-id") is not None:
        prev = _snapshot(existing_meta, None)
        _, prev_entries = read_avro(
            _local_path(prev["manifest-list"], table_path))
        for e in prev_entries:
            # optional fields from other engines may decode to None —
            # coerce to this writer's non-null schema
            row = {}
            for f in _MANIFEST_LIST_SCHEMA["fields"]:
                v = e.get(f["name"])
                if v is None:
                    v = "" if f["type"] == "string" else 0
                row[f["name"]] = v
            entries.append(row)
    entries.append({
        "manifest_path": mpath, "manifest_length": os.path.getsize(mpath),
        "partition_spec_id": 0, "content": 0, "sequence_number": seq,
        "min_sequence_number": seq, "added_snapshot_id": snap_id,
        "added_files_count": 1, "existing_files_count": 0,
        "deleted_files_count": 0, "added_rows_count": int(t.nrows),
        "existing_rows_count": 0, "deleted_rows_count": 0})
    mlist = os.path.abspath(os.path.join(
        md, f"snap-{snap_id}-1-{uuid.uuid4().hex[:12]}.avro"))
    write_avro(mlist, _MANIFEST_LIST_SCHEMA, entries)

    now_ms = int(time.time() * 1000)
    new_snap = {"snapshot-id": snap_id, "sequence-number": seq,
                "timestamp-ms": now_ms, "manifest-list": mlist,
                "schema-id": 0,
                "summary": {"operation":
                            "append" if entries[:-1] else "overwrite"}}
    if existing_meta is not None and mode != "overwrite":
        meta = dict(existing_meta)
        meta["snapshots"] = list(meta.get("snapshots", [])) + [new_snap]
    else:
        meta = {"format-version": 2,
                "table-uuid": str(uuid.uuid4()),
                "location": os.path.abspath(table_path),
                "last-column-id": len(t.columns),
                "schemas": [_iceberg_schema(t)],
                "current-schema-id": 0,
                "partition-specs": [{"spec-id": 0, "fields": []}],
                "default-spec-id": 0,
                "snapshots": [new_snap],
                "snapshot-log": []}
    meta["current-snapshot-id"] = snap_id
    meta["last-sequence-number"] = seq
    meta["last-updated-ms"] = now_ms
    meta.setdefault("snapshot-log", []).append(
        {"snapshot-id": snap_id, "timestamp-ms": now_ms})

    new_version = version + 1
    vpath = os.path.join(md, f"v{new_version}.metadata.json")
    with open(vpath, "w") as f:
        json.dump(meta, f, indent=1)
    hint_tmp = os.path.join(md, f".hint.{os.getpid()}")
    with open(hint_tmp, "w") as f:
        f.write(str(new_version))
    os.replace(hint_tmp, os.path.join(md, "version-hint.text"))
    return snap_id
