"""Iceberg connector (gated).

The reference reads/writes Iceberg tables through its connector +
pyiceberg catalogs (bodo/io/iceberg/ — 18 files). The design here is the
same split the parquet path already implements:

  1. catalog/metadata on host (pyiceberg): resolve the snapshot, collect
     data-file paths + delete files, push column pruning and partition/
     metrics filters into the scan plan,
  2. the data files are parquet — they feed the existing
     `io.parquet.read_parquet` / `plan.streaming.parquet_batches`
     machinery unchanged (row-group striping per process, batched
     streaming reads),
  3. writes go through `write_parquet`'s per-shard part files plus a
     pyiceberg append commit.

pyiceberg is not present in this environment, so the module gates with a
clear error instead of shipping an untestable implementation.
"""

from __future__ import annotations


def _require_pyiceberg():
    try:
        import pyiceberg  # noqa: F401
    except ImportError as e:  # pragma: no cover
        raise ImportError(
            "Iceberg support needs the optional 'pyiceberg' package; "
            "install it to read/write Iceberg tables "
            "(design: bodo_tpu/io/iceberg.py docstring)") from e


def read_iceberg(table_identifier: str, catalog: str = "default",
                 columns=None, snapshot_id=None):
    """Read an Iceberg table into a Table (gated on pyiceberg)."""
    _require_pyiceberg()
    raise NotImplementedError(
        "Iceberg read: catalog resolution is designed but not wired "
        "(see module docstring for the planned split)")  # pragma: no cover


def write_iceberg(t, table_identifier: str, catalog: str = "default",
                  mode: str = "append"):
    """Append/overwrite a Table into an Iceberg table (gated)."""
    _require_pyiceberg()
    raise NotImplementedError(
        "Iceberg write: parquet part files + append commit is designed "
        "but not wired (see module docstring)")  # pragma: no cover
