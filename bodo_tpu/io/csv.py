"""CSV reader (Arrow-based).

Analogue of the reference's chunked parallel CSV reader
(bodo/io/_csv_json_reader.cpp, bodo/ir/csv_ext.py:49). pyarrow's
multithreaded C++ parser does the heavy lifting on host; parse_dates
mirrors the pandas read_csv option used by the benchmarks.
"""

from __future__ import annotations

from typing import Optional, Sequence

import pyarrow as pa
import pyarrow.csv as pacsv

from bodo_tpu.io.arrow_bridge import arrow_to_table
from bodo_tpu.table.table import Table


def read_csv(path: str, columns: Optional[Sequence[str]] = None,
             parse_dates: Optional[Sequence[str]] = None) -> Table:
    convert = {}
    if parse_dates:
        convert = {c: pa.timestamp("ns") for c in parse_dates}
    at = pacsv.read_csv(
        path,
        convert_options=pacsv.ConvertOptions(
            column_types=convert,
            include_columns=list(columns) if columns else None,
        ),
    )
    return arrow_to_table(at)
