"""CSV reader (Arrow-based).

Analogue of the reference's chunked parallel CSV reader
(bodo/io/_csv_json_reader.cpp, bodo/ir/csv_ext.py:49). pyarrow's
multithreaded C++ parser does the heavy lifting on host; parse_dates
mirrors the pandas read_csv option used by the benchmarks.
"""

from __future__ import annotations

from typing import Optional, Sequence

import pyarrow as pa
import pyarrow.csv as pacsv

from bodo_tpu.io.arrow_bridge import arrow_to_table
from bodo_tpu.runtime import resilience
from bodo_tpu.table.table import Table


from bodo_tpu.utils.tracing import traced_table_op as _traced


@_traced
def read_csv(path: str, columns: Optional[Sequence[str]] = None,
             parse_dates: Optional[Sequence[str]] = None) -> Table:
    convert = {}
    if parse_dates:
        convert = {c: pa.timestamp("ns") for c in parse_dates}
    at = resilience.retry_call(
        lambda: pacsv.read_csv(
            path,
            convert_options=pacsv.ConvertOptions(
                column_types=convert,
                include_columns=list(columns) if columns else None,
            ),
        ),
        label="read_csv", point="io.read")
    t = arrow_to_table(at)
    _attach_host_ranges(t, at)
    return t


def _attach_host_ranges(t: Table, at: pa.Table) -> None:
    """Column.vrange from one arrow min/max pass at ingest (CSV has no
    footer statistics; a host pass here spares the dense-path planners a
    device reduce + sync later — on the TPU tunnel every sync is a full
    round-trip)."""
    import pyarrow.compute as pc

    from bodo_tpu.table import dtypes as dt
    for name, col in t.columns.items():
        if col.dtype.kind not in ("i", "u", "dt", "date"):
            continue
        arr = at.column(name)
        try:
            mm = pc.min_max(arr)
            lo, hi = mm["min"].as_py(), mm["max"].as_py()
        except Exception:
            continue
        if lo is None or hi is None:
            continue
        import datetime as _dtm

        import numpy as np
        if isinstance(lo, _dtm.datetime):
            lo = int(np.datetime64(lo, "ns").astype(np.int64))
            hi = int(np.datetime64(hi, "ns").astype(np.int64))
        elif isinstance(lo, _dtm.date):
            lo = int(np.datetime64(lo, "D").astype(np.int64))
            hi = int(np.datetime64(hi, "D").astype(np.int64))
        elif not isinstance(lo, (int, np.integer)):
            continue
        col.vrange = (int(lo), int(hi), True)


# ---------------------------------------------------------------------------
# chunked / parallel byte-range reader
# ---------------------------------------------------------------------------

# default byte-range chunk for the streaming reader
CHUNK_BYTES = 32 << 20


def _newline_bounds(path: str, chunk_bytes: int,
                    split_header: bool = True):
    """(header_bytes, offsets): byte-range chunk boundaries aligned to
    row starts by scanning forward to the next newline from each nominal
    split point — the reference's offset-search scheme
    (bodo/io/_csv_json_reader.cpp). Like the reference's scanner this
    assumes the row delimiter does not appear inside quoted fields.
    `split_header=False` (JSON-lines: the first line is data) returns
    header=b"" with bounds starting at byte 0."""
    import os
    size = os.path.getsize(path)
    with open(path, "rb") as f:
        if split_header:
            header = f.readline()
        else:
            header = b""
        start = f.tell()
        bounds = [start]
        pos = start + chunk_bytes
        while pos < size:
            f.seek(pos)
            f.readline()
            pos2 = f.tell()
            if pos2 >= size:
                break
            bounds.append(pos2)
            pos = pos2 + chunk_bytes
        bounds.append(size)
    return header, bounds


def iter_csv_arrow(path: str, columns: Optional[Sequence[str]] = None,
                   parse_dates: Optional[Sequence[str]] = None,
                   chunk_bytes: int = CHUNK_BYTES):
    """Yield one arrow Table per newline-aligned byte-range chunk.

    The first chunk parses synchronously and its inferred schema is
    pinned for every later chunk so dtypes cannot drift mid-file (a
    chunk whose values no longer parse under the pinned schema raises
    instead of silently widening). Remaining chunks parse on the shared
    I/O pool with ordered reassembly (runtime/io_pool.py) — output is
    identical to the serial parse; host memory stays bounded by the
    pool's in-flight window (~(threads+1) x chunk_bytes). Each task
    opens its own file handle, so no seek races across threads."""
    import io as _io

    header, bounds = _newline_bounds(path, chunk_bytes)
    column_types = {c: pa.timestamp("ns") for c in (parse_dates or [])}

    def parse_range(span, types):
        s, e = span

        def _once():
            with open(path, "rb") as f:
                f.seek(s)
                buf = f.read(e - s)
            return pacsv.read_csv(
                _io.BytesIO(header + buf),
                convert_options=pacsv.ConvertOptions(
                    column_types=dict(types),
                    include_columns=list(columns) if columns else None,
                ))
        return resilience.retry_call(_once, label="read_csv_chunk",
                                     point="io.read")

    spans = list(zip(bounds, bounds[1:]))
    if not spans:
        return
    first = parse_range(spans[0], column_types)
    for fld in first.schema:
        column_types.setdefault(fld.name, fld.type)
    yield first
    rest = spans[1:]
    if not rest:
        return
    from bodo_tpu.runtime import io_pool
    pinned = dict(column_types)
    if len(rest) > 1 and io_pool.io_thread_count() > 1:
        io_pool.count("parallel_reads")
        yield from io_pool.pool_map_ordered(
            lambda span: parse_range(span, pinned), rest)
    else:
        for span in rest:
            yield parse_range(span, pinned)


def slice_arrow_batches(src, chunksize: int):
    """Re-slice a stream of arrow Tables into exactly-`chunksize` arrow
    Tables (last may be short). Linear: the pending tail concatenates
    once per INPUT chunk, and all output slices cut from that one
    concatenation (not re-concatenated per yield)."""
    if chunksize < 1:
        raise ValueError(f"chunksize must be >= 1, got {chunksize}")
    pending = []
    pending_rows = 0
    for at in src:
        pending.append(at)
        pending_rows += at.num_rows
        if pending_rows < chunksize:
            continue
        whole = pa.concat_tables(pending)
        off = 0
        while pending_rows - off >= chunksize:
            yield whole.slice(off, chunksize)
            off += chunksize
        pending = [whole.slice(off)] if pending_rows > off else []
        pending_rows -= off
    if pending_rows:
        yield pa.concat_tables(pending)


def read_csv_chunked(path: str, chunksize: int,
                     columns: Optional[Sequence[str]] = None,
                     parse_dates: Optional[Sequence[str]] = None,
                     chunk_bytes: int = CHUNK_BYTES):
    """pandas read_csv(chunksize=N) analogue: an iterator of pandas
    DataFrames of exactly `chunksize` rows (last may be short), parsed
    chunk-at-a-time with bounded host memory (reference:
    bodo/io/csv_iterator_ext.py)."""
    for at in slice_arrow_batches(
            iter_csv_arrow(path, columns, parse_dates, chunk_bytes),
            chunksize):
        yield at.to_pandas()
