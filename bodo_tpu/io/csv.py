"""CSV reader (Arrow-based).

Analogue of the reference's chunked parallel CSV reader
(bodo/io/_csv_json_reader.cpp, bodo/ir/csv_ext.py:49). pyarrow's
multithreaded C++ parser does the heavy lifting on host; parse_dates
mirrors the pandas read_csv option used by the benchmarks.
"""

from __future__ import annotations

from typing import Optional, Sequence

import pyarrow as pa
import pyarrow.csv as pacsv

from bodo_tpu.io.arrow_bridge import arrow_to_table
from bodo_tpu.table.table import Table


from bodo_tpu.utils.tracing import traced_table_op as _traced


@_traced
def read_csv(path: str, columns: Optional[Sequence[str]] = None,
             parse_dates: Optional[Sequence[str]] = None) -> Table:
    convert = {}
    if parse_dates:
        convert = {c: pa.timestamp("ns") for c in parse_dates}
    at = pacsv.read_csv(
        path,
        convert_options=pacsv.ConvertOptions(
            column_types=convert,
            include_columns=list(columns) if columns else None,
        ),
    )
    t = arrow_to_table(at)
    _attach_host_ranges(t, at)
    return t


def _attach_host_ranges(t: Table, at: pa.Table) -> None:
    """Column.vrange from one arrow min/max pass at ingest (CSV has no
    footer statistics; a host pass here spares the dense-path planners a
    device reduce + sync later — on the TPU tunnel every sync is a full
    round-trip)."""
    import pyarrow.compute as pc

    from bodo_tpu.table import dtypes as dt
    for name, col in t.columns.items():
        if col.dtype.kind not in ("i", "u", "dt", "date"):
            continue
        arr = at.column(name)
        try:
            mm = pc.min_max(arr)
            lo, hi = mm["min"].as_py(), mm["max"].as_py()
        except Exception:
            continue
        if lo is None or hi is None:
            continue
        import datetime as _dtm

        import numpy as np
        if isinstance(lo, _dtm.datetime):
            lo = int(np.datetime64(lo, "ns").astype(np.int64))
            hi = int(np.datetime64(hi, "ns").astype(np.int64))
        elif isinstance(lo, _dtm.date):
            lo = int(np.datetime64(lo, "D").astype(np.int64))
            hi = int(np.datetime64(hi, "D").astype(np.int64))
        elif not isinstance(lo, (int, np.integer)):
            continue
        col.vrange = (int(lo), int(hi), True)
