"""Nested (list / struct / map) column encode & decode.

TPU-native strategy (vs the reference's offsets+child buffers,
bodo/libs/array_item_arr_ext.py:1, struct_arr_ext.py:1,
map_arr_ext.py:1): variable-length nested values never reach the
device. Each unique nested value lives in a host-side, sorted
dictionary; the device carries int32 codes — exactly the dict-encoded
string design, so filters, joins, sorts, and shuffles treat nested
columns as flat int32 data with no kernel changes. Accessor kernels
(list length, element get, struct field) become host-built LUTs gathered
on device.

The canonical host form of a value:
  list    -> tuple of scalars (None for null elements)
  struct  -> tuple of field values, field order fixed by the dtype
  map     -> tuple of (key, value) pairs
Tuples sort lexicographically, so code order == value order — sorting a
nested column by codes is deterministic (Python-comparable scalars
assumed within one column).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from bodo_tpu.table import dtypes as dt
from bodo_tpu.table.table import Column, round_capacity


def _scalar_dtype(values) -> dt.DType:
    """Infer the element dtype from a sample of scalars."""
    for v in values:
        if v is None:
            continue
        if isinstance(v, bool):
            return dt.BOOL
        if isinstance(v, (int, np.integer)):
            return dt.INT64
        if isinstance(v, (float, np.floating)):
            return dt.FLOAT64
        if isinstance(v, str):
            return dt.STRING
    return dt.FLOAT64


def _canon(v):
    """Canonical hashable form (tuples all the way down)."""
    if isinstance(v, dict):
        return tuple(sorted(v.items()))
    if isinstance(v, (list, tuple, np.ndarray)):
        return tuple(_canon(x) for x in v)
    if isinstance(v, np.generic):
        return v.item()
    return v


def _sort_key(v):
    # None sorts first; mixed numeric ok; strings with strings
    if isinstance(v, tuple):
        return (1, tuple(_sort_key(x) for x in v))
    if v is None:
        return (0, 0)
    if isinstance(v, str):
        return (1, v)
    return (1, float(v)) if isinstance(v, (int, float, bool)) else (1, str(v))


def encode_values(values, dtype: dt.DType,
                  capacity: Optional[int] = None) -> Column:
    """Encode an iterable of canonical nested values (or None) into a
    dict-encoded Column of the given nested dtype."""
    vals = [None if v is None else _canon(v) for v in values]
    n = len(vals)
    cap = capacity if capacity is not None else round_capacity(n)
    uniq = sorted({v for v in vals if v is not None}, key=_sort_key)
    index = {v: i for i, v in enumerate(uniq)}
    codes = np.zeros(n, dtype=np.int32)
    isna = np.zeros(n, dtype=bool)
    for i, v in enumerate(vals):
        if v is None:
            isna[i] = True
        else:
            codes[i] = index[v]
    dic = np.empty(len(uniq), dtype=object)
    for i, v in enumerate(uniq):
        dic[i] = v
    padded = np.zeros(cap, dtype=np.int32)
    padded[:n] = codes
    valid = None
    if isna.any():
        vm = np.zeros(cap, dtype=bool)
        vm[:n] = ~isna
        valid = jnp.asarray(vm)
    return Column(jnp.asarray(padded), valid, dtype, dic)


def infer_nested_dtype(values) -> Optional[dt.DType]:
    """Detect list/struct(dict)/map-shaped object values; None if flat."""
    sample = None
    for v in values:
        if v is None or (isinstance(v, float) and np.isnan(v)):
            continue
        sample = v
        break
    if sample is None:
        return None
    if isinstance(sample, dict):
        fields = [(k, _scalar_dtype([sample[k]])) for k in sample]
        return dt.struct_of(fields)
    if isinstance(sample, (list, tuple, np.ndarray)):
        elems = [x for v in values
                 if isinstance(v, (list, tuple, np.ndarray))
                 for x in v]
        return dt.list_of(_scalar_dtype(elems))
    return None


def decode_column(col: Column, nrows: int) -> np.ndarray:
    """Dict-decode a nested column back to host python objects (lists /
    dicts / list-of-pairs), None for nulls."""
    import jax
    codes = np.asarray(jax.device_get(col.data))[:nrows]
    valid = (np.asarray(jax.device_get(col.valid))[:nrows]
             if col.valid is not None else None)
    dic = col.dictionary
    out = np.empty(nrows, dtype=object)
    k = col.dtype.kind
    for i, c in enumerate(codes):
        if valid is not None and not valid[i]:
            out[i] = None
            continue
        v = dic[min(int(c), len(dic) - 1)] if len(dic) else None
        if k == "list":
            out[i] = list(v) if v is not None else None
        elif k == "struct":
            out[i] = ({n: fv for (n, _), fv
                       in zip(col.dtype.fields, v)}
                      if v is not None else None)
        else:  # map
            out[i] = list(v) if v is not None else None
    return out


# ---------------------------------------------------------------------------
# accessor LUT kernels (host dictionary -> device gather)
# ---------------------------------------------------------------------------

def list_lengths(col: Column) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
    """Per-row list length as int64 (null rows keep null)."""
    lut = jnp.asarray(np.array([len(v) for v in col.dictionary] or [0],
                               dtype=np.int64))
    codes = jnp.clip(col.data, 0, max(len(col.dictionary) - 1, 0))
    return lut[codes], col.valid


def list_get(col: Column, i: int) -> Column:
    """Element i of each list as a flat Column (null when absent)."""
    dic = col.dictionary
    elems = []
    ok = np.zeros(max(len(dic), 1), dtype=bool)
    for j, v in enumerate(dic):
        if -len(v) <= i < len(v) and v[i] is not None:
            elems.append(v[i])
            ok[j] = True
        else:
            elems.append(None)
    return _scalar_lut_column(col, elems, ok, col.dtype.elem)


def struct_field(col: Column, name: str) -> Column:
    """Field projection of a struct column."""
    names = [n for n, _ in col.dtype.fields]
    if name not in names:
        raise KeyError(name)
    fi = names.index(name)
    ft = dict(col.dtype.fields)[name]
    dic = col.dictionary
    vals = []
    ok = np.zeros(max(len(dic), 1), dtype=bool)
    for j, v in enumerate(dic):
        fv = v[fi]
        vals.append(fv)
        ok[j] = fv is not None
    return _scalar_lut_column(col, vals, ok, ft)


def map_get(col: Column, key) -> Column:
    """Value for `key` in each map (null when the key is absent)."""
    dic = col.dictionary
    vals = []
    ok = np.zeros(max(len(dic), 1), dtype=bool)
    for j, v in enumerate(dic):
        hit = None
        for kk, vv in v:
            if kk == key:
                hit = vv
                break
        vals.append(hit)
        ok[j] = hit is not None
    return _scalar_lut_column(col, vals, ok, col.dtype.value)


def _scalar_lut_column(col: Column, vals: List, ok: np.ndarray,
                       elem_dt: dt.DType) -> Column:
    """Build a flat Column by gathering a host value LUT through the
    nested column's codes; `ok[j]` marks dictionary entries with a
    present value."""
    codes = jnp.clip(col.data, 0, max(len(col.dictionary) - 1, 0))
    okv = jnp.asarray(ok)[codes]
    valid = okv if col.valid is None else (col.valid & okv)
    if elem_dt is dt.STRING:
        strs = np.array([v if isinstance(v, str) else "" for v in vals] or
                        [""], dtype=str)
        uniq, inv = np.unique(strs, return_inverse=True)
        lut = jnp.asarray(inv.astype(np.int32))
        return Column(lut[codes], valid, dt.STRING, uniq)
    np_vals = np.array([0 if v is None else v for v in vals] or [0],
                       dtype=elem_dt.numpy)
    lut = jnp.asarray(np_vals)
    return Column(lut[codes], valid, elem_dt, None)


# ---------------------------------------------------------------------------
# explode
# ---------------------------------------------------------------------------

def explode_table(t, col_name: str):
    """df.explode(col): replicate each row once per list element; empty
    and null lists produce one row with a null element (pandas
    semantics). Row replication is a device gather through host-built
    offset LUTs (reference analogue: bodo/libs/_lateral.cpp flatten).
    """
    import jax

    from bodo_tpu.table.table import REP, Table
    src = t.gather() if t.distribution != REP else t
    col = src.columns[col_name]
    if col.dtype.kind != "list":
        raise TypeError(f"explode expects a list column, got "
                        f"{col.dtype.name}")
    dic = col.dictionary
    codes = np.asarray(jax.device_get(col.data))[:src.nrows]
    codes = np.clip(codes, 0, max(len(dic) - 1, 0))
    valid = (np.asarray(jax.device_get(col.valid))[:src.nrows]
             if col.valid is not None else None)
    # per-row repeat counts (0-length and null lists still yield one row)
    lens = np.array([max(len(v), 1) for v in dic] or [1], dtype=np.int64)
    reps = lens[codes]
    if valid is not None:
        reps = np.where(valid, reps, 1)
    total = int(reps.sum())
    row_idx = np.repeat(np.arange(src.nrows), reps)
    within = np.arange(total) - np.repeat(
        np.cumsum(reps) - reps, reps)
    # element values for (code, within) pairs via a flattened LUT
    flat_vals: List = []
    offs = np.zeros(max(len(dic), 1) + 1, dtype=np.int64)
    for j, v in enumerate(dic):
        flat_vals.extend(v if len(v) else [None])
        offs[j + 1] = len(flat_vals)
    if not flat_vals:   # all-null column: empty dictionary
        flat_vals = [None]
        offs[1:] = 1
    elem_codes = offs[codes][row_idx] + within
    elems = [flat_vals[int(c)] for c in elem_codes]
    if valid is not None:
        bad = ~valid[row_idx]
        for i in np.nonzero(bad)[0]:
            elems[i] = None
    cap = round_capacity(max(total, 1))
    cols = {}
    for n, c in src.columns.items():
        if n == col_name:
            cols[n] = _elem_column(elems, c.dtype.elem, total, cap)
        else:
            cols[n] = _gather_column(c, row_idx, total, cap)
    return Table(cols, total, REP, None)


def _elem_column(elems: List, elem_dt: dt.DType, total: int,
                 cap: int) -> Column:
    """Column from a host list of scalar elements (None = null)."""
    isna = np.array([e is None for e in elems], dtype=bool)
    if elem_dt is dt.STRING:
        safe = np.array([e if isinstance(e, str) else ""
                         for e in elems], dtype=str)
        uniq, inv = (np.unique(safe, return_inverse=True)
                     if total else (np.array([], dtype=str),
                                    np.zeros(0, np.int64)))
        data = np.zeros(cap, np.int32)
        data[:total] = inv.astype(np.int32)
        vm = None
        if isna.any():
            vmn = np.zeros(cap, bool)
            vmn[:total] = ~isna
            vm = jnp.asarray(vmn)
        return Column(jnp.asarray(data), vm, dt.STRING, uniq)
    data = np.zeros(cap, elem_dt.numpy)
    data[:total] = [0 if e is None else e for e in elems]
    vm = None
    if isna.any():
        vmn = np.zeros(cap, bool)
        vmn[:total] = ~isna
        vm = jnp.asarray(vmn)
    return Column(jnp.asarray(data), vm, elem_dt, None)


def _gather_column(c: Column, row_idx: np.ndarray, total: int,
                   cap: int) -> Column:
    """Replicate a source column through the explode row gather."""
    gather = jnp.asarray(row_idx)
    data = c.data[gather]
    data = jnp.concatenate(
        [data, jnp.zeros((cap - total,), data.dtype)])
    vm = None
    if c.valid is not None:
        vmv = c.valid[gather]
        vm = jnp.concatenate(
            [vmv, jnp.zeros((cap - total,), bool)])
    return Column(data, vm, c.dtype, c.dictionary)


def flatten_table(t, col_name: str, value_name: str = "value",
                  index_name: str = "index", outer: bool = False):
    """LATERAL FLATTEN(input => col): one output row per array element,
    with VALUE and 0-based INDEX columns added and EVERY source column
    (including the array) replicated. Rows whose array is empty or null
    are DROPPED unless `outer`, which emits them once with null
    value/index (Snowflake FLATTEN semantics; reference:
    BodoSQL/bodosql/kernels/lateral.py lateral_flatten +
    bodo/libs/_lateral.cpp)."""
    import jax

    from bodo_tpu.table.table import REP, Table
    src = t.gather() if t.distribution != REP else t
    col = src.columns[col_name]
    if col.dtype.kind != "list":
        raise TypeError(f"FLATTEN expects a list column, got "
                        f"{col.dtype.name}")
    dic = col.dictionary
    codes = np.asarray(jax.device_get(col.data))[:src.nrows]
    codes = np.clip(codes, 0, max(len(dic) - 1, 0))
    valid = (np.asarray(jax.device_get(col.valid))[:src.nrows]
             if col.valid is not None else None)
    lens = np.array([len(v) for v in dic] or [0], dtype=np.int64)
    reps = lens[codes] if len(dic) else np.zeros(src.nrows, np.int64)
    if valid is not None:
        reps = np.where(valid, reps, 0)
    if outer:
        filler_src = reps == 0
        reps = np.maximum(reps, 1)
    total = int(reps.sum())
    row_idx = np.repeat(np.arange(src.nrows), reps)
    within = np.arange(total) - np.repeat(np.cumsum(reps) - reps, reps)
    # flattened per-dictionary-entry element LUT (empty lists get one
    # placeholder slot so offsets stay distinct)
    flat_vals: List = []
    offs = np.zeros(max(len(dic), 1) + 1, dtype=np.int64)
    for j, v in enumerate(dic):
        flat_vals.extend(v if len(v) else [None])
        offs[j + 1] = len(flat_vals)
    if not flat_vals:
        flat_vals = [None]
        offs[1:] = 1
    elem_codes = offs[codes][row_idx] + within
    elems = [flat_vals[int(c_)] for c_ in
             np.clip(elem_codes, 0, len(flat_vals) - 1)]
    filler = (filler_src[row_idx] if outer
              else np.zeros(total, dtype=bool))
    for i in np.nonzero(filler)[0]:
        elems[i] = None
    cap = round_capacity(max(total, 1))
    cols = {}
    for n, c in src.columns.items():
        cols[n] = _gather_column(c, row_idx, total, cap)
    cols[value_name] = _elem_column(elems, col.dtype.elem, total, cap)
    idx = np.zeros(cap, np.int64)
    idx[:total] = np.where(filler, 0, within)
    ivm = None
    if filler.any():
        ivmn = np.zeros(cap, bool)
        ivmn[:total] = ~filler
        ivm = jnp.asarray(ivmn)
    cols[index_name] = Column(jnp.asarray(idx), ivm, dt.INT64, None)
    return Table(cols, total, REP, None)
