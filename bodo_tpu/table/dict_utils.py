"""Dictionary (string) column utilities: unification and re-encoding.

The reference unifies dictionary-encoded string columns in its C++
dict-builder (bodo/libs/_dict_builder.cpp, streaming/dict_encoding.py) so
codes are comparable across tables (joins, concat). Here dictionaries are
host-side sorted numpy string arrays; unification is a host `np.union1d`
plus a device gather remap of the int32 codes (order-preserving since
dictionaries stay sorted).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from bodo_tpu.table.table import Column


# memoize unions by input-dictionary identity: kernel caches fingerprint
# dictionaries by object id, so the same union computed per streaming batch
# must return the SAME array object every time, or every batch misses the
# jit cache (same join executed once per batch)
_union_cache: dict = {}
_UNION_CACHE_MAX = 512


def _cached_union(dicts: List[np.ndarray]) -> np.ndarray:
    key = tuple(id(d) for d in dicts)
    hit = _union_cache.get(key)
    if hit is not None and all(a is b for a, b in zip(hit[0], dicts)):
        return hit[1]
    union = dicts[0]
    for d in dicts[1:]:
        union = np.union1d(union, d)
    # prefer an existing object when the union adds nothing
    for d in dicts:
        if len(d) == len(union) and np.array_equal(d, union):
            union = d
            break
    if len(_union_cache) >= _UNION_CACHE_MAX:
        _union_cache.pop(next(iter(_union_cache)))
    _union_cache[key] = (list(dicts), union)  # hold refs so ids stay valid
    return union


def unify_dictionaries(cols: Sequence[Column]) -> Tuple[np.ndarray, List[Column]]:
    """Re-encode string columns onto a shared sorted dictionary.

    Returns (union_dictionary, new columns with remapped codes)."""
    dicts = [c.dictionary if c.dictionary is not None
             else np.array([], dtype=str) for c in cols]
    union = _cached_union(dicts) if len(dicts) > 1 else dicts[0]
    out = []
    for c, d in zip(cols, dicts):
        if len(d) == len(union) and (len(d) == 0 or np.array_equal(d, union)):
            out.append(Column(c.data, c.valid, c.dtype, union))
            continue
        mapping = np.searchsorted(union, d).astype(np.int32)
        mp = jnp.asarray(mapping if len(mapping) else np.zeros(1, np.int32))
        new_codes = mp[jnp.clip(c.data, 0, max(len(d) - 1, 0))]
        out.append(Column(new_codes, c.valid, c.dtype, union))
    return union, out
