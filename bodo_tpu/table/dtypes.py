"""Logical dtype system for device-resident columns.

TPU-native analogue of the reference's `Bodo_CTypes::CTypeEnum` +
`bodo_array_type` (reference: bodo/libs/_bodo_common.h:341, :524). Every
logical type maps to a TPU-friendly physical numpy dtype:

  - integers/floats/bool map directly,
  - strings are dictionary-encoded: int32 codes on device, the dictionary
    (unique strings, lexicographically sorted so code order == string order)
    stays on host (the reference leans on the same trick:
    bodo/libs/_dict_builder.cpp, bodo/libs/dict_arr_ext.py),
  - datetime64[ns]/timedelta64[ns] are int64 ticks, dates are int32 days.

Nullability is carried by a separate validity mask (Arrow-style), matching
the reference's nullable arrays (bodo/libs/int_arr_ext.py etc.).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np


@dataclass(frozen=True)
class DType:
    name: str          # logical name
    np_dtype: str      # physical device representation
    kind: str          # 'i', 'u', 'f', 'b', 'str', 'dt', 'td', 'date'

    @property
    def numpy(self) -> np.dtype:
        return np.dtype(self.np_dtype)

    def __repr__(self) -> str:  # pragma: no cover
        return f"DType({self.name})"


INT8 = DType("int8", "int8", "i")
INT16 = DType("int16", "int16", "i")
INT32 = DType("int32", "int32", "i")
INT64 = DType("int64", "int64", "i")
UINT8 = DType("uint8", "uint8", "u")
UINT16 = DType("uint16", "uint16", "u")
UINT32 = DType("uint32", "uint32", "u")
UINT64 = DType("uint64", "uint64", "u")
FLOAT32 = DType("float32", "float32", "f")
FLOAT64 = DType("float64", "float64", "f")
BOOL = DType("bool", "bool", "b")
STRING = DType("string", "int32", "str")          # dict codes
DATETIME = DType("datetime64[ns]", "int64", "dt")  # ns ticks
TIMEDELTA = DType("timedelta64[ns]", "int64", "td")
DATE = DType("date", "int32", "date")              # days since epoch

_BY_NAME = {
    t.name: t
    for t in (INT8, INT16, INT32, INT64, UINT8, UINT16, UINT32, UINT64,
              FLOAT32, FLOAT64, BOOL, STRING, DATETIME, TIMEDELTA, DATE)
}


@dataclass(frozen=True)
class DecimalDType(DType):
    """Fixed-point decimal as a scaled int64 (value = physical / 10^scale)
    — the SURVEY §2.9 plan replacing the reference's decimal128 runtime
    (bodo/libs/_decimal_ext.cpp). Exact for +,-,*,sum,min,max,compare
    within int64 range; division and float mixing promote to float64.
    `precision` carries the source schema's decimal128 precision so a
    parquet round-trip preserves the column type; engine-created decimals
    default to the full 18 digits an int64 can hold."""
    scale: int = 2
    precision: int = 18


_DECIMALS: dict = {}


def decimal(scale: int, *, precision: int = 18) -> DecimalDType:
    """Interned decimal dtype of the given scale (identity-stable so
    kernel caches keyed on dtype objects stay warm). `precision` is
    keyword-only: positionally it would read as arrow's
    decimal128(precision, scale) order and silently swap the two."""
    t = _DECIMALS.get((scale, precision))
    if t is None:
        name = (f"decimal({scale})" if precision == 18
                else f"decimal({precision},{scale})")
        t = DecimalDType(name, "int64", "dec", scale, precision)
        _DECIMALS[(scale, precision)] = t
        _BY_NAME[t.name] = t
    return t


def is_decimal(t: DType) -> bool:
    return t.kind == "dec"


@dataclass(frozen=True)
class ListDType(DType):
    """Variable-length list column. Physical device repr is an int32
    code into a host-side dictionary of unique list values (tuples),
    sorted — the same dict-encoding strategy as strings, so every
    row-reshaping kernel (filter/join/sort/shuffle) handles list columns
    unchanged. Reference: bodo/libs/array_item_arr_ext.py (offsets+child
    repr; here variable-length data stays host-side by design)."""
    elem: DType = None


@dataclass(frozen=True)
class StructDType(DType):
    """Struct column: int32 codes into a host dictionary of unique
    field-value tuples. Reference: bodo/libs/struct_arr_ext.py."""
    fields: tuple = ()          # ((name, DType), ...)


@dataclass(frozen=True)
class MapDType(DType):
    """Map column = list<struct<key, value>> encoded the same way.
    Reference: bodo/libs/map_arr_ext.py."""
    key: DType = None
    value: DType = None


_NESTED: dict = {}


def list_of(elem: DType) -> ListDType:
    t = _NESTED.get(("list", elem.name))
    if t is None:
        t = ListDType(f"list<{elem.name}>", "int32", "list", elem)
        _NESTED[("list", elem.name)] = t
        _BY_NAME[t.name] = t
    return t


def struct_of(fields) -> StructDType:
    fields = tuple((n, t) for n, t in fields)
    key = ("struct", tuple((n, t.name) for n, t in fields))
    t = _NESTED.get(key)
    if t is None:
        inner = ", ".join(f"{n}: {ft.name}" for n, ft in fields)
        t = StructDType(f"struct<{inner}>", "int32", "struct", fields)
        _NESTED[key] = t
        _BY_NAME[t.name] = t
    return t


def map_of(key_t: DType, val_t: DType) -> MapDType:
    key = ("map", key_t.name, val_t.name)
    t = _NESTED.get(key)
    if t is None:
        t = MapDType(f"map<{key_t.name}, {val_t.name}>", "int32", "map",
                     key_t, val_t)
        _NESTED[key] = t
        _BY_NAME[t.name] = t
    return t


def is_nested(t: DType) -> bool:
    return t.kind in ("list", "struct", "map")


def by_name(name: str) -> DType:
    return _BY_NAME[name]


def from_numpy(dt: np.dtype) -> DType:
    dt = np.dtype(dt)
    if dt.kind == "M":
        return DATETIME
    if dt.kind == "m":
        return TIMEDELTA
    if dt.kind in ("U", "S", "O", "T"):
        return STRING
    name = dt.name
    if name in _BY_NAME:
        return _BY_NAME[name]
    raise TypeError(f"unsupported numpy dtype: {dt}")


def is_numeric(t: DType) -> bool:
    return t.kind in ("i", "u", "f", "b")


def is_float(t: DType) -> bool:
    return t.kind == "f"


def common_numeric(a: DType, b: DType) -> DType:
    """Result dtype of arithmetic between two numeric columns."""
    res = np.result_type(a.numpy, b.numpy)
    return from_numpy(res)
