"""Device-resident columnar tables.

TPU-native analogue of the reference's `array_info`/`table_info` columnar
core (reference: bodo/libs/_bodo_common.h:936, :1828) and the Python⇄C++
bridge (bodo/libs/array.py:242 `array_to_info`, :1993 `cpp_table_to_py_table`).

Design (SURVEY.md §7):
  - struct-of-arrays: each column is a fixed-capacity padded device array
    plus an optional validity bitmask; the number of real rows is tracked
    host-side (`nrows`, per-shard `counts` when row-sharded). Padded static
    shapes keep XLA happy; the reference's 1D_Var distribution becomes
    (padded buffer + row-count).
  - strings are dictionary-encoded; the dictionary (sorted unique strings)
    lives on host, int32 codes live on device.
  - a Table is either replicated ("REP") or row-sharded over the mesh data
    axis ("1D") — the reference's distribution lattice REP/OneD/OneD_Var
    (bodo/transforms/distributed_analysis.py:83) collapses to these two
    plus the padding counts.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd

from bodo_tpu.config import config
from bodo_tpu.parallel import mesh as mesh_mod
from bodo_tpu.table import dtypes as dt
from bodo_tpu.table.dtypes import DType

REP = "REP"   # replicated: one logical copy (device or host)
ONED = "1D"   # row-sharded over the mesh data axis


def round_capacity(n: int) -> int:
    """Round a row count up to a padded, tile-friendly capacity."""
    r = config.capacity_round
    return max(r, ((n + r - 1) // r) * r)


@dataclass
class Column:
    """One column: device data + optional validity + host dictionary."""
    data: jax.Array                      # [capacity] physical values/codes
    valid: Optional[jax.Array]           # [capacity] bool, None = no nulls
    dtype: DType
    dictionary: Optional[np.ndarray] = None  # sorted unique strings (host)
    # host-known (lo, hi) bound on the PHYSICAL values (parquet footer
    # stats, static DtField ranges, literal projections). A bound, not
    # exact: row-preserving ops (filter/sort/shuffle/join gathers) keep
    # it — the dense groupby/join/pack planners then skip their exact
    # min/max device reductions (the reference gets the same shortcut
    # from parquet row-group statistics in its planner)
    vrange: Optional[tuple] = None

    @property
    def capacity(self) -> int:
        return self.data.shape[0]

    def with_data(self, data, valid=None) -> "Column":
        return Column(data=data, valid=valid, dtype=self.dtype,
                      dictionary=self.dictionary)

    # ---- construction ----------------------------------------------------
    @staticmethod
    def from_numpy(arr: np.ndarray, capacity: Optional[int] = None,
                   valid: Optional[np.ndarray] = None) -> "Column":
        n = len(arr)
        cap = capacity if capacity is not None else round_capacity(n)
        if arr.dtype == object and _looks_decimal(arr):
            return _decimal_column(arr, cap, valid)
        if arr.dtype == object:
            from bodo_tpu.table import nested as _nested
            nt = _nested.infer_nested_dtype(arr)
            if nt is not None:
                vals = list(arr)
                if valid is not None:
                    vals = [v if ok else None
                            for v, ok in zip(vals, valid)]
                if nt.kind == "struct":
                    vals = [None if v is None else
                            tuple(v.get(fn) for fn, _ in nt.fields)
                            for v in vals]
                return _nested.encode_values(vals, nt, capacity=cap)
        dtype = dt.from_numpy(arr.dtype)
        dictionary = None
        if dtype is dt.STRING:
            vals = np.asarray(arr, dtype=object)
            isna = np.array([v is None or (isinstance(v, float) and np.isnan(v))
                             for v in vals], dtype=bool)
            if valid is not None:
                isna |= ~np.asarray(valid, dtype=bool)
            fill = vals[~isna]
            safe = np.where(isna, fill[0] if len(fill) else "", vals)
            dictionary, codes = np.unique(safe.astype(str), return_inverse=True)
            phys = codes.astype(np.int32)
            valid = None if not isna.any() else ~isna
        elif dtype is dt.DATETIME:
            a = np.asarray(arr).astype("datetime64[ns]")
            nat = np.isnat(a)
            phys = a.view(np.int64).copy()
            if nat.any():
                phys[nat] = 0
                valid = (~nat) if valid is None else (np.asarray(valid) & ~nat)
        elif dtype is dt.TIMEDELTA:
            a = np.asarray(arr).astype("timedelta64[ns]")
            nat = np.isnat(a)
            phys = a.view(np.int64).copy()
            if nat.any():
                phys[nat] = 0
                valid = (~nat) if valid is None else (np.asarray(valid) & ~nat)
        else:
            # NaN stays NaN in float data (pandas float semantics); no mask.
            phys = np.asarray(arr, dtype=dtype.numpy)
        padded = np.zeros((cap,) + phys.shape[1:], dtype=dtype.numpy)
        padded[:n] = phys
        vcol = None
        if valid is not None:
            v = np.zeros(cap, dtype=bool)
            v[:n] = np.asarray(valid, dtype=bool)
            vcol = jnp.asarray(v)
        return Column(data=jnp.asarray(padded), valid=vcol, dtype=dtype,
                      dictionary=dictionary)

    # ---- materialization -------------------------------------------------
    def to_numpy(self, nrows: int):
        """Decode the first `nrows` real rows to a host numpy/object array."""
        if dt.is_nested(self.dtype):
            from bodo_tpu.table import nested as _nested
            return _nested.decode_column(self, nrows)
        data = np.asarray(jax.device_get(self.data))[:nrows]
        valid = (np.asarray(jax.device_get(self.valid))[:nrows]
                 if self.valid is not None else None)
        if self.dtype is dt.STRING:
            assert self.dictionary is not None
            if len(self.dictionary) == 0:
                # empty dictionary: every row is null (e.g. the all-null
                # string column appended by an outer join with an empty
                # side)
                return np.full(len(data), None, dtype=object)
            out = self.dictionary[np.clip(data, 0, len(self.dictionary) - 1)]
            out = out.astype(object)
            if valid is not None:
                out[~valid] = None
            return out
        if self.dtype is dt.DATETIME:
            out = data.view("datetime64[ns]").copy()
            if valid is not None:
                out[~valid] = np.datetime64("NaT")
            return out
        if self.dtype is dt.TIMEDELTA:
            out = data.view("timedelta64[ns]").copy()
            if valid is not None:
                out[~valid] = np.timedelta64("NaT")
            return out
        if self.dtype is dt.DATE:
            # days-since-epoch → object array of datetime.date (what
            # pandas' .dt.date produces), None for nulls
            out = data.astype("datetime64[D]").astype(object)
            if valid is not None:
                out[~valid] = None
            return out
        if self.dtype.kind == "dec":
            import decimal as pydec
            q = pydec.Decimal(1).scaleb(-self.dtype.scale)
            out = np.array([pydec.Decimal(int(v))
                            .scaleb(-self.dtype.scale).quantize(q)
                            for v in data], dtype=object)
            if valid is not None:
                out[~valid] = None
            return out
        if valid is not None and self.dtype.kind in ("i", "u", "b"):
            return _masked_to_pandas(data, valid, self.dtype)
        if valid is not None and self.dtype.kind == "f":
            out = data.astype(self.dtype.numpy).copy()
            out[~valid] = np.nan
            return out
        return data


def _dec_isna(v) -> bool:
    import decimal as pydec
    if v is None or v is getattr(pd, "NA", None):
        return True
    if isinstance(v, float) and np.isnan(v):
        return True
    if isinstance(v, pydec.Decimal) and not v.is_finite():
        return True  # Decimal('NaN')/Decimal('Infinity') → null
    return False


def _looks_decimal(arr: np.ndarray) -> bool:
    import decimal as pydec
    for v in arr:
        if _dec_isna(v):
            continue
        return isinstance(v, pydec.Decimal)
    return False


def _decimal_column(arr: np.ndarray, cap: int, valid) -> "Column":
    """Object array of decimal.Decimal → scaled int64 column; the scale
    is the maximum fractional-digit count across the values."""
    import decimal as pydec
    isna = np.array([_dec_isna(v) for v in arr])
    if valid is not None:
        isna |= ~np.asarray(valid, dtype=bool)
    scale = 0
    for v, na in zip(arr, isna):
        if not na:
            scale = max(scale, -int(v.as_tuple().exponent))
    phys = np.zeros(len(arr), dtype=np.int64)
    mul = pydec.Decimal(10) ** scale
    for i, (v, na) in enumerate(zip(arr, isna)):
        if not na:
            phys[i] = int(v * mul)
    padded = np.zeros((cap,), dtype=np.int64)
    padded[:len(arr)] = phys
    vcol = None
    if isna.any():
        vm = np.zeros(cap, dtype=bool)
        vm[:len(arr)] = ~isna
        vcol = jnp.asarray(vm)
    return Column(jnp.asarray(padded), vcol, dt.decimal(scale), None)


def _masked_to_pandas(data, valid, dtype: DType):
    mask = ~np.asarray(valid, dtype=bool)
    if dtype.kind == "b":
        return pd.arrays.BooleanArray(
            np.where(valid, data, False).astype(bool), mask)
    vals = np.where(valid, data, dtype.numpy.type(0)).astype(dtype.numpy)
    return pd.arrays.IntegerArray(vals, mask)


@dataclass
class Table:
    """Host-level handle to device-resident columns.

    Not a pytree: jitted kernels consume/produce raw array pytrees via
    `device_data()` / `with_device_data()`; dictionaries and schema stay on
    host (avoids recompiles keyed on dictionary contents).
    """
    columns: Dict[str, Column]
    nrows: int
    distribution: str = REP
    counts: Optional[np.ndarray] = None  # per-shard real-row counts when 1D

    # ---- basic accessors -------------------------------------------------
    @property
    def names(self) -> List[str]:
        return list(self.columns.keys())

    @property
    def capacity(self) -> int:
        if not self.columns:
            return 0
        return next(iter(self.columns.values())).capacity

    @property
    def num_shards(self) -> int:
        return 1 if self.counts is None else len(self.counts)

    @property
    def shard_capacity(self) -> int:
        return self.capacity // self.num_shards

    def column(self, name: str) -> Column:
        return self.columns[name]

    def select(self, names: Sequence[str]) -> "Table":
        return Table({n: self.columns[n] for n in names}, self.nrows,
                     self.distribution, self.counts)

    def with_columns(self, columns: Dict[str, Column]) -> "Table":
        return Table(dict(columns), self.nrows, self.distribution, self.counts)

    # ---- conversion ------------------------------------------------------
    @staticmethod
    def from_pandas(df: pd.DataFrame, capacity: Optional[int] = None) -> "Table":
        n = len(df)
        cap = capacity if capacity is not None else round_capacity(n)
        cols: Dict[str, Column] = {}
        for name in df.columns:
            s = df[name]
            valid = None
            if s.isna().any():
                valid = (~s.isna()).to_numpy()
            if hasattr(s.dtype, "numpy_dtype"):
                # pandas masked extension dtype (Int64/boolean/...): keep the
                # exact physical dtype, don't let to_numpy() densify to
                # object/float64 (loses precision for large ints)
                np_dt = s.dtype.numpy_dtype
                arr = s.to_numpy(dtype=np_dt, na_value=np_dt.type(0))
            elif valid is not None and s.dtype.kind not in (
                    "O", "U", "T", "M", "m", "f"):
                arr = s.to_numpy(na_value=0)
            else:
                arr = s.to_numpy()
            cols[str(name)] = Column.from_numpy(arr, capacity=cap, valid=valid)
        return Table(cols, n, REP, None)

    def to_pandas(self) -> pd.DataFrame:
        from bodo_tpu.utils import tracing
        with tracing.event("to_pandas") as ev:
            t = self.gather() if self.distribution == ONED else self
            out = {}
            for name, col in t.columns.items():
                out[name] = col.to_numpy(t.nrows)
            if ev is not None:
                ev["rows"] = t.nrows
            return pd.DataFrame(out)

    # ---- distribution ----------------------------------------------------
    def shard(self) -> "Table":
        """REP -> 1D: scatter rows over the mesh data axis
        (scatterv analogue, reference distributed_api.py:1299).

        Shard i owns global rows [i*per, i*per + counts[i]) — the packed
        per-shard layout coincides with the source layout, so no
        per-shard host repack is needed: single-process, the column
        pads/zero-tails ON DEVICE and `jax.device_put` against the row
        sharding moves each slice to its device; multi-process (SPMD
        pods), `jax.make_array_from_callback` materializes only the
        shards THIS host's devices own — the full table never transits
        any single host."""
        if self.distribution == ONED:
            return self
        m = mesh_mod.get_mesh()
        s = mesh_mod.num_shards(m)
        per = round_capacity(-(-max(self.nrows, 1) // s))
        counts = np.array(
            [max(0, min(per, self.nrows - i * per)) for i in range(s)],
            dtype=np.int64)
        sharding = mesh_mod.row_sharding(m)
        target = s * per
        nrows = self.nrows
        multi = jax.process_count() > 1

        def _scatter(arr, zero):
            if multi:
                host = np.asarray(jax.device_get(arr))

                def cb(idx):
                    sl = idx[0]
                    lo = sl.start or 0
                    hi = sl.stop if sl.stop is not None else target
                    piece = np.full((hi - lo,) + host.shape[1:],
                                    zero, host.dtype)
                    take = min(hi, nrows)
                    if take > lo:
                        piece[: take - lo] = host[lo:take]
                    return piece
                return jax.make_array_from_callback(
                    (target,) + host.shape[1:], sharding, cb)
            d = arr
            if d.shape[0] < target:
                pad = jnp.full((target - d.shape[0],) + d.shape[1:],
                               zero, d.dtype)
                d = jnp.concatenate([d, pad])
            elif d.shape[0] > target:
                d = d[:target]
            if d.shape[0] > nrows:  # zero the tail (old garbage rows)
                mask = jnp.arange(target) < nrows
                d = jnp.where(
                    mask.reshape((-1,) + (1,) * (d.ndim - 1)), d,
                    jnp.asarray(zero, d.dtype))
            return jax.device_put(d, sharding)

        new_cols = {}
        for name, col in self.columns.items():
            data = _scatter(col.data, 0)
            valid = (None if col.valid is None
                     else _scatter(col.valid, False))
            new_cols[name] = Column(data, valid, col.dtype, col.dictionary,
                                    col.vrange)
        return Table(new_cols, self.nrows, ONED, counts)

    def gather(self) -> "Table":
        """1D -> REP: gather shards, trim padding, repack contiguous
        (gatherv analogue, reference distributed_api.py:713)."""
        if self.distribution == REP:
            return self
        from bodo_tpu.parallel import comm
        with comm.collective_span("gather",
                                  bytes_in=comm.table_bytes(self)) as _sp:
            out = self._gather_inner()
            _sp["bytes_out"] = comm.table_bytes(out)
        return out

    def _gather_inner(self) -> "Table":
        s = self.num_shards
        per = self.shard_capacity
        cap = round_capacity(max(self.nrows, 1))
        new_cols = {}
        for name, col in self.columns.items():
            host = np.asarray(jax.device_get(col.data))
            pieces = [host[i * per: i * per + int(self.counts[i])]
                      for i in range(s)]
            packed = np.concatenate(pieces) if pieces else host[:0]
            padded = np.zeros((cap,), dtype=host.dtype)
            padded[: self.nrows] = packed
            valid = None
            if col.valid is not None:
                hv = np.asarray(jax.device_get(col.valid))
                vp = [hv[i * per: i * per + int(self.counts[i])]
                      for i in range(s)]
                vpacked = np.concatenate(vp) if vp else hv[:0]
                vpad = np.zeros((cap,), dtype=bool)
                vpad[: self.nrows] = vpacked
                valid = jnp.asarray(vpad)
            new_cols[name] = Column(jnp.asarray(padded), valid, col.dtype,
                                    col.dictionary, col.vrange)
        return Table(new_cols, self.nrows, REP, None)

    # ---- kernel interface ------------------------------------------------
    def device_data(self):
        """Pytree view for jitted kernels: {name: (data, valid_or_None)}."""
        return {n: (c.data, c.valid) for n, c in self.columns.items()}

    def counts_device(self):
        """Per-shard row counts as a device array sharded one-per-shard
        (shape [S]; inside shard_map each shard sees [1])."""
        if self.counts is None:
            return jnp.asarray(np.array([self.nrows], dtype=np.int64))
        m = mesh_mod.get_mesh()
        return jax.device_put(self.counts.astype(np.int64),
                              mesh_mod.row_sharding(m))

    def with_device_data(self, tree, nrows: Optional[int] = None,
                         counts: Optional[np.ndarray] = None,
                         dtypes: Optional[Dict[str, DType]] = None,
                         dicts: Optional[Dict[str, np.ndarray]] = None
                         ) -> "Table":
        """Rebuild a Table from a kernel-output pytree, preserving schema
        metadata for columns that still exist (host-side dictionary
        re-attachment — see module docstring).

        Column ORDER is restored from this table, not the pytree: jax
        flattens dict pytrees in sorted-key order, so a dict that round-
        tripped through a jitted kernel comes back alphabetized."""
        order = [n for n in self.columns if n in tree] + \
            [n for n in tree if n not in self.columns]
        cols = {}
        for name in order:
            data, valid = tree[name]
            if dtypes and name in dtypes:
                dtype = dtypes[name]
            elif name in self.columns:
                dtype = self.columns[name].dtype
            else:
                dtype = dt.from_numpy(np.dtype(data.dtype))
            dictionary = None
            if dicts and name in dicts:
                dictionary = dicts[name]
            elif name in self.columns:
                dictionary = self.columns[name].dictionary
            cols[name] = Column(data, valid, dtype, dictionary)
        new_dist = self.distribution if counts is None else ONED
        return Table(cols, self.nrows if nrows is None else nrows,
                     new_dist, self.counts if counts is None else counts)

    def __repr__(self) -> str:  # pragma: no cover
        schema = ", ".join(f"{n}:{c.dtype.name}" for n, c in self.columns.items())
        return (f"Table[{self.nrows} rows, cap={self.capacity}, "
                f"{self.distribution}]({schema})")
