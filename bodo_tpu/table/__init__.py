"""Columnar data model (analogue of bodo/libs/_bodo_common.h structures)."""

from bodo_tpu.table.table import Table, Column, round_capacity, REP, ONED
from bodo_tpu.table import dtypes

__all__ = ["Table", "Column", "round_capacity", "REP", "ONED", "dtypes"]
