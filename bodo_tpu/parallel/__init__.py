"""Distribution layer: mesh management + collectives.

TPU-native replacement for the reference's MPI stack
(bodo/libs/_distributed.h, bodo/libs/distributed_api.py, bodo/spawn/).
"""
