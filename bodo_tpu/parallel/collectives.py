"""Collective primitives over the device mesh.

This module mirrors the surface of the reference's distributed API
(reference: bodo/libs/distributed_api.py — get_rank:?, dist_reduce:510,
dist_exscan:664, gatherv:713, allgatherv:1022, scatterv:1299, bcast:2578;
C++ side bodo/libs/_distributed.h:72 `BODO_ReduceOps`) but implemented with
jax.lax collectives that XLA lowers onto ICI/DCN:

    MPI_Allreduce   -> lax.psum / pmax / pmin
    MPI_Exscan      -> all_gather + masked cumsum (exscan)
    MPI_Allgatherv  -> lax.all_gather (fixed-capacity shards + row counts)
    MPI_Alltoallv   -> lax.all_to_all (fixed-capacity buckets, `tiled=True`)
    isend/irecv     -> lax.ppermute ring shifts (halo exchange)

Functions in the "axis context" section must be called inside
`shard_map`/`pjit` bodies where the mesh axis is bound; host-level
gather/scatter helpers live at the bottom.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
try:
    from jax import shard_map
except ImportError:  # jax < 0.5: shard_map still under experimental
    from jax.experimental.shard_map import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from bodo_tpu.config import config
from bodo_tpu.parallel import mesh as mesh_mod
from bodo_tpu.runtime.resilience import maybe_inject as _inject


# --------------------------------------------------------------------------
# Axis-context collectives (use inside shard_map bodies)
# --------------------------------------------------------------------------
# NOTE on fault injection: the `collective` point here fires at TRACE
# time (these run inside shard_map/jit bodies, and compiled kernels are
# cached) — it arms chaos for fresh compilations. The per-call host-level
# `collective` point lives at the distributed-op dispatchers in
# relational.py, which is what stage-degradation tests use. The
# shardcheck trace-time-side-effect lint flags exactly this pattern;
# the inline suppressions below mark it as the one intentional case.

if hasattr(lax, "axis_size"):
    axis_size = lax.axis_size
else:
    def axis_size(ax):
        # jax < 0.5 has no lax.axis_size; psum of a literal constant
        # folds to the static axis size inside any bound axis context.
        return lax.psum(1, ax)


def rank(axis: Optional[str] = None):
    """This shard's index along the data axis (MPI_Comm_rank analogue)."""
    return lax.axis_index(axis or config.data_axis)


def size(axis: Optional[str] = None) -> int:
    """Static number of shards along the data axis (MPI_Comm_size analogue)."""
    return axis_size(axis or config.data_axis)


def dist_sum(x, axis: Optional[str] = None):
    _inject("collective")  # shardcheck: ignore[trace-time-side-effect]
    return lax.psum(x, axis or config.data_axis)


def dist_max(x, axis: Optional[str] = None):
    _inject("collective")  # shardcheck: ignore[trace-time-side-effect]
    return lax.pmax(x, axis or config.data_axis)


def dist_min(x, axis: Optional[str] = None):
    _inject("collective")  # shardcheck: ignore[trace-time-side-effect]
    return lax.pmin(x, axis or config.data_axis)


def dist_exscan_sum(x, axis: Optional[str] = None):
    """Exclusive prefix sum over shards (MPI_Exscan analogue; used for
    1D_Var offset bookkeeping and dist_cumsum — reference
    bodo/libs/distributed_api.py:664, :2205)."""
    _inject("collective")  # shardcheck: ignore[trace-time-side-effect]
    ax = axis or config.data_axis
    n = axis_size(ax)
    gathered = lax.all_gather(x, ax)            # [n, ...]
    idx = lax.axis_index(ax)
    mask = (jnp.arange(n) < idx).astype(gathered.dtype)
    mask = mask.reshape((n,) + (1,) * (gathered.ndim - 1))
    return jnp.sum(gathered * mask, axis=0)


def all_gather_rows(x, axis: Optional[str] = None):
    """Concatenate each shard's rows in rank order: [cap,...] -> [S*cap,...]
    (MPI_Allgatherv analogue; padding travels with the shard and is
    resolved by the caller via per-shard row counts)."""
    _inject("collective")  # shardcheck: ignore[trace-time-side-effect]
    ax = axis or config.data_axis
    return lax.all_gather(x, ax, tiled=True)


def all_to_all_rows(x, axis: Optional[str] = None):
    """Fixed-capacity all-to-all: x has shape [S*C, ...]; contiguous block
    i of C rows is sent to shard i; result is the S received blocks
    concatenated in rank order. This is the alltoallv of the reference's
    shuffle (bodo/libs/_shuffle.h:41, streaming/_shuffle.h:777) with
    capacity-padded buckets instead of variable counts."""
    _inject("collective")  # shardcheck: ignore[trace-time-side-effect]
    ax = axis or config.data_axis
    return lax.all_to_all(x, ax, split_axis=0, concat_axis=0, tiled=True)


def ring_shift(x, shift: int = 1, axis: Optional[str] = None):
    """Send local block to rank+shift (mod S): the neighbor-exchange used
    for rolling-window halos (reference bodo/hiframes/rolling.py,
    bodo/libs/parallel_ops.py) — lax.ppermute over the ring."""
    _inject("collective")  # shardcheck: ignore[trace-time-side-effect]
    ax = axis or config.data_axis
    n = axis_size(ax)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, ax, perm)


def bcast_from(x, root: int = 0, axis: Optional[str] = None):
    """Broadcast shard `root`'s block to all shards (MPI_Bcast analogue,
    reference bodo/libs/distributed_api.py:2578)."""
    _inject("collective")  # shardcheck: ignore[trace-time-side-effect]
    ax = axis or config.data_axis
    gathered = lax.all_gather(x, ax)
    return gathered[root]


# --------------------------------------------------------------------------
# Host-level distribution helpers (outside jit)
# --------------------------------------------------------------------------

def shard_host_array(arr: np.ndarray, capacity_per_shard: Optional[int] = None):
    """Scatter a host array into a row-sharded device array
    (MPI_Scatterv analogue, reference distributed_api.py:1299).

    Each shard receives an equal padded chunk; returns
    (device_array [S*cap_per_shard], per-shard row counts [S]).
    """
    from bodo_tpu.parallel import comm
    _inject("device_put")
    m = mesh_mod.get_mesh()
    s = mesh_mod.num_shards(m)
    n = arr.shape[0]
    base = -(-n // s) if n else 0
    cap = capacity_per_shard if capacity_per_shard is not None else _round_cap(base)
    counts = np.array(
        [max(0, min(cap, n - i * cap)) for i in range(s)], dtype=np.int64
    )
    # NOTE: with cap >= ceil(n/s) every row lands in some shard
    if counts.sum() != n:
        # capacity too small for equal chunking; grow
        cap = _round_cap(-(-n // s))
        counts = np.array(
            [max(0, min(cap, n - i * cap)) for i in range(s)], dtype=np.int64
        )
    padded_shape = (s * cap,) + arr.shape[1:]
    padded = np.zeros(padded_shape, dtype=arr.dtype)
    if n:
        padded[: min(n, s * cap)] = arr[: s * cap]
    with comm.collective_span("scatter_host",
                              bytes_in=int(arr.nbytes)) as sp:
        dev = jax.device_put(padded, NamedSharding(m, P(config.data_axis)))
        sp["bytes_out"] = int(padded.nbytes)
    return dev, counts


def gather_host_rows(dev_arr, counts: np.ndarray) -> np.ndarray:
    """Gather a row-sharded device array back to a host array, trimming
    per-shard padding (MPI_Gatherv analogue, reference
    distributed_api.py:713)."""
    from bodo_tpu.parallel import comm
    s = len(counts)
    with comm.collective_span(
            "gather_host",
            bytes_in=int(getattr(dev_arr, "nbytes", 0))) as sp:
        host = np.asarray(jax.device_get(dev_arr))
        cap = host.shape[0] // s
        pieces = [host[i * cap: i * cap + int(counts[i])]
                  for i in range(s)]
        out = np.concatenate(pieces, axis=0) if pieces else host[:0]
        sp["bytes_out"] = int(out.nbytes)
    return out


def _round_cap(n: int) -> int:
    from bodo_tpu.table.table import round_capacity
    return round_capacity(n)


# --------------------------------------------------------------------------
# shard_map convenience wrapper
# --------------------------------------------------------------------------

try:  # the replication-check kwarg was renamed check_rep -> check_vma
    import inspect
    _SMAP_CHECK_KW = ("check_vma" if "check_vma"
                      in inspect.signature(shard_map).parameters
                      else "check_rep")
except (ValueError, TypeError):  # pragma: no cover - unintrospectable
    _SMAP_CHECK_KW = "check_vma"


def smap(fn, in_specs, out_specs, mesh=None):
    """shard_map over the active mesh with the data axis bound."""
    m = mesh or mesh_mod.get_mesh()
    return shard_map(fn, mesh=m, in_specs=in_specs, out_specs=out_specs,
                     **{_SMAP_CHECK_KW: False})


ROW = None  # placeholder; use P(config.data_axis) / P() at call sites
