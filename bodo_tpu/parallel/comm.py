"""Per-collective communication accounting (the observatory's core).

Every host-level collective dispatch site — the relational dispatchers
and `shuffle_by_key`, the host scatter/gather helpers in
parallel/collectives.py, the 1D→REP `Table.gather`, and the streaming
executors' per-batch shuffle steps — reports here: bytes in/out, wall
seconds of the dispatch, and the peer-wait seconds the lockstep checker
measured before the op could proceed (the arrival-skew signal: the rank
everyone waits FOR is the straggler, and it is the rank whose own wait
is smallest).

Rows are keyed ``(op, site)`` where `site` is the first user-level call
frame (same convention as the lockstep fingerprint), so `doctor` and
the bench comm suite can name the dominant collective site, not just
the op. Each span additionally lands in the trace ring as a ``comm:*``
event (per-rank lanes in the merged gang trace feed the critical-path
analyzer) and the byte/latency distributions go to the
``bodo_tpu_comm_*`` histograms push-side; cumulative gauges are synced
pull-side by ``metrics.sync_engine_metrics``.

Stdlib-only on purpose: importable from a /metrics scrape or the
telemetry sampler without forcing a jax import.
"""

from __future__ import annotations

import contextlib
import os
import sys
import threading
import time
from typing import Dict, Optional, Tuple

from bodo_tpu.config import config

_lock = threading.Lock()
# (op, site) -> accounting row
_sites: Dict[Tuple[str, str], dict] = {}
_last = {"op": "", "site": "", "wait_s": 0.0, "wall_s": 0.0, "seq": 0}
_seq = 0

# dispatch-size / dispatch-latency histogram buckets: collectives range
# from KB control payloads to multi-GB shuffles, 100us to seconds
_BYTE_BUCKETS = (1e3, 1e4, 1e5, 1e6, 1e7, 1e8, 1e9)
_TIME_BUCKETS = (1e-4, 1e-3, 5e-3, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0)

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _call_site() -> str:
    """First stack frame OUTSIDE the bodo_tpu package, as
    basename:lineno — same convention as the lockstep fingerprint so a
    comm row and a lockstep log line for one dispatch agree."""
    f = sys._getframe(2)
    while f is not None:
        fname = f.f_code.co_filename
        # collective_span reaches here through contextlib's __enter__ —
        # skip stdlib contextmanager frames along with package frames
        if not fname.startswith(_PKG_DIR) \
                and not fname.endswith("contextlib.py"):
            return f"{os.path.basename(fname)}:{f.f_lineno}"
        f = f.f_back
    return "<internal>"


def record(op: str, *, site: Optional[str] = None, bytes_in: int = 0,
           bytes_out: int = 0, wall_s: float = 0.0,
           wait_s: float = 0.0) -> None:
    """Account one dispatched collective. `wall_s` is the host dispatch
    wall (for async dispatches: enqueue time, not completion); `wait_s`
    is the lockstep peer-wait before the dispatch could proceed."""
    global _seq
    if not config.comm_accounting:
        return
    site = site or _call_site()
    with _lock:
        _seq += 1
        r = _sites.get((op, site))
        if r is None:
            r = _sites[(op, site)] = {
                "count": 0, "bytes_in": 0, "bytes_out": 0,
                "wall_s": 0.0, "max_wall_s": 0.0,
                "wait_s": 0.0, "max_wait_s": 0.0}
        r["count"] += 1
        r["bytes_in"] += int(bytes_in)
        r["bytes_out"] += int(bytes_out)
        r["wall_s"] += float(wall_s)
        r["max_wall_s"] = max(r["max_wall_s"], float(wall_s))
        r["wait_s"] += float(wait_s)
        r["max_wait_s"] = max(r["max_wait_s"], float(wait_s))
        _last.update(op=op, site=site, wait_s=float(wait_s),
                     wall_s=float(wall_s), seq=_seq)
    try:  # push-side distributions (metrics.py is stdlib — no jax pull)
        from bodo_tpu.utils import metrics
        nb = int(bytes_out) or int(bytes_in)
        if nb:
            metrics.histogram(
                "bodo_tpu_comm_dispatch_bytes",
                "bytes moved per collective dispatch", ("op",),
                buckets=_BYTE_BUCKETS).labels(op=op).observe(nb)
        metrics.histogram(
            "bodo_tpu_comm_dispatch_seconds",
            "host wall seconds per collective dispatch", ("op",),
            buckets=_TIME_BUCKETS).labels(op=op).observe(
            float(wall_s) if wall_s else float(wait_s))
    except Exception:  # pragma: no cover - metrics must not break comm
        pass


def record_in_program(group_fp: str, *, bytes_in: int = 0,
                      bytes_out: int = 0, wall_s: float = 0.0,
                      wait_s: float = 0.0) -> int:
    """Attribute the in-program collectives of one fused-group dispatch.

    Collectives traced INSIDE a compiled fusion body (all_to_all /
    psum inside a shard_map program) never hit the per-op dispatch
    hooks, so the usual ``record`` call sites cannot see them. The
    fused dispatcher calls this instead: the group's lockstep manifest
    (``register_fusion_manifest(..., in_program=...)``) declares which
    collective ops the program subsumes, and one accounting row per
    declared op is recorded at site ``fused[<fp>]`` — so ``doctor`` and
    the bench comm suite still see an all_to_all row for a shuffle that
    now lives inside a compiled stage. Group wall/wait is attributed to
    the FIRST declared op (the program is one dispatch; splitting the
    wall across members would double-count). Returns the number of
    in-program collectives attributed (0 when the manifest declares
    none or does not exist)."""
    if not config.comm_accounting:
        return 0
    from bodo_tpu.analysis import lockstep
    m = lockstep.fusion_manifest(group_fp)
    ops = tuple(m.get("in_program", ())) if m else ()
    if not ops:
        return 0
    site = f"fused[{group_fp}]"
    for i, op in enumerate(ops):
        record(op, site=site,
               bytes_in=int(bytes_in) if i == 0 else 0,
               bytes_out=int(bytes_out) if i == 0 else 0,
               wall_s=float(wall_s) if i == 0 else 0.0,
               wait_s=float(wait_s) if i == 0 else 0.0)
    return len(ops)


@contextlib.contextmanager
def collective_span(op: str, *, bytes_in: int = 0, wait_s: float = 0.0,
                    site: Optional[str] = None):
    """Time one host-level collective dispatch, emit a ``comm:<op>``
    trace event, and account it. Yields a mutable dict: set
    ``bytes_out`` (and adjust ``wait_s``) before the block exits."""
    if not config.comm_accounting:
        yield {}
        return
    site = site or _call_site()
    sp = {"bytes_out": 0, "wait_s": float(wait_s)}
    from bodo_tpu.utils import tracing
    t0 = time.perf_counter()
    try:
        with tracing.event(f"comm:{op}", site=site,
                           bytes_in=int(bytes_in)) as ev:
            yield sp
            if ev is not None:
                ev["bytes_out"] = int(sp.get("bytes_out", 0))
                ev["wait_s"] = round(float(sp.get("wait_s", 0.0)), 6)
    finally:
        record(op, site=site, bytes_in=bytes_in,
               bytes_out=int(sp.get("bytes_out", 0)),
               wall_s=time.perf_counter() - t0,
               wait_s=float(sp.get("wait_s", 0.0)))


def table_bytes(t) -> int:
    """Device bytes of a Table (best-effort input/output sizing for the
    accounting rows; 0 when the governor's sizer is unavailable)."""
    try:
        from bodo_tpu.runtime.memory_governor import table_device_bytes
        return int(table_device_bytes(t))
    except Exception:
        return 0


def stats() -> dict:
    """Full accounting snapshot: process-wide totals + per-(op@site)
    rows. JSON-safe; spawned gang workers return this from run_spmd so
    the parent can compare per-rank skew."""
    with _lock:
        sites = {f"{op}@{site}": dict(r)
                 for (op, site), r in _sites.items()}
        last = dict(_last)
    tot = {"dispatches": 0, "bytes_in": 0, "bytes_out": 0,
           "wall_s": 0.0, "wait_s": 0.0, "max_wait_s": 0.0}
    for r in sites.values():
        tot["dispatches"] += r["count"]
        tot["bytes_in"] += r["bytes_in"]
        tot["bytes_out"] += r["bytes_out"]
        tot["wall_s"] += r["wall_s"]
        tot["wait_s"] += r["wait_s"]
        tot["max_wait_s"] = max(tot["max_wait_s"], r["max_wait_s"])
    tot["sites"] = sites
    tot["last"] = last
    return tot


def per_op() -> Dict[str, dict]:
    """Accounting rows aggregated by op (site collapsed) — what the
    bench comm suite and tracing.profile's ``comm:*`` rows report."""
    out: Dict[str, dict] = {}
    with _lock:
        items = [(op, dict(r)) for (op, _site), r in _sites.items()]
    for op, r in items:
        a = out.get(op)
        if a is None:
            out[op] = r
            continue
        a["count"] += r["count"]
        a["bytes_in"] += r["bytes_in"]
        a["bytes_out"] += r["bytes_out"]
        a["wall_s"] += r["wall_s"]
        a["max_wall_s"] = max(a["max_wall_s"], r["max_wall_s"])
        a["wait_s"] += r["wait_s"]
        a["max_wait_s"] = max(a["max_wait_s"], r["max_wait_s"])
    return out


def skew_head() -> dict:
    """Small JSON-safe skew snapshot for the telemetry sampler and
    /healthz (the future scheduler's admission input, ROADMAP item 2):
    total dispatches, cumulative/worst peer-wait, the worst-wait site,
    and the wait share of total comm wall."""
    with _lock:
        worst_site, worst = "", 0.0
        wall = wait = 0.0
        n = 0
        for (op, site), r in _sites.items():
            n += r["count"]
            wall += r["wall_s"]
            wait += r["wait_s"]
            if r["max_wait_s"] > worst:
                worst = r["max_wait_s"]
                worst_site = f"{op}@{site}"
        last = dict(_last)
    return {
        "dispatches": n,
        "wait_s": round(wait, 6),
        "max_wait_s": round(worst, 6),
        "max_wait_site": worst_site,
        "wait_frac": round(wait / (wall + wait), 4) if wall + wait
        else 0.0,
        "last_op": last["op"],
        "last_seq": last["seq"],
    }


def straggler_from_logs(dirpath: str, nprocs: int,
                        epoch: int = 0) -> Optional[int]:
    """Straggler attribution from the lockstep arrival stamps: for each
    sequence number every rank reached, the rank whose wall-clock
    arrival stamp is LATEST is the one its peers waited for; the rank
    that is latest most often is the straggler. This is the signal the
    elastic layer's eviction policy uses to drop the rank the gang is
    *waiting for*, not only the one that crashed. Returns the mesh rank
    (epoch-local numbering) or None when the logs carry no comparable
    stamps (lockstep off, single rank, or no common sequence)."""
    from bodo_tpu.analysis.lockstep import _log_name
    arrivals: Dict[int, Dict[int, float]] = {}
    for rank in range(int(nprocs)):
        path = os.path.join(dirpath, _log_name(int(epoch), rank))
        try:
            with open(path) as f:
                lines = f.read().splitlines()
        except OSError:
            continue
        stamps: Dict[int, float] = {}
        for line in lines:
            parts = line.split("\t")
            if len(parts) < 3:
                continue
            try:
                stamps[int(parts[0])] = float(parts[2])
            except ValueError:
                continue
        if stamps:
            arrivals[rank] = stamps
    if len(arrivals) < 2:
        return None
    common = set.intersection(*(set(s) for s in arrivals.values()))
    if not common:
        return None
    late: Dict[int, int] = {}
    for seq in common:
        worst = max(arrivals, key=lambda r: arrivals[r][seq])
        late[worst] = late.get(worst, 0) + 1
    return max(late, key=lambda r: late[r])


def reset() -> None:
    global _seq
    with _lock:
        _sites.clear()
        _seq = 0
        _last.update(op="", site="", wait_s=0.0, wall_s=0.0, seq=0)
    try:
        from bodo_tpu.utils import metrics
        for name in ("bodo_tpu_comm_dispatch_bytes",
                     "bodo_tpu_comm_dispatch_seconds"):
            m = metrics.registry().get(name)
            if m is not None:
                m.clear()
    except Exception:  # pragma: no cover
        pass
