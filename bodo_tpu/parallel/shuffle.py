"""Hash/range shuffle over the mesh — the engine's repartitioning core.

TPU-native replacement for the reference's MPI alltoallv shuffle
(bodo/libs/_shuffle.cpp `shuffle_table`, bodo/libs/streaming/_shuffle.h:777
`IncrementalShuffleState`). The variable-count alltoallv becomes a
fixed-capacity `lax.all_to_all`: each shard packs its rows into S buckets
of static capacity C (destination = hash or range of the key), exchanges
the buckets over ICI, then compacts received rows using exchanged
per-source counts. Overflowing a bucket sets a flag the host checks and
retries with a larger C (the analogue of the reference's partition
re-splitting on memory pressure, streaming/_join.h:267).
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from bodo_tpu.config import config
from bodo_tpu.ops import kernels as K
from bodo_tpu.ops.groupby import (COMBINE_OF, DECOMPOSE, HASH_OPS,
                                  _var_from_m2, groupby_local,
                                  groupby_local_hashed_static,
                                  result_dtype)
from bodo_tpu.ops.hashing import dest_shard, hash_columns
from bodo_tpu.ops import pallas_kernels as PK
from bodo_tpu.parallel import collectives as C
from bodo_tpu.parallel import mesh as mesh_mod
from bodo_tpu.plan.fusion import fusion_stage
from bodo_tpu.utils.kernel_cache import cached_builder


# ---------------------------------------------------------------------------
# bucket pack / unpack (runs per shard, inside shard_map)
# ---------------------------------------------------------------------------
# These bodies trace into compiled sharded programs (and may be inlined
# into fused whole-stage pipelines): @fusion_stage puts them under the
# shardcheck fusion-host-call lint — no host sync is legal inside.

@fusion_stage
def bucket_rows(dest, arrays: Sequence, count, num_shards: int,
                bucket_cap: int):
    """Pack rows into per-destination buckets of capacity `bucket_cap`.

    dest: int32 [cap] destination shard per row (padding rows ignored).
    Returns (packed arrays [S*C,...], send_counts [S], overflow flag).
    """
    cap = dest.shape[0]
    padmask = K.row_mask(count, cap)
    d = jnp.where(padmask, dest, num_shards).astype(jnp.int32)
    live = padmask & (d < num_shards)
    # bucket partition scatter: the Pallas partition_rank kernel derives
    # every row's stable in-bucket rank AND the per-bucket histogram in
    # one grid pass (triangular-matmul prefix + VMEM running base), so
    # the XLA stable sort below never runs when the gate is open
    res = PK.partition_rank(d, live, num_shards)
    if res is not None:
        rank, counts = res
        ok = live & (rank >= 0) & (rank < bucket_cap)
        overflow = jnp.any(live & (rank >= bucket_cap))
        scatter_idx = jnp.where(ok, d * bucket_cap + rank,
                                num_shards * bucket_cap)
        packed = []
        for a in arrays:
            if a is None:
                packed.append(None)
                continue
            z = jnp.zeros((num_shards * bucket_cap,) + a.shape[1:],
                          dtype=a.dtype)
            packed.append(z.at[scatter_idx].set(a, mode="drop"))
        send_counts = jnp.minimum(counts.astype(jnp.int64), bucket_cap)
        return packed, send_counts, overflow
    # stable sort rows by destination
    d_s, perm = lax.sort((d, jnp.arange(cap)), num_keys=1, is_stable=True)
    pos = jnp.arange(cap)
    is_new = (d_s != jnp.roll(d_s, 1)) | (pos == 0)
    group_start = lax.cummax(jnp.where(is_new, pos, 0))
    idx_in = pos - group_start
    ok = (d_s < num_shards) & (idx_in < bucket_cap)
    overflow = jnp.any((d_s < num_shards) & (idx_in >= bucket_cap))
    scatter_idx = jnp.where(ok, d_s * bucket_cap + idx_in,
                            num_shards * bucket_cap)
    packed = []
    for a in arrays:
        if a is None:
            packed.append(None)
            continue
        z = jnp.zeros((num_shards * bucket_cap,) + a.shape[1:], dtype=a.dtype)
        packed.append(z.at[scatter_idx].set(a[perm], mode="drop"))
    # bucket-partition counting: Pallas one-hot MXU histogram when the
    # kernel gate is open (XLA lowers the segment_sum to a scatter-add
    # that serializes on the VPU); plain segment_sum elsewhere
    send_counts = PK.bucket_counts(
        jnp.minimum(d, num_shards), padmask,
        num_shards + 1)[:num_shards].astype(jnp.int64)
    send_counts = jnp.minimum(send_counts, bucket_cap)
    return packed, send_counts, overflow


@fusion_stage
def exchange_and_compact(packed: Sequence, send_counts, num_shards: int,
                         bucket_cap: int, axis: Optional[str] = None):
    """all_to_all the packed buckets + counts, then compact received rows.

    Returns (arrays [S*C,...] compacted to front, recv_count scalar).
    """
    recvd = [None if a is None else C.all_to_all_rows(a, axis) for a in packed]
    rcounts = C.all_to_all_rows(send_counts, axis)  # [S]: rows from each src
    total = num_shards * bucket_cap
    slot = jnp.arange(total)
    mask = (slot % bucket_cap) < rcounts[slot // bucket_cap]
    out, cnt = K.compact(mask, tuple(recvd))
    return list(out), cnt


@fusion_stage
def shuffle_rows(dest, arrays: Sequence, count, num_shards: int,
                 bucket_cap: int, axis: Optional[str] = None):
    """Full shuffle: bucket → all_to_all → compact. The `shuffle_table`
    analogue (reference bodo/libs/_shuffle.h:41)."""
    packed, send_counts, ovf = bucket_rows(dest, arrays, count, num_shards,
                                           bucket_cap)
    out, cnt = exchange_and_compact(packed, send_counts, num_shards,
                                    bucket_cap, axis)
    return out, cnt, ovf


# ---------------------------------------------------------------------------
# distributed groupby: partial-agg → hash shuffle → combine → finalize
# ---------------------------------------------------------------------------

def _plan_decomposition(specs: Tuple[str, ...]):
    """Map final agg specs to (partial specs, combine specs, layout).

    layout[i] = (offset, n) slice of partial columns feeding final spec i.
    """
    partial_specs: List[str] = []
    combine_specs: List[str] = []
    layout = []
    for op in specs:
        if op not in DECOMPOSE:
            raise NotImplementedError(
                f"agg '{op}' is not decomposable for the distributed "
                f"two-phase groupby; execute it via gather + local groupby "
                f"(supported distributed aggs: {sorted(DECOMPOSE)})")
        parts = DECOMPOSE[op]
        layout.append((len(partial_specs), len(parts)))
        partial_specs.extend(parts)
        combine_specs.extend(COMBINE_OF[p] for p in parts)
    return tuple(partial_specs), tuple(combine_specs), tuple(layout)


def _finalize(op: str, cols, orig_dtype):
    """Derive the final column from combined partial columns."""
    if op == "mean":
        (s, _), (cnt, _) = cols
        rdt = result_dtype("mean", orig_dtype)
        m = s.astype(rdt) / jnp.maximum(cnt, 1).astype(rdt)
        return jnp.where(cnt > 0, m, jnp.nan), None
    if op in ("var", "std", "var0", "std0"):
        # combined partials are (n, Σx, M2) — M2 already merged exactly by
        # the chan_m2 composite combine (see ops/groupby.py groupby_local)
        (cnt, _), (_s, _), (m2, _) = cols
        rdt = result_dtype(op, orig_dtype)
        ddof = 0 if op.endswith("0") else 1
        out = _var_from_m2(m2, cnt, ddof=ddof)
        return (jnp.sqrt(out) if op.startswith("std")
                else out).astype(rdt), None
    if op == "skew":
        from bodo_tpu.ops.groupby import _skew_from_moments
        (cnt, _), _s, (m2, _), (m3, _) = cols
        return _skew_from_moments(cnt, m2, m3), None
    if op == "kurt":
        from bodo_tpu.ops.groupby import _kurt_from_moments
        (cnt, _), _s, (m2, _), _m3, (m4, _) = cols
        return _kurt_from_moments(cnt, m2, m4), None
    return cols[0]


@cached_builder("shuffle")
def _build_groupby_partial(mesh_key, num_keys: int, specs: Tuple[str, ...],
                           method: str = "sort"):
    """Stage 1: per-shard partial aggregation (shrinks data before the
    wire — the reference's local-combine motivation). method='hash'
    replaces the per-shard row sort with the scatter-claim hash kernel
    (ops/hashtable.py); its traced `unresolved` flag is OR-visible to
    the host, which falls back to 'sort' on pathological keys."""
    mesh = _MESHES[mesh_key]
    axis = config.data_axis
    partial_specs, _, _ = _plan_decomposition(specs)

    def body(arrays, counts):
        count = counts[0]
        cap = arrays[0][0].shape[0]
        keys = arrays[:num_keys]
        values = arrays[num_keys:]
        p_inputs = tuple(keys) + tuple(
            values[i] for i, op in enumerate(specs)
            for _ in DECOMPOSE[op])
        if method == "hash":
            pk, pv, ng, unres = groupby_local_hashed_static(
                p_inputs, count, partial_specs, cap, num_keys)
        else:
            pk, pv, ng = groupby_local(p_inputs, count, partial_specs,
                                       cap, num_keys)
            unres = jnp.zeros((), bool)
        return (pk, pv), ng[None], unres[None]

    shd = C.smap(body, in_specs=(P(axis), P(axis)),
                 out_specs=(P(axis), P(axis), P(axis)), mesh=mesh)
    return jax.jit(shd)


def shuffle_partials(pk, pv, num_keys: int, S: int, bucket_cap: int,
                     ng, axis):
    """Hash-shuffle packed groupby partials to their owner shard.

    pk/pv: key / partial-value (data, valid) pairs packed at the front
    (ng live rows). Validity masks ride the wire as extra slots next to
    their data column; keys come back maskless (group keys are
    canonical). Returns (recv_keys, recv_vals, recv_count, overflow) —
    the one shared layout convention for every shuffle-partials caller
    (whole-table two-phase groupby and the streaming accumulator)."""
    h = hash_columns(pk)
    dest = dest_shard(h, S)
    flat: List = [d for d, _ in pk]
    has_valid: List[bool] = []
    for d, v in pv:
        flat.append(d)
        if v is not None:
            has_valid.append(True)
            flat.append(v)
        else:
            has_valid.append(False)
    out, cnt, ovf = shuffle_rows(dest, flat, ng, S, bucket_cap, axis)
    rk = tuple((out[i], None) for i in range(num_keys))
    rv = []
    j = num_keys
    for hv in has_valid:
        if hv:
            rv.append((out[j], out[j + 1].astype(bool)))
            j += 2
        else:
            rv.append((out[j], None))
            j += 1
    return rk, tuple(rv), cnt, ovf


@cached_builder("shuffle")
def _build_groupby_combine(mesh_key, num_keys: int, specs: Tuple[str, ...],
                           value_dtypes: Tuple, bucket_cap: int,
                           final_cap: int):
    """Stage 2: hash-shuffle partial rows at a tight bucket capacity, then
    combine + finalize. The host sizes bucket_cap from stage-1 counts and
    retries on overflow (analogue of partition re-splitting)."""
    mesh = _MESHES[mesh_key]
    axis = config.data_axis
    S = mesh.shape[axis]
    _, combine_specs, layout = _plan_decomposition(specs)

    def body(partials, ngs):
        pk, pv = partials
        ng = ngs[0]
        rk, rv, cnt2, ovf = shuffle_partials(pk, pv, num_keys, S,
                                             bucket_cap, ng, axis)
        fk, fv, ng2 = groupby_local(rk + rv, cnt2, combine_specs,
                                    final_cap, num_keys)
        finals = []
        for i, op in enumerate(specs):
            off, n = layout[i]
            finals.append(_finalize(op, fv[off:off + n],
                                    jnp.dtype(value_dtypes[i])))
        return (fk, tuple(finals)), ng2[None], ovf[None]

    shd = C.smap(body, in_specs=(P(axis), P(axis)),
                 out_specs=(P(axis), P(axis), P(axis)), mesh=mesh)
    return jax.jit(shd)


_MESHES = {}


def _mesh_key(mesh):
    k = (tuple(d.id for d in mesh.devices.flat), mesh.axis_names)
    _MESHES[k] = mesh
    return k


def groupby_sharded(arrays, counts, num_keys: int, specs: Tuple[str, ...],
                    bucket_cap=None, final_cap=None, mesh=None):
    """Distributed two-phase groupby over row-sharded arrays.

    arrays: tuple of (data, valid) with data sharded [S*cap]; counts [S].
    Returns ((out_keys, out_finals), n_groups [S], overflow [S]).

    Host-visible staging: after the partial stage the host reads the
    per-shard partial counts and sizes the shuffle buckets tightly
    (expected rows per (src,dest) pair × skew headroom), growing them on
    overflow up to the always-safe bound (= max partial count).

    This is a HOST-level entry (device_get between stages), so it owns
    a query-tagged tracing span; the inner shuffle_rows/shuffle_partials
    run under jit tracing and must stay side-effect free.
    """
    from bodo_tpu.utils import tracing
    with tracing.event("groupby_sharded", specs=list(specs)):
        return _groupby_sharded_impl(arrays, counts, num_keys, specs,
                                     bucket_cap, final_cap, mesh)


def _groupby_sharded_impl(arrays, counts, num_keys: int,
                          specs: Tuple[str, ...], bucket_cap=None,
                          final_cap=None, mesh=None):
    from bodo_tpu.table.table import round_capacity
    m = mesh or mesh_mod.get_mesh()
    S = m.shape[config.data_axis]
    mk = _mesh_key(m)
    value_dtypes = tuple(str(arrays[num_keys + i][0].dtype)
                         for i in range(len(specs)))

    method = "sort"
    if config.hash_groupby:
        try:
            partial_specs, _, _ = _plan_decomposition(specs)
            if all(p in HASH_OPS for p in partial_specs):
                method = "hash"
        except NotImplementedError:
            pass
    while True:
        partials, ngs, unres = _build_groupby_partial(
            mk, num_keys, specs, method)(tuple(arrays), counts)
        if method == "hash" and \
                np.asarray(jax.device_get(unres)).any():
            method = "sort"  # pathological keys on some shard
            continue
        break
    png = np.asarray(jax.device_get(ngs)).reshape(-1)
    max_png = int(png.max()) if len(png) else 0
    safe_cap = round_capacity(max(max_png, 1))
    if bucket_cap is None:
        bucket_cap = round_capacity(
            int(config.shuffle_skew_factor * max(max_png, 1) / S) + 64)
        bucket_cap = min(bucket_cap, safe_cap)
    while True:
        fcap = final_cap if final_cap is not None else S * bucket_cap
        fn = _build_groupby_combine(mk, num_keys, specs, value_dtypes,
                                    bucket_cap, fcap)
        out, ng2, ovf = fn(partials, ngs)
        if not np.asarray(jax.device_get(ovf)).any():
            return out, ng2, ovf
        if bucket_cap >= safe_cap:
            raise RuntimeError("groupby shuffle overflow at safe capacity")
        bucket_cap = min(bucket_cap * 4, safe_cap)
