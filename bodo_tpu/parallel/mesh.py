"""Device mesh management.

TPU-native replacement for the reference's MPI process model: one JAX
process per host, all chips joined into a `jax.sharding.Mesh`. Where the
reference derives parallelism from `MPI_Comm_rank/size`
(reference: bodo/libs/distributed_api.py:510, bodo/spawn/spawner.py:134),
we derive it from the mesh: rows are sharded over a single "data" axis,
and collectives ride ICI/DCN via jax.lax primitives under shard_map.
"""

from __future__ import annotations

import contextlib
import os
from functools import lru_cache
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from bodo_tpu.config import config

_active_mesh: Optional[Mesh] = None


def init_runtime() -> None:
    """Initialize the multi-host runtime if launched as one process per host.

    Analogue of the reference spawner bootstrapping MPI
    (bodo/spawn/spawner.py:148-190); here the coordination service is
    jax.distributed's KV store instead of an MPI intercomm.
    """
    # Guard on env vars only: touching jax.process_count() here would
    # initialize the local backend and make distributed.initialize fail.
    if ("JAX_COORDINATOR_ADDRESS" in os.environ
            and int(os.environ.get("JAX_NUM_PROCESSES", "1")) > 1):
        jax.distributed.initialize(
            coordinator_address=os.environ["JAX_COORDINATOR_ADDRESS"],
            num_processes=int(os.environ["JAX_NUM_PROCESSES"]),
            process_id=int(os.environ.get("JAX_PROCESS_ID", "0")),
        )
    # always-on telemetry for the production entry point: start the
    # config-gated sampler and (when telemetry_port >= 0) the
    # /metrics + /healthz endpoint. Best-effort — observability must
    # never fail runtime init.
    try:
        from bodo_tpu.runtime import telemetry
        telemetry.ensure_sampler()
        telemetry.serve()
    except Exception:
        pass


def make_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build the default 1-D data mesh over all addressable devices."""
    devs = np.array(devices if devices is not None else jax.devices())
    return Mesh(devs, axis_names=(config.data_axis,))


def get_mesh() -> Mesh:
    """Return the active mesh (creating the default one lazily)."""
    global _active_mesh
    if _active_mesh is None:
        _active_mesh = make_mesh()
    return _active_mesh


def set_mesh(mesh: Optional[Mesh]) -> None:
    global _active_mesh
    _active_mesh = mesh


@contextlib.contextmanager
def use_mesh(mesh: Mesh):
    global _active_mesh
    prev = _active_mesh
    _active_mesh = mesh
    try:
        yield mesh
    finally:
        _active_mesh = prev


def num_shards(mesh: Optional[Mesh] = None) -> int:
    """Number of row shards — the analogue of MPI world size
    (reference bodo/libs/distributed_api.py `get_size`)."""
    m = mesh or get_mesh()
    return m.shape[config.data_axis]


def row_sharding(mesh: Optional[Mesh] = None) -> NamedSharding:
    """Sharding for a 1-D row-partitioned array (the reference's OneD
    distribution, bodo/transforms/distributed_analysis.py:83)."""
    m = mesh or get_mesh()
    return NamedSharding(m, P(config.data_axis))


def replicated_sharding(mesh: Optional[Mesh] = None) -> NamedSharding:
    """Sharding for a replicated array (the reference's REP distribution)."""
    m = mesh or get_mesh()
    return NamedSharding(m, P())


def data_axis() -> str:
    return config.data_axis
