"""Utilities: verbose logging, tracing, diagnostics."""
