"""Probabilistic sketches: theta (distinct count), bloom (membership),
t-digest (quantiles).

Replaces the reference's sketch libraries (bodo/libs/_theta_sketches.cpp
via Apache DataSketches, _bodo_tdigest.cpp, the bloom filter in
_join_hashing): theta and bloom build on-device with the engine's
splitmix64 hashing (one pass, mergeable across shards — merge is how the
distributed build works: per-shard sketches combine associatively);
t-digest compresses on host (it feeds planner statistics, not the data
path).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from bodo_tpu.ops.hashing import hash_columns


def _hash_u64(data, valid=None):
    h = hash_columns(((data, valid),))
    return h.view(jnp.uint64) if h.dtype != jnp.uint64 else h


# ---------------------------------------------------------------------------
# theta sketch: K smallest normalized hashes -> distinct estimate
# ---------------------------------------------------------------------------

class ThetaSketch:
    """KMV (K minimum values) theta sketch. estimate() ≈ ndv."""

    def __init__(self, k: int = 4096, values: Optional[np.ndarray] = None):
        self.k = k
        self._vals = values if values is not None else \
            np.empty(0, np.uint64)

    @staticmethod
    def build(data, valid=None, k: int = 4096) -> "ThetaSketch":
        h = _hash_u64(data, valid)
        sentinel = np.uint64(0xFFFFFFFFFFFFFFFF)
        if valid is not None:
            h = jnp.where(valid, h, jnp.uint64(sentinel))
        # k smallest DISTINCT hashes: dedupe before truncating, or the
        # smallest slots are dominated by repeats of frequent values
        # (np.unique sorts — no need to pre-sort on device)
        uniq = np.unique(np.asarray(jax.device_get(h)))
        uniq = uniq[uniq != sentinel]  # nulls are not a distinct value
        return ThetaSketch(k, uniq[:k])

    def merge(self, other: "ThetaSketch") -> "ThetaSketch":
        vals = np.unique(np.concatenate([self._vals, other._vals]))[:self.k]
        return ThetaSketch(self.k, vals)

    def estimate(self) -> float:
        m = len(self._vals)
        if m == 0:
            return 0.0
        if m < self.k:  # exact regime
            return float(m)
        theta = float(self._vals[self.k - 1]) / float(2**64)
        return (self.k - 1) / max(theta, 1e-300)


# ---------------------------------------------------------------------------
# bloom filter
# ---------------------------------------------------------------------------

class BloomFilter:
    """Split bloom filter: d hash probes into an m-bit array (device
    scatter build, device gather probe — usable as a join prefilter)."""

    def __init__(self, m_bits: int = 1 << 20, d: int = 4,
                 bits: Optional[jnp.ndarray] = None):
        self.m = m_bits
        self.d = d
        self.bits = bits if bits is not None else \
            jnp.zeros((m_bits,), dtype=bool)

    def add(self, data, valid=None) -> "BloomFilter":
        h = _hash_u64(data, valid)
        bits = self.bits
        for i in range(self.d):
            idx = ((h >> jnp.uint64(i * 13)).astype(jnp.uint32)
                   % jnp.uint32(self.m)).astype(jnp.int32)
            if valid is not None:
                idx = jnp.where(valid, idx, self.m)  # dropped
            bits = bits.at[idx].set(True, mode="drop")
        return BloomFilter(self.m, self.d, bits)

    def contains(self, data):
        h = _hash_u64(data)
        ok = jnp.ones(h.shape, dtype=bool)
        for i in range(self.d):
            idx = ((h >> jnp.uint64(i * 13)).astype(jnp.uint32)
                   % jnp.uint32(self.m)).astype(jnp.int32)
            ok = ok & self.bits[idx]
        return ok

    def merge(self, other: "BloomFilter") -> "BloomFilter":
        return BloomFilter(self.m, self.d, self.bits | other.bits)


# ---------------------------------------------------------------------------
# t-digest (host): mergeable quantile sketch
# ---------------------------------------------------------------------------

class TDigest:
    """Simplified merging t-digest (Dunning): centroids kept under the
    k1 scale-function size bound; add/merge/quantile. Host-side numpy —
    it summarizes columns for planner statistics."""

    def __init__(self, compression: float = 100.0):
        self.compression = compression
        self.means = np.empty(0)
        self.weights = np.empty(0)

    def add(self, values: np.ndarray) -> "TDigest":
        v = np.asarray(values, dtype=np.float64)
        v = v[~np.isnan(v)]
        if len(v) == 0:
            return self
        self.means = np.concatenate([self.means, v])
        self.weights = np.concatenate([self.weights, np.ones(len(v))])
        self._compress()
        return self

    def merge(self, other: "TDigest") -> "TDigest":
        out = TDigest(self.compression)
        out.means = np.concatenate([self.means, other.means])
        out.weights = np.concatenate([self.weights, other.weights])
        out._compress()
        return out

    def _compress(self):
        if len(self.means) <= self.compression:
            order = np.argsort(self.means, kind="stable")
            self.means, self.weights = self.means[order], \
                self.weights[order]
            return
        order = np.argsort(self.means, kind="stable")
        means, weights = self.means[order], self.weights[order]
        total = weights.sum()
        # q-limits from the k1 scale function
        n_cent = int(self.compression)
        qlim = np.sin(np.linspace(-np.pi / 2, np.pi / 2, n_cent + 1))
        qlim = (qlim + 1) / 2
        cum = np.cumsum(weights) / total
        bucket = np.clip(np.searchsorted(qlim, cum, side="left") - 1,
                         0, n_cent - 1)
        new_m = np.zeros(n_cent)
        new_w = np.zeros(n_cent)
        np.add.at(new_w, bucket, weights)
        np.add.at(new_m, bucket, weights * means)
        keep = new_w > 0
        self.means = new_m[keep] / new_w[keep]
        self.weights = new_w[keep]

    def quantile(self, q: float) -> float:
        if len(self.means) == 0:
            return float("nan")
        cum = np.cumsum(self.weights) - self.weights / 2
        target = q * self.weights.sum()
        return float(np.interp(target, cum, self.means))
