"""Chrome-trace event tracing + per-operator query profile.

Analogue of the reference's tracing/profiling stack
(bodo/utils/tracing.pyx Event/dump — Chrome trace JSON;
bodo/libs/_query_profile_collector.h per-operator TIMER/STAT metrics).
Enabled via BODO_TPU_TRACING_LEVEL >= 1 (config.tracing_level); the plan
executor wraps every physical operator in an event, so `dump()` yields a
chrome://tracing-loadable timeline and `profile()` the per-operator
aggregate table.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from collections import defaultdict
from typing import Dict, List, Optional

from bodo_tpu.config import config

_events: List[dict] = []
_agg: Dict[str, dict] = defaultdict(lambda: {"count": 0, "total_s": 0.0,
                                             "max_s": 0.0, "rows": 0})
_lock = threading.Lock()


def is_tracing() -> bool:
    return config.tracing_level >= 1


@contextlib.contextmanager
def event(name: str, **args):
    """Trace one operator/phase. Cheap no-op when tracing is off."""
    if not is_tracing():
        yield None
        return
    t0 = time.perf_counter()
    ts = time.time() * 1e6
    info: dict = {}
    try:
        yield info
    finally:
        dur = time.perf_counter() - t0
        with _lock:
            _events.append({
                "name": name, "ph": "X", "ts": ts, "dur": dur * 1e6,
                "pid": os.getpid(), "tid": threading.get_ident() % 100000,
                "args": {**args, **info},
            })
            a = _agg[name]
            a["count"] += 1
            a["total_s"] += dur
            a["max_s"] = max(a["max_s"], dur)
            a["rows"] += int(info.get("rows", 0))


def reset() -> None:
    with _lock:
        _events.clear()
        _agg.clear()


def dump(path: Optional[str] = None) -> str:
    """Write chrome-trace JSON (load in chrome://tracing / Perfetto).
    Includes a `memory` section with the governor's derived budget and
    per-operator granted/peak/spilled bytes, a `resilience` section with
    fault/retry/degradation counters, an `aqe` section with adaptive
    decision counters + q-error summary, an `io` section with prefetch
    decode/stall/overlap and footer-cache counters, an `analysis`
    section with the shardcheck plan-validator/lint/lockstep counters,
    and `compile_cache` hit/miss counts when the persistent jit cache
    is active."""
    out = {"traceEvents": list(_events), "displayTimeUnit": "ms",
           "memory": memory_stats(), "resilience": resilience_stats(),
           "aqe": aqe_stats(), "io": io_stats(),
           "analysis": analysis_stats()}
    cc = compile_cache_stats()
    if cc["hits"] or cc["misses"]:
        out["compile_cache"] = cc
    text = json.dumps(out)
    if path:
        with open(path, "w") as f:
            f.write(text)
    return text


def memory_stats() -> dict:
    """Memory-governor snapshot (derived budget + per-operator bytes)."""
    from bodo_tpu.runtime.memory_governor import governor
    return governor().stats()


def resilience_stats() -> dict:
    """Fault-injection / retry / degradation counter snapshot."""
    from bodo_tpu.runtime import resilience
    return resilience.stats()


def aqe_stats() -> dict:
    """Adaptive-execution snapshot: decision counters + q-error summary."""
    from bodo_tpu.plan import adaptive
    return adaptive.stats()


def io_stats() -> dict:
    """Pipelined-I/O snapshot: prefetch decode/stall seconds, hit and
    depth counters, footer-cache hits, parallel decode units, and the
    derived overlap ratio (runtime/io_pool.py)."""
    from bodo_tpu.runtime import io_pool
    return io_pool.io_stats()


def analysis_stats() -> dict:
    """Shardcheck snapshot: plan-validator plans/nodes/violations,
    lint run counters, and lockstep dispatch/wait/divergence counters
    (analysis/)."""
    from bodo_tpu.analysis import lint, lockstep, plan_validator
    return {"plan_validator": plan_validator.stats(),
            "lint": lint.stats(), "lockstep": lockstep.stats()}


# persistent-compile-cache observability: jax's monitoring module emits
# /jax/compilation_cache/cache_hits|cache_misses events when
# jax_compilation_cache_dir is set; we fold them into hit/miss counters
_cc_lock = threading.Lock()
_cc_counts = {"hits": 0, "misses": 0}
_cc_installed = False


def install_compile_cache_listener() -> None:
    """Idempotently subscribe to jax's compilation-cache events so the
    profile can report persistent jit-cache hits/misses. Safe to call on
    jax builds without the monitoring hooks (silently does nothing)."""
    global _cc_installed
    # check-and-set under the lock: two racing installers would
    # register two listeners and double-count every cache event
    with _cc_lock:
        if _cc_installed:
            return
        _cc_installed = True
    try:
        from jax._src import monitoring

        def _listen(event: str, *a, **kw) -> None:
            if event.endswith("/cache_hits"):
                with _cc_lock:
                    _cc_counts["hits"] += 1
            elif event.endswith("/cache_misses"):
                with _cc_lock:
                    _cc_counts["misses"] += 1

        monitoring.register_event_listener(_listen)
    except Exception:
        pass


def compile_cache_stats() -> dict:
    with _cc_lock:
        return dict(_cc_counts)


def profile() -> Dict[str, dict]:
    """Per-operator aggregate metrics (query-profile-collector analogue).
    Operators the memory governor tracked additionally carry
    granted/peak/spilled bytes under a `mem:<operator>` key; resilience
    counters (fired faults, retries, degraded stages, gang retries)
    appear under `resil:<counter>` keys; the pipelined-I/O layer
    contributes `io:*` counter rows plus time-valued `io:decode`,
    `io:stall`, and `io:overlap` rows (overlap = decode hidden behind
    consumer compute); shardcheck contributes `lint:*` counters
    (plans validated/violations, lint findings) and a time-valued
    `lockstep:check` row (dispatches fingerprinted + peer-wait
    seconds) plus `lockstep:mismatches`/`lockstep:timeouts`."""
    out = {k: dict(v) for k, v in _agg.items()}
    for name, m in memory_stats().get("operators", {}).items():
        out[f"mem:{name}"] = {
            "count": m.get("count", 0), "total_s": 0.0, "max_s": 0.0,
            "rows": 0, "granted_bytes": m.get("granted", 0),
            "peak_bytes": m.get("peak", 0),
            "spilled_bytes": m.get("spilled_bytes", 0),
            "n_spills": m.get("n_spills", 0)}
    rs = resilience_stats()
    counters = {}
    for point, n in rs.get("faults_fired", {}).items():
        counters[f"resil:fault:{point}"] = n
    for label, n in rs.get("retries", {}).items():
        counters[f"resil:retry:{label}"] = n
    for stage, n in rs.get("degraded_stages", {}).items():
        counters[f"resil:degraded:{stage}"] = n
    if rs.get("gang_retries"):
        counters["resil:gang_retries"] = rs["gang_retries"]
    aq = aqe_stats()
    for decision, n in aq.get("decisions", {}).items():
        counters[f"aqe:{decision}"] = n
    ios = io_stats()
    for key in ("prefetch_hits", "prefetch_streams", "prefetch_depth",
                "stalls", "footer_hits", "footer_misses",
                "parallel_units", "parallel_reads", "decode_batches"):
        counters[f"io:{key}"] = ios.get(key, 0)
    # time-valued io rows: decode seconds (worker-side), consumer stall
    # seconds, and the decode time hidden behind compute
    if ios.get("decode_batches"):
        out["io:decode"] = {"count": int(ios["decode_batches"]),
                            "total_s": ios["decode_s"], "max_s": 0.0,
                            "rows": 0, "bytes": int(ios["decode_bytes"])}
        out["io:stall"] = {"count": int(ios["stalls"]),
                           "total_s": ios["stall_s"], "max_s": 0.0,
                           "rows": 0}
        out["io:overlap"] = {"count": int(ios["decode_batches"]),
                             "total_s": ios["overlap_s"], "max_s": 0.0,
                             "rows": 0,
                             "ratio": round(ios["overlap_ratio"], 4)}
    an = analysis_stats()
    pv = an["plan_validator"]
    if pv.get("plans"):
        counters["lint:plan_validated"] = pv["plans"]
        counters["lint:plan_violations"] = pv["violations"]
    if an["lint"].get("findings"):
        counters["lint:findings"] = an["lint"]["findings"]
    ls = an["lockstep"]
    for key in ("mismatches", "timeouts"):
        if ls.get(key):
            counters[f"lockstep:{key}"] = ls[key]
    for key, n in counters.items():
        if n:
            out[key] = {"count": int(n), "total_s": 0.0, "max_s": 0.0,
                        "rows": 0}
    # time-valued lockstep row: dispatches checked + peer-wait seconds
    if ls.get("collectives"):
        out["lockstep:check"] = {"count": int(ls["collectives"]),
                                 "total_s": ls["wait_s"],
                                 "max_s": ls["max_wait_s"], "rows": 0}
    qe = aq.get("q_error", {})
    if qe.get("count"):
        out["aqe:q_error"] = {
            "count": int(qe["count"]), "total_s": 0.0, "max_s": 0.0,
            "rows": 0, "mean": qe.get("mean"), "p50": qe.get("p50"),
            "p90": qe.get("p90"), "max": qe.get("max")}
    cc = compile_cache_stats()
    if cc["hits"] or cc["misses"]:
        out["cache:compile"] = {
            "count": cc["hits"] + cc["misses"], "total_s": 0.0,
            "max_s": 0.0, "rows": 0, "hits": cc["hits"],
            "misses": cc["misses"]}
    return out


_op_depth = threading.local()


def traced_table_op(fn):
    """Wrap a Table-returning operator so every call (through ANY entry
    point — executor, streaming, or direct relational calls) lands in
    the per-operator profile with a rows count. Only the OUTERMOST
    traced frame records (operators re-enter each other — distributed
    groupby calls local groupby, windows call sort — and double-counting
    would make profile totals exceed wall time). No-op when tracing is
    off (one predicate check)."""
    import functools

    @functools.wraps(fn)
    def wrapper(*a, **k):
        if not is_tracing():
            return fn(*a, **k)
        depth = getattr(_op_depth, "d", 0)
        if depth:
            return fn(*a, **k)
        _op_depth.d = 1
        try:
            with event(fn.__name__) as ev:
                t = fn(*a, **k)
                rows = getattr(t, "nrows", None)
                if rows is not None and ev is not None:
                    ev["rows"] = rows
                return t
        finally:
            _op_depth.d = 0
    return wrapper
