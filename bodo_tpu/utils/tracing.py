"""Chrome-trace event tracing + per-operator / per-query profile.

Analogue of the reference's tracing/profiling stack
(bodo/utils/tracing.pyx Event/dump — Chrome trace JSON;
bodo/libs/_query_profile_collector.h per-operator TIMER/STAT metrics).
Enabled via BODO_TPU_TRACING_LEVEL >= 1 (config.tracing_level); the plan
executor wraps every physical operator in an event, so `dump()` yields a
chrome://tracing-loadable timeline and `profile()` the per-operator
aggregate table.

Query scoping: a `query_span()` context assigns every event inside it a
query id (contextvar; exported as BODO_TPU_QUERY_ID so spawned gang
workers inherit the same identity), and the per-operator aggregates are
additionally keyed per query — `profile(query_id=...)` / `top_ops()`
answer "where did THIS query's time go", the accounting unit the
multi-tenant serving layer (ROADMAP item 2) schedules by.

Clock discipline: every event derives BOTH its timestamp and duration
from `time.perf_counter()` against one per-process wall-clock anchor
captured at import — timestamps are epoch-comparable across the ranks
of a gang (for `merge_trace_shards`) while durations stay monotonic.
Thread ids are mapped through a stable small-int table (raw
`threading.get_ident()` values are reused by the OS and collide when
truncated).

The event list is a ring buffer (BODO_TPU_TRACE_EVENTS_MAX, drop-oldest)
so long-running sessions cannot leak; dropped events are counted and
reported in `dump()`.

Counter-valued profile rows (`mem:`/`resil:`/`aqe:`/`io:`/`lint:`/
`lockstep:`/`cache:`) are read from the unified metrics registry
(utils/metrics.py `sync_engine_metrics`), which is also what the bench
JSON and the Prometheus exposition serve.
"""

from __future__ import annotations

import contextlib
import contextvars
import glob as _glob
import json
import os
import sys
import threading
import time
from collections import OrderedDict, deque
from typing import Dict, List, Optional, Tuple

from bodo_tpu.config import config

_lock = threading.Lock()

# one clock anchor per process: ts AND dur derive from perf_counter so a
# ts is never skewed against its own duration; the wall part makes ts
# epoch-comparable across the ranks of a gang
_ANCHOR_WALL = time.time()
_ANCHOR_PERF = time.perf_counter()


def _ts_us(perf_t: float) -> float:
    return (_ANCHOR_WALL + (perf_t - _ANCHOR_PERF)) * 1e6


def _new_events() -> deque:
    n = max(int(config.trace_events_max), 1)
    return deque(maxlen=n)


_events: deque = _new_events()
_dropped = 0
# per-(query, operator) aggregates; query None = outside any span
_agg: Dict[Tuple[Optional[str], str], dict] = {}
# stable small-int thread ids (get_ident values are reused/collide)
_tids: Dict[int, int] = {}
# completed query spans: qid -> {"wall_s": ...} (insertion-ordered)
_query_meta: "OrderedDict[str, dict]" = OrderedDict()
_MAX_QUERY_META = 256


def is_tracing() -> bool:
    return config.tracing_level >= 1


# ---------------------------------------------------------------------------
# query identity
# ---------------------------------------------------------------------------

_QID_ENV = "BODO_TPU_QUERY_ID"
_query_ctx: contextvars.ContextVar[Optional[str]] = \
    contextvars.ContextVar("bodo_tpu_query_id", default=None)
_qid_counter = [0]


def new_query_id(prefix: str = "q") -> str:
    with _lock:
        _qid_counter[0] += 1
        n = _qid_counter[0]
    return f"{prefix}{os.getpid()}-{n}"


def current_query_id() -> Optional[str]:
    """The active query id: the innermost `query_span` on this thread,
    else the gang-inherited BODO_TPU_QUERY_ID (set by the spawner so
    worker-side events carry the parent query's identity)."""
    q = _query_ctx.get()
    if q is not None:
        return q
    return os.environ.get(_QID_ENV) or None


@contextlib.contextmanager
def query_span(query_id: Optional[str] = None, env_export: bool = True):
    """Scope everything inside to one query id. Nested spans shadow the
    outer id (contextvar semantics); `env_export` additionally publishes
    the id to the environment so gangs spawned inside the span inherit
    it. Yields the query id."""
    qid = query_id or new_query_id()
    tok = _query_ctx.set(qid)
    prev_env = os.environ.get(_QID_ENV)
    if env_export:
        os.environ[_QID_ENV] = qid
    t0 = time.perf_counter()
    try:
        yield qid
    finally:
        _query_ctx.reset(tok)
        if env_export:
            if prev_env is None:
                os.environ.pop(_QID_ENV, None)
            else:
                os.environ[_QID_ENV] = prev_env
        wall = time.perf_counter() - t0
        # device-buffer leak check: the observatory closes this query's
        # HBM ledger entry (created/freed/live bytes) at span exit
        dev = None
        ob = sys.modules.get("bodo_tpu.runtime.xla_observatory")
        if ob is not None:
            try:
                dev = ob.finish_query(qid)
            except Exception:
                dev = None
        with _lock:
            meta = _query_meta.setdefault(qid, {"wall_s": 0.0})
            meta["wall_s"] += wall
            if dev is not None and dev.get("buffers"):
                meta["device_bytes"] = {
                    "created": dev["created_bytes"],
                    "freed": dev["freed_bytes"],
                    "live": dev["live_bytes"]}
            while len(_query_meta) > _MAX_QUERY_META:
                _query_meta.popitem(last=False)


def query_ids() -> List[str]:
    """Query ids seen by completed spans, oldest first."""
    with _lock:
        return list(_query_meta)


def _seen_query_ids_locked() -> List[str]:
    """All query ids this process traced under: completed spans first,
    then ids only seen via inherited context (a gang worker tagging
    events with the spawner's exported id never opens its own span)."""
    seen = list(_query_meta)
    extra = sorted({q for q, _ in _agg
                    if q is not None and q not in _query_meta})
    return seen + extra


def query_wall_s(qid: str) -> Optional[float]:
    with _lock:
        m = _query_meta.get(qid)
        return m["wall_s"] if m else None


# ---------------------------------------------------------------------------
# events
# ---------------------------------------------------------------------------

@contextlib.contextmanager
def event(name: str, **args):
    """Trace one operator/phase. Cheap no-op when tracing is off. The
    active query id (if any) is attached to the event and keys the
    per-query aggregate row."""
    if not is_tracing():
        yield None
        return
    t0 = time.perf_counter()
    qid = current_query_id()
    info: dict = {}
    try:
        yield info
    finally:
        t1 = time.perf_counter()
        dur = t1 - t0
        global _dropped
        ev_args = {**args, **info}
        if qid is not None:
            ev_args["query_id"] = qid
        with _lock:
            ident = threading.get_ident()
            tid = _tids.get(ident)
            if tid is None:
                tid = _tids[ident] = len(_tids)
            if _events.maxlen is not None and \
                    len(_events) == _events.maxlen:
                _dropped += 1
            _events.append({
                "name": name, "ph": "X", "ts": _ts_us(t0),
                "dur": dur * 1e6, "pid": os.getpid(), "tid": tid,
                "args": ev_args,
            })
            a = _agg.get((qid, name))
            if a is None:
                a = _agg[(qid, name)] = {"count": 0, "total_s": 0.0,
                                         "max_s": 0.0, "rows": 0}
            a["count"] += 1
            a["total_s"] += dur
            a["max_s"] = max(a["max_s"], dur)
            a["rows"] += int(info.get("rows", 0))


def reset() -> None:
    global _dropped
    with _lock:
        _events.clear()
        _agg.clear()
        _tids.clear()
        _query_meta.clear()
        _dropped = 0


def resize_events_buffer() -> None:
    """Rebuild the ring buffer at the current config.trace_events_max
    (keeps the newest events; called by set_config)."""
    global _events
    with _lock:
        old = list(_events)
        _events = _new_events()
        _events.extend(old[-_events.maxlen:])


def has_events() -> bool:
    with _lock:
        return bool(_events)


def dropped_events() -> int:
    with _lock:
        return _dropped


def query_agg() -> Dict[Tuple[Optional[str], str], dict]:
    """Copy of the per-(query, operator) aggregates (metrics registry
    sync reads this to publish per-query-labelled counters)."""
    with _lock:
        return {k: dict(v) for k, v in _agg.items()}


# ---------------------------------------------------------------------------
# dump + cross-rank merge
# ---------------------------------------------------------------------------

def dump(path: Optional[str] = None) -> str:
    """Write chrome-trace JSON (load in chrome://tracing / Perfetto).
    Includes a `memory` section with the governor's derived budget and
    per-operator granted/peak/spilled bytes, a `resilience` section with
    fault/retry/degradation counters, an `aqe` section with adaptive
    decision counters + q-error summary, an `io` section with prefetch
    decode/stall/overlap and footer-cache counters, an `analysis`
    section with the shardcheck plan-validator/lint/lockstep counters,
    a `metrics` section with the unified registry snapshot
    (utils/metrics.py), `compile_cache` hit/miss counts when the
    persistent jit cache is active, plus ring-buffer accounting
    (`dropped_events`) and the query ids the events belong to."""
    from bodo_tpu.utils import metrics
    with _lock:
        events = list(_events)
        dropped = _dropped
        qids = _seen_query_ids_locked()
    out = {"traceEvents": events, "displayTimeUnit": "ms",
           "memory": memory_stats(), "resilience": resilience_stats(),
           "aqe": aqe_stats(), "io": io_stats(),
           "analysis": analysis_stats(),
           "metrics": metrics.snapshot(),
           "dropped_events": dropped,
           "query_ids": qids}
    cc = compile_cache_stats()
    if cc["hits"] or cc["misses"]:
        out["compile_cache"] = cc
    text = json.dumps(out)
    if path:
        with open(path, "w") as f:
            f.write(text)
    return text


def _shard_rank() -> int:
    v = os.environ.get("BODO_TPU_PROC_ID")
    if v not in (None, ""):
        return int(v)
    return 0


def dump_shard(dirpath: str, rank: Optional[int] = None) -> str:
    """Write this process's raw trace shard into a gang-shared directory
    (spawn.py points workers at the gang temp dir). Shards carry the
    clock anchor + rank so `merge_trace_shards` can build one multi-rank
    timeline. Returns the shard path."""
    if rank is None:
        rank = _shard_rank()
    with _lock:
        events = list(_events)
        dropped = _dropped
        qids = _seen_query_ids_locked()
    payload = {"rank": int(rank), "pid": os.getpid(),
               "anchor_wall": _ANCHOR_WALL, "dropped_events": dropped,
               "query_ids": qids, "traceEvents": events}
    path = os.path.join(dirpath, f"trace_shard_{int(rank)}.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, path)
    return path


def merge_trace_shards(dirpath: str,
                       out_path: Optional[str] = None) -> Optional[dict]:
    """Merge per-rank `trace_shard_*.json` files into ONE Perfetto
    timeline: each rank becomes a process lane (pid = rank, with
    process_name/process_sort_index metadata), and all timestamps are
    normalized to the earliest event across the gang so the ranks line
    up on a common zero. Deterministic: shards are read in rank order
    and events sorted by (ts, rank, tid, name). Returns the merged dict
    (written to `out_path` when given), or None when no shards exist."""
    paths = sorted(_glob.glob(os.path.join(dirpath, "trace_shard_*.json")))
    if not paths:
        return None
    shards = []
    for p in paths:
        try:
            with open(p) as f:
                shards.append(json.load(f))
        except (OSError, ValueError):  # truncated shard: skip, keep rest
            continue
    if not shards:
        return None
    shards.sort(key=lambda s: s.get("rank", 0))
    origin = min((e["ts"] for s in shards for e in s["traceEvents"]),
                 default=0.0)
    merged: List[dict] = []
    qids: List[str] = []
    dropped = 0
    for s in shards:
        rank = int(s.get("rank", 0))
        dropped += int(s.get("dropped_events", 0))
        for q in s.get("query_ids", []):
            if q not in qids:
                qids.append(q)
        merged.append({"name": "process_name", "ph": "M", "pid": rank,
                       "tid": 0,
                       "args": {"name": f"rank {rank} "
                                        f"(pid {s.get('pid')})"}})
        merged.append({"name": "process_sort_index", "ph": "M",
                       "pid": rank, "tid": 0,
                       "args": {"sort_index": rank}})
        for e in s["traceEvents"]:
            e = dict(e)
            e["pid"] = rank
            e["ts"] = round(e["ts"] - origin, 3)
            merged.append(e)
    meta = [e for e in merged if e["ph"] == "M"]
    rest = sorted((e for e in merged if e["ph"] != "M"),
                  key=lambda e: (e["ts"], e["pid"], e.get("tid", 0),
                                 e["name"]))
    out = {"traceEvents": meta + rest, "displayTimeUnit": "ms",
           "ranks": len(shards), "origin_us": origin,
           "query_ids": qids, "dropped_events": dropped}
    if out_path:
        tmp = out_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(out, f)
        os.replace(tmp, out_path)
    return out


# ---------------------------------------------------------------------------
# subsystem snapshots (legacy dict shapes; the metrics registry is the
# canonical consumer-facing surface)
# ---------------------------------------------------------------------------

def memory_stats() -> dict:
    """Memory-governor snapshot (derived budget + per-operator bytes)."""
    from bodo_tpu.runtime.memory_governor import governor
    return governor().stats()


def resilience_stats() -> dict:
    """Fault-injection / retry / degradation counter snapshot."""
    from bodo_tpu.runtime import resilience
    return resilience.stats()


def aqe_stats() -> dict:
    """Adaptive-execution snapshot: decision counters + q-error summary."""
    from bodo_tpu.plan import adaptive
    return adaptive.stats()


def io_stats() -> dict:
    """Pipelined-I/O snapshot: prefetch decode/stall seconds, hit and
    depth counters, footer-cache hits, parallel decode units, and the
    derived overlap ratio (runtime/io_pool.py)."""
    from bodo_tpu.runtime import io_pool
    return io_pool.io_stats()


def analysis_stats() -> dict:
    """Shardcheck snapshot: plan-validator plans/nodes/violations,
    lint run counters, and lockstep dispatch/wait/divergence counters
    (analysis/)."""
    from bodo_tpu.analysis import lint, lockstep, plan_validator
    return {"plan_validator": plan_validator.stats(),
            "lint": lint.stats(), "lockstep": lockstep.stats()}


# persistent-compile-cache observability: jax's monitoring module emits
# /jax/compilation_cache/cache_hits|cache_misses events when
# jax_compilation_cache_dir is set; we fold them into hit/miss counters
_cc_lock = threading.Lock()
_cc_counts = {"hits": 0, "misses": 0}
_cc_installed = False


def install_compile_cache_listener() -> None:
    """Idempotently subscribe to jax's compilation-cache events so the
    profile can report persistent jit-cache hits/misses. Safe to call on
    jax builds without the monitoring hooks (silently does nothing)."""
    global _cc_installed
    # check-and-set under the lock: two racing installers would
    # register two listeners and double-count every cache event
    with _cc_lock:
        if _cc_installed:
            return
        _cc_installed = True
    try:
        from jax._src import monitoring

        def _listen(event: str, *a, **kw) -> None:
            if event.endswith("/cache_hits"):
                with _cc_lock:
                    _cc_counts["hits"] += 1
            elif event.endswith("/cache_misses"):
                with _cc_lock:
                    _cc_counts["misses"] += 1

        monitoring.register_event_listener(_listen)
    except Exception:
        pass


def compile_cache_stats() -> dict:
    with _cc_lock:
        return dict(_cc_counts)


# ---------------------------------------------------------------------------
# profile
# ---------------------------------------------------------------------------

def profile(query_id: Optional[str] = None) -> Dict[str, dict]:
    """Per-operator aggregate metrics (query-profile-collector analogue).
    With `query_id`, only that query's operator rows are returned (the
    counter rows below are process-wide either way). Operators the
    memory governor tracked additionally carry granted/peak/spilled
    bytes under a `mem:<operator>` key; resilience counters (fired
    faults, retries, degraded stages, gang retries) appear under
    `resil:<counter>` keys; the pipelined-I/O layer contributes `io:*`
    counter rows plus time-valued `io:decode`, `io:stall`, and
    `io:overlap` rows (overlap = decode hidden behind consumer
    compute); shardcheck contributes `lint:*` counters (plans
    validated/violations, lint findings) and a time-valued
    `lockstep:check` row (dispatches fingerprinted + peer-wait seconds)
    plus `lockstep:mismatches`/`lockstep:timeouts`; the static program
    verifier contributes a time-valued `progcheck:check` row (programs
    verified + verification seconds + the largest static HBM peak
    estimate) and a `progcheck:violations` counter; whole-stage fusion
    contributes `fusion:*` counter rows plus `fusion:cache`
    (hit/miss) and a time-valued `fusion:compile` row; the comm
    observatory contributes per-collective `comm:<op>` rows carrying
    bytes in/out and the host-wall vs peer-wait split. All counter
    rows are sourced from the unified metrics registry."""
    from bodo_tpu.utils import metrics
    out: Dict[str, dict] = {}
    with _lock:
        for (qid, name), v in _agg.items():
            if query_id is not None and qid != query_id:
                continue
            a = out.get(name)
            if a is None:
                out[name] = dict(v)
            else:
                a["count"] += v["count"]
                a["total_s"] += v["total_s"]
                a["max_s"] = max(a["max_s"], v["max_s"])
                a["rows"] += v["rows"]
    metrics.sync_engine_metrics()

    def series(name: str) -> Dict[Tuple[str, ...], float]:
        m = metrics.registry().get(name)
        return m.series() if m is not None else {}

    mem_bytes = series("bodo_tpu_mem_operator_bytes")
    mem_events = series("bodo_tpu_mem_operator_events")
    for (op, kind), v in mem_bytes.items():
        row = out.setdefault(f"mem:{op}", {
            "count": 0, "total_s": 0.0, "max_s": 0.0, "rows": 0,
            "granted_bytes": 0, "peak_bytes": 0, "spilled_bytes": 0,
            "n_spills": 0})
        row[f"{kind}_bytes"] = int(v)
    for (op, kind), v in mem_events.items():
        row = out.get(f"mem:{op}")
        if row is not None:
            row["count" if kind == "count" else "n_spills"] = int(v)
    counters: Dict[str, float] = {}
    for (point,), n in series("bodo_tpu_resil_faults_fired_total").items():
        counters[f"resil:fault:{point}"] = n
    for (label,), n in series("bodo_tpu_resil_retries_total").items():
        counters[f"resil:retry:{label}"] = n
    for (stage,), n in \
            series("bodo_tpu_resil_degraded_stages_total").items():
        counters[f"resil:degraded:{stage}"] = n
    gr = series("bodo_tpu_resil_gang_retries_total").get((), 0)
    if gr:
        counters["resil:gang_retries"] = gr
    for (decision,), n in series("bodo_tpu_aqe_decisions_total").items():
        counters[f"aqe:{decision}"] = n
    ios = series("bodo_tpu_io_events_total")
    for key in ("prefetch_hits", "prefetch_streams", "prefetch_depth",
                "stalls", "footer_hits", "footer_misses",
                "parallel_units", "parallel_reads", "decode_batches",
                "device_decode_pages", "device_decode_cols",
                "device_fallback_cols", "device_decode_errors"):
        counters[f"io:{key}"] = ios.get((key,), 0)
    # time-valued io rows: decode seconds (worker-side), consumer stall
    # seconds, and the decode time hidden behind compute
    io_s = series("bodo_tpu_io_seconds")
    if ios.get(("decode_batches",)):
        out["io:decode"] = {"count": int(ios[("decode_batches",)]),
                            "total_s": io_s.get(("decode",), 0.0),
                            "max_s": 0.0, "rows": 0,
                            "bytes": int(ios.get(("decode_bytes",), 0))}
        out["io:stall"] = {"count": int(ios.get(("stalls",), 0)),
                           "total_s": io_s.get(("stall",), 0.0),
                           "max_s": 0.0, "rows": 0}
        ratio = series("bodo_tpu_io_overlap_ratio").get((), 0.0)
        out["io:overlap"] = {"count": int(ios[("decode_batches",)]),
                             "total_s": io_s.get(("overlap",), 0.0),
                             "max_s": 0.0, "rows": 0,
                             "ratio": round(ratio, 4)}
    # device-side parquet decode: page programs dispatched, on-chip
    # decode seconds, decoded bytes, and the device fraction of all
    # decoded scan output
    if ios.get(("device_decode_pages",)) or \
            ios.get(("device_fallback_cols",)):
        frac = series("bodo_tpu_scan_device_decode_frac").get((), 0.0)
        out["io:device_decode"] = {
            "count": int(ios.get(("device_decode_pages",), 0)),
            "total_s": io_s.get(("device_decode",), 0.0),
            "max_s": 0.0, "rows": 0,
            "bytes": int(ios.get(("device_decode_bytes",), 0)),
            "frac": round(frac, 4)}
    pv = series("bodo_tpu_plans_validated_total").get((), 0)
    if pv:
        counters["lint:plan_validated"] = pv
        counters["lint:plan_violations"] = \
            series("bodo_tpu_plan_violations_total").get((), 0)
    lf = series("bodo_tpu_lint_findings_total").get((), 0)
    if lf:
        counters["lint:findings"] = lf
    for key in ("mismatches", "timeouts"):
        n = series(f"bodo_tpu_lockstep_{key}_total").get((), 0)
        if n:
            counters[f"lockstep:{key}"] = n
    for key, n in counters.items():
        if n:
            out[key] = {"count": int(n), "total_s": 0.0, "max_s": 0.0,
                        "rows": 0}
    # whole-stage fusion: per-kind counters plus a time-valued
    # fusion:compile row (fused programs built + compile wall seconds)
    fus = series("bodo_tpu_fusion_events_total")
    if any(fus.values()):
        for key in ("groups_planned", "groups_executed", "stream_chains",
                    "partial_agg", "fallbacks", "donated",
                    "device_scan_batches"):
            n = fus.get((key,), 0)
            if n:
                out[f"fusion:{key}"] = {"count": int(n), "total_s": 0.0,
                                        "max_s": 0.0, "rows": 0}
        out["fusion:cache"] = {
            "count": int(fus.get(("hits",), 0)
                         + fus.get(("misses",), 0)),
            "total_s": 0.0, "max_s": 0.0, "rows": 0,
            "hits": int(fus.get(("hits",), 0)),
            "misses": int(fus.get(("misses",), 0))}
        out["fusion:compile"] = {
            "count": int(fus.get(("compiles",), 0)),
            "total_s": series("bodo_tpu_fusion_compile_seconds").get(
                (), 0.0),
            "max_s": 0.0, "rows": 0}
    # compile & device-memory observatory: per-subsystem executable
    # populations with compile wall (time-valued), retrace causes, and
    # the live device-byte ledger
    xe = series("bodo_tpu_xla_executables")
    xc = series("bodo_tpu_xla_compile_seconds")
    xd = series("bodo_tpu_xla_dispatches_total")
    for (sub,), n in xe.items():
        if n:
            out[f"xla:{sub}"] = {
                "count": int(n), "total_s": xc.get((sub,), 0.0),
                "max_s": 0.0, "rows": 0,
                "dispatches": int(xd.get((sub,), 0))}
    for (cause,), n in series("bodo_tpu_xla_retraces_total").items():
        if n:
            out[f"xla:retrace:{cause}"] = {
                "count": int(n), "total_s": 0.0, "max_s": 0.0,
                "rows": 0}
    created = series("bodo_tpu_device_bytes_created_total").get((), 0)
    if created:
        freed = series("bodo_tpu_device_bytes_freed_total").get((), 0)
        out["xla:device_bytes"] = {
            "count": int(series("bodo_tpu_device_buffers_live")
                         .get((), 0)),
            "total_s": 0.0, "max_s": 0.0, "rows": 0,
            "created_bytes": int(created), "freed_bytes": int(freed),
            "live_bytes": int(created - freed)}
    # time-valued lockstep row: dispatches checked + peer-wait seconds
    lc = series("bodo_tpu_lockstep_collectives_total").get((), 0)
    if lc:
        out["lockstep:check"] = {
            "count": int(lc),
            "total_s": series("bodo_tpu_lockstep_wait_seconds").get(
                (), 0.0),
            "max_s": series("bodo_tpu_lockstep_max_wait_seconds").get(
                (), 0.0),
            "rows": 0}
    # time-valued progcheck row: programs statically verified at
    # registration + verification wall seconds, and the violation
    # counter when any invariant failed
    pcn = series("bodo_tpu_progcheck_programs_total").get((), 0)
    if pcn:
        out["progcheck:check"] = {
            "count": int(pcn),
            "total_s": series("bodo_tpu_progcheck_check_seconds").get(
                (), 0.0),
            "max_s": series(
                "bodo_tpu_progcheck_max_check_seconds").get((), 0.0),
            "rows": 0,
            "hbm_peak_bytes_max": int(series(
                "bodo_tpu_progcheck_hbm_peak_bytes_max").get((), 0))}
        pcv = series("bodo_tpu_progcheck_violations_total").get((), 0)
        if pcv:
            out["progcheck:violations"] = {
                "count": int(pcv), "total_s": 0.0, "max_s": 0.0,
                "rows": 0}
    # comm observatory: one row per collective op with the bytes moved
    # and the wall/peer-wait split (parallel/comm.py accounting)
    cd = series("bodo_tpu_comm_dispatches_total")
    if cd:
        cb = series("bodo_tpu_comm_bytes_total")
        cw = series("bodo_tpu_comm_seconds_total")
        for (op,), n in sorted(cd.items()):
            out[f"comm:{op}"] = {
                "count": int(n),
                "total_s": cw.get((op, "wall"), 0.0),
                "max_s": 0.0, "rows": 0,
                "bytes_in": int(cb.get((op, "in"), 0)),
                "bytes_out": int(cb.get((op, "out"), 0)),
                "wait_s": round(cw.get((op, "wait"), 0.0), 6)}
    qn = series("bodo_tpu_aqe_q_error_count").get((), 0)
    if qn:
        qe = {k: series(f"bodo_tpu_aqe_q_error_{k}").get((), 0.0)
              for k in ("mean", "p50", "p90", "max")}
        out["aqe:q_error"] = {
            "count": int(qn), "total_s": 0.0, "max_s": 0.0,
            "rows": 0, "mean": qe["mean"], "p50": qe["p50"],
            "p90": qe["p90"], "max": qe["max"]}
    cc = series("bodo_tpu_compile_cache_total")
    hits, misses = cc.get(("hit",), 0), cc.get(("miss",), 0)
    if hits or misses:
        out["cache:compile"] = {
            "count": int(hits + misses), "total_s": 0.0,
            "max_s": 0.0, "rows": 0, "hits": int(hits),
            "misses": int(misses)}
    # semantic result cache: query-level hits/misses/incremental
    # refreshes, with the wall seconds serving from cache saved
    rce = series("bodo_tpu_result_cache_events_total")
    rqh = rce.get(("q_hits",), 0)
    rqm = rce.get(("q_misses",), 0)
    if rqh or rqm:
        out["cache:result"] = {
            "count": int(rqh + rqm),
            "total_s": series("bodo_tpu_result_cache_saved_seconds"
                              ).get((), 0.0),
            "max_s": 0.0, "rows": 0, "hits": int(rqh),
            "misses": int(rqm),
            "incremental": int(rce.get(("q_incremental",), 0)),
            "evictions": int(rce.get(("evictions",), 0))}
    return out


def top_ops(query_id: Optional[str] = None, n: int = 5) -> List[dict]:
    """Top-n operators by wall seconds for one query (or overall):
    the bench artifact's "where did the time go" rows."""
    with _lock:
        rows: Dict[str, dict] = {}
        for (qid, name), v in _agg.items():
            if query_id is not None and qid != query_id:
                continue
            a = rows.get(name)
            if a is None:
                rows[name] = dict(v)
            else:
                a["count"] += v["count"]
                a["total_s"] += v["total_s"]
                a["rows"] += v["rows"]
    out = [{"op": name, "total_s": round(v["total_s"], 4),
            "count": v["count"], "rows": v["rows"]}
           for name, v in rows.items()]
    out.sort(key=lambda r: (-r["total_s"], r["op"]))
    return out[:n]


_op_depth = threading.local()


def traced_table_op(fn):
    """Wrap a Table-returning operator so every call (through ANY entry
    point — executor, streaming, or direct relational calls) lands in
    the per-operator profile with a rows count. Only the OUTERMOST
    traced frame records (operators re-enter each other — distributed
    groupby calls local groupby, windows call sort — and double-counting
    would make profile totals exceed wall time). No-op when tracing is
    off (one predicate check)."""
    import functools

    @functools.wraps(fn)
    def wrapper(*a, **k):
        if not is_tracing():
            return fn(*a, **k)
        depth = getattr(_op_depth, "d", 0)
        if depth:
            return fn(*a, **k)
        _op_depth.d = 1
        try:
            with event(fn.__name__) as ev:
                t = fn(*a, **k)
                rows = getattr(t, "nrows", None)
                if rows is not None and ev is not None:
                    ev["rows"] = rows
                return t
        finally:
            _op_depth.d = 0
    return wrapper
