"""Unified typed metrics registry: counter / gauge / histogram with labels.

The substrate PRs (memory governor, resilience, AQE, I/O pool,
shardcheck) each grew an ad-hoc ``stats()`` dict with its own shape;
`tracing.profile()` then hand-translated five shapes into ``mem:`` /
``resil:`` / ``aqe:`` / ``io:`` / ``lint:`` / ``lockstep:`` rows. This
module is the one place those translations live now: a typed registry
(the reference analogue is the per-operator metric types of
bodo/libs/_query_profile_collector.h — TIMER/STAT/BLOB — crossed with a
Prometheus-style exposition for the future serving layer,
runtime/scheduler.py, which will scrape it per session/tenant).

Three metric kinds, all label-aware and thread-safe:

  * :class:`Counter` — monotonically increasing (``.inc(n)``)
  * :class:`Gauge` — set-to-current-value (``.set(v)``)
  * :class:`Histogram` — bucketed observations (``.observe(v)``)

``sync_engine_metrics()`` pulls every subsystem's stats snapshot into
canonically named metrics (``bodo_tpu_*``); ``expose_text()`` renders
the whole registry in the Prometheus text exposition format;
``snapshot()`` returns the same data as a JSON-safe dict (embedded in
tracing dumps and bench artifacts). Query-scoped operator counters
(labelled ``query=...``/``op=...``) are synthesized from the tracing
layer's per-query aggregates, so per-query accounting needs no extra
bookkeeping on the hot event path.
"""

from __future__ import annotations

import math
import os
import re
import sys
import threading
from typing import Dict, List, Optional, Sequence, Tuple

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

# default histogram buckets: latency-shaped (seconds), 1ms .. ~2min
DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0,
                   30.0, 120.0)


class _Child:
    """One labelled series of a metric (what ``.labels(...)`` returns)."""

    __slots__ = ("_metric", "_key")

    def __init__(self, metric: "_Metric", key: Tuple[str, ...]):
        self._metric = metric
        self._key = key

    def inc(self, n: float = 1.0) -> None:
        self._metric._inc(self._key, n)

    def set(self, v: float) -> None:
        self._metric._set(self._key, v)

    def observe(self, v: float) -> None:
        self._metric._observe(self._key, v)

    def get(self) -> float:
        return self._metric.value(*self._key)


class _Metric:
    kind = ""

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = ()):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name: {name!r}")
        for ln in labelnames:
            if not _LABEL_RE.match(ln):
                raise ValueError(f"invalid label name: {ln!r}")
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._mu = threading.Lock()
        self._values: Dict[Tuple[str, ...], float] = {}

    # -- label resolution ----------------------------------------------------

    def labels(self, *args, **kwargs) -> _Child:
        if args and kwargs:
            raise ValueError("pass labels positionally OR by name")
        if kwargs:
            try:
                vals = tuple(str(kwargs[ln]) for ln in self.labelnames)
            except KeyError as e:
                raise ValueError(
                    f"{self.name}: missing label {e.args[0]!r} "
                    f"(expects {self.labelnames})") from None
            if len(kwargs) != len(self.labelnames):
                extra = set(kwargs) - set(self.labelnames)
                raise ValueError(f"{self.name}: unknown labels {extra}")
        else:
            if len(args) != len(self.labelnames):
                raise ValueError(
                    f"{self.name}: expected {len(self.labelnames)} label "
                    f"values {self.labelnames}, got {len(args)}")
            vals = tuple(str(a) for a in args)
        return _Child(self, vals)

    def _unlabelled(self) -> Tuple[str, ...]:
        if self.labelnames:
            raise ValueError(
                f"{self.name} has labels {self.labelnames}; "
                f"use .labels(...)")
        return ()

    # -- value ops (overridden per kind) -------------------------------------

    def _inc(self, key, n) -> None:
        raise TypeError(f"{self.kind} does not support inc()")

    def _set(self, key, v) -> None:
        raise TypeError(f"{self.kind} does not support set()")

    def _observe(self, key, v) -> None:
        raise TypeError(f"{self.kind} does not support observe()")

    def value(self, *labelvals) -> float:
        with self._mu:
            return self._values.get(tuple(str(v) for v in labelvals), 0.0)

    def series(self) -> Dict[Tuple[str, ...], float]:
        with self._mu:
            return dict(self._values)

    def clear(self) -> None:
        with self._mu:
            self._values.clear()


class Counter(_Metric):
    kind = "counter"

    def inc(self, n: float = 1.0) -> None:
        self._inc(self._unlabelled(), n)

    def _inc(self, key, n) -> None:
        if n < 0:
            raise ValueError(f"{self.name}: counters only go up ({n})")
        with self._mu:
            self._values[key] = self._values.get(key, 0.0) + n

    def get(self) -> float:
        return self.value()


class Gauge(_Metric):
    kind = "gauge"

    def set(self, v: float) -> None:
        self._set(self._unlabelled(), v)

    def inc(self, n: float = 1.0) -> None:
        self._inc(self._unlabelled(), n)

    def _set(self, key, v) -> None:
        with self._mu:
            self._values[key] = float(v)

    def _inc(self, key, n) -> None:
        with self._mu:
            self._values[key] = self._values.get(key, 0.0) + n

    def get(self) -> float:
        return self.value()


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 labelnames: Sequence[str] = (),
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        super().__init__(name, help, labelnames)
        b = sorted(float(x) for x in buckets)
        if not b:
            raise ValueError("histogram needs at least one bucket")
        self.buckets = tuple(b) + (math.inf,)
        # per-series state: [counts per bucket] + sum + count
        self._hist: Dict[Tuple[str, ...], dict] = {}

    def observe(self, v: float) -> None:
        self._observe(self._unlabelled(), v)

    def _observe(self, key, v) -> None:
        v = float(v)
        with self._mu:
            h = self._hist.get(key)
            if h is None:
                h = self._hist[key] = {
                    "counts": [0] * len(self.buckets),
                    "sum": 0.0, "count": 0}
            for i, ub in enumerate(self.buckets):
                if v <= ub:
                    h["counts"][i] += 1
                    break
            h["sum"] += v
            h["count"] += 1
            # keep _values in sync so snapshot() has a scalar view
            self._values[key] = h["sum"]

    def series_hist(self) -> Dict[Tuple[str, ...], dict]:
        with self._mu:
            return {k: {"counts": list(h["counts"]), "sum": h["sum"],
                        "count": h["count"]}
                    for k, h in self._hist.items()}

    def clear(self) -> None:
        with self._mu:
            self._values.clear()
            self._hist.clear()


class Registry:
    """Named metric store. ``counter``/``gauge``/``histogram`` are
    get-or-create (re-registration with a different kind or labelset is
    an error — two call sites silently disagreeing about a metric's
    meaning is exactly the bug a registry exists to prevent)."""

    def __init__(self):
        self._mu = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def _get_or_create(self, cls, name: str, help: str,
                       labelnames: Sequence[str], **kw) -> _Metric:
        with self._mu:
            m = self._metrics.get(name)
            if m is not None:
                if not isinstance(m, cls):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{m.kind}, not {cls.kind}")
                if tuple(labelnames) != m.labelnames:
                    raise ValueError(
                        f"metric {name!r} already registered with labels "
                        f"{m.labelnames}, not {tuple(labelnames)}")
                return m
            m = cls(name, help, labelnames, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_create(Histogram, name, help, labelnames,
                                   buckets=buckets)

    def get(self, name: str) -> Optional[_Metric]:
        with self._mu:
            return self._metrics.get(name)

    def metrics(self) -> List[_Metric]:
        with self._mu:
            return sorted(self._metrics.values(), key=lambda m: m.name)

    def unregister(self, name: str) -> None:
        with self._mu:
            self._metrics.pop(name, None)

    def reset(self) -> None:
        """Drop every metric (tests)."""
        with self._mu:
            self._metrics.clear()

    # -- output --------------------------------------------------------------

    def snapshot(self) -> Dict[str, dict]:
        """JSON-safe dump: {name: {kind, help, values: {label-expr or
        "": value}}} (histograms additionally carry sum/count/buckets)."""
        out: Dict[str, dict] = {}
        for m in self.metrics():
            entry: dict = {"kind": m.kind, "values": {}}
            if m.help:
                entry["help"] = m.help
            for key, v in sorted(m.series().items()):
                entry["values"][_labelexpr(m.labelnames, key)] = (
                    round(v, 6) if isinstance(v, float) else v)
            if isinstance(m, Histogram):
                entry["histogram"] = {
                    _labelexpr(m.labelnames, key): {
                        "count": h["count"], "sum": round(h["sum"], 6)}
                    for key, h in sorted(m.series_hist().items())}
            out[m.name] = entry
        return out

    def expose_text(self) -> str:
        """Prometheus text exposition (the contract the future
        runtime/scheduler.py serving layer scrapes)."""
        lines: List[str] = []
        for m in self.metrics():
            if m.help:
                lines.append(f"# HELP {m.name} {_esc_help(m.help)}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            if isinstance(m, Histogram):
                for key, h in sorted(m.series_hist().items()):
                    acc = 0
                    for ub, c in zip(m.buckets, h["counts"]):
                        acc += c
                        le = "+Inf" if ub == math.inf else _fmt(ub)
                        lines.append(
                            f"{m.name}_bucket"
                            f"{_promlabels(m.labelnames, key, le=le)}"
                            f" {acc}")
                    lines.append(f"{m.name}_sum"
                                 f"{_promlabels(m.labelnames, key)}"
                                 f" {_fmt(h['sum'])}")
                    lines.append(f"{m.name}_count"
                                 f"{_promlabels(m.labelnames, key)}"
                                 f" {h['count']}")
                continue
            series = sorted(m.series().items())
            if not series and not m.labelnames:
                series = [((), 0.0)]
            for key, v in series:
                lines.append(f"{m.name}{_promlabels(m.labelnames, key)}"
                             f" {_fmt(v)}")
        return "\n".join(lines) + "\n"


def _fmt(v: float) -> str:
    # the exposition format spells non-finite values +Inf/-Inf/NaN —
    # repr() would emit python's inf/nan, which scrapers reject
    if isinstance(v, float):
        if math.isinf(v):
            return "+Inf" if v > 0 else "-Inf"
        if math.isnan(v):
            return "NaN"
        if v.is_integer():
            return str(int(v))
    return repr(v)


def _esc(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _esc_help(v: str) -> str:
    # HELP text escapes backslash and newline only (quotes stay raw)
    return v.replace("\\", "\\\\").replace("\n", "\\n")


def _promlabels(names: Sequence[str], vals: Sequence[str],
                le: Optional[str] = None) -> str:
    parts = [f'{n}="{_esc(v)}"' for n, v in zip(names, vals)]
    if le is not None:
        parts.append(f'le="{le}"')
    return "{" + ",".join(parts) + "}" if parts else ""


def _labelexpr(names: Sequence[str], vals: Sequence[str]) -> str:
    if not names:
        return ""
    return ",".join(f"{n}={v}" for n, v in zip(names, vals))


# ---------------------------------------------------------------------------
# exposition-format checker (the /metrics compliance gate)
# ---------------------------------------------------------------------------

_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?P<labels>\{[^{}]*\})?"
    r" (?P<value>[^ ]+)"
    r"( (?P<ts>-?\d+))?$")
_LABEL_PAIR_RE = re.compile(
    r'^(?P<name>[a-zA-Z_][a-zA-Z0-9_]*)="(?P<value>(?:[^"\\]|\\["\\n])*)"$')
_VALUE_RE = re.compile(r"^(\+Inf|-Inf|NaN|[-+]?(\d+\.?\d*|\.\d+)"
                       r"([eE][-+]?\d+)?)$")
_HELP_RE = re.compile(r"^# HELP ([a-zA-Z_:][a-zA-Z0-9_:]*) (.*)$")
_TYPE_RE = re.compile(r"^# TYPE ([a-zA-Z_:][a-zA-Z0-9_:]*) "
                      r"(counter|gauge|histogram|summary|untyped)$")


def _split_labels(body: str) -> Optional[List[str]]:
    """Split the inside of a {...} label block on unescaped/unquoted
    commas. Returns None when the quoting is broken."""
    parts: List[str] = []
    cur: List[str] = []
    in_str = False
    esc = False
    for ch in body:
        if esc:
            cur.append(ch)
            esc = False
            continue
        if ch == "\\" and in_str:
            cur.append(ch)
            esc = True
            continue
        if ch == '"':
            in_str = not in_str
            cur.append(ch)
            continue
        if ch == "," and not in_str:
            parts.append("".join(cur))
            cur = []
            continue
        cur.append(ch)
    if in_str or esc:
        return None
    if cur or parts:
        parts.append("".join(cur))
    return [p for p in parts if p]


def _base_family(name: str) -> str:
    for suffix in ("_bucket", "_sum", "_count"):
        if name.endswith(suffix):
            return name[: -len(suffix)]
    return name


def check_exposition(text: str) -> List[str]:
    """Validate a Prometheus text-exposition payload line by line;
    returns a list of problems (empty = compliant). Checks: sample-line
    grammar, numeric values (incl. +Inf/-Inf/NaN spellings), label
    name/escaping rules, HELP/TYPE well-formedness and uniqueness,
    TYPE-before-samples ordering, histogram families carrying _bucket
    (with le), _sum and _count with count == the +Inf bucket."""
    problems: List[str] = []
    typed: Dict[str, str] = {}
    helped: set = set()
    sampled: set = set()
    # histogram family -> {"inf": value, "count": value, "sum": seen}
    hist: Dict[str, dict] = {}
    for i, line in enumerate(text.split("\n"), 1):
        if line == "":
            continue
        if line != line.strip():
            problems.append(f"line {i}: leading/trailing whitespace")
            continue
        if line.startswith("#"):
            mh = _HELP_RE.match(line)
            mt = _TYPE_RE.match(line)
            if mh:
                name = mh.group(1)
                if name in helped:
                    problems.append(f"line {i}: duplicate HELP {name}")
                helped.add(name)
                body = mh.group(2)
                if re.search(r"(?<!\\)\\(?![\\n])", body):
                    problems.append(
                        f"line {i}: HELP {name}: stray backslash "
                        f"escape in help text")
            elif mt:
                name = mt.group(1)
                if name in typed:
                    problems.append(f"line {i}: duplicate TYPE {name}")
                if name in sampled:
                    problems.append(
                        f"line {i}: TYPE {name} after its samples")
                typed[name] = mt.group(2)
            elif line.startswith(("# HELP", "# TYPE")):
                problems.append(f"line {i}: malformed comment: "
                                f"{line[:80]!r}")
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            problems.append(f"line {i}: unparseable sample: "
                            f"{line[:80]!r}")
            continue
        name = m.group("name")
        sampled.add(_base_family(name))
        sampled.add(name)
        if not _VALUE_RE.match(m.group("value")):
            problems.append(f"line {i}: {name}: bad value "
                            f"{m.group('value')!r}")
        labels: Dict[str, str] = {}
        if m.group("labels"):
            body = m.group("labels")[1:-1]
            pairs = _split_labels(body)
            if pairs is None:
                problems.append(
                    f"line {i}: {name}: broken label quoting")
                pairs = []
            for pair in pairs:
                ml = _LABEL_PAIR_RE.match(pair)
                if not ml:
                    problems.append(
                        f"line {i}: {name}: bad label pair "
                        f"{pair[:60]!r}")
                    continue
                if ml.group("name") in labels:
                    problems.append(
                        f"line {i}: {name}: duplicate label "
                        f"{ml.group('name')}")
                labels[ml.group("name")] = ml.group("value")
        fam = _base_family(name)
        if typed.get(fam) == "histogram":
            key = tuple(sorted((k, v) for k, v in labels.items()
                               if k != "le"))
            h = hist.setdefault(fam, {}).setdefault(
                key, {"inf": None, "count": None, "sum": False,
                      "buckets": False, "line": i})
            if name.endswith("_bucket"):
                h["buckets"] = True
                if "le" not in labels:
                    problems.append(
                        f"line {i}: {name}: _bucket without le label")
                elif labels["le"] == "+Inf":
                    h["inf"] = m.group("value")
            elif name.endswith("_sum"):
                h["sum"] = True
            elif name.endswith("_count"):
                h["count"] = m.group("value")
    for fam, series in hist.items():
        for key, h in series.items():
            where = f"histogram {fam}{dict(key) if key else ''}"
            if not h["buckets"]:
                problems.append(f"{where}: no _bucket series")
            elif h["inf"] is None:
                problems.append(f"{where}: no le=\"+Inf\" bucket")
            if not h["sum"]:
                problems.append(f"{where}: missing _sum")
            if h["count"] is None:
                problems.append(f"{where}: missing _count")
            elif h["inf"] is not None and h["count"] != h["inf"]:
                problems.append(
                    f"{where}: _count {h['count']} != +Inf bucket "
                    f"{h['inf']}")
    return problems


# ---------------------------------------------------------------------------
# process-global registry + module-level conveniences
# ---------------------------------------------------------------------------

_registry = Registry()


def registry() -> Registry:
    return _registry


def counter(name: str, help: str = "",
            labelnames: Sequence[str] = ()) -> Counter:
    return _registry.counter(name, help, labelnames)


def gauge(name: str, help: str = "",
          labelnames: Sequence[str] = ()) -> Gauge:
    return _registry.gauge(name, help, labelnames)


def histogram(name: str, help: str = "",
              labelnames: Sequence[str] = (),
              buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
    return _registry.histogram(name, help, labelnames, buckets=buckets)


def snapshot() -> Dict[str, dict]:
    sync_engine_metrics()
    return _registry.snapshot()


def expose_text() -> str:
    sync_engine_metrics()
    return _registry.expose_text()


def reset() -> None:
    _registry.reset()


# ---------------------------------------------------------------------------
# engine metric sync: the one place the legacy stats() shapes map onto
# canonical metric names
# ---------------------------------------------------------------------------

# compile-side series are fed LIVE (kernel_cache.record_compile), not
# synced — declare them eagerly so an exposition before any compile
# still shows the metric families ROADMAP item 1 is judged against
JIT_COMPILE_SECONDS = "bodo_tpu_jit_compile_seconds"
PALLAS_TRACED = "bodo_tpu_pallas_traced_into_pipeline"


def record_compile(program: str, seconds: float) -> None:
    """Per-program jit compile seconds (called by kernel_cache on every
    cache-miss first invocation — trace+lower+compile wall time)."""
    histogram(JIT_COMPILE_SECONDS,
              "wall seconds of jit trace+compile per program",
              ("program",)).labels(program=program).observe(seconds)


class _GangBound:
    """Gauge façade that injects a constant ``gang`` label value into
    every labels()/set() call (fleet gang processes only)."""

    def __init__(self, g: Gauge, gid: str):
        self._g, self._gid = g, gid

    def labels(self, **kw) -> _Child:
        kw["gang"] = self._gid
        return self._g.labels(**kw)

    def set(self, v: float) -> None:
        self._g.labels(gang=self._gid).set(v)


def _gang_gauge(name: str, help: str = "",
                labelnames: Sequence[str] = ()):
    """Gauge that grows a ``gang`` label when this process is a fleet
    gang (BODO_TPU_GANG_ID set at spawn): the controller's scrapes then
    attribute per-gang series unambiguously. Outside fleet mode the
    series keeps its classic shape — the env is set for the process's
    whole life, so the label set never flips mid-registry."""
    gid = os.environ.get("BODO_TPU_GANG_ID", "")
    if not gid:
        return gauge(name, help, labelnames)
    return _GangBound(gauge(name, help, tuple(labelnames) + ("gang",)),
                      gid)


def sync_engine_metrics() -> None:
    """Pull every subsystem's stats snapshot into the registry. Cheap
    (a few dict copies); called by snapshot()/expose_text() and by
    tracing.profile()/dump() so readers always see current values."""
    # -- memory governor -----------------------------------------------------
    try:
        from bodo_tpu.runtime.memory_governor import governor
        mem = governor().stats()
        gauge("bodo_tpu_mem_derived_budget_bytes",
              "memory governor derived per-device budget").set(
            mem.get("derived_budget_bytes", 0))
        gauge("bodo_tpu_mem_oom_retries_total",
              "stage re-runs after RESOURCE_EXHAUSTED").set(
            mem.get("n_oom_retries", 0))
        g = gauge("bodo_tpu_mem_operator_bytes",
                  "per-operator granted/peak/spilled bytes",
                  ("op", "kind"))
        ge = gauge("bodo_tpu_mem_operator_events",
                   "per-operator grant count / spill count",
                   ("op", "kind"))
        for name, m in mem.get("operators", {}).items():
            g.labels(op=name, kind="granted").set(m.get("granted", 0))
            g.labels(op=name, kind="peak").set(m.get("peak", 0))
            g.labels(op=name, kind="spilled").set(
                m.get("spilled_bytes", 0))
            ge.labels(op=name, kind="count").set(m.get("count", 0))
            ge.labels(op=name, kind="n_spills").set(m.get("n_spills", 0))
    except Exception:  # pragma: no cover - governor unavailable pre-mesh
        pass
    # -- resilience ----------------------------------------------------------
    try:
        from bodo_tpu.runtime import resilience
        rs = resilience.stats()
        g = gauge("bodo_tpu_resil_faults_fired_total",
                  "armed faults fired per injection point", ("point",))
        for point, n in rs.get("faults_fired", {}).items():
            g.labels(point=point).set(n)
        g = gauge("bodo_tpu_resil_retries_total",
                  "retry-envelope retries per label", ("label",))
        for label, n in rs.get("retries", {}).items():
            g.labels(label=label).set(n)
        g = gauge("bodo_tpu_resil_degraded_stages_total",
                  "stages re-executed replicated", ("stage",))
        for stage, n in rs.get("degraded_stages", {}).items():
            g.labels(stage=stage).set(n)
        gauge("bodo_tpu_resil_gang_retries_total",
              "whole-gang spawn retries").set(rs.get("gang_retries", 0))
    except Exception:  # pragma: no cover
        pass
    # -- adaptive execution --------------------------------------------------
    try:
        from bodo_tpu.plan import adaptive
        aq = adaptive.stats()
        g = gauge("bodo_tpu_aqe_decisions_total",
                  "adaptive-execution decisions", ("decision",))
        for decision, n in aq.get("decisions", {}).items():
            g.labels(decision=decision).set(n)
        qe = aq.get("q_error", {})
        if qe.get("count"):
            gauge("bodo_tpu_aqe_q_error_count",
                  "first-observation estimates scored").set(
                qe.get("count", 0))
            gauge("bodo_tpu_aqe_q_error_mean",
                  "mean q-error of first-observation estimates").set(
                qe.get("mean", 0.0))
            gauge("bodo_tpu_aqe_q_error_p50",
                  "median q-error of first-observation estimates").set(
                qe.get("p50", 0.0))
            gauge("bodo_tpu_aqe_q_error_p90",
                  "p90 q-error of first-observation estimates").set(
                qe.get("p90", 0.0))
            gauge("bodo_tpu_aqe_q_error_max",
                  "worst q-error of first-observation estimates").set(
                qe.get("max", 0.0))
    except Exception:  # pragma: no cover
        pass
    # -- pipelined I/O -------------------------------------------------------
    try:
        from bodo_tpu.runtime import io_pool
        ios = io_pool.io_stats()
        g = gauge("bodo_tpu_io_events_total", "io pipeline counters",
                  ("event",))
        for key in ("prefetch_hits", "prefetch_streams", "prefetch_depth",
                    "stalls", "footer_hits", "footer_misses",
                    "parallel_units", "parallel_reads", "decode_batches",
                    "decode_bytes", "device_decode_pages",
                    "device_decode_cols", "device_fallback_cols",
                    "device_decode_errors", "device_decode_bytes",
                    "host_decode_bytes", "raw_bytes"):
            g.labels(event=key).set(ios.get(key, 0))
        g = gauge("bodo_tpu_io_seconds", "io pipeline time split",
                  ("phase",))
        for phase in ("decode_s", "stall_s", "overlap_s",
                      "device_decode_s"):
            g.labels(phase=phase[:-2]).set(ios.get(phase, 0.0))
        gauge("bodo_tpu_io_overlap_ratio",
              "decode time hidden behind consumer compute").set(
            ios.get("overlap_ratio", 0.0))
        gauge("bodo_tpu_scan_device_decode_frac",
              "fraction of decoded scan bytes produced on device").set(
            ios.get("device_decode_frac", 0.0))
    except Exception:  # pragma: no cover
        pass
    # -- shardcheck (plan validator / lint / lockstep) -----------------------
    try:
        from bodo_tpu.analysis import lint, lockstep, plan_validator
        pv = plan_validator.stats()
        gauge("bodo_tpu_plans_validated_total",
              "plans checked by the plan validator").set(
            pv.get("plans", 0))
        gauge("bodo_tpu_plan_violations_total",
              "plan invariant violations raised").set(
            pv.get("violations", 0))
        gauge("bodo_tpu_lint_findings_total",
              "shardcheck lint findings").set(
            lint.stats().get("findings", 0))
        ls = lockstep.stats()
        gauge("bodo_tpu_lockstep_collectives_total",
              "host-level collective dispatches fingerprinted").set(
            ls.get("collectives", 0))
        gauge("bodo_tpu_lockstep_mismatches_total",
              "lockstep divergences detected").set(
            ls.get("mismatches", 0))
        gauge("bodo_tpu_lockstep_timeouts_total",
              "lockstep peer-wait timeouts").set(ls.get("timeouts", 0))
        gauge("bodo_tpu_lockstep_wait_seconds",
              "cumulative peer-wait seconds").set(ls.get("wait_s", 0.0))
        gauge("bodo_tpu_lockstep_max_wait_seconds",
              "worst single peer-wait seconds").set(
            ls.get("max_wait_s", 0.0))
    except Exception:  # pragma: no cover
        pass
    # -- progcheck (jaxpr-level SPMD program verifier; lazy-module rule:
    # nothing to report until a registration point has imported it) ----------
    pc = sys.modules.get("bodo_tpu.analysis.progcheck")
    if pc is not None:
        try:
            ps = pc.stats()
            gauge("bodo_tpu_progcheck_programs_total",
                  "programs statically verified at registration").set(
                ps.get("programs", 0))
            gauge("bodo_tpu_progcheck_violations_total",
                  "program invariant violations found").set(
                ps.get("violations", 0))
            gauge("bodo_tpu_progcheck_skipped_total",
                  "programs whose trace could not be reproduced").set(
                ps.get("skipped", 0))
            gauge("bodo_tpu_progcheck_check_seconds",
                  "cumulative verification wall seconds").set(
                ps.get("check_s", 0.0))
            gauge("bodo_tpu_progcheck_max_check_seconds",
                  "worst single program verification seconds").set(
                ps.get("max_check_s", 0.0))
            gauge("bodo_tpu_progcheck_manifests_total",
                  "collective manifests extracted and registered").set(
                ps.get("manifests", 0))
            gauge("bodo_tpu_progcheck_hbm_peak_bytes_max",
                  "largest static HBM peak estimate across programs").set(
                ps.get("hbm_peak_bytes_max", 0))
            gauge("bodo_tpu_progcheck_rank_variant_programs",
                  "programs with a collective under rank-derived "
                  "control flow").set(
                ps.get("rank_variant_programs", 0))
            gauge("bodo_tpu_progcheck_enforce",
                  "1 when violations raise instead of warn").set(
                ps.get("enforce", 0))
        except Exception:  # pragma: no cover
            pass
    # -- communication observatory (parallel/comm.py is stdlib-safe) ---------
    try:
        from bodo_tpu.parallel import comm
        g = gauge("bodo_tpu_comm_dispatches_total",
                  "collective dispatches accounted per op", ("op",))
        gb = gauge("bodo_tpu_comm_bytes_total",
                   "bytes through collective dispatches",
                   ("op", "direction"))
        gw = gauge("bodo_tpu_comm_seconds_total",
                   "cumulative collective host wall / peer-wait seconds",
                   ("op", "kind"))
        for op, r in comm.per_op().items():
            g.labels(op=op).set(r["count"])
            gb.labels(op=op, direction="in").set(r["bytes_in"])
            gb.labels(op=op, direction="out").set(r["bytes_out"])
            gw.labels(op=op, kind="wall").set(r["wall_s"])
            gw.labels(op=op, kind="wait").set(r["wait_s"])
        sk = comm.skew_head()
        gauge("bodo_tpu_comm_max_wait_seconds",
              "worst single collective peer-wait (arrival skew)").set(
            sk.get("max_wait_s", 0.0))
        gauge("bodo_tpu_comm_wait_frac",
              "peer-wait share of total comm time").set(
            sk.get("wait_frac", 0.0))
    except Exception:  # pragma: no cover
        pass
    # -- compile cache + pallas engagement -----------------------------------
    try:
        from bodo_tpu.utils import tracing
        cc = tracing.compile_cache_stats()
        g = gauge("bodo_tpu_compile_cache_total",
                  "persistent jit-cache lookups", ("result",))
        g.labels(result="hit").set(cc["hits"])
        g.labels(result="miss").set(cc["misses"])
    except Exception:  # pragma: no cover
        pass
    # -- semantic result cache (lazy-module rule: nothing to report
    # until the executor has loaded it anyway) -------------------------------
    rc = sys.modules.get("bodo_tpu.runtime.result_cache")
    if rc is not None:
        try:
            rs_ = rc.stats()
            g = _gang_gauge("bodo_tpu_result_cache_events_total",
                            "semantic result cache events", ("event",))
            for k in ("hits", "misses", "q_hits", "q_misses",
                      "q_incremental", "evictions", "invalidations",
                      "incremental_fallbacks", "spills", "rehydrations",
                      "rejected", "sig_uncacheable", "pressure_sheds",
                      "peer_hits", "peer_misses", "peer_serves",
                      "invalidations_remote"):
                g.labels(event=k).set(rs_.get(k, 0))
            gb = _gang_gauge("bodo_tpu_result_cache_bytes",
                             "resident result-cache bytes per tier",
                             ("tier",))
            gb.labels(tier="device").set(rs_.get("device_bytes", 0))
            gb.labels(tier="host").set(rs_.get("host_bytes", 0))
            ge2 = _gang_gauge("bodo_tpu_result_cache_entries",
                              "resident result-cache entries per tier",
                              ("tier",))
            ge2.labels(tier="device").set(rs_.get("device_entries", 0))
            ge2.labels(tier="host").set(rs_.get("host_entries", 0))
            _gang_gauge("bodo_tpu_result_cache_saved_seconds",
                        "wall seconds saved by serving cached "
                        "results").set(rs_.get("saved_wall_s", 0.0))
            _gang_gauge("bodo_tpu_result_cache_budget_bytes",
                        "device-byte budget of the result cache "
                        "(admission reads occupancy = "
                        "bytes/budget)").set(rs_.get("budget_bytes", 0))
            gs = _gang_gauge("bodo_tpu_result_cache_session_events_total",
                             "per-session result cache events",
                             ("session", "event"))
            gsb = _gang_gauge("bodo_tpu_result_cache_session_bytes",
                              "per-session resident device bytes",
                              ("session",))
            for sid, row in rs_.get("by_session", {}).items():
                for ev in ("q_hits", "q_misses", "evicted", "records"):
                    gs.labels(session=sid, event=ev).set(row.get(ev, 0))
                gsb.labels(session=sid).set(row.get("device_bytes", 0))
        except Exception:  # pragma: no cover
            pass
    # -- materialized views (lazy-module rule: a registry only exists
    # once views were created) -----------------------------------------------
    vw = sys.modules.get("bodo_tpu.runtime.views")
    if vw is not None:
        try:
            vs_ = vw.stats()
            if vs_.get("n_views"):
                g = _gang_gauge("bodo_tpu_view_events_total",
                                "materialized-view maintenance events",
                                ("event",))
                for k in ("refreshes_incremental", "refreshes_full",
                          "ticks", "detected_stale", "flagged_stale",
                          "refresh_scheduled", "refresh_rejected"):
                    g.labels(event=k).set(vs_.get(k, 0))
                _gang_gauge("bodo_tpu_view_count",
                            "registered materialized views").set(
                    vs_.get("n_views", 0))
                _gang_gauge("bodo_tpu_view_subscriptions",
                            "live continuous-query subscriptions").set(
                    vs_.get("subscriptions", 0))
                _gang_gauge("bodo_tpu_view_fanout_depth",
                            "depth of the materialized-view DAG").set(
                    vs_.get("dag_depth", 0))
                _gang_gauge("bodo_tpu_view_refresh_ratio",
                            "incremental refresh wall relative to "
                            "full-recompute wall").set(
                    vs_.get("refresh_ratio", 0.0))
                _gang_gauge("bodo_tpu_view_staleness_p99_seconds",
                            "p99 change-to-refresh staleness across "
                            "views").set(vs_.get("staleness_p99_s",
                                                 0.0))
        except Exception:  # pragma: no cover
            pass
    # -- sql plan cache (sql/plan_cache.py is stdlib-safe) -------------------
    try:
        from bodo_tpu.sql import plan_cache
        pc = plan_cache.stats()
        g = gauge("bodo_tpu_sql_plan_cache_total",
                  "persistent SQL plan cache lookups", ("result",))
        g.labels(result="hit").set(pc.get("hits", 0))
        g.labels(result="miss").set(pc.get("misses", 0))
        gps = gauge("bodo_tpu_sql_plan_cache_session_total",
                    "per-session SQL plan cache lookups",
                    ("session", "result"))
        for sid, row in pc.get("by_session", {}).items():
            gps.labels(session=sid, result="hit").set(row.get("hits", 0))
            gps.labels(session=sid, result="miss").set(
                row.get("misses", 0))
    except Exception:  # pragma: no cover
        pass
    # -- query scheduler (lazy-module rule: nothing to serve until the
    # serving layer has loaded it anyway) ------------------------------------
    sch = sys.modules.get("bodo_tpu.runtime.scheduler")
    if sch is not None:
        try:
            ss = sch.stats()
            if ss is not None:
                _gang_gauge("bodo_tpu_serve_sessions",
                            "open serving sessions").set(
                    ss.get("sessions", 0))
                _gang_gauge("bodo_tpu_serve_queued",
                            "requests queued across all sessions").set(
                    ss.get("queued", 0))
                _gang_gauge("bodo_tpu_serve_running",
                            "requests executing on the gang").set(
                    ss.get("running", 0))
                _gang_gauge("bodo_tpu_serve_workers",
                            "live scheduler worker threads").set(
                    ss.get("workers", 0))
                _gang_gauge("bodo_tpu_serve_completed_total",
                            "queries completed by the serving layer").set(
                    ss.get("completed", 0))
                _gang_gauge("bodo_tpu_serve_failed_total",
                            "queries delivered as typed failures").set(
                    ss.get("failed", 0))
                gd = _gang_gauge("bodo_tpu_serve_decisions_total",
                                 "admission decisions by action",
                                 ("action",))
                for action, n in ss.get("decisions", {}).items():
                    gd.labels(action=action).set(n)
        except Exception:  # pragma: no cover
            pass
    # pallas_kernels imports jax — only read the counter if the module
    # is already loaded (never force a jax import from a metrics scrape)
    pk = sys.modules.get("bodo_tpu.ops.pallas_kernels")
    if pk is not None:
        gauge(PALLAS_TRACED,
              "pallas kernels traced into compiled pipelines").set(
            getattr(pk, "trace_count", 0))
    # -- whole-stage fusion (same lazy-module rule: fusion imports jax) ------
    fz = sys.modules.get("bodo_tpu.plan.fusion")
    if fz is not None:
        try:
            fs = fz.stats()
            g = gauge("bodo_tpu_fusion_events_total",
                      "whole-stage fusion events", ("kind",))
            for k in ("groups_planned", "groups_executed",
                      "stream_chains", "partial_agg", "fallbacks",
                      "donated", "device_scan_batches", "hits",
                      "misses", "compiles", "evictions"):
                g.labels(kind=k).set(fs.get(k, 0))
            gauge("bodo_tpu_fusion_compile_seconds",
                  "cumulative fused-program compile wall seconds").set(
                fs.get("compile_s", 0.0))
            gauge("bodo_tpu_fusion_programs_cached",
                  "compiled fusion programs resident in the LRU").set(
                fs.get("size", 0))
        except Exception:  # pragma: no cover
            pass
    # -- compile & device-memory observatory (stdlib-only module, but
    # the same lazy rule keeps a bare metrics scrape from loading it) --------
    ob = sys.modules.get("bodo_tpu.runtime.xla_observatory")
    if ob is not None:
        try:
            os_ = ob.stats()
            g = gauge("bodo_tpu_xla_executables",
                      "registered XLA executables", ("subsystem",))
            gc_ = gauge("bodo_tpu_xla_compile_seconds",
                        "cumulative compile wall seconds",
                        ("subsystem",))
            gd = gauge("bodo_tpu_xla_dispatches_total",
                       "dispatches of registered executables",
                       ("subsystem",))
            for sub, sv in os_["by_subsystem"].items():
                g.labels(subsystem=sub).set(sv["executables"])
                gc_.labels(subsystem=sub).set(sv["compile_s"])
                gd.labels(subsystem=sub).set(sv["dispatches"])
            gauge("bodo_tpu_xla_budget_remaining",
                  "unified compile-budget units left (-1 unlimited)"
                  ).set(os_["budget"]["remaining"])
            gr = gauge("bodo_tpu_xla_retraces_total",
                       "retraces by attributed cause", ("cause",))
            for cause, n in os_["retraces"].items():
                gr.labels(cause=cause).set(n)
            led = os_["ledger"]
            gb = gauge("bodo_tpu_device_bytes_live",
                       "live device bytes by creating operator",
                       ("operator",))
            for op, ov in led["by_op"].items():
                gb.labels(operator=op).set(
                    ov["created_bytes"] - ov["freed_bytes"])
            gauge("bodo_tpu_device_bytes_created_total",
                  "device bytes created (ledger)").set(
                led["created_bytes"])
            gauge("bodo_tpu_device_bytes_freed_total",
                  "device bytes freed (ledger)").set(led["freed_bytes"])
            gauge("bodo_tpu_device_buffers_live",
                  "live tracked device buffers").set(
                led["live_buffers"])
            gdn = gauge("bodo_tpu_xla_donation_total",
                        "donated dispatches by verification result",
                        ("result",))
            gdn.labels(result="verified").set(
                led["donation"]["verified"])
            gdn.labels(result="copied").set(led["donation"]["copied"])
        except Exception:  # pragma: no cover
            pass
    # -- telemetry sampler (same lazy-module rule) ---------------------------
    tl = sys.modules.get("bodo_tpu.runtime.telemetry")
    if tl is not None:
        try:
            tl.sync_gauges()
        except Exception:  # pragma: no cover
            pass
    # -- tracing layer (events buffer + per-query operator counters) ---------
    try:
        from bodo_tpu.utils import tracing
        gauge("bodo_tpu_trace_events_dropped_total",
              "trace events dropped by the ring buffer").set(
            tracing.dropped_events())
        cs = counter("bodo_tpu_operator_seconds_total",
                     "operator wall seconds per query", ("op", "query"))
        cc2 = counter("bodo_tpu_operator_calls_total",
                      "operator invocations per query", ("op", "query"))
        cr = counter("bodo_tpu_operator_rows_total",
                     "operator output rows per query", ("op", "query"))
        # counters must be monotonic: set absolute values via the raw
        # series (tracing's per-query agg IS the source of truth)
        for (qid, op), a in tracing.query_agg().items():
            key = (str(op), str(qid or "-"))
            with cs._mu:
                cs._values[key] = a["total_s"]
            with cc2._mu:
                cc2._values[key] = float(a["count"])
            with cr._mu:
                cr._values[key] = float(a["rows"])
    except Exception:  # pragma: no cover
        pass
