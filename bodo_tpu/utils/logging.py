"""Verbose logging (analogue of bodo/user_logging.py levels 0-3).

Level 1: pushdown/fallback/IO notices; 2: plan dumps; 3: kernel trace.
"""

from __future__ import annotations

import sys

from bodo_tpu.config import config


def log(level: int, msg: str) -> None:
    if config.verbose_level >= level:
        print(f"[bodo_tpu] {msg}", file=sys.stderr)


def warn_fallback(api: str, reason: str) -> None:
    """Emit the pandas-fallback warning (reference: check_args_fallback
    warning, bodo/pandas/utils.py:346)."""
    if config.warn_fallback:
        import warnings
        warnings.warn(
            f"{api}: falling back to pandas ({reason}); this materializes "
            f"the frame on the host", stacklevel=3)
