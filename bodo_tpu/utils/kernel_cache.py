"""Bounded LRU cache for compiled kernels.

Long sessions (and the 400+-test suite) compile thousands of distinct
jitted kernels; pinning them all forever exhausts XLA:CPU's JIT code
memory and eventually segfaults the compiler. The reference contains
the same class of leak per test *module* by running each module in its
own subprocess (bodo/runtests.py:58). Here the engine itself stays
healthy: kernel caches evict least-recently-used entries so dropped
executables are garbage-collected.
"""

from __future__ import annotations

from collections import OrderedDict


class KernelCache:
    """Dict-shaped LRU with the two operations the kernel caches use
    (`get` and item assignment)."""

    def __init__(self, maxsize: int = 1024):
        self.maxsize = maxsize
        self._d: OrderedDict = OrderedDict()
        self.evictions = 0

    def get(self, key, default=None):
        try:
            self._d.move_to_end(key)
            return self._d[key]
        except KeyError:
            return default

    def __setitem__(self, key, value):
        if key in self._d:
            self._d.move_to_end(key)
        self._d[key] = value
        while len(self._d) > self.maxsize:
            self._d.popitem(last=False)
            self.evictions += 1

    def __contains__(self, key):
        return key in self._d

    def __len__(self):
        return len(self._d)

    def pop(self, key, default=None):
        return self._d.pop(key, default)

    def clear(self):
        self._d.clear()
