"""Bounded LRU cache for compiled kernels.

Long sessions (and the 400+-test suite) compile thousands of distinct
jitted kernels; pinning them all forever exhausts XLA:CPU's JIT code
memory and eventually segfaults the compiler. The reference contains
the same class of leak per test *module* by running each module in its
own subprocess (bodo/runtests.py:58). Here the engine itself stays
healthy: kernel caches evict least-recently-used entries so dropped
executables are garbage-collected.

Caches constructed with a `subsystem` tag additionally report every
store/hit/eviction to the unified program registry
(runtime/xla_observatory.py): the optional `describe(key)` callback
maps a cache key to a (base_signature, facets) pair so the registry
can attribute retraces to the facet that changed.
"""

from __future__ import annotations

import functools
import time
from collections import OrderedDict

from bodo_tpu.runtime import xla_observatory as _obs


class KernelCache:
    """Dict-shaped LRU with the two operations the kernel caches use
    (`get` and item assignment)."""

    def __init__(self, maxsize: int = 1024, *, subsystem=None,
                 describe=None):
        self.maxsize = maxsize
        self._d: OrderedDict = OrderedDict()
        self.evictions = 0
        self.subsystem = subsystem
        self.describe = describe
        self._handles: dict = {}  # key -> observatory handle
        self.last_handle = 0  # handle of the most recent store

    def get(self, key, default=None):
        try:
            self._d.move_to_end(key)
            v = self._d[key]
        except KeyError:
            return default
        if self.subsystem is not None:
            _obs.touch(self._handles.get(key, 0))
        return v

    def _describe(self, key):
        if self.describe is not None:
            try:
                return self.describe(key)
            except Exception:
                pass
        base = key[0] if isinstance(key, tuple) and key \
            and isinstance(key[0], str) else self.subsystem
        return str(base), _obs.facets_from_sig(key)

    def __setitem__(self, key, value):
        if key in self._d:
            self._d.move_to_end(key)
        elif self.subsystem is not None:
            base, facets = self._describe(key)
            h = _obs.register(self.subsystem, base, facets,
                              donated=bool(facets.get("donate")))
            self._handles[key] = h
            self.last_handle = h
            # every traceable program entering a registered cache gets
            # a progcheck proxy: its first dispatch through the cache
            # verifies the jaxpr (collective manifest, donation audit,
            # HBM estimate) against the real call args
            value = self._progcheck_wrap(value, base, h)
        self._d[key] = value
        while len(self._d) > self.maxsize:
            k, _ = self._d.popitem(last=False)
            self.evictions += 1
            _obs.mark_evicted(self._handles.pop(k, 0))

    def _progcheck_wrap(self, value, base, handle):
        if not hasattr(value, "trace") or not callable(value):
            return value
        from bodo_tpu.analysis import progcheck
        return progcheck.wrap_program(
            value, program=f"{self.subsystem}:{base}",
            subsystem=self.subsystem, obs_handle=handle)

    def __contains__(self, key):
        return key in self._d

    def __len__(self):
        return len(self._d)

    def handle_for(self, key) -> int:
        return self._handles.get(key, 0)

    def pop(self, key, default=None):
        _obs.mark_evicted(self._handles.pop(key, 0))
        return self._d.pop(key, default)

    def clear(self):
        for h in self._handles.values():
            _obs.mark_evicted(h)
        self._handles.clear()
        self._d.clear()


class FusionProgramCache(KernelCache):
    """LRU of compiled whole-stage fusion programs (plan/fusion.py),
    keyed by the fusion-group signature (op sequence + input schema/dict
    fingerprints + distribution + agg spec). Same eviction behavior as
    any kernel cache, plus the hit/miss/compile accounting that
    EXPLAIN ANALYZE, tracing.profile() and the metrics registry report
    per fusion boundary."""

    def __init__(self, maxsize: int = 256, *, subsystem=None,
                 describe=None):
        super().__init__(maxsize=maxsize, subsystem=subsystem,
                         describe=describe)
        self.hits = 0
        self.misses = 0
        self.compiles = 0
        self.compile_s = 0.0

    def lookup(self, key):
        """`get` with hit/miss accounting (use for dispatch lookups;
        plain `get` stays silent for introspection)."""
        fn = self.get(key)
        if fn is None:
            self.misses += 1
        else:
            self.hits += 1
        return fn

    def record_compile(self, program: str, seconds: float,
                       handle: int = None) -> None:
        """Account one program compilation (feeds the shared
        bodo_tpu_jit_compile_seconds histogram and the program
        registry's per-executable compile wall)."""
        self.compiles += 1
        self.compile_s += float(seconds)
        _obs.note_compile(self.last_handle if handle is None else handle,
                          seconds)
        from bodo_tpu.utils import metrics
        metrics.record_compile(program, seconds)

    def stats(self) -> dict:
        return {"size": len(self), "hits": self.hits,
                "misses": self.misses, "compiles": self.compiles,
                "compile_s": self.compile_s, "evictions": self.evictions}

    def reset_stats(self) -> None:
        self.hits = self.misses = self.compiles = 0
        self.compile_s = 0.0


class DecodeProgramCache(FusionProgramCache):
    """LRU of jitted parquet page-decode programs (io/device_decode.py),
    keyed by the page spec: encoding kind x output dtype x power-of-two
    shape buckets (page bytes, value count, run-table length, dictionary
    length) x null handling x timestamp scale. Bucketing makes the live
    program population a function of the SCHEMA, not the page count, so
    a million-page scan dispatches a handful of executables. Shares the
    fusion cache's hit/miss/compile accounting (EXPLAIN ANALYZE, the
    metrics registry, and tracing.profile() read the same shape)."""

    def __init__(self, maxsize: int = 128, *, subsystem=None,
                 describe=None):
        super().__init__(maxsize=maxsize, subsystem=subsystem,
                         describe=describe)

    def clear(self):
        super().clear()
        self.reset_stats()


def cached_builder(subsystem: str, maxsize: int = 256):
    """Registered replacement for `@lru_cache` on program-builder
    functions (hashable static config in, compiled program out): same
    memoization, but entries live in a subsystem-tagged KernelCache so
    every built program appears in the program registry with facet
    attribution, and eviction actually frees the executable (lru_cache
    would pin all 256 forever once warm)."""
    def deco(fun):
        def _describe(key):
            args, kw = key
            return fun.__name__, _obs.facets_from_sig(
                (fun.__name__,) + tuple(args) + tuple(v for _, v in kw))

        cache = KernelCache(maxsize=maxsize, subsystem=subsystem,
                            describe=_describe)

        @functools.wraps(fun)
        def wrapper(*args, **kwargs):
            key = (args, tuple(sorted(kwargs.items())))
            fn = cache.get(key)
            if fn is None:
                cache[key] = fun(*args, **kwargs)
                # hand back the cache's entry (the progcheck proxy for
                # traceable programs) so even the building call's first
                # dispatch is verified
                fn = cache.get(key)
            return fn

        wrapper.cache = cache
        wrapper.cache_clear = cache.clear
        return wrapper
    return deco


def _leaf_key(x):
    shape = getattr(x, "shape", None)
    if shape is not None and hasattr(x, "dtype"):
        return ("a", tuple(shape), str(x.dtype))
    return ("v", x)


def bounded_jit(fun=None, *, static_argnames=(), maxsize=None):
    """`jax.jit` whose live compiled executables are BOUNDED.

    A module-level `jax.jit` pins one executable per distinct
    (input avals, static args) combination forever in jax's unbounded
    per-function cache; a long session (or the 490-test suite in one
    process) accumulates thousands and XLA:CPU's compiler eventually
    segfaults. This wrapper creates one `jax.jit` object per
    combination, held in a `KernelCache` LRU keyed by the call's leaf
    avals + non-array leaf values, so evicting an entry lets jax
    garbage-collect its executables. Works inside an outer trace too
    (leaves are tracers with shape/dtype; the inner jit inlines).

    Every compiled variant registers with the program registry under
    subsystem "bounded_jit", base = the wrapped function's name, with
    shape/dtype/static facets from the cache key — so retraces are
    attributed (shape-bucket churn vs dtype churn) like any other
    subsystem's.
    """
    if fun is None:
        return functools.partial(bounded_jit,
                                 static_argnames=static_argnames,
                                 maxsize=maxsize)
    if maxsize is None:
        from bodo_tpu.config import config
        maxsize = config.kernel_cache_size

    def _describe(key):
        struct, leaf_keys = key
        return fun.__name__, _obs.facets_from_leaves(struct, leaf_keys)

    cache = KernelCache(maxsize=maxsize, subsystem="bounded_jit",
                        describe=_describe)

    @functools.wraps(fun)
    def wrapper(*args, **kwargs):
        import jax

        struct, leaves = None, None
        try:
            leaves, struct = jax.tree_util.tree_flatten((args, kwargs))
            key = (struct, tuple(_leaf_key(x) for x in leaves))
            hash(key)
        except TypeError:  # unhashable leaf — compile uncached
            return jax.jit(fun, static_argnames=static_argnames)(
                *args, **kwargs)
        fn = cache.get(key)
        if fn is None:
            fn = jax.jit(fun, static_argnames=static_argnames)
            cache[key] = fn
            # verify BEFORE timing so the recorded compile cost stays a
            # pure trace+lower+compile measurement
            from bodo_tpu.analysis import progcheck
            progcheck.check_jit(fn, args, kwargs,
                                program=f"bounded_jit:{fun.__name__}",
                                subsystem="bounded_jit",
                                obs_handle=cache.handle_for(key))
            # first invocation pays trace+lower+compile: record it as
            # this program's compile cost (bodo_tpu_jit_compile_seconds)
            t0 = time.perf_counter()
            out = fn(*args, **kwargs)
            dt = time.perf_counter() - t0
            _obs.note_compile(cache.handle_for(key), dt)
            from bodo_tpu.utils import metrics
            metrics.record_compile(fun.__name__, dt)
            return out
        return fn(*args, **kwargs)

    wrapper.cache = cache
    return wrapper
