"""bodo_tpu.views — materialized views & continuous queries.

Thin façade over ``runtime/views.py``: named materialized views that
compose into a DAG and are maintained incrementally on the serving
path. A view's materialization lives in the semantic result cache;
downstream views scan it like a table, and a base-table change
propagates topologically — appends splice a delta scan, partition-level
mutates re-merge only the affected source file's contribution, anything
ambiguous falls back to a full recompute (never a stale partial).

    import bodo_tpu
    daily = df.groupby("day").agg(s=("v", "sum"))
    bodo_tpu.views.create_view("daily", daily)
    weekly = bodo_tpu.views.read("daily").groupby("week")...
    bodo_tpu.views.create_view("weekly", weekly)

    out = bodo_tpu.views.read("weekly").to_pandas()   # serves cached

Continuous queries ride the serving layer: a tenant session registers
``session.subscribe("weekly", max_staleness_s=5.0)`` and receives every
refresh through ``Subscription.next()``; the scheduler's idle workers
poll base signatures between queue drains and run refreshes as
weighted-fair work on the system maintenance session (tenants are not
billed for shared maintenance).

Knobs: ``BODO_TPU_VIEW_*`` (see config.py) — watcher poll interval,
maintenance session weight, partition-map size bound.
"""

from __future__ import annotations

from bodo_tpu.runtime.views import (  # noqa: F401 - public re-exports
    MAINTENANCE_SESSION,
    Subscription,
    ViewError,
    base_sources,
    create_view,
    drop_view,
    list_views,
    materialized_table,
    read,
    refresh,
    reset,
    scan_node,
    stats,
    subscribe,
)

__all__ = [
    "create_view", "drop_view", "list_views", "read", "refresh",
    "materialized_table", "scan_node", "base_sources", "subscribe",
    "stats", "reset", "Subscription", "ViewError",
    "MAINTENANCE_SESSION",
]
