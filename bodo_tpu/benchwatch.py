"""Bench regression watcher: ``python -m bodo_tpu.benchwatch``.

The repo accumulates one ``BENCH_r<NN>.json`` artifact per growth round
(written by the driver that runs ``bench.py``); each carries the stable
envelope ``{"n", "cmd", "rc", "tail", "parsed"}`` where ``parsed`` is
the bench's summary line ``{"metric", "value", "unit", "vs_baseline",
"detail"}``. This module is the trajectory's watchdog: it validates
every artifact against that schema — loudly, a malformed artifact is a
broken contract, not something to skip — groups records per metric,
and compares the newest run against the history with direction-aware
relative thresholds (an ``x``/``MB/s`` metric regresses when it drops;
an ``s``/``frac`` metric regresses when it rises).

``bench.py --compare`` invokes the same comparison after a fresh run,
and ``runtests.py`` (full suite) runs ``--check`` as a gate so a
silently-degrading trajectory fails CI rather than a human's memory.

An artifact may carry an envelope-level ``"waiver": "<reason>"`` when
its round ran in a provably degraded environment (the reason should
name the control experiment). A waiver downgrades a regression verdict
for THAT round only from FAIL to WAIVED — rendered loudly with the
reason, never silently — so an invalid measurement doesn't block CI
while the trajectory still records what was measured.

Stdlib-only on purpose: the watcher must run on machines with no jax.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Dict, List, Optional

_BENCH_RE = re.compile(r"BENCH_r(\d+)\.json$")

# direction-aware threshold semantics keyed by the metric's unit
_HIGHER_BETTER = {"x", "mb/s", "gb/s", "mrows/s", "rows/s", "qps",
                  "hitrate"}
_LOWER_BETTER = {"s", "ms", "us", "frac", "%", "ratio"}

_ENVELOPE_KEYS = ("n", "cmd", "rc", "parsed")
_PARSED_KEYS = ("metric", "value", "unit")


def _validate(rec: dict, path: str) -> List[str]:
    """Schema errors for one artifact (empty list == valid)."""
    errs = []
    for k in _ENVELOPE_KEYS:
        if k not in rec:
            errs.append(f"{path}: missing envelope key {k!r}")
    parsed = rec.get("parsed")
    if parsed is None and not errs:
        return errs  # rc may be nonzero with nothing parsed
    if not isinstance(parsed, dict):
        errs.append(f"{path}: 'parsed' is not an object")
        return errs
    for k in _PARSED_KEYS:
        if k not in parsed:
            errs.append(f"{path}: parsed summary missing {k!r}")
    if "value" in parsed and not isinstance(parsed["value"],
                                            (int, float)):
        errs.append(f"{path}: parsed 'value' is not numeric")
    return errs


def load_trajectory(bench_dir: str) -> dict:
    """Read and validate every BENCH_r*.json under ``bench_dir``.
    Returns {"records": [...sorted by round...], "errors": [...]};
    unreadable or schema-violating artifacts land in errors and are
    excluded from records."""
    records, errors = [], []
    for path in sorted(glob.glob(os.path.join(bench_dir,
                                              "BENCH_r*.json"))):
        m = _BENCH_RE.search(os.path.basename(path))
        if not m:
            continue
        try:
            with open(path, "r") as f:
                rec = json.load(f)
        except (OSError, ValueError) as e:
            errors.append(f"{path}: unreadable ({e})")
            continue
        if not isinstance(rec, dict):
            errors.append(f"{path}: not a JSON object")
            continue
        errs = _validate(rec, os.path.basename(path))
        if errs:
            errors.extend(errs)
            continue
        rec["_round"] = int(m.group(1))
        rec["_path"] = os.path.basename(path)
        records.append(rec)
    records.sort(key=lambda r: r["_round"])
    return {"records": records, "errors": errors}


def _suite_summaries(rec: dict):
    """Yield every parsed summary one artifact carries: the top-level
    ``parsed`` line plus any per-suite summaries embedded under
    ``parsed.detail.suites`` (a round that runs several suites records
    each suite's own ``{"metric", "value", "unit"}`` there — r06
    onward). Each embedded summary becomes its own metric series in the
    trajectory, so a suite regression cannot hide behind a healthy
    headline number. Embedded entries missing the summary keys are
    skipped silently — ``detail`` is free-form; only well-formed suite
    summaries are promoted to tracked metrics."""
    parsed = rec.get("parsed")
    if not parsed:
        return
    yield parsed
    detail = parsed.get("detail")
    suites = detail.get("suites") if isinstance(detail, dict) else None
    if not isinstance(suites, dict):
        return
    for sub in suites.values():
        if (isinstance(sub, dict) and "metric" in sub
                and "unit" in sub
                and isinstance(sub.get("value"), (int, float))):
            yield sub


def _direction(unit: str) -> int:
    """+1 when larger values are better, -1 when smaller are, 0 when
    the unit is unknown (compared informationally, never failed)."""
    u = (unit or "").strip().lower()
    if u in _HIGHER_BETTER:
        return 1
    if u in _LOWER_BETTER:
        return -1
    return 0


def compare(records: List[dict], *, threshold: float = 0.15,
            against: str = "best") -> dict:
    """Compare the newest run of each metric against its history.

    ``against`` picks the reference: "best" (history's best value in
    the metric's direction — catches decay from the high-water mark),
    "prev" (previous round only), or "median". A metric regresses when
    the latest value is worse than the reference by more than
    ``threshold`` (relative). Metrics seen only once are "new".

    Embedded per-suite summaries (``parsed.detail.suites``) are lifted
    into their own metric series alongside the headline metric, sharing
    the round's envelope (round number, waiver) — see
    ``_suite_summaries``."""
    by_metric: Dict[str, List[dict]] = {}
    for rec in records:
        for parsed in _suite_summaries(rec):
            by_metric.setdefault(parsed["metric"], []).append(
                {"parsed": parsed, "_round": rec["_round"],
                 "_path": rec["_path"], "waiver": rec.get("waiver")})

    verdicts = {}
    for metric, recs in sorted(by_metric.items()):
        latest = recs[-1]
        lval = float(latest["parsed"]["value"])
        unit = latest["parsed"].get("unit", "")
        sign = _direction(unit)
        v: dict = {
            "unit": unit,
            "latest": lval,
            "latest_round": latest["_round"],
            "rounds": len(recs),
            "series": [round(float(r["parsed"]["value"]), 6)
                       for r in recs],
        }
        hist = [float(r["parsed"]["value"]) for r in recs[:-1]]
        if not hist:
            v["status"] = "new"
            verdicts[metric] = v
            continue
        if against == "prev":
            ref = hist[-1]
        elif against == "median":
            s = sorted(hist)
            ref = s[len(s) // 2]
        else:  # best
            ref = max(hist) if sign >= 0 else min(hist)
        v["reference"] = round(ref, 6)
        v["against"] = against
        if ref:
            delta = (lval - ref) / abs(ref)
        else:
            delta = 0.0 if lval == ref else 1.0
        v["delta_frac"] = round(delta, 4)
        if sign == 0:
            v["status"] = "untracked"  # unknown unit: report only
        elif sign * delta < -threshold:
            waiver = latest.get("waiver")
            if waiver and isinstance(waiver, str):
                v["status"] = "waived"
                v["waiver"] = waiver
            else:
                v["status"] = "regression"
        elif sign * delta > threshold:
            v["status"] = "improvement"
        else:
            v["status"] = "stable"
        verdicts[metric] = v

    failed_runs = [r["_path"] for r in records if r.get("rc")]
    return {
        "metrics": verdicts,
        "threshold": threshold,
        "failed_runs": failed_runs,
        "regressions": sorted(m for m, v in verdicts.items()
                              if v["status"] == "regression"),
    }


def watch(bench_dir: str, *, threshold: float = 0.15,
          against: str = "best") -> dict:
    """load_trajectory + compare in one verdict dict (adds "errors"
    and an overall "ok" that --check gates on)."""
    traj = load_trajectory(bench_dir)
    out = compare(traj["records"], threshold=threshold,
                  against=against)
    out["errors"] = traj["errors"]
    out["n_artifacts"] = len(traj["records"])
    out["ok"] = (not traj["errors"] and not out["regressions"]
                 and bool(traj["records"]))
    if not traj["records"] and not traj["errors"]:
        out["errors"] = [f"no BENCH_r*.json artifacts in "
                         f"{os.path.abspath(bench_dir)}"]
        out["ok"] = False
    return out


def render(out: dict) -> str:
    lines = [f"BENCH WATCH  artifacts={out.get('n_artifacts', 0)}  "
             f"threshold={out['threshold']:.0%}"]
    for metric, v in sorted(out["metrics"].items()):
        flag = {"regression": "REGRESSION", "improvement": "improved",
                "stable": "ok", "new": "new", "waived": "WAIVED",
                "untracked": "untracked"}[v["status"]]
        line = (f"  {metric}: {v['latest']:g} {v['unit']} "
                f"(round {v['latest_round']}, {flag}")
        if "reference" in v:
            line += (f"; {v['delta_frac']:+.1%} vs {v['against']} "
                     f"{v['reference']:g}")
        lines.append(line + ")")
        if v.get("waiver"):
            lines.append(f"    waived: {v['waiver']}")
        series = " -> ".join(f"{x:g}" for x in v["series"][-8:])
        lines.append(f"    trajectory: {series}")
    for path in out.get("failed_runs", []):
        lines.append(f"  WARNING: {path} recorded a nonzero bench rc")
    for err in out.get("errors", []):
        lines.append(f"  SCHEMA ERROR: {err}")
    verdict = "OK" if out.get("ok") else "FAIL"
    if out.get("regressions"):
        verdict += " (regressed: " + ", ".join(out["regressions"]) + ")"
    lines.append(f"verdict: {verdict}")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m bodo_tpu.benchwatch",
        description="Compare the BENCH_r*.json bench trajectory and "
                    "flag regressions.")
    ap.add_argument("--dir", default=".",
                    help="directory holding BENCH_r*.json (default: .)")
    ap.add_argument("--threshold", type=float, default=0.15,
                    help="relative regression threshold (default 0.15)")
    ap.add_argument("--against", choices=("best", "prev", "median"),
                    default="best",
                    help="history reference to compare the latest "
                         "round to (default: best)")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 on any regression, schema violation, "
                         "or empty trajectory (CI gate)")
    ap.add_argument("--json", action="store_true",
                    help="print the machine-readable verdict")
    args = ap.parse_args(argv)
    out = watch(args.dir, threshold=args.threshold,
                against=args.against)
    if args.json:
        print(json.dumps(out, indent=2, sort_keys=True))
    else:
        print(render(out))
    if args.check and not out["ok"]:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
