"""Multi-host SPMD process launcher (reference bodo/spawn/ analogue).

The reference lazily `MPI_Comm_spawn`s persistent workers and ships
cloudpickled functions to them (bodo/spawn/spawner.py:134 Spawner,
worker.py:636 worker_loop). On TPU pods the runtime launches one process
per host and `jax.distributed.initialize` forms the cluster over a gRPC
coordinator instead of an MPI intercomm.

`run_spmd(fn, n)` is the spawner surface: it forks n local processes,
initializes a jax.distributed CPU cluster among them (the same code path
a real multi-host pod uses), runs `fn(process_index)` in each, and
gathers the per-process return values — the analogue of
`submit_func_to_workers` + per-rank result gathering (spawner.py:292,
:383). Used for testing the multi-host path without hardware; production
pods set JAX_COORDINATOR_ADDRESS/JAX_NUM_PROCESSES/JAX_PROCESS_ID per
host and call bodo_tpu.init_runtime() instead.

SUPERVISION (runtime/resilience.py integration): every worker loads the
resilience module standalone BEFORE importing jax — so an armed
`spawn.worker_start` kill/raise fires in ~0.2s — then starts a heartbeat
file the parent watches. The parent waits on the whole gang concurrently
against one shared deadline and fast-fails the moment any rank dies
(non-zero exit) or goes silent past the heartbeat window, killing the
rest of the gang immediately and raising a structured `SpawnError` with
per-rank diagnostics. When every failing rank's stderr classifies as
transient (coordination-service init flake), the gang is retried once
(BODO_TPU_SPAWN_GANG_RETRIES).
"""

from __future__ import annotations

import os
import pickle
import signal
import socket
import subprocess
import sys
import tempfile
import time
from typing import Callable, Dict, List, Optional

import cloudpickle

from bodo_tpu.runtime import resilience

_WORKER_CODE = r"""
import os, pickle, sys


def _load_resilience():
    # standalone load by file path: no bodo_tpu package import, no jax —
    # an armed spawn.worker_start fault fires before any heavy import
    path = os.environ.get("BODO_TPU_RESIL_PATH")
    if not path:
        return None
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "bodo_tpu_resilience_boot", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["bodo_tpu_resilience_boot"] = mod
    spec.loader.exec_module(mod)
    return mod


def main():
    payload_path, out_path = sys.argv[1], sys.argv[2]
    resil = _load_resilience()
    if resil is not None:
        resil.maybe_inject("spawn.worker_start")
        hb = os.environ.get("BODO_TPU_HB_PATH")
        if hb:
            resil.start_heartbeat(hb)
    import cloudpickle
    import jax
    jax.config.update("jax_platforms", "cpu")
    try:
        # sampler + SIGUSR1 side-channel dump (trace shard, thread
        # stacks) so the spawner's teardown grace can collect this
        # rank's lane even when the rank is about to be hard-killed
        from bodo_tpu.runtime import telemetry
        telemetry.worker_init()
    except Exception:
        pass

    def _init():
        jax.distributed.initialize(
            coordinator_address=os.environ["BODO_TPU_COORD"],
            num_processes=int(os.environ["BODO_TPU_NPROCS"]),
            process_id=int(os.environ["BODO_TPU_PROC_ID"]),
        )

    def _reset(exc, attempt):
        try:
            jax.distributed.shutdown()
        except Exception:
            pass

    # elastic gangs (runtime/elastic.py) opt out of the shared
    # jax.distributed cluster: recovery moves state through host files,
    # and a shared coordination service would fatally terminate the
    # SURVIVORS ~100s after a rank loss (heartbeat timeout at the
    # shutdown barrier) — exactly the failure elasticity exists to
    # absorb. Real pods re-form the cluster per mesh epoch instead
    # (config.elastic_remesh_distributed).
    if os.environ.get("BODO_TPU_NO_JAX_DIST") != "1":
        if resil is not None:
            resil.retry_call(_init, label="jax_distributed_init",
                             on_retry=_reset)
        else:
            _init()
    with open(payload_path, "rb") as f:
        fn = cloudpickle.load(f)
    try:
        result = fn(jax.process_index())
    finally:
        _dump_trace_shard()
    with open(out_path + ".tmp", "wb") as f:
        pickle.dump(result, f)
    os.replace(out_path + ".tmp", out_path)


def _dump_trace_shard():
    # if the worker fn traced anything (tracing module loaded + events
    # recorded), leave a chrome-trace shard in the gang dir for the
    # spawner to merge into one multi-rank timeline; best-effort — a
    # shard failure never fails the worker
    tr = sys.modules.get("bodo_tpu.utils.tracing")
    d = os.environ.get("BODO_TPU_TRACE_SHARD_DIR")
    if tr is None or not d:
        return
    try:
        if tr.has_events():
            tr.dump_shard(d)
    except Exception:
        pass


main()
"""

_POLL_S = 0.05
_STDERR_TAIL = 800
# teardown grace: how long the spawner waits for SIGUSR1'd ranks to
# leave their trace shard + stacks (usr1_done_<rank> marker) before the
# uncatchable SIGKILL lands
_DUMP_GRACE_S = 2.0


class SpawnError(RuntimeError):
    """A gang launch failed. `ranks` maps every rank to a diagnostic
    dict: state ("ok" / "dead" / "hung" / "timeout" / "killed" /
    "evicted"), returncode, and a stderr tail for ranks that failed.
    "evicted" means the rank exited clean after a shrink-eviction
    (runtime/elastic.py) — it is never a gang failure. `reason` is the
    gang-level failure ("worker death", "hung worker", "gang timeout");
    `transient` is True when every failing rank's stderr classified as a
    transient flake (the caller may gang-retry)."""

    def __init__(self, reason: str, ranks: Dict[int, dict],
                 transient: bool = False):
        self.reason = reason
        self.ranks = ranks
        self.transient = transient
        lines = [f"spawn gang failed ({reason}):"]
        for i in sorted(ranks):
            d = ranks[i]
            line = f"  rank {i}: {d['state']}"
            if d.get("returncode") is not None:
                line += f" rc={d['returncode']}"
            lines.append(line)
            tail = d.get("stderr")
            if tail:
                lines.append("    " + tail.replace("\n", "\n    "))
        super().__init__("\n".join(lines))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _tracing_level() -> int:
    # workers inherit the parent's EFFECTIVE tracing level: set_config
    # changes config without touching the environment
    try:
        from bodo_tpu.config import config
        return int(config.tracing_level)
    except Exception:  # pragma: no cover
        return 0


# last merged multi-rank trace (and where it was written, when
# config.trace_dir is set): the programmatic handle for the gang
# timeline, since the gang temp dir itself is deleted after the run
_last_gang_trace: Optional[dict] = None
_last_gang_trace_path: Optional[str] = None


def last_gang_trace() -> Optional[dict]:
    return _last_gang_trace


def last_gang_trace_path() -> Optional[str]:
    return _last_gang_trace_path


def _merge_gang_trace(d: str) -> None:
    """Merge any worker trace shards from the gang dir into one
    multi-rank timeline BEFORE the TemporaryDirectory is cleaned up;
    written to config.trace_dir when set, always stashed in
    `last_gang_trace()`. Best-effort: runs on both the success and the
    failure path (a partial timeline is exactly what you want when
    diagnosing which rank died where)."""
    global _last_gang_trace, _last_gang_trace_path
    try:
        from bodo_tpu.config import config
        from bodo_tpu.utils import tracing
        out_path = None
        if config.trace_dir:
            os.makedirs(config.trace_dir, exist_ok=True)
            out_path = os.path.join(
                config.trace_dir,
                f"trace_gang_{os.getpid()}_{int(time.time() * 1e3)}.json")
        merged = tracing.merge_trace_shards(d, out_path)
        if merged is not None:
            _last_gang_trace = merged
            _last_gang_trace_path = out_path
    except Exception:  # noqa: BLE001 - observability must not fail gangs
        pass


def _register_gang_health(d: str, procs, hb_paths, start: float,
                          evicted=None) -> None:
    """Expose this gang's per-rank liveness to /healthz while it runs:
    the telemetry endpoint's server thread polls the provider closure
    (proc returncodes, heartbeat file ages, lockstep log tails)
    concurrently with the supervision loop. `evicted` is an optional
    callable returning the rank set shrink-evicted by the elastic
    layer — those ranks are flagged so /healthz reports reduced
    capacity, not an unhealthy gang. Best-effort — telemetry must
    never fail a gang."""
    try:
        from bodo_tpu.runtime import telemetry
    except Exception:  # pragma: no cover
        return

    def provider() -> Dict[int, dict]:
        now = time.monotonic()
        gone = set()
        if evicted is not None:
            try:
                gone = set(evicted())
            except Exception:  # pragma: no cover
                gone = set()
        out: Dict[int, dict] = {}
        for i, p in enumerate(procs):
            rc = p.poll()
            out[i] = {
                "alive": rc is None,
                "returncode": rc,
                "hb_age_s": round(_hb_age(hb_paths[i], now - start), 3),
                "last_collective": telemetry.lockstep_log_tail(d, i),
            }
            if i in gone:
                out[i]["evicted"] = True
        return out

    try:
        telemetry.set_gang_health_provider(provider)
    except Exception:  # pragma: no cover
        pass


def _clear_gang_health() -> None:
    tl = sys.modules.get("bodo_tpu.runtime.telemetry")
    if tl is not None:
        try:
            tl.set_gang_health_provider(None)
        except Exception:  # pragma: no cover
            pass


def _dump_flight_bundle(reason: str, ranks: Dict[int, dict],
                        gang_dir: str) -> None:
    """Flight-recorder bundle at the moment of gang failure, while the
    gang temp dir (trace shards, lockstep logs, worker stderr, SIGUSR1
    stack dumps) still exists."""
    try:
        from bodo_tpu.runtime import telemetry
        telemetry.dump_bundle("spawn_" + reason.replace(" ", "_"),
                              gang_dir=gang_dir, ranks=ranks)
    except Exception:  # noqa: BLE001 - diagnostics never fail the gang
        pass


def _hb_age(path: str, fallback_age: float) -> float:
    """Seconds since the worker's last heartbeat; until the first beat
    lands the age is measured from gang start (startup grace). The
    heartbeat file's mtime is in wall-clock epoch seconds, so it must be
    compared against time.time() — not the monotonic clock the
    supervision deadline uses — or the age would clamp to 0 forever."""
    try:
        return max(0.0, time.time() - os.path.getmtime(path))
    except OSError:
        return fallback_age


def _worker_env(d: str, i: int, n_processes: int, coord: str,
                resil_path: str, pkg_root: str,
                hb_path: str) -> Dict[str, str]:
    """Environment for one gang worker — shared between the plain
    spawner below and the elastic gang launcher (runtime/elastic.py),
    so the two can never drift on what a worker inherits."""
    env = dict(os.environ)
    # workers join the active query span: the id usually rides
    # os.environ already (query_span exports it), but a
    # contextvar-only span still propagates here
    try:
        from bodo_tpu.utils import tracing
        qid = tracing.current_query_id()
        if qid:
            env["BODO_TPU_QUERY_ID"] = qid
    except Exception:  # pragma: no cover
        pass
    env.update({
        "BODO_TPU_COORD": coord,
        "BODO_TPU_NPROCS": str(n_processes),
        "BODO_TPU_PROC_ID": str(i),
        # stable gang identity: inherited when the spawner is itself a
        # fleet gang (so sub-workers attribute to the owning gang),
        # minted from the spawner pid otherwise — controller logs,
        # /healthz and doctor output name gangs by this, never by
        # pid/port
        "BODO_TPU_GANG_ID":
            os.environ.get("BODO_TPU_GANG_ID")
            or f"gang-{os.getpid()}",
        "BODO_TPU_RESIL_PATH": resil_path,
        "BODO_TPU_HB_PATH": hb_path,
        # lockstep side-channel logs share the gang temp dir (fresh
        # per gang, so sequence numbers never collide with a previous
        # gang's logs); the mode itself is armed via
        # BODO_TPU_LOCKSTEP, inherited from the parent environment
        "BODO_TPU_LOCKSTEP_DIR": d,
        # trace shards ride the same gang-scoped side channel; the
        # spawner merges them before the dir is cleaned up
        "BODO_TPU_TRACE_SHARD_DIR": d,
        "BODO_TPU_TRACING_LEVEL": str(_tracing_level()),
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": pkg_root + os.pathsep +
        env.get("PYTHONPATH", ""),
    })
    return env


def run_spmd(fn: Callable[[int], object], n_processes: int = 2,
             timeout: float = 180.0) -> List[object]:
    """Run `fn(process_index)` across n freshly spawned processes joined
    into one jax.distributed cluster. Returns per-process results in rank
    order. On failure raises a structured `SpawnError` (per-rank state +
    stderr) as soon as the first rank dies or goes silent — not after
    the full timeout — and gang-retries once when every failure looks
    like a transient coordination flake."""
    retries = int(resilience._cfg("spawn_gang_retries",
                                  "BODO_TPU_SPAWN_GANG_RETRIES", 1, int))
    attempt = 0
    while True:
        try:
            return _run_gang(fn, n_processes, timeout)
        except SpawnError as e:
            if attempt >= retries or not e.transient:
                raise
            attempt += 1
            resilience.count_gang_retry()
            sys.stderr.write(
                f"bodo_tpu.spawn: gang attempt {attempt} failed with a "
                f"transient error ({e.reason}); retrying\n")


def _run_gang(fn: Callable[[int], object], n_processes: int,
              timeout: float) -> List[object]:
    hb_timeout = resilience._cfg("spawn_hb_timeout_s",
                                 "BODO_TPU_SPAWN_HB_TIMEOUT", 15.0, float)
    resil_path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "runtime", "resilience.py")
    with tempfile.TemporaryDirectory(prefix="bodo_tpu_spawn_") as d:
        payload = os.path.join(d, "fn.pkl")
        with open(payload, "wb") as f:
            cloudpickle.dump(fn, f)
        worker_py = os.path.join(d, "worker.py")
        with open(worker_py, "w") as f:
            f.write(_WORKER_CODE)
        coord = f"127.0.0.1:{_free_port()}"
        # workers must import this package (cloudpickle references it
        # by module), wherever the parent had it on its path
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        procs: List[subprocess.Popen] = []
        outs: List[str] = []
        err_paths: List[str] = []
        hb_paths: List[str] = []
        handles = []
        start = time.monotonic()
        try:
            for i in range(n_processes):
                out_path = os.path.join(d, f"out_{i}.pkl")
                err_path = os.path.join(d, f"err_{i}.log")
                hb_path = os.path.join(d, f"hb_{i}")
                outs.append(out_path)
                err_paths.append(err_path)
                hb_paths.append(hb_path)
                env = _worker_env(d, i, n_processes, coord, resil_path,
                                  pkg_root, hb_path)
                # stderr goes to a file, not a pipe: the parent polls
                # instead of blocking in communicate(), and a chatty
                # worker can never deadlock on a full pipe buffer
                ef = open(err_path, "wb")
                of = open(os.path.join(d, f"stdout_{i}.log"), "wb")
                handles += [ef, of]
                procs.append(subprocess.Popen(
                    [sys.executable, worker_py, payload, out_path],
                    env=env, stdout=of, stderr=ef))
            # shrink-evicted ranks (elastic layer) exit clean without a
            # result and must read as reduced capacity, never as a gang
            # failure — the marker file is the eviction record
            def _evicted() -> set:
                return {i for i in range(n_processes)
                        if os.path.exists(os.path.join(d, f"evicted_{i}"))}

            _register_gang_health(d, procs, hb_paths, start,
                                  evicted=_evicted)
            reason, failing = _supervise(procs, hb_paths, start, timeout,
                                         hb_timeout, evicted=_evicted)
            if reason is None:
                results = []
                gone = _evicted()
                for i, out_path in enumerate(outs):
                    if i in gone:
                        continue
                    if not os.path.exists(out_path):
                        reason, failing = "missing result", {i}
                        break
                else:
                    outs = [o for i, o in enumerate(outs)
                            if i not in gone]
                    for out_path in outs:
                        with open(out_path, "rb") as f:
                            results.append(pickle.load(f))
                    _merge_gang_trace(d)
                    return results
            # fast-fail: tear down the rest of the gang NOW — but give
            # live ranks one SIGUSR1 grace window first. The telemetry
            # handler in each worker dumps its trace shard + thread
            # stacks into the gang dir and drops a usr1_done_<rank>
            # marker; straight SIGKILL (uncatchable) would lose exactly
            # the lanes a post-mortem needs. A rank wedged inside
            # native code never runs the handler — the deadline bounds
            # the wait either way.
            live = [i for i, p in enumerate(procs)
                    if p.poll() is None]
            for i in live:
                try:
                    procs[i].send_signal(signal.SIGUSR1)
                except OSError:  # pragma: no cover - exited just now
                    pass
            grace = time.monotonic() + _DUMP_GRACE_S
            while live and time.monotonic() < grace:
                live = [i for i in live
                        if procs[i].poll() is None
                        and not os.path.exists(
                            os.path.join(d, f"usr1_done_{i}"))]
                if live:
                    time.sleep(_POLL_S)
            for p in procs:
                if p.poll() is None:
                    p.kill()
            for p in procs:
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:  # pragma: no cover
                    pass
            ranks: Dict[int, dict] = {}
            transient = bool(failing)
            gone = _evicted()
            for i, p in enumerate(procs):
                rc = p.poll()
                if i in gone:
                    # exited clean after shrink-eviction: reduced
                    # capacity, not a failed rank — the flight-recorder
                    # manifest must not blame it for the gang failure
                    state = "evicted"
                elif i in failing:
                    state = ("hung" if reason == "hung worker" else
                             "timeout" if reason == "gang timeout" else
                             "dead")
                elif rc == 0:
                    state = "ok"
                else:
                    state = "killed"  # collateral of the gang teardown
                diag = {"state": state, "returncode": rc}
                if state != "ok":
                    try:
                        with open(err_paths[i], "rb") as f:
                            tail = f.read()[-_STDERR_TAIL:].decode(
                                "utf-8", "replace").strip()
                    except OSError:
                        tail = ""
                    diag["stderr"] = tail
                    if i in failing:
                        diag["transient"] = \
                            resilience.classify_transient_text(tail)
                        if reason != "worker death" or \
                                not diag["transient"]:
                            transient = False
                ranks[i] = diag
            _merge_gang_trace(d)
            _dump_flight_bundle(reason, ranks, d)
            raise SpawnError(reason, ranks, transient=transient)
        finally:
            _clear_gang_health()
            for p in procs:
                if p.poll() is None:  # pragma: no cover - safety net
                    p.kill()
            for h in handles:
                h.close()


def _supervise(procs, hb_paths, start, timeout, hb_timeout,
               evicted=None):
    """Wait on all ranks concurrently against one shared deadline.
    Returns (None, set()) when every rank exited 0, else
    (reason, failing_rank_set) at the FIRST failure — a dead rank is
    noticed within one poll interval, not after earlier ranks finish.

    `evicted` is an optional callable returning the set of ranks the
    elastic layer shrink-evicted: those exited (or were torn down)
    deliberately, so they are excluded from the death/hang checks and
    from the all-exited-clean completion condition — a rank that left
    the mesh on purpose is not a rank that died."""
    deadline = start + timeout
    while True:
        now = time.monotonic()
        gone = set(evicted()) if evicted is not None else set()
        rcs = [p.poll() for p in procs]
        dead = {i for i, rc in enumerate(rcs)
                if rc not in (None, 0) and i not in gone}
        if dead:
            return "worker death", dead
        if all(rc == 0 for i, rc in enumerate(rcs) if i not in gone) \
                and all(rc is not None for rc in rcs):
            return None, set()
        hung = set()
        for i, rc in enumerate(rcs):
            if rc is None and i not in gone and \
                    _hb_age(hb_paths[i], now - start) > hb_timeout:
                hung.add(i)
        if hung:
            return "hung worker", hung
        if now >= deadline:
            return "gang timeout", {i for i, rc in enumerate(rcs)
                                    if rc is None and i not in gone}
        time.sleep(_POLL_S)
