"""Multi-host SPMD process launcher (reference bodo/spawn/ analogue).

The reference lazily `MPI_Comm_spawn`s persistent workers and ships
cloudpickled functions to them (bodo/spawn/spawner.py:134 Spawner,
worker.py:636 worker_loop). On TPU pods the runtime launches one process
per host and `jax.distributed.initialize` forms the cluster over a gRPC
coordinator instead of an MPI intercomm.

`run_spmd(fn, n)` is the spawner surface: it forks n local processes,
initializes a jax.distributed CPU cluster among them (the same code path
a real multi-host pod uses), runs `fn(process_index)` in each, and
gathers the per-process return values — the analogue of
`submit_func_to_workers` + per-rank result gathering (spawner.py:292,
:383). Used for testing the multi-host path without hardware; production
pods set JAX_COORDINATOR_ADDRESS/JAX_NUM_PROCESSES/JAX_PROCESS_ID per
host and call bodo_tpu.init_runtime() instead.
"""

from __future__ import annotations

import os
import pickle
import socket
import subprocess
import sys
import tempfile
from typing import Callable, List

import cloudpickle

_WORKER_CODE = r"""
import os, pickle, sys
import cloudpickle

def main():
    payload_path, out_path = sys.argv[1], sys.argv[2]
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(
        coordinator_address=os.environ["BODO_TPU_COORD"],
        num_processes=int(os.environ["BODO_TPU_NPROCS"]),
        process_id=int(os.environ["BODO_TPU_PROC_ID"]),
    )
    with open(payload_path, "rb") as f:
        fn = cloudpickle.load(f)
    result = fn(jax.process_index())
    with open(out_path, "wb") as f:
        pickle.dump(result, f)

main()
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def run_spmd(fn: Callable[[int], object], n_processes: int = 2,
             timeout: float = 180.0) -> List[object]:
    """Run `fn(process_index)` across n freshly spawned processes joined
    into one jax.distributed cluster. Returns per-process results in rank
    order. Exceptions in any worker surface with its stderr attached."""
    with tempfile.TemporaryDirectory(prefix="bodo_tpu_spawn_") as d:
        payload = os.path.join(d, "fn.pkl")
        with open(payload, "wb") as f:
            cloudpickle.dump(fn, f)
        worker_py = os.path.join(d, "worker.py")
        with open(worker_py, "w") as f:
            f.write(_WORKER_CODE)
        coord = f"127.0.0.1:{_free_port()}"
        procs = []
        outs = []
        for i in range(n_processes):
            out_path = os.path.join(d, f"out_{i}.pkl")
            outs.append(out_path)
            env = dict(os.environ)
            # workers must import this package (cloudpickle references it
            # by module), wherever the parent had it on its path
            pkg_root = os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))
            env.update({
                "BODO_TPU_COORD": coord,
                "BODO_TPU_NPROCS": str(n_processes),
                "BODO_TPU_PROC_ID": str(i),
                "JAX_PLATFORMS": "cpu",
                "PYTHONPATH": pkg_root + os.pathsep +
                env.get("PYTHONPATH", ""),
            })
            procs.append(subprocess.Popen(
                [sys.executable, worker_py, payload, out_path],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE))
        results = []
        errs = []
        for i, p in enumerate(procs):
            try:
                _, err = p.communicate(timeout=timeout)
            except subprocess.TimeoutExpired:
                p.kill()
                _, err = p.communicate()
                errs.append(f"rank {i}: timeout\n{err.decode()[-800:]}")
                continue
            if p.returncode != 0:
                errs.append(f"rank {i} rc={p.returncode}:\n"
                            f"{err.decode()[-800:]}")
        if errs:
            raise RuntimeError("spawn workers failed:\n" + "\n".join(errs))
        for out_path in outs:
            with open(out_path, "rb") as f:
                results.append(pickle.load(f))
        return results
