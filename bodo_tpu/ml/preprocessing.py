"""Preprocessing (reference bodo/ml_support/sklearn_preprocessing_ext.py —
distributed stats via allreduce)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from bodo_tpu.ml._data import _materialize, to_device_xy


class StandardScaler:
    def __init__(self, with_mean: bool = True, with_std: bool = True):
        self.with_mean = with_mean
        self.with_std = with_std

    def fit(self, X):
        Xd, _, mask, n = to_device_xy(X)
        w = mask.astype(Xd.dtype)[:, None]
        cnt = jnp.maximum(jnp.sum(w), 1)
        mean = jnp.sum(Xd * w, axis=0) / cnt
        var = jnp.sum(((Xd - mean) ** 2) * w, axis=0) / cnt
        self.mean_ = np.asarray(jax.device_get(mean))
        self.var_ = np.asarray(jax.device_get(var))
        self.scale_ = np.sqrt(np.where(self.var_ > 0, self.var_, 1.0))
        self.n_samples_seen_ = n
        return self

    def transform(self, X):
        Xh = np.asarray(_materialize(X), dtype=np.float64)
        if Xh.ndim == 1:
            Xh = Xh[:, None]
        out = Xh
        if self.with_mean:
            out = out - self.mean_
        if self.with_std:
            out = out / self.scale_
        return out

    def fit_transform(self, X):
        return self.fit(X).transform(X)


class LabelEncoder:
    def fit(self, y):
        yv = np.asarray(_materialize(y)).reshape(-1)
        self.classes_ = np.unique(yv)
        return self

    def transform(self, y):
        yv = np.asarray(_materialize(y)).reshape(-1)
        return np.searchsorted(self.classes_, yv)

    def fit_transform(self, y):
        return self.fit(y).transform(y)

    def inverse_transform(self, codes):
        return self.classes_[np.asarray(codes)]
