"""Gaussian Naive Bayes — exact distributed fit in one fused pass.

TPU-native replacement for the reference's sklearn_naive_bayes_ext.py
(which wraps sklearn.GaussianNB with MPI gathers): per-class counts,
means and variances are masked reductions over the row-sharded design
matrix; GSPMD inserts the psums. Fit is exact (same moments as a
single-node pass, via the stable two-pass form), not an approximation.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from bodo_tpu.ml._data import _to_numpy_1d, to_device_xy


# fixed per-estimator kernel set, bounded by construction
# shardcheck: ignore[unregistered-jit]
@partial(jax.jit, static_argnames=("n_classes",))
def _nb_fit(X, y, mask, n_classes: int):
    w = mask.astype(X.dtype)
    counts = []
    means = []
    m2s = []
    for c in range(n_classes):
        wc = w * (y == c)
        n_c = jnp.sum(wc)
        mean_c = jnp.sum(X * wc[:, None], axis=0) / jnp.maximum(n_c, 1.0)
        d = (X - mean_c[None, :]) * wc[:, None]
        m2_c = jnp.sum(d * d, axis=0)
        counts.append(n_c)
        means.append(mean_c)
        m2s.append(m2_c)
    counts = jnp.stack(counts)
    means = jnp.stack(means)
    var = jnp.stack(m2s) / jnp.maximum(counts, 1.0)[:, None]
    # sklearn smoothing scale: max per-feature variance of the WHOLE X
    # (includes between-class spread)
    n_all = jnp.maximum(jnp.sum(w), 1.0)
    mean_all = jnp.sum(X * w[:, None], axis=0) / n_all
    d_all = (X - mean_all[None, :]) * w[:, None]
    var_all_max = jnp.max(jnp.sum(d_all * d_all, axis=0) / n_all)
    return counts, means, var, var_all_max


# fixed per-estimator kernel set, bounded by construction
# shardcheck: ignore[unregistered-jit]
@jax.jit
def _nb_predict(X, mask, means, var, log_prior):
    # log N(x | mean, var) summed over features + log prior
    lv = jnp.log(2.0 * jnp.pi * var)
    ll = -0.5 * (lv[None, :, :] +
                 (X[:, None, :] - means[None, :, :]) ** 2 /
                 var[None, :, :]).sum(axis=2)
    return jnp.argmax(ll + log_prior[None, :], axis=1)


class GaussianNB:
    """sklearn.naive_bayes.GaussianNB surface (fit/predict/score)."""

    def __init__(self, var_smoothing: float = 1e-9):
        self.var_smoothing = var_smoothing

    def fit(self, X, y):
        yv = _to_numpy_1d(y)
        self.classes_, y_enc = np.unique(yv, return_inverse=True)
        Xd, _, mask, n = to_device_xy(X)
        yd = to_device_xy(np.asarray(y_enc, dtype=np.float64))[0][:, 0]
        counts, means, var, var_all_max = _nb_fit(Xd, yd, mask,
                                                  len(self.classes_))
        counts = np.asarray(jax.device_get(counts))
        self.class_count_ = counts
        self.class_prior_ = counts / counts.sum()
        self.theta_ = np.asarray(jax.device_get(means))
        self.epsilon_ = self.var_smoothing * float(
            jax.device_get(var_all_max))
        self.var_ = np.asarray(jax.device_get(var)) + self.epsilon_
        return self

    def predict(self, X):
        Xd, _, mask, n = to_device_xy(X)
        idx = np.asarray(jax.device_get(_nb_predict(
            Xd, mask, jnp.asarray(self.theta_), jnp.asarray(self.var_),
            jnp.log(jnp.asarray(self.class_prior_)))))[:n]
        return self.classes_[idx]

    def score(self, X, y) -> float:
        return float(np.mean(self.predict(X) == _to_numpy_1d(y)))
