"""Linear SVM — full-batch squared-hinge solver on the mesh.

TPU-native replacement for the reference's sklearn_svm_ext.py (wrapped
sklearn.LinearSVC trained per-rank): one global objective, gradient
steps jit-compiled over the row-sharded data with GSPMD-inserted psums
— every chip sees the exact global gradient each iteration (the
reference's per-rank SGD + averaging only approximates it).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from bodo_tpu.ml._data import _to_numpy_1d, to_device_xy


# fixed per-estimator kernel set, bounded by construction
# shardcheck: ignore[unregistered-jit]
@partial(jax.jit, static_argnames=("iters",))
def _svc_fit(X, y_pm, mask, C, iters: int):
    """Squared-hinge L2 LinearSVC (sklearn default loss), Nesterov GD."""
    n, d = X.shape
    w0 = jnp.zeros((d + 1,))
    wm = mask.astype(X.dtype)
    n_real = jnp.maximum(jnp.sum(wm), 1.0)
    Xb = jnp.concatenate([X, jnp.ones((n, 1), X.dtype)], axis=1)

    # Lipschitz bound: 2C·λmax(XᵀX) ≤ 2C·trace(XᵀX), plus 1 for the reg
    L = 2.0 * C * jnp.sum((Xb * wm[:, None]) ** 2) + 1.0
    lr = 1.0 / L

    def obj_grad(w):
        margin = y_pm * (Xb @ w)
        viol = jnp.maximum(1.0 - margin, 0.0) * wm
        g_data = -2.0 * C * Xb.T @ (viol * y_pm)
        reg = w.at[d].set(0.0)  # don't regularize the intercept
        return reg + g_data

    def step(i, state):
        w, v = state
        t = v - lr * obj_grad(v)
        v_new = t + (i / (i + 3.0)) * (t - w)
        return t, v_new

    w, _ = jax.lax.fori_loop(0, iters, step, (w0, w0))
    return w


class LinearSVC:
    """sklearn.svm.LinearSVC surface (binary and one-vs-rest)."""

    def __init__(self, C: float = 1.0, max_iter: int = 1000):
        self.C = C
        self.max_iter = max_iter

    def fit(self, X, y):
        yv = _to_numpy_1d(y)
        self.classes_, y_enc = np.unique(yv, return_inverse=True)
        Xd, _, mask, n = to_device_xy(X)
        ws = []
        if len(self.classes_) == 2:
            pm = np.where(y_enc == 1, 1.0, -1.0)
            yd = to_device_xy(pm)[0][:, 0]
            ws.append(_svc_fit(Xd, yd, mask, self.C, self.max_iter))
        else:  # one-vs-rest
            for c in range(len(self.classes_)):
                pm = np.where(y_enc == c, 1.0, -1.0)
                yd = to_device_xy(pm)[0][:, 0]
                ws.append(_svc_fit(Xd, yd, mask, self.C, self.max_iter))
        W = np.asarray(jax.device_get(jnp.stack(ws)))
        self.coef_ = W[:, :-1]
        self.intercept_ = W[:, -1]
        return self

    def decision_function(self, X):
        Xd, _, mask, n = to_device_xy(X)
        scores = np.asarray(jax.device_get(
            Xd @ jnp.asarray(self.coef_.T) +
            jnp.asarray(self.intercept_)[None, :]))[:n]
        return scores[:, 0] if len(self.classes_) == 2 and \
            scores.shape[1] == 1 else scores

    def predict(self, X):
        s = self.decision_function(X)
        if s.ndim == 1:
            return self.classes_[(s > 0).astype(int)]
        return self.classes_[np.argmax(s, axis=1)]

    def score(self, X, y) -> float:
        return float(np.mean(self.predict(X) == _to_numpy_1d(y)))
