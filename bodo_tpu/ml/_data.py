"""Shared helpers: accept numpy / jax / BodoSeries / BodoDataFrame inputs
and produce row-sharded device arrays + a padding mask."""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from bodo_tpu.parallel import mesh as mesh_mod
from bodo_tpu.table.table import round_capacity


def to_device_xy(X, y=None):
    """Returns (X [N,D] float, y [N] or None, mask [N] bool, n_rows).

    Arrays are padded to a shard-divisible capacity and row-sharded over
    the mesh (the reference's OneD distribution for ML inputs,
    bodo/transforms/distributed_analysis.py TwoD for matrices)."""
    X = _to_numpy_2d(X)
    n = X.shape[0]
    S = mesh_mod.num_shards()
    per = round_capacity(-(-max(n, 1) // S))
    cap = S * per
    Xp = np.zeros((cap, X.shape[1]), dtype=np.float64)
    Xp[:n] = X
    mask = np.zeros(cap, dtype=bool)
    mask[:n] = True
    sharding = mesh_mod.row_sharding()
    Xd = jax.device_put(Xp, sharding)
    md = jax.device_put(mask, sharding)
    yd = None
    if y is not None:
        yv = _to_numpy_1d(y).astype(np.float64)
        yp = np.zeros(cap, dtype=np.float64)
        yp[:n] = yv
        yd = jax.device_put(yp, sharding)
    return Xd, yd, md, n


def _to_numpy_2d(X) -> np.ndarray:
    X = _materialize(X)
    X = np.asarray(X, dtype=np.float64)
    if X.ndim == 1:
        X = X[:, None]
    return X


def _to_numpy_1d(y) -> np.ndarray:
    y = _materialize(y)
    return np.asarray(y).reshape(-1)


def _materialize(v):
    to_pandas = getattr(v, "to_pandas", None)
    return to_pandas() if callable(to_pandas) else v
