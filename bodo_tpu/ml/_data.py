"""Shared helpers: accept numpy / jax / BodoSeries / BodoDataFrame inputs
and produce row-sharded device arrays + a padding mask.

Lazy frames/series take the DEVICE-RESIDENT path: the executed Table's
columns cast+stack on device with sharding preserved — no to_pandas()
gather anywhere (reference: bodo/ai/train.py:104 feeds training from
worker-resident data; bodo/ml_support/ runs fit/metrics on each rank's
shard)."""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from bodo_tpu.parallel import mesh as mesh_mod
from bodo_tpu.table.table import ONED, Table, round_capacity


def _is_lazy(v) -> bool:
    from bodo_tpu.pandas_api.frame import BodoDataFrame
    from bodo_tpu.pandas_api.series import BodoSeries
    return isinstance(v, (BodoDataFrame, BodoSeries))


def _exec_lazy(v) -> Tuple[Table, list]:
    """Execute a lazy frame/series to a Table + its value column names."""
    from bodo_tpu.pandas_api.frame import BodoDataFrame
    from bodo_tpu.pandas_api.series import BodoSeries
    from bodo_tpu.plan.physical import execute
    if isinstance(v, BodoSeries):
        name = v._name or "_val"
        t = execute(v._as_projection(name))
        return t, [name]
    assert isinstance(v, BodoDataFrame)
    t = v._execute()
    cols = [c for c in t.names if c in v._data_cols()]
    return t, cols


def table_mask(t: Table):
    """Device-side live-row mask [capacity] for a Table (no host
    transit: per-shard iota < count under shard_map)."""
    if t.distribution != ONED:
        return jnp.arange(t.capacity) < t.nrows
    from bodo_tpu.parallel import collectives as C
    from bodo_tpu.config import config
    per = t.shard_capacity
    m = mesh_mod.get_mesh()
    ax = config.data_axis

    def body(c):
        return jnp.arange(per) < c[0]
    # per-call mask helper; one signature per (mesh, capacity)
    # shardcheck: ignore[unregistered-jit]
    fn = jax.jit(C.smap(body, in_specs=(P(ax),), out_specs=P(ax),
                        mesh=m))
    return fn(t.counts_device())


def table_to_device_xy(t: Table, feature_cols: Sequence[str],
                       label_col: Optional[str] = None):
    """1D/REP Table → (X [cap,D] f64, y [cap] f64 or None, mask, n)
    entirely on device; sharding (and therefore the cross-shard psum in
    whatever reduction consumes these) is preserved.

    Real rows are realigned contiguous at the front (the host-path
    layout every estimator's predict[:n] slice assumes) by a DEVICE
    gather — only the tiny int64 index vector is host-built from the
    already-host-known shard counts; the feature/label data never
    transits the host."""
    if t.distribution != ONED and mesh_mod.num_shards() > 1:
        t = t.shard()
    X = jnp.stack([t.column(c).data.astype(jnp.float64)
                   for c in feature_cols], axis=1) if feature_cols \
        else None
    mask = table_mask(t)
    for c in feature_cols:
        v = t.column(c).valid
        if v is not None:
            mask = mask & v
    yd = None
    if label_col is not None:
        yc = t.column(label_col)
        yd = yc.data.astype(jnp.float64)
        if yc.valid is not None:
            mask = mask & yc.valid
    n = t.nrows
    if t.distribution == ONED:
        per = t.shard_capacity
        cap = t.capacity
        real = np.concatenate(
            [i * per + np.arange(int(c)) for i, c in
             enumerate(t.counts)] or [np.zeros(0, np.int64)])
        idx = np.full(cap, max(cap - 1, 0), dtype=np.int64)
        idx[:n] = real
        idx_d = jax.device_put(idx, mesh_mod.row_sharding())
        X = None if X is None else X[idx_d]
        yd = None if yd is None else yd[idx_d]
        mask = mask[idx_d] & (jnp.arange(cap) < n)
    return X, yd, mask, n


def _no_dict_cols(t: Table, cols) -> bool:
    """Device numeric paths must not touch dict-coded string columns:
    codes from independently-built dictionaries are not comparable."""
    return all(t.column(c).dictionary is None for c in cols)


def lazy_pair_device(a, b):
    """Two lazy series with aligned layouts → (a_dev, b_dev, mask) for
    device reductions, or None when no gather-free path exists (layouts
    diverge, non-lazy inputs, or dict-coded strings)."""
    if not (_is_lazy(a) and _is_lazy(b)):
        return None
    ta, ca = _exec_lazy(a)
    tb, cb = _exec_lazy(b)
    if not (ta.distribution == tb.distribution
            and ta.capacity == tb.capacity and ta.nrows == tb.nrows):
        return None
    if not (_no_dict_cols(ta, ca) and _no_dict_cols(tb, cb)):
        return None
    _, ad, ma, _ = table_to_device_xy(ta, [], ca[0])
    _, bd, mb, _ = table_to_device_xy(tb, [], cb[0])
    return ad, bd, ma & mb


def to_device_xy(X, y=None):
    """Returns (X [N,D] float, y [N] or None, mask [N] bool, n_rows).

    Arrays are padded to a shard-divisible capacity and row-sharded over
    the mesh (the reference's OneD distribution for ML inputs,
    bodo/transforms/distributed_analysis.py TwoD for matrices). Lazy
    frame/series inputs stay device-resident end to end."""
    if _is_lazy(X) and (y is None or _is_lazy(y)):
        tx, xcols = _exec_lazy(X)
        if y is None and _no_dict_cols(tx, xcols):
            return table_to_device_xy(tx, xcols)
        if y is not None:
            ty, ycols = _exec_lazy(y)
            if tx.distribution == ty.distribution and \
                    tx.capacity == ty.capacity and \
                    tx.nrows == ty.nrows and \
                    _no_dict_cols(tx, xcols) and \
                    _no_dict_cols(ty, ycols):
                Xd, _, mask, n = table_to_device_xy(tx, xcols)
                _, yd, ymask, _ = table_to_device_xy(ty, [], ycols[0])
                return Xd, yd, mask & ymask, n
        # layouts diverge / dict-coded strings: host path realigns
    X = _to_numpy_2d(X)
    n = X.shape[0]
    S = mesh_mod.num_shards()
    per = round_capacity(-(-max(n, 1) // S))
    cap = S * per
    Xp = np.zeros((cap, X.shape[1]), dtype=np.float64)
    Xp[:n] = X
    mask = np.zeros(cap, dtype=bool)
    mask[:n] = True
    sharding = mesh_mod.row_sharding()
    Xd = jax.device_put(Xp, sharding)
    md = jax.device_put(mask, sharding)
    yd = None
    if y is not None:
        yv = _to_numpy_1d(y).astype(np.float64)
        yp = np.zeros(cap, dtype=np.float64)
        yp[:n] = yv
        yd = jax.device_put(yp, sharding)
    return Xd, yd, md, n


def _to_numpy_2d(X) -> np.ndarray:
    X = _materialize(X)
    X = np.asarray(X, dtype=np.float64)
    if X.ndim == 1:
        X = X[:, None]
    return X


def _to_numpy_1d(y) -> np.ndarray:
    y = _materialize(y)
    return np.asarray(y).reshape(-1)


def _materialize(v):
    to_pandas = getattr(v, "to_pandas", None)
    return to_pandas() if callable(to_pandas) else v
