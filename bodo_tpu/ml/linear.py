"""Linear models (reference bodo/ml_support/sklearn_linear_model_ext.py).

LinearRegression/Ridge solve the normal equations with a psum-reduced
Gram matrix (X^T X and X^T y accumulate per shard, reduce over the mesh,
solve replicated) — exact, one pass, MXU-friendly. LogisticRegression
runs jit-compiled full-batch Newton/gradient iterations with psum'd
gradients (the reference approximates with per-rank SGD + parameter
averaging; a global-gradient solver is both simpler and more exact)."""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from bodo_tpu.ml._data import to_device_xy


def _gram(X, y, mask):
    w = mask.astype(X.dtype)
    Xw = X * w[:, None]
    G = Xw.T @ X                      # [D,D]
    b = Xw.T @ y                      # [D]
    return G, b


# fixed per-estimator kernel set, bounded by construction
# shardcheck: ignore[unregistered-jit]
@partial(jax.jit, static_argnames=("fit_intercept",))
def _linreg_fit(X, y, mask, alpha, fit_intercept: bool):
    if fit_intercept:
        ones = jnp.where(mask, 1.0, 0.0)
        X = jnp.concatenate([X, ones[:, None]], axis=1)
    G, b = _gram(X, y, mask)
    d = G.shape[0]
    reg = alpha * jnp.eye(d)
    if fit_intercept:
        reg = reg.at[d - 1, d - 1].set(0.0)
    theta = jnp.linalg.solve(G + reg, b)
    return theta


class LinearRegression:
    def __init__(self, fit_intercept: bool = True):
        self.fit_intercept = fit_intercept
        self._alpha = 0.0

    def fit(self, X, y):
        Xd, yd, mask, n = to_device_xy(X, y)
        theta = np.asarray(jax.device_get(
            _linreg_fit(Xd, yd, mask, jnp.asarray(self._alpha),
                        self.fit_intercept)))
        if self.fit_intercept:
            self.coef_ = theta[:-1]
            self.intercept_ = float(theta[-1])
        else:
            self.coef_ = theta
            self.intercept_ = 0.0
        return self

    def predict(self, X):
        Xd, _, mask, n = to_device_xy(X)
        out = np.asarray(jax.device_get(
            Xd @ jnp.asarray(self.coef_) + self.intercept_))
        return out[:n]

    def score(self, X, y):
        from bodo_tpu.ml.metrics import r2_score
        return r2_score(np.asarray(y).reshape(-1), self.predict(X))


class Ridge(LinearRegression):
    def __init__(self, alpha: float = 1.0, fit_intercept: bool = True):
        super().__init__(fit_intercept)
        self._alpha = float(alpha)
        self.alpha = alpha


# fixed per-estimator kernel set, bounded by construction
# shardcheck: ignore[unregistered-jit]
@partial(jax.jit, static_argnames=("iters", "fit_intercept"))
def _logreg_fit(X, y, mask, lam, iters: int, fit_intercept: bool):
    if fit_intercept:
        ones = jnp.where(mask, 1.0, 0.0)
        X = jnp.concatenate([X, ones[:, None]], axis=1)
    d = X.shape[1]
    w0 = jnp.zeros(d)
    n = jnp.maximum(jnp.sum(mask), 1).astype(X.dtype)

    def newton_step(w, _):
        z = X @ w
        p = jax.nn.sigmoid(z)
        msk = mask.astype(X.dtype)
        g = X.T @ ((p - y) * msk) / n + lam * w
        r = p * (1 - p) * msk
        H = (X * r[:, None]).T @ X / n + lam * jnp.eye(d)
        w = w - jnp.linalg.solve(H, g)
        return w, None

    w, _ = jax.lax.scan(newton_step, w0, None, length=iters)
    return w


class LogisticRegression:
    """Binary logistic regression via Newton iterations (global gradient,
    exact across shards)."""

    def __init__(self, C: float = 1.0, max_iter: int = 25,
                 fit_intercept: bool = True):
        self.C = C
        self.max_iter = max_iter
        self.fit_intercept = fit_intercept

    def fit(self, X, y):
        yv = np.asarray(self._mat(y)).reshape(-1)
        self.classes_ = np.unique(yv)
        assert len(self.classes_) == 2, "binary only (round 1)"
        y01 = (yv == self.classes_[1]).astype(np.float64)
        Xd, yd, mask, n = to_device_xy(X, y01)
        lam = 1.0 / (self.C * max(n, 1))
        w = np.asarray(jax.device_get(_logreg_fit(
            Xd, yd, mask, jnp.asarray(lam), min(self.max_iter, 50),
            self.fit_intercept)))
        if self.fit_intercept:
            self.coef_ = w[None, :-1]
            self.intercept_ = np.array([w[-1]])
        else:
            self.coef_ = w[None, :]
            self.intercept_ = np.array([0.0])
        return self

    @staticmethod
    def _mat(v):
        to_pandas = getattr(v, "to_pandas", None)
        return to_pandas() if callable(to_pandas) else v

    def decision_function(self, X):
        Xd, _, mask, n = to_device_xy(X)
        z = np.asarray(jax.device_get(
            Xd @ jnp.asarray(self.coef_[0]) + self.intercept_[0]))
        return z[:n]

    def predict_proba(self, X):
        z = self.decision_function(X)
        p = 1.0 / (1.0 + np.exp(-z))
        return np.stack([1 - p, p], axis=1)

    def predict(self, X):
        return self.classes_[(self.decision_function(X) > 0).astype(int)]

    def score(self, X, y):
        from bodo_tpu.ml.metrics import accuracy_score
        return accuracy_score(np.asarray(self._mat(y)).reshape(-1),
                              self.predict(X))
