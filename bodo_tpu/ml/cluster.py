"""KMeans (reference bodo/ml_support/sklearn_cluster_ext.py — per-rank
sklearn fit + allreduce of centers). Here: jit-compiled Lloyd iterations
with psum'd center sums/counts over the mesh; k-means++-style seeding via
farthest-point sampling on a data sample."""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from bodo_tpu.ml._data import to_device_xy


# fixed per-estimator kernel set, bounded by construction
# shardcheck: ignore[unregistered-jit]
@partial(jax.jit, static_argnames=("k", "iters"))
def _lloyd(X, mask, init, k: int, iters: int):
    w = mask.astype(X.dtype)

    def step(centers, _):
        d2 = ((X[:, None, :] - centers[None, :, :]) ** 2).sum(-1)  # [N,k]
        assign = jnp.argmin(d2, axis=1)
        oh = jax.nn.one_hot(assign, k, dtype=X.dtype) * w[:, None]
        sums = oh.T @ X                        # [k,D]
        cnts = oh.sum(0)                       # [k]
        new = jnp.where(cnts[:, None] > 0, sums / jnp.maximum(cnts, 1)[:, None],
                        centers)
        return new, None

    centers, _ = jax.lax.scan(step, init, None, length=iters)
    d2 = ((X[:, None, :] - centers[None, :, :]) ** 2).sum(-1)
    assign = jnp.argmin(d2, axis=1)
    inertia = jnp.sum(jnp.min(d2, axis=1) * w)
    return centers, assign, inertia


class KMeans:
    def __init__(self, n_clusters: int = 8, max_iter: int = 50,
                 random_state: int = 0, n_init: int = 1):
        self.n_clusters = n_clusters
        self.max_iter = max_iter
        self.random_state = random_state

    def fit(self, X):
        Xd, _, mask, n = to_device_xy(X)
        host = np.asarray(jax.device_get(Xd))[np.asarray(jax.device_get(mask))]
        r = np.random.default_rng(self.random_state)
        # farthest-point seeding on a host sample (cheap, deterministic)
        sample = host[r.choice(len(host), min(len(host), 1024),
                               replace=False)]
        centers = [sample[0]]
        for _ in range(1, self.n_clusters):
            d2 = np.min(
                ((sample[:, None, :] - np.asarray(centers)[None]) ** 2)
                .sum(-1), axis=1)
            centers.append(sample[np.argmax(d2)])
        init = jnp.asarray(np.asarray(centers))
        c, a, inertia = _lloyd(Xd, mask, init, self.n_clusters,
                               self.max_iter)
        self.cluster_centers_ = np.asarray(jax.device_get(c))
        self.labels_ = np.asarray(jax.device_get(a))[:n]
        self.inertia_ = float(jax.device_get(inertia))
        return self

    def predict(self, X):
        Xd, _, mask, n = to_device_xy(X)
        d2 = ((np.asarray(jax.device_get(Xd))[:, None, :]
               - self.cluster_centers_[None]) ** 2).sum(-1)
        return np.argmin(d2, axis=1)[:n]

    def fit_predict(self, X):
        return self.fit(X).labels_
