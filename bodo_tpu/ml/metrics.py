"""Metrics (reference bodo/ml_support/sklearn_metrics_ext.py —
distributed confusion/r2/mse via allreduce; here host-side over gathered
predictions, device reductions when inputs are sharded arrays)."""

from __future__ import annotations

import numpy as np

from bodo_tpu.ml._data import _materialize


def _np(v):
    return np.asarray(_materialize(v)).reshape(-1)


def accuracy_score(y_true, y_pred) -> float:
    a, b = _np(y_true), _np(y_pred)
    return float((a == b).mean()) if len(a) else 0.0


def mean_squared_error(y_true, y_pred) -> float:
    a, b = _np(y_true).astype(float), _np(y_pred).astype(float)
    return float(((a - b) ** 2).mean()) if len(a) else 0.0


def r2_score(y_true, y_pred) -> float:
    a, b = _np(y_true).astype(float), _np(y_pred).astype(float)
    ss_res = ((a - b) ** 2).sum()
    ss_tot = ((a - a.mean()) ** 2).sum()
    return float(1.0 - ss_res / ss_tot) if ss_tot > 0 else 0.0
