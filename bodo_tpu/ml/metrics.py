"""Metrics (reference: bodo/ml_support/sklearn_metrics_ext.py —
distributed confusion/r2/mse via MPI allreduce).

Lazy-series / device-array inputs reduce ON DEVICE: jnp reductions over
row-sharded arrays let XLA insert the cross-shard psum (the allreduce
analogue), and only the final scalar reaches the host. Plain
numpy/pandas inputs take the host path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from bodo_tpu.ml._data import _materialize, lazy_pair_device


def _pair(y_true, y_pred):
    """→ (a, b, mask) device arrays when a no-gather path exists, else
    (a, b, None) host numpy. String labels always take the host path —
    dict codes from independent dictionaries are not comparable."""
    dev = lazy_pair_device(y_true, y_pred)
    if dev is not None:
        return dev
    a = np.asarray(_materialize(y_true)).reshape(-1)
    b = np.asarray(_materialize(y_pred)).reshape(-1)
    return a, b, None


def accuracy_score(y_true, y_pred) -> float:
    a, b, mask = _pair(y_true, y_pred)
    if mask is None:
        return float((a == b).mean()) if len(a) else 0.0
    n = jnp.maximum(jnp.sum(mask), 1)
    return float(jax.device_get(jnp.sum((a == b) & mask) / n))


def mean_squared_error(y_true, y_pred) -> float:
    a, b, mask = _pair(y_true, y_pred)
    if mask is None:
        a, b = a.astype(float), b.astype(float)
        return float(((a - b) ** 2).mean()) if len(a) else 0.0
    d = jnp.where(mask, a - b, 0.0)
    n = jnp.maximum(jnp.sum(mask), 1)
    return float(jax.device_get(jnp.sum(d * d) / n))


def r2_score(y_true, y_pred) -> float:
    a, b, mask = _pair(y_true, y_pred)
    if mask is None:
        a, b = a.astype(float), b.astype(float)
        ss_res = ((a - b) ** 2).sum()
        ss_tot = ((a - a.mean()) ** 2).sum()
        return float(1.0 - ss_res / ss_tot) if ss_tot > 0 else 0.0
    n = jnp.maximum(jnp.sum(mask), 1)
    d = jnp.where(mask, a - b, 0.0)
    ss_res = jnp.sum(d * d)
    mean_a = jnp.sum(jnp.where(mask, a, 0.0)) / n
    c = jnp.where(mask, a - mean_a, 0.0)
    ss_tot = jnp.sum(c * c)
    out = jnp.where(ss_tot > 0, 1.0 - ss_res / ss_tot, 0.0)
    return float(jax.device_get(out))
