"""train_test_split (reference bodo/ml_support/sklearn_model_selection_ext.py)."""

from __future__ import annotations

import numpy as np

from bodo_tpu.ml._data import _materialize


def train_test_split(*arrays, test_size=0.25, train_size=None,
                     random_state=None, shuffle=True):
    mats = [np.asarray(_materialize(a)) for a in arrays]
    n = len(mats[0])
    idx = np.arange(n)
    if shuffle:
        np.random.default_rng(random_state).shuffle(idx)
    n_test = int(round(n * test_size)) if isinstance(test_size, float) \
        else int(test_size)
    test_idx, train_idx = idx[:n_test], idx[n_test:]
    if train_size is not None:
        k = int(round(n * train_size)) if isinstance(train_size, float) \
            else int(train_size)
        train_idx = train_idx[:k]
    out = []
    for m in mats:
        out.append(m[train_idx])
        out.append(m[test_idx])
    return out
