"""Distributed ML (reference bodo/ml_support/ + bodo/ai/).

The reference wraps sklearn estimators in objmode calls plus MPI
allreduces (bodo/ml_support/sklearn_ext.py:10 pattern: per-rank
partial_fit / fit, then allreduce of coefficients). Here estimators are
jit-compiled JAX programs over row-sharded arrays: gradients/statistics
reduce with psum over the mesh — no host round-trips inside the training
loop, and the MXU does the matmuls.
"""

from bodo_tpu.ml.linear import LinearRegression, LogisticRegression, Ridge
from bodo_tpu.ml.cluster import KMeans
from bodo_tpu.ml.ensemble import (RandomForestClassifier,
                                  RandomForestRegressor)
from bodo_tpu.ml.naive_bayes import GaussianNB
from bodo_tpu.ml.preprocessing import StandardScaler, LabelEncoder
from bodo_tpu.ml.metrics import (accuracy_score, mean_squared_error,
                                 r2_score)
from bodo_tpu.ml.model_selection import train_test_split
from bodo_tpu.ml.svm import LinearSVC

__all__ = ["LinearRegression", "LogisticRegression", "Ridge", "KMeans",
           "RandomForestClassifier", "RandomForestRegressor",
           "GaussianNB", "LinearSVC",
           "StandardScaler", "LabelEncoder", "accuracy_score",
           "mean_squared_error", "r2_score", "train_test_split"]
