"""Random forests — estimator-parallel training, gathered forest.

Same strategy as the reference (sklearn_ensemble_ext.py): n_estimators
are split across processes, each trains scikit-learn trees on its LOCAL
row block, and the trees are gathered into one global forest on every
rank. Tree construction is branchy/host-bound (no TPU win there — the
reference reaches the same conclusion by delegating to sklearn); the
engine's role is the data distribution and the estimator split.

In a single-controller session (jax.process_count() == 1) the split
degenerates to per-shard row blocks trained sequentially — the same
bagging structure, one process. Under spawn.run_spmd each process
trains only its share.
"""

from __future__ import annotations

import numpy as np

from bodo_tpu.ml._data import _to_numpy_1d, _to_numpy_2d


def _portion(n: int, parts: int, i: int) -> slice:
    lo = (n * i) // parts
    hi = (n * (i + 1)) // parts
    return slice(lo, hi)


class _ForestBase:
    _is_classifier = False

    def __init__(self, n_estimators: int = 100, random_state=None, **kw):
        self.n_estimators = n_estimators
        self.random_state = random_state
        self._kw = kw

    @classmethod
    def _sk_class(cls):
        raise NotImplementedError

    def fit(self, X, y):
        import jax
        X = _to_numpy_2d(X)
        yv = _to_numpy_1d(y)
        pi, pc = jax.process_index(), jax.process_count()
        if pc == 1:
            # single controller: train block-wise forests over shard-sized
            # row blocks (bagging across blocks), then concatenate
            from bodo_tpu.parallel import mesh as mesh_mod
            parts = max(1, min(mesh_mod.num_shards(), self.n_estimators))
        else:
            parts = pc
        classes = np.unique(yv) if self._is_classifier else None
        forests = []
        my_parts = range(parts) if pc == 1 else [pi]
        for p in my_parts:
            rows = _portion(len(X), parts, p)
            est = _portion(self.n_estimators, parts, p)
            n_est = est.stop - est.start
            if n_est == 0 or rows.stop == rows.start:
                continue
            Xb, yb = X[rows], yv[rows]
            if classes is not None and \
                    len(np.unique(yb)) < len(classes):
                # a block missing a class would merge trees with
                # mismatched classes_ — train its share on all rows
                Xb, yb = X, yv
            m = self._sk_class()(
                n_estimators=n_est,
                random_state=None if self.random_state is None
                else self.random_state + p, **self._kw)
            m.fit(Xb, yb)
            forests.append(m)
        if pc > 1:
            # every rank joins the collective, even trained-empty ones
            # (n_estimators < process_count): ship a skeleton whose trees
            # are discarded so shapes/classes stay consistent
            if not forests:
                skel = self._sk_class()(n_estimators=1, **self._kw)
                skel.fit(X, yv)
                skel.estimators_ = []
                forests = [skel]
            self._merge(forests)
            self._allgather()
        else:
            self._merge(forests)
        return self

    def _merge(self, forests):
        assert forests, "no training data"
        base = forests[0]
        for m in forests[1:]:
            base.estimators_ += m.estimators_
        base.n_estimators = len(base.estimators_)
        self._model = base

    def _allgather(self):
        """Gather trees from every process (reference: chunked
        MPI bcast of estimators_, sklearn_ensemble_ext.py:304)."""
        from jax.experimental import multihost_utils
        import pickle

        import jax
        import numpy as np_
        blob = pickle.dumps(self._model.estimators_)
        arr = np_.frombuffer(blob, dtype=np_.uint8)
        # pad to the max length across processes for the allgather
        n = np_.asarray([len(arr)], dtype=np_.int32)
        lens = multihost_utils.process_allgather(n).reshape(-1)
        mx = int(lens.max())
        padded = np_.zeros(mx, dtype=np_.uint8)
        padded[:len(arr)] = arr
        gathered = multihost_utils.process_allgather(padded)
        all_est = []
        for i in range(jax.process_count()):
            all_est += pickle.loads(gathered[i][:int(lens[i])].tobytes())
        self._model.estimators_ = all_est
        self._model.n_estimators = len(all_est)

    def predict(self, X):
        return self._model.predict(_to_numpy_2d(X))

    def score(self, X, y) -> float:
        return float(np.mean(self.predict(X) == _to_numpy_1d(y)))

    @property
    def estimators_(self):
        return self._model.estimators_


class RandomForestClassifier(_ForestBase):
    _is_classifier = True

    @classmethod
    def _sk_class(cls):
        from sklearn.ensemble import RandomForestClassifier as SK
        return SK

    def predict_proba(self, X):
        return self._model.predict_proba(_to_numpy_2d(X))


class RandomForestRegressor(_ForestBase):
    @classmethod
    def _sk_class(cls):
        from sklearn.ensemble import RandomForestRegressor as SK
        return SK

    def score(self, X, y) -> float:  # R^2, sklearn convention
        yv = _to_numpy_1d(y).astype(float)
        pred = self.predict(X)
        ss_res = float(((yv - pred) ** 2).sum())
        ss_tot = float(((yv - yv.mean()) ** 2).sum())
        return 1.0 - ss_res / max(ss_tot, 1e-300)
