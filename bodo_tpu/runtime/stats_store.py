"""Persistent runtime-statistics store for adaptive execution.

Observed stage cardinalities (plan/adaptive.py) are keyed by a
NORMALIZED subplan fingerprint — stable across processes — and persisted
as JSON under ``config.stats_store_dir``, so repeated queries (TPC-H
reruns, sql/plan_cache.py hits) plan from observed rather than guessed
cardinalities from their very first stage.

Normalization rules (``fingerprint``):

  * ``FromPandas`` — the plan key's process-local counter id is replaced
    by (schema names+dtypes, nrows). Two same-shaped frames with equal
    row counts therefore share a fingerprint; stats are advisory (they
    steer plan choice, never correctness), so a collision only costs
    plan quality.
  * ``ReadParquet`` — the path is replaced by the resolved per-file
    (path, mtime, size) signatures from io/parquet's footer cache, so an
    overwritten dataset naturally invalidates its stored stats (same
    signature discipline as plan/stats._parquet_rows).
  * every other node keeps its structural ``key()`` with child keys
    substituted by child fingerprints.

The store is a single ``stats.json`` per directory, written atomically
(tmp + rename), size-capped with oldest-entry eviction, and flushed at
interpreter exit. Everything here is host-side stdlib — no jax.
"""

from __future__ import annotations

import atexit
import hashlib
import json
import os
import threading
import time
from typing import Dict, Optional

from bodo_tpu.config import config

_MAX_ENTRIES = 4096

# Paths whose dataset signature could not be computed this session. The
# all-zero fallback signature below can alias two DIFFERENT datasets'
# fingerprints — fine for advisory stats (a collision only costs plan
# quality), fatal for result caching (a collision serves wrong data).
# So the failure is LOUD (once per path) and the result cache treats
# the plan as non-cacheable (runtime/result_cache.py consults the same
# channel through note_signature_failure).
_sig_failed: set = set()
_sig_failed_mu = threading.Lock()


def note_signature_failure(path, err: BaseException) -> None:
    """Warn once per path that its dataset signature is unavailable."""
    key = str(path)
    with _sig_failed_mu:
        if key in _sig_failed:
            return
        _sig_failed.add(key)
    import warnings
    warnings.warn(
        f"dataset signature unavailable for {key!r} "
        f"({type(err).__name__}: {err}); the plan fingerprint falls "
        f"back to an all-zero signature that can alias two different "
        f"datasets — cardinality stats stay advisory, but results for "
        f"plans reading this path are NOT cached",
        RuntimeWarning, stacklevel=3)


def degraded_paths() -> set:
    """Paths with failed signatures (observability / tests)."""
    with _sig_failed_mu:
        return set(_sig_failed)


def reset_degraded() -> None:
    with _sig_failed_mu:
        _sig_failed.clear()


def _norm_key(node) -> tuple:
    """Structural plan key with process-local identities normalized out."""
    from bodo_tpu.plan import logical as L
    if isinstance(node, L.FromPandas):
        sig = tuple((n, d.name) for n, d in node.schema.items())
        return ("from_pandas", sig, int(node.table.nrows))
    if isinstance(node, L.ReadParquet):
        try:
            # shared content signature from the I/O layer's footer
            # cache keying: (path, mtime, size) per file
            from bodo_tpu.io.parquet import dataset_signature
            sigs = dataset_signature(node.path)
        except Exception as e:
            note_signature_failure(node.path, e)
            sigs = ((str(node.path), 0, 0),)
        return ("read_parquet", sigs, tuple(node.columns))
    k = node.key()
    subs = {c.key(): _norm_key(c) for c in node.children}

    def walk(x):
        if isinstance(x, tuple):
            if x in subs:
                return subs[x]
            return tuple(walk(y) for y in x)
        return x

    return walk(k)


def fingerprint(node) -> str:
    """Stable hex digest of a node's normalized subplan key (cached on
    the node — key construction recurses over the whole subtree)."""
    fp = getattr(node, "_aqe_fp", None)
    if fp is None:
        fp = hashlib.sha256(repr(_norm_key(node)).encode()).hexdigest()[:24]
        node._aqe_fp = fp
    return fp


class StatsStore:
    """Thread-safe fingerprint → observed-rows map with optional JSON
    persistence (path=None keeps it purely in-memory)."""

    def __init__(self, path: Optional[str]):
        self._path = path
        self._mu = threading.Lock()
        self._data: Dict[str, dict] = {}
        self._dirty = False
        if path and os.path.exists(path):
            try:
                with open(path) as f:
                    raw = json.load(f)
                if isinstance(raw, dict):
                    self._data = {
                        k: v for k, v in raw.items()
                        if isinstance(v, dict) and "rows" in v}
            except (OSError, ValueError):
                pass  # corrupt/unreadable store: start fresh

    def lookup(self, fp: str) -> Optional[float]:
        with self._mu:
            e = self._data.get(fp)
            return float(e["rows"]) if e is not None else None

    def record(self, fp: str, rows: int, nbytes: int = 0) -> None:
        with self._mu:
            self._data[fp] = {"rows": int(rows), "bytes": int(nbytes),
                              "ts": time.time()}
            self._dirty = True
            if len(self._data) > _MAX_ENTRIES:
                drop = sorted(self._data.items(),
                              key=lambda kv: kv[1].get("ts", 0.0))
                for k, _ in drop[:len(self._data) - _MAX_ENTRIES]:
                    del self._data[k]

    def flush(self) -> None:
        """Atomic write-out (tmp + rename); no-op when clean/in-memory."""
        with self._mu:
            if not self._dirty or not self._path:
                return
            tmp = f"{self._path}.tmp.{os.getpid()}"
            try:
                with open(tmp, "w") as f:
                    json.dump(self._data, f)
                os.replace(tmp, self._path)
                self._dirty = False
            except OSError:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass

    def __len__(self) -> int:
        with self._mu:
            return len(self._data)


_store: Optional[StatsStore] = None
_store_dir: Optional[str] = None
_store_mu = threading.Lock()


def get_store() -> StatsStore:
    """The store bound to config.stats_store_dir (rebinds on change)."""
    global _store, _store_dir
    d = config.stats_store_dir
    with _store_mu:
        if _store is None or d != _store_dir:
            if _store is not None:
                _store.flush()
            path = None
            if d:
                try:
                    os.makedirs(d, exist_ok=True)
                    path = os.path.join(d, "stats.json")
                except OSError:
                    path = None
            _store = StatsStore(path)
            _store_dir = d
    return _store


def reset_store() -> None:
    """Flush + drop the open store (set_config(stats_store_dir=...))."""
    global _store
    with _store_mu:
        if _store is not None:
            _store.flush()
        _store = None


@atexit.register
def _flush_at_exit() -> None:  # pragma: no cover - interpreter teardown
    with _store_mu:
        if _store is not None:
            _store.flush()
