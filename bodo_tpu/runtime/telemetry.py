"""Always-on telemetry: sampler, health endpoints, flight recorder.

PR 6 made single queries observable (spans, cross-rank trace merge,
EXPLAIN ANALYZE, the metrics registry); this module covers the gaps
*between* queries and *after* failures — the observability contract the
future serving layer (runtime/scheduler.py, ROADMAP item 2) scrapes per
tenant, and the Pathways-style controller function of watching a
gang-scheduled fleet centrally (PAPERS §2: health monitoring is a
first-class controller concern; §4: TPU rank loss and wedged tunnels
are routine fleet events, so the diagnostic artifact must be produced
by default).

Three parts:

1. SAMPLER — one daemon thread (config.telemetry_interval_s period)
   snapshots every subsystem's cheap stats into a bounded in-memory
   ring (config.telemetry_ring samples): memory-governor occupancy and
   spill, io_pool prefetch depth / stalls / overlap, fusion-cache
   hits/budget, lockstep sequence head, spawn heartbeat age, process
   RSS. Each sample also lands in the metrics registry as
   ``bodo_tpu_process_rss_bytes`` / ``bodo_tpu_heartbeat_age_seconds``
   / ``bodo_tpu_lockstep_sequence_head`` gauges. Subsystem modules are
   read via ``sys.modules.get`` — a sample never forces a jax import.

2. HTTP ENDPOINT — a stdlib ThreadingHTTPServer (``serve()``) bound on
   127.0.0.1 serving:
       /metrics                Prometheus text exposition
       /healthz                JSON gang health (per-rank alive / hb
                               age / last collective when a gang is
                               running, else the local process view)
       /debug/flightrecorder   trigger a bundle dump, return its path

3. FLIGHT RECORDER — ``dump_bundle(reason)`` writes a self-contained
   timestamped diagnostic directory: manifest (config + BODO_TPU_*/
   JAX_* env + armed faults + per-rank diagnostics), the telemetry
   ring, a metrics snapshot, the slowest-N EXPLAIN ANALYZE records,
   faulthandler stacks of every thread, the merged multi-rank trace
   and the lockstep side-channel logs when a gang dir is given.
   Triggered automatically by spawn.py on gang failure, by
   analysis/lockstep.py on LockstepError, and by SIGUSR1
   (``install_signal_trigger()``). ``python -m bodo_tpu.doctor
   <bundle>`` triages the result.
"""

from __future__ import annotations

import faulthandler
import http.server
import json
import os
import shutil
import signal
import sys
import tempfile
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from bodo_tpu.config import config
from bodo_tpu.utils import metrics

_lock = threading.Lock()

# ---------------------------------------------------------------------------
# sampling
# ---------------------------------------------------------------------------

_PAGE = 4096
try:
    _PAGE = os.sysconf("SC_PAGE_SIZE")
except (ValueError, OSError, AttributeError):  # pragma: no cover
    pass


def rss_bytes() -> int:
    """Resident set size of this process; /proc on Linux, getrusage
    peak-RSS fallback elsewhere (0 when neither is available)."""
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * _PAGE
    except (OSError, ValueError, IndexError):
        pass
    try:  # pragma: no cover - non-Linux fallback
        import resource
        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
    except Exception:  # pragma: no cover
        return 0


def _mod(name: str):
    """Already-imported subsystem module or None — a telemetry sample
    must never force an import (several of these pull in jax)."""
    return sys.modules.get(name)


def sample() -> dict:
    """One JSON-safe snapshot of the engine's live state. Every
    subsystem read is best-effort: a sampler tick must never raise."""
    s: dict = {"ts": round(time.time(), 3),
               "rss_bytes": rss_bytes()}
    resil = _mod("bodo_tpu.runtime.resilience")
    if resil is not None:
        try:
            age = resil.last_heartbeat_age()
            if age is not None:
                s["heartbeat_age_s"] = round(age, 3)
        except Exception:
            pass
    mg = _mod("bodo_tpu.runtime.memory_governor")
    if mg is not None:
        try:
            st = mg.governor().stats()
            ops = st.get("operators", {})
            s["mem"] = {
                "budget_bytes": int(st.get("derived_budget_bytes", 0)),
                "granted_bytes": int(sum(m.get("granted", 0)
                                         for m in ops.values())),
                "peak_bytes": int(sum(m.get("peak", 0)
                                      for m in ops.values())),
                "spilled_bytes": int(sum(m.get("spilled_bytes", 0)
                                         for m in ops.values())),
                "n_spills": int(sum(m.get("n_spills", 0)
                                    for m in ops.values())),
                "n_queued": int(st.get("n_queued", 0)),
                "oom_retries": int(st.get("n_oom_retries", 0)),
            }
        except Exception:
            pass
    iop = _mod("bodo_tpu.runtime.io_pool")
    if iop is not None:
        try:
            ios = iop.io_stats()
            s["io"] = {
                "prefetch_depth": int(ios.get("prefetch_depth", 0)),
                "prefetch_streams": int(ios.get("prefetch_streams", 0)),
                "stalls": int(ios.get("stalls", 0)),
                "decode_batches": int(ios.get("decode_batches", 0)),
                "overlap_ratio": round(float(
                    ios.get("overlap_ratio", 0.0)), 4),
            }
        except Exception:
            pass
    rc = _mod("bodo_tpu.runtime.result_cache")
    if rc is not None:
        try:
            rs = rc.stats()
            budget = int(rs.get("budget_bytes", 0))
            dev = int(rs.get("device_bytes", 0))
            s["result_cache"] = {
                "entries": int(rs.get("entries", 0)),
                "device_bytes": dev,
                "host_bytes": int(rs.get("host_bytes", 0)),
                "budget_bytes": budget,
                # occupancy + shed/eviction pressure: the admission
                # controller (runtime/scheduler.py) reads cache
                # pressure here without a full /metrics scrape
                "occupancy_frac": round(dev / budget, 4) if budget
                else 0.0,
                "evictions": int(rs.get("evictions", 0)),
                "pressure_sheds": int(rs.get("pressure_sheds", 0)),
                "rejected": int(rs.get("rejected", 0)),
                "spills": int(rs.get("spills", 0)),
                "q_hits": int(rs.get("q_hits", 0)),
                "q_misses": int(rs.get("q_misses", 0)),
                "q_incremental": int(rs.get("q_incremental", 0)),
                "hit_rate": round(float(rs.get("q_hit_rate", 0.0)), 4),
                "saved_wall_s": round(float(
                    rs.get("saved_wall_s", 0.0)), 3),
            }
        except Exception:
            pass
    sch = _mod("bodo_tpu.runtime.scheduler")
    if sch is not None:
        try:
            ss = sch.stats()
            if ss is not None:
                s["scheduler"] = {
                    "sessions": int(ss.get("sessions", 0)),
                    "queued": int(ss.get("queued", 0)),
                    "running": int(ss.get("running", 0)),
                    "completed": int(ss.get("completed", 0)),
                    "failed": int(ss.get("failed", 0)),
                    "decisions": {k: int(v) for k, v in
                                  ss.get("decisions", {}).items()},
                }
        except Exception:
            pass
    vw = _mod("bodo_tpu.runtime.views")
    if vw is not None:
        try:
            vs = vw.stats()
            if vs.get("n_views"):
                s["views"] = {
                    "n_views": int(vs.get("n_views", 0)),
                    "dag_depth": int(vs.get("dag_depth", 0)),
                    "subscriptions": int(vs.get("subscriptions", 0)),
                    "refreshes_incremental":
                        int(vs.get("refreshes_incremental", 0)),
                    "refreshes_full": int(vs.get("refreshes_full", 0)),
                    "refresh_ratio":
                        round(float(vs.get("refresh_ratio", 0.0)), 4),
                    "staleness_p99_s":
                        round(float(vs.get("staleness_p99_s", 0.0)), 4),
                    "lagging_view": vs.get("lagging_view"),
                }
        except Exception:
            pass
    fz = _mod("bodo_tpu.plan.fusion")
    if fz is not None:
        try:
            fs = fz.stats()
            s["fusion"] = {
                "cache_hits": int(fs.get("hits", 0)),
                "cache_misses": int(fs.get("misses", 0)),
                "programs_cached": int(fs.get("size", 0)),
                "budget_spent": float(fs.get("budget_spent",
                                             fs.get("compile_s", 0.0))),
            }
        except Exception:
            pass
    ob = _mod("bodo_tpu.runtime.xla_observatory")
    if ob is not None:
        try:
            st = ob.storm()
            led = ob.ledger_stats()
            bud = ob.budget()
            s["xla"] = {
                "live_device_bytes": int(led["live_bytes"]),
                "live_buffers": int(led["live_buffers"]),
                "budget_remaining": int(bud["remaining"]),
                "storming": bool(st["storming"]),
            }
            if st["storming"]:
                s["xla"]["storm_signature"] = st["signature"]
                s["xla"]["storm_compiles"] = st["compiles_in_window"]
        except Exception:
            pass
    ls = _mod("bodo_tpu.analysis.lockstep")
    if ls is not None:
        try:
            s["lockstep_seq"] = int(ls.sequence_head())
        except Exception:
            pass
    cm = _mod("bodo_tpu.parallel.comm")
    if cm is not None:
        try:
            sk = cm.skew_head()
            if sk.get("dispatches"):
                s["comm"] = sk
        except Exception:
            pass
    gid = os.environ.get("BODO_TPU_GANG_ID", "")
    if gid:
        s["gang_id"] = gid
    fl = _mod("bodo_tpu.runtime.fleet")
    if fl is not None:
        try:
            fs = fl.controller_stats()
            if fs is not None:
                s["fleet"] = fs
        except Exception:
            pass
    el = _mod("bodo_tpu.runtime.elastic")
    if el is not None:
        try:
            eh = el.head()
            # only worth a ring slot once recovery state exists — an
            # epoch-0 full-capacity gang is the default
            if eh.get("epoch") or eh.get("shrinks") or eh.get("grows") \
                    or eh.get("resumes"):
                s["elastic"] = eh
        except Exception:
            pass
    return s


def _update_gauges(s: dict) -> None:
    metrics.gauge("bodo_tpu_process_rss_bytes",
                  "resident set size of this engine process").set(
        s.get("rss_bytes", 0))
    if "heartbeat_age_s" in s:
        metrics.gauge("bodo_tpu_heartbeat_age_seconds",
                      "seconds since this worker's last heartbeat").set(
            s["heartbeat_age_s"])
    if "lockstep_seq" in s:
        metrics.gauge("bodo_tpu_lockstep_sequence_head",
                      "sequence number of the last fingerprinted "
                      "collective dispatch").set(s["lockstep_seq"])
    with _lock:
        n = len(_ring)
    metrics.gauge("bodo_tpu_telemetry_ring_samples",
                  "samples currently held in the telemetry ring").set(n)


def sync_gauges() -> None:
    """Refresh the telemetry gauges from a fresh (registry-free)
    sample. Called by metrics.sync_engine_metrics() so a /metrics
    scrape always sees current RSS even between sampler ticks."""
    _update_gauges(sample())


# ---------------------------------------------------------------------------
# ring + sampler thread
# ---------------------------------------------------------------------------

_ring: deque = deque(maxlen=600)
_sampler_stop: Optional[threading.Event] = None
_sampler_thread: Optional[threading.Thread] = None
_samples_total = 0


def record_sample() -> dict:
    """Take one sample, append it to the ring, refresh the gauges."""
    global _samples_total
    s = sample()
    with _lock:
        if _ring.maxlen != int(config.telemetry_ring):
            _resize_ring_locked()
        _ring.append(s)
        _samples_total += 1
    try:
        _update_gauges(s)
        metrics.counter("bodo_tpu_telemetry_samples_total",
                        "telemetry sampler ticks").inc()
    except Exception:
        pass
    return s


def _resize_ring_locked() -> None:
    # _locked suffix contract: every caller already holds _lock
    global _ring
    _ring = deque(_ring,  # shardcheck: ignore[unlocked-shared-state]
                  maxlen=max(1, int(config.telemetry_ring)))


def ring_snapshot() -> List[dict]:
    with _lock:
        return [dict(s) for s in _ring]


def samples_total() -> int:
    with _lock:
        return _samples_total


def _run_sampler(stop: threading.Event) -> None:
    while not stop.wait(max(0.01, float(config.telemetry_interval_s))):
        try:
            record_sample()
        except Exception:  # noqa: BLE001 - the sampler must survive
            pass


def ensure_sampler() -> bool:
    """Start the background sampler if config.telemetry allows and it
    is not already running. Returns True when a sampler is live."""
    global _sampler_stop, _sampler_thread
    if not config.telemetry:
        return False
    with _lock:
        if _sampler_thread is not None and _sampler_thread.is_alive():
            return True
        stop = threading.Event()
        t = threading.Thread(target=_run_sampler, args=(stop,),
                             name="bodo-tpu-telemetry", daemon=True)
        _sampler_stop = stop
        _sampler_thread = t
    t.start()
    return True


def stop_sampler() -> None:
    global _sampler_stop, _sampler_thread
    with _lock:
        stop, t = _sampler_stop, _sampler_thread
        _sampler_stop = None
        _sampler_thread = None
    if stop is not None:
        stop.set()
    if t is not None and t.is_alive():
        t.join(timeout=2.0)


def sampler_running() -> bool:
    with _lock:
        return _sampler_thread is not None and _sampler_thread.is_alive()


def reconfigure() -> None:
    """Apply config changes to a live sampler: stop it when telemetry
    was disabled; resize the ring. Called by set_config."""
    if not config.telemetry:
        stop_sampler()
    with _lock:
        if _ring.maxlen != int(config.telemetry_ring):
            _resize_ring_locked()


def reset() -> None:
    """Stop the sampler and clear the ring (tests)."""
    global _samples_total
    stop_sampler()
    with _lock:
        _ring.clear()
        _samples_total = 0


# ---------------------------------------------------------------------------
# gang health
# ---------------------------------------------------------------------------

# the spawner registers a provider while a gang is live: a zero-arg
# callable returning {rank: {"alive", "returncode", "hb_age_s",
# "last_collective"}}
_gang_provider: Optional[Callable[[], Dict[int, dict]]] = None


def set_gang_health_provider(fn: Optional[Callable[[], Dict[int, dict]]]
                             ) -> None:
    global _gang_provider
    with _lock:
        _gang_provider = fn


def lockstep_log_tail(dirpath: str, rank: int) -> Optional[str]:
    """Last dispatch recorded in a rank's lockstep side-channel log
    ("#seq op@site"), or None when the rank never dispatched."""
    path = os.path.join(dirpath, f"lockstep_{rank}.log")
    try:
        with open(path, "r") as f:
            last = None
            for line in f:
                if "\t" in line:
                    last = line.rstrip("\n")
            if last is None:
                return None
            # seq \t fingerprint [\t arrival-ts]
            parts = last.split("\t")
            return f"#{parts[0]} {parts[1]}"
    except OSError:
        return None


def health() -> dict:
    """Aggregated health document served at /healthz."""
    with _lock:
        provider = _gang_provider
    doc: dict = {
        "status": "ok",
        "time": round(time.time(), 3),
        "pid": os.getpid(),
    }
    gid = os.environ.get("BODO_TPU_GANG_ID", "")
    if gid:
        # stable fleet identity: the controller's scrapes (and doctor
        # triage) name gangs by this, not by pid/port
        doc["gang_id"] = gid
    resil = _mod("bodo_tpu.runtime.resilience")
    if resil is not None:
        try:
            doc["rank"] = resil.current_rank()
            age = resil.last_heartbeat_age()
            if age is not None:
                doc["heartbeat_age_s"] = round(age, 3)
        except Exception:
            pass
    if provider is not None:
        try:
            ranks = provider()
            doc["gang"] = {str(r): d for r, d in sorted(ranks.items())}
            hb_timeout = float(getattr(config, "spawn_hb_timeout_s",
                                       15.0))
            # shrink-evicted ranks left the mesh on purpose: they read
            # as reduced capacity (the elastic block below), never as
            # an unhealthy gang that needs a restart
            bad = [r for r, d in ranks.items()
                   if not d.get("evicted", False)
                   and (not d.get("alive", False)
                        or d.get("hb_age_s", 0.0) > hb_timeout)]
            if bad:
                doc["status"] = "degraded"
                doc["unhealthy_ranks"] = sorted(bad)
            evicted = sorted(r for r, d in ranks.items()
                             if d.get("evicted", False))
            if evicted:
                doc["evicted_ranks"] = evicted
        except Exception as e:
            doc["status"] = "unknown"
            doc["gang_error"] = f"{type(e).__name__}: {e}"
    el = _mod("bodo_tpu.runtime.elastic")
    if el is not None:
        try:
            eh = el.head()
            # always present once the elastic module is loaded: the
            # fleet admission twin rescales quotas/routing from
            # capacity_frac, so "1.0" (full width) is signal too
            doc["elastic"] = eh
        except Exception:
            pass
    cm = _mod("bodo_tpu.parallel.comm")
    if cm is not None:
        try:
            sk = cm.skew_head()
            if sk.get("dispatches"):
                # arrival-skew head for /healthz consumers (the future
                # scheduler's admission signal, ROADMAP item 2)
                doc["comm"] = sk
        except Exception:
            pass
    ob = _mod("bodo_tpu.runtime.xla_observatory")
    if ob is not None:
        try:
            st = ob.storm()
            if st["storming"]:
                # a signature recompiling every dispatch burns wall on
                # compiles — surfaced for admission to back the session
                # off, but it does NOT flip "status": storms are normal
                # during warm-up / test suites, and gang liveness (the
                # thing "degraded" gates restarts on) is unaffected
                doc["xla_recompile_storm"] = {
                    "signature": st["signature"],
                    "compiles_in_window": st["compiles_in_window"],
                    "window_s": st["window_s"],
                }
            doc["xla_live_device_bytes"] = int(
                ob.ledger_stats()["live_bytes"])
        except Exception:
            pass
    rc = _mod("bodo_tpu.runtime.result_cache")
    if rc is not None:
        try:
            rs = rc.stats()
            budget = int(rs.get("budget_bytes", 0))
            dev = int(rs.get("device_bytes", 0))
            # occupancy/shed block: cache pressure for the admission
            # controller without a full /metrics scrape. Like the storm
            # flag it does NOT flip "status" — a full cache is load,
            # not ill health
            doc["result_cache"] = {
                "device_bytes": dev,
                "budget_bytes": budget,
                "occupancy_frac": round(dev / budget, 4) if budget
                else 0.0,
                "entries": int(rs.get("entries", 0)),
                "evictions": int(rs.get("evictions", 0)),
                "pressure_sheds": int(rs.get("pressure_sheds", 0)),
                "rejected": int(rs.get("rejected", 0)),
            }
        except Exception:
            pass
    sch = _mod("bodo_tpu.runtime.scheduler")
    if sch is not None:
        try:
            ss = sch.stats()
            if ss is not None:
                doc["scheduler"] = {
                    "sessions": int(ss.get("sessions", 0)),
                    "queued": int(ss.get("queued", 0)),
                    "running": int(ss.get("running", 0)),
                    "decisions": {k: int(v) for k, v in
                                  ss.get("decisions", {}).items()},
                }
        except Exception:
            pass
    vw = _mod("bodo_tpu.runtime.views")
    if vw is not None:
        try:
            vs = vw.stats()
            if vs.get("n_views"):
                # like result_cache: a lagging view is maintenance
                # load, not ill health — doctor triage names the view
                doc["views"] = {
                    "n_views": int(vs.get("n_views", 0)),
                    "dag_depth": int(vs.get("dag_depth", 0)),
                    "subscriptions": int(vs.get("subscriptions", 0)),
                    "refresh_ratio":
                        round(float(vs.get("refresh_ratio", 0.0)), 4),
                    "staleness_p99_s":
                        round(float(vs.get("staleness_p99_s", 0.0)), 4),
                    "lagging_view": vs.get("lagging_view"),
                    "refresh_rejected":
                        int(vs.get("refresh_rejected", 0)),
                }
        except Exception:
            pass
    fl = _mod("bodo_tpu.runtime.fleet")
    if fl is not None:
        try:
            fs = fl.controller_stats()
            if fs is not None:
                # per-gang attribution: which gangs this controller is
                # fronting and what state each is in (ok/shed/degraded/
                # backoff/dead) — doctor triage names gangs from here
                doc["fleet"] = fs
        except Exception:
            pass
    with _lock:
        doc["telemetry"] = {
            "sampler_running": _sampler_thread is not None
            and _sampler_thread.is_alive(),
            "ring_samples": len(_ring),
            "samples_total": _samples_total,
        }
    bundle = last_bundle_path()
    if bundle:
        doc["last_flight_bundle"] = bundle
    return doc


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

_last_bundle: Optional[str] = None
_bundle_lock = threading.Lock()

_ENV_PREFIXES = ("BODO_TPU_", "JAX_", "XLA_")


def flight_dir() -> str:
    return config.flight_dir or os.path.join(tempfile.gettempdir(),
                                             "bodo_tpu_flightrec")


def last_bundle_path() -> Optional[str]:
    with _bundle_lock:
        return _last_bundle


def _sanitize(reason: str) -> str:
    return "".join(c if c.isalnum() or c in "-_" else "_"
                   for c in reason)[:60]


def _write_json(path: str, obj) -> None:
    with open(path, "w") as f:
        json.dump(obj, f, indent=2, sort_keys=True, default=str)


def dump_bundle(reason: str, *, gang_dir: Optional[str] = None,
                ranks: Optional[Dict[int, dict]] = None,
                out_dir: Optional[str] = None) -> Optional[str]:
    """Write a self-contained diagnostic bundle; returns its path (None
    when the flight recorder is disabled). Never raises — diagnostics
    must not compound the failure being diagnosed.

    Layout:
        manifest.json       reason, timestamps, pid/rank, config, env
                            (BODO_TPU_*/JAX_*/XLA_*), armed faults,
                            per-rank diagnostics when given
        telemetry.json      the sampler ring + one final fresh sample
        metrics.prom        Prometheus exposition snapshot
        slow_queries.json   slowest-N EXPLAIN ANALYZE records
        stacks.txt          faulthandler dump of every local thread
        trace_merged.json   multi-rank timeline (gang bundles)
        trace_local.json    this process's trace (non-gang bundles)
        lockstep_<r>.log    copied side-channel dispatch logs
        err_<r>.log         copied worker stderr
        stacks_<r>.txt      per-rank faulthandler stacks (SIGUSR1 path)
    """
    global _last_bundle
    try:
        if not config.flight_recorder:
            return None
        base = out_dir or flight_dir()
        ts = time.strftime("%Y%m%d_%H%M%S")
        d = os.path.join(
            base, f"bundle_{ts}_{os.getpid()}_{_sanitize(reason)}")
        os.makedirs(d, exist_ok=True)
        _write_manifest(d, reason, ranks)
        _write_telemetry(d)
        _write_metrics(d)
        _write_xla(d)
        _write_progcheck(d)
        _write_slow_queries(d)
        _write_stacks(d)
        _write_traces(d, gang_dir)
        if gang_dir:
            _copy_gang_artifacts(d, gang_dir)
        with _bundle_lock:
            _last_bundle = d
        try:
            metrics.counter("bodo_tpu_flight_bundles_total",
                            "flight-recorder bundles dumped",
                            ("reason",)).labels(
                reason=_sanitize(reason)).inc()
        except Exception:
            pass
        sys.stderr.write(
            f"bodo_tpu.telemetry: flight-recorder bundle ({reason}) "
            f"-> {d}\n")
        return d
    except Exception as e:  # noqa: BLE001 - never compound the failure
        sys.stderr.write(
            f"bodo_tpu.telemetry: bundle dump failed: "
            f"{type(e).__name__}: {e}\n")
        return None


def _write_manifest(d: str, reason: str,
                    ranks: Optional[Dict[int, dict]]) -> None:
    from dataclasses import fields as _dc_fields
    resil = _mod("bodo_tpu.runtime.resilience")
    man = {
        "reason": reason,
        "ts": round(time.time(), 3),
        "iso_time": time.strftime("%Y-%m-%dT%H:%M:%S"),
        "pid": os.getpid(),
        "gang_id": os.environ.get("BODO_TPU_GANG_ID", ""),
        "rank": resil.current_rank() if resil is not None else None,
        "config": {f.name: getattr(config, f.name)
                   for f in _dc_fields(type(config))},
        "env": {k: v for k, v in sorted(os.environ.items())
                if k.startswith(_ENV_PREFIXES)},
    }
    if resil is not None:
        try:
            man["faults_armed"] = resil.armed()
            man["resilience"] = resil.stats()
        except Exception:
            pass
    if ranks is not None:
        man["ranks"] = {str(r): dict(diag)
                        for r, diag in sorted(ranks.items())}
    _write_json(os.path.join(d, "manifest.json"), man)


def _write_telemetry(d: str) -> None:
    try:
        samples = ring_snapshot()
        samples.append(sample())  # the moment of failure itself
        _write_json(os.path.join(d, "telemetry.json"),
                    {"interval_s": float(config.telemetry_interval_s),
                     "samples": samples})
    except Exception:
        pass


def _write_metrics(d: str) -> None:
    try:
        with open(os.path.join(d, "metrics.prom"), "w") as f:
            f.write(metrics.expose_text())
    except Exception:
        pass


def _write_xla(d: str) -> None:
    """Embed the program registry + device-buffer ledger in the bundle
    (doctor's storm/leak triage reads xla_registry.json)."""
    ob = _mod("bodo_tpu.runtime.xla_observatory")
    if ob is None:
        return
    try:
        _write_json(os.path.join(d, "xla_registry.json"),
                    {"summary": ob.stats(),
                     "programs": ob.registry_dump(limit=200),
                     "leaks": ob.leak_check(collect=False)})
    except Exception:
        pass


def _write_progcheck(d: str) -> None:
    """Embed the static verifier's collective manifests + violations in
    the bundle (doctor's progcheck triage section reads this; the
    per-program verdicts ride in xla_registry.json too)."""
    pc = _mod("bodo_tpu.analysis.progcheck")
    if pc is None:
        return
    try:
        _write_json(os.path.join(d, "progcheck.json"),
                    {"stats": pc.stats(),
                     "manifests": pc.reports(),
                     "violations": pc.violations()})
    except Exception:
        pass


def _write_slow_queries(d: str) -> None:
    ex = _mod("bodo_tpu.plan.explain")
    if ex is None:
        return
    try:
        _write_json(os.path.join(d, "slow_queries.json"),
                    ex.slow_queries(int(config.flight_slow_queries)))
    except Exception:
        pass


def _write_stacks(d: str) -> None:
    try:
        with open(os.path.join(d, "stacks.txt"), "w") as f:
            faulthandler.dump_traceback(file=f)
    except Exception:
        pass


def _write_traces(d: str, gang_dir: Optional[str]) -> None:
    tr = _mod("bodo_tpu.utils.tracing")
    if tr is None:
        return
    try:
        if gang_dir:
            tr.merge_trace_shards(gang_dir,
                                  os.path.join(d, "trace_merged.json"))
        elif tr.has_events():
            tr.dump(os.path.join(d, "trace_local.json"))
    except Exception:
        pass


def _copy_gang_artifacts(d: str, gang_dir: str) -> None:
    """Carry the gang temp dir's side channels into the bundle before
    the TemporaryDirectory is cleaned up: lockstep dispatch logs,
    worker stderr, per-rank SIGUSR1 stack dumps, raw trace shards."""
    try:
        names = os.listdir(gang_dir)
    except OSError:
        return
    for name in names:
        if not (name.startswith(("lockstep_", "err_", "stacks_"))
                or name.startswith("trace_shard_")
                or name == "remesh.json"):
            continue
        try:
            shutil.copy2(os.path.join(gang_dir, name),
                         os.path.join(d, name))
        except OSError:
            pass


# ---------------------------------------------------------------------------
# SIGUSR1 trigger + worker integration
# ---------------------------------------------------------------------------

_signal_installed = False
_prev_usr1_handler = None


def install_signal_trigger() -> bool:
    """SIGUSR1 -> dump a flight-recorder bundle (and, in a spawned
    worker, leave the trace shard + stacks in the gang dir for the
    spawner's merge). Main-thread only — returns False elsewhere."""
    global _signal_installed, _prev_usr1_handler
    with _lock:
        if _signal_installed:
            return True
    try:
        prev = signal.signal(signal.SIGUSR1, _on_sigusr1)
    except (ValueError, OSError, AttributeError):
        # ValueError: not the main thread; AttributeError: no SIGUSR1
        return False
    with _lock:
        _signal_installed = True
        _prev_usr1_handler = prev
    return True


def _on_sigusr1(signum, frame) -> None:  # noqa: ARG001
    try:
        _dump_worker_side_channel()
        dump_bundle("sigusr1")
    except Exception:  # noqa: BLE001 - a signal handler must not raise
        pass


def _dump_worker_side_channel() -> None:
    """In a spawned worker: write this rank's trace shard and thread
    stacks into the gang's shared dir, then a done-marker the spawner's
    grace window polls for before the hard kill."""
    d = os.environ.get("BODO_TPU_TRACE_SHARD_DIR")
    if not d:
        return
    rank = os.environ.get("BODO_TPU_PROC_ID", "0")
    tr = _mod("bodo_tpu.utils.tracing")
    if tr is not None:
        try:
            if tr.has_events():
                tr.dump_shard(d)
        except Exception:
            pass
    try:
        with open(os.path.join(d, f"stacks_{rank}.txt"), "w") as f:
            faulthandler.dump_traceback(file=f)
    except Exception:
        pass
    try:
        with open(os.path.join(d, f"usr1_done_{rank}"), "w") as f:
            f.write(str(time.time()))
    except OSError:
        pass


def worker_init() -> None:
    """Called by the spawn worker bootstrap after the jax import:
    starts the config-gated sampler and arms the SIGUSR1 side-channel
    dump so the spawner's teardown grace can collect this rank's shard
    and stacks even when the rank is about to be killed."""
    try:
        ensure_sampler()
    except Exception:
        pass
    install_signal_trigger()
    port = int(os.environ.get("BODO_TPU_TELEMETRY_RANK_PORT", "-1"))
    if port >= 0:
        try:
            addr = serve(port)
            d = os.environ.get("BODO_TPU_TRACE_SHARD_DIR")
            rank = os.environ.get("BODO_TPU_PROC_ID", "0")
            if d and addr:
                with open(os.path.join(d, f"telemetry_{rank}.addr"),
                          "w") as f:
                    f.write(addr)
        except Exception:
            pass


# ---------------------------------------------------------------------------
# HTTP endpoint
# ---------------------------------------------------------------------------

_server: Optional[http.server.ThreadingHTTPServer] = None
_server_thread: Optional[threading.Thread] = None


class _Handler(http.server.BaseHTTPRequestHandler):
    # never chat on stderr per request
    def log_message(self, format, *args):  # noqa: A002,ARG002
        pass

    def _send(self, code: int, body: str, ctype: str) -> None:
        data = body.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler contract
        path = self.path.split("?", 1)[0]
        try:
            if path == "/metrics":
                self._send(200, metrics.expose_text(),
                           "text/plain; version=0.0.4; charset=utf-8")
            elif path == "/healthz":
                self._send(200, json.dumps(health(), indent=2,
                                           sort_keys=True, default=str),
                           "application/json")
            elif path == "/debug/flightrecorder":
                p = dump_bundle("http_request")
                self._send(200 if p else 503,
                           json.dumps({"bundle": p}),
                           "application/json")
            else:
                self._send(404, json.dumps(
                    {"error": "not found", "endpoints": [
                        "/metrics", "/healthz",
                        "/debug/flightrecorder"]}),
                    "application/json")
        except Exception as e:  # noqa: BLE001 - a scrape must not kill
            try:
                self._send(500, f"{type(e).__name__}: {e}",
                           "text/plain")
            except Exception:
                pass


def serve(port: Optional[int] = None) -> Optional[str]:
    """Start the telemetry HTTP server on 127.0.0.1 (idempotent).
    `port` defaults to config.telemetry_port; negative disables and
    returns None, 0 binds an ephemeral port. Returns "host:port".
    Also starts the sampler — an endpoint with a stale ring is a trap."""
    global _server, _server_thread
    if port is None:
        port = int(config.telemetry_port)
    if port < 0:
        return None
    with _lock:
        if _server is not None:
            srv = _server
            return f"127.0.0.1:{srv.server_address[1]}"
    srv = http.server.ThreadingHTTPServer(("127.0.0.1", port), _Handler)
    srv.daemon_threads = True
    t = threading.Thread(target=srv.serve_forever,
                         name="bodo-tpu-telemetry-http", daemon=True)
    with _lock:
        _server = srv
        _server_thread = t
    t.start()
    ensure_sampler()
    return f"127.0.0.1:{srv.server_address[1]}"


def endpoint_address() -> Optional[str]:
    with _lock:
        if _server is None:
            return None
        return f"127.0.0.1:{_server.server_address[1]}"


def shutdown_server() -> None:
    global _server, _server_thread
    with _lock:
        srv, t = _server, _server_thread
        _server = None
        _server_thread = None
    if srv is not None:
        srv.shutdown()
        srv.server_close()
    if t is not None and t.is_alive():
        t.join(timeout=2.0)
